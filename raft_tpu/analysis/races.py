"""graft-race engine 1 (static): whole-program lock-discipline lint.

The serving tier (serve engine, registry hot-swap, tombstone mutation,
fabric router, comms worker groups) is multi-threaded, and CHANGES.md
records that nearly every post-review fix in PRs 5-6 was a hand-found
concurrency bug. This engine turns that recurring review-found bug
class into a mechanical gate, the way graft-lint's GL001-GL009 did for
TPU numeric/tracing hazards.

Since r17 the engine is *whole-program* when handed more than one file:
``lint_paths`` builds a project call graph + type model
(:mod:`raft_tpu.analysis.callgraph`) and per-function lock summaries to
fixpoint (:mod:`raft_tpu.analysis.summaries`), keyed by the same
lockwatch *names* the dynamic sanitizer uses (``serve.mutation``, not
``self._lock``) — so the static acquisition graph and the runtime one
are directly comparable, and ``--reconcile <artifact>`` diffs them
(GL022 hard when the runtime observed an edge the model lacks, GL021
advisory for modeled edges no test exercised). Rules:

* **GL010 unguarded-shared-state** — infer a *guarded-by* map per
  class: an attribute written inside ``with self.<lock>:`` (or declared
  with a ``#: guarded-by(<lock>)`` annotation) is shared state, and
  accessing it outside that lock is flagged — writes anywhere, reads
  from methods reachable off ``threading.Thread``/executor/dispatcher
  entry points (methods handed to ``Thread(target=...)``, ``.submit``,
  or escaping as callbacks). Methods named ``*_locked`` assert the
  caller-holds-lock contract and are treated as holding every class
  lock. The same inference runs for helper-object receivers
  (``w.pending`` under ``with w.lock:``) module-wide.
* **GL011 check-then-act** — a test on ``self.X`` (truthiness,
  ``.is_set()``, dict membership) whose matching act (assignment,
  ``.set()``, ``.pop()``...) sits in a *different* lock region: the
  lock was dropped between check and act, so the condition can be
  invalidated in between (the PR-5 ``compact()`` single-flight class).
  ``threading.Event`` attributes are also flagged when both sides run
  with no lock at all.
* **GL012 device-work-under-lock** — ``jax.*`` calls,
  ``block_until_ready``, ``device_put``, and index ``build``/``extend``
  helpers inside a ``with <lock>:`` body (the
  side-build-under-the-mutation-RLock class).
* **GL013 lock-order-cycle** — in whole-program mode, cycles in the
  interprocedural acquisition graph (call-expanded to fixpoint through
  the summaries, reentrant re-acquisition excluded to mirror the
  sanitizer's RLock semantics), reported with the full cycle path
  naming every edge's file:line and mediating call chain. Single-file
  runs keep the original per-file nested-``with`` graph.
* **GL014 unjoined-thread** — ``threading.Thread`` created neither
  ``daemon=True`` nor joined.
* **GL020 unbalanced-acquire** — path-sensitive pairing of manual
  ``acquire()``/``release()``: an acquire whose release is skipped on
  an early return, a fall-through exit, or an exception path with no
  ``finally`` is flagged at the acquire site. Flag locks
  (``make_flag_lock`` try-acquire handoffs) are exempt; deliberate
  ownership transfers carry a reasoned suppression.
* **GL021/GL022 reconciliation** (``--reconcile``) — see above; GL022
  anchors at the artifact ("never suppress the evidence"), GL021 at the
  unexercised static edge's acquire site.

Everything here is a heuristic over syntax (the honest caveat GL001-006
carry too): it resolves ``self.X``/``cls.X``, plain-name receivers, and
call-site-propagated parameter types, and trusts the ``*_locked``
suffix. The dynamic half — the ``RAFT_TPU_THREADSAN=1`` lock sanitizer
(:mod:`raft_tpu.analysis.lockwatch`) — observes the real order at test
time; reconciliation makes the overlap a checked invariant instead of a
hope.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from raft_tpu.analysis.rules import (
    Finding,
    apply_suppressions,
    scan_suppressions,
)

# calls that construct a lock (guard-capable) or an event-like
# primitive, matched by the dotted name's LAST segment so
# `threading.Lock`, `lockwatch.make_lock`, and a from-imported bare
# `make_lock` all classify identically (the exact-match tables this
# replaces missed from-imported sanitizer factories entirely, so a
# class using `make_rlock()` had no guard inference at all)
_LOCK_FACTORIES = {"Lock", "RLock", "make_lock", "make_rlock"}
_CONDITION_FACTORIES = {"Condition", "make_condition"}
_EVENT_FACTORIES = {"Event", "Semaphore", "BoundedSemaphore"}
# flag locks are try-acquire handoffs (lockwatch.make_flag_lock):
# tracked so GL020 and the order graph can exempt them, never guards
_FLAG_FACTORIES = {"make_flag_lock"}

# attribute names that read as locks when we cannot see the constructor
# (helper-object receivers, cross-module state)
_LOCKISH_ATTR_RE = re.compile(r"(^|_)(r?lock|mutex|cond(ition)?)$")

# mutating method names that count as writes to the receiver attribute
_MUTATING_CALLS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "discard", "clear", "update", "add", "set",
    "setdefault", "sort", "reverse",
}
# the subset that acts on an Event/flag for GL011
_ACT_CALLS = _MUTATING_CALLS | {"acquire", "release"}

# GL012: device-work call screens
_DEVICE_ROOTS = {"jax", "jnp", "lax", "pl", "pltpu"}
_DEVICE_ATTRS = {"block_until_ready", "device_put"}
_DEVICE_SUFFIXES = {"build", "extend", "build_index", "build_shard_entry",
                    "warmup_handle"}

_GUARDED_BY_RE = re.compile(r"#:?\s*guarded-by\(\s*([A-Za-z_]\w*)\s*\)")

_SELF_NAMES = {"self", "cls"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_factory(node: ast.AST, last_names: Set[str]) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = _dotted(node.func) or ""
    return dotted.rsplit(".", 1)[-1] in last_names


# guard keys:
#   ("self", attr)       self.<attr> / cls.<attr> lock of the current class
#   ("mod", name)        module-level lock variable
#   ("recv", recv, attr) plain-name receiver lock (w.lock)
#   ("expr", dotted)     any other lock-ish dotted path (self.state.lock)
#   ("held-all",)        synthetic region of a *_locked method
_HELD_ALL = ("held-all",)


@dataclasses.dataclass
class _ClassInfo:
    node: ast.ClassDef
    name: str
    lock_attrs: Dict[str, str] = dataclasses.field(default_factory=dict)
    #   attr -> canonical attr (Condition aliases resolve to their lock)
    event_attrs: Set[str] = dataclasses.field(default_factory=set)
    flag_attrs: Set[str] = dataclasses.field(default_factory=set)
    guarded: Dict[str, Set[tuple]] = dataclasses.field(default_factory=dict)
    #   attr -> guard keys it was written under (or annotated with)
    methods: Dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict)


class FileRaceLinter:
    """One file's lock-discipline pass. See the module docstring."""

    def __init__(self, path: str, source: str,
                 rules: Optional[Set[str]] = None,
                 skip_gl013: bool = False,
                 project_guarded: Optional[Set[str]] = None):
        self.path = path
        self.source = source
        self.rules = rules
        # project mode: the whole-program pass owns GL013, the per-file
        # graph would only re-report a subset of each cycle
        self.skip_gl013 = skip_gl013
        # attr names with a guarded-by contract ANYWHERE in the project
        # (extends GL011's notion of interesting shared state)
        self.project_guarded = project_guarded or set()
        self.findings: List[Finding] = []
        self.tree = ast.parse(source, filename=path)
        self._comments = self._scan_comments(source)
        self.module_locks: Set[str] = set()
        self.classes: List[_ClassInfo] = []
        self._fn_class: Dict[ast.AST, Optional[_ClassInfo]] = {}
        self._entry_fns: Set[ast.AST] = set()
        self._reach_fns: Set[ast.AST] = set()
        self._prepared = False
        # receiver-aggregated guard inference: attr name -> lock attr
        # names it was written under (via `with <recv>.<lockattr>:`)
        self._recv_guarded: Dict[str, Set[str]] = {}
        # GL013 acquisition graph: (node_a, node_b) -> (line, via)
        self._edges: Dict[Tuple[str, str], Tuple[int, str]] = {}

    # -- plumbing ----------------------------------------------------------

    def _emit(self, rule: str, line: int, message: str) -> None:
        if self.rules is not None and rule not in self.rules:
            return
        self.findings.append(
            Finding(rule, self.path, line, message, engine="races"))

    @staticmethod
    def _scan_comments(source: str) -> Dict[int, str]:
        out: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass
        return out

    def prepare(self) -> None:
        """The discovery half of :meth:`run` — split out so project
        mode can pool every file's guarded-by contracts before any
        file's checks fire."""
        if self._prepared:
            return
        self._prepared = True
        self._collect_classes()
        self._collect_module_locks()
        self._collect_entries()
        self._infer_guarded()

    def guarded_attr_names(self) -> Set[str]:
        """Attr names this file declares or infers a guard for."""
        out: Set[str] = set(self._recv_guarded)
        for cls in self.classes:
            out |= set(cls.guarded)
        return out

    def run(self) -> List[Finding]:
        self.prepare()
        for cls in self.classes:
            for fn in self._class_fns(cls):
                self._check_fn(fn, cls)
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for f in ast.walk(node):
                    if isinstance(f, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        self._check_fn(f, None)
        self._check_gl013_cycles()
        self._check_gl014_threads()
        # dedupe (nested defs are visited once per enclosing walk)
        seen: Set[Tuple[str, int, str]] = set()
        unique: List[Finding] = []
        for f in self.findings:
            key = (f.rule, f.line, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        self.findings = unique
        sup = scan_suppressions(self.source)
        return apply_suppressions(self.findings, sup, self.path)

    # -- discovery ---------------------------------------------------------

    def _collect_classes(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = _ClassInfo(node, node.name)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[sub.name] = sub
                elif isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Name):
                    # class-level lock (Interruptible._lock style)
                    self._classify_lock_assign(
                        ci, sub.targets[0].id, sub.value)
            # self.X = <factory> anywhere in the class's methods
            for m in ci.methods.values():
                for sub in ast.walk(m):
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1 and \
                            isinstance(sub.targets[0], ast.Attribute) and \
                            isinstance(sub.targets[0].value, ast.Name) and \
                            sub.targets[0].value.id in _SELF_NAMES:
                        self._classify_lock_assign(
                            ci, sub.targets[0].attr, sub.value)
            self.classes.append(ci)
            for m in ci.methods.values():
                for f in ast.walk(m):
                    if isinstance(f, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                        self._fn_class[f] = ci

    def _classify_lock_assign(self, ci: _ClassInfo, attr: str,
                              value: ast.AST) -> None:
        if _is_factory(value, _FLAG_FACTORIES):
            ci.flag_attrs.add(attr)
        elif _is_factory(value, _LOCK_FACTORIES):
            ci.lock_attrs.setdefault(attr, attr)
        elif _is_factory(value, _CONDITION_FACTORIES):
            target = attr
            # Condition(self.L) aliases the condition to L
            call = value
            args = list(call.args) + [kw.value for kw in call.keywords]
            for a in args:
                if isinstance(a, ast.Attribute) and \
                        isinstance(a.value, ast.Name) and \
                        a.value.id in _SELF_NAMES:
                    target = ci.lock_attrs.get(a.attr, a.attr)
                    break
            ci.lock_attrs.setdefault(attr, target)
        elif _is_factory(value, _EVENT_FACTORIES):
            ci.event_attrs.add(attr)

    def _collect_module_locks(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) and \
                    _is_factory(node.value,
                                _LOCK_FACTORIES | _CONDITION_FACTORIES):
                self.module_locks.add(node.targets[0].id)

    def _collect_entries(self) -> None:
        """Entry functions: handed to Thread(target=...)/executor
        .submit(...), or escaping as a value (callback registration).
        Reachability closes over same-class ``self.m()`` calls."""
        name_defs: Dict[str, List[ast.AST]] = {}
        for f, _ in self._fn_class.items():
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name_defs.setdefault(f.name, []).append(f)
        for node in self.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for f in ast.walk(node):
                    if isinstance(f, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        name_defs.setdefault(f.name, []).append(f)
                        self._fn_class.setdefault(
                            f, self._fn_class.get(node))

        def mark_target(expr: ast.AST, cls: Optional[_ClassInfo]) -> None:
            if isinstance(expr, ast.Attribute) and \
                    isinstance(expr.value, ast.Name) and \
                    expr.value.id in _SELF_NAMES and cls is not None:
                m = cls.methods.get(expr.attr)
                if m is not None:
                    self._entry_fns.add(m)
            elif isinstance(expr, ast.Name):
                for f in name_defs.get(expr.id, ()):
                    self._entry_fns.add(f)

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func) or ""
            cls = self._enclosing_class(node)
            is_thread = fname.endswith("Thread")
            is_submit = fname.rsplit(".", 1)[-1] in ("submit",
                                                     "call_soon",
                                                     "run_in_executor")
            if is_thread:
                for kw in node.keywords:
                    if kw.arg == "target":
                        mark_target(kw.value, cls)
            elif is_submit and node.args:
                mark_target(node.args[0], cls)
            else:
                # escaping as a value: self.M passed/stored, not called
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if isinstance(arg, ast.Attribute) and \
                            isinstance(arg.value, ast.Name) and \
                            arg.value.id in _SELF_NAMES and \
                            cls is not None and arg.attr in cls.methods:
                        self._entry_fns.add(cls.methods[arg.attr])
        # closure over same-class self-calls
        frontier = list(self._entry_fns)
        self._reach_fns = set(frontier)
        while frontier:
            fn = frontier.pop()
            cls = self._fn_class.get(fn)
            if cls is None:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id in _SELF_NAMES:
                    m = cls.methods.get(sub.func.attr)
                    if m is not None and m not in self._reach_fns:
                        self._reach_fns.add(m)
                        frontier.append(m)

    def _enclosing_class(self, node: ast.AST) -> Optional[_ClassInfo]:
        # cheap: attribute via the fn map of any ancestor FunctionDef —
        # recompute by walking each class's span instead
        for ci in self.classes:
            if ci.node.lineno <= getattr(node, "lineno", 0) <= \
                    (ci.node.end_lineno or 1 << 30):
                # nested classes resolve to the innermost span
                best = ci
                for cj in self.classes:
                    if cj is ci:
                        continue
                    if ci.node.lineno <= cj.node.lineno and \
                            (cj.node.end_lineno or 0) <= \
                            (ci.node.end_lineno or 1 << 30) and \
                            cj.node.lineno <= node.lineno <= \
                            (cj.node.end_lineno or 1 << 30):
                        best = cj
                return best
        return None

    # -- guard machinery ---------------------------------------------------

    def _guard_key(self, expr: ast.AST,
                   cls: Optional[_ClassInfo]) -> Optional[tuple]:
        """The guard key of a with-item context expression, or None when
        it is not lock-ish."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            recv, attr = expr.value.id, expr.attr
            if recv in _SELF_NAMES and cls is not None:
                if attr in cls.lock_attrs:
                    return ("self", cls.name, cls.lock_attrs[attr])
                if _LOCKISH_ATTR_RE.search(attr):
                    return ("self", cls.name, attr)
                return None
            if _LOCKISH_ATTR_RE.search(attr):
                return ("recv", recv, attr)
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return ("mod", expr.id)
            if _LOCKISH_ATTR_RE.search(expr.id):
                return ("mod", expr.id)
            return None
        dotted = _dotted(expr)
        if dotted and _LOCKISH_ATTR_RE.search(dotted.rsplit(".", 1)[-1]):
            return ("expr", dotted)
        return None

    def _node_label(self, key: tuple) -> str:
        if key[0] == "self":
            return f"{key[1]}.{key[2]}"
        if key[0] == "recv":
            return f"{key[1]}.{key[2]}"
        return key[-1]

    def _class_fns(self, cls: _ClassInfo):
        seen: Set[ast.AST] = set()
        for m in cls.methods.values():
            for f in ast.walk(m):
                if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and f not in seen:
                    seen.add(f)
                    yield f

    def _annotated_guards(self, cls: _ClassInfo) -> Dict[str, Set[tuple]]:
        """``#: guarded-by(<lock>)`` annotations on `self.attr = ...`
        lines (same line or the line above)."""
        out: Dict[str, Set[tuple]] = {}
        for m in cls.methods.values():
            for sub in ast.walk(m):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                        and isinstance(sub.targets[0], ast.Attribute) and \
                        isinstance(sub.targets[0].value, ast.Name) and \
                        sub.targets[0].value.id in _SELF_NAMES:
                    for line in (sub.lineno, sub.lineno - 1):
                        c = self._comments.get(line, "")
                        mt = _GUARDED_BY_RE.search(c)
                        if mt:
                            lock = cls.lock_attrs.get(mt.group(1),
                                                      mt.group(1))
                            out.setdefault(sub.targets[0].attr, set()).add(
                                ("self", cls.name, lock))
                            break
        return out

    def _infer_guarded(self) -> None:
        for cls in self.classes:
            cls.guarded = self._annotated_guards(cls)
            for fn in self._class_fns(cls):
                self._walk_regions(
                    fn, cls,
                    on_access=self._guard_recorder(cls))
        # receiver-aggregated inference (module-wide)
        for node in self.tree.body:
            targets = [node] if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)) else []
            for t in targets:
                for fn in ast.walk(t):
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        self._walk_regions(
                            fn, self._fn_class.get(fn),
                            on_access=self._recv_recorder())

    def _guard_recorder(self, cls: _ClassInfo):
        def on_access(recv, attr, is_write, guards, node, fn):
            if recv in _SELF_NAMES and is_write and guards:
                keys = {g for g in guards
                        if g[0] == "self" and g[1] == cls.name}
                if keys:
                    cls.guarded.setdefault(attr, set()).update(keys)
        return on_access

    def _recv_recorder(self):
        def on_access(recv, attr, is_write, guards, node, fn):
            if recv in _SELF_NAMES or not is_write:
                return
            locks = {g[2] for g in guards
                     if g[0] == "recv" and g[1] == recv}
            if locks:
                self._recv_guarded.setdefault(attr, set()).update(locks)
        return on_access

    def _walk_regions(self, fn: ast.AST, cls: Optional[_ClassInfo],
                      on_access=None, on_call=None, on_with=None,
                      on_node=None) -> None:
        """Walk one function body with an active guard-region stack.

        Nested function definitions are NOT descended into (their bodies
        run later, outside these regions); they are analyzed as their
        own functions. ``*_locked`` methods start inside the synthetic
        :data:`_HELD_ALL` region. ``on_node(node, stack)`` fires for
        every visited non-``With`` node with the LIVE (read-only) stack
        of ``(guard_key, with_node)`` entries — the one walker every
        region-aware rule builds on."""
        stack: List[Tuple[tuple, ast.With]] = []
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                fn.name.endswith("_locked"):
            stack.append((_HELD_ALL, None))

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fn:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in node.items:
                    key = self._guard_key(item.context_expr, cls)
                    if key is not None:
                        if on_with is not None:
                            on_with(key, [k for k, _ in stack], node)
                        stack.append((key, node))
                        pushed += 1
                for item in node.items:
                    visit(item.context_expr)
                    if item.optional_vars is not None:
                        visit(item.optional_vars)
                for child in node.body:
                    visit(child)
                for _ in range(pushed):
                    stack.pop()
                return
            if on_access is not None:
                self._emit_accesses(node, on_access,
                                    [k for k, _ in stack], fn)
            if on_call is not None and isinstance(node, ast.Call):
                on_call(node, [(k, w) for k, w in stack])
            if on_node is not None:
                on_node(node, stack)
            for child in ast.iter_child_nodes(node):
                visit(child)

        body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
        for child in body:
            visit(child)

    def _emit_accesses(self, node: ast.AST, on_access, guards,
                       fn) -> None:
        """Classify direct attribute reads/writes on plain receivers."""
        def attr_of(target: ast.AST):
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name):
                return target.value.id, target.attr
            return None

        if isinstance(node, ast.Assign):
            for t in node.targets:
                ra = attr_of(t)
                if ra:
                    on_access(ra[0], ra[1], True, guards, node, fn)
                elif isinstance(t, ast.Subscript):
                    ra = attr_of(t.value)
                    if ra:
                        on_access(ra[0], ra[1], True, guards, node, fn)
        elif isinstance(node, ast.AugAssign):
            ra = attr_of(node.target)
            if ra:
                on_access(ra[0], ra[1], True, guards, node, fn)
            elif isinstance(node.target, ast.Subscript):
                ra = attr_of(node.target.value)
                if ra:
                    on_access(ra[0], ra[1], True, guards, node, fn)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATING_CALLS:
            ra = attr_of(node.func.value)
            if ra:
                on_access(ra[0], ra[1], True, guards, node, fn)
        elif isinstance(node, ast.Attribute) and \
                isinstance(node.ctx, ast.Load) and \
                isinstance(node.value, ast.Name):
            on_access(node.value.id, node.attr, False, guards, node, fn)

    # -- GL010 / GL011 / GL012 per-function checks -------------------------

    def _check_fn(self, fn: ast.AST, cls: Optional[_ClassInfo]) -> None:
        cls = self._fn_class.get(fn, cls)
        fn_name = getattr(fn, "name", "<lambda>")
        in_reach = fn in self._reach_fns
        is_init = fn_name in ("__init__", "__new__")
        is_locked_fn = fn_name.endswith("_locked")

        def on_access(recv, attr, is_write, guards, node, afn):
            if self.rules is not None and "GL010" not in self.rules:
                return
            if is_init or is_locked_fn or _HELD_ALL in guards:
                return
            line = getattr(node, "lineno", getattr(fn, "lineno", 0))
            if recv in _SELF_NAMES:
                if cls is None or attr not in cls.guarded:
                    return
                if attr in cls.lock_attrs or attr in cls.event_attrs:
                    return
                want = cls.guarded[attr]
                held = {g for g in guards
                        if g[0] == "self" and g[1] == cls.name}
                if held & want:
                    return
                if is_write or in_reach:
                    locks = ", ".join(sorted(
                        self._node_label(k) for k in want))
                    kind = "write to" if is_write else \
                        "thread-reachable read of"
                    self._emit(
                        "GL010", line,
                        f"{kind} {recv}.{attr} outside its guarding "
                        f"lock ({locks}): {attr} is written under that "
                        f"lock elsewhere, so this access races it; "
                        f"hold the lock, rename the method *_locked if "
                        f"the caller holds it, or suppress with a "
                        f"reason")
            else:
                want_locks = self._recv_guarded.get(attr)
                if not want_locks or _LOCKISH_ATTR_RE.search(attr):
                    return
                held = {g[2] for g in guards
                        if g[0] == "recv" and g[1] == recv}
                if held & want_locks:
                    return
                if is_write or in_reach:
                    kind = "write to" if is_write else \
                        "thread-reachable read of"
                    self._emit(
                        "GL010", line,
                        f"{kind} {recv}.{attr} outside "
                        f"{recv}.{'/'.join(sorted(want_locks))}: "
                        f"'{attr}' is written under that lock elsewhere "
                        f"in this module; hold it here or suppress with "
                        f"a reason")

        def on_call(node, stack):
            if self.rules is not None and "GL012" not in self.rules:
                return
            lock_keys = [k for k, _ in stack if k != _HELD_ALL]
            if not lock_keys:
                return
            dotted = _dotted(node.func) or ""
            root = dotted.split(".", 1)[0]
            last = dotted.rsplit(".", 1)[-1]
            hit = None
            if root in _DEVICE_ROOTS:
                hit = f"device call {dotted}()"
            elif last in _DEVICE_ATTRS:
                hit = f"blocking device call .{last}()"
            elif last in _DEVICE_SUFFIXES:
                hit = f"index build/upload helper {dotted or last}()"
            if hit is None:
                return
            locks = ", ".join(self._node_label(k) for k in lock_keys)
            self._emit(
                "GL012", node.lineno,
                f"{hit} inside `with {locks}:` — device dispatch/"
                f"compile/upload under a lock stalls every concurrent "
                f"acquirer; snapshot under the lock, compute outside, "
                f"or suppress with a reason")

        def on_with(key, held, node):
            if not held:
                return
            a = self._node_label(held[-1])
            b = self._node_label(key)
            if a == b:
                return
            self._edges.setdefault((a, b),
                                   (node.lineno, "nested with"))

        self._walk_regions(fn, cls, on_access=on_access, on_call=on_call,
                           on_with=on_with)
        # one-hop call expansion for GL013: `with A:` body calling a
        # same-class method that acquires B adds A -> B
        if cls is not None:
            self._expand_call_edges(fn, cls)
        self._check_gl011(fn, cls)
        self._check_gl020(fn, cls)

    def _expand_call_edges(self, fn: ast.AST, cls: _ClassInfo) -> None:
        acquires: Dict[str, List[Tuple[tuple, int]]] = {}

        def collect(m: ast.AST) -> List[Tuple[tuple, int]]:
            out: List[Tuple[tuple, int]] = []
            self._walk_regions(m, cls, on_with=lambda k, h, n:
                               out.append((k, n.lineno)))
            return out

        def on_call(node, stack):
            lock_keys = [k for k, _ in stack if k != _HELD_ALL]
            if not lock_keys:
                return
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in _SELF_NAMES:
                callee = cls.methods.get(node.func.attr)
                if callee is None or callee is fn:
                    return
                if node.func.attr not in acquires:
                    acquires[node.func.attr] = collect(callee)
                a = self._node_label(lock_keys[-1])
                for key, _line in acquires[node.func.attr]:
                    b = self._node_label(key)
                    if a != b:
                        self._edges.setdefault(
                            (a, b),
                            (node.lineno,
                             f"call to {node.func.attr}()"))

        self._walk_regions(fn, cls, on_call=on_call)

    # -- GL011 -------------------------------------------------------------

    def _check_gl011(self, fn: ast.AST, cls: Optional[_ClassInfo]) -> None:
        if self.rules is not None and "GL011" not in self.rules:
            return
        fn_name = getattr(fn, "name", "<lambda>")
        if fn_name in ("__init__", "__new__"):
            return
        has_locks = bool(
            (cls is not None and cls.lock_attrs) or self.module_locks)
        if not has_locks:
            return

        def checked_attrs(test: ast.AST):
            out: Set[Tuple[str, str]] = set()
            for sub in ast.walk(test):
                if isinstance(sub, ast.Attribute) and \
                        isinstance(sub.value, ast.Name):
                    out.add((sub.value.id, sub.attr))
                elif isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in ("is_set", "locked", "empty",
                                          "full") and \
                        isinstance(sub.func.value, ast.Attribute) and \
                        isinstance(sub.func.value.value, ast.Name):
                    out.add((sub.func.value.value.id,
                             sub.func.value.attr))
            return out

        def interesting(recv: str, attr: str) -> bool:
            if recv in _SELF_NAMES and cls is not None:
                return attr in cls.guarded or attr in cls.event_attrs
            return attr in self._recv_guarded or \
                attr in self.project_guarded

        def act_attr(node: ast.AST) -> Optional[Tuple[str, str]]:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    if isinstance(base, ast.Attribute) and \
                            isinstance(base.value, ast.Name):
                        return base.value.id, base.attr
            elif isinstance(node, ast.AugAssign):
                base = node.target.value \
                    if isinstance(node.target, ast.Subscript) \
                    else node.target
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name):
                    return base.value.id, base.attr
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _ACT_CALLS:
                recv = node.func.value
                if isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name):
                    return recv.value.id, recv.attr
            return None

        # (recv, attr) -> (check region, check line); LATEST check wins:
        # the double-checked idiom (re-check inside the act's own
        # region) legitimately supersedes an earlier region's check
        # region identity = the innermost (guard_key, with_node) stack
        # entry (None = unlocked; the *_locked synthetic entry compares
        # equal function-wide, so caller-held checks/acts are one
        # region). The traversal itself is _walk_regions' — one walker
        # for every region-aware rule.
        pending: Dict[Tuple[str, str], Tuple[object, int]] = {}
        # local flags carrying a check: `free = k not in self._jobs` then
        # `if free:` inherits the check's attr and region
        flag_vars: Dict[str, Tuple[Tuple[str, str], object, int]] = {}

        def on_node(node: ast.AST, stack) -> None:
            region = stack[-1] if stack else None
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                hits = [ra for ra in checked_attrs(node.value)
                        if interesting(*ra)]
                if hits:
                    flag_vars[node.targets[0].id] = (
                        hits[0], region, node.lineno)
                else:
                    flag_vars.pop(node.targets[0].id, None)
            if isinstance(node, ast.If):
                for recv, attr in checked_attrs(node.test):
                    if interesting(recv, attr):
                        pending[(recv, attr)] = (region, node.lineno)
                for sub in ast.walk(node.test):
                    if isinstance(sub, ast.Name) and sub.id in flag_vars:
                        key, reg, line = flag_vars[sub.id]
                        pending[key] = (reg, line)
            ra = act_attr(node)
            hit = pending.get(ra) if ra is not None else None
            if hit is None:
                return
            check_region, check_line = hit
            same_region = (check_region == region and
                           check_region is not None)
            if check_region is None and region is None:
                # only Events/locks are flagged fully unlocked:
                # unguarded lazy-init of plain attrs is a
                # single-thread idiom
                recv, attr = ra
                is_event = (recv in _SELF_NAMES and cls is not None and
                            attr in cls.event_attrs)
                if not is_event:
                    same_region = True     # exempt
            if not same_region:
                self._emit(
                    "GL011", node.lineno,
                    f"check-then-act on {ra[0]}.{ra[1]}: checked "
                    f"at line {check_line} in a different lock "
                    f"region than this act — the condition can be "
                    f"invalidated between them; merge into one "
                    f"critical section or use an atomic "
                    f"test-and-set (non-blocking Lock.acquire)")

        self._walk_regions(fn, cls, on_node=on_node)

    # -- GL020 -------------------------------------------------------------

    @staticmethod
    def _nonblocking_call(node: ast.Call) -> bool:
        if node.args and isinstance(node.args[0], ast.Constant) and \
                node.args[0].value is False:
            return True
        return any(kw.arg == "blocking" and
                   isinstance(kw.value, ast.Constant) and
                   kw.value.value is False for kw in node.keywords)

    def _gl020_label(self, expr: ast.AST,
                     cls: Optional[_ClassInfo]) -> Optional[str]:
        key = self._guard_key(expr, cls)
        if key is None:
            return None
        if key[0] == "self" and cls is not None and \
                key[2] in cls.flag_attrs:
            return None               # try-acquire handoff, never held
        return self._node_label(key)

    def _check_gl020(self, fn: ast.AST, cls: Optional[_ClassInfo]) -> None:
        """Path-sensitive pairing of manual ``acquire()``/``release()``.

        A ``with`` block cannot leak its lock; a manual pair can, two
        ways this flags at the ACQUIRE line (one finding per site):

        * an early ``return`` (or the fall-through exit) while still
          holding the lock, with no enclosing ``finally`` releasing it;
        * work between acquire and release that can raise, with no
          enclosing ``try``/``finally`` releasing it — the exception
          propagates out still holding the lock.

        Non-blocking acquires (``blocking=False`` — the test-and-set
        idiom), flag locks, and functions that ARE the transfer idiom
        (``acquire``/``__enter__`` wrappers) are exempt. Intentional
        ownership transfers suppress with a reason naming the
        releasing site.
        """
        if self.rules is not None and "GL020" not in self.rules:
            return
        if getattr(fn, "name", "") in ("acquire", "__enter__",
                                       "release", "__exit__"):
            return
        reported: Set[Tuple[str, int]] = set()

        def emit(label: str, line: int, why: str) -> None:
            if (label, line) in reported:
                return
            reported.add((label, line))
            self._emit(
                "GL020", line,
                f"manual {label}.acquire() can leak: {why}; use `with` "
                f"or try/finally, or — if ownership transfers to a "
                f"caller that releases it — suppress with a reason "
                f"naming the releasing site")

        # held: label -> [acquire line, protected by finally, risky
        # call count since acquire]
        def scan(node: ast.AST, held: Dict[str, list],
                 protectors: Set[str]) -> None:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if isinstance(f, ast.Attribute) and f.attr == "acquire":
                    label = self._gl020_label(f.value, cls)
                    if label is None or self._nonblocking_call(sub) or \
                            label in held:
                        continue
                    held[label] = [sub.lineno, label in protectors, 0]
                elif isinstance(f, ast.Attribute) and f.attr == "release":
                    label = self._gl020_label(f.value, cls)
                    rec = held.pop(label, None) if label else None
                    if rec is not None and not rec[1] and rec[2] > 0:
                        emit(label, rec[0],
                             "work between acquire and release can "
                             "raise, exiting still holding the lock")
                else:
                    for rec in held.values():
                        rec[2] += 1

        def released_in(stmts: List[ast.stmt]) -> Set[str]:
            out: Set[str] = set()
            for st in stmts:
                for sub in ast.walk(st):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Attribute) and \
                            sub.func.attr == "release":
                        lb = self._gl020_label(sub.func.value, cls)
                        if lb:
                            out.add(lb)
            return out

        def walk(stmts: List[ast.stmt], held: Dict[str, list],
                 protectors: Set[str]) -> None:
            for st in stmts:
                if isinstance(st, ast.Try):
                    fin = released_in(st.finalbody)
                    walk(st.body, held, protectors | fin)
                    for h in st.handlers:
                        walk(h.body, held, protectors)
                    walk(st.orelse, held, protectors | fin)
                    walk(st.finalbody, held, protectors)
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    for item in st.items:
                        scan(item.context_expr, held, protectors)
                    walk(st.body, held, protectors)
                elif isinstance(st, ast.If):
                    scan(st.test, held, protectors)
                    other = {k: list(v) for k, v in held.items()}
                    walk(st.body, held, protectors)
                    walk(st.orelse, other, protectors)
                    for k, v in other.items():   # may-hold union
                        held.setdefault(k, v)
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    scan(st.iter, held, protectors)
                    walk(st.body, held, protectors)
                    walk(st.orelse, held, protectors)
                elif isinstance(st, ast.While):
                    scan(st.test, held, protectors)
                    walk(st.body, held, protectors)
                    walk(st.orelse, held, protectors)
                elif isinstance(st, ast.Return):
                    for label, rec in held.items():
                        if label not in protectors and not rec[1]:
                            emit(label, rec[0],
                                 f"the return at line {st.lineno} "
                                 f"exits still holding it")
                elif isinstance(st, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                else:
                    scan(st, held, protectors)

        body = fn.body if not isinstance(fn, ast.Lambda) else []
        held: Dict[str, list] = {}
        walk(list(body), held, set())
        for label, rec in held.items():
            if not rec[1]:
                emit(label, rec[0],
                     "no release on the fall-through exit path")

    # -- GL013 -------------------------------------------------------------

    def _check_gl013_cycles(self) -> None:
        if self.skip_gl013:
            return      # project mode: the whole-program graph owns GL013
        if self.rules is not None and "GL013" not in self.rules:
            return
        graph: Dict[str, Dict[str, Tuple[int, str]]] = {}
        for (a, b), (line, via) in self._edges.items():
            graph.setdefault(a, {})[b] = (line, via)
        reported: Set[frozenset] = set()
        for start in sorted(graph):
            # DFS cycle detection from each node
            path: List[str] = []

            def dfs(n: str) -> Optional[List[str]]:
                if n in path:
                    return path[path.index(n):] + [n]
                if n not in graph:
                    return None
                path.append(n)
                for succ in sorted(graph[n]):
                    cyc = dfs(succ)
                    if cyc is not None:
                        return cyc
                path.pop()
                return None

            cyc = dfs(start)
            if cyc is None:
                continue
            key = frozenset(cyc)
            if key in reported:
                continue
            reported.add(key)
            line = min(graph[a][b][0] for a, b in zip(cyc, cyc[1:])
                       if b in graph.get(a, {}))
            detail = "; ".join(
                f"{a} -> {b} at line {graph[a][b][0]} ({graph[a][b][1]})"
                for a, b in zip(cyc, cyc[1:]) if b in graph.get(a, {}))
            self._emit(
                "GL013", line,
                f"lock-order cycle {' -> '.join(cyc)}: two paths acquire "
                f"these locks in opposite orders and can deadlock "
                f"({detail}); pick one global order (docs/serving.md "
                f"lock hierarchy) and restructure the out-of-order "
                f"acquisition")

    # -- GL014 -------------------------------------------------------------

    def _check_gl014_threads(self) -> None:
        if self.rules is not None and "GL014" not in self.rules:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func) or ""
            if fname not in ("threading.Thread", "Thread"):
                continue
            if any(kw.arg == "daemon" and
                   isinstance(kw.value, ast.Constant) and
                   kw.value.value is True for kw in node.keywords):
                continue
            # assigned to a name/attr that is later joined or daemonized?
            target = self._assign_target_of(node)
            if target is not None and (
                    re.search(rf"\b{re.escape(target)}\s*\.\s*join\s*\(",
                              self.source) or
                    re.search(rf"\b{re.escape(target)}\s*\.\s*daemon\s*=",
                              self.source)):
                continue
            self._emit(
                "GL014", node.lineno,
                "threading.Thread created neither daemon=True nor "
                "joined: it outlives close()/shutdown, pins its closure "
                "and can hang interpreter exit; pass daemon=True or "
                "join it in the owning lifecycle")

    def _assign_target_of(self, call: ast.Call) -> Optional[str]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and node.value is call and \
                    len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    return t.id
                d = _dotted(t)
                if d:
                    return d.rsplit(".", 1)[-1]
        return None


# ---------------------------------------------------------------------------
# public API (mirrors analysis.lint)
# ---------------------------------------------------------------------------


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Set[str]] = None) -> List[Finding]:
    return FileRaceLinter(path, source, rules).run()


def lint_file(path, rules: Optional[Set[str]] = None) -> List[Finding]:
    p = Path(path)
    try:
        source = p.read_text()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding("GL000", str(p), 0, f"unreadable: {e}",
                        engine="races")]
    try:
        return lint_source(source, str(p), rules)
    except SyntaxError as e:
        return [Finding("GL000", str(p), e.lineno or 0,
                        f"syntax error: {e.msg}", engine="races")]


def _collect_files(paths: Sequence) -> Tuple[List[Path], bool]:
    files: List[Path] = []
    any_dir = False
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            any_dir = True
            files.extend(sorted(
                f for f in p.rglob("*.py")
                if "__pycache__" not in f.parts))
        else:
            files.append(p)
    return files, any_dir


def lint_paths(paths: Sequence, rules: Optional[Set[str]] = None,
               project: Optional[bool] = None,
               reconcile: Optional[str] = None) -> List[Finding]:
    """Race-lint files and directories (``**/*.py``, sans __pycache__).

    With more than one file in scope (or any directory), the pass runs
    in **whole-program mode**: a project call graph + per-function lock
    summaries (:mod:`callgraph`/:mod:`summaries`) replace the per-file
    GL013 graph with the interprocedural one (cycles reported with the
    full cross-file path), guarded-by contracts propagate across
    modules (GL010 on typed foreign receivers, GL011's shared-state
    set), and — when ``reconcile`` names a lockwatch graph artifact —
    the static model is diffed against the runtime one (GL022 hard /
    GL021 advisory). ``project=False`` forces the old per-file pass.
    """
    files, any_dir = _collect_files(paths)
    if project is None:
        # reconciliation diffs the WHOLE-PROGRAM graph by definition,
        # so --reconcile forces project mode even for one file
        project = any_dir or len(files) > 1 or reconcile is not None
    summaries = None
    if project:
        try:
            from raft_tpu.analysis.summaries import build_summaries
            summaries = build_summaries(paths)
        except Exception:
            summaries = None       # degrade to per-file, never crash

    linters: Dict[str, FileRaceLinter] = {}
    findings: List[Finding] = []
    for f in files:
        try:
            source = f.read_text()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding("GL000", str(f), 0,
                                    f"unreadable: {e}", engine="races"))
            continue
        try:
            linters[str(f)] = FileRaceLinter(
                str(f), source, rules,
                skip_gl013=summaries is not None)
        except SyntaxError as e:
            findings.append(Finding("GL000", str(f), e.lineno or 0,
                                    f"syntax error: {e.msg}",
                                    engine="races"))

    # pool every file's guarded-by contracts BEFORE any checks run
    project_guarded: Set[str] = set()
    for lt in linters.values():
        lt.prepare()
        project_guarded |= lt.guarded_attr_names()
    for lt in linters.values():
        if summaries is not None:
            lt.project_guarded = project_guarded
        findings.extend(lt.run())

    if summaries is not None:
        extra = _global_gl013(summaries, rules)
        extra += _cross_module_gl010(summaries, linters, rules)
        if reconcile is not None:
            extra += _reconcile_findings(summaries, reconcile, rules)
        findings.extend(_apply_file_suppressions(extra, linters))

    # per-file and whole-program passes overlap on purpose; keep the
    # first (per-file, already suppression-applied) finding per site.
    # GL010 dedupes by LINE (the two passes word the same defect
    # differently); other rules keep distinct messages per line
    seen: Set[tuple] = set()
    unique: List[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.line) if f.rule == "GL010" \
            else (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


def _apply_file_suppressions(findings: List[Finding],
                             linters: Dict[str, FileRaceLinter]
                             ) -> List[Finding]:
    """Run global-pass findings through their home file's inline
    suppressions (GL022 anchors to the runtime artifact, which has no
    source to suppress in — by design)."""
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for path, fs in by_path.items():
        lt = linters.get(path)
        if lt is None:
            out.extend(fs)
            continue
        sup = scan_suppressions(lt.source)
        # drop the GL000s apply_suppressions re-reports for this file:
        # the per-file pass already emitted them once
        for f in apply_suppressions(fs, sup, path):
            if f in fs:
                out.append(f)
    return out


def _short(path: str) -> str:
    for marker in ("raft_tpu/", "raft_tpu\\"):
        i = path.find(marker)
        if i >= 0:
            return path[i:].replace("\\", "/")
    return path


def _global_gl013(summaries, rules: Optional[Set[str]]) -> List[Finding]:
    """Whole-program lock-order cycles, named with the full cross-file
    path (every edge's site) — the per-file GL013's interprocedural
    replacement."""
    if rules is not None and "GL013" not in rules:
        return []
    out: List[Finding] = []
    edges = summaries.edges()
    for cyc in summaries.cycles():
        es = [edges[p] for p in zip(cyc, cyc[1:]) if p in edges]
        if not es:
            continue
        first = min(es, key=lambda e: (e.path, e.line))
        detail = "; ".join(
            f"{e.a} -> {e.b} at {_short(e.path)}:{e.line} ({e.via})"
            for e in es)
        out.append(Finding(
            "GL013", first.path, first.line,
            f"whole-program lock-order cycle {' -> '.join(cyc)}: two "
            f"paths acquire these locks in opposite orders and can "
            f"deadlock ({detail}); pick one global order "
            f"(docs/serving.md lock hierarchy) and restructure the "
            f"out-of-order acquisition", engine="races"))
    return out


def _cross_module_gl010(summaries, linters: Dict[str, FileRaceLinter],
                        rules: Optional[Set[str]]) -> List[Finding]:
    """GL010 across module boundaries: an access through a TYPED
    receiver (param/local/attr annotation, constructor inference) whose
    home class declares a guarded-by contract for that attribute, made
    outside the guarding lock.

    The per-file pass sees ``self.X`` and same-module ``w.pending``
    idioms; this pass is what makes ``hl.state.tombstones`` in fabric
    answer to the contract ``MutableState`` declared in another file.
    Held locks are tracked by lockwatch NAME via the project model, so
    ``with st.lock:`` in the caller satisfies a ``serve.mutation``
    contract no matter which alias spells it.
    """
    if rules is not None and "GL010" not in rules:
        return []
    g = summaries.graph
    # (module path, class name) -> per-file class info (the contracts)
    infos: Dict[Tuple[str, str], _ClassInfo] = {}
    for lt in linters.values():
        for ci in lt.classes:
            infos[(lt.path, ci.name)] = ci

    def want_names(cls_decl, ci: _ClassInfo, attr: str) -> Set[str]:
        out: Set[str] = set()
        for key in ci.guarded.get(attr, ()):
            lockattr = key[-1]
            decl = cls_decl.lock_attrs.get(lockattr)
            out.add(decl.name if decl is not None
                    else f"{cls_decl.name}.{lockattr}")
        return out

    out: List[Finding] = []
    seen: Set[Tuple[str, int, str, str]] = set()

    def check_access(fn, env, held: List[str], recv: str, attr: str,
                     is_write: bool, line: int) -> None:
        if recv in _SELF_NAMES:
            return                 # the per-file pass owns self.X
        for t in env.get(recv, ()):
            if t.container is not None:
                continue
            ci = infos.get((t.cls.module.path, t.cls.name))
            if ci is None or attr not in ci.guarded:
                continue
            if attr in ci.lock_attrs or attr in ci.event_attrs or \
                    attr in ci.flag_attrs:
                continue
            want = want_names(t.cls, ci, attr)
            if not want or set(held) & want:
                continue
            if not is_write and fn not in g.reachable:
                continue
            key = (fn.module.path, line, recv, attr)
            if key in seen:
                continue
            seen.add(key)
            kind = "write to" if is_write else "thread-reachable read of"
            locks = ", ".join(sorted(want))
            out.append(Finding(
                "GL010", fn.module.path, line,
                f"{kind} {recv}.{attr} outside its guarding lock "
                f"({locks}): the guarded-by contract is declared by "
                f"{t.cls.name} in {_short(t.cls.module.path)} — hold "
                f"the lock here, or suppress with a reason",
                engine="races"))

    for fn in summaries.direct:
        node = fn.node
        if isinstance(node, ast.Lambda) or \
                getattr(node, "name", "").endswith("_locked") or \
                getattr(node, "name", "") in ("__init__", "__new__"):
            continue
        env = g.local_types(fn)
        held: List[str] = []

        def visit(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not fn.node:
                return
            if isinstance(n, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in n.items:
                    decl = g.lock_node(item.context_expr, fn)
                    if decl is not None and decl.kind != "flag":
                        held.append(decl.name)
                        pushed += 1
                for child in n.body:
                    visit(child)
                for _ in range(pushed):
                    held.pop()
                return
            ra: Optional[Tuple[str, str, bool]] = None
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    if isinstance(base, ast.Attribute) and \
                            isinstance(base.value, ast.Name):
                        ra = (base.value.id, base.attr, True)
            elif isinstance(n, ast.AugAssign):
                base = n.target.value \
                    if isinstance(n.target, ast.Subscript) else n.target
                if isinstance(base, ast.Attribute) and \
                        isinstance(base.value, ast.Name):
                    ra = (base.value.id, base.attr, True)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr in _MUTATING_CALLS and \
                    isinstance(n.func.value, ast.Attribute) and \
                    isinstance(n.func.value.value, ast.Name):
                ra = (n.func.value.value.id, n.func.value.attr, True)
            elif isinstance(n, ast.Attribute) and \
                    isinstance(n.ctx, ast.Load) and \
                    isinstance(n.value, ast.Name):
                ra = (n.value.id, n.attr, False)
            if ra is not None:
                check_access(fn, env, held, ra[0], ra[1], ra[2],
                             getattr(n, "lineno", 0))
            for child in ast.iter_child_nodes(n):
                visit(child)

        for child in node.body:
            visit(child)
    return out


def _reconcile_findings(summaries, artifact: str,
                        rules: Optional[Set[str]]) -> List[Finding]:
    """Static ↔ dynamic graph diff (``--reconcile``): GL022 for runtime
    edges the model cannot see (hard — a soundness gap), GL021 for
    static edges no threadsan run exercised (advisory coverage debt)."""
    import json as _json
    try:
        with open(artifact) as fh:
            data = _json.load(fh)
    except (OSError, ValueError) as e:
        return [Finding("GL000", str(artifact), 0,
                        f"unreadable lock-graph artifact: {e}",
                        engine="races")]
    graph = data.get("graph", data) if isinstance(data, dict) else {}
    missing, untested = summaries.reconcile(graph)
    out: List[Finding] = []
    if rules is None or "GL022" in rules:
        for a, b, site in missing:
            where = f" (first seen at {site})" if site else ""
            out.append(Finding(
                "GL022", str(artifact), 0,
                f"runtime lock edge {a} -> {b}{where} is absent from "
                f"the static model: the sanitizer observed this order "
                f"under test and the whole-program analysis cannot see "
                f"it — extend the call-graph typing or annotate the "
                f"acquisition path (never suppress the evidence)",
                engine="races"))
    if rules is None or "GL021" in rules:
        for e in untested:
            out.append(Finding(
                "GL021", e.path, e.line,
                f"static lock-order edge {e.a} -> {e.b} ({e.via}) was "
                f"never exercised under the runtime sanitizer — add "
                f"threadsan coverage driving this path, or the "
                f"hierarchy claim rests on the static model alone",
                engine="races", advisory=True))
    return out
