"""Dynamic drivers for the kernel contracts (graft-kern's second half).

Each driver materializes one adversarial case from a
:class:`~raft_tpu.analysis.contracts.KernelContract` sweep, runs the
kernel (interpret mode on CPU for tier-1; ``interpret=False`` for the
on-chip rerun in ``scripts/tpu_parity.py``), and judges it against an
XLA oracle built from the SAME arithmetic the kernel runs (dot_general
with f32 accumulation — a BLAS matmul would sum in a different order
and flip near-ties; learned in PR 8). Exact arms must match bitwise on
ids; partial-reduction arms must stay inside the contract's recall
band; every arm must honor the library-wide invalid-slot convention
((+inf, -1) pairs, no id at or past the live row count).

Cases marked ``static_only`` exist for the static engine's geometry
bindings (e.g. the packed i4/pq4 scan storage layouts) and are skipped
here — their dynamics are pinned by the dedicated ivf_pq / beam-step
suites.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class CaseReport:
    ok: bool
    kind: str          # "bitwise" | "recall" | "skipped" | "error"
    detail: str = ""
    recall: Optional[float] = None


_SEED = 0xC0FFEE


def _rng(case: dict):
    import zlib

    import numpy as np

    # deterministic per-case seed so failures reproduce standalone —
    # crc32 over the sorted repr, NOT hash(): str hashing is salted per
    # process (PYTHONHASHSEED), which would regenerate different data
    # on every rerun and make a CI/on-chip failure unreproducible
    blob = repr(sorted((k, str(v)) for k, v in case.items())).encode()
    return np.random.default_rng(_SEED + zlib.crc32(blob))


def _recall(got_ids, want_ids) -> float:
    import numpy as np

    got = np.asarray(got_ids)
    want = np.asarray(want_ids)
    rows = got.reshape(-1, got.shape[-1])
    wrows = want.reshape(-1, want.shape[-1])
    hits = []
    for g, w in zip(rows, wrows):
        w = w[w >= 0]
        if len(w) == 0:
            continue
        hits.append(len(np.intersect1d(g, w)) / len(w))
    return float(sum(hits) / max(len(hits), 1))


def _invalid_slots_ok(od, oi) -> Optional[str]:
    """(+inf, -1) must pair up exactly (the library-wide convention)."""
    import numpy as np

    od = np.asarray(od)
    oi = np.asarray(oi)
    if not ((oi == -1) == np.isinf(od)).all():
        return "invalid-slot contract broken: -1 ids and +inf distances " \
               "do not pair up"
    return None


# ---------------------------------------------------------------------------
# fused_topk (brute-force distance + partial top-k)
# ---------------------------------------------------------------------------


def _bf_oracle(qj, xj, metric_kind, k):
    """The kernel's own expanded-form arithmetic through XLA ops."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.ops.fused_topk import COSINE, IP, L2

    dots = jax.lax.dot_general(
        qj, xj, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if metric_kind == IP:
        dist = -dots
    else:
        q32 = qj.astype(jnp.float32)
        x32 = xj.astype(jnp.float32)
        xn = jnp.sum(x32 * x32, axis=1)
        if metric_kind == L2:
            qn = jnp.sum(q32 * q32, axis=1)
            dist = jnp.maximum(qn[:, None] + xn[None, :] - 2.0 * dots, 0.0)
        else:
            assert metric_kind == COSINE
            qa = jnp.linalg.norm(q32, axis=1)
            xlen = jnp.sqrt(jnp.maximum(xn, 1e-30))
            dist = 1.0 - dots / jnp.maximum(qa[:, None] * xlen[None, :],
                                            1e-30)
    negd, idx = jax.lax.top_k(-dist, k)
    return -negd, idx


def drive_fused_topk(contract, case: dict, interpret: bool = True
                     ) -> CaseReport:
    import numpy as np
    import jax.numpy as jnp

    from raft_tpu.ops.fused_topk import fused_topk

    if case.get("static_only"):
        return CaseReport(True, "skipped", "static-only geometry case")
    rng = _rng(case)
    m, n, d, k = case["m"], case["n"], case["d"], case["k"]
    variant = case["variant"]
    mk = case.get("metric_kind", 0)
    dtype = jnp.dtype(case.get("dtype", "float32"))
    q = jnp.asarray(rng.standard_normal((m, d)).astype(np.float32), dtype)
    x = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32), dtype)
    want_d, want = _bf_oracle(q, x, mk, k)
    want_d, want = np.asarray(want_d), np.asarray(want)
    od, oi = fused_topk(q, x, k, metric_kind=mk, variant=variant,
                        interpret=interpret)
    od_np, oi_np = np.asarray(od), np.asarray(oi)
    bad = _invalid_slots_ok(od, oi)
    if bad:
        return CaseReport(False, "error", bad)
    if oi_np.max() >= n:
        return CaseReport(False, "error",
                          f"id {oi_np.max()} at or past row count {n} "
                          "escaped the pad mask")
    if variant == "exact":
        if mk == 0 and dtype == jnp.float32:
            # tie-free continuous keys: ids must agree bitwise (the
            # pallas_parity contract). Distances are NOT compared
            # bitwise — XLA vectorizes the padded-tile dot and the
            # raw-oracle dot differently, so dots differ at ulp scale
            # without any selection consequence.
            if not (oi_np == want).all():
                frac = float((oi_np != want).mean())
                return CaseReport(False, "bitwise",
                                  f"{frac:.1%} of ids differ from the "
                                  "XLA oracle")
            return CaseReport(True, "bitwise")
        # bf16 / division-based metrics: ulp-scale epilogue differences
        # can flip genuine near-ties, so judge distances numerically
        # and ids as recall
        valid = np.isfinite(want_d)
        if not np.allclose(od_np[valid], want_d[valid],
                           rtol=1e-4, atol=1e-5):
            return CaseReport(False, "error",
                              "top-k distances diverge from the oracle "
                              "beyond ulp tolerance")
        r = _recall(oi_np, want)
        return CaseReport(r >= 0.99, "recall",
                          f"recall {r:.4f} vs floor 0.99", recall=r)
    r = _recall(oi_np, want)
    floor = contract.recall_floor
    return CaseReport(r >= floor, "recall",
                      f"recall {r:.4f} vs floor {floor}", recall=r)


# ---------------------------------------------------------------------------
# ivf list scan
# ---------------------------------------------------------------------------


def drive_list_scan(contract, case: dict, interpret: bool = True
                    ) -> CaseReport:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors.common import merge_topk
    from raft_tpu.ops import ivf_scan

    if case.get("static_only"):
        return CaseReport(True, "skipped", "static-only geometry case")
    rng = _rng(case)
    C, cap, d = case["C"], case["cap"], case["d"]
    G, nb, k = case["G"], case["nb"], case["k"]
    extract = case["extract"]
    rabitq = bool(case.get("rabitq"))
    dtype = jnp.dtype(case.get("dtype", "float32"))
    storage = rng.standard_normal((C, cap, d)).astype(np.float32)
    ids = np.arange(C * cap, dtype=np.int32).reshape(C, cap)
    buckets = (np.arange(nb, dtype=np.int32) % C)
    if rabitq:
        # materialize the sign-bit arm: storage rows become ±1 codes
        # packed 32/word (transposed [C, nw, cap]), per-row correction
        # fac = ||r||²/||r||₁, norms = TRUE ||r||², queries zero-padded
        # to the word width. The effective scanned vectors — what the
        # XLA oracle below scores — are the dequantized r̂ = fac·sign(r)
        # with the stored true-norm term.
        dp = case["dp"]
        signs = np.where(storage > 0, 1.0, -1.0).astype(np.float32)
        bits = (storage > 0).astype(np.uint32)
        bits = np.concatenate(
            [bits, np.zeros((C, cap, dp - d), np.uint32)], axis=2)
        words = (bits.reshape(C, cap, dp // 32, 32)
                 << np.arange(32, dtype=np.uint32)).sum(
                     axis=3, dtype=np.uint32)
        packed = np.swapaxes(words, 1, 2)                  # [C, nw, cap]
        l1 = np.abs(storage).sum(2)
        n2 = (storage ** 2).sum(2)
        fac = (n2 / np.maximum(l1, 1e-30)).astype(np.float32)
        # oracle scans the estimator's own arithmetic: dequantized rows
        # r̂ (zero-padded) against the padded query, true norms
        eff = signs * fac[:, :, None]                      # [C, cap, d]
        eff = np.concatenate(
            [eff, np.zeros((C, cap, dp - d), np.float32)], axis=2)
        true_norms = n2.astype(np.float32)
        qfull = rng.standard_normal((nb, G, d)).astype(np.float32)
        qpad = np.concatenate(
            [qfull, np.zeros((nb, G, dp - d), np.float32)], axis=2)
        qv = jnp.asarray(qpad, dtype)
    else:
        qv = jnp.asarray(rng.standard_normal((nb, G, d)).astype(np.float32),
                         dtype)
    # two passes over the SAME shapes: full lists, then short lists
    # (the live-size tail the extraction must mask) — no extra trace
    for size in (cap, max(1, min(cap, k) if k < cap else cap // 2 + 1)):
        sizes = np.full((C,), size, np.int32)
        q32 = qv.astype(jnp.float32)
        qaux = jnp.sum(q32 * q32, axis=2)
        if rabitq:
            norms = jnp.asarray(true_norms)
            od, oi = ivf_scan.fused_list_scan_topk(
                jnp.asarray(packed), jnp.asarray(ids), jnp.asarray(sizes),
                jnp.asarray(buckets), qv, qaux, norms, None,
                row_scale=jnp.asarray(fac),
                k=k, metric_kind=ivf_scan.L2,
                approx=extract != "exact", interpret=interpret,
                packed_bits=True, extract=extract)
        else:
            norms = jnp.asarray((storage ** 2).sum(2).astype(np.float32))
            od, oi = ivf_scan.fused_list_scan_topk(
                jnp.asarray(storage), jnp.asarray(ids), jnp.asarray(sizes),
                jnp.asarray(buckets), qv, qaux, norms, None,
                k=k, metric_kind=ivf_scan.L2,
                approx=extract != "exact", interpret=interpret,
                extract=extract)
        if extract == "fold":
            nb_, G_, kc = oi.shape
            od2, oi2 = merge_topk(
                jnp.asarray(od).reshape(nb_ * G_, kc),
                jnp.asarray(oi).reshape(nb_ * G_, kc), min(k, kc), True)
            od = np.asarray(od2).reshape(nb_, G_, -1)
            oi = np.asarray(oi2).reshape(nb_, G_, -1)
        od, oi = np.asarray(od), np.asarray(oi)
        bad = _invalid_slots_ok(od, oi)
        if bad:
            return CaseReport(False, "error", f"size={size}: {bad}")
        # oracle: the kernel's expanded arithmetic over the live rows —
        # for the rabitq arm that means dot against the DECODED ±1
        # signs first, THEN the per-row fac scale (matching the
        # kernel's S·fac association; fac-premultiplied rows would
        # round differently and flip near-ties on the bitwise arm)
        want = np.full((nb, G, k), -1, np.int64)
        for b in range(nb):
            if rabitq:
                sp_ = np.concatenate(
                    [signs[buckets[b]],
                     -np.ones((cap, dp - d), np.float32)], axis=1)
                blk = jnp.asarray(sp_, dtype)
            else:
                blk = jnp.asarray(storage[buckets[b]], dtype)
            dots = jax.lax.dot_general(
                qv[b], blk, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dots = np.asarray(dots)
            if rabitq:
                dots = dots * fac[buckets[b]][None, :]
            qn = np.asarray(qaux[b])
            xn = np.asarray(norms[buckets[b]])
            dist = np.maximum(qn[:, None] + xn[None, :]
                              - 2.0 * dots, 0.0)
            dist[:, size:] = np.inf
            order = np.argsort(dist, axis=1, kind="stable")[:, :k]
            w = ids[buckets[b]][order]
            w[np.take_along_axis(dist, order, axis=1) == np.inf] = -1
            want[b, :, :] = w
        live = oi[oi >= 0]
        if live.size and (live % cap >= size).any():
            return CaseReport(
                False, "error",
                f"size={size}: a tombstoned/tail row id escaped the "
                "live-size mask")
        if extract == "exact" and dtype == jnp.float32:
            if not (oi == want).all():
                frac = float((oi != want).mean())
                return CaseReport(False, "bitwise",
                                  f"size={size}: {frac:.1%} of ids differ "
                                  "from the XLA oracle")
        else:
            r = _recall(oi, want)
            if r < contract.recall_floor:
                return CaseReport(False, "recall",
                                  f"size={size}: recall {r:.4f} under "
                                  f"floor {contract.recall_floor}",
                                  recall=r)
    return CaseReport(True,
                      "bitwise" if extract == "exact" else "recall")


# ---------------------------------------------------------------------------
# beam merge step
# ---------------------------------------------------------------------------


def _packed_score_xla(pack, qrep, parents, deg: int, d: int, ip: bool,
                      interpret_match: bool = False):
    """The beam kernel's packed-row scoring, re-expressed op for op
    (2-op sign-extending byte extract, bf16 product, f32 accumulation,
    one-hot segment matmul). With ``interpret_match`` the mirror runs
    inside a trivial interpret-mode ``pallas_call`` so its bf16
    intermediates round exactly like the kernel under test's (interpret
    mode evaluates bf16 at different intermediate precision than plain
    XLA — without the wrapper the two sides differ at rounding scale)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from raft_tpu.ops.beam_step import _INVALID, packed_row_layout

    m, width, W = pack.shape
    dw, o_norm, o_id, _ = packed_row_layout(deg, d, ip)

    def score(pack_v, qrep_v, parents_v):
        seg = (
            jax.lax.broadcasted_iota(jnp.int32, (dw, deg), 0) // (d // 4)
            == jax.lax.broadcasted_iota(jnp.int32, (dw, deg), 1)
        ).astype(jnp.float32)
        cds, cis = [], []
        for w in range(width):
            words = pack_v[:, w, :dw]                    # [m, dw]
            acc = jnp.zeros((m, dw), jnp.float32)
            for j in range(4):
                b = (words << (24 - 8 * j)) >> 24
                acc = acc + (b.astype(jnp.bfloat16) * qrep_v[:, j, :]
                             ).astype(jnp.float32)
            dots = jax.lax.dot_general(
                acc, seg, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)      # [m, deg]
            idw = pack_v[:, w, o_id:o_id + deg]
            if ip:
                cdw = -dots
            else:
                cdw = jax.lax.bitcast_convert_type(
                    pack_v[:, w, o_norm:o_norm + deg], jnp.float32) - dots
            pok = (parents_v[w, :] >= 0)[:, None]
            cdw = jnp.where((idw < 0) | (~pok), jnp.inf, cdw)
            idw = jnp.where(pok, idw, _INVALID)
            cds.append(cdw.T)
            cis.append(idw.T)
        return jnp.concatenate(cds, axis=0), jnp.concatenate(cis, axis=0)

    if not interpret_match:
        return score(pack, qrep, parents)

    def kernel(pack_ref, qrep_ref, par_ref, cd_ref, ci_ref):
        cd, ci = score(pack_ref[...], qrep_ref[...], par_ref[...])
        cd_ref[...] = cd
        ci_ref[...] = ci

    C = width * deg
    return pl.pallas_call(
        kernel,
        out_shape=[jax.ShapeDtypeStruct((C, m), jnp.float32),
                   jax.ShapeDtypeStruct((C, m), jnp.int32)],
        interpret=True,
    )(pack, qrep, parents)


def _drive_beam_packed(contract, case: dict, interpret: bool
                       ) -> CaseReport:
    """Drive the packed-scoring arm: real inline rows built by the
    cagra packer, in-kernel decode+score+merge vs the same scoring
    through XLA feeding the numpy merge oracle. Interpret mode asserts
    bitwise; a compiled run (tpu_parity) is judged per-id within
    rounding and as set recall, because MXU accumulation order can flip
    genuine near-ties the CPU oracle cannot reproduce."""
    import numpy as np
    import jax.numpy as jnp

    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors import cagra
    from raft_tpu.ops.beam_step import beam_merge_step

    rng = _rng(case)
    L, m, width = case["L"], case["m"], case["width"]
    deg, d = case["deg"], case["d"]
    window = case.get("window", 2)
    ip = bool(case.get("ip", False))
    emit = bool(case.get("emit_cands", False))
    g = case.get("g", 128)
    n = 512
    x = rng.standard_normal((n, d)).astype(np.float32)
    graph = rng.integers(0, n, (n, deg)).astype(np.int32)
    metric = (DistanceType.InnerProduct if ip
              else DistanceType.L2Expanded)
    idx = cagra.from_graph(x, graph, metric)
    if idx.nbr_pack is None:
        return CaseReport(False, "error", "inline layout unavailable")

    q = rng.standard_normal((m, d)).astype(np.float32)
    two_scale = (1.0 if ip else 2.0) * idx.code_scale
    qs = jnp.asarray(q * two_scale, jnp.bfloat16)
    qperm = jnp.transpose(qs.reshape(m, d // 4, 4), (0, 2, 1))
    qrep = jnp.tile(qperm, (1, 1, deg))                  # [m, 4, dw]
    parents = rng.integers(0, n, (width, m)).astype(np.int32)
    parents[rng.random((width, m)) < 0.1] = -1           # masked blocks
    parents = jnp.asarray(parents)
    pack = idx.nbr_pack[jnp.maximum(parents.T, 0)]       # [m, width, W]

    bd = np.full((L, m), np.inf, np.float32)
    bi = np.full((L, m), -1, np.int32)
    be = np.zeros((L, m), np.int32)
    outs = beam_merge_step(
        jnp.asarray(bd), jnp.asarray(bi), jnp.asarray(be),
        qrep=qrep, pack=pack, parents=parents,
        deg=deg, d=d, width=width, window=window, ip=ip, g=g,
        interpret=interpret, emit_cands=emit,
    )
    od, oi, oe, par = outs[:4]

    cd, ci = _packed_score_xla(pack, qrep, parents, deg, d, ip,
                               interpret_match=interpret)
    cd_np, ci_np = np.asarray(cd), np.asarray(ci)
    wd, wi, we, wpar = _np_beam_oracle(bd, bi, be, cd_np, ci_np, L,
                                       width, window)
    if emit:
        ocd, oci = np.asarray(outs[4]), np.asarray(outs[5])
        if interpret and not ((oci == ci_np).all()
                              and np.allclose(ocd[np.isfinite(ocd)],
                                              cd_np[np.isfinite(cd_np)])):
            return CaseReport(False, "bitwise",
                              "emit_cands candidates differ from the "
                              "XLA decode oracle")
    oi_np, od_np = np.asarray(oi), np.asarray(od)
    if interpret:
        if not (oi_np == wi).all():
            return CaseReport(False, "bitwise",
                              "packed-arm merged ids differ from the "
                              "XLA-decode + numpy merge oracle")
        if not (np.asarray(par) == wpar).all():
            return CaseReport(False, "bitwise",
                              "packed-arm picked parents differ")
        return CaseReport(True, "bitwise")
    # compiled: judge per-id distances + set recall (rounding-robust)
    want_map = [dict(zip(ci_np[:, c], cd_np[:, c])) for c in range(m)]
    for c in range(m):
        for t in range(L):
            if oi_np[t, c] < 0:
                continue
            w = want_map[c].get(oi_np[t, c])
            if w is None:
                return CaseReport(False, "error",
                                  f"col {c}: id {oi_np[t, c]} was never "
                                  "a candidate")
            if np.isfinite(w) and abs(od_np[t, c] - w) > \
                    1e-2 * max(1.0, abs(w)):
                return CaseReport(False, "recall",
                                  f"col {c}: distance for id "
                                  f"{oi_np[t, c]} off the decode oracle")
    r = _recall(oi_np.T, wi.T)
    return CaseReport(r >= 0.98, "recall",
                      f"packed-arm merged-id recall {r:.4f}", recall=r)


def drive_beam_step(contract, case: dict, interpret: bool = True
                    ) -> CaseReport:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from raft_tpu.ops.beam_step import beam_merge_step

    if case.get("static_only"):
        return CaseReport(True, "skipped", "static-only geometry case")
    if not case.get("scored", True):
        return _drive_beam_packed(contract, case, interpret)
    rng = _rng(case)
    L, C, m, width = case["L"], case["C"], case["m"], case["width"]
    window = case.get("window", 2)
    # distance == id: globally unique keys, ties only between duplicate
    # ids (the windowed-dedup invariant, as in test_beam_step)
    bi = rng.permutation(np.arange(0, 4 * (L + C) * m))[: L * m] \
        .reshape(L, m).astype(np.int32)
    be = (rng.random((L, m)) < 0.5).astype(np.int32)
    ci = rng.permutation(
        np.arange(4 * (L + C) * m, 8 * (L + C) * m))[: C * m] \
        .reshape(C, m).astype(np.int32)
    for c in range(m):
        ndup = max(1, min(C // 4, L, C))   # tiny-buffer cases: L < C//4
        slots = rng.choice(C, size=ndup, replace=False)
        rows = rng.choice(L, size=ndup, replace=False)
        ci[slots, c] = bi[rows, c]
    bd = bi.astype(np.float32)
    cd = ci.astype(np.float32)
    order = np.argsort(bd, axis=0, kind="stable")
    bd = np.take_along_axis(bd, order, axis=0)
    bi = np.take_along_axis(bi, order, axis=0)
    be = np.take_along_axis(be, order, axis=0)

    od, oi, oe, par = jax.jit(
        lambda a, b, c, e, f: beam_merge_step(
            a, b, c, cand_d=e, cand_i=f, width=width, window=window,
            g=case.get("g", 128), interpret=interpret)
    )(jnp.asarray(bd), jnp.asarray(bi), jnp.asarray(be),
      jnp.asarray(cd), jnp.asarray(ci))

    wd, wi, we, wpar = _np_beam_oracle(bd, bi, be, cd, ci, L, width,
                                       window)
    if not (np.asarray(oi) == wi).all():
        return CaseReport(False, "bitwise", "merged ids differ from the "
                                            "numpy oracle")
    if not np.allclose(np.asarray(od), wd, rtol=1e-6):
        return CaseReport(False, "bitwise", "merged distances differ")
    if not (np.asarray(par) == wpar).all():
        return CaseReport(False, "bitwise", "picked parents differ")
    return CaseReport(True, "bitwise")


def _np_beam_oracle(bd, bi, be, cd, ci, L, width, window=2):
    """Numpy mirror of one beam merge step — THE single oracle home:
    tests/test_beam_step.py imports it (as its ``_np_merge_oracle``)
    and the contract sweep + tpu_parity's compiled rerun use it here,
    so every beam assertion judges against the same semantics."""
    import numpy as np

    m = bd.shape[1]
    LL = 1 << (L + cd.shape[0] - 1).bit_length()
    od = np.full((L, m), np.inf, np.float32)
    oi = np.full((L, m), -1, np.int32)
    oe = np.ones((L, m), np.int32)
    parents = np.full((width, m), -1, np.int32)
    for c in range(m):
        rows = list(zip(bd[:, c], bi[:, c], be[:, c])) + [
            (cd[j, c], ci[j, c], 0) for j in range(cd.shape[0])
        ]
        rows += [(np.inf, -1, 1)] * (LL - len(rows))
        rows.sort(key=lambda t: t[0])
        dist = np.array([r[0] for r in rows], np.float32)
        ids = np.array([r[1] for r in rows], np.int32)
        expl = np.array([r[2] for r in rows], np.int32)
        dup = np.zeros(LL, bool)
        e = expl.copy()
        for s in range(1, window + 1):
            eq = (ids[s:] == ids[:-s]) & (ids[s:] >= 0)
            dup[s:] |= eq
            e[:-s] |= eq & (expl[s:] > 0)
        dist = np.where(dup, np.inf, dist)
        ids = np.where(dup, -1, ids)
        e = np.where(dup, 1, e)
        got = 0
        for t in range(L):
            od[t, c], oi[t, c], oe[t, c] = dist[t], ids[t], e[t]
            if not e[t] and ids[t] >= 0 and np.isfinite(dist[t]) \
                    and got < width:
                parents[got, c] = ids[t]
                oe[t, c] = 1
                got += 1
    return od, oi, oe, parents


# ---------------------------------------------------------------------------
# graph local join (nn-descent fused score + unique-merge)
# ---------------------------------------------------------------------------


def drive_graph_join(contract, case: dict, interpret: bool = True
                     ) -> CaseReport:
    """Drive one fused local-join case against the XLA dispatch
    fallback (the bitwise oracle): einsum scoring + the keep-min
    ``_merge_topk_unique``. Planted hazards per case: invalid candidate
    slots, duplicate candidates within a row, candidates already on the
    current list (both provenances of a duplicate id), rows with no
    valid candidate at all."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors.nn_descent import _merge_topk_unique
    from raft_tpu.ops.graph_join import graph_local_join

    if case.get("static_only"):
        return CaseReport(True, "skipped", "static-only geometry case")
    rng = _rng(case)
    B, C, d, K = case["B"], case["C"], case["d"], case["K"]
    ip = bool(case.get("ip", False))
    tile_b = case.get("tile_b")
    N = max(4 * (K + C), 64)
    vecs = rng.standard_normal((N, d)).astype(np.float32)
    q = rng.standard_normal((B, d)).astype(np.float32)
    cand = rng.integers(0, N, (B, C)).astype(np.int32)
    cand[rng.random((B, C)) < 0.15] = -1                 # invalid slots
    if C >= 2:
        cand[:, 1] = cand[:, 0]                          # in-row dup
    cur_i = np.stack([
        rng.choice(N, size=min(K, N), replace=False)[:K].astype(np.int32)
        for _ in range(B)
    ])
    live = rng.integers(1, K + 1, B)                     # short lists too
    cur_i[np.arange(K)[None, :] >= live[:, None]] = -1
    if C >= 3:
        # candidate that already sits on the list (cross-provenance dup)
        cand[:, 2] = cur_i[:, 0]
    if B >= 2:
        # starved row LAST, so the dup plants above cannot re-validate
        # it — the exhausted-pool sentinel path (m=inf -> id -1) must
        # stay exercised in the compiled sweep too
        cand[B - 1, :] = -1
    norms = (vecs ** 2).sum(1).astype(np.float32)
    qn = (q ** 2).sum(1).astype(np.float32)
    cs = np.maximum(cand, 0)
    dots = np.einsum("bd,bkd->bk", q, vecs[np.maximum(cur_i, 0)])
    if ip:
        cur_d = -dots
    else:
        cur_d = np.maximum(
            qn[:, None] + norms[np.maximum(cur_i, 0)] - 2.0 * dots, 0.0)
    cur_d = np.where(cur_i < 0, np.inf, cur_d).astype(np.float32)

    kd, ki = graph_local_join(
        jnp.asarray(q), jnp.asarray(cand), jnp.asarray(vecs[cs]),
        jnp.asarray(cur_d), jnp.asarray(cur_i),
        None if ip else jnp.asarray(qn),
        None if ip else jnp.asarray(norms[cs]),
        ip=ip, tile_b=tile_b, interpret=interpret,
    )
    # oracle: the XLA fallback path's own arithmetic (nn_descent._score
    # einsum + keep-min merge)
    odots = jnp.einsum(
        "bd,bcd->bc", jnp.asarray(q), jnp.asarray(vecs[cs]),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGH)
    if ip:
        cd = -odots
    else:
        cd = jnp.maximum(jnp.asarray(qn)[:, None]
                         + jnp.asarray(norms[cs]) - 2.0 * odots, 0.0)
    cd = jnp.where(jnp.asarray(cand) < 0, jnp.inf, cd)
    wd, wi = _merge_topk_unique(
        jnp.asarray(cur_d), jnp.asarray(cur_i), cd, jnp.asarray(cand), K)
    kd_np, ki_np = np.asarray(kd), np.asarray(ki)
    wd_np, wi_np = np.asarray(wd), np.asarray(wi)
    bad = _invalid_slots_ok(kd_np, ki_np)
    if bad:
        return CaseReport(False, "error", bad)
    if ki_np.max() >= N:
        return CaseReport(False, "error",
                          f"id {ki_np.max()} past the vector pool")
    for b in range(B):
        row = ki_np[b][ki_np[b] >= 0]
        if np.unique(row).size != row.size:
            return CaseReport(False, "error",
                              f"row {b}: duplicate id in the merged "
                              "top-K (uniqueness contract broken)")
    if not (ki_np == wi_np).all():
        frac = float((ki_np != wi_np).mean())
        return CaseReport(False, "bitwise",
                          f"{frac:.1%} of merged ids differ from the "
                          "XLA fallback oracle")
    fin = np.isfinite(wd_np)
    if not np.allclose(kd_np[fin], wd_np[fin], rtol=1e-5, atol=1e-5):
        return CaseReport(False, "bitwise",
                          "merged distances diverge from the XLA "
                          "fallback beyond ulp tolerance")
    return CaseReport(True, "bitwise")


# ---------------------------------------------------------------------------
# select_k rungs (hierarchical / tournament)
# ---------------------------------------------------------------------------


def drive_select_k(contract, case: dict, interpret: bool = True
                   ) -> CaseReport:
    import numpy as np
    import jax.numpy as jnp

    from raft_tpu.matrix.select_k import select_k

    if case.get("static_only"):
        return CaseReport(True, "skipped", "static-only geometry case")
    rng = _rng(case)
    batch, n, k = case["batch"], case["n"], case["k"]
    impl = case["impl"]
    dtype = jnp.dtype(case.get("dtype", "float32"))
    distinct = True
    if dtype == jnp.bool_:
        x = rng.random((batch, n)) < 0.5
        distinct = False
    elif jnp.issubdtype(dtype, jnp.integer):
        # offset past 2^24: pins the integer-domain exactness the f32
        # cast collapses (the ADVICE-r5 class)
        base = np.stack([rng.permutation(n) for _ in range(batch)])
        x = (base + (2**25 if jnp.dtype(dtype).itemsize >= 4 else 7)
             ).astype(dtype)
    else:
        x = np.stack([rng.permutation(n) for _ in range(batch)]) \
            .astype(np.float32)
        if case.get("nan"):
            x[x % 7 == 3] = np.nan
            distinct = False
        # graft-lint: allow-host-sync oracle harness materializes the dtype-rounded keys on host by design
        x = np.asarray(jnp.asarray(x, dtype))
        distinct = distinct and dtype == jnp.float32
    xj = jnp.asarray(x, dtype)
    for select_min in (True, False):
        vals, idxs = select_k(xj, k, select_min=select_min, impl=impl)
        # graft-lint: allow-f64 host-side numpy oracle comparison space (never reaches a device)
        vals = np.asarray(vals).astype(np.float64)
        idxs = np.asarray(idxs)
        # graft-lint: allow-f64 host-side numpy oracle comparison space (never reaches a device)
        xs = np.asarray(xj).astype(np.float64)
        if case.get("nan"):
            xs = np.where(np.isnan(xs), np.inf if select_min else -np.inf,
                          xs)
        order = np.argsort(xs if select_min else -xs, axis=1,
                           kind="stable")[:, :k]
        want_vals = np.take_along_axis(xs, order, axis=1)
        got_vals = np.where(np.isnan(vals),
                            np.inf if select_min else -np.inf, vals)
        if not (np.sort(got_vals, axis=1)
                == np.sort(want_vals, axis=1)).all():
            return CaseReport(
                False, "bitwise",
                f"select_min={select_min}: selected value multiset "
                "differs from the sort oracle")
        if distinct and not (idxs == order).all():
            return CaseReport(
                False, "bitwise",
                f"select_min={select_min}: ids differ from the stable "
                "sort oracle on distinct keys")
    return CaseReport(True, "bitwise")
