"""ANN benchmark harness.

Analog of the reference's bench driver (cpp/bench/ann/src/common/
benchmark.hpp: ``bench_build``:124, ``bench_search``:174, in-harness recall
:341-375) and the raft-ann-bench orchestration
(python/raft-ann-bench/src/raft-ann-bench/run/__main__.py): JSON configs
name a dataset + algo + param sets; the harness builds, searches, computes
recall against ground truth, and reports QPS / latency / build time.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np


@dataclasses.dataclass
class BenchResult:
    name: str
    build_s: float
    search_s: float
    qps: float
    recall: float
    k: int
    n_queries: int
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def row(self) -> dict:
        return {
            "name": self.name,
            "build_time": self.build_s,
            "search_time": self.search_s,
            "qps": self.qps,
            "recall": self.recall,
            "k": self.k,
            "n_queries": self.n_queries,
            **self.extra,
        }


def compute_recall(found_idx: np.ndarray, true_idx: np.ndarray) -> float:
    """Set-intersection recall@k (reference benchmark.hpp:341-375)."""
    n, k = found_idx.shape
    true_idx = true_idx[:, :k]
    hits = 0
    for i in range(n):
        hits += len(np.intersect1d(found_idx[i], true_idx[i], assume_unique=False))
    return hits / (n * k)


def time_fn(fn: Callable[[], Any], iters: int = 10, warmup: int = 2) -> float:
    """Mean wall-clock of fn() amortized over a pipelined batch.

    Dispatch latency to the device (especially over a remote-tunnel
    platform) is amortized by enqueueing ``iters`` calls back-to-back and
    materializing only the final result on the host — the same way a
    production search service pipelines query batches. Per-call blocking
    would measure round-trip latency, not throughput.

    CAVEAT: on a remote-tunnel platform, repeated *identical* calls can be
    served from a result cache and unfetched outputs may be elided, so
    this can over-report. Prefer ``scan_qps_time`` (distinct inputs,
    on-device loop, two-point timing) when the workload can be expressed
    as ``step(queries)``.
    """
    out = None
    for _ in range(warmup):
        out = fn()
    # graft-lint: allow-host-sync bench timing — the fetch IS the measurement fence
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    # graft-lint: allow-host-sync bench timing — the fetch IS the measurement fence
    np.asarray(jax.tree_util.tree_leaves(out)[0])  # fetch forces completion
    return (time.perf_counter() - t0) / iters


def scan_qps_time(search_step, queries, n1: int = 3, n2: int = 13,
                  operands=None) -> float:
    """Trustworthy per-iteration seconds of ``search_step(q) -> (d, i)``
    (or ``search_step(q, operands)`` when ``operands`` is given).

    Runs N iterations of the step *inside one jitted program* (lax.scan),
    each on a rolled — hence distinct — query batch, folding every output
    into a returned checksum so no iteration can be cached or elided.
    Times the program at two iteration counts and reports
    (T2-T1)/(N2-N1), cancelling constant dispatch/RTT/fetch overhead.
    This is steady-state on-device throughput, robust against the axon
    tunnel's async ``block_until_ready`` and result caching.

    Pass the index through ``operands`` (any pytree — the Index
    dataclasses are registered pytrees): closure-captured arrays would be
    baked into the HLO as constants, which blows up remote compilation
    for GB-scale indexes.
    """
    import jax.numpy as jnp

    def runner(iters):
        @jax.jit
        def run(qs, salt, ops):
            def body(carry, i):
                q = jnp.roll(qs, i + 1 + salt, axis=0)
                if ops is None:
                    d, idx = search_step(q)
                else:
                    d, idx = search_step(q, ops)
                return carry + d.sum() + idx.sum(), None

            acc, _ = jax.lax.scan(body, jnp.float32(0.0), jnp.arange(iters))
            return acc

        return run

    # every executed (program, input) pair is unique — the `salt` operand
    # changes each call so a platform-level result cache can never serve a
    # timed execution from the warmup (or a previous timed) run
    r1, r2 = runner(n1), runner(n2)
    # graft-lint: allow-host-sync bench timing — sync fences bracket each timed run
    _ = float(r1(queries, jnp.int32(0), operands))  # compile + warm both
    # graft-lint: allow-host-sync bench timing
    _ = float(r2(queries, jnp.int32(1), operands))
    t0 = time.perf_counter()
    # graft-lint: allow-host-sync bench timing
    _ = float(r1(queries, jnp.int32(2), operands))
    t1 = time.perf_counter()
    # graft-lint: allow-host-sync bench timing
    _ = float(r2(queries, jnp.int32(3), operands))
    t2 = time.perf_counter()
    per_iter = ((t2 - t1) - (t1 - t0)) / (n2 - n1)
    if per_iter <= 0:
        # fast workloads on a local backend can be noise-dominated; fall
        # back to the overhead-inclusive total (never over-reports QPS)
        t3 = time.perf_counter()
        # graft-lint: allow-host-sync bench timing
        _ = float(r2(queries, jnp.int32(4), operands))
        per_iter = (time.perf_counter() - t3) / n2
    return per_iter


# ---------------------------------------------------------------------------
# Roofline (ROADMAP item 1: "fast as the hardware allows" as a NUMBER)
# ---------------------------------------------------------------------------

# Peak-throughput specs per dispatch backend (tuning.backend_name()),
# captured 2026-08-04 (r6):
# - tpu: TPU v5e (v5 lite, the axon chip) — 197 TFLOP/s bf16 MXU peak
#   and 819 GB/s HBM per chip (public v5e spec sheet). f32-carried
#   matmuls run multi-pass on the MXU, so bf16 peak is the honest
#   denominator for the bf16-operand hot paths this repo ships.
# - cpu: placeholder spec for the CI container (no public number for a
#   fractional-socket slice). On CPU the roofline column DOCUMENTS THE
#   HARNESS — the fractions are only meaningful relative to each other,
#   never as a hardware claim (BENCH artifacts carry the backend name).
# Every spec row carries machine-readable provenance (``source`` +
# ``recorded``): GL005 (undated-perf) demands each number name its
# origin, and the roofline output echoes ``peak_source`` into every
# BENCH artifact so a stale spec is detectable from the artifact alone.
PEAK_SPECS = {
    "tpu": {"flops_peak": 197.0e12, "hbm_gbps": 819.0,
            "recorded": "2026-08-04",
            "source": "TPU v5e public spec sheet (bf16 MXU peak, "
                      "per-chip HBM), recorded 2026-08-04 (r6)"},
    "cpu": {"flops_peak": 1.0e11, "hbm_gbps": 25.0,
            "recorded": "2026-08-04",
            "source": "CI-host placeholder (harness documentation only),"
                      " recorded 2026-08-04 (r6)"},
}


def roofline(bytes_moved: float, flops: float, seconds: float,
             backend: Optional[str] = None) -> dict:
    """One roofline row: achieved GB/s + GFLOP/s against the backend's
    peak spec, which ceiling binds, and the achieved fraction of that
    ceiling (docs/kernels.md §roofline).

    ``bytes_moved``/``flops`` are the op's COST MODEL (ideal HBM traffic
    and arithmetic of the algorithm as implemented); ``seconds`` the
    measured wall time. ``peak_fraction`` is achieved/peak on the
    BINDING axis: ops whose arithmetic intensity (flops/byte) clears
    the ridge point are scored against the FLOP/s peak, the rest
    against HBM bandwidth — so 1.0 always means "the hardware can do no
    better", which is exactly the ROADMAP's finish line."""
    if backend is None:
        from raft_tpu import tuning

        backend = tuning.backend_name()
    spec = PEAK_SPECS.get(backend, PEAK_SPECS["cpu"])
    seconds = max(float(seconds), 1e-12)
    gbps = bytes_moved / seconds / 1e9
    gflops = flops / seconds / 1e9
    intensity = flops / max(bytes_moved, 1.0)
    ridge = spec["flops_peak"] / (spec["hbm_gbps"] * 1e9)
    bound = "compute" if intensity >= ridge else "memory"
    frac = (gflops * 1e9 / spec["flops_peak"] if bound == "compute"
            else gbps / spec["hbm_gbps"])
    return {
        "backend": backend,
        "bytes": int(bytes_moved),
        "flops": int(flops),
        "gbps": round(gbps, 2),
        "gflops": round(gflops, 2),
        "intensity_flops_per_byte": round(intensity, 3),
        "ridge_flops_per_byte": round(ridge, 3),
        "bound": bound,
        "peak_fraction": round(frac, 4),
        "peak_source": spec["source"],
    }


def probe_tpu(timeout_s: float = 120.0):
    """Subprocess probe for a live TPU-class backend (platform 'tpu' or
    'axon'). Returns (ok, detail). A subprocess because the known outage
    mode HANGS inside device init holding the GIL (no in-process
    deadline can fire), and because a clean init failure silently falls
    back to the CPU backend — which must read as unavailable, not as a
    catastrophically slow TPU. Shared by bench.py and the measurement
    battery runner."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "assert d[0].platform.lower() in ('tpu', 'axon'), d; "
             "print(d)"],
            timeout=timeout_s, capture_output=True,
        )
        out = (r.stdout + r.stderr).decode(errors="replace")[-200:]
        return r.returncode == 0, out
    except subprocess.TimeoutExpired:
        return False, "probe timeout (backend init hang)"


def latency_percentiles(search_step, queries, batch: int,
                        n_calls: int = 50, operands=None) -> dict:
    """Per-call latency distribution for small-batch serving (the
    reference's `--mode latency` measurement,
    docs/source/raft_ann_benchmarks.md:240-254): each timed call
    dispatches ONE ``batch``-sized query slice and blocks for its
    result — end-to-end serving latency including dispatch, which is
    what a latency SLO sees (unlike scan-chained throughput timing,
    which amortizes dispatch away). Every call — warmup included —
    dispatches a DISTINCT row rotation of the pool (strided slicing
    degenerates to a repeated slice whenever (m - batch) divides batch,
    m == batch included), defeating platform result caching for any
    n_calls < m. Rotation is materialized before the clock starts.
    Returns seconds: {p50, p95, mean, batch, n_calls}.
    """
    import jax
    import jax.numpy as jnp

    m = queries.shape[0]
    if m < batch:
        raise ValueError(f"need >= {batch} queries, got {m}")
    jitted = jax.jit(
        search_step if operands is None
        else functools.partial(search_step, ops=operands)
    )
    # warmup/compile on rotation n_calls+1 — outside the timed rotation
    # set {1..n_calls}, so no timed call can be served its cached result
    qs = jnp.roll(queries, n_calls + 1, axis=0)[:batch]
    jax.block_until_ready(jitted(qs))
    times = []
    for c in range(n_calls):
        q = jnp.roll(queries, c + 1, axis=0)[:batch]
        q = jax.block_until_ready(q)   # keep rotation out of the timed call
        t0 = time.perf_counter()
        out = jitted(q)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    arr = np.sort(np.asarray(times))
    return {
        "p50": float(np.percentile(arr, 50)),
        "p95": float(np.percentile(arr, 95)),
        "mean": float(arr.mean()),
        "batch": batch,
        "n_calls": n_calls,
    }


def run_case(
    name: str,
    build_fn: Callable[[], Any],
    search_fn: Callable[[Any], tuple],
    true_idx: np.ndarray,
    k: int,
    n_queries: int,
    iters: int = 10,
    extra: Optional[dict] = None,
) -> BenchResult:
    t0 = time.perf_counter()
    index = build_fn()
    # block on every array the build produced (norms, list structures, ...),
    # not just the dataset, so build_s covers the whole build
    leaves = [
        v for v in vars(index).values() if isinstance(v, jax.Array)
    ] if hasattr(index, "__dict__") else [index]
    jax.block_until_ready(leaves)
    build_s = time.perf_counter() - t0

    dist, idx = search_fn(index)
    jax.block_until_ready(idx)
    recall = compute_recall(np.asarray(idx), true_idx)
    search_s = time_fn(lambda: search_fn(index)[1], iters=iters)
    return BenchResult(
        name=name,
        build_s=build_s,
        search_s=search_s,
        qps=n_queries / search_s,
        recall=recall,
        k=k,
        n_queries=n_queries,
        extra=extra or {},
    )


def write_obs_snapshot(path: str) -> str:
    """Write the graft-scope metrics snapshot (docs/observability.md) as
    a JSON sidecar next to a bench artifact — every ``BENCH_*.json`` run
    with ``--obs-snapshot`` gains the dispatch-winner counts, per-algo
    latency histograms, ladder/retry counters, and device memory gauges
    that explain its headline numbers. Returns ``path``."""
    from raft_tpu import obs

    return obs.write_snapshot(path)


def export_csv(results: List[BenchResult], path: str) -> None:
    """gbench-JSON→CSV analog (raft-ann-bench data_export)."""
    import csv

    rows = [r.row() for r in results]
    if not rows:
        return
    keys = sorted({k for r in rows for k in r})
    with open(path, "w", newline="") as fp:
        w = csv.DictWriter(fp, fieldnames=keys)
        w.writeheader()
        w.writerows(rows)


def pareto_frontier(results: List[BenchResult]) -> List[BenchResult]:
    """Recall-vs-QPS Pareto frontier (raft-ann-bench plot's frontier logic)."""
    frontier: List[BenchResult] = []
    best_qps = -1.0
    for r in sorted(results, key=lambda r: (-r.recall, -r.qps)):
        if r.qps > best_qps:
            frontier.append(r)
            best_qps = r.qps
    return frontier
