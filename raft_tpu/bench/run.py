"""Config-driven benchmark orchestration — the raft-ann-bench analog
(reference python/raft-ann-bench/src/raft-ann-bench/run/__main__.py:62-130
and its conf/*.json format; plot: .../plot/__main__.py).

A config names a dataset (file-backed .fbin or a synthetic spec) and a
list of index definitions, each with one build param set and many search
param sets — exactly the reference layout:

    {
      "dataset": {"name": "sift-1m-synth", "synthetic": {"n": 1000000,
                  "dim": 128, "n_queries": 10000, "seed": 1},
                  "distance": "sqeuclidean", "k": 10},
      "index": [
        {"name": "ivf_flat.1024", "algo": "ivf_flat",
         "build_param": {"n_lists": 1024},
         "search_params": [{"n_probes": 16}, {"n_probes": 64}]}
      ]
    }

File-backed datasets use ``base_file``/``query_file``/``groundtruth_file``
(big-ann .fbin/.ibin layout, bench/datasets.py). Ground truth is computed
with tiled brute force and cached next to the dataset when absent —
the reference's generate_groundtruth tool
(python/raft-ann-bench/src/raft-ann-bench/generate_groundtruth/).

Usage:
    python -m raft_tpu.bench.run --config conf.json --output out/
    python -m raft_tpu.bench.run --config conf.json --plot  # + pareto png
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import time
from typing import Any, Dict, List, Tuple

import numpy as np

from raft_tpu.bench import datasets as ds
from raft_tpu.bench.harness import (
    BenchResult,
    compute_recall,
    export_csv,
    pareto_frontier,
)


def _synthetic(spec: dict) -> Tuple[np.ndarray, np.ndarray]:
    """Low-intrinsic-dimension manifold data (real descriptor sets have
    intrinsic dim far below ambient; isolated-blob mixtures disconnect
    KNN graphs and make graph-ANN recall meaningless).

    Generated in row blocks, float32 throughout — float64 [n, d]
    temporaries would need >20 GB host RAM at DEEP-10M scale."""
    rng = np.random.default_rng(spec.get("seed", 0))
    n, d, nq = spec["n"], spec["dim"], spec["n_queries"]
    intrinsic = spec.get("intrinsic_dim", 16)
    proj = np.random.default_rng(12345).normal(
        0, 1.0 / np.sqrt(intrinsic), (intrinsic, d)
    ).astype(np.float32)

    def gen(count):
        out = np.empty((count, d), np.float32)
        for r0 in range(0, count, 1 << 20):
            r1 = min(r0 + (1 << 20), count)
            z = rng.normal(0, 24.0, (r1 - r0, intrinsic)).astype(np.float32)
            blk = 64.0 + z @ proj
            blk += rng.normal(0, 2.0, (r1 - r0, d)).astype(np.float32)
            np.clip(blk, 0, 255, out=out[r0:r1])
        return out

    return gen(n), gen(nq)


def synthetic_dataset(n, dim, n_queries, seed=0, intrinsic_dim=16):
    """Shared generator for bench.py and config-driven runs — ONE set of
    constants so the headline bench and the orchestrated runs see
    byte-identical datasets for the same spec."""
    return _synthetic({"n": n, "dim": dim, "n_queries": n_queries,
                       "seed": seed, "intrinsic_dim": intrinsic_dim})


@functools.lru_cache(maxsize=None)
def _gen_device_block(count: int, d: int, intr: int):
    """One shared jitted generator per shape (defining it per call would
    defeat jit's function-identity cache and recompile every time)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def gen(key):
        kp = jax.random.PRNGKey(12345)
        proj = jax.random.normal(kp, (intr, d), jnp.float32) / jnp.sqrt(
            jnp.float32(intr)
        )
        kz, kn = jax.random.split(key)
        z = 24.0 * jax.random.normal(kz, (count, intr), jnp.float32)
        blk = 64.0 + z @ proj + 2.0 * jax.random.normal(
            kn, (count, d), jnp.float32
        )
        return jnp.clip(blk, 0, 255)

    return gen


def synthetic_dataset_device(n, dim, n_queries, seed=0, intrinsic_dim=16,
                             block: int = 4 << 20):
    """Same manifold recipe as ``synthetic_dataset`` generated ON DEVICE
    with jax.random (bit-different values, identical structure). On the
    tunnelled dev TPU (r4), host->device of a 10M-row dataset costs
    minutes at ~20 MB/s while real TPU hosts move it over PCIe in under
    a second —
    device-side generation keeps benchmarks about the framework, not the
    tunnel. Generated in fixed-shape row blocks so each generator
    program's temporaries stay at ``block`` rows; the assembled output
    (plus up to one extra copy during the final concatenate) still needs
    ~2x the dataset's bytes of HBM headroom — size n accordingly. Ground
    truth must be computed from the returned arrays."""
    import jax
    import jax.numpy as jnp

    def make(count, key):
        if count <= block:
            return _gen_device_block(int(count), int(dim),
                                     int(intrinsic_dim))(key)
        parts = []
        for off in range(0, count, block):
            key, sub = jax.random.split(key)
            rows = min(block, count - off)
            parts.append(
                _gen_device_block(int(rows), int(dim), int(intrinsic_dim))(sub)
            )
        return jnp.concatenate(parts, axis=0)

    kb, kq = jax.random.split(jax.random.PRNGKey(seed))
    return make(int(n), kb), make(int(n_queries), kq)


def load_dataset(cfg: dict) -> Tuple[np.ndarray, np.ndarray]:
    if "synthetic" in cfg:
        return _synthetic(cfg["synthetic"])
    base = ds.read_bin(cfg["base_file"])
    queries = ds.read_bin(cfg["query_file"])
    return base, queries


def generate_groundtruth(
    base: np.ndarray, queries: np.ndarray, k: int, metric: str,
    chunk: int = 1_000_000,
) -> np.ndarray:
    """Tiled exact KNN ground truth (generate_groundtruth analog)."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.distance.types import is_min_close, resolve_metric
    from raft_tpu.neighbors import brute_force
    from raft_tpu.neighbors.common import knn_merge_parts

    select_min = is_min_close(resolve_metric(metric))
    n = base.shape[0]
    if n <= chunk:
        _, idx = brute_force.knn(jnp.asarray(queries), jnp.asarray(base), k,
                                 metric=metric)
        return np.asarray(idx)
    parts_d, parts_i, offs = [], [], []
    q_dev = jax.device_put(queries)
    for c0 in range(0, n, chunk):
        block = jax.device_put(base[c0 : c0 + chunk])
        dd, ii = brute_force.knn(q_dev, block, k, metric=metric)
        parts_d.append(dd)
        parts_i.append(ii)
        offs.append(c0)
        del block
    md, mi = knn_merge_parts(
        jnp.stack(parts_d), jnp.stack(parts_i), k, select_min=select_min,
        translations=jnp.asarray(offs),
    )
    return np.asarray(mi)


def get_groundtruth(cfg: dict, base, queries, k: int) -> np.ndarray:
    metric = cfg.get("distance", "sqeuclidean")
    gt_file = cfg.get("groundtruth_file")
    if gt_file and os.path.exists(gt_file + ".neighbors.ibin"):
        gt = ds.read_groundtruth(gt_file)[0]
        if gt.shape[1] < k:
            raise ValueError(
                f"groundtruth_file has {gt.shape[1]} neighbors < k={k}"
            )
        if gt.shape[0] != queries.shape[0]:
            raise ValueError(
                f"groundtruth_file has {gt.shape[0]} rows but the query "
                f"set has {queries.shape[0]}"
            )
        return gt[:, :k]
    cache = cfg.get("groundtruth_cache")
    if cache is None and "synthetic" in cfg and cfg.get("name"):
        # deterministic synthetic data: default a cache keyed on the FULL
        # spec (a name-only key poisons runs whose configs share a name
        # but differ in size/seed)
        spec = cfg["synthetic"]
        tag = "-".join(
            [str(spec.get(f, "")) for f in
             ("n", "dim", "n_queries", "seed", "intrinsic_dim")]
            + [str(cfg.get("distance", "sqeuclidean"))]
        )
        os.makedirs(".bench_cache", exist_ok=True)
        cache = os.path.join(".bench_cache", f"{cfg['name']}-{tag}-gt")
    if cache and os.path.exists(cache + ".neighbors.ibin"):
        gt = ds.read_groundtruth(cache)[0]
        if gt.shape[1] >= k and gt.shape[0] == queries.shape[0]:
            return gt[:, :k]
    gt = generate_groundtruth(base, queries, max(k, 100), metric)
    if cache:
        ds.write_groundtruth(cache, gt)
    return gt[:, :k]


# --- algo adapters ---------------------------------------------------------


_HOST_ALGOS = frozenset({"hnswlib_cpu"})


def _make_case(algo: str, metric: str, build_param: dict, search_param: dict,
               base, k: int):
    """Returns (build_fn, search_q) closures for one (build, search) pair;
    ``search_q(ix, q)`` is query-parametrized so the timing loop can feed
    rolled (distinct) batches."""
    import jax.numpy as jnp

    if algo == "brute_force":
        from raft_tpu.neighbors import brute_force

        return (
            lambda: brute_force.build(jnp.asarray(base), metric),
            lambda ix, q: brute_force.search(ix, q, k, **search_param),
        )
    if algo == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat

        params = ivf_flat.IndexParams(metric=metric, **build_param)
        sp = ivf_flat.SearchParams(**search_param)
        return (
            lambda: ivf_flat.build(params, base),
            lambda ix, q: ivf_flat.search(sp, ix, q, k),
        )
    if algo == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq

        params = ivf_pq.IndexParams(metric=metric, **build_param)
        sp = ivf_pq.SearchParams(**search_param)
        return (
            lambda: ivf_pq.build(params, base),
            lambda ix, q: ivf_pq.search(sp, ix, q, k),
        )
    if algo == "cagra":
        from raft_tpu.neighbors import cagra

        params = cagra.IndexParams(metric=metric, **build_param)
        sp = cagra.SearchParams(**search_param)
        return (
            lambda: cagra.build(params, base),
            lambda ix, q: cagra.search(sp, ix, q, k),
        )
    if algo == "ball_cover":
        from raft_tpu.neighbors import ball_cover

        return (
            lambda: ball_cover.build(base, metric=metric, **build_param),
            lambda ix, q: ball_cover.knn_query(ix, q, k, **search_param),
        )
    if algo == "hnswlib_cpu":
        # competitor wrapper (the reference benches hnswlib via
        # cpp/bench/ann/src/hnswlib/): the real library is not
        # installable here, so the CAGRA graph is exported to the
        # hnswlib format and searched with hnswlib's base-layer
        # algorithm on the host (neighbors/hnswlib_io.py) — a CPU
        # single-thread baseline, honest about what it is
        import tempfile

        import numpy as _np

        from raft_tpu.neighbors import cagra
        from raft_tpu.neighbors.hnswlib_io import (
            greedy_search, load_hnswlib_index,
        )

        ef = int(search_param.get("ef", 96))

        def _build():
            import os as _os

            params = cagra.IndexParams(metric=metric, **build_param)
            idx = cagra.build(params, base)
            fd, path = tempfile.mkstemp(suffix=".hnsw")
            _os.close(fd)
            try:
                cagra.serialize_to_hnswlib(path, idx)
                return load_hnswlib_index(path, dim=base.shape[1])
            finally:
                _os.unlink(path)

        def _search(ix, q):
            qh = _np.asarray(q)
            ds = _np.full((qh.shape[0], k), _np.inf, _np.float32)
            ids = _np.full((qh.shape[0], k), -1, _np.int64)
            for i in range(qh.shape[0]):
                di, ii = greedy_search(ix, qh[i], k, ef=max(ef, k))
                ds[i, : len(ii)] = di[: k]
                ids[i, : len(ii)] = ii[: k]
            return jnp.asarray(ds), jnp.asarray(ids)

        return _build, _search
    raise ValueError(f"unknown algo {algo!r}")


def run_config(cfg: dict, iters: int = 10,
               mode: str = "throughput") -> List[BenchResult]:
    """``mode``: "throughput" (scan-chained batch QPS, default) or
    "latency" (reference raft_ann_benchmarks.md:240-254 `--mode latency`:
    per-call p50/p95 at batch 1 and 10; qps is then batch/p50)."""
    dcfg = cfg["dataset"]
    k = int(dcfg.get("k", 10))
    metric = dcfg.get("distance", "sqeuclidean")
    base, queries = load_dataset(dcfg)
    gt = get_groundtruth(dcfg, base, queries, k)
    results: List[BenchResult] = []
    for index_def in cfg["index"]:
        algo = index_def["algo"]
        bp = index_def.get("build_param", {})
        index = None
        build_s = 0.0
        from raft_tpu.bench.constraints import check_case

        if not check_case(algo, bp, {}, int(base.shape[1]), k):
            print(f"[bench] skip invalid build {algo} {bp}")
            continue
        for si, sp in enumerate(index_def.get("search_params", [{}])):
            if not check_case(algo, bp, sp, int(base.shape[1]), k):
                print(f"[bench] skip invalid case {algo} {bp} {sp}")
                continue
            build_fn, search_q = _make_case(algo, metric, bp, sp, base, k)
            if index is None:
                # build once per index definition, like the reference's
                # bench_build / bench_search split (benchmark.hpp:124,174)
                t0 = time.time()
                index = build_fn()
                import jax

                leaves = (
                    [v for v in vars(index).values() if isinstance(v, jax.Array)]
                    if hasattr(index, "__dict__") else [index]
                )
                jax.block_until_ready(leaves)
                build_s = time.time() - t0
            from raft_tpu.bench.harness import scan_qps_time
            import jax
            import jax.numpy as jnp

            q_dev = jnp.asarray(queries)
            dist, idx = search_q(index, q_dev)
            recall = compute_recall(np.asarray(idx), gt)
            if mode == "latency":
                from raft_tpu.bench.harness import latency_percentiles

                lat = {}
                for b in (1, 10):
                    lat[f"b{b}"] = latency_percentiles(
                        lambda q, ops: search_q(ops, q), q_dev, b,
                        n_calls=max(10, iters * 3), operands=index,
                    )
                p50_10 = lat["b10"]["p50"]
                r = BenchResult(
                    name=f"{index_def['name']}#{si}",
                    build_s=build_s,
                    search_s=p50_10 / 10.0,
                    qps=10.0 / p50_10,
                    recall=recall,
                    k=k,
                    n_queries=queries.shape[0],
                    extra={"algo": algo, "mode": "latency",
                           **{f"lat.{bk}.{mk}": round(mv, 6)
                              for bk, d_ in lat.items()
                              for mk, mv in d_.items()},
                           **{f"s.{kk}": vv for kk, vv in sp.items()}},
                )
                results.append(r)
                print(json.dumps(r.row()), flush=True)
                continue
            if algo in _HOST_ALGOS:
                # pure-host competitors can't jit at all; plain host timer
                from raft_tpu.bench.harness import time_fn

                search_s = time_fn(
                    lambda: search_q(index, q_dev)[1], iters=max(1, iters // 4)
                )
            else:
                try:
                    search_s = scan_qps_time(
                        lambda qq, ix: search_q(ix, qq),
                        q_dev, n1=max(2, iters // 4), n2=max(4, iters),
                        operands=index,
                    )
                except (jax.errors.TracerBoolConversionError,
                        jax.errors.ConcretizationTypeError):
                    # algos with host-side adaptive loops (ball_cover's
                    # certification rounds) can't run inside the scan;
                    # fall back to the pipelined host timer
                    from raft_tpu.bench.harness import time_fn

                    search_s = time_fn(
                        lambda: search_q(index, q_dev)[1], iters=iters
                    )
            r = BenchResult(
                name=f"{index_def['name']}#{si}",
                build_s=build_s,
                search_s=search_s,
                qps=queries.shape[0] / search_s,
                recall=recall,
                k=k,
                n_queries=queries.shape[0],
                extra={"algo": algo,
                       **{f"s.{kk}": vv for kk, vv in sp.items()}},
            )
            results.append(r)
            print(json.dumps(r.row()), flush=True)
    return results


def plot_results(results: List[BenchResult], path: str) -> None:
    """Recall-vs-QPS scatter + Pareto frontier PNG
    (raft-ann-bench.plot analog)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 5))
    by_algo: Dict[str, List[BenchResult]] = {}
    for r in results:
        by_algo.setdefault(r.extra.get("algo", "?"), []).append(r)
    for algo, rs in by_algo.items():
        ax.scatter([r.recall for r in rs], [r.qps for r in rs], label=algo,
                   s=24)
    front = pareto_frontier(results)
    ax.plot([r.recall for r in front], [r.qps for r in front], "k--",
            lw=1, label="pareto")
    ax.set_xlabel(f"recall@{results[0].k}")
    ax.set_ylabel("QPS")
    ax.set_yscale("log")
    ax.legend()
    fig.tight_layout()
    fig.savefig(path, dpi=120)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", required=True)
    ap.add_argument("--output", default=".")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--plot", action="store_true")
    ap.add_argument("--mode", choices=("throughput", "latency"),
                    default="throughput")
    ap.add_argument("--obs-snapshot", action="store_true",
                    help="run instrumented (graft-scope; forces "
                         "RAFT_TPU_OBS=on if off) and write a "
                         "<stem>.obs.json metrics sidecar next to the "
                         "results (docs/observability.md)")
    args = ap.parse_args(argv)
    if args.obs_snapshot:
        from raft_tpu import obs

        if not obs.enabled():
            obs.set_mode("on")
    cfg = json.load(open(args.config))
    os.makedirs(args.output, exist_ok=True)
    results = run_config(cfg, iters=args.iters, mode=args.mode)
    stem = os.path.splitext(os.path.basename(args.config))[0]
    export_csv(results, os.path.join(args.output, f"{stem}.csv"))
    with open(os.path.join(args.output, f"{stem}.json"), "w") as fp:
        json.dump([r.row() for r in results], fp, indent=2)
    if args.obs_snapshot:
        from raft_tpu.bench.harness import write_obs_snapshot

        write_obs_snapshot(os.path.join(args.output, f"{stem}.obs.json"))
    if args.plot:
        plot_results(results, os.path.join(args.output, f"{stem}.png"))


if __name__ == "__main__":
    main()
