"""Benchmark harness (SURVEY.md §2.16): dataset IO + ANN bench driver."""

from raft_tpu.bench.datasets import read_bin, write_bin, read_groundtruth, write_groundtruth
from raft_tpu.bench.harness import (
    BenchResult,
    compute_recall,
    export_csv,
    pareto_frontier,
    run_case,
    time_fn,
)

__all__ = [
    "read_bin",
    "write_bin",
    "read_groundtruth",
    "write_groundtruth",
    "BenchResult",
    "compute_recall",
    "export_csv",
    "pareto_frontier",
    "run_case",
    "time_fn",
]
