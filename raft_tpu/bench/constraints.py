"""Benchmark parameter-validity constraints.

Analog of the reference's `raft-ann-bench` `constraints/` module
(python/raft-ann-bench/src/raft-ann-bench/constraints/__init__.py): the
orchestrator calls these before launching a (build_param, search_param)
case and SKIPS invalid combinations instead of crashing mid-sweep —
essential when sweeping Cartesian parameter grids.
"""

from __future__ import annotations


def ivf_pq_build(build_param: dict, dim: int) -> bool:
    """Mirror of the reference's raft_ivf_pq_build_constraints: pq_dim
    must divide into the (rounded) rotated dim and stay <= dim."""
    pq_dim = int(build_param.get("pq_dim", 0))
    if pq_dim == 0:
        return True
    return 0 < pq_dim <= dim


def ivf_pq_search(search_param: dict, build_param: dict, k: int) -> bool:
    """raft_ivf_pq_search_constraints analog: probes within the list
    count, and forced fused scans need k within the kernel's 256-per-list
    extraction budget."""
    n_probes = int(search_param.get("n_probes", 20))
    n_lists = int(build_param.get("n_lists", 1024))
    if not 0 < n_probes <= n_lists:
        return False
    if str(search_param.get("scan_impl", "auto")).startswith("pallas"):
        return k <= 256
    return True


def ivf_flat_search(search_param: dict, build_param: dict, k: int) -> bool:
    n_probes = int(search_param.get("n_probes", 20))
    n_lists = int(build_param.get("n_lists", 1024))
    return 0 < n_probes <= n_lists


def cagra_build(build_param: dict, dim: int) -> bool:
    """raft_cagra_build_constraints analog: graph_degree <= intermediate."""
    g = int(build_param.get("graph_degree", 32))
    ig = int(build_param.get("intermediate_graph_degree", 64))
    return 0 < g <= ig


def cagra_search(search_param: dict, build_param: dict, k: int) -> bool:
    """hnswlib/CAGRA-style: itopk >= k; the fused beam kernel bounds
    search_width x graph_degree by VMEM (~128 candidates/iteration)."""
    itopk = int(search_param.get("itopk_size", 64))
    width = int(search_param.get("search_width", 4))
    deg = int(build_param.get("graph_degree", 32))
    return itopk >= k and width * deg <= 256


_BUILD = {"ivf_pq": ivf_pq_build, "cagra": cagra_build}
_SEARCH = {
    "ivf_pq": ivf_pq_search,
    "ivf_flat": ivf_flat_search,
    "cagra": cagra_search,
}


def check_case(algo: str, build_param: dict, search_param: dict,
               dim: int, k: int) -> bool:
    """True when the (build, search) combination is worth launching."""
    b = _BUILD.get(algo)
    if b is not None and not b(build_param, dim):
        return False
    s = _SEARCH.get(algo)
    if s is not None and not s(search_param, build_param, k):
        return False
    return True
