"""ANN benchmark dataset I/O.

Analog of the reference bench harness's dataset loaders
(cpp/bench/ann/src/common/dataset.hpp:45-128): ``.fbin`` / ``.u8bin`` /
``.i8bin`` binary files — a header of two little-endian uint32 (n_rows,
n_cols) followed by row-major data — memory-mapped with optional row
subsets. Ground-truth neighbor files use the same container with int32/
float32 payloads (bigann convention).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

_SUFFIX_DTYPES = {
    ".fbin": np.float32,
    ".u8bin": np.uint8,
    ".i8bin": np.int8,
    ".ibin": np.int32,
}


def _dtype_for(path: str, dtype=None):
    if dtype is not None:
        return np.dtype(dtype)
    for suffix, dt in _SUFFIX_DTYPES.items():
        if path.endswith(suffix):
            return np.dtype(dt)
    raise ValueError(f"cannot infer dtype from {path!r}; pass dtype=")


def read_bin(
    path: str,
    dtype=None,
    rows: Optional[Tuple[int, int]] = None,
    mmap: bool = True,
) -> np.ndarray:
    """Read a *.bin dataset; ``rows=(start, count)`` selects a subset
    (reference dataset.hpp subset support)."""
    dt = _dtype_for(path, dtype)
    with open(path, "rb") as fp:
        header = np.fromfile(fp, dtype=np.uint32, count=2)
        n, d = int(header[0]), int(header[1])
    offset = 8
    if mmap:
        arr = np.memmap(path, dtype=dt, mode="r", offset=offset, shape=(n, d))
    else:
        with open(path, "rb") as fp:
            fp.seek(offset)
            arr = np.fromfile(fp, dtype=dt).reshape(n, d)
    if rows is not None:
        start, count = rows
        arr = arr[start : start + count]
    return arr


def write_bin(path: str, arr: np.ndarray) -> None:
    arr = np.ascontiguousarray(arr)
    with open(path, "wb") as fp:
        np.asarray(arr.shape, dtype=np.uint32).tofile(fp)
        arr.tofile(fp)


def read_groundtruth(prefix: str) -> Tuple[np.ndarray, np.ndarray]:
    """Read bigann-style groundtruth: ``<prefix>.neighbors.ibin`` +
    ``<prefix>.distances.fbin`` (raft-ann-bench generate_groundtruth
    layout)."""
    neighbors = read_bin(prefix + ".neighbors.ibin")
    distances = (
        read_bin(prefix + ".distances.fbin")
        if os.path.exists(prefix + ".distances.fbin")
        else None
    )
    return np.asarray(neighbors), None if distances is None else np.asarray(distances)


def write_groundtruth(prefix: str, neighbors: np.ndarray, distances: Optional[np.ndarray] = None) -> None:
    write_bin(prefix + ".neighbors.ibin", neighbors.astype(np.int32))
    if distances is not None:
        write_bin(prefix + ".distances.fbin", distances.astype(np.float32))
