"""Label utilities (reference cpp/include/raft/label/{classlabels,
merge_labels}.cuh — SURVEY.md §2 layer 11).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def get_unique_labels(labels) -> jax.Array:
    """Sorted distinct label values (classlabels.cuh getUniquelabels).
    Host-compressing (count is data-dependent)."""
    return jnp.asarray(np.unique(np.asarray(labels)))


def get_ovr_labels(labels, target, true_val=1, false_val=0) -> jax.Array:
    """One-vs-rest relabeling (classlabels.cuh getOvrlabels)."""
    labels = jnp.asarray(labels)
    return jnp.where(labels == target, true_val, false_val).astype(jnp.int32)


def make_monotonic(labels) -> Tuple[jax.Array, jax.Array]:
    """Map arbitrary label values onto 0..k-1 by sorted rank
    (classlabels.cuh make_monotonic). Returns (mapped, unique_values)."""
    labels = jnp.asarray(labels)
    uniq = get_unique_labels(labels)
    mapped = jnp.searchsorted(uniq, labels).astype(jnp.int32)
    return mapped, uniq


def merge_labels(labels_a, labels_b, mask, max_iters: int | None = None
                 ) -> jax.Array:
    """Merge two labelings over the same vertices
    (merge_labels.cuh merge_labels, the DBSCAN multi-batch merge): two
    vertices end up with the same output label iff they are connected in
    the union relation {same label in A} ∪ {same label in B, restricted
    to vertices where ``mask`` holds}. Output labels are the minimum
    vertex-id of each merged group (the reference propagates min label
    through its label-equivalence graph the same way).
    """
    la = jnp.asarray(labels_a).astype(jnp.int32)
    lb = jnp.asarray(labels_b).astype(jnp.int32)
    mask = jnp.asarray(mask).astype(bool)
    n = la.shape[0]
    # graft-lint: allow-host-sync contingency-table shape must be concrete to allocate
    ka = int(jnp.max(la)) + 1 if n else 1
    # graft-lint: allow-host-sync contingency-table shape must be concrete to allocate
    kb = int(jnp.max(lb)) + 1 if n else 1
    big = jnp.int32(n)

    def body(state):
        l, _ = state
        # propagate min through A-groups (all vertices participate)
        ga = jnp.full((ka,), big, jnp.int32).at[la].min(l)
        l2 = jnp.minimum(l, ga[la])
        # propagate min through B-groups (only mask vertices)
        gb = jnp.full((kb,), big, jnp.int32).at[
            jnp.where(mask, lb, kb - 1)
        ].min(jnp.where(mask, l2, big))
        l3 = jnp.where(mask, jnp.minimum(l2, gb[lb]), l2)
        return l3, jnp.any(l3 != l)

    l0 = jnp.arange(n, dtype=jnp.int32)
    l, _ = jax.lax.while_loop(
        lambda s: s[1], body, (l0, jnp.bool_(True))
    )
    return l
