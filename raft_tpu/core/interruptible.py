"""Cooperative cancellation of device synchronization points.

Analog of the reference's ``raft::interruptible``
(cpp/include/raft/core/interruptible.hpp:39-105): one token per thread,
``cancel`` from another thread makes the target thread's next
``synchronize`` raise. With XLA async dispatch the sync points are
``block_until_ready`` calls; we poll the flag while waiting.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

import jax

from raft_tpu.analysis import lockwatch


class InterruptedException(RuntimeError):
    pass


class Interruptible:
    _tokens: Dict[int, "Interruptible"] = {}
    # graft-race sanitizer node "core.interruptible" (note: constructed
    # at import, so RAFT_TPU_THREADSAN must be set process-wide to
    # sanitize this one)
    _lock = lockwatch.make_lock("core.interruptible")

    def __init__(self) -> None:
        self._cancelled = threading.Event()

    @classmethod
    def get_token(cls, thread_id: int | None = None) -> "Interruptible":
        tid = thread_id if thread_id is not None else threading.get_ident()
        with cls._lock:
            if tid not in cls._tokens:
                cls._tokens[tid] = Interruptible()
            return cls._tokens[tid]

    def cancel(self) -> None:
        self._cancelled.set()

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def check(self) -> None:
        """Raise if cancelled, clearing the flag (one-shot like the ref)."""
        if self._cancelled.is_set():
            self._cancelled.clear()  # graft-lint: allow-check-then-act token is thread-affine by contract (one token per get_token thread id); a racing double-check at worst double-raises the same cancellation
            raise InterruptedException("raft_tpu: interrupted")

    def synchronize(self, arr: jax.Array, poll_s: float = 0.01) -> None:
        """Interruptible block_until_ready (interruptible.hpp:71-100)."""
        # jax has no timed wait; emulate with a worker thread + polling.
        done = threading.Event()
        err: list[BaseException] = []

        def _wait():
            try:
                jax.block_until_ready(arr)
            except BaseException as e:  # graft-lint: allow-unclassified-swallow captured and re-raised on the waiting thread after the poll loop
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=_wait, daemon=True)
        t.start()
        while not done.wait(poll_s):
            self.check()
        self.check()
        if err:
            raise err[0]


def synchronize(arr: jax.Array) -> None:
    Interruptible.get_token().synchronize(arr)


def cancel(thread_id: int) -> None:
    Interruptible.get_token(thread_id).cancel()
