"""pylibraft-compatible device array container + output conversion hooks
(reference python/pylibraft/pylibraft/common/device_ndarray.py and
common/outputs.py auto_convert_output).

Backing storage is a jax.Array; interop rides the DLPack protocol both
ways (torch, cupy, numpy ≥1.23 all speak it), so a pylibraft user's
``device_ndarray`` call sites work unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


class device_ndarray:
    """Device-resident ndarray (pylibraft common/device_ndarray.py).

    Construct from any array-like (host copies to device) or any object
    speaking ``__dlpack__`` (zero-copy when the producer is on the same
    device).
    """

    def __init__(self, np_ndarray):
        if isinstance(np_ndarray, device_ndarray):
            self._array = np_ndarray._array
        elif isinstance(np_ndarray, jax.Array):
            self._array = np_ndarray
        elif hasattr(np_ndarray, "__dlpack__") and not isinstance(
            np_ndarray, np.ndarray
        ):
            self._array = jnp.from_dlpack(np_ndarray)
        else:
            self._array = jnp.asarray(np_ndarray)

    @classmethod
    def empty(cls, shape, dtype=np.float32, order="C"):
        """Uninitialized-semantics device allocation (zeros here — XLA has
        no uninitialized alloc; matches pylibraft's contract of
        'contents undefined')."""
        return cls(jnp.zeros(shape, dtype))

    @property
    def c_contiguous(self) -> bool:
        return True  # XLA arrays are dense row-major

    @property
    def f_contiguous(self) -> bool:
        return self._array.ndim <= 1

    @property
    def dtype(self):
        return np.dtype(self._array.dtype.name)

    @property
    def shape(self):
        return tuple(self._array.shape)

    @property
    def strides(self):
        itemsize = self.dtype.itemsize
        strides = []
        acc = itemsize
        for dim in reversed(self.shape):
            strides.append(acc)
            acc *= dim
        return tuple(reversed(strides))

    @property
    def jax_array(self) -> jax.Array:
        return self._array

    def get(self):
        """The array in the globally configured output format
        (raft_tpu.config.set_output_as — pylibraft's output hook analog);
        default: the underlying jax.Array."""
        from raft_tpu.config import as_output

        return as_output(self._array)

    def copy_to_host(self) -> np.ndarray:
        """Device → host numpy copy (device_ndarray.copy_to_host)."""
        return np.asarray(self._array)

    def __dlpack__(self, *args, **kwargs):
        return self._array.__dlpack__(*args, **kwargs)

    def __dlpack_device__(self):
        return self._array.__dlpack_device__()

    def __array__(self, dtype=None):
        host = self.copy_to_host()
        return host.astype(dtype) if dtype is not None else host

    def __repr__(self):
        return f"device_ndarray(shape={self.shape}, dtype={self.dtype})"


def auto_convert_output(f: Callable) -> Callable:
    """Decorator converting returned jax arrays to ``device_ndarray``
    (pylibraft common/outputs.py auto_convert_output analog)."""
    import functools

    def conv(x: Any) -> Any:
        if isinstance(x, jax.Array):
            return device_ndarray(x)
        if isinstance(x, tuple):
            return tuple(conv(v) for v in x)
        if isinstance(x, list):
            return [conv(v) for v in x]
        return x

    @functools.wraps(f)
    def wrapper(*args, **kwargs):
        return conv(f(*args, **kwargs))

    return wrapper


def cai_wrapper(obj) -> jax.Array:
    """Accept any array-ish input (numpy, device_ndarray, DLPack
    producers like torch tensors) as a jax array — the role pylibraft's
    cai_wrapper (CUDA array interface) plays at every API boundary."""
    if isinstance(obj, device_ndarray):
        return obj.jax_array
    if isinstance(obj, jax.Array) or isinstance(obj, np.ndarray):
        return jnp.asarray(obj)
    if hasattr(obj, "__dlpack__"):
        return jnp.from_dlpack(obj)
    return jnp.asarray(obj)
