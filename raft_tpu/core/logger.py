"""Logging with a callback sink.

Analog of the reference's spdlog-backed logger with a Python-interceptable
callback sink (cpp/include/raft/core/logger-inl.hpp:74-112,
core/logger-macros.hpp). We build on the stdlib ``logging`` module and keep
the callback-sink hook so embedders can intercept records the way pylibraft
intercepts spdlog.
"""

from __future__ import annotations

import logging
import sys
from typing import Callable, Optional

_FMT = "[%(levelname)s] [%(asctime)s] %(name)s: %(message)s"

logger = logging.getLogger("raft_tpu")
if not logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(_FMT))
    logger.addHandler(_h)
    logger.setLevel(logging.WARNING)

# level names matching the reference's RAFT_LEVEL_* (logger-macros.hpp)
TRACE = 5
DEBUG = logging.DEBUG
INFO = logging.INFO
WARN = logging.WARNING
ERROR = logging.ERROR
CRITICAL = logging.CRITICAL
OFF = logging.CRITICAL + 10

logging.addLevelName(TRACE, "TRACE")


def set_level(level: int) -> None:
    logger.setLevel(level)


def set_pattern(fmt: str) -> None:
    for h in logger.handlers:
        h.setFormatter(logging.Formatter(fmt))


class _CallbackHandler(logging.Handler):
    def __init__(self, cb: Callable[[int, str], None], flush_cb: Optional[Callable[[], None]] = None):
        super().__init__()
        self._cb = cb
        self._flush_cb = flush_cb

    def emit(self, record: logging.LogRecord) -> None:
        self._cb(record.levelno, self.format(record))

    def flush(self) -> None:
        if self._flush_cb:
            self._flush_cb()


_callback_handler: Optional[_CallbackHandler] = None


def set_callback(cb: Optional[Callable[[int, str], None]], flush_cb=None) -> None:
    """Install/remove a callback sink (reference logger-inl.hpp:74 sink)."""
    global _callback_handler
    if _callback_handler is not None:
        logger.removeHandler(_callback_handler)
        _callback_handler = None
    if cb is not None:
        _callback_handler = _CallbackHandler(cb, flush_cb)
        _callback_handler.setFormatter(logging.Formatter(_FMT))
        logger.addHandler(_callback_handler)


def log_trace(msg: str, *args) -> None:
    logger.log(TRACE, msg, *args)
