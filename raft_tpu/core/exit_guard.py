"""Dead-backend exit guard — shared by tests/conftest.py and the
long-running scripts (VERDICT r5 weak #6 / next-round #7).

With the axon TPU plugin installed but the backend unreachable, the
interpreter HANGS at teardown: the plugin's exit-time client cleanup
blocks holding the GIL, so a fully-finished process sits forever and the
caller reads an external-timeout rc=124 instead of the real rc. The
guard records the real rc and hard-exits with it from an atexit hook.

Ordering matters: atexit is LIFO, so :func:`install` must be called
AFTER ``import jax`` — then the guard runs BEFORE any backend-client
teardown can hang. The guard only ARMS when an out-of-tree PJRT plugin
could be present (plugin entry points / jax_plugins namespace / PJRT env
/ a non-cpu JAX_PLATFORMS) — on a plain-CPU machine normal interpreter
teardown is kept, so earlier-registered atexit hooks (e.g. coverage.py's
data save) still run. Disable explicitly with RAFT_TPU_NO_EXIT_GUARD=1.

Two entry styles:

* pytest (tests/conftest.py): :func:`install` once at import, then
  :func:`set_exit_rc` from ``pytest_sessionfinish``; the atexit hook
  does the rest.
* scripts: ``guarded_exit(main())`` as the last line — flushes and
  ``os._exit``\\ s immediately when a plugin could hang, plain
  ``sys.exit`` otherwise.
"""

from __future__ import annotations

import atexit
import os
import sys

_STATE = {"rc": None, "armed": False}


def pjrt_plugin_possible() -> bool:
    plat = os.environ.get("JAX_PLATFORMS", "")
    if plat and plat.strip().lower() not in ("", "cpu"):
        return True
    if os.environ.get("PJRT_NAMES_AND_LIBRARY_PATHS"):
        return True
    try:
        import importlib.metadata as _md

        if list(_md.entry_points(group="jax_plugins")):
            return True
    except Exception:  # noqa: BLE001
        pass
    try:
        import jax_plugins  # namespace package  # noqa: F401

        return True
    except Exception:  # noqa: BLE001
        return False


def _hard_exit_hook() -> None:
    rc = _STATE["rc"]
    if rc is None or os.environ.get("RAFT_TPU_NO_EXIT_GUARD"):
        return  # session never finished (collection crash): teardown as-is
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(int(rc))


def install() -> None:
    """Arm the guard (idempotent). Call AFTER ``import jax``."""
    if _STATE["armed"]:
        return
    _STATE["armed"] = True
    if pjrt_plugin_possible():
        atexit.register(_hard_exit_hook)


def set_exit_rc(rc: int) -> None:
    """Record the real exit code the atexit hook should force."""
    _STATE["rc"] = int(rc)


def guarded_exit(rc: int) -> None:
    """Terminate NOW with ``rc``, bypassing a hanging plugin teardown.

    Script analog of the conftest hook pair: when a PJRT plugin could be
    present (and the guard is not disabled), flush and ``os._exit`` so a
    dead axon backend cannot swallow a finished run; otherwise a normal
    ``sys.exit`` keeps standard teardown.
    """
    set_exit_rc(rc)
    if pjrt_plugin_possible() and not os.environ.get("RAFT_TPU_NO_EXIT_GUARD"):
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(int(rc))
    sys.exit(int(rc))
