"""Core runtime layer: resources handle, bitset, serialization, logging.

TPU-native analog of the reference's ``cpp/include/raft/core`` (SURVEY.md
§2.1). There are no streams or BLAS handles here — XLA owns scheduling — so
the handle shrinks to mesh/device/RNG/logger state plus a lazy slot registry
retained for comms injection.
"""

from raft_tpu.core.resources import Resources, DeviceResources
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.serialize import save_npy, load_npy, serialize_mdspan, deserialize_mdspan
from raft_tpu.core.logger import logger, set_level
from raft_tpu.core.trace import annotate, push_range, pop_range
from raft_tpu.core.interruptible import Interruptible, synchronize
from raft_tpu.core.device_ndarray import auto_convert_output, cai_wrapper, device_ndarray
from raft_tpu.core.pipeline import Prefetcher, overlap, resolve_depth

__all__ = [
    "Resources",
    "device_ndarray",
    "auto_convert_output",
    "cai_wrapper",
    "DeviceResources",
    "Bitset",
    "save_npy",
    "load_npy",
    "serialize_mdspan",
    "deserialize_mdspan",
    "logger",
    "set_level",
    "annotate",
    "push_range",
    "pop_range",
    "Interruptible",
    "synchronize",
    "Prefetcher",
    "overlap",
    "resolve_depth",
]
