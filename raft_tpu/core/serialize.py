"""NumPy-format array (de)serialization.

Analog of the reference's mdspan serializer
(cpp/include/raft/core/serialize.hpp:35,
cpp/include/raft/core/detail/mdspan_numpy_serializer.hpp), which writes
arrays in the NumPy ``.npy`` format so artifacts interoperate with numpy.
We write the exact same format via numpy itself, plus small helpers for
length-prefixed multi-array index files with version tags (the per-index
serializers in neighbors/ build on these).
"""

from __future__ import annotations

import io
import json
import struct
from typing import Any, BinaryIO

import jax
import numpy as np

MAGIC = b"RAFT_TPU"


def serialize_mdspan(fp: BinaryIO, arr) -> None:
    """Write one array in .npy format (reference core/serialize.hpp:35)."""
    np.save(fp, np.asarray(arr), allow_pickle=False)


def deserialize_mdspan(fp: BinaryIO) -> np.ndarray:
    return np.load(fp, allow_pickle=False)


def save_npy(path: str, arr) -> None:
    np.save(path, np.asarray(arr), allow_pickle=False)


def load_npy(path: str) -> np.ndarray:
    return np.load(path, allow_pickle=False)


def serialize_scalar(fp: BinaryIO, value) -> None:
    """Scalar serialization matching the reference's serialize_scalar idea."""
    if isinstance(value, bool):
        fp.write(struct.pack("<B?", 0, value))
    elif isinstance(value, int):
        fp.write(struct.pack("<Bq", 1, value))
    elif isinstance(value, float):
        fp.write(struct.pack("<Bd", 2, value))
    elif isinstance(value, str):
        raw = value.encode()
        fp.write(struct.pack("<Bq", 3, len(raw)))
        fp.write(raw)
    else:
        raise TypeError(f"unsupported scalar type {type(value)}")


def deserialize_scalar(fp: BinaryIO):
    (tag,) = struct.unpack("<B", fp.read(1))
    if tag == 0:
        return struct.unpack("<?", fp.read(1))[0]
    if tag == 1:
        return struct.unpack("<q", fp.read(8))[0]
    if tag == 2:
        return struct.unpack("<d", fp.read(8))[0]
    if tag == 3:
        (n,) = struct.unpack("<q", fp.read(8))
        return fp.read(n).decode()
    raise ValueError(f"bad scalar tag {tag}")


def write_index_file(path: str, kind: str, version: int, meta: dict[str, Any], arrays: dict[str, Any]) -> None:
    """Versioned index container: header + json meta + named .npy blocks.

    Analog of the reference's per-index binary serializers with version tags
    (neighbors/ivf_flat_serialize.cuh, detail/ivf_pq_serialize.cuh,
    detail/cagra/cagra_serialize.cuh).
    """
    with open(path, "wb") as fp:
        fp.write(MAGIC)
        meta_blob = json.dumps(
            {"kind": kind, "version": version, "meta": meta, "arrays": list(arrays)}
        ).encode()
        fp.write(struct.pack("<q", len(meta_blob)))
        fp.write(meta_blob)
        for name, arr in arrays.items():
            serialize_mdspan(fp, arr)


def read_index_file(path: str, kind: str, min_version: int = 0):
    """Returns (version, meta, arrays-dict of numpy arrays)."""
    with open(path, "rb") as fp:
        magic = fp.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(f"{path}: not a raft_tpu index file")
        (n,) = struct.unpack("<q", fp.read(8))
        header = json.loads(fp.read(n).decode())
        if header["kind"] != kind:
            raise ValueError(f"{path}: expected index kind {kind!r}, found {header['kind']!r}")
        if header["version"] < min_version:
            raise ValueError(f"{path}: version {header['version']} < required {min_version}")
        arrays = {name: deserialize_mdspan(fp) for name in header["arrays"]}
        return header["version"], header["meta"], arrays
