"""Profiler range annotations.

Analog of the reference's NVTX ranges (cpp/include/raft/core/nvtx.hpp:48-96:
RAII ``range`` + ``push_range``/``pop_range``), mapped onto
``jax.profiler.TraceAnnotation`` so ranges show up in XLA/TPU profiler
traces. Disabled cheaply when profiling is off.

Absorbed by graft-scope (:mod:`raft_tpu.obs`): when ``RAFT_TPU_OBS`` is
on, :func:`annotate`/:func:`annotated` delegate to
:func:`raft_tpu.obs.span` — the same call then lands in the structured
span tree AND the XLA trace (obs spans emit the TraceAnnotation
themselves, forwarding scalar attrs as annotation metadata, so profiler
output matches the direct path for scalar kwargs; non-scalar metadata
survives only in the span tree). With obs off, the plain
TraceAnnotation path below is unchanged.

The ``push_range``/``pop_range`` stack is per-thread
(``threading.local``): the reference's nvtx ranges are thread-scoped
too, and a module-global list would let concurrent streaming threads
pop each other's ranges.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Iterator

import jax

from raft_tpu.obs import config as _obs_config

_tls = threading.local()


def _range_stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


@contextlib.contextmanager
def annotate(name: str, **kwargs) -> Iterator[None]:
    """RAII-style range (reference nvtx.hpp ``common::nvtx::range``)."""
    if _obs_config.ENABLED:
        from raft_tpu import obs

        with obs.span(name, **kwargs):
            yield
    else:
        with jax.profiler.TraceAnnotation(name, **kwargs):
            yield


def push_range(name: str) -> None:
    t = jax.profiler.TraceAnnotation(name)
    t.__enter__()
    _range_stack().append(t)


def pop_range() -> None:
    stack = _range_stack()
    if stack:
        stack.pop().__exit__(None, None, None)


def annotated(name: str | None = None):
    """Decorator adding a trace annotation around a function."""

    def deco(fn):
        label = name or f"raft_tpu.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with annotate(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco
