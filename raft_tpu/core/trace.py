"""Profiler range annotations.

Analog of the reference's NVTX ranges (cpp/include/raft/core/nvtx.hpp:48-96:
RAII ``range`` + ``push_range``/``pop_range``), mapped onto
``jax.profiler.TraceAnnotation`` so ranges show up in XLA/TPU profiler
traces. Disabled cheaply when profiling is off.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Iterator

import jax

_range_stack: list[Any] = []


@contextlib.contextmanager
def annotate(name: str, **kwargs) -> Iterator[None]:
    """RAII-style range (reference nvtx.hpp ``common::nvtx::range``)."""
    with jax.profiler.TraceAnnotation(name, **kwargs):
        yield


def push_range(name: str) -> None:
    t = jax.profiler.TraceAnnotation(name)
    t.__enter__()
    _range_stack.append(t)


def pop_range() -> None:
    if _range_stack:
        _range_stack.pop().__exit__(None, None, None)


def annotated(name: str | None = None):
    """Decorator adding a trace annotation around a function."""

    def deco(fn):
        label = name or f"raft_tpu.{fn.__qualname__}"

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco
