"""Packed bitset — backbone of filtered ANN search.

TPU-native analog of the reference's ``raft::core::bitset``
(cpp/include/raft/core/bitset.cuh:68,91,147). Bits are packed into uint32
words in a jax array; `test` is a vectorized gather+mask, `set` is a
scatter over words. All ops are jit-compatible.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class Bitset:
    """A device bitset over ``n_bits`` items, packed into uint32 words.

    Unlike the reference's mutable device structure, this is a thin wrapper
    over an immutable jax array; mutating ops return updated arrays (stored
    back on the wrapper for convenience).
    """

    WORD_BITS = 32

    def __init__(self, n_bits: int, bits: jax.Array | None = None, default: bool = True):
        self.n_bits = int(n_bits)
        # bumped by every in-place mutator (set/flip/resize) so caches
        # keyed on wrapper identity can detect content changes
        self._version = 0
        n_words = (self.n_bits + self.WORD_BITS - 1) // self.WORD_BITS
        if bits is not None:
            assert bits.shape == (n_words,)
            self.bits = bits.astype(jnp.uint32)
        else:
            fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
            self.bits = jnp.full((n_words,), fill, dtype=jnp.uint32)

    # -- functional kernels -------------------------------------------------
    @staticmethod
    def test_bits(bits: jax.Array, idx: jax.Array) -> jax.Array:
        """Vectorized test: returns bool array, True where bit set.

        Reference: ``bitset_view::test`` core/bitset.cuh:68.
        """
        word = bits[idx // Bitset.WORD_BITS]
        return ((word >> (idx % Bitset.WORD_BITS).astype(jnp.uint32)) & 1).astype(jnp.bool_)

    @staticmethod
    def set_bits(bits: jax.Array, idx: jax.Array, value: bool | jax.Array) -> jax.Array:
        """Vectorized set of bits at `idx` to `value` (core/bitset.cuh:91)."""
        word_idx = idx // Bitset.WORD_BITS
        mask = (jnp.uint32(1) << (idx % Bitset.WORD_BITS).astype(jnp.uint32)).astype(jnp.uint32)
        if isinstance(value, bool):
            value = jnp.full(idx.shape, value)
        # OR in set-bits, then AND out clear-bits. Scatter via segment ops so
        # duplicate word indices combine correctly.
        n_words = bits.shape[0]
        set_mask = jax.ops.segment_sum(
            jnp.where(value, mask, jnp.uint32(0)).astype(jnp.uint32),
            word_idx,
            num_segments=n_words,
            indices_are_sorted=False,
        )
        # segment_sum on uint32 masks with distinct bits == OR; duplicates of
        # the same bit would carry, so use segment_max of the single-bit mask
        # per bit position instead: build OR via bitwise accumulation.
        set_or = _segment_or(jnp.where(value, mask, jnp.uint32(0)), word_idx, n_words)
        clear_or = _segment_or(jnp.where(value, jnp.uint32(0), mask), word_idx, n_words)
        del set_mask
        return (bits | set_or) & ~clear_or

    def test(self, idx: jax.Array) -> jax.Array:
        return Bitset.test_bits(self.bits, jnp.asarray(idx))

    def set(self, idx: jax.Array, value: bool = True) -> "Bitset":
        self.bits = Bitset.set_bits(self.bits, jnp.asarray(idx), value)
        self._version += 1
        return self

    def flip(self) -> "Bitset":
        self.bits = ~self.bits
        self._version += 1
        return self

    @staticmethod
    def count_bits(bits: jax.Array, n_bits: int) -> jax.Array:
        """Functional set-bit count over raw words (tail bits of the last
        word beyond ``n_bits`` are masked out). Jit-safe for static
        ``n_bits`` — the helper behind :meth:`count` and the serving
        layer's tombstone/live-row accounting."""
        word_ids = jnp.arange(bits.shape[0]) * Bitset.WORD_BITS
        # bits valid in each word
        nvalid = jnp.clip(n_bits - word_ids, 0, Bitset.WORD_BITS)
        tail_mask = jnp.where(
            nvalid >= 32,
            jnp.uint32(0xFFFFFFFF),
            (jnp.uint32(1) << nvalid.astype(jnp.uint32)) - jnp.uint32(1),
        )
        return _popcount(bits & tail_mask).sum()

    def count(self) -> jax.Array:
        """Number of set bits (masking tail bits of the last word)."""
        return Bitset.count_bits(self.bits, self.n_bits)

    def copy(self) -> "Bitset":
        """An independent wrapper over the same (immutable) word array —
        later ``set``/``resize`` on either side cannot alias."""
        return Bitset(self.n_bits, bits=self.bits)

    def resize(self, n_bits: int, default: bool = True) -> "Bitset":
        """Grow (or shrink) to ``n_bits`` in place; new bits get ``default``.

        The tombstone-growth primitive (ISSUE 5): an index ``extend``
        appends rows whose ids exceed the filter built before it, and a
        tombstone keep-mask must default those NEW ids to *kept* —
        ``resize(new_n)`` does the word-array surgery (tail-bit fill of
        the old last word + appended fill words) that callers previously
        hand-rolled. Shrinking truncates. Returns ``self``.
        """
        n_bits = int(n_bits)
        old_n = self.n_bits
        if n_bits == old_n:
            return self
        fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
        n_words = (n_bits + self.WORD_BITS - 1) // self.WORD_BITS
        bits = self.bits
        if n_bits > old_n:
            tail = old_n % self.WORD_BITS
            if tail:
                # bits [tail, 32) of the old last word are undefined
                # (constructor fill / from_dense zero-pad): force `default`
                li = old_n // self.WORD_BITS
                mask = (jnp.uint32(1) << jnp.uint32(tail)) - jnp.uint32(1)
                bits = bits.at[li].set((bits[li] & mask) | (fill & ~mask))
            if n_words > bits.shape[0]:
                bits = jnp.concatenate(
                    [bits, jnp.full((n_words - bits.shape[0],), fill,
                                    dtype=jnp.uint32)]
                )
        else:
            bits = bits[:n_words]
        self.bits = bits
        self.n_bits = n_bits
        self._version += 1
        return self

    def to_dense(self) -> jax.Array:
        """Bool vector of length n_bits."""
        idx = jnp.arange(self.n_bits)
        return Bitset.test_bits(self.bits, idx)

    @staticmethod
    def from_dense(mask: jax.Array) -> "Bitset":
        mask = jnp.asarray(mask).astype(jnp.bool_)
        n = mask.shape[0]
        pad = (-n) % Bitset.WORD_BITS
        m = jnp.pad(mask, (0, pad)).reshape(-1, Bitset.WORD_BITS)
        weights = (jnp.uint32(1) << jnp.arange(Bitset.WORD_BITS, dtype=jnp.uint32))
        words = (m.astype(jnp.uint32) * weights[None, :]).sum(axis=1).astype(jnp.uint32)
        return Bitset(n, bits=words)


def _segment_or(values: jax.Array, segment_ids: jax.Array, num_segments: int) -> jax.Array:
    """Bitwise-OR segment combine for uint32 masks.

    Implemented as per-bit segment_max over the 32 bit planes would be slow;
    instead use the identity OR(a,b) = max per bit — realized by scattering
    with `jax.lax.scatter` in 'or' mode via int32 view and segment_max of
    each single-bit contribution: since each value has at most a few bits
    set and duplicates of the *same* (word,bit) pair are idempotent under
    max-of-masks only when masks are equal, we conservatively OR by
    accumulating with at[].max over identical masks then OR-ing residue.

    Simpler correct approach used here: sort-free `at[].apply` is not
    available, so do a loop over WORD_BITS bit-planes (static, 32 iters).
    """
    out = jnp.zeros((num_segments,), dtype=jnp.uint32)
    for b in range(32):
        bit = (values >> jnp.uint32(b)) & jnp.uint32(1)
        plane = jax.ops.segment_max(bit, segment_ids, num_segments=num_segments)
        out = out | (plane.astype(jnp.uint32) << jnp.uint32(b))
    return out


def _popcount(x: jax.Array) -> jax.Array:
    """Per-element popcount of uint32 (SWAR)."""
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return ((x * jnp.uint32(0x01010101)) >> 24).astype(jnp.int32)
