"""graft-flow: bounded-depth staged prefetch for streaming data paths.

Every out-of-core tier here has the same serial shape — read a chunk
from the host tier (memmap slice, ``.bin`` file block, shortlist
gather), upload it, score it, repeat — so the device idles during the
read and the host idles during the score. FusionANNS (arXiv:2409.16576)
earns its billion-scale numbers precisely by hiding storage fetch
behind GPU compute; with XLA's async dispatch the device side of that
overlap is already free, and the missing piece is a *background
producer* that keeps the next chunk's host work off the consumer's
critical path. That producer is this module.

:class:`Prefetcher` wraps any chunk iterator in a bounded buffer
(``depth`` slots, default 2 = classic double buffering) filled by one
background thread:

* **bitwise-off switch** — ``depth<=0`` runs the source inline on the
  consumer thread: no thread, no buffer, byte-identical scheduling to
  the pre-pipeline code. Depth only moves *when* work happens, never
  what is computed, so pipeline on vs off is bitwise-identical by
  construction on every wired path.
* **error attribution** — a producer exception is caught, carried
  through the buffer in order, and re-raised (the original object, so
  :func:`raft_tpu.resilience.errors.classify` and the faultinject
  classes survive) at the consuming ``next()`` — faults injected in a
  read stage attribute to the chunk's consuming iteration, not to a
  background stack.
* **cancellation** — ``close()`` (and the consumer's
  :class:`~raft_tpu.core.interruptible.Interruptible` token) stops the
  producer at its next buffer interaction and joins it; the thread is
  daemonized so even a producer wedged inside a slow read can never pin
  interpreter exit (GL014).
* **resize/flush** — :meth:`flush` discards buffered-but-unconsumed
  chunks and restarts the producer from a fresh iterator, the hook the
  OOM degradation ladder needs: after a downshift the already-prefetched
  chunks carry the old batch geometry, so the ladder rewinds the source
  (``start_row``), shrinks it (``set_batch_rows``), and flushes.
* **accounting** — ``pipeline.stall_ms{path}`` (consumer waited on the
  producer), ``pipeline.occupancy`` / ``pipeline.prefetch_depth``
  gauges, and :meth:`stats` totals for the bench scripts'
  overlap-fraction columns (docs/observability.md).

Checkpoint composition (docs/resilience.md): prefetch hands the
consumer chunks *earlier*, never marks them done — StreamCheckpoint
writes remain strictly consumption-ordered, so kill+resume stays
bitwise with any number of chunks in flight.

``pipeline_depth`` rides the tuning-budget plumbing
(:func:`resolve_depth`): ``RAFT_TPU_TUNING`` modes read a measured
depth from the active dispatch table, and a runtime
:func:`raft_tpu.tuning.record_budget` ceiling (recorded when a
downshift proves memory pressure) clamps it process-wide.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Iterable, Iterator, Optional, Union

from raft_tpu import obs, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.core.interruptible import Interruptible

# tuning-budget key for the prefetch buffer depth (docs/dispatch_tuning.md)
PIPELINE_DEPTH_BUDGET = "pipeline_depth"
# double-buffered: one chunk in flight while one is consumed — the knee
# of the occupancy curve on every measured leg (PIPE_r16.json)
DEFAULT_DEPTH = 2

# depth candidates the capture harness races (scripts/r5_measure_all.py
# --stage pipeline): 0 = off, 1 = single-slot handoff, 2 = double
# buffer, 4 = deep (only wins when read latency is bursty)
PIPELINE_DEPTH_CANDIDATES = (0, 1, 2, 4)


def resolve_depth(depth: Optional[int] = None) -> int:
    """The effective prefetch depth: an explicit ``depth`` wins, else the
    ``pipeline_depth`` tuning budget (table value in non-off modes, the
    double-buffered default otherwise, always clamped by a recorded
    runtime ceiling). Never negative; 0 = pipeline off."""
    if depth is not None:
        return max(int(depth), 0)
    return max(int(tuning.budget(PIPELINE_DEPTH_BUDGET, DEFAULT_DEPTH)), 0)


Source = Union[Iterable, Callable[[], Iterator]]


def _make_iter(source: Source) -> Iterator:
    return iter(source() if callable(source) else source)


class Prefetcher:
    """Iterate ``source`` with up to ``depth`` items produced ahead.

    ``source`` is an iterable or a zero-arg callable returning an
    iterator; a callable (or a re-iterable like ``BatchLoadIterator``)
    is required for :meth:`flush` to restart after a resize. Yields the
    source's items unchanged and in order.

    ``depth<=0`` is the off mode: items are pulled inline on the
    consumer thread with zero added machinery. ``token`` (default: the
    constructing thread's token) wakes a parked consumer promptly on
    cross-thread ``cancel()`` and stops the producer at its next
    buffer interaction.

    Use as a context manager (or call :meth:`close`) so the producer is
    joined on every exit path, including consumer-side exceptions.
    """

    def __init__(
        self,
        source: Source,
        depth: Optional[int] = None,
        *,
        path: str = "pipeline",
        token: Optional[Interruptible] = None,
    ):
        self._source = source
        self._depth = resolve_depth(depth)
        self._path = path
        self._token = token if token is not None \
            else Interruptible.get_token()
        # one condition guards buffer+epoch+stop; "core.pipeline" is its
        # node in the lock hierarchy (docs/serving.md §11) — leaf-level,
        # never held across a callback into user code
        self._cv = lockwatch.make_condition(
            lockwatch.make_lock("core.pipeline"))
        self._buf: deque = deque()
        self._epoch = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._inline_it: Optional[Iterator] = None
        # accounting (consumer-thread writes; reads via stats())
        self._stall_ms = 0.0
        self._wait_ms = 0.0
        self._items = 0
        self._stalls = 0
        self._occ_sum = 0
        if self._depth > 0:
            obs.gauge("pipeline.prefetch_depth", self._depth,
                      path=self._path)

    @property
    def depth(self) -> int:
        """The effective (resolved) prefetch depth; 0 = off/inline."""
        return self._depth

    # -- lifecycle ---------------------------------------------------------

    def _start_locked(self) -> None:
        epoch = self._epoch
        it = _make_iter(self._source)
        t = threading.Thread(
            target=self._produce, args=(it, epoch),
            name=f"raft-tpu-prefetch-{self._path}", daemon=True,
        )
        self._thread = t
        t.start()

    def _produce(self, it: Iterator, epoch: int) -> None:
        try:
            for item in it:
                with self._cv:
                    while (len(self._buf) >= self._depth
                           and not self._stop and self._epoch == epoch):
                        self._cv.wait()
                    if self._stop or self._epoch != epoch:
                        return
                    self._buf.append(("item", item))
                    self._cv.notify_all()
                if self._token.cancelled():
                    # drain, don't raise: the consumer's own token.check()
                    # raises InterruptedException at its chunk boundary;
                    # the producer just stops feeding and exits
                    return
        except BaseException as e:  # noqa: BLE001 — carried to the consumer and re-raised at the consuming next(); classification happens there
            with self._cv:
                if self._epoch == epoch and not self._stop:
                    self._buf.append(("err", e))
                    self._cv.notify_all()
            return
        with self._cv:
            if self._epoch == epoch and not self._stop:
                self._buf.append(("end", None))
                self._cv.notify_all()

    def flush(self) -> None:
        """Discard produced-but-unconsumed items and restart the
        producer from a fresh ``iter(source)`` at the next pull — the
        OOM-downshift hook: rewind/shrink the source first, then flush.
        No-op in off mode (nothing is ever buffered ahead)."""
        if self._depth <= 0:
            self._inline_it = None
            return
        with self._cv:
            self._epoch += 1
            self._buf.clear()
            self._cv.notify_all()
            t, self._thread = self._thread, None
        if t is not None:
            # the producer exits at its next buffer interaction; a read
            # wedged in slow IO keeps the (daemon) thread alive past the
            # timeout, and its stale item is dropped by the epoch check
            t.join(timeout=30.0)
        obs.counter("pipeline.flushes", path=self._path)

    def close(self) -> None:
        """Stop and join the producer, dropping buffered items. Safe to
        call twice; called by ``__exit__`` and by the wired paths'
        ``finally`` blocks so no exit path leaks the thread."""
        if self._depth <= 0:
            self._inline_it = None
            return
        with self._cv:
            self._stop = True
            self._epoch += 1
            self._buf.clear()
            self._cv.notify_all()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)
        if self._items:
            obs.gauge("pipeline.occupancy",
                      self._occ_sum / max(self._items, 1), path=self._path)

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- consumption -------------------------------------------------------

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self):
        if self._depth <= 0:
            if self._inline_it is None:
                self._inline_it = _make_iter(self._source)
            t0 = time.perf_counter()
            item = next(self._inline_it)
            # in off mode the whole read IS a stall: the consumer waits
            # for it inline. Recording it makes the depth=0 vs depth=2
            # stall comparison a single metric query.
            ms = (time.perf_counter() - t0) * 1e3
            self._stall_ms += ms
            self._wait_ms += ms
            self._items += 1
            self._stalls += 1
            if obs.enabled():
                obs.observe("pipeline.stall_ms", ms, path=self._path)
            return item
        t0 = time.perf_counter()
        with self._cv:
            if self._thread is None and not self._buf and not self._stop:
                self._start_locked()
            stalled = not self._buf
            while not self._buf:
                if self._token.cancelled():
                    self._token.check()     # raises InterruptedException
                if self._thread is None or not self._thread.is_alive():
                    raise RuntimeError(
                        f"pipeline[{self._path}]: producer thread died "
                        "without delivering an end/err envelope")
                self._cv.wait(0.05)
            kind, val = self._buf.popleft()
            if kind == "item":
                # mean-occupancy sample: this item plus what is still
                # buffered; end/err envelopes are not occupancy
                self._occ_sum += len(self._buf) + 1
            self._cv.notify_all()
        wait = (time.perf_counter() - t0) * 1e3
        self._wait_ms += wait
        if stalled:
            self._stall_ms += wait
            self._stalls += 1
            if obs.enabled():
                obs.observe("pipeline.stall_ms", wait, path=self._path)
        if kind == "err":
            self.close()
            raise val
        if kind == "end":
            self.close()
            raise StopIteration
        self._items += 1
        return val

    # -- accounting --------------------------------------------------------

    def stats(self) -> dict:
        """Consumer-side totals: ``stall_ms`` (time the consumer spent
        blocked on the producer — in off mode, the full inline read
        time), ``items``, ``stalls``, ``occupancy`` (mean buffered
        items at pop, in [0, depth]), and the effective ``depth``. The
        bench scripts derive their overlap-fraction column as
        ``1 - stall_ms(depth=N) / stall_ms(depth=0)``."""
        return {
            "depth": self._depth,
            "path": self._path,
            "items": self._items,
            "stalls": self._stalls,
            "stall_ms": self._stall_ms,
            "wait_ms": self._wait_ms,
            "occupancy": self._occ_sum / max(self._items, 1),
        }


class _Staged:
    """Iterator applying ``fn`` to an upstream iterator's items — the
    restartable unit :func:`overlap` chains Prefetchers over."""

    def __init__(self, upstream: Source, fn: Callable):
        self._upstream = upstream
        self._fn = fn

    def __call__(self) -> Iterator:
        fn = self._fn
        return (fn(x) for x in _make_iter(self._upstream))


def overlap(
    source: Source,
    *stages: Callable,
    depth: Optional[int] = None,
    path: str = "pipeline",
    token: Optional[Interruptible] = None,
) -> Prefetcher:
    """Compose a staged pipeline over ``source``: each stage is a unary
    function applied to the previous stage's items, every stage boundary
    gets its own bounded :class:`Prefetcher`, and the caller consumes
    the final stage's output. ``overlap(read_chunks, upload, ...)``
    therefore runs chunk N+1's read concurrently with chunk N's upload
    while the caller computes on chunk N-1 — the classic
    read/upload/compute overlap with the compute stage being the
    consuming loop itself.

    Returns the outermost :class:`Prefetcher` (iterate it, ``close()``
    it or use it as a context manager — closing it closes the whole
    chain). ``depth<=0`` composes inline on the consumer thread and is
    bitwise-equivalent scheduling to the unpipelined loop.
    """
    d = resolve_depth(depth)
    if not stages:
        return Prefetcher(source, depth=d, path=path, token=token)
    up: Source = source
    chain: list = []                      # upstream-first
    names = [getattr(s, "__name__", f"s{i}") for i, s in enumerate(stages)]
    for i, stage in enumerate(stages):
        pf = Prefetcher(_Staged(up, stage), depth=d,
                        path=f"{path}.{names[i]}", token=token)
        chain.append(pf)
        up = pf
    outer = chain[-1]

    # closing the outermost prefetcher must join EVERY producer in the
    # chain, upstream-first: stopping an upstream unblocks the stage
    # thread pulling from it, so each join returns promptly instead of
    # waiting out a producer parked on a live upstream
    def close_chain(_chain=tuple(chain)):
        for p in _chain:
            Prefetcher.close(p)

    outer.close = close_chain  # type: ignore[method-assign]
    return outer
