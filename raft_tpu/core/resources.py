"""The resources "handle".

TPU-native analog of the reference's ``raft::resources`` /
``raft::device_resources`` (reference: cpp/include/raft/core/resources.hpp:47,
cpp/include/raft/core/device_resources.hpp:61). The reference handle is a
type-indexed lazy container of CUDA streams, cuBLAS/cuSOLVER handles, and
communicators. On TPU, XLA owns scheduling and kernel libraries, so the
handle shrinks to:

  * the target device (or sharding mesh) computations should run on,
  * a functional RNG key (split on demand),
  * an optional communicator (comms facade over jax collectives),
  * a logger and workspace-size hints used by tiled algorithms.

The lazy slot-registry *idea* is kept (``add_resource_factory`` /
``get_resource``) so that comms and future subsystems can be injected the
same way the reference injects its COMMUNICATOR slot
(cpp/include/raft/core/resource/resource_types.hpp:29).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import jax
import numpy as np

from raft_tpu.analysis import lockwatch


class resource_type:
    """Slot names for the lazy resource registry.

    Mirrors the reference's ``enum resource_type``
    (core/resource/resource_types.hpp:29-48); the CUDA-specific slots
    (cublas/cusolver/stream pool/...) have no TPU analog and are absent.
    """

    DEVICE = "device"
    MESH = "mesh"
    COMMUNICATOR = "communicator"
    SUB_COMMUNICATOR = "sub_communicator"
    RNG_KEY = "rng_key"
    WORKSPACE_LIMIT = "workspace_limit"
    LOGGER = "logger"


class Resources:
    """Lazy, type-indexed resource container (reference core/resources.hpp:47).

    Factories are registered per slot and instantiated on first
    ``get_resource``. Thread-safe like the reference (which documents the
    handle as not thread-safe for mutation but safe for reads; we just lock).
    """

    def __init__(self) -> None:
        self._factories: dict[str, Callable[[], Any]] = {}
        self._resources: dict[str, Any] = {}
        # graft-race sanitizer node "core.resources"
        self._lock = lockwatch.make_lock("core.resources")

    def add_resource_factory(self, slot: str, factory: Callable[[], Any]) -> None:
        with self._lock:
            self._factories[slot] = factory
            self._resources.pop(slot, None)

    def has_resource_factory(self, slot: str) -> bool:
        with self._lock:
            return slot in self._factories or slot in self._resources

    def get_resource(self, slot: str) -> Any:
        with self._lock:
            if slot not in self._resources:
                if slot not in self._factories:
                    raise KeyError(f"no resource factory registered for slot {slot!r}")
                self._resources[slot] = self._factories[slot]()
            return self._resources[slot]

    def set_resource(self, slot: str, value: Any) -> None:
        with self._lock:
            self._resources[slot] = value


class DeviceResources(Resources):
    """The user-facing handle (reference core/device_resources.hpp:61).

    Convenience accessors over `Resources`. Where the reference exposes
    ``get_cuda_stream``/``get_cublas_handle``, we expose the device/mesh, a
    splittable RNG key, and the communicator.

    Parameters
    ----------
    device : optional jax.Device — default device for placement.
    mesh : optional jax.sharding.Mesh for distributed algorithms.
    seed : int seed for the handle's RNG stream.
    workspace_limit : soft cap (bytes) tiled algorithms use when picking
        batch/tile sizes (analog of the reference's workspace memory
        resource limit, device_resources.hpp:64-70).
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        mesh: Optional["jax.sharding.Mesh"] = None,
        seed: int = 0,
        workspace_limit: int = 2 * 1024**3,
    ) -> None:
        super().__init__()
        self.add_resource_factory(
            resource_type.DEVICE, lambda: device if device is not None else jax.devices()[0]
        )
        self.add_resource_factory(resource_type.MESH, lambda: mesh)
        self.add_resource_factory(resource_type.RNG_KEY, lambda: jax.random.PRNGKey(seed))
        self.add_resource_factory(resource_type.WORKSPACE_LIMIT, lambda: workspace_limit)

    # -- accessors (reference: core/resource/*.hpp, 15 accessor headers) ----
    @property
    def device(self) -> jax.Device:
        return self.get_resource(resource_type.DEVICE)

    @property
    def mesh(self):
        return self.get_resource(resource_type.MESH)

    def set_mesh(self, mesh) -> None:
        self.set_resource(resource_type.MESH, mesh)

    @property
    def comms(self):
        """The injected communicator (reference core/resource/comms.hpp)."""
        return self.get_resource(resource_type.COMMUNICATOR)

    def set_comms(self, comms) -> None:
        self.set_resource(resource_type.COMMUNICATOR, comms)

    @property
    def workspace_limit(self) -> int:
        return self.get_resource(resource_type.WORKSPACE_LIMIT)

    def set_workspace_limit(self, nbytes: int) -> None:
        self.set_resource(resource_type.WORKSPACE_LIMIT, nbytes)

    def rng_key(self) -> jax.Array:
        """Split and return a fresh PRNG key from the handle's stream.

        Functional replacement for the reference's per-handle RngState
        mutation — each call advances the handle's key.
        """
        key = self.get_resource(resource_type.RNG_KEY)
        key, sub = jax.random.split(key)
        self.set_resource(resource_type.RNG_KEY, key)
        return sub

    def sync(self) -> None:
        """Block until all queued work is complete.

        Analog of ``device_resources::sync_stream``; with XLA async dispatch
        this blocks on all live arrays (used by benches for timing).
        """
        (jax.device_put(np.zeros(()), self.device) + 0).block_until_ready()


# Process-wide default-handle pool: analog of device_resources_manager
# (reference core/device_resources_manager.hpp:43) — one handle per device,
# created on first use.
_default_handles: dict[int, DeviceResources] = {}
_default_lock = lockwatch.make_lock("core.resources_default")


def get_device_resources(device: Optional[jax.Device] = None) -> DeviceResources:
    dev = device if device is not None else jax.devices()[0]
    with _default_lock:
        if dev.id not in _default_handles:
            _default_handles[dev.id] = DeviceResources(device=dev)
        return _default_handles[dev.id]
