"""graft-serve: the online serving engine (ISSUE 5; docs/serving.md).

Everything before this package is a library: you hold the index, you
call search, you own the batch shapes. ``raft_tpu.serve`` makes it a
*service* — the piece FusionANNS (PAPERS.md) shows the end-to-end win
lives in, and the piece TPU-KNN's peak-FLOP/s numbers quietly assume
(fixed, padded batch shapes):

* **dynamic micro-batching** (:mod:`raft_tpu.serve.batcher`) —
  concurrent ``submit(query, k)`` calls coalesce into padded batches
  drawn from a fixed power-of-two bucket ladder, warmed at startup so
  steady-state serving never traces (the GL007 zero-recompile bar);
  bounded-queue backpressure rejects with :class:`Overloaded`
  (classified transient through ``resilience``);
* **versioned hot-swap** (:mod:`raft_tpu.serve.registry`) — named
  indexes advance through refcounted generations: background build/load,
  one atomic swap, in-flight batches finish on the generation they
  pinned, the old one frees when its last pin drains;
* **tombstone mutation** (:mod:`raft_tpu.serve.mutation`) —
  ``delete``/``upsert`` as a keep-mask composed into the existing
  filtered-search paths of all four index types, upserts served from a
  brute-force side buffer merged via ``merge_topk`` until a background
  ``extend`` + swap compacts them in;
* the engine (:mod:`raft_tpu.serve.engine`) threading it through
  ``obs`` (queue depth, fill ratio, rejects, swaps, per-bucket
  latency), ``resilience.run`` (classified retry; OOM downshifts the
  bucket ceiling), and ``tuning`` (measured bucket choice, learned
  row budgets);
* **multi-host fabric** (:mod:`raft_tpu.serve.fabric`, ISSUE 6) — the
  cluster tier: N worker processes own index shards
  (:mod:`raft_tpu.comms.procgroup`), a router fans each micro-batch to
  shard owners with health-tracked circuit breaking, hedged retries,
  per-row coverage on degraded answers, and a two-phase cross-host
  hot-swap barrier over the registry (docs/serving.md §10);
* **self-healing control plane** (:mod:`raft_tpu.serve.controller`,
  ISSUE 18) — graft-helm closes the cluster loops the fabric leaves to
  an operator: p2c replica load-balancing feeds a controller that
  rebalances shards off workers whose circuits stay open past the
  tuning budget and autoscales the worker set on saturated-stage
  signals with cooldown/hysteresis (docs/serving.md §10);
* **online quality control** (:mod:`raft_tpu.serve.quality`, ISSUE 19)
  — graft-gauge samples answered live queries onto a best-effort
  shadow lane, re-runs them through the generation-pinned exhaustive
  oracle, exports windowed Wilson-interval recall estimates
  (``serve.recall_estimate{index,rung}``), and closes the loop:
  bounded ``AdaptivePolicy`` retunes under the stated recall band and
  probation rollback of a degrading hot-swap (docs/serving.md §14).
"""

from raft_tpu.serve.adaptive import AdaptivePolicy, probe_ladder
from raft_tpu.serve.controller import HelmController, HelmParams
from raft_tpu.serve.batcher import (
    Batch,
    MicroBatcher,
    Overloaded,
    Request,
    bucket_ladder,
    choose_bucket,
)
from raft_tpu.serve.engine import ServeParams, Server
from raft_tpu.serve.fabric import (
    Fabric,
    FabricParams,
    FabricSwapError,
    WorkerHealth,
)
from raft_tpu.serve.mutation import MutableState
from raft_tpu.serve.quality import QualityMonitor, wilson_interval
from raft_tpu.serve.registry import Generation, Registry

# the jitted hot-path entry points whose trace caches must stay FLAT in
# steady-state serving — the serve-side extension of
# obs.metrics._TRACKED_JITS; tests/test_serve.py asserts zero growth
# across a mixed-size post-warmup stream with trace_cache_sizes()
TRACKED_JITS = (
    ("raft_tpu.neighbors.brute_force", "_search"),
    ("raft_tpu.neighbors.ivf_flat", "_ivf_search"),
    ("raft_tpu.neighbors.ivf_flat", "_coarse_margins"),
    ("raft_tpu.neighbors.ivf_pq", "_pq_search"),
    ("raft_tpu.neighbors.cagra", "_beam_search"),
    ("raft_tpu.neighbors.cagra", "_beam_search_pallas"),
    ("raft_tpu.neighbors.refine", "_refine"),
    ("raft_tpu.neighbors.tiered", "_score_fetched"),
    ("raft_tpu.neighbors.tiered", "_score_fetched_hot"),
    ("raft_tpu.neighbors.tiered", "_promote_scatter"),
    ("raft_tpu.serve.engine", "_merge_with_side"),
    ("raft_tpu.neighbors.hybrid", "_fuse_rescore"),
    ("raft_tpu.sparse.neighbors", "_score_block_dense_q"),
    ("raft_tpu.matrix.select_k", "_select_k"),
    ("raft_tpu.matrix.select_k", "_tournament_topk"),
)


def trace_cache_sizes() -> dict:
    """Per-function jit trace-cache entry counts for the serving hot
    paths (the GL007 trace-counting hook, serving edition). Compare
    before/after a traffic window: any growth means a shape escaped the
    bucket/k ladder."""
    import importlib

    out = {}
    for mod_name, fn_name in TRACKED_JITS:
        try:
            fn = getattr(importlib.import_module(mod_name), fn_name, None)
        except ImportError:
            continue
        size_of = getattr(fn, "_cache_size", None)
        if size_of is None:
            continue
        try:
            out[f"{mod_name.rsplit('.', 1)[-1]}.{fn_name}"] = int(size_of())
        except Exception:  # noqa: BLE001 — private jax API probe; a missing gauge is the degraded answer
            continue
    return out


def total_trace_count() -> int:
    """Sum of :func:`trace_cache_sizes` — the single number the
    trace-stability acceptance test pins."""
    return sum(trace_cache_sizes().values())


__all__ = [
    "AdaptivePolicy", "Batch", "Fabric", "FabricParams",
    "FabricSwapError", "Generation", "HelmController", "HelmParams",
    "MicroBatcher", "MutableState", "Overloaded", "QualityMonitor",
    "Registry",
    "Request", "ServeParams", "Server", "TRACKED_JITS", "WorkerHealth",
    "bucket_ladder", "choose_bucket", "probe_ladder",
    "total_trace_count", "trace_cache_sizes", "wilson_interval",
]
