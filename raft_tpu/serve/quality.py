"""graft-gauge: online recall estimation + closed-loop quality control
(ISSUE 19; docs/serving.md §14).

Recall is the metric this reproduction exists to serve, yet until now it
was only ever measured OFFLINE (the ann-bench harness) — rungs were
calibrated once and trusted forever while drift arrived with the data
distribution, the mutation load, and every hot-swap. graft-gauge closes
that gap with a shadow-oracle lane:

* **sampling** — :meth:`QualityMonitor.offer` runs at delivery for
  every answered live batch and picks ~``quality_sample_rate`` of the
  requests by a deterministic counter stride (no RNG, no allocation on
  the skip path). A sampled request's queries + SERVED ids are copied
  and queued on the batcher's best-effort shadow lane with an extra pin
  on the generation that answered — a hot-swap between sampling and
  re-run cannot re-point the oracle at a different index, so the score
  is always "what we served" vs "that same generation's exact answer";
* **the oracle** — the engine re-runs each shadow batch through
  :meth:`_IndexServing._run_search` at ``rung=None``: the exhaustive
  top rung, the very program warmup already traced for every
  (bucket, k) — so the shadow lane adds ZERO steady-state traces and
  only ever runs when both live lanes are idle;
* **estimation** — per-slot matches aggregate into a sliding window of
  (matched, slots) counts per probe rung; each scored batch refreshes
  Wilson score intervals exported as ``serve.recall_estimate`` /
  ``serve.recall_ci_low`` / ``serve.recall_ci_high`` gauges (per rung
  plus the pooled ``rung="all"``) and a ``serve.recall_sample``
  histogram on the unit-interval buckets — all of which federate
  across a fabric exactly like every other registry series;
* **the closed loop** — when the pooled CI's UPPER bound drops below
  the stated recall band, quality is degraded beyond statistical doubt:
  a post-swap probation window whose estimate also degrades versus the
  predecessor's rolls the swap back
  (:meth:`raft_tpu.serve.registry.Registry.rollback`); otherwise the
  generation's :class:`~raft_tpu.serve.adaptive.AdaptivePolicy` is
  retuned one bounded step toward recall
  (:meth:`~raft_tpu.serve.adaptive.AdaptivePolicy.tightened`), with
  cooldown windows between steps and a hysteresis band before any
  relax — no human in the loop.

Everything here is OFF the latency path: with
``quality_sample_rate=0`` the delivery hook is one attribute read; with
obs off the sampling decision is one module-attribute read; shadow
re-runs ride the best-effort lane that only drains when no live work is
queued.
"""

from __future__ import annotations

import collections
import math
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.analysis import lockwatch
from raft_tpu.obs import config as _obs_config
from raft_tpu.serve.batcher import Batch, Request
from raft_tpu.serve.registry import Generation

# recall band default in BASIS POINTS (the unit tuning budgets carry):
# below 0.90 pooled recall the closed loop acts
DEFAULT_RECALL_BAND_BP = 9000

# normal z for the 95% Wilson score interval
_WILSON_Z = 1.96

# CI-low must clear band + hysteresis before a relax step — without the
# dead zone the loop would tighten/relax forever around the band edge
RELAX_HYSTERESIS = 0.02

# a successor must estimate this far under its predecessor before the
# degradation reads as "the swap did it" rather than noise
ROLLBACK_MARGIN = 0.02


def wilson_interval(successes: float, trials: float,
                    z: float = _WILSON_Z) -> Tuple[float, float]:
    """The Wilson score interval for a binomial proportion — the small-n
    honest version of the normal approximation: never escapes [0, 1]
    and stays informative at the handful-of-samples scale a 0.1%%
    shadow lane starts from."""
    n = float(trials)
    if n <= 0:
        return 0.0, 1.0
    p = min(max(float(successes) / n, 0.0), 1.0)
    z2 = z * z
    denom = 1.0 + z2 / n
    center = (p + z2 / (2.0 * n)) / denom
    half = (z / denom) * math.sqrt(
        p * (1.0 - p) / n + z2 / (4.0 * n * n))
    return max(0.0, center - half), min(1.0, center + half)


class ShadowSample:
    """One sampled request's scoring payload, carried through the
    shadow lane on :attr:`Request.shadow`: the pinned generation that
    served it, the probe rung it served at, and the ids the client
    actually received. The pin is THIS sample's: released after
    scoring, on drop-oldest overflow, and at close."""

    __slots__ = ("gen", "rung", "served", "k")

    def __init__(self, gen: Generation, rung: Optional[int],
                 served: np.ndarray, k: int):
        self.gen = gen
        self.rung = rung
        self.served = served
        self.k = int(k)


def _rung_label(rung: Optional[int]) -> str:
    return "exhaustive" if rung is None else str(rung)


class QualityMonitor:
    """Per-index online recall estimator + quality-control actuator.

    Created by the engine's serving unit when
    ``ServeParams.quality_sample_rate > 0``; all entry points are
    internal to serving:

    * :meth:`offer` — the delivery-side sampler (batcher or completion
      thread);
    * :meth:`score_batch` — called by the engine's shadow dispatch with
      the oracle's answers;
    * :meth:`before_publish` / :meth:`after_publish` — the swap
      probation hooks (pin the predecessor, baseline its estimate);
    * :meth:`stats` — the introspection surface ``Server.stats`` and
      the drift drill read.
    """

    def __init__(self, serving, name: str):
        self.serving = serving
        self.name = name
        p = serving.params
        rate = float(p.quality_sample_rate)
        # deterministic stride sampling: request j is sampled iff
        # j % stride == 0 — no RNG state, nothing allocated per skip
        self.stride = max(1, int(round(1.0 / rate))) if rate > 0 else 0
        band = p.quality_band
        if band is None:
            from raft_tpu import tuning

            band = tuning.budget("serve_recall_band_bp",
                                 DEFAULT_RECALL_BAND_BP) / 1e4
        self.band = float(band)
        self.window = max(int(p.quality_window), 8)
        self.min_samples = max(int(p.quality_min_samples), 4)
        self.retune_enabled = bool(p.quality_retune)
        self.rollback_enabled = bool(p.quality_rollback)
        self.max_retunes = int(p.quality_max_retunes)
        # graft-race sanitizer node "serve.quality" — below the engine
        # lock (publish hooks run under it), above registry/generation
        self._lock = lockwatch.make_lock("serve.quality")
        self._tick = 0
        # sliding sample window: (matched_slots, total_slots, rung_label)
        self._samples: Deque[Tuple[int, int, str]] = collections.deque(
            maxlen=self.window)
        self._since_action = 0          # samples since the last retune
        self._est: Optional[Tuple[float, float, float, int]] = None
        # retune state: the policy the current generation STARTED with,
        # so step n is base.tightened()^n and a relax is exactly n-1
        self._base_policy = None
        self._base_version: Optional[int] = None
        self._steps = 0
        # a refine-ladder retune's re-warm, handed out of the lock by
        # score_batch (warmup acquires the mutation-state lock)
        self._deferred_rewarm = None
        # swap probation: a pin + baseline on the predecessor until the
        # successor proves itself (or degrades and is rolled back)
        self._prev_gen: Optional[Generation] = None
        self._prev_est: Optional[float] = None
        self._succ_version: Optional[int] = None
        self._succ_samples = 0
        self._closed = False
        # action log for the drill / stats: (kind, detail) tuples
        self.actions: Deque[Tuple[str, dict]] = collections.deque(
            maxlen=64)

    # -- sampling (the delivery hook) --------------------------------------

    def offer(self, batch: Batch, gen: Generation, h,
              ext: np.ndarray) -> None:
        """Sample answered requests out of a delivered live batch onto
        the shadow lane. Called by ``_deliver`` AFTER the futures
        resolve — the client's latency never includes this. The skip
        path is a counter increment and a modulo per request; only a
        sampled hit copies its queries/ids and takes a pin."""
        if not _obs_config.ENABLED or self.stride <= 0:
            return
        picked: List[Tuple[Request, int]] = []
        with self._lock:
            if self._closed:
                return
            row = 0
            for r in batch.requests:
                self._tick += 1
                if self._tick % self.stride == 0:
                    picked.append((r, row))
                row += r.rows
        if not picked:
            return
        # the copies + pins happen OUTSIDE the monitor lock: nothing
        # here races (the slices are this thread's delivery arrays)
        for r, start in picked:
            served = np.array(ext[start:start + r.rows, :r.k],
                              copy=True)
            try:
                gen.pin()
            except RuntimeError:
                continue       # drained under us: sample dies unscored
            sample = ShadowSample(gen, batch.rung, served, r.k)
            req = Request(
                queries=np.array(r.queries, copy=True, dtype=h.dtype),
                k=r.k, prefilter=batch.prefilter, future=Future(),
                shadow=sample)
            dropped = self.serving.batcher.submit_shadow(req)
            for dr in dropped:
                dr.shadow.gen.release()
            if dropped:
                obs.counter("serve.shadow_dropped_total", len(dropped),
                            index=self.name)

    # -- scoring (the shadow-dispatch callback) ----------------------------

    def score_batch(self, batch: Batch, oracle_ext: np.ndarray) -> None:
        """Score each shadow sample's SERVED ids against the oracle's
        exhaustive answer and fold the counts into the estimate window.
        recall@k per row = |served ∩ oracle| / |oracle's valid slots|
        (masked ``-1`` slots — tombstoned / beyond the live row count —
        count for neither side)."""
        row = 0
        scored = 0
        with self._lock:
            if self._closed:
                return
            for r in batch.requests:
                s: ShadowSample = r.shadow
                matched = 0
                slots = 0
                for j in range(r.rows):
                    truth = oracle_ext[row + j, :s.k]
                    truth = set(int(x) for x in truth if int(x) >= 0)
                    got = set(int(x) for x in s.served[j] if int(x) >= 0)
                    matched += len(got & truth)
                    slots += max(len(truth), 1)
                row += r.rows
                self._samples.append(
                    (matched, slots, _rung_label(s.rung)))
                self._since_action += 1
                self._succ_samples += 1
                scored += 1
                obs.observe("serve.recall_sample",
                            matched / slots if slots else 0.0,
                            buckets=obs.UNIT_BUCKETS, index=self.name,
                            rung=_rung_label(s.rung))
            if scored:
                obs.counter("serve.shadow_samples_total", scored,
                            index=self.name)
                self._update_estimates_locked()
                self._act_locked()
            rewarm = self._deferred_rewarm
            self._deferred_rewarm = None
        # the refine-ladder re-warm acquires the mutation-state lock
        # (warmup snapshots tombstone bits); run it AFTER releasing the
        # monitor lock or the quality->mutation edge closes a GL013
        # cycle with _publish_guarded (engine->quality) and compaction
        # (mutation->engine)
        if rewarm is not None and self.serving.warmup_enabled:
            self.serving.warmup_handle(rewarm)

    def _update_estimates_locked(self) -> None:
        by_rung: Dict[str, List[int]] = {}
        for matched, slots, rung in self._samples:
            agg = by_rung.setdefault(rung, [0, 0])
            agg[0] += matched
            agg[1] += slots
        total = [0, 0]
        for matched, slots in by_rung.values():
            total[0] += matched
            total[1] += slots
        for rung, (matched, slots) in list(by_rung.items()) + \
                [("all", tuple(total))]:
            if not slots:
                continue
            est = matched / slots
            lo, hi = wilson_interval(matched, slots)
            obs.gauge("serve.recall_estimate", est, index=self.name,
                      rung=rung)
            obs.gauge("serve.recall_ci_low", lo, index=self.name,
                      rung=rung)
            obs.gauge("serve.recall_ci_high", hi, index=self.name,
                      rung=rung)
            if rung == "all":
                self._est = (est, lo, hi, slots)

    # -- the closed loop ---------------------------------------------------

    def _act_locked(self) -> None:
        if self._est is None or len(self._samples) < self.min_samples:
            return
        est, lo, hi, _slots = self._est
        degraded = hi < self.band
        if (self._prev_gen is not None and not degraded
                and self._succ_samples >= self.window):
            # the successor held the band for a full window of its own
            # samples: probation over, the predecessor may drain (its
            # device arrays are only as free as this pin)
            self._clear_probation_locked()
        if degraded:
            obs.event("recall_alarm", index=self.name,
                      estimate=round(est, 4), ci_high=round(hi, 4),
                      band=self.band)
        if degraded and self._rollback_due_locked(hi):
            self._rollback_locked(est, hi)
            return
        if degraded and self.retune_enabled:
            if (self._since_action >= self.min_samples
                    and self._steps < self.max_retunes):
                self._retune_locked("tighten", est, hi)
            return
        if (not degraded and self.retune_enabled and self._steps > 0
                and lo > self.band + RELAX_HYSTERESIS
                and self._since_action >= self.window):
            self._retune_locked("relax", est, hi)

    def _rollback_due_locked(self, ci_high: float) -> bool:
        """A degraded estimate is pinned on the SWAP (not drift) when a
        probation window is open, the successor has enough of its own
        samples, and the predecessor's baseline was measurably
        better."""
        if not self.rollback_enabled or self._prev_gen is None:
            return False
        if self._succ_samples < self.min_samples:
            return False
        if self._prev_est is None:
            # no pre-swap estimate to compare against: the band breach
            # alone convicts the swap — the predecessor served inside
            # the band long enough that no alarm ever fired
            return True
        return ci_high < self._prev_est - ROLLBACK_MARGIN

    def _rollback_locked(self, est: float, hi: float) -> None:
        prev = self._prev_gen
        registry = self.serving.registry
        try:
            new = registry.rollback(self.name, prev)
        except (ValueError, KeyError):
            # predecessor drained in the window (e.g. compaction
            # retired it): nothing left to restore — fall through to
            # the retune path on the next scored batch
            self._clear_probation_locked()
            return
        self.actions.append(("rollback", {
            "to_version": prev.version, "version": new.version,
            "estimate": round(est, 4), "ci_high": round(hi, 4),
            "prev_estimate": self._prev_est}))
        self._clear_probation_locked()
        # fresh verdicts for the restored generation
        self._samples.clear()
        self._est = None
        self._since_action = 0
        self._base_policy = None
        self._steps = 0

    def _retune_locked(self, direction: str, est: float,
                       hi: float) -> None:
        cur = self.serving.registry.get(self.name)
        h = cur.handle if cur is not None else None
        if h is None or h.adaptive is None:
            return          # nothing to actuate on a non-adaptive index
        if self._base_policy is None or \
                self._base_version != cur.version:
            self._base_policy = h.adaptive
            self._base_version = cur.version
            self._steps = 0
        self._steps += 1 if direction == "tighten" else -1
        self._steps = max(self._steps, 0)
        pol = self._base_policy
        for _ in range(self._steps):
            pol = pol.tightened()
        old_refines = h.adaptive.refine_ladder()
        h.adaptive = pol
        # a margin retune only reweights already-warmed rungs; the
        # refine_ratio bump is the one shape-bearing change — re-warm
        # exactly then (the upsert growth precedent), or the next
        # shadow/live batch at the new over-fetch would retrace. The
        # warmup itself is DEFERRED to score_batch's unlock (lock
        # order: warmup takes the mutation-state lock)
        if pol.refine_ladder() != old_refines:
            self._deferred_rewarm = h
        obs.counter("serve.recall_retunes_total", index=self.name,
                    direction=direction)
        obs.event("recall_retune", index=self.name, direction=direction,
                  step=self._steps, estimate=round(est, 4),
                  ci_high=round(hi, 4),
                  easy_margin=round(pol.easy_margin, 5),
                  floor_margin=round(pol.floor_margin, 5),
                  refine_ratio=pol.refine_ratio)
        self.actions.append((direction, {
            "step": self._steps, "estimate": round(est, 4),
            "easy_margin": round(pol.easy_margin, 5),
            "floor_margin": round(pol.floor_margin, 5),
            "refine_ratio": pol.refine_ratio}))
        # verdicts must come from POST-retune samples only
        self._samples.clear()
        self._est = None
        self._since_action = 0

    # -- swap probation hooks (called by Server._publish_guarded) ----------

    def before_publish(self) -> None:
        """Pin the outgoing generation and baseline its estimate BEFORE
        the registry retires it — after publish its refcount may
        already be zero and the handle gone."""
        prev = self.serving.registry.get(self.name)
        with self._lock:
            if self._closed or prev is None:
                return
            try:
                prev.pin()
            except RuntimeError:
                return
            if self._prev_gen is not None:
                self._prev_gen.release()
            self._prev_gen = prev
            self._prev_est = (self._est[0] if self._est is not None
                              and len(self._samples) >= self.min_samples
                              else None)

    def after_publish(self, gen: Generation) -> None:
        """Reset the estimator for the successor: its quality verdicts
        must come from its own samples, and its retune base is its own
        freshly-derived policy."""
        with self._lock:
            self._succ_version = gen.version
            self._succ_samples = 0
            self._samples.clear()
            self._est = None
            self._since_action = 0
            self._base_policy = None
            self._steps = 0

    def _clear_probation_locked(self) -> None:
        if self._prev_gen is not None:
            self._prev_gen.release()
            self._prev_gen = None
        self._prev_est = None
        self._succ_samples = 0

    # -- lifecycle / introspection -----------------------------------------

    def release_samples(self, reqs: List[Request]) -> None:
        """Release the generation pins of shadow requests that will
        never be scored (batcher overflow hand-back, close-time
        drain)."""
        for r in reqs:
            if r.shadow is not None:
                r.shadow.gen.release()
        if reqs:
            obs.counter("serve.shadow_dropped_total", len(reqs),
                        index=self.name)

    def close(self, leftovers: Optional[List[Request]] = None) -> None:
        with self._lock:
            self._closed = True
            self._clear_probation_locked()
        if leftovers:
            self.release_samples(leftovers)

    def stats(self) -> dict:
        with self._lock:
            est = self._est
            return {
                "band": self.band,
                "samples": len(self._samples),
                "estimate": None if est is None else round(est[0], 4),
                "ci_low": None if est is None else round(est[1], 4),
                "ci_high": None if est is None else round(est[2], 4),
                "slots": None if est is None else est[3],
                "retune_steps": self._steps,
                "probation_open": self._prev_gen is not None,
                "actions": [list(a) for a in self.actions],
            }


__all__ = [
    "DEFAULT_RECALL_BAND_BP", "QualityMonitor", "RELAX_HYSTERESIS",
    "ROLLBACK_MARGIN", "ShadowSample", "wilson_interval",
]
