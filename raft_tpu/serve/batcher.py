"""Dynamic micro-batching: the request queue and the bucket ladder.

The TPU serving problem (docs/serving.md §2): XLA compiles one program
per input *shape*, so a query stream with arbitrary row counts would
retrace constantly — the exact failure mode the GL007 recompile audit
gates against. The fix is the FusionANNS/TPU-KNN serving shape: requests
land in a thread-safe queue, a dispatcher coalesces whatever is pending
into a batch padded up to a **fixed bucket ladder** (powers of two up to
``max_batch_rows``), and every bucket × k-rung combination is traced
once at warmup — steady-state serving then never compiles.

Pieces here:

* :func:`bucket_ladder` / :func:`choose_bucket` — the ladder and the
  measured bucket choice (``tuning.choose("serve_bucket", ...)``: a
  dispatch table can prefer padding further up the ladder when the
  larger matmul measures faster than the smaller one plus a second
  dispatch);
* :class:`Overloaded` — the bounded-queue admission rejection,
  classified through ``resilience.classify`` (``queue_full`` is
  transient — the client's correct move is backoff-and-retry;
  ``closed`` is fatal — the server can never accept again);
* :class:`MicroBatcher` — the queue + linger/drain dispatcher loop with
  ``max_wait_ms`` and ``max_batch_rows`` knobs.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.analysis import lockwatch
from raft_tpu.obs import trace as obs_trace
from raft_tpu.resilience import errors as _rerrors
from raft_tpu.utils.math import next_pow2

# batch_fill_ratio histogram edges: rows / bucket after padding — the
# shared unit-interval preset (ISSUE 19), so fill ratios land on the
# same [0,1] resolution as the recall estimates
FILL_BUCKETS: Tuple[float, ...] = obs.UNIT_BUCKETS


class Overloaded(RuntimeError):
    """Admission rejection. ``reason="queue_full"`` (bounded queue),
    ``reason="not_ready"`` (first generation still building/warming),
    ``reason="quota"`` (per-index admission quota, docs/serving.md §13),
    and ``reason="deadline"`` (the request's SLO deadline cannot be met
    — shed instead of served late) carry ``fault_kind = "transient"``
    so :func:`raft_tpu.resilience.classify` files them with the
    retryable kinds — all are backoff-and-retry (or re-budget) signals,
    not errors in the request. ``reason="closed"`` is the opposite
    contract: the server can never accept again, so it classifies
    ``fatal`` and resilience-aware clients fail fast instead of
    retrying a shutdown forever."""

    def __init__(self, msg: str, reason: str = "queue_full"):
        super().__init__(msg)
        self.reason = reason
        self.fault_kind = (_rerrors.FATAL if reason == "closed"
                           else _rerrors.TRANSIENT)


def bucket_ladder(max_rows: int) -> Tuple[int, ...]:
    """The fixed bucket ladder: powers of two ``1..next_pow2(max_rows)``.

    Every batch dispatches at exactly one of these row counts, so the
    set of traced shapes is finite and warmable."""
    top = next_pow2(max(int(max_rows), 1))
    out, b = [], 1
    while b <= top:
        out.append(b)
        b <<= 1
    return tuple(out)


def choose_bucket(ladder: Sequence[int], rows: int,
                  ceiling: Optional[int] = None) -> int:
    """Pick the dispatch bucket for ``rows`` pending rows.

    The analytic fallback is the smallest ladder rung >= rows; the
    choice is registered with ``tuning/`` under op ``serve_bucket`` so a
    measured table can prefer the next rung up (on a TPU the 2x-wider
    matmul can cost the same wall-clock, and the wider trace doubles as
    headroom for the next batch — a TPU-shaped PROJECTION as of r6:
    the axon backend has been dead since r4 and ``tables/cpu.json``
    carries no ``serve_bucket`` entries, so the fallback always wins
    until ``capture_dispatch_tables.py`` runs on a live chip).
    ``ceiling`` (the OOM-downshifted max) caps the answer except when
    a single oversized request needs the bigger rung anyway — the
    dispatcher's splitter handles that.
    """
    from raft_tpu import tuning

    rows = max(int(rows), 1)
    eligible = [b for b in ladder if b >= rows]
    if not eligible:
        return ladder[-1]
    if ceiling is not None:
        capped = [b for b in eligible if b <= ceiling]
        eligible = capped or eligible[:1]
    fallback = eligible[0]
    cands = [str(b) for b in eligible[:2]]   # this rung or one up
    w = tuning.choose("serve_bucket", {"rows_bucket": fallback},
                      cands, str(fallback))
    try:
        return int(w)
    except (TypeError, ValueError):
        return fallback


@dataclasses.dataclass
class Request:
    """One queued ``submit`` call: ``rows`` query rows answered together."""

    queries: np.ndarray           # [rows, dim] host array
    k: int
    prefilter: object             # user filter (batch-grouping key)
    future: Future
    t_enqueue: float = 0.0
    # SLO deadline as an ABSOLUTE time.monotonic() value (ISSUE 14):
    # deadline-carrying requests ride the priority lane, skip linger
    # when their slack drops under the measured service estimate, and
    # are shed/downshifted at dispatch when they would certainly miss
    deadline: Optional[float] = None
    # graft-trace context (ISSUE 13): minted at submit, carried by the
    # batch as a span LINK (one batch serves many traces), completed at
    # delivery — None when obs is off
    trace: Optional[obs_trace.TraceContext] = None
    # graft-gauge shadow payload (ISSUE 19): the quality monitor's
    # sample record (pinned generation + the SERVED ids to score
    # against the oracle re-run). Non-None marks a shadow request —
    # the future is a placeholder nobody awaits.
    shadow: object = None

    @property
    def rows(self) -> int:
        return int(self.queries.shape[0])


@dataclasses.dataclass
class Batch:
    """One coalesced dispatch unit: requests sharing a user prefilter,
    padded up to ``bucket`` rows."""

    requests: List[Request]
    rows: int
    bucket: int
    prefilter: object
    seq: int = 0
    # the head request's formation wait — the linger attribution every
    # member trace's batch stage carries
    linger_ms: float = 0.0
    # the probe rung this batch dispatches at (ISSUE 14): None = the
    # non-adaptive/exhaustive path; set by the engine's split-by-rung
    # partition (and by warmup, which forces each ladder rung once)
    rung: Optional[int] = None
    # graft-gauge (ISSUE 19): True for a shadow-oracle batch drained
    # from the best-effort lane — the engine routes it to the quality
    # monitor's exhaustive re-run instead of the serving path
    shadow: bool = False

    @property
    def k_max(self) -> int:
        return max(r.k for r in self.requests)


class MicroBatcher:
    """Thread-safe request queue + coalescing dispatcher.

    ``submit`` enqueues and returns immediately (backpressure: a full
    queue raises :class:`Overloaded`); a daemon dispatcher thread
    lingers up to ``max_wait_ms`` for the queue to fill toward the
    bucket ceiling, drains a filter-homogeneous run of requests, and
    hands the padded :class:`Batch` to ``dispatch_fn`` (the engine's
    resilience-wrapped search). The ceiling is dynamic: the engine's OOM
    ladder calls :meth:`set_ceiling` to downshift it.
    """

    def __init__(
        self,
        dispatch_fn: Callable[[Batch], None],
        *,
        max_batch_rows: int = 256,
        max_wait_ms: float = 2.0,
        max_queue_rows: int = 4096,
        shadow_queue_rows: int = 256,
        name: str = "default",
    ):
        self.ladder = bucket_ladder(max_batch_rows)
        self.max_batch_rows = self.ladder[-1]
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue_rows = int(max_queue_rows)
        self.name = name
        self._dispatch = dispatch_fn
        self._q: "collections.deque[Request]" = collections.deque()
        # the priority lane (ISSUE 14): deadline-carrying requests queue
        # here and are drained ahead of the normal lane — an SLO-bound
        # request must not wait behind a backlog of best-effort work
        self._qp: "collections.deque[Request]" = collections.deque()
        # the best-effort shadow lane (ISSUE 19): quality-monitor
        # oracle re-runs queue here and drain ONLY when both live lanes
        # are empty. Its rows never count against ``max_queue_rows``
        # (a full shadow lane must not backpressure live admission) —
        # it is bounded separately by ``shadow_queue_rows`` with
        # drop-oldest overflow, surfaced to the caller so generation
        # pins ride out with the dropped samples.
        self._qs: "collections.deque[Request]" = collections.deque()
        self._shadow_cap = int(shadow_queue_rows)
        self._shadow_rows = 0
        # per-bucket service-time samples (ms), fed back by the engine
        # after each dispatch; the deadline-aware linger reads their p95
        # (falling back to the dispatch table's serve_service medians —
        # never a hardcoded guess)
        self._svc: dict = {}
        self._pending_rows = 0
        self._ceiling = self.max_batch_rows
        self._closed = False
        self._seq = 0
        # graft-race sanitizer node "serve.batcher" (RAFT_TPU_THREADSAN)
        self._lock = lockwatch.make_lock("serve.batcher")
        self._cond = threading.Condition(self._lock)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"raft-tpu-serve-batcher-{name}",
        )
        self._thread.start()

    # -- admission ---------------------------------------------------------

    def submit(self, queries: np.ndarray, k: int,
               prefilter=None, deadline: Optional[float] = None) -> Future:
        """Enqueue ``queries`` ([rows, dim]) at ``k``; returns the Future
        the dispatcher resolves with ``(distances, ids)`` host arrays.
        ``deadline`` (absolute ``time.monotonic()``) routes the request
        through the priority lane with deadline-aware linger.

        Raises :class:`Overloaded` (classified transient) when admission
        would push the queue past ``max_queue_rows`` — bounded queues
        are the backpressure contract: reject at the door, never grow
        an unbounded latency tail."""
        with obs.span("serve.submit", index=self.name,
                      rows=int(queries.shape[0]), k=int(k)):
            req = Request(queries=queries, k=int(k), prefilter=prefilter,
                          future=Future(), deadline=deadline)
            # the serving entry mints the trace (ISSUE 13): the id is
            # minted BEFORE admission so a rejection still completes a
            # (tiny) waterfall naming why the query died at the door
            req.trace = obs_trace.start_trace(
                "serve.submit", index=self.name, rows=req.rows,
                k=int(k))
            if req.rows > self.max_batch_rows:
                obs_trace.finish(req.trace, status="rejected",
                                 reason="oversized")
                raise ValueError(
                    f"request rows={req.rows} exceeds max_batch_rows="
                    f"{self.max_batch_rows}; split the query block or "
                    "raise ServeParams.max_batch_rows"
                )
            reason = None
            with self._cond:
                if self._closed or \
                        self._pending_rows + req.rows > self.max_queue_rows:
                    reason = "closed" if self._closed else "queue_full"
                    pending = self._pending_rows
                else:
                    req.t_enqueue = time.monotonic()
                    (self._qp if req.deadline is not None
                     else self._q).append(req)
                    self._pending_rows += req.rows
                    depth = self._pending_rows
                    self._cond.notify_all()
            # bookkeeping OUTSIDE the admission lock: classify() in
            # flight mode synchronously dumps the 4096-event ring to
            # disk for the fatal `closed` rejection — doing that under
            # _cond would stall every concurrent submit and the
            # dispatcher for the dump's duration
            if reason is not None:
                obs.counter("serve.rejects_total", index=self.name,
                            reason=reason)
                obs_trace.finish(req.trace, status="rejected",
                                 reason=reason)
                exc = Overloaded(
                    f"serve[{self.name}]: {reason} "
                    f"(pending={pending} rows, "
                    f"max_queue_rows={self.max_queue_rows})",
                    reason=reason,
                )
                _rerrors.classify(exc)   # file with errors_total/flight
                raise exc
            obs.gauge("serve.queue_depth", depth, index=self.name)
            obs.counter("serve.requests_total", index=self.name)
            return req.future

    # graft-lint: allow-unspanned-entry shadow lane is off the latency path by contract; its only telemetry is the serve.shadow_* counters
    def submit_shadow(self, req: Request) -> List[Request]:
        """Enqueue a shadow-oracle sample on the best-effort lane
        (ISSUE 19). Never raises and never backpressures live traffic:
        past ``shadow_queue_rows`` the OLDEST queued samples are
        dropped to make room (fresh samples estimate current quality;
        stale ones estimate history). Returns the dropped requests —
        ``req`` itself when the batcher is closed or the sample alone
        exceeds the cap — so the caller can release their generation
        pins and count the drops."""
        dropped: List[Request] = []
        with self._cond:
            if self._closed or req.rows > self._shadow_cap:
                return [req]
            while self._qs and \
                    self._shadow_rows + req.rows > self._shadow_cap:
                old = self._qs.popleft()
                self._shadow_rows -= old.rows
                dropped.append(old)
            self._qs.append(req)
            self._shadow_rows += req.rows
            self._cond.notify_all()
        return dropped

    def drain_shadow(self) -> List[Request]:
        """Remove and return every queued shadow sample (close-time
        cleanup: the caller releases their generation pins)."""
        with self._cond:
            leftovers = list(self._qs)
            self._qs.clear()
            self._shadow_rows = 0
        return leftovers

    # -- knobs -------------------------------------------------------------

    @property
    def ceiling(self) -> int:
        return self._ceiling

    def set_ceiling(self, rows: int) -> None:
        """Set the dispatch bucket ceiling (clamped to the ladder)."""
        with self._cond:
            self._ceiling = max(min(int(rows), self.max_batch_rows),
                                self.ladder[0])
            obs.gauge("serve.bucket_ceiling", self._ceiling,
                      index=self.name)

    def lower_ceiling(self, rows: int) -> int:
        """Monotonically clamp the ceiling DOWN to ``rows`` (never up),
        atomically. The OOM ladder's downshift used to read ``ceiling``
        then call :meth:`set_ceiling` with the min — two concurrent OOM
        batches could interleave the read-modify-write and the later,
        SHALLOWER downshift would raise the ceiling back over the
        deeper one (a GL010/GL011 lost update). Returns the new
        ceiling."""
        with self._cond:
            self._ceiling = max(min(self._ceiling, int(rows)),
                                self.ladder[0])
            obs.gauge("serve.bucket_ceiling", self._ceiling,
                      index=self.name)
            return self._ceiling

    def depth_rows(self) -> int:
        with self._lock:
            return self._pending_rows

    # -- service-time feedback (the deadline slack test's estimate) --------

    def note_service_ms(self, bucket: int, ms: float,
                        rung: Optional[int] = None) -> None:
        """Record one dispatch's service time for the (bucket, rung)
        shape (called by the engine after every batch); the
        deadline-aware linger and the engine's shed/downshift decisions
        read the p95. Keyed per RUNG on purpose: an exhaustive-rung
        batch costs a multiple of a floor-rung one, and a pooled
        estimate would neither shed the former nor spare the latter.

        A shape's FIRST sample is discarded: without warmup it is the
        XLA compile, a 10-100x outlier that would poison the tail
        estimate and shed healthy requests until the ring ages it
        out."""
        with self._lock:
            ring = self._svc.get((int(bucket), rung))
            if ring is None:
                self._svc[(int(bucket), rung)] = collections.deque(
                    maxlen=64)
                return
            ring.append(float(ms))

    def service_p95_ms(self, bucket: int,
                       rung: Optional[int] = None) -> float:
        """The (bucket, rung) shape's measured p95 service time (ms).
        Falls back: exact-shape samples -> the bucket's samples across
        all rungs -> the dispatch table's captured ``serve_service``
        median (scripts/capture_dispatch_tables.py --ops
        serve_service) -> the deadline headroom budget — never a
        hardcoded guess."""
        with self._lock:
            xs, pooled = self._svc_samples_locked(bucket, rung)
        return self._p95_from(xs, pooled, bucket, rung)

    def _service_p95_locked(self, bucket: int,
                            rung: Optional[int] = None) -> float:
        """:meth:`service_p95_ms` for callers already holding ``_cond``
        (the dispatcher's linger) — ``_cond`` wraps the SAME lock, and
        re-acquiring it from the public entry deadlocks the loop."""
        xs, pooled = self._svc_samples_locked(bucket, rung)
        return self._p95_from(xs, pooled, bucket, rung)

    def _svc_samples_locked(self, bucket: int, rung: Optional[int]):
        xs = sorted(self._svc.get((int(bucket), rung), ()))
        pooled = sorted(
            v for (b, _r), ring in self._svc.items()
            if b == int(bucket) for v in ring)
        return xs, pooled

    @staticmethod
    def _p95_from(xs, pooled, bucket: int, rung: Optional[int]) -> float:
        from raft_tpu.serve import adaptive as _adaptive

        if len(xs) >= 8:
            return xs[min(len(xs) - 1, int(0.95 * len(xs)))]
        # pooled LIVE samples of this index beat the dispatch table's
        # capture (measured on a fixed toy index, keyed only by
        # (bucket, rung)) — a much bigger served index would otherwise
        # be gated by the toy's far smaller medians and admit work
        # that certainly misses its SLO
        if len(pooled) >= 8:
            return pooled[min(len(pooled) - 1, int(0.95 * len(pooled)))]
        est = _adaptive.service_estimate_ms(bucket, rung)
        if est is not None:
            return est
        if pooled:
            return pooled[-1]
        return _adaptive.deadline_headroom_ms()

    # -- lifecycle ---------------------------------------------------------

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop admissions, drain the queue through the dispatcher, join."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout_s)

    # -- the dispatcher loop ----------------------------------------------

    def _loop(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            try:
                self._dispatch(batch)
            except BaseException as e:  # noqa: BLE001 — classified by the engine; the loop must survive to fail ONLY this batch
                for r in batch.requests:
                    obs_trace.finish(r.trace, status="error",
                                     error=type(e).__name__)
                    if not r.future.done():
                        r.future.set_exception(e)

    def _next_batch(self) -> Optional[Batch]:
        with self._cond:
            while True:
                while not self._q and not self._qp and not self._qs \
                        and not self._closed:
                    self._cond.wait(timeout=0.1)
                lane = self._qp if self._qp else self._q
                if not lane:
                    if self._closed:
                        # leftover shadow samples are NOT dispatched on
                        # close — drain_shadow() hands them back so the
                        # owner can release their pins
                        return None              # closed and drained
                    if self._qs:
                        # both live lanes idle: drain one shadow batch
                        # immediately, no linger — best-effort work
                        # must never hold the lock waiting for more
                        # best-effort work while live requests queue
                        return self._drain_shadow_locked()
                    continue                     # spurious wake
                # linger: let the queue fill toward the ceiling, but
                # never hold the head request past max_wait_ms — and
                # never past a deadline request's slack: when the head's
                # remaining budget minus the measured service estimate
                # (p95 at the ceiling bucket, plus the headroom budget)
                # is already spent, it skips linger entirely
                head = lane[0]
                deadline = head.t_enqueue + self.max_wait_s
                if head.deadline is not None:
                    from raft_tpu.serve import adaptive as _adaptive

                    # reserve TWICE the headroom the dispatch gate
                    # keeps: a request released at exactly the gate's
                    # margin would be sheddable by the time it drains
                    est_s = (self._service_p95_locked(self._ceiling)
                             + 2 * _adaptive.deadline_headroom_ms()) / 1e3
                    deadline = min(deadline, head.deadline - est_s)
                while (not self._closed and lane
                       and self._head_run_rows_locked(lane)
                       < self._ceiling):
                    if lane is self._q and self._qp:
                        break        # a priority request arrived: yield
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                lane = self._qp if self._qp else self._q
                if not lane:                     # close raced the linger
                    continue
                return self._drain_locked(lane)

    def _head_run_rows_locked(self, lane=None) -> int:
        """Rows in the longest filter-homogeneous run at the queue head
        (only those can coalesce into one batch); caller holds
        ``_cond``."""
        if lane is None:
            lane = self._qp if self._qp else self._q
        if not lane:
            return 0
        key = id(lane[0].prefilter) if lane[0].prefilter is not None \
            else None
        rows = 0
        for r in lane:
            rk = id(r.prefilter) if r.prefilter is not None else None
            if rk != key:
                break
            rows += r.rows
            if rows >= self._ceiling:
                # the linger loop only compares against the ceiling, so
                # scanning past it is wasted work done under the shared
                # admission lock on every dispatcher wake — bound each
                # scan at the ceiling instead of the full backlog
                break
        return rows

    def _drain_locked(self, lane=None) -> Batch:
        if lane is None:
            lane = self._qp if self._qp else self._q
        head = lane[0]
        key = id(head.prefilter) if head.prefilter is not None else None
        cap = max(self._ceiling, head.rows)   # oversized head still goes
        taken: List[Request] = []
        rows = 0
        while lane:
            r = lane[0]
            rk = id(r.prefilter) if r.prefilter is not None else None
            if rk != key or (taken and rows + r.rows > cap):
                break
            taken.append(lane.popleft())
            rows += r.rows
        self._pending_rows -= rows
        obs.gauge("serve.queue_depth", self._pending_rows, index=self.name)
        bucket = choose_bucket(self.ladder, rows, ceiling=cap)
        self._seq += 1
        obs.counter("serve.batches_total", index=self.name,
                    bucket=str(bucket))
        obs.observe("serve.batch_fill_ratio", rows / bucket,
                    buckets=FILL_BUCKETS, index=self.name)
        now = time.monotonic()
        linger_ms = (now - head.t_enqueue) * 1e3
        obs.observe("serve.queue_wait_ms", linger_ms, index=self.name)
        # per-request queue_wait stages: each member trace records ITS
        # enqueue->drain wait, with the batch seq as the span link tying
        # the traces this batch serves together
        for r in taken:
            obs_trace.stage(r.trace, "queue_wait",
                            ms=(now - r.t_enqueue) * 1e3,
                            batch_seq=self._seq, bucket=bucket)
        return Batch(requests=taken, rows=rows, bucket=bucket,
                     prefilter=head.prefilter, seq=self._seq,
                     linger_ms=linger_ms)

    def _drain_shadow_locked(self) -> Batch:
        """Drain one filter-homogeneous run off the shadow lane into a
        ``shadow=True`` batch (caller holds ``_cond``). Deliberately
        skips ALL live-lane bookkeeping — no ``_pending_rows``, no
        fill-ratio/queue-wait series, no trace stages (shadow requests
        carry no trace): the shadow lane must not perturb the signals
        the live dispatcher and its SLOs are steered by."""
        head = self._qs[0]
        key = id(head.prefilter) if head.prefilter is not None else None
        cap = max(self._ceiling, head.rows)
        taken: List[Request] = []
        rows = 0
        while self._qs:
            r = self._qs[0]
            rk = id(r.prefilter) if r.prefilter is not None else None
            if rk != key or (taken and rows + r.rows > cap):
                break
            taken.append(self._qs.popleft())
            rows += r.rows
        self._shadow_rows -= rows
        bucket = choose_bucket(self.ladder, rows, ceiling=cap)
        self._seq += 1
        obs.counter("serve.shadow_batches_total", index=self.name)
        return Batch(requests=taken, rows=rows, bucket=bucket,
                     prefilter=head.prefilter, seq=self._seq,
                     shadow=True)


def pad_rows(queries: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad ``queries`` up to ``bucket`` rows ON THE HOST (numpy):
    the pad must happen before the device transfer so the traced program
    only ever sees ladder shapes — a ``jnp.pad`` here would itself trace
    once per distinct input row count, defeating the ladder."""
    rows = queries.shape[0]
    if rows == bucket:
        return queries
    pad = np.zeros((bucket - rows,) + queries.shape[1:], queries.dtype)
    return np.concatenate([queries, pad], axis=0)
