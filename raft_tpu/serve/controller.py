"""graft-helm: the self-healing fabric control plane (ISSUE 18;
docs/serving.md §10).

The fabric (:mod:`raft_tpu.serve.fabric`) gives every failure a local
answer — a breaker opens, a hedge covers, a probe readmits — but leaves
the CLUSTER decisions to a human: when is a worker dead enough that its
shards should move, when does load justify another worker, when can one
drain out. :class:`HelmController` closes those three loops:

1. **repair** — a worker whose circuit has been open past the
   ``fabric_rebalance_budget_ms`` tuning budget is evicted: the current
   generation is republished over the survivors through the fabric's
   two-phase barrier, restoring the replication factor; a replacement
   is admitted when the survivor set is too small to hold it. Before
   spending the budget the controller respawns a dead process (up to
   ``restart_budget`` times, fault plan inherited so chaos drills
   model machines, not processes).
2. **autoscale** — the saturated-STAGE signal decides growth: mean
   in-flight RPCs per worker (the queue-depth analog the p2c balancer
   already tracks) crossing ``scale_up_inflight`` for
   ``sustain_ticks`` consecutive ticks admits a worker — but only when
   the waterfall p99s say the bottleneck is worker-side (``rpc`` /
   ``worker_scan`` stages); a router-bound fleet (``merge`` dominating)
   holds with a reason instead of wasting a machine. The mirror-image
   low-water signal drains the highest-rank worker out.
3. **hysteresis** — every membership action arms a cooldown; sustain
   counters reset on action or signal loss; the breaker's open-episode
   clock (:meth:`WorkerHealth` ``open_since``) survives failed
   half-open probes but clears on readmission — so a FLAPPING worker
   (recovers, dies, recovers) never accumulates enough open time to
   get evicted, while a solidly dead one always does. The thrash
   negative test (tests/test_controller.py) pins this under
   ``flap@proc``.

Single-actor contract: membership mutation (admit / retire / respawn /
rebalance) goes through ONE controller per fabric — the same rule
:class:`~raft_tpu.comms.procgroup.ProcGroup` documents for its rank
table. The controller state lock ("helm.state") sits ABOVE the fabric's
locks in the hierarchy; fabric code never calls back into the
controller.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict, List, Optional

from raft_tpu import obs, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.obs import trace as obs_trace
from raft_tpu.resilience import errors as _rerrors
from raft_tpu.serve.fabric import CLOSED, OPEN, Fabric


@dataclasses.dataclass
class HelmParams:
    """Control-plane knobs (docs/serving.md §10)."""

    # tick cadence; None -> tuning budget helm_interval_ms (200)
    interval_s: Optional[float] = None
    # open-episode ceiling before eviction; None -> tuning budget
    # fabric_rebalance_budget_ms (1500)
    rebalance_budget_ms: Optional[float] = None
    restart_budget: int = 2       # respawns per rank before eviction
    respawn: bool = True          # try respawn before rebalancing away
    inherit_faults: bool = True   # respawns keep the rank's fault plan
    min_workers: int = 2
    max_workers: int = 8
    # autoscale watermarks on mean in-flight RPCs per active worker
    scale_up_inflight: float = 3.0
    scale_down_inflight: float = 0.25
    sustain_ticks: int = 3        # consecutive ticks before acting
    # post-action quiet period; None -> tuning budget helm_cooldown_ms
    # (2000)
    cooldown_s: Optional[float] = None
    # waterfalls sampled per tick for saturated-stage attribution
    trace_window: int = 64
    retire_timeout_s: float = 30.0
    # graft-gauge quality alarms (ISSUE 19): when on, each tick scrapes
    # the fleet's federated recall estimates
    # (:meth:`Fabric.recall_estimates`) and surfaces any pooled
    # ``rung="all"`` CI upper bound under ``recall_band`` into the
    # action journal as a ``quality_alarm`` — the helm does NOT act on
    # it (retune/rollback live with the per-index QualityMonitor that
    # owns the estimate); it makes the fleet-level breach visible where
    # operators already watch membership actions. None band -> tuning
    # budget serve_recall_band_bp (9000 = 0.90).
    quality_alarms: bool = False
    recall_band: Optional[float] = None


class HelmController:
    """The fabric's self-healing control loop::

        fab = serve.Fabric(dataset, params=serve.FabricParams())
        helm = serve.HelmController(fab, params=serve.HelmParams())
        helm.start()          # background loop
        ...
        helm.stop()

    or tick it deterministically (the tests do)::

        report = helm.step()  # {"actions": [...], "held": ..., ...}
    """

    def __init__(self, fabric: Fabric, *,
                 params: Optional[HelmParams] = None):
        self.fabric = fabric
        self.params = params or HelmParams()
        p = self.params
        if p.min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        if p.max_workers < p.min_workers:
            raise ValueError("max_workers must be >= min_workers")
        interval = p.interval_s
        if interval is None:
            interval = tuning.budget("helm_interval_ms", 200) / 1e3
        self._interval_s = float(interval)
        budget = p.rebalance_budget_ms
        if budget is None:
            budget = tuning.budget("fabric_rebalance_budget_ms", 1500)
        self._rebalance_budget_ms = float(budget)
        cooldown = p.cooldown_s
        if cooldown is None:
            cooldown = tuning.budget("helm_cooldown_ms", 2000) / 1e3
        self._cooldown_s = float(cooldown)
        band = p.recall_band
        if band is None:
            band = tuning.budget("serve_recall_band_bp", 9000) / 1e4
        self._recall_band = float(band)
        # graft-race sanitizer node "helm.state" — sits above the
        # fabric's locks (step() holds it across fabric actions; the
        # fabric never calls back up)
        self._lock = lockwatch.make_lock("helm.state")
        self._restarts: Dict[int, int] = {}
        self._evicted: set = set()
        self._hot_ticks = 0
        self._cold_ticks = 0
        self._cooldown_until = 0.0
        self._ticks = 0
        # bounded membership-action journal — the loadgen's chaos
        # timeline reads it through stats()["actions"]
        self._actions_log: collections.deque = collections.deque(
            maxlen=512)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- the control loop ---------------------------------------------------

    def step(self) -> dict:
        """One deterministic control tick: repair, then autoscale.
        Returns a report of what happened —
        ``{"actions": [(kind, rank), ...], "held": reason|None,
        "mean_inflight": float, "workers": int}`` — consumed by the
        tests and the loadgen's chaos timeline."""
        with obs.span("helm.tick", index=self.fabric.name):
            with self._lock:
                self._ticks += 1
                obs.counter("helm.ticks_total")
                actions: List[tuple] = []
                self._repair_locked(actions)
                held = self._autoscale_locked(actions)
                active = self.fabric.active_ranks()
                mean_inflight = self._mean_inflight(active)
                now = time.monotonic()
                for kind, rank in actions:
                    self._actions_log.append(
                        {"t": now, "action": kind, "worker": rank})
            if self.params.quality_alarms:
                # OUTSIDE the state lock: the federated scrape RPCs a
                # timeout's worth of workers — holding helm.state that
                # long would stall manual scale/rebalance entry points
                alarms = self._quality_alarms()
                if alarms:
                    now = time.monotonic()
                    with self._lock:
                        for kind, key in alarms:
                            self._actions_log.append(
                                {"t": now, "action": kind,
                                 "worker": key})
                    actions = actions + alarms
            obs.gauge("helm.workers", len(active))
            obs.gauge("helm.mean_inflight", round(mean_inflight, 4))
            for kind, rank in actions:
                obs.counter("helm.actions_total", action=kind)
                obs.event("helm_action", action=kind, worker=rank)
            if held:
                obs.counter("helm.held_total", reason=held)
            return {"actions": actions, "held": held,
                    "mean_inflight": mean_inflight,
                    "workers": len(active)}

    def _quality_alarms(self) -> List[tuple]:
        """Fleet-level recall breaches (graft-gauge, ISSUE 19): every
        pooled (``rung="all"``) federated estimate whose CI upper bound
        sits under the band. Surfaced, not acted on — the per-index
        :class:`~raft_tpu.serve.quality.QualityMonitor` that owns the
        estimate also owns the retune/rollback actuators."""
        try:
            ests = self.fabric.recall_estimates()
        except BaseException as e:  # noqa: BLE001 — classified: a mute fleet scrape degrades the alarm, never the tick
            _rerrors.classify(e)
            return []
        out: List[tuple] = []
        for key, vals in sorted(ests.items()):
            if not key.endswith("|all"):
                continue
            hi = vals.get("ci_high")
            if hi is not None and float(hi) < self._recall_band:
                out.append(("quality_alarm", key))
                obs.event("helm_quality_alarm", key=key,
                          ci_high=round(float(hi), 4),
                          band=self._recall_band)
        return out

    def _repair_locked(self, actions: List[tuple]) -> None:
        """Respawn dead workers while the restart budget lasts; evict
        any rank whose open episode outlived the rebalance budget."""
        fab = self.fabric
        p = self.params
        episodes = fab.open_episodes()
        for rank, episode_s in sorted(episodes.items()):
            hl = fab.health[rank]
            if hl.state != OPEN and episode_s <= 0.0:
                continue
            dead = not fab.group.alive(rank)
            spent = self._restarts.get(rank, 0)
            if (dead and p.respawn and spent < p.restart_budget):
                try:
                    fab.restart_worker(
                        rank, inherit_faults=p.inherit_faults)
                except BaseException as e:  # noqa: BLE001 — classified; a failed respawn burns budget toward eviction
                    _rerrors.classify(e)
                self._restarts[rank] = spent + 1
                actions.append(("respawn", rank))
                continue
            if episode_s * 1e3 > self._rebalance_budget_ms:
                self._evict_locked(rank, actions)

    def _evict_locked(self, rank: int, actions: List[tuple]) -> None:
        fab = self.fabric
        p = self.params
        if rank in self._evicted:
            return
        try:
            fab.retire_worker(rank, timeout_s=p.retire_timeout_s,
                              reason="evict")
        except BaseException as e:  # noqa: BLE001 — classified; an unretirable rank stays excluded next tick
            _rerrors.classify(e)
            return
        self._evicted.add(rank)
        actions.append(("evict", rank))
        self._arm_cooldown_locked()
        # the survivor set may be too small to hold the replication
        # factor — admit a replacement (the "respawned replacement"
        # arm of the rebalancing loop)
        floor = max(p.min_workers, fab.params.replication)
        if len(fab.active_ranks()) < floor:
            try:
                new_rank = fab.add_worker()
            except BaseException as e:  # noqa: BLE001 — classified; next tick retries admission
                _rerrors.classify(e)
                return
            actions.append(("admit", new_rank))

    def _autoscale_locked(self, actions: List[tuple]) -> Optional[str]:
        """Grow/shrink on the mean-inflight watermark, gated by
        saturated-stage attribution, sustain, and cooldown. Returns the
        hold reason when a crossed watermark was NOT acted on."""
        fab = self.fabric
        p = self.params
        active = fab.active_ranks()
        mean_inflight = self._mean_inflight(active)
        hot = mean_inflight >= p.scale_up_inflight
        cold = mean_inflight <= p.scale_down_inflight
        self._hot_ticks = self._hot_ticks + 1 if hot else 0
        self._cold_ticks = self._cold_ticks + 1 if cold else 0
        if actions:
            # repair already mutated membership this tick — let the
            # new topology settle before judging load on it
            self._hot_ticks = self._cold_ticks = 0
            return None
        if any(fab.health[r].state != CLOSED for r in active):
            # degraded fleet: a down worker reads as low load (its
            # RPCs are not in flight) — scaling on that signal would
            # drain capacity exactly when the repair loop needs it.
            # Health first, capacity second.
            self._hot_ticks = self._cold_ticks = 0
            return "degraded" if (hot or cold) else None
        now = time.monotonic()
        if hot:
            if self._hot_ticks < p.sustain_ticks:
                return None
            if now < self._cooldown_until:
                return "cooldown"
            if len(active) >= p.max_workers:
                return "max_workers"
            if not self._worker_bound():
                return "router_bound"
            try:
                rank = fab.add_worker()
            except BaseException as e:  # noqa: BLE001 — classified; admission retried next sustained window
                _rerrors.classify(e)
                return "admit_failed"
            actions.append(("scale_up", rank))
            self._hot_ticks = 0
            self._arm_cooldown_locked()
            return None
        if cold:
            if self._cold_ticks < p.sustain_ticks:
                return None
            if now < self._cooldown_until:
                return "cooldown"
            floor = max(p.min_workers, fab.params.replication)
            if len(active) <= floor:
                return "min_workers"
            # drain the newest admission first: highest live rank —
            # deterministic, and shard movement is smallest at the
            # round-robin tail
            candidates = [r for r in active if fab.group.alive(r)]
            if not candidates:
                return "no_candidate"
            rank = max(candidates)
            try:
                fab.retire_worker(rank, timeout_s=p.retire_timeout_s,
                                  reason="scale_down")
            except BaseException as e:  # noqa: BLE001 — classified; drain retried next sustained window
                _rerrors.classify(e)
                return "retire_failed"
            actions.append(("scale_down", rank))
            self._cold_ticks = 0
            self._arm_cooldown_locked()
            return None
        return None

    def _arm_cooldown_locked(self) -> None:
        self._cooldown_until = time.monotonic() + self._cooldown_s

    def _mean_inflight(self, active: List[int]) -> float:
        snap = self.fabric.load_snapshot()
        inflight = snap["inflight"]
        if not active:
            return 0.0
        return sum(inflight.get(r, 0) for r in active) / len(active)

    def _worker_bound(self) -> bool:
        """Saturated-stage attribution over the recent waterfalls:
        scaling workers only helps when worker-side stages (``rpc``,
        which brackets queueing + ``worker_scan``) dominate the
        router-side ``merge``. With too few samples, default to
        worker-bound — the sustain/cooldown gates already damp a wrong
        early guess."""
        wfs = obs_trace.trace_report(limit=self.params.trace_window)
        if not wfs:
            return True
        per = obs_trace.stage_stats(wfs)
        rpc = per.get("rpc", {})
        merge = per.get("merge", {})
        rpc_p99 = rpc.get("p99_ms")
        merge_p99 = merge.get("p99_ms")
        if rpc_p99 is None or merge_p99 is None:
            return True
        return merge_p99 <= rpc_p99

    # -- explicit operator actions (spanned serve entry points) -------------

    def scale_up(self) -> int:
        """Admit one worker now (operator override; same placement path
        as the autoscaler). Returns the new rank."""
        with obs.span("helm.scale_up", index=self.fabric.name):
            with self._lock:
                rank = self.fabric.add_worker()
                self._arm_cooldown_locked()
                return rank

    def scale_down(self, rank: Optional[int] = None) -> int:
        """Drain one worker out now (highest live rank when
        unspecified). Returns the retired rank."""
        with obs.span("helm.scale_down", index=self.fabric.name):
            with self._lock:
                fab = self.fabric
                if rank is None:
                    candidates = [r for r in fab.active_ranks()
                                  if fab.group.alive(r)]
                    if not candidates:
                        raise RuntimeError("no live worker to drain")
                    rank = max(candidates)
                fab.retire_worker(
                    rank, timeout_s=self.params.retire_timeout_s,
                    reason="scale_down")
                self._arm_cooldown_locked()
                return int(rank)

    def rebalance(self, exclude=(), *, reason: str = "manual") -> int:
        """Republish the current generation over the membership minus
        ``exclude`` (operator override of the repair loop)."""
        with obs.span("helm.rebalance", index=self.fabric.name):
            with self._lock:
                return self.fabric.rebalance(exclude, reason=reason)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Run the control loop on a background daemon thread."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"raft-tpu-helm-{self.fabric.name}")
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.step()
            except BaseException as e:  # noqa: BLE001 — classified: the controller must outlive any single bad tick
                _rerrors.classify(e)

    def stats(self) -> dict:
        with self._lock:
            return {
                "ticks": self._ticks,
                "restarts": dict(self._restarts),
                "evicted": sorted(self._evicted),
                "actions": list(self._actions_log),
                "hot_ticks": self._hot_ticks,
                "cold_ticks": self._cold_ticks,
                "cooldown_remaining_s": max(
                    self._cooldown_until - time.monotonic(), 0.0),
                "rebalance_budget_ms": self._rebalance_budget_ms,
            }

    def __enter__(self) -> "HelmController":
        self.start()
        return self

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
