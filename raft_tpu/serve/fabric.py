"""Fault-tolerant multi-host serving fabric (ISSUE 6; docs/serving.md §10).

Everything below this module survives failures *inside* one process
(the resilience ladder, the serve engine's retry/downshift). A
production jax_graft deployment runs the index sharded across many
hosts, where the dominant failure mode is a *peer* that hangs, dies, or
answers late — RAFT's raft-dask tier (PAPER.md), and the regime Fantasy
(PAPERS.md) shows wants asynchronous per-shard routing with explicit
failure handling rather than lockstep collectives that stall every
query on the slowest rank. This module is that tier:

    search ──► router ──pin──► Registry generation (cluster shard map)
                 │ per-shard RPC (deadline, classified retry,
                 │               hedged duplicate past the latency
                 │               percentile)
                 ▼
        worker processes (comms/procgroup.py) — shard owners
                 │ per-shard top-k
                 ▼
        merge_topk + per-row coverage ──► (d, i, coverage)

Robustness core:

* **health tracking** — a per-worker circuit breaker
  (:class:`WorkerHealth`): consecutive classified failures open the
  circuit, a confirmed-dead process opens it immediately, and recovery
  goes through half-open probing (the in-process
  ``resilience.backend_alive`` liveness check promoted to a peer
  ``ping`` RPC);
* **hedged retries** — per-shard RPC deadlines with classified
  retry/backoff (``resilience.run``'s contract generalized across the
  process boundary), plus a hedged duplicate request to a replica once
  the primary is slower than the measured latency percentile
  (first answer wins, the loser is discarded);
* **coverage-degraded answers** — a lost shard degrades the answer
  instead of failing it: per-ROW coverage rides back with every result
  (the ``partial_ok`` machinery of ``comms/sharded.py`` generalized
  across processes), and :class:`ShardDropoutError` fires only when
  coverage falls below the configured floor (or ``partial_ok=False``);
* **coordinated hot-swap** — a two-phase generation barrier over the
  PR 5 registry: prepare-and-warm on every live worker, then one
  atomic cluster-wide publish; any prepare failure aborts and rolls
  every worker back, so answers either come fully from the old
  generation or fully from the new one (each RPC pins its generation
  id; a mixed-generation merge is structurally impossible and counted
  if a worker ever violates it).

Every failure path is deterministically CPU-testable: workers are
``multiprocessing`` children (:class:`~raft_tpu.comms.procgroup.ProcGroup`)
or in-process threads (:class:`~raft_tpu.comms.procgroup.LocalGroup`),
and the fault grammar gains process scopes (``dead@proc:R``,
``slow@proc:R*K``, ``drop@rpc:METHOD`` — docs/resilience.md §6).
"""

from __future__ import annotations

import collections
import dataclasses
import random
import threading
import time
from concurrent.futures import (
    FIRST_COMPLETED,
    Future,
    ThreadPoolExecutor,
)
from concurrent.futures import TimeoutError as FutureTimeoutError
from concurrent.futures import wait as _futures_wait
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from raft_tpu import obs, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.comms.procgroup import LocalGroup, ProcGroup, is_no_gen
from raft_tpu.obs import federation as obs_federation
from raft_tpu.obs import trace as obs_trace
from raft_tpu.resilience import ShardDropoutError
from raft_tpu.resilience import errors as _rerrors
from raft_tpu.serve.registry import Registry

# circuit-breaker states
CLOSED = "closed"          # routable
OPEN = "open"              # excluded from routing, awaiting half-open
HALF_OPEN = "half_open"    # one probe decides readmission

_HEALTH_VALUE = {CLOSED: 1.0, HALF_OPEN: 0.5, OPEN: 0.0}

# per-shard RPC latency histogram edges (ms) — finer than the serve
# batch buckets: hedging decisions live in the single-digit range
_RPC_LAT_BUCKETS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                    5000)


class FabricSwapError(RuntimeError):
    """A cluster-wide swap failed during PREPARE and was rolled back on
    every worker — the old generation keeps serving, so the correct
    client move is backoff-and-retry (``fault_kind = transient``)."""

    fault_kind = _rerrors.TRANSIENT


@dataclasses.dataclass
class FabricParams:
    """Fabric knobs (docs/serving.md §10)."""

    n_workers: int = 3            # initial worker processes
    # shard count is FIXED for the fabric's lifetime (None -> initial
    # n_workers); the worker set is not — the control plane admits and
    # retires workers (ISSUE 18), and each published generation places
    # the same shards over the membership of its moment
    n_shards: Optional[int] = None
    replication: int = 2          # owners per shard (hedge/failover pool)
    # read routing policy: "p2c" spreads each shard read over ALL live
    # owners by power-of-two-choices on an inflight x EWMA-latency
    # score (replicas contribute THROUGHPUT); "primary" is the
    # pre-ISSUE-18 primary-first order (the A/B baseline)
    balance: str = "p2c"
    worker_algo: str = "brute_force"   # per-shard index ("ivf_flat" too)
    rpc_deadline_s: float = 5.0   # per-shard RPC budget (all attempts)
    rpc_retries: int = 2          # classified retries per shard
    retry_backoff_s: float = 0.02
    hedge_after_ms: Optional[float] = None  # None -> measured percentile
    hedge_percentile: float = 95.0
    partial_ok: bool = True       # degrade instead of raising
    coverage_floor: float = 0.0   # min per-row coverage before raising
    fail_threshold: int = 3       # consecutive failures -> circuit opens
    halfopen_after_s: float = 0.25
    # consecutive successes before a readmitted worker's failure budget
    # refills — until then ONE failure re-opens it (flap hysteresis)
    probation_successes: int = 3
    probe_interval_s: Optional[float] = None  # None -> tuning budget
    probe_timeout_s: float = 5.0
    swap_deadline_s: float = 120.0
    slow_ms: float = 150.0        # injected slow@proc stall length
    worker_platform: Optional[str] = "cpu"
    # per-shard routing tasks are WAIT-bound (deadline waits, backoff
    # sleeps), not CPU-bound: size this >= expected concurrent searches
    # x n_workers, or shard tasks queue behind blocked ones and one
    # slow worker's deadline waits head-of-line block healthy shards
    # of unrelated requests
    router_threads: int = 64
    auto_probe: bool = True       # background prober thread


class WorkerHealth:
    """One worker's circuit breaker: CLOSED (routable) → OPEN after
    ``fail_threshold`` consecutive classified failures (immediately on
    a confirmed-dead process) → HALF_OPEN once ``halfopen_after_s`` has
    passed → CLOSED again on a successful probe, or back to OPEN on a
    failed one. Transitions are gauged/counted through graft-scope
    (``fabric.worker_health{worker}``,
    ``fabric.circuit_transitions{worker,to}``).

    Readmission is PROBATIONAL (ISSUE 18 flap hysteresis): a half-open
    probe success closes the circuit but does NOT refund the failure
    budget — a worker that flaps straight back down re-opens on its
    first post-probe failure, not after ``fail_threshold`` fresh ones.
    The budget refills only after ``probation_successes`` consecutive
    successes."""

    def __init__(self, rank: int, fail_threshold: int,
                 halfopen_after_s: float,
                 probation_successes: int = 3):
        self.rank = int(rank)
        self.fail_threshold = int(fail_threshold)
        self.halfopen_after_s = float(halfopen_after_s)
        self.probation_successes = int(probation_successes)
        # graft-race sanitizer node "fabric.health"
        self.lock = lockwatch.make_lock("fabric.health")
        self.state = CLOSED
        self.failures = 0
        self.successes = 0      # consecutive — the probation counter
        self.opened_at = 0.0    # last trip (half-open scheduling)
        # first trip of the CURRENT open episode: survives failed
        # half-open probes, ends on readmission — what the control
        # plane's rebalance budget is measured against
        self.open_since = 0.0
        obs.gauge("fabric.worker_health", 1.0, worker=self.rank)

    def _transition_locked(self, to: str) -> None:
        # *_locked: caller holds self.lock (the GL010 contract suffix)
        self.state = to
        obs.counter("fabric.circuit_transitions", worker=self.rank,
                    to=to)
        obs.gauge("fabric.worker_health", _HEALTH_VALUE[to],
                  worker=self.rank)
        obs.event("fabric_circuit", worker=self.rank, to=to)

    def record_success(self) -> None:
        with self.lock:
            self.successes += 1
            if self.state != CLOSED:
                # probational readmission: the failure budget stays
                # spent, so the next failure re-opens immediately
                self.failures = max(self.failures, self.fail_threshold)
                self.successes = 1
                self.open_since = 0.0
                self._transition_locked(CLOSED)
            if self.successes >= self.probation_successes:
                self.failures = 0

    def record_failure(self, kind: str) -> None:
        with self.lock:
            self.successes = 0
            self.failures += 1
            trip = (self.state == HALF_OPEN
                    or kind == _rerrors.DEAD_BACKEND
                    or self.failures >= self.fail_threshold)
            if trip:
                if self.state == CLOSED:
                    self.open_since = time.monotonic()
                if self.state != OPEN:
                    self._transition_locked(OPEN)
                self.opened_at = time.monotonic()

    def routable(self) -> bool:
        with self.lock:
            return self.state == CLOSED

    def due_for_probe(self, now: float) -> bool:
        with self.lock:
            return (self.state == OPEN
                    and now - self.opened_at >= self.halfopen_after_s)

    def to_half_open(self) -> None:
        with self.lock:
            if self.state == OPEN:
                self._transition_locked(HALF_OPEN)

    def force_open(self) -> None:
        """Used by restart: a respawned worker is not routable until a
        half-open probe admits it (``opened_at`` reset to the epoch so
        the probe is due immediately). The open EPISODE restarts — a
        fresh incarnation gets a fresh rebalance budget; the
        controller's restart budget bounds the total attempts."""
        with self.lock:
            if self.state != OPEN:
                self._transition_locked(OPEN)
            self.opened_at = 0.0
            self.open_since = time.monotonic()


class _ClusterGen:
    """One published cluster generation: the shard→owners map plus the
    shapes the router validates against. The registry manages identity
    and lifetime (pins, drain) exactly as it does for the single-process
    engine's handles."""

    __slots__ = ("gen_id", "owners", "n_shards", "rows", "dim")

    def __init__(self, gen_id: int, owners: Dict[int, Tuple[int, ...]],
                 rows: int, dim: int):
        self.gen_id = int(gen_id)
        self.owners = owners
        self.n_shards = len(owners)
        self.rows = int(rows)
        self.dim = int(dim)


def shard_bounds(n_rows: int, n_shards: int) -> List[int]:
    """Contiguous near-equal row split: ``bounds[s]:bounds[s+1]`` is
    shard ``s``. Shared with the tests' surviving-shard oracle."""
    return [round(n_rows * s / n_shards) for s in range(n_shards + 1)]


def merge_shard_results(
    n_shards: int,
    results: Dict[int, Optional[tuple]],
    m: int,
    k: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Merge per-shard ``(worker, d, i)`` results (``None`` = shard
    uncovered) into a global top-k via the existing ``merge_topk``,
    returning host ``(d [m,k], i [m,k], validity [S,m])``.

    Row-granular validity, matching ``comms/sharded._mask_invalid``:
    an uncovered shard invalidates all its rows; a NaN row inside a
    covered shard's answer invalidates only that row. Invalid entries
    ride at the worst-possible sentinel with ids -1, so the merge ranks
    every surviving candidate ahead of them."""
    import jax.numpy as jnp

    from raft_tpu.neighbors.common import merge_topk

    cd = np.full((m, n_shards * k), np.inf, np.float32)
    ci = np.full((m, n_shards * k), -1, np.int32)
    validity = np.zeros((n_shards, m), bool)
    for s in range(n_shards):
        res = results.get(s)
        if res is None:
            continue
        _worker, d, i = res
        d = np.asarray(d, np.float32)
        i = np.asarray(i, np.int32)
        row_ok = ~np.isnan(d).any(axis=1)
        cd[:, s * k:(s + 1) * k] = np.where(row_ok[:, None], d, np.inf)
        ci[:, s * k:(s + 1) * k] = np.where(row_ok[:, None], i, -1)
        validity[s] = row_ok
    md, mi = merge_topk(jnp.asarray(cd), jnp.asarray(ci), int(k), True)
    return np.asarray(md), np.asarray(mi), validity


_GROUPS = {"proc": ProcGroup, "local": LocalGroup}


class Fabric:
    """The multi-host serving tier: N workers each own index shards, a
    router fans each micro-batch to shard owners and merges per-shard
    top-k, returning ``(d, i, coverage)``::

        fab = serve.Fabric(dataset, params=serve.FabricParams())
        d, i, coverage = fab.search(queries, k=10)
        fab.swap(new_dataset)          # two-phase cluster hot-swap
        fab.restart_worker(2)          # after a machine loss
        fab.close()

    Metric: squared euclidean (the library's min-close default) — the
    merge sentinel and validity masks assume select-min.
    """

    def __init__(self, dataset, *, params: Optional[FabricParams] = None,
                 name: str = "default", group="proc",
                 fault_spec: Optional[str] = None):
        self.params = params or FabricParams()
        p = self.params
        dataset = np.ascontiguousarray(np.asarray(dataset),
                                       dtype=np.float32)
        if dataset.ndim != 2:
            raise ValueError("dataset must be [rows, dim]")
        if p.balance not in ("p2c", "primary"):
            raise ValueError(
                f"balance must be 'p2c' or 'primary', got {p.balance!r}")
        self.n_shards = int(p.n_shards or p.n_workers)
        if self.n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {self.n_shards}")
        if dataset.shape[0] < self.n_shards:
            raise ValueError(
                f"dataset rows {dataset.shape[0]} < n_shards "
                f"{self.n_shards}: every shard needs a non-empty slice")
        self.name = name
        self.dim = int(dataset.shape[1])
        self.registry = Registry()
        self.health = [
            WorkerHealth(r, p.fail_threshold, p.halfopen_after_s,
                         p.probation_successes)
            for r in range(p.n_workers)
        ]
        self._counters: collections.Counter = collections.Counter()
        # graft-race sanitizer nodes "fabric.stats" / "fabric.swap" /
        # "fabric.load"
        self._stats_lock = lockwatch.make_lock("fabric.stats")
        self._lat_ms: collections.deque = collections.deque(maxlen=256)
        self._cov_ewma: Optional[float] = None
        self._gen_counter = 0
        self._swap_lock = lockwatch.make_lock("fabric.swap")
        # replica read load-balancing state (ISSUE 18): per-worker
        # outstanding RPC count + EWMA latency, read by the p2c router
        # and the helm controller. LEAF lock — metrics are emitted
        # outside it.
        self._load_lock = lockwatch.make_lock("fabric.load")
        self._inflight: Dict[int, int] = {}
        self._ewma_ms: Dict[int, float] = {}
        # seeded: p2c sampling is deterministic per fabric instance
        self._rng = random.Random(0x9E3779B9)
        # ranks retired by the control plane: never routed, probed, or
        # placed in a generation again (ranks are append-only, so the
        # set only grows)
        self._retired: set = set()
        self._closed = False
        self._dataset = dataset
        if isinstance(group, str):
            self.group = _GROUPS[group](
                p.n_workers, algo=p.worker_algo, slow_s=p.slow_ms / 1e3,
                fault_spec=fault_spec, platform=p.worker_platform)
        else:
            self.group = group
        self._pool = ThreadPoolExecutor(
            max_workers=p.router_threads,
            thread_name_prefix=f"raft-tpu-fabric-{name}")
        # initial load rides the SAME two-phase protocol as every later
        # swap — one code path, one set of failure modes
        try:
            self._publish_generation(dataset, initial=True)
        except BaseException as e:  # noqa: BLE001 — classified, then the half-built fabric is torn down before re-raising
            _rerrors.classify(e)
            self._closed = True
            self._pool.shutdown(wait=False)
            self.group.close()
            raise
        interval = p.probe_interval_s
        if interval is None:
            # probe cadence as a measured budget: a recorded ceiling
            # (e.g. from a deployment that learned its failure-detection
            # latency requirement) clamps the default
            interval = tuning.budget("fabric_probe_interval_ms", 250) / 1e3
        self._probe_interval_s = float(interval)
        self._prober: Optional[threading.Thread] = None
        if p.auto_probe:
            self._prober = threading.Thread(
                target=self._probe_loop, daemon=True,
                name=f"raft-tpu-fabric-prober-{name}")
            self._prober.start()

    # -- the data plane -----------------------------------------------------

    def search(self, queries, k: int, *, partial_ok: Optional[bool] = None,
               detail: bool = False):
        """Fan one micro-batch to the shard owners and merge.

        Returns ``(d [m,k], i [m,k], coverage [m])`` — ``coverage`` is
        the per-row fraction of shards that contributed a valid answer.
        With ``detail=True`` the return grows to ``(d, i, coverage,
        validity [S,m], gen_id)`` for callers that need to audit which
        shards covered which rows (the chaos acceptance test's
        surviving-shard oracle).

        ``partial_ok=False`` raises :class:`ShardDropoutError` on ANY
        dropout; the default (:attr:`FabricParams.partial_ok`) degrades
        gracefully until per-row coverage falls below
        :attr:`FabricParams.coverage_floor`."""
        p = self.params
        partial = p.partial_ok if partial_ok is None else bool(partial_ok)
        q = np.ascontiguousarray(np.asarray(queries, dtype=np.float32))
        if q.ndim == 1:
            q = q[None, :]
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(
                f"queries must be [rows, {self.dim}], got {q.shape}")
        if self._closed:
            raise RuntimeError("fabric is closed")
        m = int(q.shape[0])
        k = int(k)
        # graft-trace (ISSUE 13): one trace id for the whole query path
        # — ALWAYS minted here (the serving entry owns its waterfall;
        # adopting an ambient context would collide with — or, cross-
        # process, miss — the record keyed under that id). An enclosing
        # caller's context is kept as a link attr instead.
        ambient = obs_trace.current()
        ctx = obs_trace.start_trace(
            "fabric.search", index=self.name, rows=m, k=k,
            **({"parent_trace": ambient.trace_id} if ambient else {}))
        with obs.entry_span("search", "fabric", queries=m, k=k):
            try:
                gen = self.registry.pin(self.name)
                try:
                    h: _ClusterGen = gen.handle
                    if k > h.rows:
                        raise ValueError(
                            f"k={k} exceeds fabric rows={h.rows}")
                    futs = {
                        s: self._pool.submit(self._search_shard, h, s, q,
                                             k, ctx)
                        for s in range(h.n_shards)
                    }
                    results = {s: f.result() for s, f in futs.items()}
                    gen_id = h.gen_id
                    n_shards = h.n_shards
                finally:
                    gen.release()
                t_merge = time.perf_counter()
                d, i, validity = merge_shard_results(n_shards, results, m,
                                                     k)
                obs_trace.stage(
                    ctx, "merge",
                    ms=(time.perf_counter() - t_merge) * 1e3,
                    t_start=t_merge, shards=n_shards)
            except BaseException as e:  # noqa: BLE001 — re-raised below; caught only to complete the waterfall honestly
                obs_trace.finish(ctx, status="failed",
                                 error=type(e).__name__)
                raise
            coverage = (validity.mean(axis=0, dtype=np.float32) if m
                        else np.ones((0,), np.float32))
            cov_min = float(coverage.min()) if m else 1.0
            cov_mean = float(coverage.mean()) if m else 1.0
            obs.gauge("fabric.coverage", cov_mean)
            with self._stats_lock:
                # smoothed coverage for the helm controller's rebalance
                # signal — one bad batch should not trigger a publish
                self._cov_ewma = (cov_mean if self._cov_ewma is None
                                  else 0.5 * self._cov_ewma
                                  + 0.5 * cov_mean)
            uncovered = sorted(s for s, r in results.items() if r is None)
            if uncovered:
                self._count("dropouts", len(uncovered))
                obs.counter("fabric.dropouts_total", len(uncovered))
                obs.event("fabric_shard_dropout", shards=uncovered,
                          coverage=cov_min, gen=gen_id)
            covered = sorted(s for s, r in results.items()
                             if r is not None)
            # the status must tell the truth about what the CALLER got:
            # a coverage shortfall that is about to raise is a FAILED
            # query (no answer delivered), not a degraded answer — the
            # loadgen's answered/complete columns and the chaos >=99%
            # acceptance count ok/degraded only
            will_raise = ((not partial and cov_min < 1.0)
                          or (partial and cov_min < p.coverage_floor))
            obs_trace.finish(
                ctx,
                status=("failed" if will_raise
                        else "degraded" if cov_min < 1.0 else "ok"),
                gen=gen_id, coverage_min=round(cov_min, 5),
                covered_shards=covered,
                **({"error": "ShardDropoutError"} if will_raise else {}))
            if not partial and cov_min < 1.0:
                raise ShardDropoutError(
                    f"fabric[{self.name}]: coverage {cov_min:.3f} < 1 "
                    f"(shards {uncovered or 'row-invalid'} dropped); "
                    "pass partial_ok=True to accept degraded answers")
            if partial and cov_min < p.coverage_floor:
                raise ShardDropoutError(
                    f"fabric[{self.name}]: coverage {cov_min:.3f} below "
                    f"floor {p.coverage_floor} (shards {uncovered})")
            if detail:
                return d, i, coverage, validity, gen_id
            return d, i, coverage

    # -- per-shard routing --------------------------------------------------

    def member_ranks(self) -> List[int]:
        """Every rank the fabric has ever admitted (append-only; a
        retired rank keeps its number). Falls back to the initial
        ``n_workers`` for caller-supplied group objects without a
        ``ranks()`` surface."""
        ranks = getattr(self.group, "ranks", None)
        if ranks is None:
            return list(range(self.params.n_workers))
        return list(ranks())

    def _route_order(self, owners: Sequence[int],
                     exclude: Sequence[int]) -> List[int]:
        """Owner preference for one attempt: healthy (closed) owners
        first, then half-open ones as a last resort (their
        probe-in-flight state tolerates one trial request);
        open-circuit owners, retired ranks, and already-tried primaries
        are out.

        Under ``balance="p2c"`` the closed set is reordered by
        power-of-two-choices: sample two owners, lead with the one
        whose ``(inflight + 1) x EWMA-latency`` score is lower — so
        replicas contribute THROUGHPUT instead of idling as failover
        spares, and a slow-but-alive owner sheds load without tripping
        its breaker. ``balance="primary"`` keeps the declared order
        (the pre-ISSUE-18 behaviour, and the A/B baseline)."""
        closed = [r for r in owners
                  if r not in exclude and r not in self._retired
                  and self.health[r].routable()]
        half = [r for r in owners
                if r not in exclude and r not in self._retired
                and self.health[r].state == HALF_OPEN]
        if self.params.balance == "p2c" and len(closed) >= 2:
            closed = self._balanced_order(closed)
        return closed + half

    def _balanced_order(self, closed: List[int]) -> List[int]:
        with self._load_lock:
            a, b = self._rng.sample(closed, 2)
            lead = (a if self._score_locked(a) <= self._score_locked(b)
                    else b)
        return [lead] + [r for r in closed if r != lead]

    def _score_locked(self, rank: int) -> float:
        # an unmeasured worker scores 0 — strictly optimistic, so a
        # fresh replica wins its first comparisons and gets measured
        # instead of starving behind sub-millisecond incumbents
        ewma = self._ewma_ms.get(rank)
        return ((self._inflight.get(rank, 0) + 1)
                * (ewma if ewma is not None else 0.0))

    def _load_begin(self, rank: int) -> None:
        with self._load_lock:
            n = self._inflight.get(rank, 0) + 1
            self._inflight[rank] = n
        # gauge OUTSIDE the load lock: obs sinks may take their own
        # locks and fabric.load must stay a leaf
        obs.gauge("fabric.worker_inflight", n, worker=rank)

    def _load_end(self, rank: int) -> None:
        with self._load_lock:
            n = max(self._inflight.get(rank, 0) - 1, 0)
            self._inflight[rank] = n
        obs.gauge("fabric.worker_inflight", n, worker=rank)

    def load_snapshot(self) -> dict:
        """Per-worker routing-load view (the helm controller's primary
        utilization signal): outstanding RPC count and EWMA latency."""
        with self._load_lock:
            return {"inflight": dict(self._inflight),
                    "ewma_ms": dict(self._ewma_ms)}

    def _search_shard(self, h: _ClusterGen, shard: int, q: np.ndarray,
                      k: int,
                      ctx: Optional[obs_trace.TraceContext] = None,
                      ) -> Optional[tuple]:
        """One shard's routed search: deadline-bounded, classified
        retry/backoff across owners, hedged duplicate past the latency
        percentile. Returns ``(worker, d, i)`` or ``None`` (shard
        uncovered this batch). Never raises — an uncovered shard is a
        coverage event, not an exception."""
        p = self.params
        deadline = time.monotonic() + p.rpc_deadline_s
        payload = obs_trace.traced_payload(
            {"gen": h.gen_id, "shard": int(shard), "q": q, "k": int(k)},
            ctx)
        tried: List[int] = []
        attempt = 0
        while True:
            owners = self._route_order(h.owners[shard], tried)
            if not owners:
                return None
            primary = owners[0]
            out = self._rpc_hedged(primary, owners[1:], payload, deadline,
                                   shard, ctx)
            if out is not None:
                return out
            tried.append(primary)
            attempt += 1
            if attempt > p.rpc_retries:
                return None
            # full-jitter sleep under the UNJITTERED cap for deadline
            # math — the conservative bound keeps the retry budget
            # honest while the jitter decorrelates retry stampedes
            cap = p.retry_backoff_s * (2 ** (attempt - 1))
            backoff = _rerrors.backoff_jitter_s(attempt - 1,
                                                p.retry_backoff_s)
            if time.monotonic() + cap >= deadline:
                return None
            self._count("retries")
            obs.counter("fabric.rpc_retries_total")
            obs_trace.stage(ctx, "retry", status="retry", shard=shard,
                            worker=primary, attempt=attempt,
                            backoff_ms=round(backoff * 1e3, 3))
            time.sleep(backoff)

    def _rpc_hedged(self, primary: int, alternates: Sequence[int],
                    payload: dict, deadline: float, shard: int,
                    ctx: Optional[obs_trace.TraceContext] = None,
                    ) -> Optional[tuple]:
        """One routed attempt: RPC the primary; once it is slower than
        the hedge threshold, duplicate the request to the first
        alternate and take whichever valid answer lands first. The
        loser's late response is discarded by the transport. Every
        attempt — winner, hedge loser, failure, timeout — lands in the
        query's waterfall as an ``rpc`` stage with its status."""
        p = self.params
        self._load_begin(primary)
        outstanding: List[Tuple[int, Future]] = [
            # graft-lint: allow-untraced-rpc payload pre-threaded by _search_shard via obs.trace.traced_payload
            (primary, self.group.call(primary, "search", payload))
        ]
        hedge_s = self._hedge_delay_ms() / 1e3
        hedged = False
        # per-rank send times: a hedge win must be timed from ITS call
        # site, or every win would record hedge-delay + replica latency
        # — inflating the measured percentile the next hedge delay is
        # derived from, and blaming the fast replica for the wait
        sent = {primary: time.perf_counter()}
        while outstanding:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                for rank, f in outstanding:
                    kind = (_rerrors.TRANSIENT if self.group.alive(rank)
                            else _rerrors.DEAD_BACKEND)
                    self.health[rank].record_failure(kind)
                    obs.counter("fabric.rpc_timeouts_total", worker=rank,
                                kind=kind)
                    obs_trace.stage(
                        ctx, "rpc",
                        ms=(time.perf_counter() - sent[rank]) * 1e3,
                        t_start=sent[rank], worker=rank, shard=shard,
                        status="timeout", kind=kind)
                    # abandon the request at the transport so a reply
                    # that never comes (dropped RPC, hung worker) does
                    # not pin its Future + query payload forever
                    self.group.forget(rank, f)
                    self._load_end(rank)
                return None
            wait_s = remaining
            if not hedged and alternates:
                wait_s = min(wait_s, max(hedge_s, 1e-4))
            done, _ = _futures_wait([f for _, f in outstanding],
                                    timeout=wait_s,
                                    return_when=FIRST_COMPLETED)
            if not done:
                if not hedged and alternates:
                    alt = alternates[0]
                    sent[alt] = time.perf_counter()
                    self._load_begin(alt)
                    outstanding.append(
                        # graft-lint: allow-untraced-rpc payload pre-threaded by _search_shard via obs.trace.traced_payload
                        (alt, self.group.call(alt, "search", payload)))
                    hedged = True
                    self._count("hedges")
                    obs.counter("fabric.hedges_total", worker=alt)
                    obs.event("fabric_hedge", shard=shard,
                              primary=primary, hedge=alt)
                continue
            for rank, f in list(outstanding):
                if f not in done:
                    continue
                outstanding.remove((rank, f))
                self._load_end(rank)
                rpc_ms = (time.perf_counter() - sent[rank]) * 1e3
                try:
                    res = f.result()
                except BaseException as e:  # noqa: BLE001 — classified right here, per worker
                    kind = self._fail_kind(e, rank)
                    if is_no_gen(e):
                        # stale, not sick: missed a publish while
                        # partitioned — the next probe round re-syncs
                        # it (every non-open worker is pinged, and a
                        # ping that misses the current generation
                        # triggers _sync_worker); do not trip the
                        # breaker
                        obs.counter("fabric.stale_worker_total",
                                    worker=rank)
                        obs_trace.stage(ctx, "rpc", ms=rpc_ms,
                                        t_start=sent[rank], worker=rank,
                                        shard=shard, status="failed",
                                        kind="stale")
                    else:
                        self.health[rank].record_failure(kind)
                        obs.counter("fabric.rpc_errors_total",
                                    worker=rank, kind=kind)
                        obs_trace.stage(ctx, "rpc", ms=rpc_ms,
                                        t_start=sent[rank], worker=rank,
                                        shard=shard, status="failed",
                                        kind=kind)
                    continue
                if int(res["gen"]) != int(payload["gen"]):
                    # structurally impossible (workers answer from the
                    # requested generation) — counted so the chaos
                    # acceptance can PROVE no mixed-generation answer
                    # ever merged
                    self._count("mixed_gen")
                    obs.counter("fabric.mixed_generation_total",
                                worker=rank)
                    continue
                self._observe_latency(rank, rpc_ms)
                self.health[rank].record_success()
                obs_trace.stage(
                    ctx, "rpc", ms=rpc_ms, t_start=sent[rank],
                    worker=rank, shard=shard,
                    status="hedge_win" if hedged and rank != primary
                    else "ok")
                # the worker's span summary piggybacked on the reply:
                # its device-complete scan time becomes the trace's
                # worker_scan stage (positioned by subtracting its
                # duration from the arrival time — worker clocks are
                # not comparable across processes)
                for s in res.get("spans", ()):
                    if not isinstance(s, dict):
                        continue
                    s_ms = float(s.get("ms", 0.0))
                    obs_trace.stage(
                        ctx, s.get("name", "worker_scan"), ms=s_ms,
                        t_start=time.perf_counter() - s_ms / 1e3,
                        worker=s.get("worker", rank), shard=shard,
                        device_complete=bool(
                            s.get("device_complete", False)))
                for loser, lf in outstanding:
                    # hedge loser: drop its pending entry now — a slow
                    # reply cleans itself up on arrival, but a reply
                    # that never comes would leak the Future
                    self.group.forget(loser, lf)
                    self._load_end(loser)
                    obs_trace.stage(
                        ctx, "rpc",
                        ms=(time.perf_counter() - sent[loser]) * 1e3,
                        t_start=sent[loser], worker=loser, shard=shard,
                        status="hedge_loser")
                return rank, np.asarray(res["d"]), np.asarray(res["i"])
        return None

    def _fail_kind(self, exc: BaseException, rank: int) -> str:
        if isinstance(exc, FutureTimeoutError):
            return (_rerrors.TRANSIENT if self.group.alive(rank)
                    else _rerrors.DEAD_BACKEND)
        return _rerrors.classify(exc)

    def _call_control(self, rank: int, method: str,
                      payload: Optional[dict] = None) -> Future:
        """The control plane's ONE transport call site (ping / prepare /
        publish / abort / retire / collect_metrics). Deliberately
        untraced: control RPCs belong to no query, so threading a trace
        context would stamp whatever query happens to be ambient on the
        calling thread onto cluster management noise."""
        return self.group.call(rank, method, payload)  # graft-lint: allow-untraced-rpc control-plane RPC — belongs to no query trace (GL019 scopes the data plane)

    # -- hedge-delay measurement --------------------------------------------

    def _hedge_delay_ms(self) -> float:
        p = self.params
        if p.hedge_after_ms is not None:
            return float(p.hedge_after_ms)
        with self._stats_lock:
            samples = list(self._lat_ms)
        if len(samples) >= 16:
            return max(
                float(np.percentile(samples, p.hedge_percentile)), 0.5)
        return float(tuning.budget("fabric_hedge_ms", 50))

    def _observe_latency(self, rank: int, ms: float) -> None:
        obs.observe("fabric.rpc_latency_ms", ms,
                    buckets=_RPC_LAT_BUCKETS, worker=rank)
        with self._stats_lock:
            self._lat_ms.append(ms)
        with self._load_lock:
            # success-only EWMA: failures route through the breaker,
            # not the balancer score
            prev = self._ewma_ms.get(rank)
            self._ewma_ms[rank] = (ms if prev is None
                                   else 0.8 * prev + 0.2 * ms)

    # -- two-phase cluster hot-swap -----------------------------------------

    def swap(self, dataset) -> int:
        """Replace the whole fabric's content with a two-phase
        generation barrier: (1) PREPARE — every live worker builds and
        warms its new shards under the staged generation; any failure
        aborts and rolls all of them back
        (:class:`FabricSwapError`, old generation keeps serving);
        (2) PUBLISH — one atomic cluster-wide switch, after which the
        registry advances and in-flight batches finish on the
        generation they pinned. Returns the new generation id."""
        with obs.span("fabric.swap", index=self.name):
            dataset = np.ascontiguousarray(np.asarray(dataset),
                                           dtype=np.float32)
            if dataset.ndim != 2 or dataset.shape[1] != self.dim:
                raise ValueError(
                    f"dataset must be [rows, {self.dim}], "
                    f"got {dataset.shape}")
            if dataset.shape[0] < self.n_shards:
                # same contract as __init__ — and a ValueError, not a
                # transient FabricSwapError a resilience-aware client
                # would retry forever
                raise ValueError(
                    f"dataset rows {dataset.shape[0]} < n_shards "
                    f"{self.n_shards}: every shard needs a non-empty "
                    "slice")
            if self._closed:
                raise RuntimeError("fabric is closed")
            return self._publish_generation(dataset)

    def rebalance(self, exclude: Sequence[int] = (), *,
                  reason: str = "manual") -> int:
        """Re-replicate the CURRENT dataset over the current
        membership minus ``exclude`` — the shard-rebalancing move
        (ISSUE 18): when a worker dies for good, excluding it places
        its shards' replicas on the survivors through the SAME
        two-phase prepare/publish barrier as a content swap, restoring
        the replication factor without dropping an in-flight search
        (old-generation pins drain on the old owner map). Returns the
        new generation id."""
        with obs.span("fabric.rebalance", index=self.name,
                      reason=reason):
            if self._closed:
                raise RuntimeError("fabric is closed")
            gen = self._publish_generation(exclude=exclude)
            self._count("rebalances")
            obs.counter("fabric.rebalances_total", reason=reason)
            obs.event("fabric_rebalance", gen=gen, reason=reason,
                      exclude=sorted(set(int(r) for r in exclude)))
            return gen

    def _publish_generation(self, dataset: Optional[np.ndarray] = None,
                            initial: bool = False,
                            exclude: Sequence[int] = ()) -> int:
        p = self.params
        with self._swap_lock:
            if dataset is None:
                dataset = self._dataset
            self._gen_counter += 1
            gen_id = self._gen_counter
            bounds = shard_bounds(dataset.shape[0], self.n_shards)
            # placement = current members minus retired/excluded ranks
            # — NOT live-only: a briefly-down worker keeps its slots
            # (the half-open resync heals it in place); only an
            # explicit eviction moves shards
            out = set(self._retired)
            out.update(int(r) for r in exclude)
            placement = [r for r in self.member_ranks() if r not in out]
            if not placement:
                raise FabricSwapError(
                    f"generation {gen_id} impossible: no admissible "
                    f"workers (members {self.member_ranks()}, "
                    f"excluded {sorted(out)})")
            owners = {
                s: tuple(placement[(s + j) % len(placement)]
                         for j in range(min(p.replication,
                                            len(placement))))
                for s in range(self.n_shards)
            }
            live = [r for r in placement if self.group.alive(r)]
            if initial and len(live) < len(placement):
                raise RuntimeError(
                    "fabric bootstrap needs every worker alive, got "
                    f"{live} of {placement}")
            for s, ranks in owners.items():
                if not any(r in live for r in ranks):
                    raise FabricSwapError(
                        f"generation {gen_id} impossible: shard {s} has "
                        f"no live owner (owners {ranks})")
            per_worker: Dict[int, dict] = {r: {} for r in live}
            for s, ranks in owners.items():
                vec = dataset[bounds[s]:bounds[s + 1]]
                for r in ranks:
                    if r in per_worker:
                        per_worker[r][s] = (vec, bounds[s])
            deadline = time.monotonic() + p.swap_deadline_s
            # phase 1: prepare-and-warm everywhere, or roll back
            futs = {
                r: self._call_control(r, "prepare",
                                   {"gen": gen_id,
                                    "shards": per_worker[r]})
                for r in live
            }
            failed = self._await_all(futs, deadline)
            if failed:
                self._abort_generation(gen_id, live)
                self._count("swap_aborts")
                obs.counter("fabric.swap_aborts_total")
                obs.event("fabric_swap_abort", gen=gen_id,
                          failed={r: str(e)[:160]
                                  for r, e in failed.items()})
                raise FabricSwapError(
                    f"generation {gen_id} aborted: prepare failed on "
                    f"worker(s) {sorted(failed)}; rolled back — "
                    f"generation {self.generation()} keeps serving")
            # phase 2: publish. A local pointer swap — an alive worker
            # can only fail it by dying or losing the ack, and either
            # way it is no longer treated as live: its circuit opens
            # and the half-open resync path re-publishes the staged
            # generation (publish is idempotent), so live workers are
            # never mixed-generation.
            futs = {r: self._call_control(r, "publish", {"gen": gen_id})
                    for r in live}
            failed = self._await_all(futs, deadline)
            for r in failed:
                # a lost publish ack evicts the worker from routing
                # until the half-open resync re-publishes the staged
                # generation (idempotent) and readmits it
                self.health[r].force_open()
            # capture the prior generation's id BEFORE publishing: with
            # no pins outstanding, publish retires-and-drains it inline,
            # nulling its handle
            prior = self.registry.get(self.name)
            old_gid = (prior.handle.gen_id
                       if prior is not None and prior.handle is not None
                       else None)
            handle = _ClusterGen(gen_id, owners, dataset.shape[0],
                                 self.dim)
            self.registry.publish(self.name, handle)
            self._dataset = dataset
            if old_gid is not None:
                # workers keep the retired generation until its last
                # router pin drops — in-flight batches finish on it
                prior.add_on_drain(
                    lambda _g, gid=old_gid: self._retire_cluster(gid))
            self._count("swaps")
            obs.counter("fabric.swaps_total")
            obs.gauge("fabric.generation", gen_id)
            obs.event("fabric_generation_published", gen=gen_id,
                      workers=sorted(live))
            return gen_id

    def _await_all(self, futs: Dict[int, Future],
                   deadline: float) -> Dict[int, BaseException]:
        failed: Dict[int, BaseException] = {}
        for r, f in futs.items():
            remaining = max(deadline - time.monotonic(), 1e-3)
            try:
                f.result(timeout=remaining)
                self.health[r].record_success()
            except BaseException as e:  # noqa: BLE001 — collected per worker, classified via _fail_kind
                failed[r] = e
                self.health[r].record_failure(self._fail_kind(e, r))
                self.group.forget(r, f)
        return failed

    def _abort_generation(self, gen_id: int,
                          ranks: Sequence[int]) -> None:
        futs = [(r, self._call_control(r, "abort", {"gen": gen_id}))
                for r in ranks]
        for r, f in futs:
            try:
                f.result(timeout=2.0)
            except BaseException as e:  # noqa: BLE001 — classified: abort is best-effort, a dead worker has nothing staged to drop
                _rerrors.classify(e)
                self.group.forget(r, f)

    def _retire_cluster(self, gen_id: int) -> None:
        for r in self.member_ranks():
            if r in self._retired or not self.group.alive(r):
                continue
            try:
                self._call_control(r, "retire", {"gen": gen_id})
            except BaseException as e:  # noqa: BLE001 — classified: retire is best-effort GC of a drained generation
                _rerrors.classify(e)

    # -- health probing / recovery ------------------------------------------

    def probe_now(self) -> Dict[int, str]:
        """One synchronous probe round (the background prober's body,
        callable directly for deterministic tests): due open circuits
        move to half-open; half-open and closed workers are pinged; a
        stale-but-alive worker is re-synced to the current generation
        before re-admission. Returns the post-round state map."""
        with obs.span("fabric.probe_round", index=self.name):
            now = time.monotonic()
            members = self.member_ranks()
            for rank in members:
                if rank in self._retired:
                    continue
                hl = self.health[rank]
                if hl.state == OPEN:
                    if not hl.due_for_probe(now):
                        continue
                    hl.to_half_open()
                self._probe_worker(rank)
            return {r: self.health[r].state
                    for r in members if r not in self._retired}

    def _probe_worker(self, rank: int) -> bool:
        p = self.params
        self._count("probes")
        fut = self._call_control(rank, "ping", {})
        try:
            res = fut.result(timeout=p.probe_timeout_s)
        except BaseException as e:  # noqa: BLE001 — classified via _fail_kind
            self.health[rank].record_failure(self._fail_kind(e, rank))
            obs.counter("fabric.probes_total", outcome="fail")
            self.group.forget(rank, fut)
            return False
        cur = self.registry.get(self.name)
        want = (cur.handle.gen_id
                if cur is not None and cur.handle is not None else None)
        if want is not None and want not in res.get("gens", ()):
            # alive but missed a publish (restarted, or partitioned
            # through the barrier): load it before readmitting, or it
            # would answer every search with no_gen
            if not self._sync_worker(rank, want):
                obs.counter("fabric.probes_total", outcome="stale")
                return False
        self.health[rank].record_success()
        obs.counter("fabric.probes_total", outcome="ok")
        return True

    def _sync_worker(self, rank: int, gen_id: int) -> bool:
        """Prepare+publish the current generation on one stale worker
        (the unilateral tail of the two-phase protocol — safe because
        the cluster decision for ``gen_id`` is already COMMIT)."""
        # snapshot (generation, dataset) under the swap lock: a swap
        # concurrent with this probe could otherwise install the NEW
        # dataset under the OLD generation id on the worker — a silent
        # wrong-answer source the gen-id pin could not catch
        with self._swap_lock:
            cur = self.registry.get(self.name)
            if cur is None or cur.handle is None \
                    or cur.handle.gen_id != gen_id:
                return False
            h: _ClusterGen = cur.handle
            dataset = self._dataset
        bounds = shard_bounds(dataset.shape[0], h.n_shards)
        shards = {
            s: (dataset[bounds[s]:bounds[s + 1]], bounds[s])
            for s, ranks in h.owners.items() if rank in ranks
        }
        fut = None
        try:
            fut = self._call_control(rank, "prepare",
                                  {"gen": gen_id, "shards": shards})
            fut.result(timeout=self.params.swap_deadline_s)
            fut = self._call_control(rank, "publish", {"gen": gen_id})
            fut.result(timeout=self.params.probe_timeout_s)
        except BaseException as e:  # noqa: BLE001 — classified via _fail_kind; the breaker records the verdict
            self.health[rank].record_failure(self._fail_kind(e, rank))
            if fut is not None:
                self.group.forget(rank, fut)
            return False
        obs.counter("fabric.worker_resyncs_total", worker=rank)
        obs.event("fabric_worker_resync", worker=rank, gen=gen_id)
        return True

    def restart_worker(self, rank: int, *,
                       inherit_faults: bool = False) -> None:
        """Respawn a lost worker and stage it for HALF-OPEN
        re-admission: the fresh process holds no index state, so it is
        forced open (unrouted) and the next probe round re-syncs it to
        the current generation before closing its circuit.

        ``inherit_faults=True`` (the helm controller's respawn path)
        re-installs the rank's remaining spawn-time fault plan on the
        replacement — a ``dead@proc`` rank stays dead, a
        ``flap@proc:R*K`` rank keeps flapping until its budget is
        spent — so chaos drills model machines, not processes."""
        if rank in self._retired:
            raise ValueError(f"worker {rank} is retired")
        with obs.span("fabric.restart_worker", index=self.name,
                      worker=rank):
            if inherit_faults:
                self.group.restart(rank, inherit_faults=True)
            else:
                self.group.restart(rank)
            self.health[rank].force_open()
            self._count("restarts")
            obs.counter("fabric.worker_restarts_total", worker=rank)
            obs.event("fabric_worker_restart", worker=rank)

    # -- control plane: membership ------------------------------------------

    def add_worker(self, fault_spec: Optional[str] = None) -> int:
        """Admit one fresh worker (scale-up): spawn it at the next
        rank, then republish the current generation over the grown
        membership so the newcomer owns shards before it takes
        traffic. Returns the new rank."""
        if self._closed:
            raise RuntimeError("fabric is closed")
        with obs.span("fabric.add_worker", index=self.name):
            p = self.params
            rank = self.group.add_worker(fault_spec)
            while len(self.health) <= rank:
                self.health.append(
                    WorkerHealth(len(self.health), p.fail_threshold,
                                 p.halfopen_after_s,
                                 p.probation_successes))
            try:
                self.rebalance(reason="scale_up")
            except BaseException:
                # the spawn succeeded but placement failed — evict the
                # orphan so it never takes traffic half-synced
                self._retired.add(rank)
                self.group.retire(rank)
                raise
            self._count("adds")
            obs.counter("fabric.worker_adds_total", worker=rank)
            obs.event("fabric_worker_add", worker=rank)
            return rank

    def retire_worker(self, rank: int, timeout_s: float = 30.0, *,
                      reason: str = "scale_down") -> None:
        """Drain one worker out of the fabric (scale-down or eviction)
        WITHOUT dropping a query: republish the current generation with
        the rank excluded, wait for the prior generation (whose owner
        map may still route to it) to drain its in-flight pins, then
        stop the process. The rank number is never reused."""
        rank = int(rank)
        if rank in self._retired:
            return
        if self._closed:
            raise RuntimeError("fabric is closed")
        with obs.span("fabric.retire_worker", index=self.name,
                      worker=rank, reason=reason):
            prior = self.registry.get(self.name)
            self._retired.add(rank)
            try:
                self.rebalance(reason=reason)
            except BaseException:
                self._retired.discard(rank)
                raise
            # in-flight searches pinned the PRIOR generation and may
            # still read this rank; the pin-drain event bounds the wait
            if prior is not None:
                prior.drained.wait(timeout=timeout_s)
            self.health[rank].force_open()
            self.group.retire(rank)
            self._count("retires")
            obs.counter("fabric.worker_retires_total", worker=rank,
                        reason=reason)
            obs.event("fabric_worker_retire", worker=rank,
                      reason=reason)

    def _probe_loop(self) -> None:
        while not self._closed:
            time.sleep(self._probe_interval_s)
            if self._closed:
                return
            try:
                self.probe_now()
            except BaseException as e:  # noqa: BLE001 — classified: the prober must outlive any single bad round
                _rerrors.classify(e)

    # -- introspection / lifecycle ------------------------------------------

    def generation(self) -> int:
        cur = self.registry.get(self.name)
        if cur is None or cur.handle is None:
            return 0
        return cur.handle.gen_id

    def coverage_ewma(self) -> Optional[float]:
        """Smoothed mean coverage over recent searches (``None``
        before the first) — the helm controller's rebalance trigger."""
        with self._stats_lock:
            return self._cov_ewma

    def active_ranks(self) -> List[int]:
        """Members minus retired — the ranks the control plane manages."""
        return [r for r in self.member_ranks()
                if r not in self._retired]

    def open_episodes(self, now: Optional[float] = None) -> Dict[int, float]:
        """Seconds each active worker's circuit has been in its current
        OPEN episode (0.0 when closed). Flapping resets nothing here —
        the episode clock survives failed half-open probes and only a
        real readmission clears it, so the controller's rebalance
        budget distinguishes solid death from flapping."""
        now = time.monotonic() if now is None else float(now)
        out: Dict[int, float] = {}
        for r in self.active_ranks():
            hl = self.health[r]
            with hl.lock:
                since = hl.open_since
            out[r] = (now - since) if since > 0.0 else 0.0
        return out

    def collect_metrics(self, include_router: bool = True,
                        timeout_s: Optional[float] = None) -> dict:
        """Fleet metrics federation (ISSUE 13): scrape every live
        worker's metrics registry over the ``collect_metrics`` RPC and
        merge the snapshots — each worker's series under a
        ``worker="w<rank>"`` label, the router's own registry under
        ``worker="router"`` — into one snapshot-shaped dict
        (:func:`raft_tpu.obs.federation.federated_snapshot`, plus
        ``generation`` and per-worker ``health``). A worker that fails
        the scrape is recorded against its circuit breaker and skipped;
        the snapshot's ``workers`` list names exactly the workers that
        answered."""
        with obs.span("fabric.collect_metrics", index=self.name):
            timeout = (float(timeout_s) if timeout_s is not None
                       else self.params.probe_timeout_s)
            futs = {
                r: self._call_control(r, "collect_metrics", {})
                for r in self.member_ranks()
                if r not in self._retired and self.group.alive(r)
            }
            # ONE shared deadline across the fleet, not timeout-per-rank:
            # a scrape endpoint over N hung workers must answer in
            # ~timeout, not N x timeout
            deadline = time.monotonic() + timeout
            parts: Dict[str, dict] = {}
            answered: List[str] = []
            shared = False
            for r, f in futs.items():
                try:
                    res = f.result(
                        timeout=max(deadline - time.monotonic(), 1e-3))
                except BaseException as e:  # noqa: BLE001 — classified via _fail_kind; a mute worker degrades the snapshot, never fails it
                    self.health[r].record_failure(self._fail_kind(e, r))
                    obs.counter("fabric.federation_errors_total",
                                worker=r)
                    self.group.forget(r, f)
                    continue
                answered.append(f"w{r}")
                if res.get("shared_registry"):
                    # LocalGroup twin: the worker shares THIS process's
                    # registry — it answered, but its series arrive
                    # once, as the router's, or every fleet sum would
                    # multiply (n_workers+1)x
                    shared = True
                    continue
                parts[f"w{r}"] = res.get("metrics", {})
            if include_router and obs.enabled():
                parts["router"] = obs.snapshot(
                    runtime_gauges=False)["metrics"]
            obs.gauge("fabric.federation_workers", len(answered))
            # the workers list names exactly the WORKERS that answered;
            # the router's own series ride the metrics map under
            # worker="router"
            fed = obs_federation.federated_snapshot(
                parts, workers=sorted(answered))
            if shared:
                fed["shared_registry"] = True
            fed["generation"] = self.generation()
            fed["worker_health"] = {
                f"w{r}": self.health[r].state
                for r in self.member_ranks() if r not in self._retired
            }
            return fed

    def export_federated_prometheus(self) -> str:
        """One Prometheus text exposition for the whole fleet — the
        scrape-endpoint body a router-side HTTP handler serves
        (docs/observability.md §federation)."""
        fed = self.collect_metrics()
        return obs_federation.render_prometheus(fed["metrics"])

    def recall_estimates(self) -> Dict[str, dict]:
        """The fleet's graft-gauge quality view (ISSUE 19): every
        ``serve.recall_estimate`` / ``_ci_low`` / ``_ci_high`` series
        from :meth:`collect_metrics`, regrouped per
        ``(worker, index, rung)`` as ``{"estimate": ..., "ci_low": ...,
        "ci_high": ...}``. The recall series federate like any other
        registry metric — this just gives the helm/quality-alarm
        consumers (and ``obs_report.py recall``) the stitched view
        without re-walking the snapshot shape."""
        fed = self.collect_metrics()
        out: Dict[str, dict] = {}
        fields = {"serve.recall_estimate": "estimate",
                  "serve.recall_ci_low": "ci_low",
                  "serve.recall_ci_high": "ci_high"}
        for name, field in fields.items():
            m = fed.get("metrics", {}).get(name)
            if not m:
                continue
            for p in m.get("points", ()):
                lab = p.get("labels", {})
                key = "|".join((lab.get("worker", "router"),
                                lab.get("index", "?"),
                                lab.get("rung", "all")))
                out.setdefault(key, {})[field] = p.get("value")
        return out

    def stats(self) -> dict:
        with self._stats_lock:
            counters = dict(self._counters)
            lat = list(self._lat_ms)
        active = self.active_ranks()
        return {
            "generation": self.generation(),
            "n_workers": len(active),
            "n_shards": self.n_shards,
            "members": self.member_ranks(),
            "retired": sorted(self._retired),
            "replication": self.params.replication,
            "balance": self.params.balance,
            "health": {r: self.health[r].state for r in active},
            "counters": counters,
            "rpc_p50_ms": (round(float(np.percentile(lat, 50)), 3)
                           if lat else None),
            "rpc_p95_ms": (round(float(np.percentile(lat, 95)), 3)
                           if lat else None),
            "hedge_delay_ms": round(self._hedge_delay_ms(), 3),
        }

    def close(self, timeout_s: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        if self._prober is not None:
            self._prober.join(timeout=max(self._probe_interval_s * 2,
                                          1.0))
        self.registry.drop(self.name)
        self._pool.shutdown(wait=False)
        self.group.close(timeout_s=timeout_s)

    def __enter__(self) -> "Fabric":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _count(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._counters[key] += n
