"""The serving engine: Server, per-index serving units, warmed dispatch.

This ties the serving pieces together (docs/serving.md §1):

    submit ──► MicroBatcher (bucket ladder, backpressure)
                   │ Batch
                   ▼
            dispatch  ──pin──►  Registry generation (hot-swap)
                   │                    │ handle
                   ▼                    ▼
        resilience.run( main filtered search + side-buffer search
                        + merge_topk )  ◄── MutableState (tombstones)
                   │
                   ▼
            futures resolved with host (distances, external ids)

Trace discipline: every device-facing shape is drawn from a finite set —
query rows from the bucket ladder, k from the k-ladder (powers of two
plus the ``max_k`` top rung),
filter words from the mutation state's power-of-two filter-capacity
rung (:meth:`MutableState.filter_capacity` — so per-upsert id growth
does not change the kernels' static ``filter_nbits``), side-buffer rows
from its power-of-two capacity — and :meth:`Server.warmup` drives each
combination once at publish time, so steady-state serving dispatches
only cached executables (the GL007 zero-recompile requirement; the
`test_serve` suite asserts it with the same trace-counting hook).

Failure discipline: batch dispatch runs under
:func:`raft_tpu.resilience.run` (classified retry for transient /
dead-backend); an OOM-classified failure downshifts the batcher's
bucket ceiling (recorded via ``tuning.record_budget`` so later servers
in the process start safe), splits the batch, and re-dispatches — the
serving instance of the resilience OOM ladder.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.obs import config as _obs_config
from raft_tpu.obs import trace as obs_trace
from raft_tpu.core import pipeline as _pipeline
from raft_tpu.core.bitset import Bitset
from raft_tpu.distance.types import is_min_close, resolve_metric
from raft_tpu.neighbors import brute_force, cagra, hybrid, ivf_flat, ivf_pq
from raft_tpu.neighbors.common import BitsetFilter, merge_topk
from raft_tpu.resilience import errors as _rerrors
from raft_tpu.resilience import faultinject
from raft_tpu.serve import adaptive as _adaptive
from raft_tpu.serve.batcher import (
    Batch,
    MicroBatcher,
    Overloaded,
    Request,
    choose_bucket,
    pad_rows,
)
from raft_tpu.serve.mutation import MutableState
from raft_tpu.serve.quality import QualityMonitor
from raft_tpu.serve.registry import Registry

ALGOS = ("brute_force", "ivf_flat", "ivf_pq", "cagra", "hybrid")

# the refine over-fetch a rabitq-cache index is served at when the
# caller left refine_ratio defaulted — ONE home: _Handle.pipeline_rr
# feeds dispatch AND warmup, which must agree or warmup traces the
# wrong shortlist-width rungs and steady state silently recompiles
RABITQ_DEFAULT_REFINE_RATIO = 4

# latency histogram edges tuned for ms-scale online serving
_LAT_BUCKETS = (0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 5000)

# difficulty-margin histogram edges: the policy thresholds live in the
# low decades (floor ~0.02, easy ~0.20), so the mass needs resolution
# there (docs/serving.md §13)
_MARGIN_BUCKETS = (0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.35, 0.5, 1.0)


@dataclasses.dataclass
class ServeParams:
    """Serving knobs (docs/serving.md §6)."""

    max_batch_rows: int = 256       # bucket-ladder top (rounded to pow2)
    max_wait_ms: float = 2.0        # linger before dispatching a partial batch
    max_queue_rows: int = 4096      # admission bound -> Overloaded past it
    max_k: int = 128                # k-ladder top (requests cap here)
    side_capacity: int = 64         # initial upsert side-buffer capacity (pow2)
    compact_threshold: int = 512    # side rows that trigger background
    #                                 compaction (0 = manual compact() only)
    warmup: bool = True             # trace the ladder at publish time
    dispatch_retries: int = 2       # classified transient/dead retries
    retry_backoff_s: float = 0.05
    request_timeout_s: float = 120.0  # Server.search() convenience bound
    # tiered-memory rerank (docs/serving.md §12, ISSUE 12): keep the
    # raw originals HOST-resident and fetch only unique shortlist rows
    # per batch through neighbors.tiered, instead of uploading the
    # whole dataset per generation (ivf_pq with refine_ratio > 1 or a
    # rabitq cache). hot_rows=None draws the HBM hot-row budget from
    # tuning.budget("tiered_hot_rows").
    tiered_rerank: bool = False
    tiered_hot_rows: Optional[int] = None
    # bounded result cache in front of dispatch: repeated queries
    # (Zipf traffic) answered without touching the engine, keyed on
    # (query bytes, k) x generation x mutation epoch so hot-swap and
    # delete/upsert invalidate correctly. 0 = off.
    result_cache_entries: int = 0
    # SLO-aware adaptive execution (ISSUE 14, docs/serving.md §13):
    # per-query difficulty (coarse centroid-distance margin) chooses a
    # pow2 probe rung for ivf_flat/ivf_pq; the resolved n_probes (the
    # old exhaustive pin) becomes the ladder's CEILING. Ambiguous
    # queries escape to the top rung — bitwise-identical to the
    # non-adaptive path — so correctness-first deployments lose
    # nothing by leaving this off (the default).
    adaptive_probes: bool = False
    # default per-request SLO deadline (ms from submit); per-call
    # submit(deadline_ms=...) overrides. None = no deadline.
    deadline_ms: Optional[float] = None
    # what to do with a request whose slack no longer covers the
    # measured service estimate: "downshift" drops it one probe rung at
    # a time (adaptive indexes only; sheds when the floor rung still
    # misses), "shed" fails it immediately with
    # Overloaded(reason="deadline") — both counted in
    # serve.deadline_shed_total{action}
    deadline_action: str = "downshift"
    # multi-tenant admission: per-index pending-row quotas atop the
    # shared max_queue_rows backpressure ({index_name: rows}); and an
    # optional server-wide pending-row bound across all indexes. Both
    # reject with Overloaded(reason="quota") (transient).
    admission_quotas: Optional[Dict[str, int]] = None
    max_total_queue_rows: Optional[int] = None
    # graft-gauge online quality control (ISSUE 19, docs/serving.md
    # §14): sample this fraction of answered live requests onto the
    # batcher's best-effort shadow lane, re-run them through the
    # generation-pinned exhaustive oracle, and export windowed
    # Wilson-interval recall estimates. 0 disables the whole subsystem
    # (the delivery hook is then a single attribute read).
    quality_sample_rate: float = 0.0
    # stated recall floor the closed loop defends; None draws from
    # tuning.budget("serve_recall_band_bp") (default 9000 = 0.90)
    quality_band: Optional[float] = None
    quality_window: int = 128        # samples per estimate window
    quality_min_samples: int = 24    # no verdicts below this many
    # actuators: margin/refine retune (bounded, with hysteresis) and
    # post-swap probation rollback
    quality_retune: bool = True
    quality_rollback: bool = True
    quality_max_retunes: int = 8
    # shadow-lane row bound (drop-oldest past it; never backpressures
    # live admission)
    shadow_queue_rows: int = 256
    # graft-flow dispatch pipelining (docs/serving.md §12): the batcher
    # thread stops at ASYNC dispatch and hands the in-flight batch (a
    # ticket holding its pinned generation) to a per-index completion
    # thread that syncs + delivers, so batch N+1's host work — padding,
    # H2D upload, and the tiered rerank gather — overlaps batch N's
    # device time. The value bounds tickets in flight (backpressure
    # blocks the batcher past it); 0 forces the classic synchronous
    # dispatch, bitwise-identical results either way. None draws from
    # tuning.budget("pipeline_depth") (default 2).
    pipeline_depth: Optional[int] = None


class _Handle:
    """One generation's immutable serving state: the index, its searcher
    configuration, and the (shared, mutable) tombstone overlay."""

    __slots__ = ("algo", "index", "state", "search_params",
                 "user_search_params", "build_params",
                 "refine_ratio", "metric", "select_min", "dtype", "dim",
                 "rows", "raw_dataset", "_raw_dev", "_side_cache",
                 "tiered_source", "adaptive", "_plan_cache",
                 "_plan_memo")

    def __init__(self, algo: str, index, state: MutableState,
                 search_params, build_params, refine_ratio: int,
                 raw_dataset: Optional[np.ndarray],
                 user_search_params=None, tiered_source=None,
                 adaptive=None):
        self.algo = algo
        self.index = index
        self.state = state
        self.search_params = search_params
        # the params the CALLER supplied (None when defaulted): a swap
        # inherits these, not the resolved ones — the serving defaults
        # (n_probes = n_lists, now the adaptive ladder's exhaustive
        # CEILING) must be re-derived against the NEW index, or a
        # bigger successor silently serves the old index's probe count
        # — and the whole probe-rung ladder, not just the ceiling,
        # re-derives with it (ISSUE 14)
        self.user_search_params = user_search_params
        self.build_params = build_params
        self.refine_ratio = int(refine_ratio)
        self.metric = _index_metric(algo, index)
        self.select_min = is_min_close(self.metric)
        self.rows = _index_rows(algo, index)
        self.dim = _index_dim(algo, index)
        self.dtype = np.dtype(np.float32)
        self.raw_dataset = raw_dataset
        self._raw_dev = None                  # device copy, cached lazily
        self._side_cache: Optional[Tuple[int, object, object]] = None
        # tiered rerank source (ISSUE 12): when set, the ivf_pq refine
        # paths fetch only unique shortlist rows from the HOST raw
        # store instead of device-uploading it wholesale (raw_dev).
        # Per-generation on purpose — a swap/compaction gets a FRESH
        # hot-row cache, so stale rows can never serve after a content
        # change.
        self.tiered_source = tiered_source
        # SLO-aware adaptive policy (ISSUE 14): per-generation, like
        # everything shape-bearing — its ladder tops at THIS index's
        # resolved n_probes ceiling, so a swap re-derives the whole
        # ladder (not just the ceiling) against the successor index
        self.adaptive = adaptive
        # compiled query plans (ISSUE 20): one CompiledPlan per
        # (k, rung, n_probes, refine_ratio) point, built lazily and by
        # warmup — per GENERATION, so a swap/compaction recompiles
        # against the successor index by construction. The memo shares
        # derived device arrays (the slot-substituted indices block)
        # across this handle's variants.
        self._plan_cache: Dict[tuple, object] = {}
        self._plan_memo: Dict[str, object] = {}

    def pipeline_rr(self) -> int:
        """The refine_ratio the multi-stage pipeline dispatches at:
        the caller's when set, else the rabitq serving default. Used
        by BOTH dispatch and warmup — they must agree, or warmup
        traces the wrong shortlist-width rungs and steady-state
        serving recompiles per batch."""
        return (self.refine_ratio if self.refine_ratio > 1
                else RABITQ_DEFAULT_REFINE_RATIO)

    def margins(self, qdev) -> jax.Array:
        """Per-query difficulty margins from the coarse quantizer (the
        adaptive policy's input); only called when ``adaptive`` is
        set. One jitted shape per query bucket — warmup traces it."""
        mod = ivf_flat if self.algo == "ivf_flat" else ivf_pq
        return mod.coarse_margins(self.index, qdev,
                                  p=self.adaptive.margin_p)

    def rung_params(self, rung: Optional[int]):
        """(search_params, rabitq refine_ratio) for a probe rung.

        ``rung=None`` (the non-adaptive path, and the escape hatch's
        target when it equals the ceiling) returns the resolved params
        verbatim. A rung override replaces only ``n_probes`` — the
        trace key is the VALUE, so the top rung dispatches the exact
        program the non-adaptive path compiled (bitwise escape
        hatch). A rung on a NON-adaptive ivf handle is the shadow
        oracle's full-probe override (ISSUE 19) — same replace, same
        trace-key-is-the-value discipline."""
        if rung is None:
            return self.search_params, self.pipeline_rr()
        if rung == "exact":
            # ROADMAP 9(a): the shadow oracle's exact-tier rung —
            # exhaustive probing with the shortlist re-ranked from the
            # exact tier. n_probes carries the VALUE (n_lists), so the
            # trace-key-is-the-value discipline holds: an adaptive and
            # a non-adaptive handle compile the same program here.
            sp = dataclasses.replace(self.search_params,
                                     n_probes=int(self.index.n_lists))
            return sp, self.pipeline_rr()
        if self.adaptive is None:
            if self.algo in ("ivf_flat", "ivf_pq"):
                sp = dataclasses.replace(self.search_params,
                                         n_probes=int(rung))
                return sp, self.pipeline_rr()
            return self.search_params, self.pipeline_rr()
        pol = self.adaptive
        idx = pol.ladder.index(rung) if rung in pol.ladder \
            else len(pol.ladder) - 1
        sp = dataclasses.replace(self.search_params, n_probes=int(rung))
        return sp, pol.refine_for(idx)

    def oracle_rung(self) -> Optional[int]:
        """The shadow oracle's ground-truth rung (graft-gauge, ISSUE
        19): the index's FULL probe count when the serving ceiling sits
        below it, else None (the resolved exhaustive program already IS
        the top tier). The distinction matters for the under-trained-
        swap failure mode: a generation configured with a crippled
        ``n_probes`` would otherwise be its own oracle and score its
        own degraded answers as perfect. ivf_flat at ``n_lists`` probes
        is exact over the filtered index whatever the training quality;
        ivf_pq's refined pipeline reranks its shortlist with exact
        distances — both outrank any ceiling a bad swap can configure.
        brute_force/cagra have no probe axis to escalate.

        ROADMAP 9(a) bias fix: when the generation carries an EXACT
        tier (a tiered ``RerankSource`` or the raw row store), the
        oracle is the exact-rerank plan at exhaustive probing (the
        ``"exact"`` rung) — not the same quantizer's exhaustive rung. A
        quantized oracle scores its own quantization error as ground
        truth: the candidates IT mis-ranks look "matched" when serving
        mis-ranks them the same way, so recall over-estimates on
        ivf_pq/rabitq exactly where the estimate matters."""
        if self.algo not in ("ivf_flat", "ivf_pq"):
            return None
        n_lists = int(self.index.n_lists)
        cur = int(getattr(self.search_params, "n_probes", n_lists))
        if self.algo == "ivf_pq" and (
                getattr(self, "tiered_source", None) is not None
                or getattr(self, "raw_dataset", None) is not None):
            if n_lists > cur:
                return "exact"
            # ceiling already exhaustive: the exact tier still outranks
            # a quantized-only serving path (refine_ratio == 1); the
            # refined pipelines already ARE the exact-rerank program
            return "exact" if self.plan_variant(None) == "plain" else None
        return n_lists if n_lists > cur else None

    def raw_dev(self):
        """Device-resident raw row store (refine operand) — transferred
        once per generation, not per batch."""
        if self._raw_dev is None and self.raw_dataset is not None:
            self._raw_dev = jax.device_put(self.raw_dataset)
        return self._raw_dev

    # -- the per-algo search adapters -------------------------------------

    def search_main(self, qdev, k: int, filt: BitsetFilter,
                    rung: Optional[int] = None):
        """Search the main index through this generation's compiled
        query plan (ISSUE 20); ``rung`` (an adaptive probe-ladder
        value, or the shadow oracle's ``"exact"``) selects among the
        compiled plan variants — each overriding only ``n_probes``
        (and, on the rabitq pipeline, the per-rung refine_ratio), so
        the trace key stays the VALUE. ``rung=None`` is the
        exhaustive/non-adaptive path, byte-for-byte today's."""
        return self.compiled(int(k), rung)(qdev, prefilter=filt)

    def plan_variant(self, rung) -> str:
        """Which canonical serve plan (plan/canonical.py) this handle's
        configuration dispatches for ``rung`` — the same resolution
        order the hand-wired ``search_main`` branched through:
        tiered-source refined, rabitq refined (raw store else packed
        codes), raw-refine over-fetch, else the plain scan. The shadow
        oracle's ``"exact"`` rung is its own variant (same DAG as the
        tiered refined plan; the bias fix is in what the rung binds)."""
        if self.algo != "ivf_pq":
            return "plain"
        if rung == "exact":
            return "exact"
        kind = getattr(self.index, "cache_kind", "none")
        if self.tiered_source is not None and (
                kind == "rabitq" or self.refine_ratio > 1):
            # the tiered-memory shape (docs/serving.md §12): the raw
            # originals stay HOST-resident and the rerank stage fetches
            # only this batch's unique shortlist rows
            return "refined_tiered"
        if kind == "rabitq" and (
                self.raw_dataset is not None
                or int(self.index.codes.shape[-1]) > 0):
            # the rabitq rung IS a multi-stage pipeline: sign-bit first
            # stage + exact rerank. Rerank source: the generation's raw
            # row store when serving kept it, else the index's own PQ
            # codes.
            return ("refined_tiered" if self.raw_dataset is not None
                    else "refined_codes")
        if self.refine_ratio > 1 and self.raw_dataset is not None:
            return "raw_refine"
        return "plain"

    def compiled(self, k: int, rung=None):
        """The compiled plan for one (k, rung) point — cached per
        generation. The key carries the RESOLVED (n_probes,
        refine_ratio) pair, not just the rung, so a quality retune that
        moves a rung's refine ratio compiles a fresh program instead of
        serving a stale one (the hand-wired path re-resolved per call;
        the cache must not change that)."""
        sp, rr = self.rung_params(rung)
        key = (int(k), rung, getattr(sp, "n_probes", None), rr)
        cp = self._plan_cache.get(key)
        if cp is None:
            cp = self._compile_variant(int(k), rung, sp, rr)
            # benign publish race: concurrent threads compile identical
            # programs for the same key; last write wins
            self._plan_cache[key] = cp
        return cp

    def _compile_variant(self, k: int, rung, sp, rr: int):
        from raft_tpu import plan as _plan
        from raft_tpu.neighbors import tiered as _tiered

        variant = self.plan_variant(rung)
        p = _plan.serve_plan(self.algo, variant)
        source = None
        raw_dev = None
        refine_ratio = rr
        if variant in ("refined_tiered", "exact"):
            # the exact tier: the host tiered source when serving keeps
            # one, else the device-resident raw rows as a full-upload
            # source (bitwise-identical scoring either way)
            source = (self.tiered_source
                      if self.tiered_source is not None
                      else _tiered.as_source(self.raw_dev()))
        elif variant == "raw_refine":
            raw_dev = self.raw_dev()
            refine_ratio = self.refine_ratio
        extra = {"select_min": self.select_min}
        if self.algo == "hybrid":
            extra["fuse_expand"] = int(getattr(sp, "fuse_expand", 4))
        return _plan.compile(p, self.index, k=int(k), rung=rung,
                             search_params=sp,
                             refine_ratio=int(refine_ratio),
                             source=source, raw_dev=raw_dev,
                             memo=self._plan_memo, **extra)

    def side_index(self):
        """Brute-force index + device id map over the (padded) side
        buffer, cached per side-content seq — serving rebuilds it only
        when the side buffer's CONTENT changed (an upsert appended or a
        compaction shifted it), not on every mutation: a delete of base
        rows bumps the global ``seq`` for the tombstone bitsets but
        leaves the side vectors untouched, and must not force a
        brute-force rebuild + device re-upload here."""
        with self.state.lock:
            snap = self.side_snapshot_locked()
        return self.side_build(snap)

    def side_snapshot_locked(self) -> Optional[tuple]:
        """Cheap side-content snapshot; the caller must hold
        ``state.lock``. Split from :meth:`side_build` so the dispatcher
        can copy the side rows inside its consistency-pinned critical
        section but run the brute-force build + device upload AFTER
        releasing it — with the RLock held by the outer frame, doing
        both in :meth:`side_index` stalls every concurrent
        delete/upsert for the full build each side epoch."""
        st = self.state
        if st.side_cap == 0:
            return None
        hit = self._side_cache
        if hit is not None and hit[0] == st.side_seq:
            return hit                     # (seq, idx, ids_dev) — built
        return (st.side_seq, st.side_vecs.copy(), st.side_int.copy())

    def side_build(self, snap: Optional[tuple]):
        """Materialize a :meth:`side_snapshot_locked` result (lock-free
        for the expensive part)."""
        if snap is None:
            return None, None
        seq, a, b = snap
        if not isinstance(a, np.ndarray):  # cache hit: already built
            return a, b
        if self.algo == "hybrid":
            # column weights fold the fuse into the side scan: a plain
            # IP over weighted rows IS the fused score, so side hits
            # merge against main-index hits on the same scale
            a = a * hybrid.side_scale(self.index)[None, :]
        idx = brute_force.build(a, metric=self.metric)
        ids_dev = jax.device_put(b.astype(np.int32))
        with self.state.lock:
            self._side_cache = (seq, idx, ids_dev)
        return idx, ids_dev

    def k_ladder(self, max_k: int) -> Tuple[int, ...]:
        """k rungs this generation can serve: powers of two below
        ``max_k`` plus ``max_k`` itself as the top rung — submit admits
        any ``k <= max_k``, so the ladder must always have a rung that
        covers it (a pow2-only ladder under e.g. ``max_k=100`` would
        top out at 64 and fail every admitted k in (64, 100] at
        delivery). Each rung is capped by the index size (brute force
        rejects k > n)."""
        out: List[int] = []
        b = 1
        while b < max_k:
            out.append(min(b, self.rows))
            b <<= 1
        out.append(min(max_k, self.rows))
        return tuple(sorted(set(out)))

    def k_pad(self, k: int, max_k: int) -> int:
        ladder = self.k_ladder(max_k)
        for rung in ladder:
            if rung >= k:
                return rung
        return ladder[-1]


def _index_rows(algo: str, index) -> int:
    if algo == "cagra":
        return int(index.dataset.shape[0])
    return int(index.size)


def _index_dim(algo: str, index) -> int:
    return int(index.dim)


def _index_metric(algo: str, index):
    return resolve_metric(index.metric)


@functools.partial(jax.jit, static_argnums=(5, 6))
def _merge_with_side(d, i, sd, sp, side_int, k: int, select_min: bool):
    """Merge the main index's top-k with the side-buffer's: side result
    POSITIONS resolve to internal ids through the device id map, then one
    ``merge_topk`` keeps the global best-k. Invalid side slots (-1 /
    filtered) ride at the sentinel distance and sink."""
    si = jnp.where(
        sp >= 0,
        side_int[jnp.clip(sp, 0, side_int.shape[0] - 1)],
        jnp.int32(-1),
    )
    cd = jnp.concatenate([d, sd.astype(d.dtype)], axis=1)
    ci = jnp.concatenate([i.astype(jnp.int32), si], axis=1)
    return merge_topk(cd, ci, k, select_min)


class _ResultCache:
    """Bounded LRU result cache in front of dispatch (ISSUE 12,
    docs/serving.md §12): repeated queries — the Zipf head of real
    traffic — answered from host memory without touching the engine.

    Entries are keyed on ``(query bytes, k)`` and stamped with the
    ``(generation, mutation seq)`` pair they were computed under; a
    lookup only hits when BOTH still match the serving state, so a
    hot-swap (new generation) or a delete/upsert (seq bump)
    invalidates every stale answer implicitly. Stale entries are
    evicted on touch; capacity evicts least-recently-used."""

    def __init__(self, entries: int):
        self.entries = int(entries)
        from collections import OrderedDict

        self._od: "OrderedDict" = OrderedDict()
        self._lock = lockwatch.make_lock("serve.result_cache")

    def get(self, key, gen: int, epoch: int):
        with self._lock:
            v = self._od.get(key)
            if v is None:
                return None
            if v[0] != gen or v[1] != epoch:
                del self._od[key]          # stale: swap or mutation
                return None
            self._od.move_to_end(key)
            return v[2]

    def put(self, key, gen: int, epoch: int, value) -> None:
        with self._lock:
            self._od[key] = (gen, epoch, value)
            self._od.move_to_end(key)
            while len(self._od) > self.entries:
                self._od.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)


class _IndexServing:
    """One named index's serving unit: batcher + mutation overlay +
    dispatch/warmup logic against the shared registry."""

    def __init__(self, server: "Server", name: str):
        self.server = server
        self.name = name
        self.params = server.params
        self.registry = server.registry
        # effective warmup choice for THIS index: _install overwrites it
        # with the per-call override, and every later implicit warmup
        # (upsert re-warm, compaction, swap) honors it — a user who
        # opted out at create_index must not eat a full ladder compile
        # on their first growing upsert
        self.warmup_enabled = self.params.warmup
        # non-blocking acquire = atomic test-and-set: exactly one
        # compaction runs per index (released by the background thread).
        # A handoff FLAG, not a critical-section lock — see
        # lockwatch.make_flag_lock for why the sanitizer exempts it
        self.compacting = lockwatch.make_flag_lock("serve.compacting")
        self.result_cache = (_ResultCache(self.params.result_cache_entries)
                             if self.params.result_cache_entries > 0
                             else None)
        self.batcher = MicroBatcher(
            self._dispatch,
            max_batch_rows=self.params.max_batch_rows,
            max_wait_ms=self.params.max_wait_ms,
            max_queue_rows=self.params.max_queue_rows,
            shadow_queue_rows=self.params.shadow_queue_rows,
            name=name,
        )
        # graft-gauge (ISSUE 19): None when disabled, so the delivery
        # hook costs exactly one attribute read
        self.quality = (QualityMonitor(self, name)
                        if self.params.quality_sample_rate > 0 else None)
        # an OOM survivor recorded by an earlier server in this process
        # clamps the starting ceiling (same contract as the streaming
        # paths' budget names)
        ceiling = tuning.budget("serve_batch_rows",
                                self.batcher.max_batch_rows)
        if ceiling < self.batcher.max_batch_rows:
            self.batcher.set_ceiling(ceiling)
        # graft-flow dispatch pipeline (docs/serving.md §12): bounded
        # ticket queue between the batcher thread (async dispatch) and
        # a completion thread (sync + deliver). Each ticket carries its
        # OWN pinned generation — a hot swap or compaction can publish
        # a new generation while the ticket is in flight and the old
        # one stays alive until the ticket's release, exactly the
        # invalidation contract the synchronous path had.
        self.pipeline_depth = _pipeline.resolve_depth(
            self.params.pipeline_depth)
        self._pipe_cv = lockwatch.make_condition(
            lockwatch.make_lock("serve.pipeline"))
        self._pipe_q: collections.deque = collections.deque()
        self._pipe_thread: Optional[threading.Thread] = None
        self._pipe_stop = False

    # -- dispatch ----------------------------------------------------------

    def _pin_consistent(self):
        """Pin the current generation AND acquire its mutation lock such
        that (generation, mutation state) are a consistent pair — a
        compaction commits its side-buffer shift and publishes the
        extended generation under the same lock, so observing one
        without the other would drop the compacted rows for one batch."""
        for _ in range(8):
            gen = self.registry.pin(self.name)
            st = gen.handle.state
            # graft-lint: allow-unbalanced-acquire ownership transfer: _dispatch_once's finally releases st.lock with gen
            st.lock.acquire()
            if self.registry.get(self.name) is gen:
                return gen, st
            st.lock.release()
            gen.release()
        # a swap storm: serve from the latest pin anyway (its handle and
        # state are still a valid pair for a non-compaction swap)
        gen = self.registry.pin(self.name)
        st = gen.handle.state
        # graft-lint: allow-unbalanced-acquire ownership transfer: _dispatch_once's finally releases st.lock with gen
        st.lock.acquire()
        return gen, st

    # -- SLO-aware partition: deadline shed/downshift + split-by-rung ------

    def _partition(self, batch: Batch) -> List[Batch]:
        """Pre-dispatch policy pass (ISSUE 14, docs/serving.md §13):

        1. shed requests whose deadline can no longer be met (counted
           in ``serve.deadline_shed_total{action="shed"}``, failed with
           ``Overloaded(reason="deadline")`` — transient: the client's
           correct move is to re-budget and retry);
        2. on an adaptive handle, estimate each request's difficulty
           from the coarse margins and split the batch by chosen probe
           rung (the split-by-rung analog of the batcher's
           filter-homogeneous grouping); deadline pressure downshifts
           a request's rung before shedding it when
           ``deadline_action="downshift"``.

        Rung decisions happen on a PINNED generation but the pin drops
        before dispatch; a swap landing in between is safe — dispatch
        clamps ``n_probes`` to the new index's ``n_lists`` exactly like
        the non-adaptive path does.
        """
        if not batch.requests:
            return []
        gen = self.registry.pin(self.name)
        try:
            h: _Handle = gen.handle
            now = time.monotonic()
            live = self._shed_missed(batch, h, now)
            if not live:
                return []
            if batch.rung is not None or h.adaptive is None:
                # already rung-partitioned (a later part re-gated while
                # it queued behind its siblings, or an OOM re-split) /
                # non-adaptive: shed-only pass
                if len(live) == len(batch.requests):
                    return [batch]
                return [self._sub_batch(batch, live, rung=batch.rung)]
            return self._split_by_rung(h, batch, live, now)
        finally:
            gen.release()

    def _shed_missed(self, batch: Batch, h: "_Handle",
                     now: float) -> List[Request]:
        """Drop requests that would certainly miss their SLO: expired
        deadlines always; predicted misses (slack below the bucket's
        measured p95 service time) when ``deadline_action="shed"`` or
        when no adaptive ladder exists to downshift instead. Returns
        the surviving requests."""
        est_ms = None
        head_ms = _adaptive.deadline_headroom_ms()
        live: List[Request] = []
        for r in batch.requests:
            if r.future.done() or r.deadline is None:
                live.append(r)
                continue
            slack_ms = (r.deadline - now) * 1e3
            if (slack_ms > 0 and batch.rung is None
                    and h.adaptive is not None):
                # the rung assignment handles pressure (either mode:
                # _deadline_adjust downshifts or sheds at the rung the
                # policy actually chose, not the exhaustive estimate)
                live.append(r)
                continue
            if est_ms is None:
                est_ms = self.batcher.service_p95_ms(batch.bucket,
                                                     batch.rung)
            if slack_ms <= 0 or slack_ms < est_ms + head_ms:
                self._shed(r, slack_ms)
            else:
                live.append(r)
        return live

    def _shed(self, r: Request, slack_ms: float) -> None:
        obs.counter("serve.deadline_shed_total", index=self.name,
                    action="shed")
        obs_trace.finish(r.trace, status="rejected", reason="deadline",
                         deadline_slack_ms=round(slack_ms, 3))
        exc = Overloaded(
            f"serve[{self.name}]: deadline "
            f"(slack {slack_ms:.1f} ms cannot cover the measured "
            "service estimate)", reason="deadline")
        _rerrors.classify(exc)
        if not r.future.done():
            r.future.set_exception(exc)

    def _split_by_rung(self, h: "_Handle", batch: Batch,
                       live: List[Request], now: float) -> List[Batch]:
        """Assign each request a probe rung from its coarse margin and
        regroup the batch rung-homogeneously. The margins run at the
        batch's already-formed bucket shape (warmed), so the estimate
        itself adds no retrace."""
        pol = h.adaptive
        q = np.concatenate([r.queries for r in live], axis=0)
        q = pad_rows(np.ascontiguousarray(q, dtype=h.dtype), batch.bucket)
        # graft-lint: allow-host-sync rung choice regroups the batch on the host — the margins must land here before dispatch
        margins = np.asarray(h.margins(jax.device_put(q)))
        kq = h.k_pad(batch.k_max, self.params.max_k)
        groups: Dict[int, List[Request]] = {}
        row = 0
        for r in live:
            m = float(margins[row:row + r.rows].min())
            row += r.rows
            obs.observe("serve.difficulty_margin", m,
                        buckets=_MARGIN_BUCKETS, index=self.name)
            idx = pol.choose_idx(m, kq)
            if r.deadline is not None:
                idx = self._deadline_adjust(r, pol, idx, kq,
                                            batch.bucket, now)
                if idx is None:
                    continue
            groups.setdefault(idx, []).append(r)
        out: List[Batch] = []
        for idx in sorted(groups):
            rung = pol.rung(idx)
            obs.counter("serve.probe_rung", len(groups[idx]),
                        index=self.name, rung=str(rung))
            out.append(self._sub_batch(batch, groups[idx], rung=rung))
        return out

    def _deadline_adjust(self, r: Request, pol, idx: int, kq: int,
                         bucket: int, now: float) -> Optional[int]:
        """Fit a deadline request's rung to its slack: with
        ``deadline_action="downshift"``, drop one rung at a time while
        the (bucket, rung) service estimate exceeds the remaining
        budget, shedding when even the floor rung cannot make it; with
        ``"shed"``, never trade recall — shed as soon as the
        margin-chosen rung's estimate misses. Returns the adjusted
        ladder index, or None if the request was shed."""
        slack_ms = (r.deadline - now) * 1e3
        budget = slack_ms - _adaptive.deadline_headroom_ms()
        floor = pol.min_idx(kq)
        shifted = False
        if self.params.deadline_action == "downshift":
            while (idx > floor and
                   self.batcher.service_p95_ms(bucket, pol.rung(idx))
                   > budget):
                idx -= 1
                shifted = True
        if (slack_ms <= 0 or
                self.batcher.service_p95_ms(bucket, pol.rung(idx))
                > budget):
            self._shed(r, slack_ms)
            return None
        if shifted:
            obs.counter("serve.deadline_shed_total", index=self.name,
                        action="downshift")
        return idx

    def _sub_batch(self, batch: Batch, requests: List[Request],
                   rung: Optional[int]) -> Batch:
        rows = sum(r.rows for r in requests)
        return Batch(
            requests=requests, rows=rows,
            bucket=choose_bucket(self.batcher.ladder, rows,
                                 ceiling=self.batcher.ceiling),
            prefilter=batch.prefilter, seq=batch.seq,
            linger_ms=batch.linger_ms, rung=rung,
        )

    def _dispatch(self, batch: Batch) -> None:
        """Batcher callback: deadline shed + adaptive rung partition,
        then resilience-wrapped dispatch + OOM ladder per part. Each
        part retries/splits independently — a failure in one rung's
        sub-batch must not re-dispatch requests another rung already
        delivered."""
        if batch.shadow:
            self._dispatch_shadow(batch)
            return
        for i, part in enumerate(self._partition(batch)):
            if i:
                # later parts queued behind their siblings' device time:
                # re-gate so work whose budget the earlier parts burned
                # is shed instead of served certainly-late
                regated = self._partition(part)
                if not regated:
                    continue
                part = regated[0]
            self._dispatch_part(part)

    def _dispatch_part(self, batch: Batch, force_sync: bool = False) -> None:
        try:
            _rerrors.run(
                functools.partial(self._dispatch_once,
                                  force_sync=force_sync),
                batch,
                retries=self.params.dispatch_retries,
                backoff_s=self.params.retry_backoff_s,
            )
        except BaseException as e:  # noqa: BLE001 — classified right below
            kind = _rerrors.classify(e)
            if kind == _rerrors.OOM and len(batch.requests) > 1:
                self._downshift_and_split(batch, force_sync=force_sync)
                return
            if kind == _rerrors.OOM:
                # single request: record the learned ceiling anyway
                self._downshift(max(batch.bucket // 2, 1))
            for r in batch.requests:
                obs_trace.finish(r.trace, status="error", kind=kind,
                                 error=type(e).__name__)
                if not r.future.done():
                    r.future.set_exception(e)

    def _dispatch_shadow(self, batch: Batch) -> None:
        """graft-gauge's oracle re-run (ISSUE 19; docs/serving.md §14):
        answer each shadow sample EXHAUSTIVELY on the generation that
        served it, then hand the truth to the quality monitor for
        scoring.

        Trace discipline: the re-run is :meth:`_Handle.oracle_rung` —
        the resolved exhaustive program when the ceiling is already the
        full probe count, else the full-probe override warmup traced
        alongside the ladder — over the same padded buckets and
        k-ladder rungs as live dispatch, so a shadow batch can NEVER
        mint a new XLA trace. It runs synchronously on the batcher thread, which is
        idle by construction (the shadow lane only drains when both
        live lanes are empty); a failure is counted and swallowed —
        quality sampling must never take serving down with it."""
        mon = self.quality
        try:
            if mon is None:
                return
            # group by pinned generation: a hot-swap between two
            # samples' deliveries means one shadow batch can carry
            # samples from two generations, each of which must be
            # scored against ITS OWN index
            groups: List[Tuple[object, List[Request]]] = []
            for r in batch.requests:
                gen = r.shadow.gen
                if groups and groups[-1][0] is gen:
                    groups[-1][1].append(r)
                else:
                    groups.append((gen, [r]))
            for gen, reqs in groups:
                h: _Handle = gen.handle
                if h is None:      # impossible while pinned; belt+braces
                    continue
                st = h.state
                with st.lock:
                    if batch.prefilter is None:
                        main_bits = st.tombstone_bits()
                        side_bits = st.side_keep_bits()
                    else:
                        main_bits, side_bits = st.compose_user_filter(
                            batch.prefilter)
                    side_snap = h.side_snapshot_locked()
                side_idx, side_ids = h.side_build(side_snap)
                rows = sum(r.rows for r in reqs)
                sub = Batch(
                    requests=reqs, rows=rows,
                    bucket=choose_bucket(self.batcher.ladder, rows,
                                         ceiling=self.batcher.ceiling),
                    prefilter=batch.prefilter, seq=batch.seq,
                    rung=h.oracle_rung(), shadow=True)
                with obs.span("serve.shadow_batch", index=self.name,
                              bucket=sub.bucket, rows=rows,
                              generation=gen.version):
                    d, i = self._run_search(h, sub, main_bits,
                                            side_bits, side_idx,
                                            side_ids)
                    jax.block_until_ready((d, i))
                d = np.asarray(d)
                i = np.asarray(i)
                ext = st.translate_out(i.astype(np.int64)) \
                    if st.has_translation else i
                sent = np.inf if h.select_min else -np.inf
                ext = np.where(d == sent, np.asarray(-1, ext.dtype),
                               ext)
                mon.score_batch(sub, ext)
        except BaseException as e:  # noqa: BLE001 — quality is advisory: classify + count, never fail serving
            _rerrors.classify(e)
            obs.counter("serve.shadow_errors_total", index=self.name,
                        error=type(e).__name__)
        finally:
            for r in batch.requests:
                if r.shadow is not None:
                    r.shadow.gen.release()

    def _downshift(self, new_ceiling: int) -> None:
        new_ceiling = max(int(new_ceiling), self.batcher.ladder[0])
        # atomic monotone clamp: two concurrent OOM downshifts used to
        # race the ceiling read and the shallower one could win
        self.batcher.lower_ceiling(new_ceiling)
        tuning.record_budget("serve_batch_rows", new_ceiling)
        obs.counter("oom_ladder_downshifts", path="serve")
        obs.event("serve_downshift", index=self.name, ceiling=new_ceiling)

    def _downshift_and_split(self, batch: Batch,
                             force_sync: bool = False) -> None:
        """The serving OOM ladder: halve the bucket ceiling and re-dispatch
        the batch as two ladder-shaped halves (requests are the atomic
        unit — row-independent searches make the split result-identical)."""
        self._downshift(batch.bucket // 2)
        for r in batch.requests:
            # a retry stage, not a finish: the split halves re-dispatch
            # and each member trace completes at its half's delivery
            obs_trace.stage(r.trace, "retry", status="retry",
                            reason="oom_split", bucket=batch.bucket)
        half_rows = batch.rows // 2
        left: List = []
        rows = 0
        for r in batch.requests:
            if left and rows + r.rows > half_rows:
                break
            left.append(r)
            rows += r.rows
        right = batch.requests[len(left):]
        for part in (left, right):
            if not part:
                continue
            # rung rides along: the halves must re-dispatch at the rung
            # the policy already chose, not re-partition (the member
            # futures' policy decisions are final)
            self._dispatch_part(
                self._sub_batch(batch, part, rung=batch.rung),
                force_sync=force_sync)

    def _dispatch_once(self, batch: Batch,
                       force_sync: bool = False) -> None:
        pipelined = self.pipeline_depth > 0 and not force_sync
        gen, st = self._pin_consistent()
        handed_off = False
        try:
            h: _Handle = gen.handle
            try:
                # snapshot the mutation overlay while (generation, state)
                # are verified consistent; the device arrays captured here
                # are immutable, so the search itself runs lock-free
                if batch.prefilter is None:
                    main_bits = st.tombstone_bits()
                    side_bits = st.side_keep_bits()
                else:
                    main_bits, side_bits = st.compose_user_filter(
                        batch.prefilter)
                # snapshot only — the brute-force build + upload run
                # below, after the mutation lock drops
                side_snap = h.side_snapshot_locked()
            finally:
                st.lock.release()
            side_idx, side_ids = h.side_build(side_snap)
            t0 = time.perf_counter()
            with obs.span("serve.batch", index=self.name,
                          bucket=batch.bucket, rows=batch.rows,
                          rung=batch.rung, generation=gen.version,
                          pipelined=pipelined) as sp:
                # fault point: where a real device failure would surface.
                # Deliberately BEFORE the async handoff — injected faults
                # strike here on the batcher thread, inside
                # resilience.run, so retry and OOM-ladder semantics are
                # byte-for-byte those of the synchronous path at any
                # pipeline depth.
                faultinject.check(stage="serve.dispatch", chunk=batch.seq)
                d, i = self._run_search(
                    h, batch, main_bits, side_bits, side_idx, side_ids)
                if not pipelined:
                    jax.block_until_ready((d, i))
                sp.set(k_pad=int(d.shape[1]))
            if pipelined:
                # graft-flow handoff: the ticket owns the pin from here;
                # the completion thread syncs, records service time, and
                # delivers while this (batcher) thread pads + uploads +
                # gathers for the NEXT batch. XLA's async dispatch means
                # the device is already running this batch.
                self._pipe_put((batch, gen, h, d, i, t0))
                handed_off = True
                return
            latency_ms = (time.perf_counter() - t0) * 1e3
            # feed the deadline machinery's service estimate (the
            # batcher's linger slack test and _shed_missed read the
            # p95, keyed per rung — rungs differ by multiples)
            self.batcher.note_service_ms(batch.bucket, latency_ms,
                                         rung=batch.rung)
            self._deliver(batch, gen, h, np.asarray(d), np.asarray(i),
                          latency_ms)
        finally:
            if not handed_off:
                gen.release()

    # -- graft-flow completion pipeline (docs/serving.md §12) --------------

    def _pipe_put(self, ticket) -> None:
        """Enqueue an in-flight batch for the completion thread, blocking
        while ``pipeline_depth`` tickets are already outstanding — the
        backpressure that bounds device-queue depth (and pinned
        generations) exactly as the synchronous path did with one."""
        t0 = time.perf_counter()
        with self._pipe_cv:
            while (len(self._pipe_q) >= self.pipeline_depth
                   and not self._pipe_stop):
                self._pipe_cv.wait(0.05)
            waited_ms = (time.perf_counter() - t0) * 1e3
            if waited_ms >= 0.05:
                obs.observe("pipeline.stall_ms", waited_ms,
                            path="serve.dispatch")
            if self._pipe_stop:
                # close raced the dispatch: complete inline — the ticket
                # must never be dropped (its futures and pin would leak)
                pass
            else:
                self._pipe_q.append(ticket)
                obs.gauge("pipeline.occupancy", float(len(self._pipe_q)),
                          path="serve.dispatch")
                if self._pipe_thread is None or not self._pipe_thread.is_alive():
                    self._pipe_thread = threading.Thread(
                        target=self._complete_loop, daemon=True,
                        name=f"serve-pipe-{self.name}")
                    self._pipe_thread.start()
                self._pipe_cv.notify_all()
                return
        self._complete_ticket(ticket)

    def _complete_loop(self) -> None:
        while True:
            with self._pipe_cv:
                while not self._pipe_q and not self._pipe_stop:
                    self._pipe_cv.wait(0.05)
                if not self._pipe_q:
                    return                # stop + drained
                ticket = self._pipe_q.popleft()
                self._pipe_cv.notify_all()
            self._complete_ticket(ticket)

    def _complete_ticket(self, ticket) -> None:
        """Sync one in-flight batch and deliver it, releasing the
        ticket's generation pin. Error recovery mirrors
        ``_dispatch_part``'s classification: a REAL device failure that
        surfaces at the wait (injected faults never reach here — they
        strike pre-dispatch) re-dispatches the batch in FORCED-SYNC
        mode, so resilience.run's retry budget and the OOM
        split-ladder apply without this thread ever re-entering its own
        queue (the self-deadlock a recursive enqueue would be)."""
        batch, gen, h, d, i, t0 = ticket
        try:
            try:
                jax.block_until_ready((d, i))
            except BaseException as e:  # noqa: BLE001 — classified below
                kind = _rerrors.classify(e)
                if kind in (_rerrors.TRANSIENT, _rerrors.DEAD,
                            _rerrors.OOM):
                    for r in batch.requests:
                        obs_trace.stage(r.trace, "retry", status="retry",
                                        reason="pipeline_sync", kind=kind)
                    self._dispatch_part(batch, force_sync=True)
                    return
                for r in batch.requests:
                    obs_trace.finish(r.trace, status="error", kind=kind,
                                     error=type(e).__name__)
                    if not r.future.done():
                        r.future.set_exception(e)
                return
            latency_ms = (time.perf_counter() - t0) * 1e3
            self.batcher.note_service_ms(batch.bucket, latency_ms,
                                         rung=batch.rung)
            self._deliver(batch, gen, h, np.asarray(d), np.asarray(i),
                          latency_ms)
        except BaseException as e:  # noqa: BLE001 — must not kill the loop
            kind = _rerrors.classify(e)
            for r in batch.requests:
                if not r.future.done():
                    obs_trace.finish(r.trace, status="error", kind=kind,
                                     error=type(e).__name__)
                    r.future.set_exception(e)
        finally:
            gen.release()

    def close_pipeline(self, timeout_s: float = 30.0) -> None:
        """Drain outstanding tickets and join the completion thread.
        Called after the batcher closes (no new tickets can arrive);
        every queued ticket is still completed — futures resolve, pins
        release — before the thread exits."""
        with self._pipe_cv:
            self._pipe_stop = True
            self._pipe_cv.notify_all()
            t = self._pipe_thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)
        # a thread that never started (or died): drain inline
        while True:
            with self._pipe_cv:
                if not self._pipe_q:
                    break
                ticket = self._pipe_q.popleft()
            self._complete_ticket(ticket)

    def _run_search(self, h: _Handle, batch: Batch, main_bits: Bitset,
                    side_bits: Optional[Bitset], side_idx, side_ids):
        """The shape-stable search core (shared verbatim by warmup): pad
        rows on the HOST up to the bucket, search the main index under
        the composed keep-mask, then merge the side buffer's exact
        results."""
        q = np.concatenate([r.queries for r in batch.requests], axis=0) \
            if batch.requests else np.zeros((0, h.dim), h.dtype)
        q = pad_rows(np.ascontiguousarray(q, dtype=h.dtype), batch.bucket)
        qdev = jax.device_put(q)
        kq = h.k_pad(batch.k_max, self.params.max_k)
        d, i = h.search_main(qdev, kq, BitsetFilter(main_bits),
                             rung=batch.rung)
        if side_idx is not None:
            k_side = min(kq, side_idx.size)
            # graft-lint: allow-hand-wired-pipeline deliberate single-stage fast path: the side upsert buffer is a small exact scan merged after the main compiled plan, not a pipeline
            sd, sp = brute_force.search(
                side_idx, qdev, k_side,
                prefilter=None if side_bits is None
                else BitsetFilter(side_bits))
            d, i = _merge_with_side(d, i, sd, sp, side_ids, kq,
                                    h.select_min)
        return d, i

    def _deliver(self, batch: Batch, gen, h: _Handle,
                 d: np.ndarray, i: np.ndarray, latency_ms: float) -> None:
        row = 0
        ext = h.state.translate_out(i.astype(np.int64)) \
            if h.state.has_translation else i
        # a slot at the sentinel distance is a filtered-out (tombstoned)
        # or padding candidate that survived top-k only because fewer
        # than k live rows existed: brute_force._search and the side
        # merge keep such slots' REAL ids (ivf_* map them to -1 in the
        # kernel), so mask them here rather than hand a deleted row's id
        # to the client
        sent = np.inf if h.select_min else -np.inf
        ext = np.where(d == sent, np.asarray(-1, ext.dtype), ext)
        now = time.monotonic()
        for r in batch.requests:
            rd = d[row:row + r.rows, :r.k]
            ri = ext[row:row + r.rows, :r.k]
            row += r.rows
            r.future.generation = gen.version
            # remaining SLO budget at delivery: negative = a miss (the
            # request was served late rather than shed — counted so the
            # SLO harness can tell the two apart)
            slack_ms = None
            if r.deadline is not None:
                slack_ms = round((r.deadline - now) * 1e3, 3)
                if slack_ms < 0:
                    obs.counter("serve.deadline_miss_total",
                                index=self.name)
            # the shared device work, attributed to every member trace:
            # batch_seq is the span LINK (one batch serves many traces),
            # linger_ms the batching policy's share of the wait; rung /
            # deadline_slack_ms are the ISSUE-14 waterfall columns
            # (obs_report.py renders them per stage)
            obs_trace.stage(r.trace, "batch_search", ms=latency_ms,
                            bucket=batch.bucket, batch_seq=batch.seq,
                            linger_ms=round(batch.linger_ms, 3),
                            rung=batch.rung,
                            deadline_slack_ms=slack_ms,
                            generation=gen.version)
            if r.future.done():
                obs_trace.finish(r.trace, status="error",
                                 error="already_done")
                continue
            if rd.shape[1] < r.k:
                # a swap shrank the index below this request's k after
                # admission: fail loudly, never hand back fewer columns
                # than asked
                obs_trace.finish(r.trace, status="failed",
                                 error="k_exceeds_rows")
                r.future.set_exception(ValueError(
                    f"k={r.k} exceeds index rows={h.rows} after swap"))
            else:
                obs_trace.finish(r.trace, status="ok",
                                 generation=gen.version)
                r.future.set_result((rd, ri))
        obs.counter("serve.queries_total", batch.rows, index=self.name)
        obs.observe("serve.batch_latency_ms", latency_ms,
                    buckets=_LAT_BUCKETS, index=self.name,
                    bucket=str(batch.bucket))
        # graft-gauge sampling (ISSUE 19) — AFTER the futures resolved,
        # so the client's latency never includes it. Disabled: one
        # attribute read. Obs off: one module-attribute read (offer is
        # never entered).
        mon = self.quality
        if mon is not None and _obs_config.ENABLED:
            mon.offer(batch, gen, h, ext)

    # -- warmup ------------------------------------------------------------

    def warmup_handle(self, h: _Handle) -> int:
        """Trace every (bucket, k-rung[, probe-rung]) combination
        through the REAL dispatch core so steady-state serving never
        compiles — the adaptive ladder (ISSUE 14) adds the probe-rung
        axis, and the margin estimator itself is traced once per
        bucket. Returns the number of shapes warmed."""
        with obs.span("serve.warmup", index=self.name):
            st = h.state
            with st.lock:
                main_bits = st.tombstone_bits()
                side_bits = st.side_keep_bits()
            side_idx, side_ids = h.side_index()
            warmed = 0
            oom = False
            # rung=None is today's exhaustive program, and the ladder's
            # TOP rung dispatches the identical trace (same n_probes
            # value -> same program, the bitwise escape hatch) — skip
            # it outright so warmup pays for each distinct program
            # once, not the most expensive one twice per (bucket, k)
            rungs: List[Optional[int]] = [None]
            if h.adaptive is not None:
                rungs += list(h.adaptive.ladder[:-1])
            # graft-gauge (ISSUE 19): the shadow oracle's full-probe
            # override is one more program per (bucket, k) — warmed
            # here so a quality re-run can never retrace in steady
            # state (the distinct-VALUE trace key rule: when the
            # ceiling already equals n_lists, oracle_rung() is None
            # and the exhaustive program above covers it)
            orung = h.oracle_rung()
            if self.quality is not None and orung is not None:
                rungs.append(orung)
            for bucket in self.batcher.ladder:
                if oom:
                    break
                q = np.zeros((bucket, h.dim), h.dtype)
                if h.adaptive is not None:
                    # the difficulty estimator's own trace (per bucket)
                    jax.block_until_ready(
                        h.margins(jax.device_put(q)))
                for kq in h.k_ladder(self.params.max_k):
                    for rung in rungs:
                        if oom:
                            break
                        fake = Batch(requests=[], rows=bucket,
                                     bucket=bucket, prefilter=None,
                                     rung=rung)
                        fake.requests = [_warm_request(q, kq)]
                        try:
                            out = self._run_search(h, fake, main_bits,
                                                   side_bits, side_idx,
                                                   side_ids)
                            jax.block_until_ready(out)
                            warmed += 1
                            if (h.tiered_source is not None
                                    and h.algo == "ivf_pq"
                                    and (h.refine_ratio > 1 or getattr(
                                        h.index, "cache_kind", "none")
                                        == "rabitq"
                                        or rung == "exact")):
                                # tiered rerank: the fetched-block rung
                                # is data-dependent (unique shortlist
                                # rows), so trace the whole pow2 rung
                                # ladder for this (bucket, k, rung) —
                                # steady state then never compiles
                                # whatever the miss mix is
                                sp_r, rr_r = h.rung_params(rung)
                                kc = ivf_pq.refined_shortlist_width(
                                    sp_r, h.index, kq, rr_r)
                                h.tiered_source.warm(bucket, kc, kq,
                                                     h.metric)
                        except ValueError as e:
                            # a rung this index cannot serve (e.g. k
                            # beyond the probed candidate pool) fails
                            # identically at dispatch — nothing to
                            # warm, but a silently skipped rung voids
                            # the zero-recompile guarantee for that
                            # shape, so leave a signal naming which
                            # one and why
                            obs.counter("serve.warmup_skipped",
                                        index=self.name)
                            obs.event("serve_warmup_rung_skipped",
                                      index=self.name, bucket=bucket,
                                      k=kq, rung=rung, error=str(e))
                            continue
                        except Exception as e:  # noqa: BLE001 — only the classified-OOM kind is handled; the rest re-raise
                            if _rerrors.classify(e) != _rerrors.OOM:
                                raise
                            # device OOM tracing this rung: at dispatch
                            # the ladder would halve the ceiling and
                            # keep serving — do the same here, so a
                            # server whose top bucket doesn't fit
                            # still comes up serving the buckets that
                            # do (larger rungs can only OOM harder)
                            self._downshift(bucket // 2)
                            obs.event("serve_warmup_oom",
                                      index=self.name, bucket=bucket,
                                      k=kq)
                            oom = True
                            break
            obs.counter("serve.warmup_shapes", warmed, index=self.name)
            return warmed


def _warm_request(q: np.ndarray, k: int) -> Request:
    return Request(queries=q, k=k, prefilter=None, future=Future())


class Server:
    """The online serving engine (ISSUE 5 tentpole; docs/serving.md).

    One ``Server`` hosts any number of named indexes, each with its own
    micro-batcher, versioned generations, and tombstone overlay::

        srv = serve.Server()
        srv.create_index("vectors", dataset, algo="ivf_flat")
        fut = srv.submit(queries, k=10, index="vectors")
        dists, ids = fut.result()
        srv.delete([3, 17], index="vectors")
        srv.swap("vectors", dataset=new_dataset)     # background + atomic
        srv.close()
    """

    def __init__(self, params: Optional[ServeParams] = None):
        self.params = params or ServeParams()
        self.registry = Registry()
        self._servings: Dict[str, _IndexServing] = {}
        # graft-race sanitizer node "serve.engine"
        self._lock = lockwatch.make_lock("serve.engine")
        self._closed = False

    # -- index lifecycle ---------------------------------------------------

    def create_index(self, name: str, dataset, algo: str = "brute_force",
                     build_params=None, search_params=None,
                     ids=None, refine_ratio: int = 1,
                     warmup: Optional[bool] = None):
        """Build ``algo`` over ``dataset`` in-process and publish it as
        generation 1 of ``name`` (warming the trace ladder first unless
        disabled). ``ids`` optionally names rows with external ids
        (default: row positions)."""
        with obs.span("serve.create_index", index=name, algo=algo):
            dataset = np.ascontiguousarray(np.asarray(dataset),
                                           dtype=np.float32)
            index = _build_index(algo, dataset, build_params)
            return self._install(name, algo, index, dataset, build_params,
                                 search_params, ids, refine_ratio, warmup)

    def add_index(self, name: str, index, algo: str, dataset=None,
                  build_params=None, search_params=None, ids=None,
                  refine_ratio: int = 1, warmup: Optional[bool] = None):
        """Publish a prebuilt index object under ``name``."""
        with obs.span("serve.add_index", index=name, algo=algo):
            ds = None if dataset is None else np.ascontiguousarray(
                np.asarray(dataset), dtype=np.float32)
            return self._install(name, algo, index, ds, build_params,
                                 search_params, ids, refine_ratio, warmup)

    def load_index(self, name: str, path: str, algo: str,
                   search_params=None, refine_ratio: int = 1,
                   warmup: Optional[bool] = None):
        """Load a ``core/serialize`` snapshot and publish it — the
        cold-start / cross-process half of the hot-swap protocol."""
        with obs.span("serve.load_index", index=name, algo=algo):
            index = _ALGO_MODULES[algo].load(path)
            return self._install(name, algo, index, None, None,
                                 search_params, None, refine_ratio, warmup)

    def _install(self, name, algo, index, dataset, build_params,
                 search_params, ids, refine_ratio, warmup):
        if algo not in ALGOS:
            raise ValueError(f"algo must be one of {ALGOS}, got {algo!r}")
        rows = _index_rows(algo, index)
        dim = _index_dim(algo, index)
        state = MutableState(
            rows, dim, np.float32, ext_ids=ids,
            side_capacity=self.params.side_capacity,
        )
        raw = _raw_dataset(algo, index, dataset)
        sp = _default_search_params(algo, index, search_params)
        h = _Handle(algo, index, state, sp,
                    build_params, refine_ratio, raw,
                    user_search_params=search_params,
                    tiered_source=self._make_tiered(algo, raw),
                    adaptive=self._make_adaptive(algo, index, sp,
                                                 refine_ratio))
        with self._lock:
            # checked under the SAME lock that registers the serving: a
            # close() racing the unlocked gap would snapshot _servings
            # without this entry and leave its batcher thread running
            # forever
            if self._closed:
                raise RuntimeError("server is closed")
            serving = self._servings.get(name)
            if serving is None:
                serving = _IndexServing(self, name)
                self._servings[name] = serving
        serving.warmup_enabled = warmup if warmup is not None \
            else self.params.warmup
        if serving.warmup_enabled:
            serving.warmup_handle(h)
        gen = self._publish_guarded(name, h)
        return gen.version

    def _make_tiered(self, algo: str, raw: Optional[np.ndarray]):
        """A per-generation tiered rerank source over the host raw row
        store (None unless ``tiered_rerank`` is on and this algo can
        use it). Fresh per generation: compaction/swap content changes
        must not serve a predecessor's hot rows."""
        if (not self.params.tiered_rerank or algo != "ivf_pq"
                or raw is None):
            return None
        from raft_tpu.neighbors import tiered

        return tiered.HostArraySource(
            raw, hot_rows=self.params.tiered_hot_rows)

    def _make_adaptive(self, algo: str, index, search_params,
                       refine_ratio: int):
        """Build the per-generation adaptive policy (ISSUE 14;
        docs/serving.md §13) — None unless ``adaptive_probes`` is on
        and the algo has a coarse quantizer to read margins from.

        The ladder's CEILING is the generation's resolved ``n_probes``:
        the ``_default_search_params`` pin (``n_probes = n_lists``) is
        thereby demoted from "the" probe count to the exhaustive top
        rung, and an explicit user ``n_probes`` caps the ladder at the
        user's own budget. Derived per generation, so a swap re-derives
        the whole LADDER against the new index — not just the ceiling
        (the regression test pins top-rung == new ``n_lists``)."""
        if (not self.params.adaptive_probes
                or algo not in _adaptive.ADAPTIVE_ALGOS):
            return None
        ceiling = int(min(int(search_params.n_probes), index.n_lists))
        if ceiling < 2:
            return None              # a 1-list index has nothing to adapt
        if algo == "ivf_flat":
            list_cap = int(index.storage.shape[1])
        else:
            list_cap = int(index.indices.shape[1])
        rr = (int(refine_ratio) if int(refine_ratio) > 1
              else RABITQ_DEFAULT_REFINE_RATIO
              if getattr(index, "cache_kind", "none") == "rabitq" else 1)
        return _adaptive.AdaptivePolicy.build(ceiling, list_cap,
                                              refine_ratio=rr)

    def _publish_guarded(self, name: str, h: "_Handle"):
        """Publish under the server lock: a background build finishing
        after :meth:`close` must not resurrect the name — a generation
        published then would hold its device arrays with nothing left to
        retire it."""
        with self._lock:
            if self._closed:
                raise RuntimeError("server is closed")
            serving = self._servings.get(name)
            mon = serving.quality if serving is not None else None
            # graft-gauge swap probation (ISSUE 19): pin + baseline the
            # outgoing generation BEFORE publish retires it — the
            # rollback path needs its handle alive until the successor
            # proves itself. Deliberately NOT hooked into compaction's
            # direct registry.publish: a compaction folds the same
            # content, so its predecessor is no quality baseline.
            if mon is not None:
                mon.before_publish()
            gen = self.registry.publish(name, h)
            if mon is not None:
                mon.after_publish(gen)
            return gen

    # -- the data plane ----------------------------------------------------

    def submit(self, queries, k: int, *, index: str = "default",
               prefilter=None,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue a search; returns a Future resolving to host
        ``(distances [rows, k], external ids [rows, k])``. ``queries``
        is one query ``[dim]`` or a block ``[rows, dim]`` answered
        together. ``deadline_ms`` (or ``ServeParams.deadline_ms``)
        attaches an SLO deadline: the request rides the batcher's
        priority lane, skips linger when its slack runs out, and is
        shed with ``Overloaded(reason="deadline")`` — or downshifted a
        probe rung — when it would certainly miss (docs/serving.md
        §13). Raises :class:`Overloaded` when the bounded queue (or a
        per-index admission quota) is full (classified transient —
        back off and retry)."""
        with obs.span("serve.request", index=index):
            q = np.asarray(queries, dtype=np.float32)
            if q.ndim == 1:
                q = q[None, :]
            if q.ndim != 2:
                raise ValueError(f"queries must be [dim] or [rows, dim], "
                                 f"got shape {q.shape}")
            if not 0 < int(k) <= self.params.max_k:
                raise ValueError(
                    f"k={k} outside (0, max_k={self.params.max_k}]")
            serving = self._serving(index)
            gen = self.registry.get(index)
            handle = gen.handle if gen is not None else None
            # closed is read AFTER the registry lookup: a close() that
            # drained the registry between the two must surface as the
            # fatal `closed` rejection (batcher-side), never as a
            # transient not_ready a well-behaved client would retry
            # against a permanently closed server
            with self._lock:
                closed = self._closed
            if handle is None and not closed:
                # create_index/add_index registers the serving BEFORE its
                # first publish, and warmup can hold that window open for
                # minutes — a request admitted now would skip the k/dim
                # door checks below and fail later with the dispatcher's
                # internal KeyError instead of a retryable rejection
                obs.counter("serve.rejects_total", index=index,
                            reason="not_ready")
                exc = Overloaded(
                    f"serve[{index}]: not_ready "
                    "(first generation still building/warming)",
                    reason="not_ready",
                )
                _rerrors.classify(exc)
                raise exc
            if handle is not None and int(k) > handle.rows:
                # the k-ladder caps at the index size, so this request
                # would be silently truncated at delivery — reject it at
                # the door instead
                raise ValueError(
                    f"k={k} exceeds index rows={handle.rows}")
            if handle is not None and q.shape[1] != handle.dim:
                # a wrong-width query would fail the whole coalesced
                # batch at dispatch (np.concatenate), taking innocent
                # requests down with it — reject it at the door
                raise ValueError(
                    f"query dim {q.shape[1]} != index dim {handle.dim}")
            self._check_quota(serving, index, int(q.shape[0]))
            if deadline_ms is None:
                deadline_ms = self.params.deadline_ms
            deadline = (time.monotonic() + float(deadline_ms) / 1e3
                        if deadline_ms is not None else None)
            if (serving.result_cache is not None and prefilter is None
                    and handle is not None):
                return self._submit_cached(serving, handle, gen, q,
                                           int(k), index,
                                           deadline=deadline)
            return serving.batcher.submit(q, int(k), prefilter=prefilter,
                                          deadline=deadline)

    def _check_quota(self, serving: "_IndexServing", index: str,
                     rows: int) -> None:
        """Multi-tenant admission (docs/serving.md §13): per-index
        pending-row quotas and the server-wide total bound, both atop
        the batcher's own max_queue_rows backpressure. Advisory
        check-then-act (the hard bound stays the batcher's bounded
        queue): two racing submits can both pass a nearly-full quota —
        by at most one batch's rows, which the hard bound still caps."""
        p = self.params
        quota = (p.admission_quotas or {}).get(index)
        if quota is not None and \
                serving.batcher.depth_rows() + rows > int(quota):
            self._reject_quota(index, rows, f"index quota {quota}")
        if p.max_total_queue_rows is not None:
            with self._lock:
                servings = list(self._servings.values())
            total = sum(s.batcher.depth_rows() for s in servings)
            if total + rows > int(p.max_total_queue_rows):
                self._reject_quota(
                    index, rows,
                    f"server-wide quota {p.max_total_queue_rows}")

    def _reject_quota(self, index: str, rows: int, detail: str) -> None:
        obs.counter("serve.rejects_total", index=index, reason="quota")
        exc = Overloaded(
            f"serve[{index}]: quota ({rows} rows would exceed {detail})",
            reason="quota")
        _rerrors.classify(exc)
        raise exc

    def _submit_cached(self, serving: "_IndexServing", handle: "_Handle",
                       gen, q: np.ndarray, k: int, index: str,
                       deadline: Optional[float] = None) -> Future:
        """The result-cache front (docs/serving.md §12): answer a
        repeated (query, k) from host memory when nothing changed since
        it was computed; otherwise submit and install the answer once
        it delivers — only if the serving state is STILL the one the
        key was stamped with (a swap or mutation racing the in-flight
        request must not be cached under the older stamp)."""
        cache = serving.result_cache
        key = (q.tobytes(), k)
        with handle.state.lock:
            epoch = handle.state.seq
        gen_v = gen.version
        hit = cache.get(key, gen_v, epoch)
        if hit is not None:
            obs.counter("serve.result_cache_hits_total", index=index)
            fut: Future = Future()
            fut.generation = gen_v
            # hand back COPIES: a caller mutating its result in place
            # must not poison every later hit
            fut.set_result((hit[0].copy(), hit[1].copy()))
            return fut
        obs.counter("serve.result_cache_misses_total", index=index)
        fut = serving.batcher.submit(q, k, prefilter=None,
                                     deadline=deadline)

        def _install(f: Future) -> None:
            if f.exception() is not None:
                return
            if getattr(f, "generation", None) != gen_v:
                return                    # answered by a newer swap
            try:
                cur = self.registry.get(index)
                if cur is None or cur.version != gen_v:
                    return
                st = cur.handle.state
                with st.lock:
                    if st.seq != epoch:
                        return            # a mutation landed in flight
            except Exception:  # noqa: BLE001 — cache-insert probe only; a torn-down registry just skips the insert
                return
            d, i = f.result()
            cache.put(key, gen_v, epoch, (d.copy(), i.copy()))

        fut.add_done_callback(_install)
        return fut

    def search(self, queries, k: int, *, index: str = "default",
               prefilter=None, timeout_s: Optional[float] = None,
               deadline_ms: Optional[float] = None):
        """Blocking convenience over :meth:`submit`."""
        with obs.span("serve.search", index=index):
            fut = self.submit(queries, k, index=index, prefilter=prefilter,
                              deadline_ms=deadline_ms)
            return fut.result(timeout=timeout_s
                              if timeout_s is not None
                              else self.params.request_timeout_s)

    # -- mutation ----------------------------------------------------------

    def delete(self, ids, *, index: str = "default") -> int:
        """Tombstone rows by external id; takes effect on the next batch
        (the keep-mask composes with any user prefilter). Returns the
        number of rows that were live."""
        with obs.span("serve.delete", index=index):
            self._serving(index)
            # pin: a concurrent swap retiring the generation must not
            # drain its handle out from under the mutation
            gen = self._pin(index)
            try:
                st = gen.handle.state
                n = st.delete(ids)
                obs.counter("serve.deletes_total", n, index=index)
                obs.gauge("serve.tombstoned_rows", st.deleted_rows(),
                          index=index)
                return n
            finally:
                gen.release()

    def upsert(self, vectors, ids, *, index: str = "default") -> int:
        """Insert-or-replace vectors under external ``ids``: old rows are
        tombstoned, new rows land in the brute-force side buffer (merged
        into every search) until compaction folds them into the main
        index. Returns the side-buffer occupancy."""
        with obs.span("serve.upsert", index=index):
            serving = self._serving(index)
            # pin: a concurrent swap retiring the generation must not
            # drain its handle out from under the mutation
            gen = self._pin(index)
            try:
                h: _Handle = gen.handle
                v = np.asarray(vectors)
                n_rows = 1 if v.ndim == 1 else int(v.shape[0])
                side_rows, grew = h.state.upsert(v, ids)
                obs.counter("serve.upserts_total", n_rows, index=index)
                obs.gauge("serve.side_rows", side_rows, index=index)
                if grew and serving.warmup_enabled:
                    # a traced shape grew (side capacity, or the filter
                    # capacity rung crossed a pow2 boundary): re-warm so
                    # serving goes back to zero-compile steady state
                    serving.warmup_handle(h)
            finally:
                gen.release()
            if (self.params.compact_threshold
                    and side_rows >= self.params.compact_threshold):
                self.compact(index=index)
            return side_rows

    def compact(self, *, index: str = "default",
                wait: bool = False) -> Optional[Future]:
        """Fold the side buffer into the main index: background
        ``extend`` (or full rebuild for graph indexes) + warmup + atomic
        swap; the tombstone mask carries over (deleted rows stay
        tombstoned inside the extended index until the next full swap).
        No-op when the side buffer is empty."""
        with obs.span("serve.compact", index=index):
            serving = self._serving(index)
            if not serving.compacting.acquire(blocking=False):
                return None
            fut: Future = Future()

            def _run():
                try:
                    fut.set_result(self._compact_sync(serving))
                except BaseException as e:  # noqa: BLE001 — handed to the future; classified by resilience inside
                    _rerrors.classify(e)
                    fut.set_exception(e)
                finally:
                    serving.compacting.release()

            t = threading.Thread(target=_run, daemon=True,
                                 name=f"raft-tpu-serve-compact-{index}")
            t.start()
            if wait:
                fut.result()
            return fut

    def _compact_sync(self, serving: _IndexServing) -> int:
        name = serving.name
        gen = self._pin(name)
        try:
            h: _Handle = gen.handle
            st = h.state
            ticket = st.begin_compaction()
            if ticket is None:
                return self.registry.version(name)
            with obs.span("serve.compact_build", index=name,
                          rows=ticket.count):
                new_index, new_raw = _extend_index(
                    h, ticket.vectors, ticket.int_ids)
                # extend keeps n_lists, so the resolved params stay
                # valid; the raw user params ride along for later swaps
                new_h = _Handle(h.algo, new_index, st, h.search_params,
                                h.build_params, h.refine_ratio, new_raw,
                                user_search_params=h.user_search_params,
                                tiered_source=self._make_tiered(
                                    h.algo, new_raw),
                                adaptive=self._make_adaptive(
                                    h.algo, new_index, h.search_params,
                                    h.refine_ratio))
                if serving.warmup_enabled:
                    serving.warmup_handle(new_h)
                # commit + publish under the mutation lock: a dispatcher
                # pins (generation, state) as a consistent pair, so the
                # side-buffer shift and the extended index appear
                # atomically. self._lock nests inside (never the reverse
                # order anywhere), serializing against close().
                with st.lock, self._lock:
                    if self._closed:
                        obs.event("compaction_aborted", index=name,
                                  reason="server_closed")
                        return self.registry.version(name)
                    if self.registry.get(name) is not gen:
                        # a content swap superseded the generation this
                        # extend was built from — publishing would revert
                        # it to pre-swap data. Abort; the swap reset the
                        # overlay, so the snapshot is moot.
                        obs.event("compaction_aborted", index=name,
                                  reason="superseded_by_swap")
                        return self.registry.version(name)
                    st.commit_compaction(ticket)
                    v = self.registry.publish(name, new_h).version
                obs.counter("serve.compactions_total", index=name)
                return v
        finally:
            gen.release()

    # -- hot swap ----------------------------------------------------------

    def swap(self, name: str = "default", *, dataset=None, prebuilt=None,
             path=None, algo: Optional[str] = None, build_params=None,
             search_params=None, ids=None,
             refine_ratio: Optional[int] = None,
             wait: bool = False) -> Future:
        """Replace ``name``'s content with a freshly built/loaded index —
        in the background, then one atomic generation swap. In-flight
        batches finish on the old generation; it drains (and frees) when
        their pins drop. Exactly one of ``dataset`` (in-process build),
        ``prebuilt`` (an already-built index object), or ``path``
        (``core/serialize`` snapshot). The kwarg is ``prebuilt``, NOT
        ``index``, on purpose: every other Server method spells the
        index *name* ``index=``, so an ``index=`` here would make the
        habitual ``srv.swap(index="vectors")`` silently target
        "default" and hand the name string to the build thread.

        The mutable overlay RESETS with the new content (a swap is a
        wholesale replacement; use :meth:`compact` to fold mutations in
        instead)."""
        with obs.span("serve.swap", index=name):
            serving = self._serving(name)
            # pin for the handle read: an unpinned registry.get().handle
            # races a concurrent swap's drain (handle nulled) and raises
            # AttributeError after close() instead of KeyError. The local
            # `h` keeps the _Handle itself alive for the build thread.
            cur = self._pin(name)
            try:
                h: _Handle = cur.handle
            finally:
                cur.release()
            a = algo or h.algo
            fut: Future = Future()

            def _run():
                try:
                    if path is not None:
                        new_index = _ALGO_MODULES[a].load(path)
                        ds = None
                    elif prebuilt is not None:
                        new_index, ds = prebuilt, dataset
                    else:
                        ds = np.ascontiguousarray(np.asarray(dataset),
                                                  dtype=np.float32)
                        new_index = _build_index(
                            a, ds, build_params
                            if build_params is not None else h.build_params)
                    rows = _index_rows(a, new_index)
                    dim = _index_dim(a, new_index)
                    state = MutableState(
                        rows, dim, np.float32, ext_ids=ids,
                        side_capacity=self.params.side_capacity)
                    # inherit the caller's RAW params (not the resolved
                    # ones): defaulted n_probes = n_lists must be
                    # re-derived from the NEW index, or a swap to a
                    # bigger dataset silently clamps probing at the old
                    # index's n_lists and serves non-exhaustive results
                    # — and with adaptive_probes on, the whole probe
                    # LADDER re-derives from the re-resolved ceiling
                    # (not just the ceiling itself), so a bigger
                    # successor's top rung is its own n_lists
                    sp_user = (search_params if search_params is not None
                               else h.user_search_params
                               if a == h.algo else None)
                    new_raw = _raw_dataset(a, new_index, ds)
                    sp_new = _default_search_params(a, new_index, sp_user)
                    rr_new = (refine_ratio if refine_ratio is not None
                              else h.refine_ratio)
                    new_h = _Handle(
                        a, new_index, state, sp_new,
                        build_params if build_params is not None
                        else h.build_params,
                        rr_new,
                        new_raw,
                        user_search_params=sp_user,
                        tiered_source=self._make_tiered(a, new_raw),
                        adaptive=self._make_adaptive(a, new_index,
                                                     sp_new, rr_new))
                    if serving.warmup_enabled:
                        serving.warmup_handle(new_h)
                    gen = self._publish_guarded(name, new_h)
                    fut.set_result(gen.version)
                except BaseException as e:  # noqa: BLE001 — handed to the future; classified for obs/flight
                    _rerrors.classify(e)
                    fut.set_exception(e)

            t = threading.Thread(target=_run, daemon=True,
                                 name=f"raft-tpu-serve-swap-{name}")
            t.start()
            if wait:
                fut.result()
            return fut

    # -- introspection / lifecycle ----------------------------------------

    def warmup(self, index: str = "default") -> int:
        """(Re)trace the serving ladder for ``index``'s current
        generation; returns the number of shapes warmed."""
        with obs.span("serve.warmup_entry", index=index):
            serving = self._serving(index)
            # pinned: the generation cannot drain (and null its handle)
            # while the warmup sweep is tracing against it
            gen = self._pin(index)
            try:
                return serving.warmup_handle(gen.handle)
            finally:
                gen.release()

    def generation(self, index: str = "default") -> int:
        return self.registry.version(index)

    def stats(self, index: str = "default") -> dict:
        gen = self.registry.get(index)
        serving = self._servings.get(index)
        handle = gen.handle if gen is not None else None  # single read: a
        #                       concurrent drain nulls it between accesses
        st = handle.state if handle is not None else None
        return {
            "generation": self.registry.version(index),
            "queue_rows": serving.batcher.depth_rows() if serving else 0,
            "bucket_ceiling": serving.batcher.ceiling if serving else 0,
            "ladder": list(serving.batcher.ladder) if serving else [],
            "live_rows": st.live_rows() if st else 0,
            "tombstoned_rows": st.deleted_rows() if st else 0,
            "side_rows": st.side_rows_live() if st else 0,
            "generations_live": len(self.registry.live_generations()),
            "probe_ladder": (list(handle.adaptive.ladder)
                             if handle is not None
                             and handle.adaptive is not None else None),
            "quality": (serving.quality.stats()
                        if serving is not None
                        and serving.quality is not None else None),
        }

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop admissions, drain every queue, retire every index."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            servings = list(self._servings.values())
        for s in servings:
            s.batcher.close(timeout_s=timeout_s)
        for s in servings:
            # after the batcher drains no new tickets can arrive; now
            # drain the graft-flow completion queue so every in-flight
            # batch resolves its futures and releases its pin
            s.close_pipeline(timeout_s=timeout_s)
        for s in servings:
            # shadow samples still queued at close are dropped, not
            # dispatched — their generation pins (and the probation
            # pin) must release or the retired generations never drain
            if s.quality is not None:
                s.quality.close(s.batcher.drain_shadow())
        for name in self.registry.names():
            self.registry.drop(name)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _serving(self, name: str) -> _IndexServing:
        with self._lock:
            s = self._servings.get(name)
        if s is None:
            raise KeyError(
                f"no index named {name!r}; create_index/add_index first")
        return s

    def _pin(self, name: str):
        """Pin ``name``'s current generation, diagnosing a closed server
        correctly: close() drops every registry name, so a bare
        registry.pin after close raises KeyError claiming the index was
        never published — the truthful, fail-fast signal is 'server is
        closed' (the submit path's Overloaded(reason="closed")
        analog for the mutation/warmup entry points)."""
        try:
            return self.registry.pin(name)
        except KeyError:
            with self._lock:
                closed = self._closed
            if closed:
                raise RuntimeError("server is closed") from None
            raise


# ---------------------------------------------------------------------------
# per-algo construction adapters
# ---------------------------------------------------------------------------

_ALGO_MODULES = {
    "brute_force": brute_force,
    "ivf_flat": ivf_flat,
    "ivf_pq": ivf_pq,
    "cagra": cagra,
    "hybrid": hybrid,
}


def _build_index(algo: str, dataset: np.ndarray, build_params):
    if algo == "hybrid":
        if build_params is None:
            raise ValueError(
                "algo='hybrid' needs build_params=hybrid.IndexParams("
                "dense_dim=...) — the engine cannot guess where the "
                "dense columns end and the vocab begins")
        return hybrid.build(build_params, dataset)
    if algo == "brute_force":
        if build_params is None:
            return brute_force.build(dataset)
        return brute_force.build(dataset, metric=build_params.metric,
                                 metric_arg=build_params.metric_arg)
    if build_params is None:
        n = dataset.shape[0]
        if algo == "ivf_flat":
            build_params = ivf_flat.IndexParams(
                n_lists=max(1, min(64, n // 32)))
        elif algo == "ivf_pq":
            build_params = ivf_pq.IndexParams(
                n_lists=max(1, min(64, n // 32)))
        else:
            build_params = cagra.IndexParams()
    return _ALGO_MODULES[algo].build(build_params, dataset)


def _default_search_params(algo: str, index, search_params):
    if search_params is not None:
        return search_params
    if algo == "ivf_flat":
        # serving default: exhaustive probing — exact recall over the
        # tombstone-filtered index, the contract the correctness
        # acceptance tests pin. With ServeParams.adaptive_probes this
        # pin is the adaptive ladder's exhaustive CEILING, not the
        # per-query probe count: easy queries serve from lower rungs
        # and ambiguous ones escape back up to exactly this program
        # (ISSUE 14; docs/serving.md §13)
        return ivf_flat.SearchParams(n_probes=index.n_lists,
                                     compute_dtype="f32",
                                     local_recall_target=1.0)
    if algo == "ivf_pq":
        return ivf_pq.SearchParams(n_probes=index.n_lists,
                                   local_recall_target=1.0)
    if algo == "cagra":
        return cagra.SearchParams(itopk_size=128)
    if algo == "hybrid":
        return hybrid.SearchParams()
    return None


def _raw_dataset(algo: str, index, dataset: Optional[np.ndarray]):
    """The raw row store serving keeps for refine + graph rebuilds,
    indexed by internal id. brute_force/cagra carry it on the index."""
    if algo in ("brute_force", "cagra"):
        return np.asarray(index.dataset)
    return dataset


def _extend_index(h: _Handle, vectors: np.ndarray, int_ids: np.ndarray):
    """Compaction build: fold side rows into the main index. ivf_* use
    the module ``extend``; brute_force/cagra (positional ids) rebuild
    over the concatenated row store. Returns (new_index, new_raw)."""
    algo = h.algo
    if algo == "ivf_flat":
        new = ivf_flat.extend(h.index, vectors,
                              int_ids.astype(np.int32))
        raw = None if h.raw_dataset is None else np.concatenate(
            [h.raw_dataset, vectors], axis=0)
        return new, raw
    if algo == "ivf_pq":
        new = ivf_pq.extend(h.index, vectors, int_ids.astype(np.int32))
        raw = None if h.raw_dataset is None else np.concatenate(
            [h.raw_dataset, vectors], axis=0)
        return new, raw
    full = np.concatenate([np.asarray(h.raw_dataset), vectors], axis=0)
    if algo == "brute_force":
        return brute_force.build(full, metric=h.metric,
                                 metric_arg=h.index.metric_arg), full
    if algo == "hybrid":
        return hybrid.build(h.build_params, full), full
    params = h.build_params or cagra.IndexParams()
    return cagra.build(params, full), full
