"""Tombstone mutation: delete/upsert over a live index without rebuilds.

The mutation model (docs/serving.md §4): the served index itself is
immutable (generations swap atomically — :mod:`raft_tpu.serve.registry`);
mutability is layered on top as

* a **tombstone keep-mask** (:class:`raft_tpu.core.bitset.Bitset`
  semantics, maintained host-side as a dense bool array and lowered to
  packed device words on demand) composed with any user ``prefilter``
  and fed to the existing filtered-search paths of every index type;
* an **upsert side-buffer**: new/replacement vectors accumulate in a
  small brute-force-searched buffer (padded to a power-of-two capacity
  so its traces are stable) whose per-batch results are merged into the
  main index's via ``merge_topk`` — FusionANNS' delta-store shape;
* a **compaction** step: past a threshold the engine folds the side
  buffer into the main index with a background ``extend`` + hot-swap.

Ids: callers speak **external ids**; internally every row ever admitted
gets a fresh monotonically-increasing **internal id** (never reused), so
a replaced row and its replacement coexist under different internal ids
and the tombstone mask can hide exactly the old one. While no upsert has
ever happened the two spaces are identical and the translation layer is
skipped entirely.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from raft_tpu.analysis import lockwatch
from raft_tpu.core.bitset import Bitset
from raft_tpu.utils.math import next_pow2


def _dense_from_bitset_host(bits_words: np.ndarray, n_bits: int) -> np.ndarray:
    """Host-side unpack of packed uint32 filter words to dense bool."""
    w = bits_words.astype(np.uint32, copy=False)
    idx = np.arange(n_bits)
    return ((w[idx // 32] >> (idx % 32)) & 1).astype(bool)


def _pow2_ceil(n: int) -> int:
    # next_pow2 maps 0 -> 1 (a ladder rung); an empty id space stays 0
    return next_pow2(n) if n else 0


class CompactionTicket:
    """Snapshot handed to the background compactor: the side rows (and
    their internal ids) that the new generation's ``extend`` will fold
    in. Mutations arriving while the build runs keep editing the live
    state; the tombstone mask is shared, so a delete of a snapshotted
    row simply holds its keep-bit down across the swap."""

    __slots__ = ("base_ids", "count", "vectors", "int_ids")

    def __init__(self, base_ids: int, count: int, vectors: np.ndarray,
                 int_ids: np.ndarray):
        self.base_ids = base_ids
        self.count = count
        self.vectors = vectors
        self.int_ids = int_ids


class MutableState:
    """The mutable overlay of one named index: tombstones, the side
    buffer, and the external↔internal id maps. Thread-safe. The overlay
    is carried across *compaction* swaps (the extended generation keeps
    this object, so tombstones and post-snapshot upserts survive), but a
    *content* swap (:meth:`Server.swap` — new dataset, new id space)
    installs a fresh overlay: deletes and upserts against the old
    content do not apply to the replacement."""

    def __init__(self, n_rows: int, dim: int, dtype,
                 ext_ids: Optional[np.ndarray] = None,
                 side_capacity: int = 256):
        # constructed through the graft-race sanitizer: under
        # RAFT_TPU_THREADSAN=1 every acquisition feeds the lock-order
        # graph as node "serve.mutation" (docs/serving.md lock hierarchy)
        self.lock = lockwatch.make_rlock("serve.mutation")
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.base_ids = int(n_rows)          # internal ids [0, base_ids)
        self.next_int = int(n_rows)
        self.seq = 0                          # bumped on every mutation
        self.side_seq = 0                     # bumped only when side-buffer
        #                                       CONTENT changes (append /
        #                                       compaction shift) — keys the
        #                                       engine's side-index cache so
        #                                       base-row deletes don't force
        #                                       a side rebuild
        # keep-mask over internal ids [0, next_int): True = live
        self._keep = np.ones(max(n_rows, 1), dtype=bool)
        if n_rows == 0:
            self._keep = self._keep[:0]
        # side buffer (allocated on first upsert)
        self.side_capacity_hint = int(side_capacity)
        self.side_cap = 0
        self.side_used = 0
        self.side_vecs: Optional[np.ndarray] = None
        self.side_int: Optional[np.ndarray] = None   # internal id per slot
        self._side_keep: Optional[np.ndarray] = None
        # id translation (None while external == internal)
        self._ext2int: Optional[Dict[int, int]] = None
        self._int2ext: Optional[np.ndarray] = None
        if ext_ids is not None:
            ext_ids = np.asarray(ext_ids, dtype=np.int64)
            if ext_ids.shape != (n_rows,):
                raise ValueError("ext_ids must be [n_rows]")
            if not np.array_equal(ext_ids, np.arange(n_rows)):
                self._install_translation(ext_ids)
        # packed-device caches (rebuilt lazily per seq)
        self._dev_cache: Dict[object, Tuple[int, object]] = {}

    # -- id translation ----------------------------------------------------

    def _install_translation(self, ext_ids: Optional[np.ndarray] = None):
        # takes the (reentrant) mutation lock itself: __init__ calls
        # this pre-publication, upsert under its own hold — both nest
        # cleanly, and the map writes are never unlocked (GL010)
        with self.lock:
            if self._ext2int is not None:
                return
            if ext_ids is None:
                ext_ids = np.arange(self.next_int, dtype=np.int64)
            self._int2ext = ext_ids.copy()
            # only LIVE rows get a forward mapping: ids deleted back in
            # identity mode must stay deleted (to_internal → None), not
            # be resurrected by the switch to explicit translation
            self._ext2int = {int(e): i for i, e in enumerate(ext_ids)
                             if i >= self._keep.shape[0] or self._keep[i]}

    @property
    def has_translation(self) -> bool:
        return self._ext2int is not None

    def to_internal(self, ext_id: int) -> Optional[int]:
        """Live internal id for ``ext_id`` (None when absent/deleted)."""
        with self.lock:
            if self._ext2int is None:
                i = int(ext_id)
                return i if 0 <= i < self.next_int and self._keep[i] \
                    else None
            return self._ext2int.get(int(ext_id))

    def translate_out(self, internal_ids: np.ndarray) -> np.ndarray:
        """Map result internal ids back to external (-1 passes through)."""
        with self.lock:
            if self._int2ext is None:
                return internal_ids
            out = np.where(
                internal_ids >= 0,
                self._int2ext[np.clip(internal_ids, 0,
                                      self._int2ext.shape[0] - 1)],
                np.int64(-1),
            )
            return out

    # -- mutation ----------------------------------------------------------

    def delete(self, ext_ids) -> int:  # graft-lint: allow-unspanned-entry state layer; Server.delete opens the serve.delete entry span around this
        """Tombstone ``ext_ids``; returns how many were live. Idempotent:
        already-deleted / never-seen ids are skipped."""
        ext_ids = np.atleast_1d(np.asarray(ext_ids)).astype(np.int64)
        n = 0
        with self.lock:
            for e in ext_ids:
                i = self._to_internal_locked(int(e))
                if i is None:
                    continue
                self._keep[i] = False
                if i >= self.base_ids and self.side_used:
                    slots = np.nonzero(self.side_int[:self.side_used] == i)[0]
                    if slots.size:
                        self._side_keep[slots] = False
                if self._ext2int is not None:
                    self._ext2int.pop(int(e), None)
                n += 1
            if n:
                self.seq += 1
        return n

    def _to_internal_locked(self, ext_id: int) -> Optional[int]:
        if self._ext2int is None:
            i = ext_id
            return i if 0 <= i < self.next_int and self._keep[i] else None
        return self._ext2int.get(ext_id)

    def upsert(self, vectors: np.ndarray, ext_ids) -> Tuple[int, bool]:  # graft-lint: allow-unspanned-entry state layer; Server.upsert opens the serve.upsert entry span around this
        """Insert-or-replace ``vectors`` under ``ext_ids``. Returns
        ``(side_rows_now, shape_grew)`` — the engine compacts past its
        threshold and re-warms when a traced shape grew (the side
        capacity, or the filter capacity rung of
        :meth:`filter_capacity`)."""
        vectors = np.asarray(vectors, dtype=self.dtype)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        ext_ids = np.atleast_1d(np.asarray(ext_ids)).astype(np.int64)
        if vectors.shape[0] != ext_ids.shape[0]:
            raise ValueError("vectors and ids row counts differ")
        if vectors.shape[1] != self.dim:
            raise ValueError(
                f"vector dim {vectors.shape[1]} != index dim {self.dim}")
        grew = False
        with self.lock:
            cap0 = self._filter_capacity_locked()
            # upserts break the identity assumption the moment a fresh
            # internal id stands in for an external one
            self._install_translation()
            for v, e in zip(vectors, ext_ids):
                old = self._ext2int.get(int(e))
                if old is not None:
                    self._keep[old] = False
                    if old >= self.base_ids and self.side_used:
                        slots = np.nonzero(
                            self.side_int[:self.side_used] == old)[0]
                        if slots.size:
                            self._side_keep[slots] = False
                i = self.next_int
                self.next_int += 1
                if self._keep.shape[0] < self.next_int:
                    extra = max(self._keep.shape[0], 64)
                    self._keep = np.concatenate(
                        [self._keep, np.zeros(extra, dtype=bool)])
                self._keep[i] = True
                if self._int2ext.shape[0] < self.next_int:
                    extra = max(self._int2ext.shape[0], 64)
                    self._int2ext = np.concatenate(
                        [self._int2ext, np.full(extra, -1, np.int64)])
                self._int2ext[i] = int(e)
                self._ext2int[int(e)] = i
                grew |= self._side_append_locked(v, i)
            grew |= self._filter_capacity_locked() != cap0
            self.seq += 1
            return self.side_used, grew

    def _side_append_locked(self, vec: np.ndarray, internal_id: int) -> bool:
        # caller (upsert) holds self.lock — the *_locked contract GL010
        # checks
        grew = False
        if self.side_vecs is None or self.side_used >= self.side_cap:
            new_cap = next_pow2(max(self.side_capacity_hint,
                                    1 if self.side_cap == 0
                                    else self.side_cap * 2))
            vecs = np.zeros((new_cap, self.dim), self.dtype)
            ints = np.full(new_cap, -1, np.int64)
            keep = np.zeros(new_cap, dtype=bool)
            if self.side_vecs is not None:
                vecs[:self.side_used] = self.side_vecs[:self.side_used]
                ints[:self.side_used] = self.side_int[:self.side_used]
                keep[:self.side_used] = self._side_keep[:self.side_used]
            self.side_vecs, self.side_int, self._side_keep = vecs, ints, keep
            self.side_cap = new_cap
            grew = True
        s = self.side_used
        self.side_vecs[s] = vec
        self.side_int[s] = internal_id
        self._side_keep[s] = True
        self.side_used += 1
        self.side_seq += 1
        return grew

    # -- filters (device views) -------------------------------------------

    _DEV_CACHE_MAX = 32

    def _cached(self, key, build, pin=None):
        """Mutation-seq-keyed device-view cache. ``pin`` holds a strong
        reference to the object whose ``id()`` is part of ``key`` — while
        the entry lives, CPython cannot reuse that address for a new
        filter, so identity keying is safe. Bounded: stale-seq entries
        are evicted first, then oldest-inserted, so per-request filters
        cannot grow device memory without bound.

        ``build()`` runs OUTSIDE the lock (the GL012
        device-work-under-lock class): the dispatcher calls this while
        already holding the reentrant mutation lock for its consistency
        pin — there the build still runs under that outer hold, seq
        cannot advance, and behavior is unchanged — but a lock-free
        caller (warmup) no longer stalls concurrent delete/upsert for
        the device lowering. The entry is stored under the seq read
        BEFORE the build, so a mutation landing mid-build leaves a
        stale-keyed entry the next call rebuilds instead of serving."""
        with self.lock:
            hit = self._dev_cache.get(key)
            if hit is not None and hit[0] == self.seq:
                return hit[1]
            seq0 = self.seq
        val = build()
        with self.lock:
            hit = self._dev_cache.get(key)
            if hit is not None and hit[0] == self.seq:
                return hit[1]          # a racer built it first — it wins
            self._dev_cache[key] = (seq0, val, pin)
            if len(self._dev_cache) > self._DEV_CACHE_MAX:
                stale = [k for k, v in self._dev_cache.items()
                         if v[0] != self.seq]
                for k in stale:
                    del self._dev_cache[k]
                while len(self._dev_cache) > self._DEV_CACHE_MAX:
                    self._dev_cache.pop(next(iter(self._dev_cache)))
            return val

    def _filter_capacity_locked(self) -> int:
        return _pow2_ceil(self.next_int)

    def filter_capacity(self) -> int:
        """``n_bits`` of every device filter this state hands out: the
        next power of two ≥ ``next_int``. ``n_bits`` (and the packed
        word count behind it) is a STATIC argument of every filtered
        search kernel, so growing it per upsert would retrace each
        (bucket, k) shape on every single upsert — the pow2 ladder makes
        it step only when ``next_int`` crosses a boundary, and
        :meth:`upsert` reports that crossing as ``shape_grew`` so the
        engine re-warms. Pad bits cover ids no index row ever carries
        (main sample ids < base_ids ≤ next_int), so their value is
        inert; they are left 0."""
        with self.lock:
            return self._filter_capacity_locked()

    def tombstone_bits(self) -> Bitset:
        """The packed device keep-mask over internal ids [0, next_int)
        (every id the main index OR the side buffer can produce),
        zero-padded to the stable :meth:`filter_capacity` rung."""
        def _build():
            with self.lock:
                n = self.next_int
                dense = np.zeros(self._filter_capacity_locked(),
                                 dtype=bool)
                dense[:n] = self._keep[:n]
            return Bitset.from_dense(dense)
        return self._cached("tomb", _build)

    def side_keep_bits(self) -> Optional[Bitset]:
        """Keep-mask over side-buffer SLOTS (pad + dead slots dropped)."""
        if self.side_cap == 0:
            return None

        def _build():
            with self.lock:
                dense = self._side_keep.copy()
            return Bitset.from_dense(dense)
        return self._cached("side_keep", _build)

    def compose_user_filter(self, filt) -> Tuple[Bitset, Optional[Bitset]]:
        """Compose a user prefilter (over EXTERNAL ids, honoring its
        ``out_of_range`` mode) with the tombstone mask. Returns
        ``(main_bits, side_bits)`` device bitsets — main over internal
        ids (padded to :meth:`filter_capacity`), side over side slots.
        Cached per (filter identity, filter content version, mutation
        seq): the host-side translation pass is paid once per filter per
        mutation epoch, not per batch, and an in-place ``set``/``flip``/
        ``resize`` of the user's Bitset bumps its version so the stale
        composition is never served."""
        bitset = getattr(filt, "bitset", filt)
        oor = getattr(filt, "out_of_range", "drop")
        # safe: _cached pins `bitset`, so its id cannot be reused while
        # the entry lives, and _version tracks in-place mutation
        key = ("user", id(bitset), getattr(bitset, "_version", 0), oor)

        def _build():
            user_words = np.asarray(bitset.bits)
            user_n = int(bitset.n_bits)
            with self.lock:
                n = self.next_int
                cap = self._filter_capacity_locked()
                keep = self._keep[:n].copy()
                int2ext = None if self._int2ext is None \
                    else self._int2ext[:n].copy()
                side_cap, side_used = self.side_cap, self.side_used
                side_keep = None if self._side_keep is None \
                    else self._side_keep.copy()
                side_int = None if self.side_int is None \
                    else self.side_int.copy()
            ext = np.arange(n, dtype=np.int64) if int2ext is None \
                else int2ext
            in_range = (ext >= 0) & (ext < user_n)
            user_dense = np.zeros(n, dtype=bool)
            if user_n:
                safe = np.clip(ext, 0, user_n - 1)
                user_dense = _dense_from_bitset_host(user_words, user_n)[safe]
            user_keep = np.where(in_range, user_dense, oor == "keep")
            main_dense = np.zeros(cap, dtype=bool)
            main_dense[:n] = keep & user_keep
            main = Bitset.from_dense(main_dense)
            side = None
            if side_cap:
                slot_user = np.zeros(side_cap, dtype=bool)
                live = side_int[:side_used]
                slot_user[:side_used] = user_keep[
                    np.clip(live, 0, n - 1)] & (live >= 0)
                side = Bitset.from_dense(side_keep & slot_user)
            return main, side
        return self._cached(key, _build, pin=bitset)

    # -- accounting --------------------------------------------------------

    def live_rows(self) -> int:
        with self.lock:
            return int(self._keep[:self.next_int].sum())

    def deleted_rows(self) -> int:
        with self.lock:
            return int(self.next_int - self._keep[:self.next_int].sum())

    def side_rows_live(self) -> int:
        with self.lock:
            if self._side_keep is None:
                return 0
            return int(self._side_keep[:self.side_used].sum())

    # -- compaction --------------------------------------------------------

    def begin_compaction(self) -> Optional[CompactionTicket]:
        """Snapshot the current side rows for a background extend."""
        with self.lock:
            if self.side_used == 0:
                return None
            s0 = self.side_used
            return CompactionTicket(
                base_ids=self.base_ids,
                count=s0,
                vectors=self.side_vecs[:s0].copy(),
                int_ids=self.side_int[:s0].copy(),
            )

    def commit_compaction(self, ticket: CompactionTicket) -> None:
        """Fold the snapshotted rows into the base id range and shift the
        side tail left. Runs under the mutation lock at swap time; the
        shared keep-mask already reflects any deletes that landed while
        the extend was building."""
        with self.lock:
            s0 = ticket.count
            tail = self.side_used - s0
            if tail > 0:
                self.side_vecs[:tail] = self.side_vecs[s0:self.side_used]
                self.side_int[:tail] = self.side_int[s0:self.side_used]
                self._side_keep[:tail] = self._side_keep[s0:self.side_used]
            self.side_used = max(tail, 0)
            self.side_vecs[self.side_used:] = 0
            self.side_int[self.side_used:] = -1
            self._side_keep[self.side_used:] = False
            self.base_ids = ticket.base_ids + s0
            self.seq += 1
            self.side_seq += 1
