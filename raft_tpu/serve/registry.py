"""Versioned index registry: named indexes, generations, atomic hot-swap.

The serving invariant (docs/serving.md §3): a batch answers from exactly
ONE generation. Each named index points at its current
:class:`Generation`; a swap publishes a fully-built successor and
atomically redirects the name. In-flight batches keep a **pin**
(refcount) on the generation they started with and finish on it; the
retired generation is released — its drain event fires — only when the
last pin drops, which is when its device arrays become collectable.
This is the reference's ``raft::resources``-lifetime discipline applied
to whole indexes, and the serving-side analog of
``core/serialize``'s versioned index files (a generation can be
published from one).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from raft_tpu import obs
from raft_tpu.analysis import lockwatch


class Generation:
    """One immutable published version of a named index.

    ``handle`` is the engine's serving state (index + searcher +
    shapes); the registry only manages identity and lifetime. Pins are
    taken via :meth:`Registry.pin` (atomic with the name lookup) and
    dropped with :meth:`release`; after :meth:`retire`, the final
    release fires ``drained`` and the ``on_drain`` callbacks.
    """

    __slots__ = ("name", "version", "handle", "drained", "_refs",
                 "_retired", "_draining", "_lock", "_on_drain")

    def __init__(self, name: str, version: int, handle):
        self.name = name
        self.version = int(version)
        self.handle = handle
        self.drained = threading.Event()
        self._refs = 0
        self._retired = False
        self._draining = False
        # graft-race sanitizer node "serve.generation"
        self._lock = lockwatch.make_lock("serve.generation")
        self._on_drain: List[Callable[["Generation"], None]] = []

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Generation({self.name!r}, v{self.version}, "
                f"refs={self._refs}, retired={self._retired})")

    @property
    def refs(self) -> int:
        return self._refs

    @property
    def retired(self) -> bool:
        return self._retired

    def _pin_locked(self) -> "Generation":
        self._refs += 1
        return self

    def pin(self) -> "Generation":
        """Take an ADDITIONAL pin on a generation the caller already
        holds alive (graft-gauge's shadow samples and swap-probation
        holds, ISSUE 19). Unlike :meth:`Registry.pin` this does not
        re-resolve the name — the whole point is to keep THIS
        generation, current or retired, from draining. Raises if the
        generation already drained (there is no handle left to keep)."""
        with self._lock:
            if self.drained.is_set():
                raise RuntimeError(
                    f"generation v{self.version} of {self.name!r} "
                    "already drained")
            return self._pin_locked()

    def release(self) -> None:
        """Drop one pin; the last release of a retired generation drains
        it (fires ``drained`` + callbacks, drops the handle)."""
        fire = False
        with self._lock:
            self._refs -= 1
            if self._refs <= 0 and self._retired and \
                    not self.drained.is_set():
                fire = True
        if fire:
            self._drain()

    def retire(self) -> None:
        """Mark superseded; drains immediately if nothing has it pinned."""
        with self._lock:
            self._retired = True
            fire = self._refs <= 0 and not self.drained.is_set()
        if fire:
            self._drain()

    def add_on_drain(self, cb: Callable[["Generation"], None]) -> None:
        with self._lock:
            # _draining, not drained: _drain captures the list ONCE
            # (under this lock) and only sets the event after the
            # callbacks ran — a cb appended in that window would sit in
            # _on_drain forever (for the fabric: _retire_cluster never
            # fires and every worker pins the retired shards)
            if not self._draining:
                self._on_drain.append(cb)
                return
        # drain already in flight (or done): invoke OUTSIDE the lock,
        # matching _drain's contract — a callback touching
        # release()/retire() would deadlock on the non-reentrant lock
        # otherwise
        cb(self)

    def _drain(self) -> None:
        obs.counter("serve.generations_drained", index=self.name)
        obs.event("generation_drained", index=self.name,
                  version=self.version)
        # capture-and-clear under the lock (GL010: _on_drain is
        # lock-guarded state — a concurrent add_on_drain racing an
        # unlocked clear() could drop its callback); _draining flips in
        # the SAME hold, so a late add_on_drain self-invokes instead of
        # appending to a list nobody will read again. The callbacks
        # themselves still run outside the lock, per add_on_drain's
        # contract.
        with self._lock:
            self._draining = True
            cbs = list(self._on_drain)
            self._on_drain.clear()
        for cb in cbs:
            cb(self)
        # the handle holds the device arrays; dropping the reference here
        # is what actually returns the old generation's HBM once callers
        # holding pins are gone
        self.handle = None
        self.drained.set()


class Registry:
    """Name → current :class:`Generation`, with monotone versions.

    All transitions go through :meth:`publish` (atomic swap) and
    :meth:`pin` (atomic current-lookup + refcount) under one lock, so a
    reader can never observe a half-swapped index: it either pins the
    old generation (and the old generation survives, whole, until that
    pin drops) or the new one.
    """

    def __init__(self):
        # graft-race sanitizer node "serve.registry"
        self._lock = lockwatch.make_lock("serve.registry")
        self._current: Dict[str, Generation] = {}
        self._versions: Dict[str, int] = {}
        self._live: List[Generation] = []   # published, not yet drained

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._current)

    def get(self, name: str) -> Optional[Generation]:
        """The current generation (NOT pinned — introspection only)."""
        with self._lock:
            return self._current.get(name)

    def version(self, name: str) -> int:
        with self._lock:
            return self._versions.get(name, 0)

    def pin(self, name: str) -> Generation:
        """Atomically look up the current generation and take a pin on
        it. Callers MUST :meth:`Generation.release` when their batch
        completes (success or failure)."""
        with self._lock:
            gen = self._current.get(name)
            if gen is None:
                raise KeyError(f"no index published under {name!r}")
            with gen._lock:
                return gen._pin_locked()

    def publish(self, name: str, handle,
                on_drain: Optional[Callable] = None) -> Generation:
        """Atomically swap ``name`` to a new generation wrapping
        ``handle``; the previous generation (if any) is retired and
        drains when its last pin drops. Returns the new generation."""
        with obs.span("serve.publish", index=name):
            with self._lock:
                v = self._versions.get(name, 0) + 1
                self._versions[name] = v
                gen = Generation(name, v, handle)
                if on_drain is not None:
                    gen.add_on_drain(on_drain)
                old = self._current.get(name)
                self._current[name] = gen
                self._live = [g for g in self._live
                              if not g.drained.is_set()]
                self._live.append(gen)
                # counted under the lock: a concurrent publish reassigns
                # _live, so an off-lock comprehension would read a list
                # from neither consistent state
                live_n = len(self._live)
            if old is not None:
                old.retire()
            obs.counter("serve.swaps_total", index=name)
            obs.gauge("serve.generation", v, index=name)
            obs.gauge("serve.generations_live", live_n)
            obs.event("generation_published", index=name, version=v)
            return gen

    def rollback(self, name: str, gen: Generation,
                 on_drain: Optional[Callable] = None) -> Generation:
        """Republish ``gen``'s handle as a NEW generation of ``name`` —
        the recall-alarm rollback path (graft-gauge, ISSUE 19): a
        hot-swap whose post-publish recall estimate degrades versus its
        predecessor's is reverted by re-promoting the predecessor's
        handle. The caller must still hold a pin on ``gen`` (the
        quality monitor's probation pin) — a drained generation has no
        handle left to serve, and this raises then. Versions stay
        monotone: the rollback is a fresh generation wrapping the old
        handle, so in-flight batches on the degraded generation finish
        on their pins exactly like any other swap."""
        handle = gen.handle
        if handle is None or gen.drained.is_set():
            raise ValueError(
                f"cannot roll back {name!r} to v{gen.version}: "
                "generation already drained")
        new = self.publish(name, handle, on_drain=on_drain)
        obs.counter("serve.recall_rollbacks_total", index=name)
        obs.event("generation_rolled_back", index=name,
                  version=new.version, restored_version=gen.version)
        return new

    def drop(self, name: str) -> None:
        """Unpublish ``name`` (retire its current generation)."""
        with self._lock:
            old = self._current.pop(name, None)
        if old is not None:
            old.retire()

    def live_generations(self) -> List[Generation]:
        with self._lock:
            self._live = [g for g in self._live if not g.drained.is_set()]
            return list(self._live)
