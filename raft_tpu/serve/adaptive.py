"""SLO-aware adaptive execution: per-query probe rungs + deadline
budgets (ISSUE 14; docs/serving.md §13).

One global ``n_probes`` burns the whole latency budget on easy queries
and starves hard ones (JUNO, PAPERS.md). This module is the policy half
of the fix, consumed by :mod:`raft_tpu.serve.engine`:

* **difficulty estimation** — the coarse scan's centroid-distance
  margin (``ivf_flat.coarse_margins`` / ``ivf_pq.coarse_margins``): the
  normalized gap between the best and the p-th coarse centroid. A large
  margin means the query sits firmly inside one list's basin — few
  probes recover its neighbors; a vanishing margin means the coarse
  quantizer cannot tell the candidate lists apart and only exhaustive
  probing is safe;
* **the pow2 probe-rung ladder** — ``n_probes`` is only ever served at
  :func:`probe_ladder` values (powers of two plus the ceiling), so the
  set of traced shapes stays finite and warmable: the engine's warmup
  drives every (bucket, k, rung) combination once and steady-state
  serving never retraces (the GL007 bar, extended to the rung axis);
* **the recall-floor escape hatch** — a margin below ``floor_margin``
  maps to the TOP rung (the exhaustive ceiling), which dispatches the
  exact same program as the non-adaptive path: ambiguous queries are
  served bitwise-identically to today's exhaustive serving;
* **deadline budgets** — :func:`service_estimate_ms` reads the
  per-(bucket, rung) service-time medians that
  ``scripts/capture_dispatch_tables.py`` captures into the dispatch
  table (op ``serve_service``), so the batcher's slack test and the
  engine's shed/downshift decisions run on measured numbers instead of
  a hardcoded guess.

Thresholds come from ``tuning.budget`` (integer basis points, so they
ride the same table plumbing as the byte budgets):
``serve_probe_margin`` (the easy threshold — at or above it the
minimum feasible rung serves), ``serve_probe_floor`` (the escape
hatch — below it the exhaustive rung serves), and
``serve_deadline_headroom_ms`` (slack the batcher reserves on top of
the service estimate before a deadline request skips linger).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

# thresholds in integer BASIS POINTS (1e-4), the unit tuning budgets
# can carry; defaults validated on the clustered easy/hard mix in
# tests/test_serve_adaptive.py and the SLO_r14.json capture
DEFAULT_EASY_MARGIN_BP = 2000      # margin >= 0.20: min feasible rung
DEFAULT_FLOOR_MARGIN_BP = 200      # margin <  0.02: exhaustive escape
DEFAULT_HEADROOM_MS = 5            # slack reserve for deadline linger

# the fused Pallas scan caps per-list extraction at 256 candidates; the
# rung floor must keep rung * min(cap, 256) >= k or the probed pool
# cannot hold a full top-k (ivf_flat.search raises exactly then)
_KERNEL_LIST_CAP = 256

ADAPTIVE_ALGOS = ("ivf_flat", "ivf_pq")


def probe_ladder(ceiling: int) -> Tuple[int, ...]:  # graft-lint: allow-unspanned-entry pure host math (pow2 ladder shape); the serving spans live on the engine's dispatch path
    """The pow2 probe-rung ladder under ``ceiling``: powers of two below
    it plus ``ceiling`` itself as the top rung (mirrors the serve
    k-ladder — the ceiling need not be a power of two, but must be a
    rung, because it is the escape hatch's exhaustive target)."""
    ceiling = max(int(ceiling), 1)
    out, b = [], 1
    while b < ceiling:
        out.append(b)
        b <<= 1
    out.append(ceiling)
    return tuple(out)


def margin_thresholds() -> Tuple[float, float]:
    """(easy, floor) margin thresholds from the tuning budgets (basis
    points -> fractions). floor is clamped strictly below easy so the
    interpolation below never divides by zero."""
    from raft_tpu import tuning

    easy = tuning.budget("serve_probe_margin", DEFAULT_EASY_MARGIN_BP) / 1e4
    floor = tuning.budget("serve_probe_floor", DEFAULT_FLOOR_MARGIN_BP) / 1e4
    easy = max(easy, 1e-4)
    floor = min(max(floor, 0.0), easy * 0.99)
    return easy, floor


def deadline_headroom_ms() -> float:
    """Slack reserve (ms) the deadline-aware linger keeps on top of the
    measured service estimate."""
    from raft_tpu import tuning

    return float(tuning.budget("serve_deadline_headroom_ms",
                               DEFAULT_HEADROOM_MS))


@dataclasses.dataclass(frozen=True)
class AdaptivePolicy:
    """One generation's margin -> probe-rung mapping.

    ``ladder`` tops at the generation's exhaustive ceiling (the resolved
    ``n_probes`` — the caller's explicit value, else ``n_lists``: the
    old ``_default_search_params`` pin, demoted from "the" probe count
    to the policy's ceiling). ``list_cap`` is the index's padded list
    capacity — the rung floor keeps ``rung * min(cap, 256) >= k`` so a
    downshifted query can still fill its top-k.
    """

    ladder: Tuple[int, ...]
    list_cap: int
    easy_margin: float
    floor_margin: float
    refine_ratio: int = 1          # the rabitq pipeline's serving rr
    margin_p: int = 2              # the "top-1 vs top-p" gap's p

    @classmethod
    def build(cls, ceiling: int, list_cap: int,  # graft-lint: allow-unspanned-entry policy constructor, no device work; engine warmup/dispatch spans cover the serving surface
              refine_ratio: int = 1) -> "AdaptivePolicy":
        easy, floor = margin_thresholds()
        return cls(ladder=probe_ladder(ceiling), list_cap=int(list_cap),
                   easy_margin=easy, floor_margin=floor,
                   refine_ratio=int(refine_ratio))

    # -- rung selection ----------------------------------------------------

    def min_idx(self, k: int) -> int:
        """Smallest ladder index whose probed candidate pool can hold a
        full top-``k`` (rung * min(cap, 256) >= k)."""
        cap = min(max(self.list_cap, 1), _KERNEL_LIST_CAP)
        for i, rung in enumerate(self.ladder):
            if rung * cap >= int(k):
                return i
        return len(self.ladder) - 1

    def choose_idx(self, margin: float, k: int = 1) -> int:
        """Map a difficulty margin to a ladder index.

        margin >= easy  -> the minimum feasible rung;
        margin <  floor -> the TOP rung (exhaustive escape hatch,
        bitwise-identical to the non-adaptive path);
        in between      -> linear interpolation across the ladder.
        """
        top = len(self.ladder) - 1
        m = float(margin)
        if not math.isfinite(m) or m < self.floor_margin:
            idx = top
        elif m >= self.easy_margin:
            idx = 0
        else:
            frac = ((self.easy_margin - m)
                    / (self.easy_margin - self.floor_margin))
            idx = min(top, int(math.ceil(frac * top)))
        return max(idx, self.min_idx(k))

    def rung(self, idx: int) -> int:
        return self.ladder[max(0, min(int(idx), len(self.ladder) - 1))]

    def refine_for(self, idx: int) -> int:
        """Per-rung rabitq refine_ratio (ROADMAP item 2b): the easiest
        rung halves the over-fetch (its shortlist already comes from the
        query's own basin), every other rung — including the exhaustive
        escape — keeps the serving default, so the escape hatch stays
        bitwise-identical to the non-adaptive pipeline."""
        if self.refine_ratio <= 1:
            return self.refine_ratio
        if int(idx) == 0 and len(self.ladder) > 1:
            return max(2, self.refine_ratio // 2)
        return self.refine_ratio

    def refine_ladder(self) -> Tuple[int, ...]:
        """Distinct refine_ratio values the ladder can dispatch (what
        warmup must trace)."""
        return tuple(sorted({self.refine_for(i)
                             for i in range(len(self.ladder))}))

    # -- the graft-gauge closed loop (ISSUE 19) ----------------------------

    def tightened(self, max_refine: int = 16) -> "AdaptivePolicy":
        """One bounded quality-retune step toward recall (graft-gauge's
        closed loop): double both margin thresholds — more queries read
        as "hard" and interpolate to higher rungs, and more fall under
        the exhaustive escape floor — and double the rabitq over-fetch
        one notch (capped at ``max_refine``). The ladder itself never
        changes: a margin retune only REWEIGHTS the already-warmed
        rungs, so it cannot mint a new traced shape; the refine bump is
        the one shape-bearing change, and the engine re-warms exactly
        when :meth:`refine_ladder` grew. The monitor applies retunes as
        ``base.tightened()^n`` so a relax step is exact (n-1), not a
        drifting inverse."""
        easy = min(self.easy_margin * 2.0, 0.95)
        floor = (self.floor_margin * 2.0 if self.floor_margin > 0
                 else easy / 8.0)
        floor = min(floor, easy * 0.99)
        rr = self.refine_ratio
        if rr > 1:
            rr = min(rr * 2, max(int(max_refine), rr))
        return dataclasses.replace(self, easy_margin=easy,
                                   floor_margin=floor, refine_ratio=rr)


def service_estimate_ms(bucket: int,
                        rung: Optional[int] = None) -> Optional[float]:
    """Measured service-time median for a (bucket[, rung]) shape from
    the dispatch table's ``serve_service`` op (captured by
    ``scripts/capture_dispatch_tables.py --ops serve_service``), or
    None when no table entry is near the key — callers fall back to
    their own live measurements."""
    from raft_tpu import tuning

    t = tuning.get_table()
    if t is None:
        return None
    key: Dict[str, int] = {"bucket": int(bucket)}
    if rung is not None:
        key["rung"] = int(rung)
    entry = t.lookup_entry("serve_service", key)
    if entry is None:
        return None
    times = entry.get("times_ms") or {}
    try:
        return float(min(times.values()))
    except (TypeError, ValueError):
        return None


__all__ = [
    "ADAPTIVE_ALGOS", "AdaptivePolicy", "DEFAULT_EASY_MARGIN_BP",
    "DEFAULT_FLOOR_MARGIN_BP", "DEFAULT_HEADROOM_MS",
    "deadline_headroom_ms", "margin_thresholds", "probe_ladder",
    "service_estimate_ms",
]
