"""Gram (kernel) matrices: linear / polynomial / RBF / tanh.

Analog of the reference's gram kernels
(cpp/include/raft/distance/kernels.cuh, detail/kernels/ — SVM-style kernel
matrices). All four are GEMM + elementwise epilogue → pure MXU work.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.distance.types import KernelParams, KernelType
from raft_tpu.utils.precision import dist_dot


def linear_kernel(x, y) -> jax.Array:
    return dist_dot(jnp.asarray(x, jnp.float32), jnp.asarray(y, jnp.float32).T)


def polynomial_kernel(x, y, gamma: float = 1.0, coef0: float = 0.0, degree: int = 3) -> jax.Array:
    return (gamma * linear_kernel(x, y) + coef0) ** degree


def tanh_kernel(x, y, gamma: float = 1.0, coef0: float = 0.0) -> jax.Array:
    return jnp.tanh(gamma * linear_kernel(x, y) + coef0)


def rbf_kernel(x, y, gamma: float = 1.0) -> jax.Array:
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    dot = dist_dot(x, y.T)
    xn = jnp.sum(x * x, axis=1)
    yn = jnp.sum(y * y, axis=1)
    d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * dot, 0.0)
    return jnp.exp(-gamma * d2)


def gram_matrix(x, y, params: KernelParams) -> jax.Array:
    """Dispatch on KernelParams (reference detail/kernels/gram_matrix.cuh)."""
    if params.kernel == KernelType.LINEAR:
        return linear_kernel(x, y)
    if params.kernel == KernelType.POLYNOMIAL:
        return polynomial_kernel(x, y, params.gamma, params.coef0, params.degree)
    if params.kernel == KernelType.RBF:
        return rbf_kernel(x, y, params.gamma)
    if params.kernel == KernelType.TANH:
        return tanh_kernel(x, y, params.gamma, params.coef0)
    raise ValueError(f"unknown kernel {params.kernel}")
