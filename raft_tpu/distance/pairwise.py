"""Pairwise distances — all dense metrics of the reference.

TPU-native replacement for the reference's tiled pairwise-distance engine
(cpp/include/raft/distance/distance-inl.cuh:67,238; metric ops under
distance/detail/distance_ops/*.cuh; tiling policies in
linalg/contractions.cuh:61). Design notes (SURVEY.md §7):

* "Expanded" metrics (L2/cosine/correlation/inner-product/hellinger/
  russelrao) are a GEMM plus an elementwise epilogue — exactly what the
  reference's SM80 CUTLASS path fuses. On TPU the GEMM rides the MXU via
  ``jnp.dot`` and XLA fuses the epilogue; no hand-written kernel needed.
* "Unexpanded" metrics (L1/Linf/Canberra/Lp/...) reduce elementwise over
  the feature axis. Those are computed in (tile_m × tile_n) blocks with a
  broadcast-reduce, sequentially scanned with ``lax.map`` so peak memory is
  tile_m*tile_n*d instead of m*n*d.

Epilogue formulas follow the reference ops exactly (e.g. hamming × 1/k,
russelrao (k-dot)/k, jensen-shannon sqrt(0.5·acc), KL 0.5·Σx(log x−log y),
hellinger sqrt(rectified 1−Σ√x√y)): distance/detail/distance_ops/*.cuh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.utils.precision import dist_dot
from raft_tpu.utils.math import cdiv, round_up_to_multiple

# metrics computable as GEMM + epilogue (MXU path)
_EXPANDED = {
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.CosineExpanded,
    DistanceType.InnerProduct,
    DistanceType.CorrelationExpanded,
    DistanceType.HellingerExpanded,
    DistanceType.RusselRaoExpanded,
    DistanceType.JaccardExpanded,
    DistanceType.DiceExpanded,
}


def pairwise_distance(
    x,
    y,
    metric="euclidean",
    metric_arg: float = 2.0,
    tile_m: Optional[int] = None,
    tile_n: Optional[int] = None,
) -> jax.Array:
    """Compute the full [m, n] distance matrix between rows of x and y.

    pylibraft-compatible entry point
    (reference distance/distance-inl.cuh:238 ``pairwise_distance``).

    Parameters
    ----------
    x : [m, d] array. y : [n, d] array.
    metric : DistanceType or name (see types.METRIC_NAMES).
    metric_arg : p for Minkowski/Lp.
    tile_m/tile_n : block sizes for the elementwise path (default: sized to
        keep blocks ~VMEM-friendly).
    """
    metric = resolve_metric(metric)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    if x.ndim != 2 or y.ndim != 2 or x.shape[1] != y.shape[1]:
        raise ValueError(f"bad shapes {x.shape} vs {y.shape}")
    if metric == DistanceType.Precomputed:
        raise ValueError("Precomputed is not a computable metric")
    if metric == DistanceType.Haversine and x.shape[1] != 2:
        raise ValueError("haversine requires d=2 (lat, lon in radians)")
    return _pairwise(x, y, int(metric), float(metric_arg), tile_m, tile_n)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _pairwise(x, y, metric_val: int, p: float, tile_m, tile_n) -> jax.Array:
    metric = DistanceType(metric_val)
    compute = jnp.promote_types(x.dtype, jnp.float32)
    x = x.astype(compute)
    y = y.astype(compute)
    if metric in _EXPANDED:
        return _expanded_path(x, y, metric)
    return _elementwise_path(x, y, metric, p, tile_m, tile_n)


# --------------------------------------------------------------------------
# Expanded (GEMM) path
# --------------------------------------------------------------------------


def _expanded_path(x, y, metric: DistanceType) -> jax.Array:
    m, d = x.shape
    n, _ = y.shape
    k = jnp.asarray(d, x.dtype)

    if metric == DistanceType.HellingerExpanded:
        # reference sqrt-transforms inputs then matmuls (distance.cuh hellinger
        # distance_impl); epilogue distance_ops/hellinger.cuh.
        x = jnp.sqrt(x)
        y = jnp.sqrt(y)

    dot = dist_dot(x, y.T)

    if metric == DistanceType.InnerProduct:
        return dot
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        xn = jnp.sum(x * x, axis=1)
        yn = jnp.sum(y * y, axis=1)
        d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * dot, 0.0)
        # zero exact self-pairs like the reference epilogue (l2_exp.cuh
        # "Self-neighboring points" correction) is implicit via the clamp.
        return jnp.sqrt(d2) if metric == DistanceType.L2SqrtExpanded else d2
    if metric == DistanceType.CosineExpanded:
        xn = jnp.sqrt(jnp.sum(x * x, axis=1))
        yn = jnp.sqrt(jnp.sum(y * y, axis=1))
        denom = jnp.maximum(xn[:, None] * yn[None, :], jnp.finfo(x.dtype).tiny)
        return 1.0 - dot / denom
    if metric == DistanceType.CorrelationExpanded:
        # 1 - centered cosine (distance_ops/correlation.cuh)
        xm = x.mean(axis=1, keepdims=True)
        ym = y.mean(axis=1, keepdims=True)
        xc_n = jnp.sqrt(jnp.sum((x - xm) ** 2, axis=1))
        yc_n = jnp.sqrt(jnp.sum((y - ym) ** 2, axis=1))
        num = dot - k * xm[:, 0][:, None] * ym[:, 0][None, :]
        denom = jnp.maximum(xc_n[:, None] * yc_n[None, :], jnp.finfo(x.dtype).tiny)
        return 1.0 - num / denom
    if metric == DistanceType.HellingerExpanded:
        return jnp.sqrt(jnp.maximum(1.0 - dot, 0.0))
    if metric == DistanceType.RusselRaoExpanded:
        # (k - Σ x·y) / k on boolean-ish inputs (distance_ops/russel_rao.cuh)
        return (k - dot) / k
    if metric == DistanceType.JaccardExpanded:
        xs = jnp.sum(x, axis=1)
        ys = jnp.sum(y, axis=1)
        union = xs[:, None] + ys[None, :] - dot
        return 1.0 - dot / jnp.where(union == 0, 1.0, union)
    if metric == DistanceType.DiceExpanded:
        xs = jnp.sum(x, axis=1)
        ys = jnp.sum(y, axis=1)
        denom = xs[:, None] + ys[None, :]
        return 1.0 - 2.0 * dot / jnp.where(denom == 0, 1.0, denom)
    raise AssertionError(metric)


# --------------------------------------------------------------------------
# Elementwise (broadcast-reduce) path
# --------------------------------------------------------------------------


def _block_distance(xb, yb, metric: DistanceType, p: float) -> jax.Array:
    """Distance between row-blocks: xb [tm, d], yb [tn, d] → [tm, tn].

    Each branch mirrors one distance_ops/*.cuh core+epilog pair.
    """
    d = xb.shape[-1]
    xi = xb[:, None, :]  # [tm, 1, d]
    yi = yb[None, :, :]  # [1, tn, d]
    if metric == DistanceType.L1:
        return jnp.sum(jnp.abs(xi - yi), axis=-1)
    if metric in (DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded):
        acc = jnp.sum((xi - yi) ** 2, axis=-1)
        return jnp.sqrt(acc) if metric == DistanceType.L2SqrtUnexpanded else acc
    if metric == DistanceType.Linf:
        return jnp.max(jnp.abs(xi - yi), axis=-1)
    if metric == DistanceType.Canberra:
        diff = jnp.abs(xi - yi)
        add = jnp.abs(xi) + jnp.abs(yi)
        return jnp.sum(jnp.where(add == 0, 0.0, diff / jnp.where(add == 0, 1.0, add)), axis=-1)
    if metric == DistanceType.LpUnexpanded:
        acc = jnp.sum(jnp.abs(xi - yi) ** p, axis=-1)
        return acc ** (1.0 / p)
    if metric == DistanceType.BrayCurtis:
        num = jnp.sum(jnp.abs(xi - yi), axis=-1)
        den = jnp.sum(jnp.abs(xi + yi), axis=-1)
        return jnp.where(den == 0, 0.0, num / jnp.where(den == 0, 1.0, den))
    if metric == DistanceType.JensenShannon:
        m = 0.5 * (xi + yi)
        logm = jnp.where(m == 0, 0.0, jnp.log(jnp.where(m == 0, 1.0, m)))
        lx = jnp.where(xi == 0, 0.0, jnp.log(jnp.where(xi == 0, 1.0, xi)))
        ly = jnp.where(yi == 0, 0.0, jnp.log(jnp.where(yi == 0, 1.0, yi)))
        acc = jnp.sum(xi * (lx - logm) + yi * (ly - logm), axis=-1)
        return jnp.sqrt(jnp.maximum(0.5 * acc, 0.0))
    if metric == DistanceType.HammingUnexpanded:
        return jnp.sum((xi != yi).astype(xb.dtype), axis=-1) / d
    if metric == DistanceType.KLDivergence:
        lx = jnp.where(xi == 0, 0.0, jnp.log(jnp.where(xi == 0, 1.0, xi)))
        ly = jnp.where(yi == 0, 0.0, jnp.log(jnp.where(yi == 0, 1.0, yi)))
        return 0.5 * jnp.sum(xi * (lx - ly), axis=-1)
    if metric == DistanceType.Haversine:
        # spatial/knn/detail/haversine_distance.cuh
        lat1, lon1 = xi[..., 0], xi[..., 1]
        lat2, lon2 = yi[..., 0], yi[..., 1]
        sdlat = jnp.sin(0.5 * (lat1 - lat2))
        sdlon = jnp.sin(0.5 * (lon1 - lon2))
        a = sdlat**2 + jnp.cos(lat1) * jnp.cos(lat2) * sdlon**2
        return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
    raise AssertionError(metric)


def _elementwise_path(x, y, metric: DistanceType, p: float, tile_m, tile_n) -> jax.Array:
    m, d = x.shape
    n, _ = y.shape
    # Budget ~32 MiB of broadcast intermediate per block.
    if tile_m is None or tile_n is None:
        budget_elems = (32 * 1024 * 1024) // 4
        tn = min(round_up_to_multiple(n, 128), 2048)
        tm = max(8, min(round_up_to_multiple(m, 8), budget_elems // max(tn * d, 1)))
        tile_m = tile_m or tm
        tile_n = tile_n or tn
    if m * n * d <= (8 * 1024 * 1024) // 4:
        return _block_distance(x, y, metric, p)

    mp = round_up_to_multiple(m, tile_m)
    np_ = round_up_to_multiple(n, tile_n)
    xpad = jnp.pad(x, ((0, mp - m), (0, 0)))
    ypad = jnp.pad(y, ((0, np_ - n), (0, 0)))
    x_tiles = xpad.reshape(mp // tile_m, tile_m, d)
    y_tiles = ypad.reshape(np_ // tile_n, tile_n, d)

    def row_tile(xt):
        def col_tile(yt):
            return _block_distance(xt, yt, metric, p)

        blocks = jax.lax.map(col_tile, y_tiles)  # [Tn, tm, tn]
        return jnp.transpose(blocks, (1, 0, 2)).reshape(tile_m, np_)

    rows = jax.lax.map(row_tile, x_tiles)  # [Tm, tm, n_pad]
    return rows.reshape(mp, np_)[:m, :n]


def distance(x, y, metric="euclidean", metric_arg: float = 2.0) -> jax.Array:
    """Alias matching the reference's ``raft::distance::distance``."""
    return pairwise_distance(x, y, metric, metric_arg)
