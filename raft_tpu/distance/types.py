"""Distance metric types.

Mirrors the reference's ``raft::distance::DistanceType``
(cpp/include/raft/distance/distance_types.hpp:23-67) including enum values,
plus ``is_min_close`` (:72) and the gram-kernel params (:87-104).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class DistanceType(enum.IntEnum):
    L2Expanded = 0
    L2SqrtExpanded = 1
    CosineExpanded = 2
    L1 = 3
    L2Unexpanded = 4
    L2SqrtUnexpanded = 5
    InnerProduct = 6
    Linf = 7
    Canberra = 8
    LpUnexpanded = 9
    CorrelationExpanded = 10
    JaccardExpanded = 11
    HellingerExpanded = 12
    Haversine = 13
    BrayCurtis = 14
    JensenShannon = 15
    HammingUnexpanded = 16
    KLDivergence = 17
    RusselRaoExpanded = 18
    DiceExpanded = 19
    Precomputed = 100


# pylibraft-compatible metric name aliases
# (python/pylibraft/pylibraft/distance/pairwise_distance.pyx DISTANCE_TYPES)
METRIC_NAMES: dict[str, DistanceType] = {
    "sqeuclidean": DistanceType.L2Expanded,
    "l2": DistanceType.L2SqrtExpanded,
    "euclidean": DistanceType.L2SqrtExpanded,
    "l2_expanded": DistanceType.L2Expanded,
    "l2_sqrt_expanded": DistanceType.L2SqrtExpanded,
    "cosine": DistanceType.CosineExpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "l2_unexpanded": DistanceType.L2Unexpanded,
    "l2_sqrt_unexpanded": DistanceType.L2SqrtUnexpanded,
    "inner_product": DistanceType.InnerProduct,
    "dot": DistanceType.InnerProduct,
    "chebyshev": DistanceType.Linf,
    "linf": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "minkowski": DistanceType.LpUnexpanded,
    "lp": DistanceType.LpUnexpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "haversine": DistanceType.Haversine,
    "braycurtis": DistanceType.BrayCurtis,
    "jensenshannon": DistanceType.JensenShannon,
    "hamming": DistanceType.HammingUnexpanded,
    "kl_divergence": DistanceType.KLDivergence,
    "russellrao": DistanceType.RusselRaoExpanded,
    "dice": DistanceType.DiceExpanded,
}


def resolve_metric(metric) -> DistanceType:
    if isinstance(metric, DistanceType):
        return metric
    if isinstance(metric, int):
        return DistanceType(metric)
    name = str(metric).lower()
    if name not in METRIC_NAMES:
        raise ValueError(f"unknown metric {metric!r}; known: {sorted(METRIC_NAMES)}")
    return METRIC_NAMES[name]


def is_min_close(metric: DistanceType) -> bool:
    """True if smaller distance = more similar (distance_types.hpp:72)."""
    return metric != DistanceType.InnerProduct


def pair_flops(metric: DistanceType, d: int) -> int:
    """FLOPs to score ONE (query, row) pair at dimension ``d`` — the
    numerator of the roofline column (docs/kernels.md §roofline). The
    expanded metrics are one length-d MXU dot (2d) plus an O(1)
    epilogue; the direct (non-expanded) forms pay the elementwise
    difference on top. Used by bench.py's per-op roofline rows, so the
    model is deliberately the ACHIEVED-algorithm count (expanded form
    with precomputed norms), not the naive 3d subtraction form."""
    d = int(d)
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        return 2 * d + 4          # dot + (qn + xn - 2ab, clamp)
    if metric == DistanceType.InnerProduct:
        return 2 * d
    if metric == DistanceType.CosineExpanded:
        return 2 * d + 5          # dot + norm product, divide, 1 - r
    # direct forms (L2Unexpanded, L1, ...): diff + accumulate per dim
    return 3 * d


class KernelType(enum.IntEnum):
    LINEAR = 0
    POLYNOMIAL = 1
    RBF = 2
    TANH = 3


@dataclass
class KernelParams:
    """Gram kernel params (distance_types.hpp:98-104)."""

    kernel: KernelType = KernelType.LINEAR
    degree: int = 3
    gamma: float = 1.0
    coef0: float = 0.0
