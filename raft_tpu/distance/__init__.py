"""Distance layer (SURVEY.md §2.6): pairwise distances over all reference
metrics, fused 1-NN argmin, masked NN, and gram kernels."""

from raft_tpu.distance.types import (
    DistanceType,
    KernelParams,
    KernelType,
    METRIC_NAMES,
    is_min_close,
    resolve_metric,
)
from raft_tpu.distance.pairwise import pairwise_distance, distance
from raft_tpu.distance.fused_l2_nn import (
    fused_l2_nn_argmin,
    fused_l2_nn_min_reduce,
    masked_l2_nn_argmin,
)
from raft_tpu.distance.kernels import (
    gram_matrix,
    linear_kernel,
    polynomial_kernel,
    rbf_kernel,
    tanh_kernel,
)

__all__ = [
    "DistanceType",
    "KernelParams",
    "KernelType",
    "METRIC_NAMES",
    "is_min_close",
    "resolve_metric",
    "pairwise_distance",
    "distance",
    "fused_l2_nn_argmin",
    "fused_l2_nn_min_reduce",
    "masked_l2_nn_argmin",
    "gram_matrix",
    "linear_kernel",
    "polynomial_kernel",
    "rbf_kernel",
    "tanh_kernel",
]
