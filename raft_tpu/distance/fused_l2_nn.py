"""Fused L2 distance + argmin 1-nearest-neighbor.

TPU-native analog of the reference's ``fused_l2_nn`` / ``fusedL2NNMinReduce``
(cpp/include/raft/distance/fused_l2_nn-inl.cuh:76-181) — the key primitive
under k-means predict and 1-NN queries. Instead of a custom CUDA kernel with
atomics, we scan over tiles of ``y`` keeping a running (min, argmin): each
tile is a GEMM on the MXU plus an elementwise epilogue, and the running
reduction keeps peak memory at m×tile instead of m×n.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.utils.math import round_up_to_multiple
from raft_tpu.utils.precision import dist_dot


def fused_l2_nn_argmin(
    x,
    y,
    sqrt: bool = False,
    tile_n: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of x, the L2 distance and index of its nearest row of y.

    Returns ``(min_dist [m], argmin [m])`` — the reference's KVP output
    (fused_l2_nn-inl.cuh:76 with MinAndDistanceReduceOp).

    ``sqrt=True`` applies the square root in the epilogue
    (fused_l2_nn-inl.cuh Sqrt template param).
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    n = y.shape[0]
    if tile_n is None:
        # whole-y fast path for modest n (e.g. kmeans centers)
        tile_n = n if n * x.shape[0] <= (256 * 1024 * 1024) // 4 else 4096
    return _fused_l2_nn(x, y, bool(sqrt), int(min(tile_n, n)))


@functools.partial(jax.jit, static_argnums=(2, 3))
def _fused_l2_nn(x, y, sqrt: bool, tile_n: int):
    compute = jnp.promote_types(x.dtype, jnp.float32)
    x = x.astype(compute)
    y = y.astype(compute)
    m, d = x.shape
    n, _ = y.shape
    xn = jnp.sum(x * x, axis=1)

    if tile_n >= n:
        dot = dist_dot(x, y.T)
        yn = jnp.sum(y * y, axis=1)
        d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * dot, 0.0)
        idx = jnp.argmin(d2, axis=1).astype(jnp.int32)
        val = jnp.take_along_axis(d2, idx[:, None], axis=1)[:, 0]
        return (jnp.sqrt(val) if sqrt else val), idx

    npad = round_up_to_multiple(n, tile_n)
    ypad = jnp.pad(y, ((0, npad - n), (0, 0)))
    y_tiles = ypad.reshape(npad // tile_n, tile_n, d)
    n_tiles = npad // tile_n

    def body(carry, inp):
        best_val, best_idx = carry
        t, yt = inp
        dot = dist_dot(x, yt.T)
        yn = jnp.sum(yt * yt, axis=1)
        d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * dot, 0.0)
        col = jnp.arange(tile_n) + t * tile_n
        d2 = jnp.where(col[None, :] < n, d2, jnp.inf)
        tile_idx = jnp.argmin(d2, axis=1)
        tile_val = jnp.take_along_axis(d2, tile_idx[:, None], axis=1)[:, 0]
        take = tile_val < best_val
        best_val = jnp.where(take, tile_val, best_val)
        best_idx = jnp.where(take, (tile_idx + t * tile_n).astype(jnp.int32), best_idx)
        return (best_val, best_idx), None

    init = (jnp.full((m,), jnp.inf, compute), jnp.zeros((m,), jnp.int32))
    (best_val, best_idx), _ = jax.lax.scan(
        body, init, (jnp.arange(n_tiles), y_tiles)
    )
    return (jnp.sqrt(best_val) if sqrt else best_val), best_idx


def fused_l2_nn_min_reduce(x, y, sqrt: bool = False):
    """Reference-named alias (fused_l2_nn-inl.cuh:163 fusedL2NNMinReduce)."""
    return fused_l2_nn_argmin(x, y, sqrt=sqrt)


def masked_l2_nn_argmin(
    x,
    y,
    adj,
    group_idxs=None,
    sqrt: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Masked fused NN (reference distance/masked_nn.cuh).

    ``adj``: bool [m, n_groups] adjacency — row i may match group g only if
    adj[i, g]. ``group_idxs``: [n_groups] *end* offsets partitioning y's rows
    into contiguous groups (reference masked_l2_nn semantics); None = one
    group per y row (adj is [m, n]).
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    adj = jnp.asarray(adj).astype(jnp.bool_)
    n = y.shape[0]
    if group_idxs is None:
        mask = adj
    else:
        group_idxs = jnp.asarray(group_idxs)
        # map each y row to its group: group g covers [prev_end, end)
        row = jnp.arange(n)
        grp = jnp.searchsorted(group_idxs, row, side="right")
        mask = adj[:, grp]  # [m, n]
    compute = jnp.promote_types(x.dtype, jnp.float32)
    xw = x.astype(compute)
    yw = y.astype(compute)
    dot = dist_dot(xw, yw.T)
    xn = jnp.sum(xw * xw, axis=1)
    yn = jnp.sum(yw * yw, axis=1)
    d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * dot, 0.0)
    d2 = jnp.where(mask, d2, jnp.inf)
    idx = jnp.argmin(d2, axis=1).astype(jnp.int32)
    val = jnp.take_along_axis(d2, idx[:, None], axis=1)[:, 0]
    if sqrt:
        val = jnp.sqrt(val)
    return val, idx
