"""Spectral graph partitioning and modularity clustering
(reference cpp/include/raft/spectral/{partition,modularity_maximization,
eigen_solvers,cluster_solvers}.cuh — SURVEY.md §2.8 layer 11).

TPU formulation: the eigen stage is the existing Lanczos solver
(linalg/lanczos.py — full-reorth, GEMM-dominated) driven by a sparse
Laplacian/modularity matvec (segment-sum SpMV); the cluster stage is the
existing Lloyd kmeans on the embedding rows. This mirrors the reference's
lanczos_solver_t + kmeans_solver_t plumbing (spectral/partition.cuh:67).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans
from raft_tpu.linalg.lanczos import lanczos_eigsh
from raft_tpu.sparse import linalg as sparse_linalg
from raft_tpu.sparse.types import CSR, csr_to_coo


def _laplacian_matvec(adj: CSR):
    """v ↦ L v = D v - A v without materializing L
    (spectral/matrix_wrappers.hpp laplacian_matrix_t::mv)."""
    d = sparse_linalg.degree(adj)

    def mv(v):
        return d * v - sparse_linalg.spmv(adj, v)

    return mv


def _modularity_matvec(adj: CSR):
    """v ↦ B v = A v - (dᵀv) d / 2m (modularity_matrix_t::mv)."""
    d = sparse_linalg.degree(adj)
    two_m = jnp.maximum(jnp.sum(adj.vals), 1e-30)

    def mv(v):
        return sparse_linalg.spmv(adj, v) - d * (jnp.dot(d, v) / two_m)

    return mv


def fit_embedding(
    adj: CSR, n_components: int, n_iters: int | None = None, seed: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Spectral embedding: ``n_components`` non-trivial *Laplacian*
    eigenpairs (the reference's computeSmallestEigenvectors stage).

    Skips the trivial constant eigenvector (eigenvalue 0) by requesting
    one extra pair and dropping the first. Returns (eigenvalues [k],
    embedding [n, k]). (Modularity-matrix embeddings live in
    ``modularity_maximization``, which drives the Lanczos solver with
    its own operator.)
    """
    n = adj.shape[0]
    k = n_components + 1
    evals, evecs = lanczos_eigsh(
        _laplacian_matvec(adj), n, min(k, n), n_iters=n_iters,
        key=jax.random.PRNGKey(seed), which="smallest",
    )
    return evals[1:], evecs[:, 1:]


def partition(
    adj: CSR,
    n_clusters: int,
    n_eigenvecs: int | None = None,
    n_lanczos_iters: int | None = None,
    kmeans_max_iter: int = 100,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Spectral min-balanced-cut partition (reference
    spectral/partition.cuh:67 ``partition``): Laplacian smallest
    eigenvectors → kmeans on the embedding rows.

    Returns (labels [n], eigenvalues [k], eigenvectors [n, k]).
    """
    k = n_eigenvecs or n_clusters
    evals, embed = fit_embedding(adj, k, n_iters=n_lanczos_iters, seed=seed)
    # row-normalize the embedding: standard scaling for spectral kmeans
    # (the reference scales by eigenvalue transform inside its solver)
    norms = jnp.linalg.norm(embed, axis=1, keepdims=True)
    embed_n = embed / jnp.maximum(norms, 1e-12)
    params = kmeans.KMeansParams(
        n_clusters=n_clusters, max_iter=kmeans_max_iter, seed=seed,
        init="k-means++",
    )
    labels, _, _, _ = kmeans.fit_predict(params, embed_n)
    return labels, evals, embed


def modularity_maximization(
    adj: CSR,
    n_clusters: int,
    n_eigenvecs: int | None = None,
    seed: int = 0,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Modularity-maximizing clustering (reference
    spectral/modularity_maximization.cuh:62): largest eigenvectors of the
    modularity matrix B = A - d dᵀ/2m → kmeans."""
    k = n_eigenvecs or n_clusters
    n = adj.shape[0]
    mv = _modularity_matvec(adj)
    evals, evecs = lanczos_eigsh(
        mv, n, min(k, n), key=jax.random.PRNGKey(seed), which="largest"
    )
    norms = jnp.linalg.norm(evecs, axis=1, keepdims=True)
    embed = evecs / jnp.maximum(norms, 1e-12)
    params = kmeans.KMeansParams(n_clusters=n_clusters, seed=seed,
                                 init="k-means++")
    labels, _, _, _ = kmeans.fit_predict(params, embed)
    return labels, evals, evecs


def analyze_partition(adj: CSR, labels) -> Tuple[jax.Array, jax.Array]:
    """Partition quality (reference spectral/partition.cuh:151
    ``analyzePartition``): returns (edge_cut, cost = Σ_k cut_k/size_k)."""
    coo = csr_to_coo(adj)
    labels = jnp.asarray(labels)
    cross = labels[coo.rows] != labels[coo.cols]
    cross_w = jnp.where(cross, coo.vals, 0.0)
    edge_cut = jnp.sum(cross_w) / 2.0
    # graft-lint: allow-host-sync cluster count sizes the segment-sum buffer
    k = int(jnp.max(labels)) + 1 if labels.shape[0] else 0
    k = max(k, 1)
    # per-cluster cut and size in one segment-sum pass each: with both
    # directions of every edge stored, scattering cross_w by the row
    # endpoint's label lands each undirected cross edge's full weight on
    # both incident clusters — exactly cut_c
    cut_k = jnp.zeros((k,), jnp.float32).at[labels[coo.rows]].add(cross_w)
    size_k = jnp.maximum(
        jnp.zeros((k,), jnp.float32).at[labels].add(1.0), 1.0
    )
    cost = jnp.sum(cut_k / size_k)
    return edge_cut, cost


def analyze_modularity(adj: CSR, labels) -> jax.Array:
    """Modularity Q of a clustering (reference
    spectral/modularity_maximization.cuh:94 analyzeModularity):
    Q = (1/2m) Σ_ij [A_ij - d_i d_j / 2m] δ(c_i, c_j)."""
    coo = csr_to_coo(adj)
    labels = jnp.asarray(labels)
    d = sparse_linalg.degree(adj)
    two_m = jnp.maximum(jnp.sum(coo.vals), 1e-30)
    same = labels[coo.rows] == labels[coo.cols]
    a_term = jnp.sum(jnp.where(same, coo.vals, 0.0))
    # Σ_k (Σ_{i∈k} d_i)² / 2m
    # graft-lint: allow-host-sync cluster count sizes the segment-sum buffer
    k = int(jnp.max(labels)) + 1 if labels.shape[0] else 0
    dk = jnp.zeros((max(k, 1),), jnp.float32).at[labels].add(d)
    null_term = jnp.sum(dk * dk) / two_m
    return (a_term - null_term) / two_m
