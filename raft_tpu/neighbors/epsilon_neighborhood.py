"""Epsilon neighborhood — dense boolean adjacency within a radius
(reference neighbors/epsilon_neighborhood.cuh epsUnexpL2SqNeighborhood:
tiled L2² + threshold + per-vertex degree, spatial/knn/detail/
epsilon_neighborhood.cuh).

TPU: one tiled pairwise pass (MXU for the L2 term) with the comparison
and row-degree reduction fused by XLA into the same pass.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.distance.types import DistanceType, resolve_metric


def eps_neighbors(
    x, y, eps: float, metric="sqeuclidean"
) -> Tuple[jax.Array, jax.Array]:
    """Adjacency ``adj[i, j] = dist(x_i, y_j) <= eps`` and per-row degrees.

    Mirrors ``epsUnexpL2SqNeighborhood(adj, vd, x, y, eps)`` — with
    metric="sqeuclidean" and eps in squared units, exactly the reference
    semantics; other metrics compare in their own units.
    """
    metric = resolve_metric(metric)
    d = pairwise_distance(x, y, metric)
    adj = d <= jnp.asarray(eps, d.dtype)
    vd = jnp.sum(adj, axis=1, dtype=jnp.int32)
    return adj, vd


def eps_neighbors_l2sq(x, y, eps_sq: float) -> Tuple[jax.Array, jax.Array]:
    """Reference-named alias: squared-L2 threshold."""
    return eps_neighbors(x, y, eps_sq, DistanceType.L2Expanded)
