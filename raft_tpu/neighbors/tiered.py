"""Tiered-memory rerank sources: host/mmap originals, shortlist-only
fetch, and Zipf-aware hot-row residency (ROADMAP item 3; ISSUE 12).

FusionANNS (PAPERS.md, arXiv:2409.16576) shows the billion-scale win is
a memory-hierarchy split: compressed codes stay accelerator-resident,
raw vectors live on host RAM / SSD, and only *shortlist* bytes ever
cross the link. This module is that split for the
``ivf_pq.search_refined`` pipeline:

* :class:`RerankSource` — one interface over every place the exact
  rerank stage can read originals from: a host numpy array or
  ``np.memmap`` file (:class:`HostArraySource`), an already
  device-resident array (:class:`DeviceSource`, the old full-upload
  fast path), with the index's own device cache/codes paths staying
  inside ``search_refined`` (they never fetch — the compressed rungs
  ARE resident).
* **Shortlist-only fetch** — per batch, the host source gathers only
  the **unique** valid shortlist rows, pads them to a power-of-two
  rung (so serve's zero-retrace warmup can enumerate every fetched
  block shape), uploads just those ``<= m*kc`` rows, and scores them
  with :func:`raft_tpu.neighbors.refine.score_gathered` — the SAME
  arithmetic as the full-upload path, so results are bitwise
  identical on the same shortlist while bytes-moved drops from
  ``n*d*itemsize`` to shortlist scale.
* **Hot-row residency** — real traffic is Zipf-skewed (JUNO's workload
  analysis, PAPERS.md), so a fixed-budget HBM hot-row cache
  (clock/second-chance; budget rows via ``tuning.budget`` knob
  ``tiered_hot_rows``) is consulted before the host gather: rows
  fetched repeatedly are promoted device-side FROM the already
  uploaded miss block (no second transfer), hits are served from HBM
  with zero link bytes, and evictions are counted.

Observability (docs/observability.md): ``tiered.hit_rate{tier=hbm|
host}``, ``tiered.hits_total{tier}``, ``tiered.lookups_total``,
``tiered.bytes_moved_total{link}``, ``tiered.evictions_total``,
``tiered.promotions_total`` — bytes-moved-per-query is the bench
column ROADMAP item 3 budgets against.

Thread model: host bookkeeping (hot-cache maps, counters) is guarded
by a lock; device work runs outside it. Concurrent ``rerank`` calls
are safe: a batch classifies hits and snapshots the hot block under
ONE lock hold (the map never references a row the snapshot lacks —
promotions reserve slots at plan time but only enter the map at a
compare-and-swap commit after their rows landed in an installed
block, and the scatter is undonated so an in-flight reader's
snapshot stays readable). Interleaved promoters can lose a commit —
costing a duplicate fetch later, never a wrong result.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs, tuning
from raft_tpu.analysis import lockwatch
from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.neighbors.refine import refine as _refine_exact
from raft_tpu.neighbors.refine import score_gathered as _score_gathered
from raft_tpu.utils.math import next_pow2

# tuning.budget knob: HBM hot-row cache capacity in ROWS (docs/
# dispatch_tuning.md). A site-captured table or a runtime
# record_budget ceiling overrides the default.
HOT_ROWS_BUDGET = "tiered_hot_rows"
DEFAULT_HOT_ROWS = 4096
# smallest fetched-block rung: bounds the warmup trace count (rungs
# per (m, c) shape = log2(next_pow2(m*c) / RUNG_FLOOR) + 1) without
# inflating small fetches beyond one tile's worth of rows
RUNG_FLOOR = 64
# fixed-width promotion scatter: at most this many rows enter the hot
# cache per batch, through ONE shape-stable (undonated — see
# _promote_scatter) scatter; promotion pressure beyond it carries over
# via the miss counts, and the hottest rows — highest miss counts — go
# first
PROMOTE_BATCH = 256


@dataclasses.dataclass
class FetchInfo:
    """What one shortlist fetch actually moved (the dedup-honest
    numbers behind ``rerank.bytes_fetched_total`` / ``tiered.*``)."""

    valid_slots: int = 0      # shortlist slots with a real candidate
    unique_rows: int = 0      # distinct row ids among them
    hbm_hits: int = 0         # served from the hot-row cache
    host_rows: int = 0        # gathered from the host/mmap source
    rung: int = 0             # padded upload rows (the link shape)
    bytes_link: int = 0       # bytes that crossed host->device
    bytes_rows: int = 0       # unique fetched-row payload (deduped)
    promotions: int = 0
    evictions: int = 0


class RerankSource:
    """One interface over every exact-rerank fidelity source. The
    contract: ``rerank(queries, candidate_ids, k, metric)`` re-scores
    global-id candidates exactly and returns host-of-jit ``(d, ids)``
    [m, k]; negative ids are invalid and sink to the sentinel."""

    kind = "abstract"
    dim: int = 0
    row_bytes: int = 0

    def rerank(self, queries, candidates, k: int, metric
               ) -> Tuple[jax.Array, jax.Array]:
        d, i, _ = self.rerank_info(queries, candidates, k, metric)
        return d, i

    def rerank_info(self, queries, candidates, k: int, metric
                    ) -> Tuple[jax.Array, jax.Array, FetchInfo]:
        raise NotImplementedError

    def prepare(self, queries, candidates):
        """The fetch half of a stage-split rerank: everything host-side
        — shortlist sync, dedup/classify, gather, device upload —
        packaged as an opaque handle for :meth:`score`. graft-flow's
        producers run this for batch N+1 while batch N scores; the
        default defers everything to ``score`` (device-resident sources
        have no host fetch to overlap)."""
        return (queries, candidates)

    def score(self, prepared, k: int, metric
              ) -> Tuple[jax.Array, jax.Array, FetchInfo]:
        """The device half: exact-score a :meth:`prepare` handle.
        ``score(prepare(q, c), k, metric)`` is always bitwise
        ``rerank_info(q, c, k, metric)`` — the split moves *when* the
        fetch happens, never what is computed."""
        queries, candidates = prepared
        return self.rerank_info(queries, candidates, k, metric)

    def warm(self, m: int, c: int, k: int, metric,
             query_dtype=jnp.float32) -> int:
        """Trace every device shape an [m, c] shortlist rerank at
        ``k`` can dispatch (serve's zero-retrace warmup hook).
        Returns the number of shapes traced."""
        return 0

    def stats(self) -> dict:
        return {}


class DeviceSource(RerankSource):
    """The pre-tiered fast path: the whole dataset device-resident,
    rerank is one gather + exact scoring (``neighbors.refine``). Right
    when the originals fit HBM next to the index — no fetch, no
    residency policy, nothing to warm beyond ``refine._refine``."""

    kind = "device"

    def __init__(self, dataset):
        self.dataset = (dataset if isinstance(dataset, jax.Array)
                        else jnp.asarray(dataset))
        if self.dataset.ndim != 2:
            raise ValueError(
                f"dataset must be [n, dim], got {self.dataset.shape}")
        self.dim = int(self.dataset.shape[1])
        self.row_bytes = self.dim * self.dataset.dtype.itemsize

    def rerank_info(self, queries, candidates, k, metric):
        d, i = _refine_exact(self.dataset, queries, candidates,
                                  int(k), metric)
        info = FetchInfo(rung=int(self.dataset.shape[0]))
        return d, i, info


class HostArraySource(RerankSource):
    """Host-resident originals (numpy array or ``np.memmap``): the
    rerank stage fetches only the unique shortlist rows per batch —
    the dataset itself never crosses the link. See the module
    docstring for the residency policy."""

    kind = "host"

    def __init__(self, dataset: np.ndarray,
                 hot_rows: Optional[int] = None,
                 promote_after: int = 2,
                 promote_batch: int = PROMOTE_BATCH):
        if not isinstance(dataset, np.ndarray):
            raise TypeError(
                "HostArraySource wants a host numpy array or np.memmap; "
                f"got {type(dataset).__name__} — pass device arrays to "
                "DeviceSource (the full-upload fast path) instead")
        if dataset.ndim != 2:
            raise ValueError(f"dataset must be [n, dim], got {dataset.shape}")
        self.dataset = dataset
        self.rows = int(dataset.shape[0])
        self.dim = int(dataset.shape[1])
        self.dtype = np.dtype(dataset.dtype)
        self.row_bytes = self.dim * self.dtype.itemsize
        if hot_rows is None:
            hot_rows = tuning.budget(HOT_ROWS_BUDGET, DEFAULT_HOT_ROWS)
        self.hot_capacity = max(min(int(hot_rows), self.rows), 0)
        self.promote_after = max(int(promote_after), 1)
        # the fixed promotion-scatter width (shape-stable per source)
        self.promote_batch = max(int(promote_batch), 1)
        self._lock = lockwatch.make_lock("tiered.source")
        # clock/second-chance residency state (guarded by _lock)
        self._slot_of: dict = {}                   # row id -> slot
        self._id_at = np.full(self.hot_capacity, -1, np.int64)
        self._ref = np.zeros(self.hot_capacity, bool)
        self._hand = 0
        self._used = 0
        self._miss_counts: dict = {}               # row id -> fetches seen
        self._hot_block: Optional[jax.Array] = None
        # per-rung device zero blocks: a fully-hot batch (no misses)
        # still needs a miss-block operand for the shape-stable scorer,
        # but it must not UPLOAD one — steady state at hit-rate ~1
        # would otherwise pay a pointless RUNG_FLOOR transfer per batch
        # and inflate bytes_moved (benign-race dict: worst case two
        # threads build the same zeros block once)
        self._zero_blocks: dict = {}
        # cumulative fetch accounting (stats()/tests; obs mirrors it)
        self._lookups = 0
        self._hbm_hits = 0
        self._host_rows = 0
        self._bytes_link = 0
        self._evictions = 0
        self._promotions = 0

    # -- residency bookkeeping (host-side, under _lock) -------------------

    def _classify_locked(self, uniq: np.ndarray):
        """Split sorted unique ids into hot hits (with slots) and
        misses; mark hit slots' reference bits (second chance)."""
        if self.hot_capacity == 0 or not self._slot_of:
            return np.full(uniq.size, -1, np.int64)
        slots = np.fromiter(
            (self._slot_of.get(int(i), -1) for i in uniq),
            np.int64, uniq.size)
        hit = slots >= 0
        if hit.any():
            self._ref[slots[hit]] = True
        return slots

    def _evict_slot_locked(self) -> int:
        """Clock hand: skip (and clear) referenced slots once, evict
        the first unreferenced one."""
        cap = self.hot_capacity
        for _ in range(2 * cap):
            h = self._hand
            self._hand = (h + 1) % cap
            if self._ref[h]:
                self._ref[h] = False
                continue
            old = int(self._id_at[h])
            if old >= 0:
                self._slot_of.pop(old, None)
                self._evictions += 1
            return h
        return self._hand  # unreachable: a full sweep clears every bit

    def _plan_promotions_locked(self, miss_ids: np.ndarray):
        """Count misses; rows past ``promote_after`` fetches get a hot
        slot (evicting via the clock when full). Returns (ids, slots)
        capped at ``promote_batch`` — overflow keeps its count and
        promotes on the next fetch.

        The plan only RESERVES: eviction victims leave the slot map
        here (nobody may hit a slot whose content is about to change),
        but the promoted ids are NOT mapped yet — that happens in
        :meth:`_commit_promotions_locked` once their rows have
        actually landed in a new hot block, so a concurrent classify
        can never hit a slot whose data is still in flight."""
        if self.hot_capacity == 0:
            return [], []
        eligible = []
        for i in miss_ids:
            i = int(i)
            c = self._miss_counts.get(i, 0) + 1
            self._miss_counts[i] = c
            if c >= self.promote_after:
                eligible.append((c, i))
        # hottest first: the promote_batch budget goes to the rows with
        # the most recorded fetches, so the Zipf head becomes resident
        # before the tail ever competes for slots
        eligible.sort(reverse=True)
        # keyed by SLOT: an eviction inside this same batch can hand a
        # slot out twice, and a scatter with duplicate destinations has
        # an unspecified winner — the superseded entry must leave the
        # plan, or the slot map can end up pointing at the loser's row
        plan: dict = {}
        for _, i in eligible[:self.promote_batch]:
            self._miss_counts.pop(i, None)
            if self._used < self.hot_capacity:
                slot = self._used
                self._used += 1
            else:
                slot = self._evict_slot_locked()
            self._id_at[slot] = -1        # pending: reserved, unmapped
            self._ref[slot] = True
            plan[slot] = i
        slots = list(plan.keys())
        ids = [plan[s] for s in slots]
        # crude aging: the miss-count map must not grow with the key
        # space — when it outruns the cache by 8x, start over (hot rows
        # already resident are unaffected; cold tails just re-count)
        if len(self._miss_counts) > max(8 * self.hot_capacity, 1 << 16):
            self._miss_counts.clear()
        return ids, slots

    def _commit_promotions_locked(self, old_blk, new_blk, ids, slots
                                  ) -> bool:
        """Install a promotion scatter's result — only if ``old_blk``
        is still the current block (compare-and-swap). A concurrent
        promoter that lost the race leaves its slots reserved-but-empty
        (the clock reclaims them) and its rows simply re-count toward
        the next promotion; a lost update can only cost a re-fetch,
        never serve a wrong row."""
        if self._hot_block is not old_blk:
            return False
        self._hot_block = new_blk
        for i, slot in zip(ids, slots):
            self._slot_of[i] = slot
            self._id_at[slot] = i
        self._promotions += len(ids)
        return True

    def _ensure_hot_block(self):
        if self.hot_capacity == 0:
            return None
        blk = self._hot_block
        if blk is None:
            blk = jnp.zeros((self.hot_capacity, self.dim), self.dtype)
            with self._lock:
                if self._hot_block is None:
                    self._hot_block = blk
                blk = self._hot_block
        return blk

    # -- the fetch ---------------------------------------------------------

    def _gather(self, ids_host: np.ndarray):
        """The shortlist-only fetch: dedupe, split hot/miss, gather
        misses from the host source padded to a pow2 rung, plan
        promotions. Returns device operands + :class:`FetchInfo`."""
        m, c = ids_host.shape
        valid = ids_host >= 0
        info = FetchInfo(valid_slots=int(np.count_nonzero(valid)))
        vids = ids_host[valid].astype(np.int64, copy=False)
        uniq = np.unique(vids)                     # sorted
        info.unique_rows = int(uniq.size)
        with self._lock:
            ev0 = self._evictions
            slots = self._classify_locked(uniq)
            hot_u = slots >= 0
            miss_ids = uniq[~hot_u]
            pro_ids, pro_slots = self._plan_promotions_locked(miss_ids)
            info.promotions = len(pro_ids)
            info.evictions = self._evictions - ev0
            # the block snapshot rides the SAME lock hold as the
            # classification: every slot the map just handed out holds
            # its row in THIS block, and (undonated) XLA buffers stay
            # live for in-flight readers even after a later commit
            # installs a successor
            blk = self._hot_block
        info.hbm_hits = int(np.count_nonzero(hot_u))
        info.host_rows = int(miss_ids.size)
        rung = max(next_pow2(max(info.host_rows, 1)),
                   min(RUNG_FLOOR, next_pow2(max(m * c, 1))))
        info.rung = rung
        if miss_ids.size:
            block = np.zeros((rung, self.dim), self.dtype)
            # sorted unique ids -> one ascending strided read; the
            # memmap-friendly access pattern refine_host also relies on
            block[:miss_ids.size] = self.dataset[miss_ids]
            miss_dev = jax.device_put(block)
            info.bytes_link = rung * self.row_bytes
        else:
            # fully hot: serve the scorer a cached device zeros block —
            # nothing crosses the link
            miss_dev = self._zero_blocks.get(rung)
            if miss_dev is None:
                miss_dev = jnp.zeros((rung, self.dim), self.dtype)
                self._zero_blocks[rung] = miss_dev
            info.bytes_link = 0
        info.bytes_rows = info.host_rows * self.row_bytes
        # per-unique-row position: hot rows index the resident block,
        # misses index the freshly fetched one (in sorted-miss order)
        upos = np.empty(uniq.size, np.int32)
        upos[hot_u] = slots[hot_u].astype(np.int32)
        upos[~hot_u] = np.arange(info.host_rows, dtype=np.int32)
        safe = np.where(valid, ids_host, uniq[0] if uniq.size else 0)
        j = np.searchsorted(uniq, safe) if uniq.size else np.zeros(
            (m, c), np.int64)
        pos = upos[j] if uniq.size else np.zeros((m, c), np.int32)
        is_hot = hot_u[j] if uniq.size else np.zeros((m, c), bool)
        pos_dev = jax.device_put(np.ascontiguousarray(pos, np.int32))
        hot_dev = jnp.asarray(is_hot)
        promote = None
        if pro_ids:
            src = np.searchsorted(miss_ids, np.asarray(pro_ids, np.int64))
            src = np.resize(src.astype(np.int32), self.promote_batch)
            dst = np.full(self.promote_batch, self.hot_capacity, np.int32)
            dst[:len(pro_slots)] = np.asarray(pro_slots, np.int32)
            promote = (jax.device_put(src), jax.device_put(dst),
                       pro_ids, pro_slots)
        self._record(info)
        return miss_dev, pos_dev, hot_dev, blk, promote, info

    def _record(self, info: FetchInfo) -> None:
        with self._lock:
            self._lookups += info.unique_rows
            self._hbm_hits += info.hbm_hits
            self._host_rows += info.host_rows
            self._bytes_link += info.bytes_link
            lookups, hits = self._lookups, self._hbm_hits
        obs.counter("tiered.lookups_total", info.unique_rows)
        obs.counter("tiered.hits_total", info.hbm_hits, tier="hbm")
        obs.counter("tiered.hits_total", info.host_rows, tier="host")
        obs.counter("tiered.bytes_moved_total", info.bytes_link,
                    link="host_to_device")
        if info.promotions:
            obs.counter("tiered.promotions_total", info.promotions)
        if info.evictions:
            obs.counter("tiered.evictions_total", info.evictions)
        if lookups:
            obs.gauge("tiered.hit_rate", hits / lookups, tier="hbm")
            obs.gauge("tiered.hit_rate", 1.0 - hits / lookups,
                      tier="host")

    # -- the rerank --------------------------------------------------------

    def prepare(self, queries, candidates):
        """The host fetch for one shortlist batch: sync the ids, dedupe
        + hot/miss classify, gather misses, upload. Runs on graft-flow
        producer threads: the lock discipline in :meth:`_gather` and
        the CAS promotion commit in :meth:`score` make an overlapped
        ``prepare(N+1)`` vs ``score(N)`` race-free — at worst a
        concurrent classify misses a just-promoted row and re-fetches
        it (module docstring), never a wrong result."""
        from raft_tpu.resilience import faultinject

        # the fetch-stage fault point: slow@stage:tiered.fetch models
        # host-tier gather latency (stage-scoped only — chunk faults
        # stay with the consuming dispatch)
        faultinject.check(stage="tiered.fetch", stage_only=True)
        # the structural host sync of the tiered pipeline: the
        # shortlist ids must reach the host to drive the gather — this
        # is the ONE device->host hop the architecture is built around
        ids_host = np.asarray(candidates)  # the sync IS the tier boundary
        if ids_host.ndim != 2:
            raise ValueError(f"candidates must be [m, c], got "
                             f"{ids_host.shape}")
        if self.hot_capacity:
            self._ensure_hot_block()       # device alloc OUTSIDE _lock
        gathered = self._gather(ids_host)
        q = queries if isinstance(queries, jax.Array) \
            else jnp.asarray(queries)
        # stage 1 hands us a device int32 array: reuse it rather than
        # re-uploading the ids we just pulled down for the gather
        if (isinstance(candidates, jax.Array)
                and candidates.dtype == jnp.int32):
            cand = candidates
        else:
            cand = jnp.asarray(ids_host.astype(np.int32, copy=False))
        return (q, cand, gathered)

    def score(self, prepared, k, metric):
        q, cand, (miss_dev, pos_dev, hot_mask, blk, promote,
                  info) = prepared
        metric = resolve_metric(metric)
        if self.hot_capacity:
            d, i = _score_fetched_hot(q, miss_dev, blk, pos_dev,
                                      hot_mask, cand, int(k),
                                      int(metric))
            if promote is not None:
                # promoted rows are a subset of THIS batch's miss
                # block: scatter device-to-device (no second upload,
                # and NOT donated — a concurrent reader's snapshot of
                # the old block must stay readable). The plan reserved
                # the slots; the map only learns the new ids at the
                # compare-and-swap commit below, once their rows exist
                # in an installed block.
                src_pos, dst_slot, pro_ids, pro_slots = promote
                new_blk = _promote_scatter(blk, miss_dev, src_pos,
                                           dst_slot)
                with self._lock:
                    self._commit_promotions_locked(blk, new_blk,
                                                   pro_ids, pro_slots)
        else:
            d, i = _score_fetched(q, miss_dev, pos_dev, cand, int(k),
                                  int(metric))
        return d, i, info

    def rerank_info(self, queries, candidates, k, metric):
        return self.score(self.prepare(queries, candidates), k, metric)

    # -- warmup / stats ----------------------------------------------------

    def rungs(self, max_unique: int):
        """Every fetched-block rung an ``max_unique``-row shortlist can
        produce (the pow2 ladder warmup must cover)."""
        top = next_pow2(max(int(max_unique), 1))
        r = min(RUNG_FLOOR, top)
        out = []
        while r < top:
            out.append(r)
            r <<= 1
        out.append(top)
        return out

    def warm(self, m: int, c: int, k: int, metric,
             query_dtype=jnp.float32) -> int:
        """Trace the scorer (and the promotion scatter) at every rung
        an [m, c] shortlist can fetch, so steady-state serving adds
        zero XLA traces (the GL007 bar — serve's warmup calls this per
        (bucket, k-rung) pair)."""
        metric = resolve_metric(metric)
        q = jnp.zeros((m, self.dim), query_dtype)
        cand = jnp.full((m, c), -1, jnp.int32)
        pos = jnp.zeros((m, c), jnp.int32)
        hot_mask = jnp.zeros((m, c), bool)
        blk = self._ensure_hot_block()
        traced = 0
        for rung in self.rungs(m * c):
            miss = jnp.zeros((rung, self.dim), self.dtype)
            if blk is not None:
                out = _score_fetched_hot(q, miss, blk, pos, hot_mask,
                                         cand, int(k), int(metric))
                src = jnp.zeros((self.promote_batch,), jnp.int32)
                dst = jnp.full((self.promote_batch,), self.hot_capacity,
                               jnp.int32)
                # trace only — every real dst is out of bounds, and
                # without donation the result needs no install
                out = (out, _promote_scatter(blk, miss, src, dst))
            else:
                out = _score_fetched(q, miss, pos, cand, int(k),
                                     int(metric))
            jax.block_until_ready(out)
            traced += 1
        return traced

    def stats(self) -> dict:
        with self._lock:
            lookups = self._lookups
            return {
                "lookups": lookups,
                "hbm_hits": self._hbm_hits,
                "host_rows": self._host_rows,
                "hit_rate_hbm": (self._hbm_hits / lookups) if lookups
                else 0.0,
                "bytes_moved": self._bytes_link,
                "evictions": self._evictions,
                "promotions": self._promotions,
                "hot_capacity": self.hot_capacity,
                "hot_used": self._used,
            }


# ---------------------------------------------------------------------------
# device kernels (shape-stable: traced per (m, c, rung); warm() covers
# the rung ladder so serving never compiles in steady state)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(4, 5))
def _score_fetched(queries, block, pos, candidates, k: int,
                   metric_val: int):
    """Exact scoring over the fetched miss block only (no hot cache):
    gather [m, c, d] candidate vectors by block position, then the
    shared :func:`refine.score_gathered` tail."""
    metric = DistanceType(metric_val)
    compute = jnp.promote_types(queries.dtype, jnp.float32)
    q = queries.astype(compute)
    safe = jnp.clip(pos, 0, block.shape[0] - 1)
    cand_vecs = block[safe].astype(compute)
    return _score_gathered(q, cand_vecs, candidates, k, metric)


@functools.partial(jax.jit, static_argnums=(6, 7))
def _score_fetched_hot(queries, block, hot_block, pos, is_hot,
                       candidates, k: int, metric_val: int):
    """Exact scoring over the two-tier candidate store: ``pos`` indexes
    the hot HBM block where ``is_hot``, the fetched miss block
    elsewhere. Two gathers + a select keep the cost O(m*c*d) — never
    O(hot_capacity) per batch."""
    metric = DistanceType(metric_val)
    compute = jnp.promote_types(queries.dtype, jnp.float32)
    q = queries.astype(compute)
    vm = block[jnp.clip(pos, 0, block.shape[0] - 1)]
    vh = hot_block[jnp.clip(pos, 0, hot_block.shape[0] - 1)]
    cand_vecs = jnp.where(is_hot[..., None], vh, vm).astype(compute)
    return _score_gathered(q, cand_vecs, candidates, k, metric)


@jax.jit
def _promote_scatter(hot_block, miss_block, src_pos, dst_slot):
    """Build the successor hot block: promoted rows scattered in FROM
    the already uploaded miss block (device-to-device — promotion
    costs zero link bytes). Padding entries carry ``dst_slot ==
    capacity`` and drop at the out-of-bounds scatter. Deliberately NOT
    donated: an in-flight reader scores against its own snapshot of
    the old block, which must stay readable after the commit installs
    this result — promotions pay one block copy for that (bounded by
    the hot budget, and steady state promotes nothing)."""
    rows = miss_block[jnp.clip(src_pos, 0, miss_block.shape[0] - 1)]
    return hot_block.at[dst_slot].set(rows, mode="drop")


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------


def as_source(dataset, hot_rows: Optional[int] = None) -> RerankSource:
    """Resolve a ``dataset=`` value to a :class:`RerankSource`:

    * a source instance passes through (the persistent-hot-cache path);
    * a device ``jax.Array`` keeps the full-upload
      :class:`DeviceSource` fast path (back-compat: an
      already-uploaded dataset is never re-tiered);
    * a host ``np.ndarray`` / ``np.memmap`` becomes a
      :class:`HostArraySource` — per-call, so the hot cache defaults
      OFF here (``hot_rows=0``); construct the source yourself to keep
      residency across calls;
    * anything else (lists, tuples) uploads like before.
    """
    if isinstance(dataset, RerankSource):
        return dataset
    if isinstance(dataset, jax.Array):
        return DeviceSource(dataset)
    if isinstance(dataset, np.ndarray):
        return HostArraySource(
            dataset, hot_rows=0 if hot_rows is None else hot_rows)
    return DeviceSource(jnp.asarray(dataset))


def memmap_source(path: str, dim: Optional[int] = None, dtype=None,
                  hot_rows: Optional[int] = None,
                  offset: int = 0) -> HostArraySource:
    """Open a raw row-major vector file as a memory-mapped
    :class:`HostArraySource`. With ``dim=None`` the file is read as
    big-ann ``*.bin``/``.fbin`` (8-byte ``[n, d]`` uint32 header, f32
    rows unless ``dtype`` says otherwise) — the same layout
    :class:`~raft_tpu.utils.batch.FileBatchLoadIterator` streams."""
    if dim is None:
        header = np.fromfile(path, dtype=np.uint32, count=2)
        n, dim = int(header[0]), int(header[1])
        offset = 8
        dtype = np.float32 if dtype is None else dtype
        mm = np.memmap(path, dtype=np.dtype(dtype), mode="r",
                       offset=offset, shape=(n, dim))
    else:
        dtype = np.float32 if dtype is None else dtype
        mm = np.memmap(path, dtype=np.dtype(dtype), mode="r",
                       offset=offset)
        n = mm.size // int(dim)
        mm = mm[: n * int(dim)].reshape(n, int(dim))
    return HostArraySource(mm, hot_rows=hot_rows)


__all__ = [
    "DEFAULT_HOT_ROWS", "DeviceSource", "FetchInfo", "HOT_ROWS_BUDGET",
    "HostArraySource", "PROMOTE_BATCH", "RUNG_FLOOR", "RerankSource",
    "as_source", "memmap_source",
]
