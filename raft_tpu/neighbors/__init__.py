"""Neighbors layer — the core product (SURVEY.md §2.9)."""

from raft_tpu.neighbors import (
    ball_cover,
    brute_force,
    cagra,
    epsilon_neighborhood,
    hybrid,
    ivf_flat,
    ivf_pq,
    nn_descent,
    refine as _refine_mod,
)
from raft_tpu.neighbors.common import (
    BitsetFilter,
    IndexParams,
    NoneSampleFilter,
    SearchParams,
    knn_merge_parts,
    merge_topk,
)
from raft_tpu.neighbors.refine import refine, refine_host
from raft_tpu.neighbors import stream, tiered

__all__ = [
    "ball_cover",
    "brute_force",
    "epsilon_neighborhood",
    "hybrid",
    "nn_descent",
    "cagra",
    "ivf_flat",
    "ivf_pq",
    "refine",
    "refine_host",
    "stream",
    "tiered",
    "BitsetFilter",
    "IndexParams",
    "NoneSampleFilter",
    "SearchParams",
    "knn_merge_parts",
    "merge_topk",
]
