"""Neighbors layer — the core product (SURVEY.md §2.9)."""

from raft_tpu.neighbors import (
    brute_force,
    cagra,
    ivf_flat,
    ivf_pq,
    refine as _refine_mod,
)
from raft_tpu.neighbors.common import (
    BitsetFilter,
    IndexParams,
    NoneSampleFilter,
    SearchParams,
    knn_merge_parts,
    merge_topk,
)
from raft_tpu.neighbors.refine import refine

__all__ = [
    "brute_force",
    "cagra",
    "ivf_flat",
    "ivf_pq",
    "refine",
    "BitsetFilter",
    "IndexParams",
    "NoneSampleFilter",
    "SearchParams",
    "knn_merge_parts",
    "merge_topk",
]
