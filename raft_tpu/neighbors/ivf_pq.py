"""IVF-PQ: inverted-file index with product-quantized residuals.

TPU-native analog of the reference's ivf_pq
(cpp/include/raft/neighbors/ivf_pq.cuh; types ivf_pq_types.hpp:48-146; build
detail/ivf_pq_build.cuh:1753; search detail/ivf_pq_search.cuh:732 + LUT
similarity kernel detail/ivf_pq_compute_similarity-inl.cuh).

Build mirrors the reference pipeline: balanced-kmeans coarse centers, an
orthogonal rotation (QR of a random matrix, make_rotation_matrix:122),
per-subspace or per-cluster PQ codebooks trained on residuals
(train_per_subset:395 / train_per_cluster:472), then codes packed into
padded list blocks (process_and_fill_codes:1322).

Search is re-designed for the MXU rather than ported (SURVEY.md §7 "hard
parts" #1): the reference builds a per-(query,probe) LUT in shared memory
and gathers LUT entries per code. TPUs have no fast per-lane gather, so we
**decode-then-matmul**: reconstruct each probed list block from its codes
(a small codebook gather), then score a whole query group against the block
with one ``[G, rot_dim] x [rot_dim, cap]`` MXU contraction — identical
shape to the IVF-Flat scan, with ``||recon||^2`` precomputed at build. The
index stays PQ-compressed in HBM (codes + 1 f32 norm per vector), which is
what buys billion-scale capacity; decode cost is amortized over the whole
query group sharing the list.

Uses the same bucketize-by-list machinery as ivf_flat (bucketize_pairs).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu import obs
from raft_tpu.core.serialize import read_index_file, write_index_file
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.matrix.select_k import select_k
from raft_tpu.neighbors.common import (
    as_filter,
    filter_keep,
    merge_topk,
    resolve_filter_bits,
    sentinel_for,
)
from raft_tpu.neighbors.ivf_flat import (
    _pack_lists,
    bucketize_pairs,
    unbucketize_merge,
)
from raft_tpu.utils.math import round_up_to_multiple
from raft_tpu.utils.precision import dist_dot

_SERIAL_VERSION = 4  # v4: rabitq sign-bit cache (cache_fac sidecar)
# (v3: serialized cache for cache-only indexes;
#  v2: bit-packed uint32 code words + pq_dim in meta)


class codebook_gen:
    """Codebook training mode (reference ivf_pq_types.hpp:48)."""

    PER_SUBSPACE = 0
    PER_CLUSTER = 1


# metrics the PQ residual scoring path implements; anything else would be
# silently mis-scored as L2 (reference ivf_pq has the same L2/IP restriction)
_SUPPORTED_METRICS = frozenset({
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded,
    DistanceType.InnerProduct,
})


@dataclasses.dataclass
class IndexParams:
    """Build params (reference ivf_pq_types.hpp:48-97)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8
    pq_dim: int = 0  # 0 → auto: dim/4 rounded to a multiple of 8 (reference heuristic)
    codebook_kind: int = codebook_gen.PER_SUBSPACE
    force_random_rotation: bool = False
    add_data_on_build: bool = True
    # coarse-quantizer training GEMM dtype ("f32" | "bf16", see ivf_flat)
    kmeans_compute_dtype: str = "f32"
    # build the decoded-residual cache (fused-Pallas search path);
    # auto-skipped above _CACHE_BUDGET bytes
    cache_decoded: bool = True
    # cache precision: "auto" picks int8 when it fits _CACHE_BUDGET and
    # falls to a half-byte rung (0.5 B/component — the 100M-scale regime
    # where int8 cannot share HBM with the codes) when that fits; which
    # half-byte rung (packed int4 residuals vs pq4 codes, recall-tied at
    # equal bytes) comes from the measured dispatch table
    # (docs/dispatch_tuning.md), defaulting to int4. "i8" / "i4" / "pq4"
    # force a kind (still budget-gated)
    cache_dtype: str = "auto"

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in _SUPPORTED_METRICS:
            raise ValueError(
                f"ivf_pq supports {sorted(m.name for m in _SUPPORTED_METRICS)}, "
                f"got {self.metric!r}"
            )
        if not 4 <= self.pq_bits <= 8:
            raise ValueError(f"pq_bits must be in [4, 8], got {self.pq_bits}")


@dataclasses.dataclass
class SearchParams:
    """Search params (reference ivf_pq_types.hpp:110-146)."""

    n_probes: int = 20
    # Decode/scoring operand dtype ladder (the reference's LUT dtype ladder,
    # ivf_pq_types.hpp lut_dtype fp32/fp16/fp8): "auto" | "i8" | "f32" |
    # "bf16" | "f8". "auto" (default) scans the int8 decoded-residual
    # cache when the index carries one (the fast path; finer than the
    # reference's fp8 LUT) and falls back to f32 decode. "i8" requires the
    # cache. Explicit "f32"/"bf16"/"f8" force the decode-then-matmul scan
    # at that precision (jnp dtypes accepted).
    lut_dtype: object = "auto"
    # Distance accumulation/report dtype: "f32" | "bf16" (the reference's
    # internal_distance_dtype fp32/fp16 analog).
    internal_distance_dtype: object = "f32"
    # TPU tuning knobs (same role as in ivf_flat.SearchParams)
    query_group: int = 256
    bucket_batch: int = 32
    compute_dtype: str = "bf16"        # matmul operand dtype (f32 accumulate)
    # recall target for the per-list approx top-k; >= 1.0 runs it exactly.
    # The fused Pallas path also caps per-list extraction at 256
    # candidates (the reference's kMaxCapacity analog) — see
    # ivf_flat.SearchParams.local_recall_target.
    local_recall_target: float = 0.95
    # recall target for the FINAL cross-probe merge. Default 1.0 = exact
    # final selection, matching the reference's exact select_k merge
    # (ivf_pq_search.cuh:587); < 1.0 opts into the approximate merge.
    merge_recall_target: float = 1.0
    # "auto" = fused Pallas scan over the decoded-residual cache when the
    # index has one (TPU, lane-aligned cap, k<=64), else the XLA
    # decode-then-matmul scan; "pallas" | "pallas_interpret" | "xla" force
    scan_impl: str = "auto"


@dataclasses.dataclass
class Index:
    """IVF-PQ index (reference ivf_pq_types.hpp:199+).

    ``codes`` [n_lists, cap, n_words] uint32 — **bit-packed** PQ codes:
    ``32 // pq_bits`` codes per word (the reference packs a dense byte
    bitfield, ivf_pq_types.hpp:172-187; the word layout here avoids
    word-straddling codes, wasting <= 4 bits/word for pq_bits in {5,6,7}
    and nothing for 4/8 — shift+mask decode stays a pure VPU op).
    ``rec_norms`` [n_lists, cap] f32 (``||reconstructed residual||^2``);
    ``pq_centers``: [pq_dim, K, pq_len] (PER_SUBSPACE) or
    [n_lists, K, pq_len] (PER_CLUSTER); ``rotation`` [rot_dim, dim].
    """

    centers: jax.Array          # [n_lists, dim] f32
    centers_rot: jax.Array      # [n_lists, rot_dim] f32
    rotation: jax.Array         # [rot_dim, dim] f32
    pq_centers: jax.Array
    codes: jax.Array            # [n_lists, cap, n_words] uint32 (packed)
    indices: jax.Array          # [n_lists, cap] int32
    list_sizes: jax.Array       # [n_lists] int32
    rec_norms: jax.Array        # [n_lists, cap] f32
    metric: DistanceType
    pq_dim_: int
    metric_arg: float = 2.0
    codebook_kind: int = codebook_gen.PER_SUBSPACE
    pq_bits: int = 8
    # optional decoded-residual cache: int8 [n_lists, cap, rot_dim] (with
    # scalar ``recon_scale``) or packed int4 [n_lists, rot_dim//8, cap]
    # uint32 (with PER-LIST per-component ``cache_scales``
    # [n_lists, rot_dim] and dequantized norms ``cache_qnorms``). The codes stay the compressed
    # source of truth; search scans the cache with the fused Pallas
    # kernel (one MXU matmul per list block) instead of decode-then-
    # matmul. Budget-gated by _CACHE_BUDGET; rebuilt on load/extend
    # unless the index is cache-only (keep_codes=False), in which case
    # the cache IS serialized.
    recon_cache: object = None
    recon_scale: float = 1.0
    cache_scales: object = None      # [n_lists, rot_dim] f32 (int4 only)
    cache_qnorms: object = None      # [n_lists, cap] f32 (i4/rabitq caches)
    # rabitq per-row correction fac = ||r||²/||r||₁ ([n_lists, cap] f32):
    # the RaBitQ estimator's scalar — <q, r> ≈ fac · Σ_j sign(r_j)·q_j.
    # Presence discriminates the rabitq sign-bit cache from the other
    # uint32 kinds (see cache_kind)
    cache_fac: object = None
    cache_decoded: bool = True
    cache_dtype: str = "auto"

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.rotation.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_dim(self) -> int:
        return self.pq_dim_

    @property
    def pq_len(self) -> int:
        return self.rot_dim // self.pq_dim

    @property
    def pq_book_size(self) -> int:
        return 1 << self.pq_bits

    @property
    def size(self) -> int:
        return int(self.list_sizes.sum())

    @property
    def cache_kind(self) -> str:
        """Which fused-scan operand the index carries: "i8" (int8 decoded
        residuals), "i4" (packed int4 raw residuals + per-list scales),
        "pq4" (transposed packed 4-bit codes — exact one-hot code scan),
        "rabitq" (packed sign bits + per-row norm/fac scalars — the
        ~32×-compressed first-stage rung), or "none". The u32 kinds are
        discriminated by their scalar sidecars: rabitq cannot exist
        without cache_fac, the i4 residual cache not without its
        per-list scales."""
        if self.recon_cache is None:
            return "none"
        if self.recon_cache.dtype == jnp.uint32:
            if self.cache_fac is not None:
                return "rabitq"
            return "i4" if self.cache_scales is not None else "pq4"
        return "i8"


jax.tree_util.register_dataclass(
    Index,
    data_fields=["centers", "centers_rot", "rotation", "pq_centers", "codes",
                 "indices", "list_sizes", "rec_norms", "recon_cache",
                 "cache_scales", "cache_qnorms", "cache_fac"],
    meta_fields=["metric", "pq_dim_", "metric_arg", "codebook_kind",
                 "pq_bits", "recon_scale", "cache_decoded", "cache_dtype"],
)

# decoded-residual cache is skipped when n_lists * cap * rot_dim exceeds
# this budget (bytes) — the decode-then-matmul scan path is used instead
_CACHE_BUDGET = 10 << 30


# ---------------------------------------------------------------------------
# bit-packed code words (reference ivf_pq_types.hpp:172-187 bitfield)
# ---------------------------------------------------------------------------


def codes_per_word(pq_bits: int) -> int:
    return 32 // pq_bits


def packed_words(pq_dim: int, pq_bits: int) -> int:
    return -(-pq_dim // codes_per_word(pq_bits))


def pack_codes(codes, pq_bits: int) -> jax.Array:
    """[..., pq_dim] uint8 -> [..., n_words] uint32 (no straddling)."""
    cpw = codes_per_word(pq_bits)
    p = codes.shape[-1]
    nw = packed_words(p, pq_bits)
    pad = nw * cpw - p
    c = jnp.asarray(codes).astype(jnp.uint32)
    if pad:
        c = jnp.concatenate(
            [c, jnp.zeros((*c.shape[:-1], pad), jnp.uint32)], axis=-1
        )
    c = c.reshape(*c.shape[:-1], nw, cpw)
    shifts = (jnp.arange(cpw, dtype=jnp.uint32) * pq_bits)
    return jnp.sum(c << shifts, axis=-1, dtype=jnp.uint32)


def unpack_codes(packed, pq_dim: int, pq_bits: int) -> jax.Array:
    """[..., n_words] uint32 -> [..., pq_dim] int32."""
    cpw = codes_per_word(pq_bits)
    j = jnp.arange(pq_dim)
    words = jnp.take(packed, j // cpw, axis=-1)          # [..., p]
    shifts = ((j % cpw) * pq_bits).astype(jnp.uint32)
    mask = jnp.uint32((1 << pq_bits) - 1)
    return ((words >> shifts) & mask).astype(jnp.int32)


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def make_rotation_matrix(
    rot_dim: int, dim: int, force_random: bool, key
) -> jax.Array:
    """Orthogonal rotation (reference ivf_pq_build.cuh:122): identity-padded
    unless forced random or rot_dim != dim, in which case QR of a Gaussian."""
    if not force_random and rot_dim == dim:
        return jnp.eye(dim, dtype=jnp.float32)
    g = jax.random.normal(key, (max(rot_dim, dim), max(rot_dim, dim)), jnp.float32)
    q, _ = jnp.linalg.qr(g)
    return q[:rot_dim, :dim]


def _auto_pq_dim(dim: int) -> int:
    # reference heuristic: dim/4 rounded down to a multiple of 8, >= 8
    v = max(8, (dim // 4) // 8 * 8)
    return min(v, dim)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _encode_subspace(residuals, pq_centers, K: int, block: int = 1 << 14):
    """codes[n, p] = argmin_j ||residuals[n,p,:] - pq_centers[p,j,:]||^2.

    Row-blocked under ``lax.map`` so the [block, p, K] distance tensor is
    the peak transient — unblocked, n=1M × p=64 × K=256 is a 65 GB
    intermediate (this crashed a v5e at CAGRA-build scale)."""
    n, p, plen = residuals.shape
    cn = jnp.sum(pq_centers * pq_centers, axis=2)[None, :, :]

    def one_block(res_b):
        dots = jnp.einsum(
            "npl,pkl->npk", res_b, pq_centers,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        rn = jnp.sum(res_b * res_b, axis=2)[:, :, None]
        return jnp.argmin(rn - 2.0 * dots + cn, axis=2).astype(jnp.uint8)

    if n <= block:
        return one_block(residuals)
    npad = -(-n // block) * block
    res_p = jnp.pad(residuals, ((0, npad - n), (0, 0), (0, 0)))
    out = jax.lax.map(one_block, res_p.reshape(npad // block, block, p, plen))
    return out.reshape(npad, p)[:n]


def _decode_gather(codes, pq_centers, codebook_kind: int, list_ids=None):
    """Reconstruct rotated residuals from codes: one flat row-gather.

    codes [..., pq_dim] uint8 → [..., rot_dim] f32.
    PER_SUBSPACE: pq_centers [p, K, len], row index = s*K + code;
    PER_CLUSTER: pq_centers [C, K, len], row index = list*K + code with
    ``list_ids`` broadcastable to codes[..., 0]."""
    c32 = codes.astype(jnp.int32)
    K = pq_centers.shape[1]
    if codebook_kind == codebook_gen.PER_SUBSPACE:
        p = pq_centers.shape[0]
        flat_idx = c32 + (jnp.arange(p, dtype=jnp.int32) * K)  # [..., p]
    else:
        flat_idx = c32 + (jnp.asarray(list_ids, jnp.int32) * K)[..., None]
    table = pq_centers.reshape(-1, pq_centers.shape[-1])  # [p*K | C*K, len]
    recon = jnp.take(table, flat_idx, axis=0)  # [..., p, len]
    return recon.reshape(*codes.shape[:-1], -1)


def build(params: IndexParams, dataset, batch_size: Optional[int] = None) -> Index:
    """Build the index (reference ivf_pq_build.cuh:1753).

    ``batch_size`` streams an out-of-core host dataset through the encoder
    in fixed-size device batches (the reference's batch_load_iterator
    pipeline, spatial/knn/detail/ann_utils.cuh:397) — only the trainset,
    the per-batch slab, and the compressed codes ever live in HBM.
    """
    stream = batch_size is not None
    if stream and not isinstance(dataset, jax.Array):
        dataset = np.asarray(dataset)
    elif not stream:
        dataset = jnp.asarray(dataset)
    n, dim = dataset.shape

    with obs.entry_span("build", "ivf_pq", rows=int(n),
                        n_lists=int(params.n_lists), streamed=stream):
        # coarse centers train on a subsample (build.cuh: build_clusters)
        frac = float(params.kmeans_trainset_fraction)
        if 0 < frac < 1.0 and int(n * frac) >= int(params.n_lists):
            trainset = jnp.asarray(dataset[:: max(int(1.0 / frac), 1)])
        else:
            trainset = jnp.asarray(dataset)
        with obs.span("ivf_pq.build.train"):
            index = _quantizer_index(params, trainset, dim)
        if not params.add_data_on_build:
            return index
        with obs.span("ivf_pq.build.encode"):
            if not stream:
                return extend(index, dataset, jnp.arange(n, dtype=jnp.int32))
            return _stream_encode(params, index, dataset, n, int(batch_size))


def _quantizer_index(params: IndexParams, trainset, dim: int) -> Index:
    """Train all quantizers (coarse centers, rotation, PQ codebooks) on
    ``trainset`` and return the EMPTY index (reference ivf_pq_build.cuh
    steps: build_clusters, make_rotation_matrix:122, select_residuals:166,
    train_per_subset:395 / train_per_cluster:472)."""
    n_lists = int(params.n_lists)
    pq_dim = int(params.pq_dim) or _auto_pq_dim(dim)
    pq_len = -(-dim // pq_dim)
    rot_dim = pq_dim * pq_len
    K = 1 << int(params.pq_bits)
    key = jax.random.PRNGKey(0)

    kb = KMeansBalancedParams(
        n_clusters=n_lists,
        n_iters=int(params.kmeans_n_iters),
        metric=(
            DistanceType.InnerProduct
            if params.metric == DistanceType.InnerProduct
            else DistanceType.L2Expanded
        ),
        compute_dtype=str(params.kmeans_compute_dtype),
    )
    centers = kmeans_balanced.fit(kb, trainset)

    # 2. rotation (build.cuh:122 make_rotation_matrix)
    key, kr = jax.random.split(key)
    rotation = make_rotation_matrix(
        rot_dim, dim, bool(params.force_random_rotation), kr
    )
    centers_rot = dist_dot(centers, rotation.T)  # [C, rot_dim]

    # 3. residuals of the trainset (build.cuh:166 select_residuals)
    t32 = trainset.astype(jnp.float32)
    t_labels = kmeans_balanced.predict(kb, centers, trainset)
    t_rot = dist_dot(t32, rotation.T)
    t_res = (t_rot - centers_rot[t_labels]).reshape(-1, pq_dim, pq_len)

    # 4. PQ codebooks — batched device training, one compiled program for
    # all books (train_per_subset:395 / train_per_cluster:472 replacements;
    # the reference launches one balanced-kmeans per book)
    key, ks = jax.random.split(key)
    n_train = t_res.shape[0]
    if params.codebook_kind == codebook_gen.PER_SUBSPACE:
        # xs [p, S, len]: same row subsample for every subspace
        S = min(n_train, max(K * 32, 8192))
        sel = jax.random.choice(ks, n_train, (S,), replace=n_train < S)
        xs = jnp.transpose(t_res[sel], (1, 0, 2))          # [p, S, len]
        key, kt = jax.random.split(key)
        pq_centers = kmeans_balanced.build_clusters_batched(xs, K, 10, kt)
    else:
        # xs [C, S, len]: S rows per cluster, wrapped from each cluster's
        # contiguous run in label-sorted order; empty clusters fall back
        # to global rows. S caps the per-book subvector count (~16k) to
        # bound the gather.
        S = max(64, 16384 // pq_dim)
        flat = t_res.reshape(n_train, pq_dim * pq_len)
        order = jnp.argsort(t_labels)
        counts = jnp.bincount(t_labels, length=n_lists)
        starts = jnp.cumsum(counts) - counts
        s_idx = jnp.arange(S)
        pos = starts[:, None] + s_idx[None, :] % jnp.maximum(counts[:, None], 1)
        pos = jnp.where(counts[:, None] > 0, pos, s_idx[None, :] % n_train)
        rows = flat[order][pos]                             # [C, S, p*len]
        # a cluster codebook is trained on all its subvectors jointly
        xs = rows.reshape(n_lists, S * pq_dim, pq_len)
        key, kt = jax.random.split(key)
        pq_centers = kmeans_balanced.build_clusters_batched(xs, K, 10, kt)

    index = Index(
        centers=centers,
        centers_rot=centers_rot,
        rotation=rotation,
        pq_centers=pq_centers,
        codes=jnp.zeros(
            (n_lists, 0, packed_words(pq_dim, int(params.pq_bits))),
            jnp.uint32,
        ),
        indices=jnp.full((n_lists, 0), -1, jnp.int32),
        list_sizes=jnp.zeros((n_lists,), jnp.int32),
        rec_norms=jnp.zeros((n_lists, 0), jnp.float32),
        metric=params.metric,
        pq_dim_=pq_dim,
        metric_arg=params.metric_arg,
        codebook_kind=int(params.codebook_kind),
        pq_bits=int(params.pq_bits),
        cache_decoded=bool(params.cache_decoded),
        cache_dtype=str(params.cache_dtype),
    )
    return index


def _stream_encode(params: IndexParams, index: Index, dataset, n: int,
                   batch_size: int) -> Index:
    """Streaming encode over a materialized (host or device) dataset:
    fixed-shape batches keep one compiled encoder; only compressed codes
    accumulate on device. Device-resident datasets are sliced in place
    (a host round-trip through the BatchLoadIterator would cost minutes
    over the dev tunnel)."""
    n_lists = index.n_lists
    pq_dim = index.pq_dim
    parts_labels, parts_codes = [], []
    if isinstance(dataset, jax.Array):
        bs = int(batch_size)
        for off in range(0, n, bs):
            # dynamic_slice clamps an out-of-bounds start, producing the
            # shifted static-shape tail window the `keep` logic expects
            batch = jax.lax.dynamic_slice_in_dim(
                dataset, off, min(bs, n), axis=0,
            )
            lab, packed = encode(index, batch)
            if off + bs > n and n >= bs:
                # final window was shifted back to keep a static shape;
                # keep only the genuinely-new tail rows
                keep = n - off
                lab = lab[-keep:]
                packed = packed[-keep:]
            parts_labels.append(lab)
            parts_codes.append(packed)
    else:
        from raft_tpu.utils.batch import BatchLoadIterator

        for off, batch in BatchLoadIterator(dataset, int(batch_size),
                                            pad_to_full=True):
            lab, packed = encode(index, batch)
            parts_labels.append(lab)
            parts_codes.append(packed)
    labels = jnp.concatenate(parts_labels)[:n]
    packed = jnp.concatenate(parts_codes)[:n]
    ids = jnp.arange(n, dtype=jnp.int32)

    from raft_tpu.neighbors.ivf_flat import _aligned_cap

    counts = np.bincount(np.asarray(labels), minlength=n_lists)
    cap = _aligned_cap(int(counts.max()))
    codes_packed, indices, list_sizes = _pack_lists(
        packed, labels, ids, n_lists, cap
    )
    rec_norms = _rec_norms(
        codes_packed, index.pq_centers, int(params.codebook_kind),
        pq_dim, int(params.pq_bits),
    )
    return _attach_cache(dataclasses.replace(
        index,
        codes=codes_packed,
        indices=indices,
        list_sizes=list_sizes,
        rec_norms=rec_norms,
    ))


def _restore_quantizer(params: IndexParams, arrays, dim: int) -> Index:
    """Rebuild the empty quantizer Index from checkpointed arrays — the
    resume path must NOT retrain kmeans (bitwise identity of the resumed
    build is anchored on the exact quantizers the killed run used)."""
    n_lists = int(params.n_lists)
    pq_dim = int(params.pq_dim) or _auto_pq_dim(dim)
    return Index(
        centers=jnp.asarray(arrays["centers"]),
        centers_rot=jnp.asarray(arrays["centers_rot"]),
        rotation=jnp.asarray(arrays["rotation"]),
        pq_centers=jnp.asarray(arrays["pq_centers"]),
        codes=jnp.zeros(
            (n_lists, 0, packed_words(pq_dim, int(params.pq_bits))),
            jnp.uint32,
        ),
        indices=jnp.full((n_lists, 0), -1, jnp.int32),
        list_sizes=jnp.zeros((n_lists,), jnp.int32),
        rec_norms=jnp.zeros((n_lists, 0), jnp.float32),
        metric=params.metric,
        pq_dim_=pq_dim,
        metric_arg=params.metric_arg,
        codebook_kind=int(params.codebook_kind),
        pq_bits=int(params.pq_bits),
        cache_decoded=bool(params.cache_decoded),
        cache_dtype=str(params.cache_dtype),
    )


def _quant_arrays(index: Index, ts_scales) -> dict:
    out = {
        "centers": index.centers,
        "centers_rot": index.centers_rot,
        "rotation": index.rotation,
        "pq_centers": index.pq_centers,
    }
    if ts_scales is not None:
        out["ts_scales"] = ts_scales
    return out


def build_streamed(
    params: IndexParams,
    make_batches,
    n: int,
    dim: int,
    trainset,
    keep_codes: bool = True,
    cap_rows: Optional[int] = None,
    verbose: bool = False,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 8,
    resume: bool = False,
    token=None,
    pipeline_depth: Optional[int] = None,
) -> Index:
    """Build from a re-iterable stream of fixed-shape device batches —
    the out-of-core path for datasets too large for HBM or host RAM.
    Thin observed entry: opens the ``ivf_pq_streamed.build`` span and
    counts per-phase progress (``stream_chunks_total{stage=build.pass1|
    build.pass2}``) around :func:`_build_streamed_impl`, which carries
    the full memory-model / resilience contract docs."""
    with obs.entry_span("build", "ivf_pq_streamed", rows=int(n),
                        n_lists=int(params.n_lists), resume=bool(resume),
                        keep_codes=bool(keep_codes)):
        return _build_streamed_impl(
            params, make_batches, n, dim, trainset, keep_codes=keep_codes,
            cap_rows=cap_rows, verbose=verbose,
            checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
            resume=resume, token=token, pipeline_depth=pipeline_depth,
        )


def _build_streamed_impl(
    params: IndexParams,
    make_batches,
    n: int,
    dim: int,
    trainset,
    keep_codes: bool = True,
    cap_rows: Optional[int] = None,
    verbose: bool = False,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 8,
    resume: bool = False,
    token=None,
    pipeline_depth: Optional[int] = None,
) -> Index:
    """Build from a RE-ITERABLE stream of fixed-shape device batches —
    the path for datasets too large for HBM *or host RAM* (DEEP-100M at
    f32 is 38 GB; the reference handles this scale by mmap +
    batch_load_iterator, ann_utils.cuh:397 + dataset.hpp:45).

    ``make_batches()`` must return a fresh iterator of [batch, dim]
    device arrays each call (iterated twice: label-count pass, then
    encode+scatter pass); the final batch may be zero-padded — only the
    first ``n`` total rows are stored. ``trainset`` is the
    quantizer-training subsample (device array).

    Memory model: accumulators are written in place per batch via buffer
    donation, so peak HBM is the final index plus ONE batch's transients
    — the materialized [n, n_words] code slab of the `build(batch_size=)`
    path never exists. With ``keep_codes=False`` the packed codes
    themselves are dropped and only the quantized residual cache is
    stored — int8 decoded-PQ when it fits _CACHE_BUDGET, else the
    packed-int4 RAW-residual cache at 0.5 B/component (the DEEP-100M
    configuration: codes and any cache together exceed HBM at that
    scale); such an index searches via the fused cache path only.

    Resilience (docs/resilience.md): ``checkpoint_dir`` persists a
    per-chunk manifest + state blob (quantizers after training, labels
    through pass 1, the donated accumulators every ``checkpoint_every``
    batches of pass 2); ``resume=True`` restores the latest state —
    quantizers are NOT retrained, so the resumed build's output is
    bitwise identical to the uninterrupted one (resume with the same
    ``make_batches`` shape). Each blob is SELF-CONTAINED (quantizers +
    labels-so-far + accumulators) so a single file always suffices to
    resume — the cost is rewriting that state every save, so size
    ``checkpoint_every`` to the scale: at 100M rows each pass-2 save
    moves the full accumulator set; larger ``checkpoint_every`` trades
    replayed batches for checkpoint I/O. ``token`` (default: the calling
    thread's :class:`~raft_tpu.core.interruptible.Interruptible`) is
    checked at every batch so ``cancel()`` from another thread stops the
    hours-long job at the next chunk boundary.

    ``pipeline_depth`` (default: the ``pipeline_depth`` tuning budget)
    runs ``make_batches()`` on a graft-flow producer for each pass, so
    the caller's host read + device upload for batch N+1 overlaps batch
    N's label/scatter compute. Bitwise-invariant at any depth (the
    stream's items and order are unchanged); checkpoints still save
    only after a batch's scatter dispatched (a prefetched batch is
    never marked done), and a caller-side read error surfaces at the
    consuming batch, classified as usual.
    """
    from raft_tpu.core import pipeline as _pipeline
    from raft_tpu.neighbors.ivf_flat import _aligned_cap
    from raft_tpu import resilience
    from raft_tpu.core.interruptible import Interruptible
    from raft_tpu.resilience import faultinject

    import time as _time

    _t0 = _time.time()
    if token is None:
        token = Interruptible.get_token()
    ck = (resilience.StreamCheckpoint(checkpoint_dir)
          if checkpoint_dir else None)
    _every = max(int(checkpoint_every), 1)
    _fp = {
        "n": int(n), "dim": int(dim), "n_lists": int(params.n_lists),
        "pq_dim": int(params.pq_dim), "pq_bits": int(params.pq_bits),
        "codebook_kind": int(params.codebook_kind),
        "metric": int(params.metric), "keep_codes": bool(keep_codes),
        "cap_rows": cap_rows, "cache_dtype": str(params.cache_dtype),
    }
    _state = (ck.load(fingerprint=_fp)
              if (ck is not None and resume) else None)
    _phase = _state[0] if _state is not None else None
    _restored_scales = None
    if _state is not None:
        index = _restore_quantizer(params, _state[3], dim)
        if "ts_scales" in _state[3]:
            _restored_scales = jnp.asarray(_state[3]["ts_scales"])
    else:
        index = _quantizer_index(params, jnp.asarray(trainset), int(dim))
        jax.block_until_ready(index.pq_centers)
    kb_scales = KMeansBalancedParams(
        n_clusters=index.n_lists,
        metric=(
            DistanceType.InnerProduct
            if params.metric == DistanceType.InnerProduct
            else DistanceType.L2Expanded
        ),
    )
    ts_scales = _restored_scales
    # The padded i8 footprint is C*cap*rot with cap unknown until pass 1,
    # but it is bounded below by n*rot (C*cap >= n) and, when the caller
    # bounds list capacity, above by C*aligned_cap(cap_rows)*rot — enough
    # to decide BEFORE the expensive labeling pass whether the i4 scales
    # must be precomputed (at 100M scale a post-pass-1 "scales missing"
    # failure throws away hours of work; ADVICE r4).
    _cap_bound = (
        index.n_lists * _aligned_cap(int(cap_rows)) * index.rot_dim
        if cap_rows is not None else None
    )
    _i8_may_miss = (
        n * index.rot_dim > _CACHE_BUDGET // 2      # padding factor <= 2x
        or (_cap_bound is not None and _cap_bound > _CACHE_BUDGET)
        # unbounded cap + fatal-on-miss: any padding blowup must not
        # strike after pass 1, so be conservative and pay the scale pass
        or (cap_rows is None and not keep_codes
            and n * index.rot_dim > _CACHE_BUDGET // 8)
    )
    if str(params.cache_dtype) == "pq4":
        # the pq4 transposed-code cache has no streamed scatter; say so
        # up front instead of silently building without a cache
        raise ValueError(
            "cache_dtype='pq4' is not supported by build_streamed (the "
            "transposed-code cache is attached by the batch build); use "
            "cache_dtype='auto'/'i8'/'i4'/'rabitq' here"
        )
    i4_possible = (
        params.cache_decoded and index.rot_dim % 8 == 0
        and (str(params.cache_dtype) == "i4"
             or (str(params.cache_dtype) == "auto" and _i8_may_miss))
    )
    if not keep_codes:
        # keep_codes=False REQUIRES some cache; decide from the pre-pass-1
        # bounds (floor n*rot since C*cap >= n; cap_rows gives the padded
        # ceiling) whether any requested kind can possibly fit, and fail
        # now rather than after the hours-long labeling pass (ADVICE r4).
        # A cap_rows bound under budget legitimately truncates rows until
        # the cache fits — those builds proceed.
        cd = str(params.cache_dtype)
        i8_can = cd in ("auto", "i8") and (
            n * index.rot_dim <= _CACHE_BUDGET
            or (_cap_bound is not None and _cap_bound <= _CACHE_BUDGET)
        )
        i4_can = (
            cd in ("auto", "i4")
            and params.cache_decoded and index.rot_dim % 8 == 0
            and (n * index.rot_dim // 2 <= _CACHE_BUDGET
                 or (_cap_bound is not None
                     and _cap_bound // 2 <= _CACHE_BUDGET))
        )
        # rabitq: sign bits + 2 f32 scalars per row — feasible whenever
        # its (much smaller) footprint fits; streamed scatter mirrors i4
        rabitq_can = (
            cd in ("auto", "rabitq") and params.cache_decoded
            and n * (bits_words(index.rot_dim) * 4 + 8) <= _CACHE_BUDGET
        )
        if not (i8_can or i4_can or rabitq_can):
            raise ValueError(
                "keep_codes=False requires a residual cache but no "
                f"cache_dtype={cd!r} kind can fit _CACHE_BUDGET at "
                f"{n} rows x {index.rot_dim} rot dims (i4 additionally "
                "needs cache_decoded=True and rot_dim % 8 == 0)"
            )
        # An EXPLICIT cache_dtype passing only on the optimistic floor
        # n*rot (C*cap >= n) with no cap_rows ceiling under budget can
        # still miss after the hours-long labeling pass once list
        # padding inflates C*cap past n — and unlike "auto" it has no
        # i4 fallback to degrade to. Mirror _i8_may_miss's conservative
        # <= 2x padding factor and warn up front (ADVICE r5 finding 4).
        if cd != "auto":
            # per-kind padded-ceiling bytes (from the cap_rows element
            # bound) and optimistic row-floor bytes; rabitq's row cost
            # is its word+scalar bytes, not a rot fraction
            if cd == "rabitq":
                _rb = bits_words(index.rot_dim) * 4 + 8
                _ceil = (None if _cap_bound is None
                         else (_cap_bound // index.rot_dim) * _rb)
                floor = n * _rb
            else:
                _ceil = (None if _cap_bound is None
                         else (_cap_bound if cd == "i8"
                               else _cap_bound // 2))
                floor = (n * index.rot_dim if cd == "i8"
                         else n * index.rot_dim // 2)
        if cd != "auto" and not (_ceil is not None
                                 and _ceil <= _CACHE_BUDGET):
            if floor * 2 > _CACHE_BUDGET:
                import warnings

                warnings.warn(
                    f"build_streamed(keep_codes=False, cache_dtype={cd!r}): "
                    f"the padded {cd} cache fits _CACHE_BUDGET only if "
                    "list padding stays under "
                    f"{_CACHE_BUDGET / max(floor, 1):.2f}x the row floor — "
                    "the build may fail AFTER the labeling pass. Set "
                    "cap_rows to bound list capacity (or lower n_lists "
                    "imbalance) to make feasibility decidable up front.",
                    RuntimeWarning, stacklevel=2,
                )
                print("[build_streamed] WARNING: explicit "
                      f"cache_dtype={cd!r} feasibility depends on list "
                      "padding (floor*2 exceeds _CACHE_BUDGET); consider "
                      "cap_rows", flush=True)
        if i4_can and not i8_can:
            # only i4 can fit: make sure its scales actually get computed
            # (the auto heuristic above may not have triggered)
            i4_possible = True
    if i4_possible and ts_scales is None:
        # per-list int4 scales need the trainset — computed before it is
        # freed, used only if the budget later picks the i4 cache
        ts_scales = _trainset_i4_scales(jnp.asarray(trainset), index,
                                        kb_scales)
        jax.block_until_ready(ts_scales)
    trainset = None   # free before the accumulators go up (HBM headroom)
    if ck is not None and _state is None:
        ck.save("quant", 0, {}, _quant_arrays(index, ts_scales),
                fingerprint=_fp)
    if verbose:
        print(f"[build_streamed] quantizers: {_time.time()-_t0:.0f} s",
              flush=True)
    C = index.n_lists
    pq_dim = index.pq_dim
    pq_bits = int(params.pq_bits)
    nw = packed_words(pq_dim, pq_bits)
    rot = index.rot_dim
    kb = KMeansBalancedParams(
        n_clusters=C,
        metric=(
            DistanceType.InnerProduct
            if params.metric == DistanceType.InnerProduct
            else DistanceType.L2Expanded
        ),
    )

    # ---- pass 1: labels for every row (4 B/row; reused in pass 2) ----
    # throttle: async dispatch would otherwise enqueue EVERY generated
    # batch ahead of execution (batches alive until consumed -> tens of
    # GB of queued inputs); a tiny host fetch forces real completion
    # (block_until_ready does not reliably sync on the tunnel platform)
    if _phase == "pass2":
        # labels are in the pass-2 checkpoint (post padding-transform)
        labels_all = jnp.asarray(_state[3]["labels_all"])
    else:
        parts = []
        _p1_done = 0
        _p1_restored_rows = 0
        _p1_skipped = 0
        if _phase == "pass1":
            parts = [jnp.asarray(_state[3]["labels_parts"])]
            _p1_done = int(_state[2]["batches_done"])
            _p1_restored_rows = int(parts[0].shape[0])
        # graft-flow: the caller's host read + upload for batch N+1
        # runs on a producer while batch N labels (depth 0 = the old
        # inline loop); closed on every exit path via the context
        with _pipeline.Prefetcher(make_batches, depth=pipeline_depth,
                                  path="build.pass1", token=token) as _pf1:
            for bi, batch in enumerate(_pf1):
                if bi < _p1_done:
                    _p1_skipped += int(batch.shape[0])
                    continue             # resumed past this chunk
                if _p1_done and _p1_skipped != _p1_restored_rows:
                    # the new make_batches yields different shapes than
                    # the killed run's — skipping by batch INDEX would
                    # silently drop or duplicate rows
                    raise ValueError(
                        f"build_streamed resume misalignment: checkpoint "
                        f"covers {_p1_restored_rows} pass-1 rows in "
                        f"{_p1_done} batches but the first {_p1_done} "
                        f"batches of this run hold {_p1_skipped} rows; "
                        "resume with the make_batches shape the "
                        "checkpoint was written at"
                    )
                token.check()
                faultinject.check(stage="build.pass1", chunk=bi)
                obs.counter("stream_chunks_total", stage="build.pass1")
                parts.append(
                    kmeans_balanced.predict(kb, index.centers, batch))
                if bi % 8 == 7:
                    np.asarray(parts[-1][0])
                if ck is not None and (bi + 1) % _every == 0 \
                        and bi + 1 > _p1_done:
                    ck.save(
                        "pass1", bi, {"batches_done": bi + 1},
                        dict(_quant_arrays(index, ts_scales),
                             labels_parts=jnp.concatenate(parts)),
                        fingerprint=_fp,
                    )
        if _p1_done and _p1_skipped != _p1_restored_rows:
            raise ValueError(
                "build_streamed resume misalignment: the stream ended "
                f"inside the resumed prefix ({_p1_skipped} rows skipped "
                f"vs {_p1_restored_rows} checkpointed); resume with the "
                "make_batches shape the checkpoint was written at"
            )
        labels_all = jnp.concatenate(parts)
        del parts
        total = labels_all.shape[0]
        labels_all = jnp.where(
            jnp.arange(total) < n, labels_all, C   # padding rows -> dropped
        ).astype(jnp.int32)
    counts = jnp.zeros((C + 1,), jnp.int32).at[labels_all].add(1)[:C]
    cap = _aligned_cap(int(counts.max()))
    if cap_rows is not None and cap > cap_rows:
        # bounded list capacity: overflow rows of outlier lists are
        # DROPPED (the accumulator's slot bound), trading a small stored
        # fraction for an HBM-sized index — callers see the truncation in
        # list_sizes.sum(); padding-vs-max-list imbalance at 100M scale
        # otherwise inflates the codes array past HBM
        cap = _aligned_cap(int(cap_rows))
    if verbose:
        # graft-lint: allow-host-sync build verbose-path truncation report
        dropped = int(jnp.maximum(counts - cap, 0).sum())
        try:
            st = jax.devices()[0].memory_stats()
            mem = f" hbm_in_use={st.get('bytes_in_use', 0)/2**30:.2f}G"
        except Exception:  # noqa: BLE001  # graft-lint: allow-unclassified-swallow verbose-only memory_stats probe; absence of stats is not a fault
            mem = ""
        print(f"[build_streamed] pass1 labels: {_time.time()-_t0:.0f} s "
              f"cap={cap} dropped={dropped}{mem}", flush=True)

    cache_kind = _cache_kind_for(
        bool(params.cache_decoded), str(params.cache_dtype), C, cap, rot
    ) or "none"
    if not keep_codes and cache_kind == "none":
        raise ValueError(
            "keep_codes=False requires the decoded-residual cache "
            "(cache_decoded=True and the cache within _CACHE_BUDGET)"
        )
    if cache_kind == "i4":
        if ts_scales is None:
            # auto picked i4 only because list-padding inflated the i8
            # footprint past budget while n*rot_dim alone looked safe —
            # the trainset (and its scales) are already gone. Degrade
            # loudly rather than silently mis-scale.
            print("[build_streamed] WARNING: i4 cache wanted but per-list "
                  "scales were not precomputed (borderline auto budget); "
                  "building without a cache. Set cache_dtype='i4' to force "
                  "eager scale computation.", flush=True)
            cache_kind = "none"
            if not keep_codes:
                raise ValueError(
                    "keep_codes=False needs the i4 cache; pass "
                    "cache_dtype='i4' explicitly"
                )
        scale = ts_scales                                  # [C, rot]
    if cache_kind != "i4":
        scale = jnp.maximum(jnp.max(jnp.abs(index.pq_centers)), 1e-30) / 127.0
    nw4 = rot // 8
    nwb = bits_words(rot)

    # ---- pass 2: encode + donated scatter into the final layout ------
    # accumulators stay FLAT [C*cap, ...] through the loop: a 2-D-indexed
    # row scatter on [C, cap, ...] makes XLA relayout-copy the whole
    # multi-GB operand per call, while the 1-D row scatter aliases the
    # donated buffer; the final 3-D view is a donated in-jit reshape
    # (bitcast). The int4 cache accumulates TRANSPOSED as [C*nw4, cap]
    # to match the fused kernel's dense block layout — its scatter is
    # per-element (nw4 words per row) with 2-D (row, col) indices, which
    # keep every coordinate under int32 where a flat element index
    # overflows at 100M scale.
    want_qnorms = cache_kind in ("i4", "rabitq") and keep_codes
    want_fac = cache_kind == "rabitq"
    if _phase == "pass2":
        # restored accumulators ONLY — allocating the zero set first
        # would double peak HBM exactly when a resume is memory-tight
        _a = _state[3]
        acc_codes = jnp.asarray(_a["acc_codes"])
        acc_cache = jnp.asarray(_a["acc_cache"])
        acc_norms = jnp.asarray(_a["acc_norms"])
        acc_qnorms = jnp.asarray(_a["acc_qnorms"])
        acc_fac = (jnp.asarray(_a["acc_fac"]) if "acc_fac" in _a
                   else jnp.zeros((0,), jnp.float32))
        acc_ids = jnp.asarray(_a["acc_ids"])
        fill = jnp.asarray(_a["fill"])
        off = int(_state[2]["off"])
        nbatch = int(_state[2]["nbatch"])
    else:
        acc_codes = jnp.zeros((C * cap, nw if keep_codes else 0),
                              jnp.uint32)
        if cache_kind == "i4":
            acc_cache = jnp.zeros((C * nw4, cap), jnp.uint32)
        elif cache_kind == "rabitq":
            # transposed sign-bit accumulator (same dense layout + 2-D
            # scatter coordinates as the i4 cache, 4x narrower)
            acc_cache = jnp.zeros((C * nwb, cap), jnp.uint32)
        else:
            acc_cache = jnp.zeros(
                (C * cap, rot if cache_kind == "i8" else 0), jnp.int8
            )
        acc_qnorms = jnp.zeros((C * cap if want_qnorms else 0,),
                               jnp.float32)
        acc_fac = jnp.zeros((C * cap if want_fac else 0,), jnp.float32)
        acc_norms = jnp.zeros((C * cap,), jnp.float32)
        acc_ids = jnp.full((C * cap,), -1, jnp.int32)
        fill = jnp.zeros((C,), jnp.int32)
        off = 0
        nbatch = 0
    _p2_done = nbatch
    _p2_skipped = 0
    with _pipeline.Prefetcher(make_batches, depth=pipeline_depth,
                              path="build.pass2", token=token) as _pf2:
        for bi, batch in enumerate(_pf2):
            if bi < _p2_done:
                _p2_skipped += int(batch.shape[0])
                continue                 # resumed past this chunk
            if bi == _p2_done and _p2_done and _p2_skipped != off:
                # index-based skipping only works when the new stream's
                # batch shapes match the killed run's (off is the
                # row-exact encode position the checkpoint restored)
                raise ValueError(
                    f"build_streamed resume misalignment: checkpoint "
                    f"encoded {off} rows in {_p2_done} batches but the "
                    f"first {_p2_done} batches of this run hold "
                    f"{_p2_skipped} rows; resume with the make_batches "
                    "shape the checkpoint was written at"
                )
            token.check()
            faultinject.check(stage="build.pass2", chunk=bi)
            obs.counter("stream_chunks_total", stage="build.pass2")
            bs = batch.shape[0]
            lab = jax.lax.dynamic_slice_in_dim(labels_all, off, bs)
            (acc_codes, acc_cache, acc_norms, acc_qnorms, acc_fac,
             acc_ids, fill) = (
                _scatter_encode_batch(
                    acc_codes, acc_cache, acc_norms, acc_qnorms, acc_fac,
                    acc_ids, fill,
                    batch, lab, jnp.int32(off), scale,
                    index.centers_rot, index.rotation, index.pq_centers,
                    C, cap, int(index.codebook_kind), pq_dim, pq_bits,
                    keep_codes, cache_kind,
                )
            )
            nbatch += 1
            if nbatch % 4 == 0:
                np.asarray(fill[0])    # throttle the async queue (above)
            if verbose and nbatch == 1:
                np.asarray(fill[0])
                print("[build_streamed] first scatter ok", flush=True)
            off += bs
            if ck is not None and nbatch % _every == 0 \
                    and nbatch > _p2_done:
                ck.save(
                    "pass2", nbatch, {"off": off, "nbatch": nbatch},
                    dict(_quant_arrays(index, ts_scales),
                         labels_all=labels_all, acc_codes=acc_codes,
                         acc_cache=acc_cache, acc_norms=acc_norms,
                         acc_qnorms=acc_qnorms, acc_fac=acc_fac,
                         acc_ids=acc_ids, fill=fill),
                    fingerprint=_fp,
                )

    if _p2_done and nbatch == _p2_done and _p2_skipped != off:
        raise ValueError(
            "build_streamed resume misalignment: the stream ended inside "
            f"the resumed prefix ({_p2_skipped} rows skipped vs {off} "
            "checkpointed); resume with the make_batches shape the "
            "checkpoint was written at"
        )
    # the [C, cap, nw] native TPU layout is transposed relative to the
    # flat bytes (small minor dims get split/packed), so materializing it
    # costs a full-array relayout copy — fine at GB scale, impossible at
    # 100M scale. Big code arrays stay FLAT [C*cap, nw]; every consumer
    # (search, extend, serialize) handles both forms.
    big_codes = keep_codes and C * cap * nw * 4 > (2 << 30)
    if cache_kind == "i4":
        recon_cache = _donated_reshape3(acc_cache, C, nw4)
    elif cache_kind == "rabitq":
        recon_cache = _donated_reshape3(acc_cache, C, nwb)
    elif cache_kind == "i8":
        recon_cache = _donated_reshape3(acc_cache, C, cap)
    else:
        recon_cache = None
    out = dataclasses.replace(
        index,
        codes=(acc_codes if big_codes
               else _donated_reshape3(acc_codes, C, cap)),
        indices=_donated_reshape2(acc_ids, C, cap),
        list_sizes=jnp.minimum(fill, cap),
        rec_norms=_donated_reshape2(acc_norms, C, cap),
        recon_cache=recon_cache,
        recon_scale=float(scale) if cache_kind == "i8" else 1.0,
        cache_scales=scale if cache_kind == "i4" else None,
        cache_qnorms=(_donated_reshape2(acc_qnorms, C, cap)
                      if want_qnorms else None),
        cache_fac=(_donated_reshape2(acc_fac, C, cap)
                   if want_fac else None),
    )
    return out


@functools.partial(jax.jit, donate_argnums=(0,), static_argnums=(1, 2))
def _donated_reshape3(a, C: int, cap: int):
    """Leading-dim split reshape that ALIASES the (donated) input — the
    op-by-op equivalent copies the multi-GB accumulator."""
    return a.reshape(C, cap, -1)


@functools.partial(jax.jit, donate_argnums=(0,), static_argnums=(1, 2))
def _donated_reshape2(a, C: int, cap: int):
    return a.reshape(C, cap)


@functools.partial(
    jax.jit,
    donate_argnums=(0, 1, 2, 3, 4, 5, 6),
    static_argnums=(14, 15, 16, 17, 18, 19, 20),
)
def _scatter_encode_batch(
    acc_codes, acc_cache, acc_norms, acc_qnorms, acc_fac, acc_ids, fill,
    batch, labels, id0, scale, centers_rot, rotation, pq_centers,
    C: int, cap: int, codebook_kind: int, pq_dim: int, pq_bits: int,
    keep_codes: bool, cache_kind: str,
):
    """Encode one batch and scatter rows into their final list slots
    (donated accumulators -> in-place updates; the _pack_lists slotting
    logic, offset by the running per-list fill). Accumulators are FLAT
    [C*cap, ...]: 1-D row scatters alias the donated buffers, where
    2-D-indexed scatters forced an 8.5 GB relayout copy per call."""
    bs, dim = batch.shape
    pq_len = rotation.shape[0] // pq_dim
    K = pq_centers.shape[1]
    x32 = batch.astype(jnp.float32)
    x_rot = dist_dot(x32, rotation.T)
    res = (x_rot - centers_rot[jnp.minimum(labels, C - 1)]).reshape(
        bs, pq_dim, pq_len
    )
    lab_safe = jnp.minimum(labels, C - 1)
    if codebook_kind == codebook_gen.PER_SUBSPACE:
        codes = _encode_subspace(res, pq_centers, K)
        flat_idx = codes.astype(jnp.int32) + (
            jnp.arange(pq_dim, dtype=jnp.int32) * K
        )
    else:
        codes = _encode_per_cluster(res, lab_safe, pq_centers)
        flat_idx = codes.astype(jnp.int32) + (lab_safe * K)[:, None]
    # ||recon||^2 = sum_s ||book_s[code_s]||^2 — a norm-TABLE gather whose
    # minor dim is pq_dim, not pq_len (a [bs, p, len] decode transient is
    # lane-padded len -> 128 by the TPU layout: 64x memory at len=2)
    book_norms = jnp.sum(
        pq_centers.astype(jnp.float32) ** 2, axis=-1
    ).reshape(-1)
    rnorm = jnp.sum(jnp.take(book_norms, flat_idx, axis=0), axis=-1)

    ids_global = id0 + jnp.arange(bs, dtype=jnp.int32)
    # slot assignment: stable sort by label, rank within the batch run,
    # offset by the accumulated fill (labels == C drop out of bounds)
    order = jnp.argsort(labels, stable=True)
    sl = labels[order]
    counts_b = jnp.zeros((C + 1,), jnp.int32).at[labels].add(1)[:C]
    starts = jnp.cumsum(counts_b) - counts_b
    sl_safe = jnp.minimum(sl, C - 1)
    pos = (jnp.arange(bs) - starts[sl_safe]) + fill[sl_safe]
    # dropped rows (label C padding / list overflow): out-of-bounds slots
    # make the scatter update drop
    slot = jnp.where((sl < C) & (pos < cap), sl * cap + pos, C * cap)

    if keep_codes:
        packed = pack_codes(codes, pq_bits)
        acc_codes = acc_codes.at[slot].set(packed[order])
    if cache_kind == "i4":
        # the int4 cache quantizes the RAW rotated residual (not the PQ
        # reconstruction): one quantization error source instead of two —
        # measured 0.917 vs 0.895 recall on DEEP-like data at the same
        # byte budget. The stored norm is the dequantized vector's (what
        # search scores against).
        raw = res.reshape(bs, -1)                          # [bs, rot]
        q, qn = _quant_pack_i4(raw, scale[lab_safe])       # [bs, nw4]
        # transposed element scatter into the [C*nw4, cap] accumulator:
        # word w of the row assigned to (list l, slot pos) lands at
        # (l*nw4 + w, pos). 2-D indices keep every coordinate < 2^31 —
        # a flat 1-D index (l*nw4 + w)*cap + pos OVERFLOWS int32 at the
        # DEEP-100M target shape (32768*16*4352 = 2.28e9 elements)
        nw4 = q.shape[1]
        qs = q[order]
        l_idx = slot // cap
        pos_idx = slot % cap
        row = l_idx[:, None] * nw4 + jnp.arange(nw4, dtype=jnp.int32)[None, :]
        row = jnp.where(slot[:, None] >= C * cap, C * nw4, row)  # drop
        col = jnp.broadcast_to(pos_idx[:, None], row.shape)
        acc_cache = acc_cache.at[row.reshape(-1), col.reshape(-1)].set(
            qs.reshape(-1)
        )
        if keep_codes:
            # codes remain the decode path's source of truth: keep the PQ
            # reconstruction norms in rec_norms and stash the dequantized
            # norms separately for the cache scan
            acc_qnorms = acc_qnorms.at[slot].set(qn[order])
        else:
            rnorm = qn
    elif cache_kind == "rabitq":
        # sign bits of the RAW rotated residual (not the PQ recon —
        # same fidelity choice as the i4 cache above) + the estimator's
        # per-row scalars: fac = ||r||²/||r||₁ and the TRUE ||r||².
        # Needs NO trainset scale pass at all — RaBitQ's build-side win.
        # Same transposed [C*nwb, cap] element scatter as i4 (2-D
        # coordinates keep every index under int32 at 100M scale).
        raw = res.reshape(bs, -1)                          # [bs, rot]
        q, fac_b, qn = _quant_pack_rabitq(raw)             # [bs, nwb]
        nwb = q.shape[1]
        qs = q[order]
        l_idx = slot // cap
        pos_idx = slot % cap
        row = l_idx[:, None] * nwb + jnp.arange(nwb, dtype=jnp.int32)[None, :]
        row = jnp.where(slot[:, None] >= C * cap, C * nwb, row)  # drop
        col = jnp.broadcast_to(pos_idx[:, None], row.shape)
        acc_cache = acc_cache.at[row.reshape(-1), col.reshape(-1)].set(
            qs.reshape(-1)
        )
        acc_fac = acc_fac.at[slot].set(fac_b[order])
        if keep_codes:
            acc_qnorms = acc_qnorms.at[slot].set(qn[order])
        else:
            rnorm = qn
    elif cache_kind == "i8":
        # full decode, chunked: the [chunk, p, len] transient is
        # lane-padded len -> 128, so chunks stay small
        chunk = 1 << 13
        npad = -(-bs // chunk) * chunk
        cpad = jnp.pad(codes, ((0, npad - bs), (0, 0)))
        lpad = jnp.pad(lab_safe, (0, npad - bs))

        def dec(inp):
            cb, lb = inp
            if codebook_kind == codebook_gen.PER_SUBSPACE:
                r = _decode_gather(cb, pq_centers, codebook_kind)
            else:
                r = _decode_gather(cb, pq_centers, codebook_kind, lb)
            return jnp.clip(jnp.round(r / scale), -127, 127).astype(jnp.int8)

        q = jax.lax.map(
            dec,
            (cpad.reshape(npad // chunk, chunk, pq_dim),
             lpad.reshape(npad // chunk, chunk)),
        ).reshape(npad, -1)[:bs]
        acc_cache = acc_cache.at[slot].set(q[order])
    acc_norms = acc_norms.at[slot].set(rnorm[order])
    acc_ids = acc_ids.at[slot].set(ids_global[order])
    fill = fill + counts_b
    # pin the 2-D accumulators to row-major: XLA's scatter layout
    # assignment otherwise drifts them to a transposed layout, which
    # turns the final [C, cap, ...] view into an 8.5 GB relayout copy
    # (row-major -> the view is a pure bitcast)
    try:
        from jax.experimental.layout import Layout, with_layout_constraint

        acc_codes = with_layout_constraint(acc_codes, Layout((0, 1)))
        # both cache accumulators are 2-D with a leading-split final
        # reshape ([C*cap, rot] -> [C, cap, rot]; [C*nw4, cap] ->
        # [C, nw4, cap]), so the row-major pin keeps that view a bitcast
        acc_cache = with_layout_constraint(acc_cache, Layout((0, 1)))
    except Exception:  # noqa: BLE001 - layout API absent on some backends
        pass
    return (acc_codes, acc_cache, acc_norms, acc_qnorms, acc_fac, acc_ids,
            fill)


def encode(index: Index, vectors) -> Tuple[jax.Array, jax.Array]:
    """Label + PQ-encode vectors against an index's quantizers (reference
    process_and_fill_codes:1322, minus the list scatter). Returns
    (labels [n] int32, packed codes [n, n_words] uint32)."""
    vectors = jnp.asarray(vectors)
    kb = KMeansBalancedParams(
        n_clusters=index.n_lists,
        metric=(
            DistanceType.InnerProduct
            if index.metric == DistanceType.InnerProduct
            else DistanceType.L2Expanded
        ),
    )
    labels = kmeans_balanced.predict(kb, index.centers, vectors)

    # encode: rotated residual → per-subspace nearest codebook entry
    x32 = vectors.astype(jnp.float32)
    x_rot = dist_dot(x32, index.rotation.T)
    res = (x_rot - index.centers_rot[labels]).reshape(
        -1, index.pq_dim, index.pq_len
    )
    if index.codebook_kind == codebook_gen.PER_SUBSPACE:
        codes = _encode_subspace(res, index.pq_centers, index.pq_book_size)
    else:
        codes = _encode_per_cluster(res, labels, index.pq_centers)
    return labels, pack_codes(codes, index.pq_bits)


def _encode_per_cluster(res, labels, pq_centers, block: int = 1 << 14):
    """PER_CLUSTER encode, row-blocked like _encode_subspace (the book
    gather [n, K, len] plus the [n, p, K] distances OOM unblocked)."""
    n, p, plen = res.shape

    def one_block(inp):
        res_b, lab_b = inp
        books = pq_centers[lab_b]  # [block, K, len]
        dots = jnp.einsum(
            "npl,nkl->npk", res_b, books,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        rn = jnp.sum(res_b * res_b, axis=2)[:, :, None]
        cn = jnp.sum(books * books, axis=2)[:, None, :]
        return jnp.argmin(rn - 2.0 * dots + cn, axis=2).astype(jnp.uint8)

    if n <= block:
        return one_block((res, labels))
    npad = -(-n // block) * block
    res_p = jnp.pad(res, ((0, npad - n), (0, 0), (0, 0)))
    lab_p = jnp.pad(labels, (0, npad - n))
    out = jax.lax.map(
        one_block,
        (res_p.reshape(npad // block, block, p, plen),
         lab_p.reshape(npad // block, block)),
    )
    return out.reshape(npad, p)[:n]


def extend(index: Index, new_vectors, new_ids=None) -> Index:
    """Encode + add vectors (reference ivf_pq_build.cuh extend /
    process_and_fill_codes:1322)."""
    if index.codes.shape[-1] == 0 and index.size > 0:
        raise ValueError(
            "cache-only index (built with keep_codes=False) cannot be "
            "extended — the packed codes were dropped at build"
        )
    new_vectors = jnp.asarray(new_vectors)
    n_new = new_vectors.shape[0]
    if new_ids is None:
        new_ids = jnp.arange(index.size, index.size + n_new, dtype=jnp.int32)
    new_ids = jnp.asarray(new_ids).astype(jnp.int32)

    labels, new_packed = encode(index, new_vectors)

    # merge with existing lists and repack, all on device: old padding rows
    # get the out-of-range label n_lists so _pack_lists drops them (no
    # host round-trip)
    C = index.n_lists
    nw = packed_words(index.pq_dim, index.pq_bits)
    old_cap = index.indices.shape[1]
    if old_cap > 0 and index.size > 0:
        old_codes = index.codes.reshape(-1, nw)
        old_ids = index.indices.reshape(-1)
        old_labels = jnp.where(
            old_ids >= 0,
            jnp.repeat(jnp.arange(C, dtype=jnp.int32), old_cap),
            jnp.int32(C),
        )
        codes_all = jnp.concatenate([old_codes, new_packed], axis=0)
        labels_all = jnp.concatenate([old_labels, labels])
        ids_all = jnp.concatenate([old_ids, new_ids])
    else:
        codes_all, labels_all, ids_all = new_packed, labels, new_ids

    counts = np.asarray(index.list_sizes) + np.bincount(
        np.asarray(labels), minlength=C
    )
    from raft_tpu.neighbors.ivf_flat import _aligned_cap

    cap = _aligned_cap(int(counts.max()))
    codes_packed, indices, list_sizes = _pack_lists(
        codes_all, labels_all, ids_all, C, cap
    )

    rec_norms = _rec_norms(
        codes_packed, index.pq_centers, index.codebook_kind,
        index.pq_dim, index.pq_bits,
    )

    return _attach_cache(dataclasses.replace(
        index,
        codes=codes_packed,
        indices=indices,
        list_sizes=list_sizes,
        rec_norms=rec_norms,
    ))


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _rec_norms(codes_packed, pq_centers, codebook_kind: int, pq_dim: int,
               pq_bits: int):
    """||reconstructed residual||^2 per stored vector, scanned over lists
    so the unpacked [cap, pq_dim] codes never materialize for the whole
    index at once."""
    C = codes_packed.shape[0]

    def body(_, inp):
        blk, lid = inp                                     # [cap, nw], []
        u = unpack_codes(blk, pq_dim, pq_bits)             # [cap, p]
        if codebook_kind == codebook_gen.PER_SUBSPACE:
            recon = _decode_gather(u, pq_centers, codebook_kind)
        else:
            recon = _decode_gather(u, pq_centers, codebook_kind,
                                   jnp.full((u.shape[0],), lid))
        return None, jnp.sum(recon * recon, axis=-1)

    _, norms = jax.lax.scan(
        body, None, (codes_packed, jnp.arange(C, dtype=jnp.int32))
    )
    return norms


# ---------------------------------------------------------------------------
# int4 reconstruction cache (the cache-doesn't-fit regime)
# ---------------------------------------------------------------------------
#
# At 100M scale the int8 cache (1 B/component) cannot share HBM with the
# packed codes, which forced round 3's DEEP-100M search onto the slow
# decode-gather path (195 QPS). The int4 cache halves that to 0.5
# B/component — for pq_len=2 exactly the size of the codes themselves —
# so a cache-only (keep_codes=False) index fits 100M x rot128 in ~9 GB
# and keeps the fused one-matmul-per-block scan. This is the TPU answer
# to the reference's in-register compressed-code scoring
# (ivf_pq_compute_similarity-inl.cuh:164-185): the "compressed form" is
# re-quantized reconstructions rather than raw PQ codes, because TPUs
# score via the MXU (which wants dense operands) instead of per-lane
# shared-memory LUT gathers.
#
# Layout is TRANSPOSED [C, rot//8, cap]: components-packed-in-words on
# sublanes, rows on lanes — dense under the (8, 128) Mosaic tiling
# (row-major [cap, rot//8] would lane-pad the narrow word dim 8x).
# Per-component scales come from the codebook itself (every reconstructed
# component IS a codebook entry), so no data pass is needed.


def _quant_pack_i4(recon, scales):
    """[..., rot] f32 -> ([..., rot//8] u32 packed signed nibbles,
    [...] f32 dequantized-vector norms)."""
    q = jnp.clip(jnp.round(recon / scales), -8, 7).astype(jnp.int32)
    deq = q.astype(jnp.float32) * scales
    qnorm = jnp.sum(deq * deq, axis=-1)
    nib = (q & 0xF).astype(jnp.uint32)
    nib = nib.reshape(*q.shape[:-1], q.shape[-1] // 8, 8)
    shifts = (jnp.arange(8, dtype=jnp.uint32) * 4)
    return jnp.sum(nib << shifts, axis=-1, dtype=jnp.uint32), qnorm


def _trainset_i4_scales(trainset, index: "Index", kb) -> jax.Array:
    """Per-list int4 scales [C, rot] estimated from the quantizer-training
    subsample's residual ranges (the streamed build must know scales
    before its single encode+scatter pass; out-of-sample rows beyond the
    1.15x headroom saturate at +/-8, which is rare and bounded)."""
    C, rot = index.n_lists, index.rot_dim
    chunk = min(1 << 19, trainset.shape[0])
    n = trainset.shape[0]
    npad = -(-n // chunk) * chunk
    ts = jnp.asarray(trainset)
    # pad the tail chunk by wrapping real rows (zero-padding would inject
    # |0 - c_rot| phantom residuals that inflate one list's scale)
    tp = jnp.concatenate([ts, ts[: npad - n]]) if npad > n else ts
    tchunks = tp.reshape(npad // chunk, chunk, -1)

    def res_of(tb):
        lab = kmeans_balanced.predict(kb, index.centers, tb)
        t_rot = dist_dot(tb.astype(jnp.float32), index.rotation.T)
        return lab, t_rot - index.centers_rot[lab]

    def max_body(lmax, tb):
        lab, res = res_of(tb)
        return lmax.at[lab].max(jnp.abs(res)), None

    lmax0 = jnp.zeros((C, rot), jnp.float32)
    lmax, _ = jax.lax.scan(max_body, lmax0, tchunks)
    # thin/empty lists fall back to the global max
    gmax = jnp.max(lmax, axis=0)
    lmax = jnp.where(lmax > 0, lmax, gmax[None, :])
    base = jnp.maximum(lmax * 1.1, 1e-30) / 7.0

    # second pass: per-list MSE-optimal clip multiplier on the trainset
    # residuals (see _pick_clip_scale)
    M = len(_CLIP_CANDIDATES)

    def err_body(errs, tb):
        lab, res = res_of(tb)
        s_rows = base[lab]                                  # [chunk, rot]
        for mi, m in enumerate(_CLIP_CANDIDATES):
            s = s_rows * m
            q = jnp.clip(jnp.round(res / s), -8, 7)
            e = jnp.sum((q * s - res) ** 2, axis=-1)        # [chunk]
            errs = errs.at[lab, mi].add(e)
        return errs, None

    errs, _ = jax.lax.scan(err_body, jnp.zeros((C, M), jnp.float32), tchunks)
    m_best = jnp.asarray(_CLIP_CANDIDATES, jnp.float32)[
        jnp.argmin(errs, axis=1)
    ]                                                       # [C]
    return base * m_best[:, None]


def unpack_i4(packed):
    """[..., nw] u32 -> [..., nw*8] f32 raw values in [-8, 7] (callers
    apply scales). XLA analog of the kernel's sign-extending decode."""
    w = packed.astype(jnp.int32)
    j = jnp.arange(8, dtype=jnp.int32)
    vals = (w[..., None] << (28 - 4 * j)) >> 28          # [..., nw, 8]
    return vals.reshape(*packed.shape[:-1], -1).astype(jnp.float32)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _recon_cache_scan_i4(codes_packed, indices, pq_centers,
                         codebook_kind: int, pq_dim: int, pq_bits: int):
    """Packed-int4 decoded-residual cache ([C, rot//8, cap] u32 transposed)
    + PER-LIST per-component scales [C, rot] + dequantized norms, scanned
    over lists. Per-list scales measured ~0.14 recall better than global
    max-based scales on adversarial blob sets (list residual ranges vary
    widely when coarse clusters differ in spread)."""
    C = codes_packed.shape[0]
    lids = jnp.arange(C, dtype=jnp.int32)

    def decode(blk, lid):
        u = unpack_codes(blk, pq_dim, pq_bits)             # [cap, p]
        if codebook_kind == codebook_gen.PER_SUBSPACE:
            return _decode_gather(u, pq_centers, codebook_kind)
        return _decode_gather(u, pq_centers, codebook_kind,
                              jnp.full((u.shape[0],), lid))

    def max_body(_, inp):
        blk, ids_row, lid = inp
        recon = decode(blk, lid)                           # [cap, rot]
        m = jnp.max(jnp.where(ids_row[:, None] >= 0, jnp.abs(recon), 0.0),
                    axis=0)
        return None, m

    _, list_max = jax.lax.scan(max_body, None, (codes_packed, indices, lids))
    base = jnp.maximum(list_max, 1e-30) / 7.0              # [C, rot]

    def body(_, inp):
        blk, ids_row, lid = inp                            # [cap, nw], []
        recon = decode(blk, lid)
        ok = (ids_row >= 0)[:, None]
        # per-list clip multiplier: a clipped quantizer (scale < max/7)
        # often beats full range coverage in MSE — pick per list
        s_best = _pick_clip_scale(recon, base[lid], ok)
        packed, qnorm = _quant_pack_i4(recon, s_best)      # [cap, nw4]
        return None, (packed.T, qnorm, s_best)

    _, (cache_t, qnorms, scales) = jax.lax.scan(
        body, None, (codes_packed, indices, lids)
    )
    return cache_t, scales, qnorms


_CLIP_CANDIDATES = (0.6, 0.7, 0.8, 0.9, 1.0)


def _pick_clip_scale(vals, base_scale, ok, qmax: int = 7):
    """Per-list MSE-optimal clip multiplier: quantize ``vals``
    [..., n, rot] (validity mask ``ok`` [..., n, 1]) at each candidate
    scale m * base_scale [..., rot] and keep, per leading batch entry,
    the m with least total squared error (measured: m=0.7 lifts
    DEEP-like int4 recall 0.882 -> 0.917 vs full-range m=1.0). The one
    clip-search implementation shared by the streamed scale pass, the
    decoded-cache scan, and attach_raw_residual_cache."""
    best_err = best_m = None
    for m in _CLIP_CANDIDATES:
        s = base_scale * m
        q = jnp.clip(jnp.round(vals / s[..., None, :]), -qmax - 1, qmax)
        err = jnp.sum(jnp.where(ok, (q * s[..., None, :] - vals) ** 2, 0.0),
                      axis=(-2, -1))
        if best_err is None:
            best_err, best_m = err, jnp.full_like(err, m)
        else:
            take = err < best_err
            best_err = jnp.minimum(err, best_err)
            best_m = jnp.where(take, m, best_m)
    return base_scale * best_m[..., None]


# ---------------------------------------------------------------------------
# rabitq sign-bit cache (the ~32x-compressed first-stage rung, ISSUE 11)
# ---------------------------------------------------------------------------
#
# IVF-RaBitQ (PAPERS.md) quantizes each rotated residual r to ONE sign
# bit per component plus two per-row f32 scalars, and recovers an
# UNBIASED estimate of <q, r> from them:
#
#     r̂ = fac · sign(r),   fac = ||r||² / ||r||₁
#     <q, r> ≈ <q, r̂> = fac · Σ_j sign(r_j) · q_j
#
# (<r̂, r> = ||r||² exactly — the collinearity-corrected projection; for
# incoherent directions, i.e. after a random rotation, the cross terms
# cancel in expectation). The L2 estimator then uses the TRUE stored
# norm, not ||r̂||²:  d²(q_res, r) ≈ ||q_res||² + ||r||² − 2·fac·S.
# Storage is sign bits packed 32-per-u32 lane word, TRANSPOSED to
# [C, ceil(rot/32), cap] like the i4 cache (components on sublanes, rows
# on lanes — Mosaic-dense); rot dims beyond the last full word are pad
# bits (decode −1, nulled by zero-padded queries). At 1 bit/dim this is
# ~32× less HBM per scanned row than f32 and 4× less than the i4 rung —
# the first-stage scan of the multi-stage rerank pipeline
# (search_refined), never a fidelity source on its own.


def bits_words(rot: int) -> int:
    """Sign-bit words per row: ceil(rot / 32) (partial last word ok)."""
    return -(-rot // 32)


def pack_sign_bits(vals) -> jax.Array:
    """[..., d] f32 -> [..., ceil(d/32)] u32 sign-bit words (bit j of
    word w set where vals[..., 32w + j] > 0; pad bits zero)."""
    d = vals.shape[-1]
    nwb = bits_words(d)
    pad = nwb * 32 - d
    b = (vals > 0).astype(jnp.uint32)
    if pad:
        b = jnp.concatenate(
            [b, jnp.zeros((*b.shape[:-1], pad), jnp.uint32)], axis=-1)
    b = b.reshape(*b.shape[:-1], nwb, 32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(b << shifts, axis=-1, dtype=jnp.uint32)


def unpack_sign_bits(packed, d: int) -> jax.Array:
    """[..., nw] u32 -> [..., d] f32 in {−1, +1} (pad bits dropped).
    XLA analog of the kernel's 2-op bit decode."""
    w = packed.astype(jnp.int32)
    j = jnp.arange(d, dtype=jnp.int32)
    words = jnp.take(w, j // 32, axis=-1)                # [..., d]
    bit = (words >> (j % 32)) & 1
    return (2 * bit - 1).astype(jnp.float32)


def _quant_pack_rabitq(res):
    """[..., rot] f32 residuals -> (packed [..., ceil(rot/32)] u32,
    fac [...] f32, norm2 [...] f32). All-zero rows (padding slots,
    exact-center residuals) get fac 0 — their estimated dot is 0."""
    norm2 = jnp.sum(res * res, axis=-1)
    l1 = jnp.sum(jnp.abs(res), axis=-1)
    fac = norm2 / jnp.maximum(l1, 1e-30)
    return pack_sign_bits(res), fac, norm2


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _rabitq_cache_scan(codes_packed, indices, pq_centers,
                       codebook_kind: int, pq_dim: int, pq_bits: int):
    """Sign-bit cache from the PQ codes, scanned over lists: binarize
    the DECODED reconstruction (the batch-build analog of the streamed
    path's raw-residual signs — same asymmetry the i4 cache has; the
    sign pattern survives PQ quantization far better than magnitudes
    do). Returns (cache_t [C, nw, cap] u32, fac [C, cap],
    qnorms [C, cap] — the reconstruction's true norms, what the
    estimator scores against). Padding slots (ids < 0) are zeroed."""
    C = codes_packed.shape[0]
    lids = jnp.arange(C, dtype=jnp.int32)

    def body(_, inp):
        blk, ids_row, lid = inp                          # [cap, nw], []
        u = unpack_codes(blk, pq_dim, pq_bits)           # [cap, p]
        if codebook_kind == codebook_gen.PER_SUBSPACE:
            recon = _decode_gather(u, pq_centers, codebook_kind)
        else:
            recon = _decode_gather(u, pq_centers, codebook_kind,
                                   jnp.full((u.shape[0],), lid))
        recon = jnp.where((ids_row >= 0)[:, None], recon, 0.0)
        packed, fac, n2 = _quant_pack_rabitq(recon)      # [cap, nw], ...
        return None, (packed.T, fac, n2)

    _, (cache_t, fac, qnorms) = jax.lax.scan(
        body, None, (codes_packed, indices, lids)
    )
    return cache_t, fac, qnorms


def scan_bytes_per_row(kind: str, rot: int, pq_dim: int = 0):
    """First-stage scan cost model, ONE home for bench + tests:
    returns ``(code_bytes, total_bytes)`` streamed per scanned row.

    ``code_bytes`` is the quantized payload alone — the
    rows-per-HBM-byte ladder figure (the convention behind the "~32×
    compressed" 1-bit claim; i4→rabitq is exactly 4× here when
    ``rot % 32 == 0``). ``total_bytes`` adds the per-row scalar
    sidecars and the 4-byte id/slot row the scan also streams — the
    honest roofline traffic (the rabitq ratio lands ~2.3–3.5× there
    because two f32 estimator scalars ride every 1-bit row)."""
    if kind == "rabitq":
        return bits_words(rot) * 4, bits_words(rot) * 4 + 12
    if kind == "i4":
        return rot // 2, rot // 2 + 8
    if kind == "i8":
        return rot, rot + 8
    if kind == "pq4":
        return pq_dim // 2, pq_dim // 2 + 8
    raise ValueError(f"unknown scan kind {kind!r}")


def attach_rabitq_cache(index: Index) -> Index:
    """Swap the index onto the rabitq rung: rebuild the sign-bit cache
    (+ fac/norm sidecars) from the packed codes, replacing whatever
    cache the index carried — the batch-path attach for A/B runs and
    for serving an existing index through the multi-stage pipeline
    without retraining quantizers."""
    if index.codes.ndim != 3 or index.codes.shape[-1] == 0:
        raise ValueError(
            "attach_rabitq_cache needs the packed codes (cache-only "
            "indexes already carry their final cache)")
    cache_t, fac, qnorms = _rabitq_cache_scan(
        index.codes, index.indices, index.pq_centers,
        index.codebook_kind, index.pq_dim, index.pq_bits,
    )
    return dataclasses.replace(
        index, recon_cache=cache_t, recon_scale=1.0,
        cache_scales=None, cache_qnorms=qnorms, cache_fac=fac,
    )


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _recon_cache_scan(codes_packed, pq_centers, codebook_kind: int,
                      pq_dim: int, pq_bits: int):
    """int8-quantized decoded residuals per stored vector ([C, cap,
    rot_dim]), scanned over lists. The dequant scale is bounded by the
    codebook itself (every reconstructed component IS a codebook entry),
    so no data pass is needed."""
    C = codes_packed.shape[0]
    scale = jnp.maximum(jnp.max(jnp.abs(pq_centers)), 1e-30) / 127.0

    def body(_, inp):
        blk, lid = inp                                     # [cap, nw], []
        u = unpack_codes(blk, pq_dim, pq_bits)             # [cap, p]
        if codebook_kind == codebook_gen.PER_SUBSPACE:
            recon = _decode_gather(u, pq_centers, codebook_kind)
        else:
            recon = _decode_gather(u, pq_centers, codebook_kind,
                                   jnp.full((u.shape[0],), lid))
        q = jnp.clip(jnp.round(recon / scale), -127, 127).astype(jnp.int8)
        return None, q

    _, cache = jax.lax.scan(
        body, None, (codes_packed, jnp.arange(C, dtype=jnp.int32))
    )
    return cache, scale


def attach_raw_residual_cache(index: Index, dataset,
                              block_lists: int = 64,
                              dtype: str = "i4") -> Index:
    """Attach a RAW rotated-residual cache (packed int4 at 0.5
    B/component or int8 at 1 B/component, both with per-list scales)
    built from the original dataset — the refine/scan fidelity source
    for in-core and sharded indexes (streamed keep_codes=False builds
    produce the identical i4 cache on the fly; this is the batch-path
    equivalent).

    The distinction matters: ``_attach_cache``'s kinds quantize the
    DECODED PQ reconstruction (fidelity = PQ, usable by the fused scan
    but worthless as a refine source — re-ranking PQ scores with PQ
    fidelity gains nothing), while this cache quantizes the raw rotated
    residual. dtype picks the rung: "i4" matches the PQ bytes (0.5
    B/dim) and "i8" doubles them for ~16x lower quantization error —
    the DEEP-1B per-chip refine source (1.8 GB/chip at 1B rows/64
    chips). On the quantization-hostile unit-norm synthetic
    (scripts/sharded_deep1b.py), end-to-end residual-cache recall@10 is
    ~0.95 at i8 vs ~0.58 at i4 (and quantizing the VECTORS directly,
    with no residual structure subtracting the ~4x-smaller list offsets,
    ranks at 0.897/0.123 — the floor the residual form lifts). The
    reference refines from the raw f32 dataset instead
    (detail/refine_host-inl.hpp), which at 1B scale can never be HBM
    resident. Scales are per-list MSE-optimal-clip on the actual stored
    residuals. Processes ``block_lists`` lists per step to bound the
    [B, cap, rot] f32 transient."""
    if dtype not in ("i4", "i8"):
        raise ValueError(f"dtype must be i4|i8, got {dtype!r}")
    qmax = 7 if dtype == "i4" else 127
    C, cap = index.indices.shape
    rot = index.rot_dim
    if dtype == "i4" and rot % 8 != 0:
        raise ValueError(f"int4 cache needs rot_dim % 8 == 0, got {rot}")
    ds = jnp.asarray(dataset)
    caches, scales, qnorms = [], [], []
    for c0 in range(0, C, block_lists):
        ids = index.indices[c0:c0 + block_lists]           # [B, cap]
        B = ids.shape[0]
        ok = (ids >= 0)[..., None]
        rows = ds[jnp.maximum(ids, 0)].astype(jnp.float32)  # [B, cap, d]
        r_rot = dist_dot(rows.reshape(B * cap, -1), index.rotation.T)
        res = (r_rot.reshape(B, cap, rot)
               - index.centers_rot[c0:c0 + B][:, None, :])
        res = jnp.where(ok, res, 0.0)
        base = jnp.maximum(
            jnp.max(jnp.abs(res), axis=1), 1e-30) / qmax    # [B, rot]
        s_blk = _pick_clip_scale(res, base, ok, qmax=qmax)  # [B, rot]
        if dtype == "i4":
            packed, qn = _quant_pack_i4(res, s_blk[:, None, :])
            caches.append(jnp.swapaxes(packed, 1, 2))       # [B, nw4, cap]
        else:
            q8 = jnp.clip(jnp.round(res / s_blk[:, None, :]), -128, 127)
            deq = q8 * s_blk[:, None, :]
            qn = jnp.sum(deq * deq, axis=-1)
            caches.append(q8.astype(jnp.int8))              # [B, cap, rot]
        scales.append(s_blk)
        qnorms.append(jnp.where(ok[..., 0], qn, 0.0))
    return dataclasses.replace(
        index,
        recon_cache=jnp.concatenate(caches),
        recon_scale=1.0,
        cache_scales=jnp.concatenate(scales),
        cache_qnorms=jnp.concatenate(qnorms),
        cache_fac=None,
    )


def _cache_kind_for(cache_decoded: bool, cache_dtype: str, C: int,
                    cap: int, rot: int, pq_bits: int = 8,
                    pq_dim: int = 0, per_subspace: bool = True,
                    ) -> Optional[str]:
    """The budget/dtype ladder shared by batch and streamed builds.

    "auto" is fidelity-first at the top: i8 (1 matmul pass,
    1 B/component, the finest cache) whenever it fits. Below the i8
    budget the two half-byte rungs — packed i4 raw residuals (1 MXU
    pass + in-kernel nibble decode, slightly lossy) and pq4 transposed
    codes (exact PQ distances, 16-pass one-hot contraction) — measured
    recall-TIED at equal bytes (EQUAL_BYTES_r05.json), so picking
    between them is a pure throughput question: it goes through the
    per-backend dispatch table (the measured ``pq_scan`` race,
    docs/dispatch_tuning.md), with i4 as the analytic fallback (~16x
    less MXU work per the projection; a table can overturn that where
    the one-hot contraction's locality actually wins). pq4 stays the
    explicit choice for pq_dim < dim compression below 0.5 B/dim —
    the reference's high-compression regime
    (ivf_pq_compute_similarity-inl.cuh LUT scoring) where no residual
    cache can operate.

    "rabitq" (ISSUE 11) is the 1-bit/dim bottom rung — sign-bit codes
    plus two per-row scalars, ~4× fewer code bytes than the half-byte
    rungs. Its FIRST-STAGE recall sits well below i4's, so "auto"
    only ever picks it through a MEASURED table winner (microbench
    races it at matched recall through its rerank pipeline — an arm
    that can't hit the band is filtered before the race); the analytic
    fallback never does, and when no kind fits the budget "auto" still
    returns None (no cache — plain search keeps its exact PQ code
    scan, the pre-r10 semantics; a silent 1-bit downgrade there would
    regress recall for plain-search callers). An auto- or
    explicitly-rabitq index should be searched through
    ``search_refined`` (the multi-stage pipeline); plain ``search``
    serves first-stage estimates."""
    if not cache_decoded or cap == 0:
        return None
    i8_ok = C * cap * rot <= _CACHE_BUDGET
    i4_ok = rot % 8 == 0 and C * cap * rot // 2 <= _CACHE_BUDGET
    pq4_ok = (pq_bits == 4 and per_subspace and pq_dim > 0
              and pq_dim % 8 == 0
              and C * cap * pq_dim // 2 <= _CACHE_BUDGET)
    # sign-bit cache: nw u32 words + fac/norm f32 scalars per row;
    # word padding makes any rot legal
    rabitq_ok = C * cap * (bits_words(rot) * 4 + 8) <= _CACHE_BUDGET
    if cache_dtype == "auto":
        if i8_ok:
            return "i8"
        feasible = [kind for kind, ok in
                    (("i4", i4_ok), ("pq4", pq4_ok),
                     ("rabitq", rabitq_ok)) if ok]
        if not feasible:
            return None
        from raft_tpu import tuning

        return tuning.choose(
            "pq_scan",
            {"n_lists": C, "cap": cap, "rot": rot, "pq_dim": pq_dim,
             "pq_bits": pq_bits},
            feasible, "i4" if i4_ok else None,
        )
    if cache_dtype == "i8":
        return "i8" if i8_ok else None
    if cache_dtype == "i4":
        return "i4" if i4_ok else None
    if cache_dtype == "pq4":
        return "pq4" if pq4_ok else None
    if cache_dtype == "rabitq":
        return "rabitq" if rabitq_ok else None
    raise ValueError(f"unknown cache_dtype {cache_dtype!r}")


def _resolve_cache_kind(index: "Index") -> Optional[str]:
    """Which cache precision to build for this index (None = no cache)."""
    return _cache_kind_for(
        bool(index.cache_decoded), str(index.cache_dtype), index.n_lists,
        index.indices.shape[1], index.rot_dim, int(index.pq_bits),
        int(index.pq_dim),
        int(index.codebook_kind) == codebook_gen.PER_SUBSPACE,
    )


def _attach_cache(index: "Index") -> "Index":
    """(Re)build the decoded-residual cache when enabled and affordable.
    Cache-only indexes (codes dropped at build) keep their existing cache
    — there is nothing to rebuild from."""
    kind = _resolve_cache_kind(index)
    if index.codes.ndim != 3 or index.codes.shape[-1] == 0:
        # flat streamed codes / cache-only: never rebuilt here
        if index.codes.shape[-1] == 0 and index.recon_cache is not None:
            return index
        return dataclasses.replace(
            index, recon_cache=None, cache_scales=None, cache_qnorms=None,
            cache_fac=None,
        )
    if kind is None:
        return dataclasses.replace(
            index, recon_cache=None, cache_scales=None, cache_qnorms=None,
            cache_fac=None,
        )
    if kind == "i8":
        cache, scale = _recon_cache_scan(
            index.codes, index.pq_centers, index.codebook_kind,
            index.pq_dim, index.pq_bits,
        )
        return dataclasses.replace(
            index, recon_cache=cache, recon_scale=float(scale),
            cache_scales=None, cache_qnorms=None, cache_fac=None,
        )
    if kind == "pq4":
        # the "cache" IS the packed codes, transposed to the kernel's
        # dense [C, nw, cap] layout (discriminated from the i4 residual
        # cache by cache_scales is None — see Index.cache_kind)
        return dataclasses.replace(
            index, recon_cache=jnp.swapaxes(index.codes, 1, 2),
            recon_scale=1.0, cache_scales=None, cache_qnorms=None,
            cache_fac=None,
        )
    if kind == "rabitq":
        cache_t, fac, qnorms = _rabitq_cache_scan(
            index.codes, index.indices, index.pq_centers,
            index.codebook_kind, index.pq_dim, index.pq_bits,
        )
        return dataclasses.replace(
            index, recon_cache=cache_t, recon_scale=1.0,
            cache_scales=None, cache_qnorms=qnorms, cache_fac=fac,
        )
    cache_t, scales, qnorms = _recon_cache_scan_i4(
        index.codes, index.indices, index.pq_centers, index.codebook_kind,
        index.pq_dim, index.pq_bits,
    )
    return dataclasses.replace(
        index, recon_cache=cache_t, recon_scale=1.0,
        cache_scales=scales, cache_qnorms=qnorms, cache_fac=None,
    )


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnums=(1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
)
def _pq_search(
    arrays,
    k: int,
    n_probes: int,
    metric_val: int,
    group: int,
    bucket_batch: int,
    codebook_kind: int,
    filter_nbits: int,
    compute_dtype: str = "bf16",
    local_recall_target: float = 0.95,
    merge_recall_target: float = 1.0,
    lut_dtype: str = "f32",
    internal_dtype: str = "f32",
    pq_dim: int = 0,
    pq_bits: int = 8,
    scan_impl: str = "xla",
):
    (queries, centers, centers_rot, rotation, pq_centers, codes, indices,
     list_sizes, rec_norms, filter_bits, recon_cache, recon_scale,
     cache_scales, cache_qnorms, cache_fac) = arrays
    cache_kind = ("none" if recon_cache is None
                  else "i8" if recon_cache.dtype != jnp.uint32
                  else "rabitq" if cache_fac is not None
                  else "i4" if cache_scales is not None
                  else "pq4")
    cache_i4 = cache_kind == "i4"
    cache_rabitq = cache_kind == "rabitq"
    metric = DistanceType(metric_val)
    select_min = is_min_close(metric)
    C, cap = indices.shape   # codes may be FLAT [C*cap, nw] (streamed
    # 100M-scale builds: the 3-D native layout would need a multi-GB
    # relayout copy) or the regular [C, cap, nw]
    p = pq_dim
    rot_dim = rotation.shape[0]
    q32 = queries.astype(jnp.float32)
    m = q32.shape[0]
    sentinel = sentinel_for(metric, jnp.float32)

    # coarse phase (ivf_pq_search.cuh:70 select_clusters)
    cdot = dist_dot(q32, centers.T)
    if metric == DistanceType.InnerProduct:
        coarse = cdot
    else:
        qn2 = jnp.sum(q32 * q32, axis=1, keepdims=True)
        cn2 = jnp.sum(centers * centers, axis=1)
        coarse = qn2 + cn2[None, :] - 2.0 * cdot
    _, probes = select_k(coarse, n_probes, select_min=select_min)

    (bucket_list, bucket_q, pair_bucket, pair_pos, order, total, nb_pad) = (
        bucketize_pairs(probes, m, n_probes, C, group, bucket_batch)
    )

    kl = min(k, cap)
    q_rot = dist_dot(q32, rotation.T)  # [m, rot_dim]
    mm = jnp.bfloat16 if compute_dtype == "bf16" else jnp.float32
    # lut_dtype lowers the decode precision below the compute dtype —
    # the reference's fp16/fp8 LUT ladder (detail/ivf_pq_fp_8bit.cuh)
    if lut_dtype == "bf16" and mm is jnp.float32:
        mm = jnp.bfloat16
    decode_via_f8 = lut_dtype == "f8"

    if scan_impl.startswith("pallas"):
        # fused Pallas scan over the int8 decoded-residual cache: identical
        # machinery to ivf_flat's kernel — the PQ twist is that the scanned
        # space is the rotated residual space, so the per-bucket "queries"
        # are query residuals vs the probed list's center, with the int8
        # dequant scale folded into them (dots then equal q_res . recon)
        from raft_tpu.ops import ivf_scan

        kl = min(kl, 256)  # in-kernel extraction budget (see ivf_flat)
        qsafe_b = jnp.maximum(bucket_q, 0)
        q_res = q_rot[qsafe_b] - centers_rot[bucket_list][:, None, :]
        # dequant scaling folds into the query side so the kernel scores
        # raw cached integers: scalar recon_scale for int8, the per-LIST
        # per-component scale rows for packed int4 (qv is per-bucket and a
        # bucket is one list — free per-list granularity). The pq4 code
        # scan is scale-free (the codebook lives in the kernel's LUT
        # weights), so qv stays the raw residual.
        qscale = (cache_scales[bucket_list][:, None, :]
                  if cache_scales is not None       # per-list (raw caches)
                  else 1.0 if cache_kind in ("pq4", "rabitq")
                  else recon_scale)
        qv = (q_res * qscale).astype(mm)                     # [nb, G, rot]
        ip = metric == DistanceType.InnerProduct
        if ip:
            # dist contribution = -(q_rot . recon); the per-(query, list)
            # constant q_rot . c_l is added back after the kernel
            qv = (q_rot[qsafe_b] * qscale).astype(mm)
            mk, qaux = ivf_scan.IP, None
        else:
            mk, qaux = ivf_scan.L2, jnp.sum(q_res * q_res, axis=2)
        if cache_rabitq:
            # zero-pad queries to the sign-word width: pad bits decode
            # -1 in-kernel, so a zero query component nulls them; the
            # per-row fac scale rides as the kernel's row_scale operand
            # and norms hold the TRUE residual norms (the estimator's
            # correct norm term — not the reconstruction's)
            dpad = recon_cache.shape[1] * 32 - rot_dim
            if dpad:
                qv = jnp.pad(qv, ((0, 0), (0, 0), (0, dpad)))
        keep = None
        if filter_bits is not None:
            keep = filter_keep(filter_bits, filter_nbits, indices).astype(
                jnp.int32
            )
        lut_w = None
        if cache_kind == "pq4":
            # block-diagonal codebook weights W[v][s*pl + l, s] =
            # pq_centers[s, v, l]: one [rot, p] matmul per code value
            # turns the per-subspace LUT build into MXU work (PER_SUBSPACE
            # only — a per-list codebook would need C of these)
            p_, K_, pl_ = pq_centers.shape
            eye = jnp.eye(p_, dtype=jnp.float32)
            lut_w = (pq_centers.transpose(1, 0, 2)[:, :, :, None]
                     * eye[None, :, None, :]).reshape(K_, p_ * pl_, p_)
        norms = rec_norms if cache_qnorms is None else cache_qnorms
        out_d, cand_i = ivf_scan.fused_list_scan_topk(
            recon_cache, indices, list_sizes, bucket_list, qv, qaux,
            None if ip else norms,       # IP kernel never reads norms
            keep,
            lut_weights=lut_w,
            row_scale=cache_fac if cache_rabitq else None,
            k=kl, metric_kind=mk, approx=local_recall_target < 1.0,
            recall_target=float(local_recall_target),
            interpret=scan_impl == "pallas_interpret",
            packed_i4=cache_i4,
            packed_bits=cache_rabitq,
        )                                                    # ids in-kernel
        if ip:
            qc = jnp.einsum(
                "bgd,bd->bg", q_rot[qsafe_b], centers_rot[bucket_list],
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            cand_d = qc[:, :, None] + (-out_d)               # min-space -> score
        else:
            cand_d = out_d
        cand_d = jnp.where(jnp.isinf(out_d), sentinel, cand_d)
        # candidate width off the kernel output (fold arm emits R*128)
        out_d, out_i = unbucketize_merge(
            cand_d, cand_i, pair_bucket, pair_pos, order, total, m,
            n_probes, int(cand_d.shape[2]), k, select_min, sentinel,
            approx=merge_recall_target < 1.0,
            recall_target=merge_recall_target,
        )
        out_i = jnp.where(out_d == sentinel, -1, out_i)
        if metric == DistanceType.L2SqrtExpanded:
            out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
        return out_d, out_i

    def body(_, inp):
        bl, bq = inp  # [bb], [bb, group]
        ids = indices[bl]
        sizes = list_sizes[bl]
        # pq4's transposed-code "cache" is not a decoded-residual block;
        # the XLA body scores it from the packed codes like any code index
        use_cache_blk = (cache_kind in ("i8", "i4", "rabitq")
                         and lut_dtype in ("auto", "i8"))
        rn = (cache_qnorms if use_cache_blk and cache_qnorms is not None
              else rec_norms)[bl]
        if use_cache_blk:
            # decoded-residual cache: a contiguous block load + cast
            # replaces the per-element codebook gather (the decode gather
            # measured ~5x the block matmul at CAGRA-build shapes). Only
            # taken when lut_dtype allows it — explicit f32/bf16/f8 get
            # the true decode at that precision
            if cache_rabitq:
                # XLA mirror of the kernel's estimator: dequantized
                # r̂ = fac·sign(r) scores the cross term, rn (above)
                # already selected the TRUE residual norms
                blk_t = recon_cache[bl]                # [bb, nwb, cap]
                signs = unpack_sign_bits(
                    jnp.swapaxes(blk_t, 1, 2), rot_dim)
                recon = signs * cache_fac[bl][:, :, None]
            elif cache_i4:
                blk_t = recon_cache[bl]                # [bb, nw4, cap]
                raw = unpack_i4(jnp.swapaxes(blk_t, 1, 2))
                recon = raw * cache_scales[bl][:, None, :]
            else:
                sc = (cache_scales[bl][:, None, :]
                      if cache_scales is not None      # raw i8 per-list
                      else recon_scale)
                recon = recon_cache[bl].astype(jnp.float32) * sc
        else:
            if codes.ndim == 2:
                # flat streamed codes: gather each probed list's row range
                rows = bl[:, None] * cap + jnp.arange(cap)[None, :]
                blk_raw = codes[rows]                  # [bb, cap, nw]
            else:
                blk_raw = codes[bl]
            blk_codes = unpack_codes(blk_raw, p, pq_bits)  # [bb, cap, p]
            if codebook_kind == codebook_gen.PER_SUBSPACE:
                recon = _decode_gather(blk_codes, pq_centers, codebook_kind)
            else:
                recon = _decode_gather(
                    blk_codes, pq_centers, codebook_kind, bl[:, None]
                )                        # [bb, cap, rot_dim]
        if decode_via_f8:
            # scaled round-trip through e4m3 (the reference's fp8 LUT
            # stores a shared exponent bias, ivf_pq_fp_8bit.cuh) —
            # unscaled values beyond ±448 would become NaN
            f8_scale = jnp.maximum(jnp.max(jnp.abs(recon)), 1e-30) / 240.0
            recon = (
                (recon / f8_scale).astype(jnp.float8_e4m3fn).astype(jnp.float32)
                * f8_scale
            )
        recon = recon.astype(mm)
        qsafe = jnp.maximum(bq, 0)
        q_res = q_rot[qsafe] - centers_rot[bl][:, None, :]  # [bb, g, rot_dim]
        dots = jnp.einsum(
            "bgd,bcd->bgc", q_res.astype(mm), recon,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if metric == DistanceType.InnerProduct:
            # q·x ≈ q·c_l + q_rot·recon (rotation is orthogonal)
            qc = jnp.einsum(
                "bgd,bd->bg", q_rot[qsafe], centers_rot[bl],
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            qdots = jnp.einsum(
                "bgd,bcd->bgc", q_rot[qsafe].astype(mm), recon,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            dist = qc[:, :, None] + qdots
        else:
            qrn = jnp.sum(q_res * q_res, axis=2)  # [bb, g]
            dist = jnp.maximum(
                qrn[:, :, None] - 2.0 * dots + rn[:, None, :], 0.0
            )
        col_ok = (jnp.arange(cap)[None, :] < sizes[:, None])[:, None, :]
        valid = col_ok & (bq >= 0)[:, :, None]
        if filter_bits is not None:
            valid = valid & filter_keep(filter_bits, filter_nbits, ids)[:, None, :]
        dist = jnp.where(valid, dist, sentinel)
        if internal_dtype == "bf16":
            # lower-precision internal distances (reference fp16 analog)
            dist = dist.astype(jnp.bfloat16).astype(jnp.float32)
        ld, li = merge_topk(
            dist, jnp.broadcast_to(ids[:, None, :], dist.shape), kl, select_min,
            approx=local_recall_target < 1.0,
            recall_target=local_recall_target,
        )
        # flatten [bb, group, kl] -> [bb, group*kl]: the scan's stacked
        # output otherwise pads the kl minor dim to 128 lanes (12.8x HBM
        # at k=10 — 5.2 GB at the DEEP-100M config)
        bb = ld.shape[0]
        return None, (ld.reshape(bb, -1), li.reshape(bb, -1))

    xs = (
        bucket_list.reshape(-1, bucket_batch),
        bucket_q.reshape(-1, bucket_batch, group),
    )
    _, (cand_d, cand_i) = jax.lax.scan(body, None, xs)
    out_d, out_i = unbucketize_merge(
        cand_d.reshape(nb_pad, group, kl),
        cand_i.reshape(nb_pad, group, kl),
        pair_bucket, pair_pos, order, total, m, n_probes, kl, k,
        select_min, sentinel,
        approx=merge_recall_target < 1.0,
        recall_target=merge_recall_target,
    )
    # fewer than k valid candidates: id must be -1 (documented contract);
    # otherwise refine re-scores filtered-out ids back into the top-k
    out_i = jnp.where(out_d == sentinel, -1, out_i)
    if metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    return out_d, out_i


def search(
    search_params: SearchParams,
    index: Index,
    queries,
    k: int,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate k-NN search (reference ivf_pq-inl.cuh:480). Distances are
    PQ approximations — pair with ``neighbors.refine`` for exact re-ranking
    (the reference benchmarks do the same)."""
    queries = jnp.asarray(queries)
    n_probes = int(min(search_params.n_probes, index.n_lists))
    cap = index.indices.shape[1]
    if cap == 0:
        raise ValueError("index is empty — build with add_data_on_build or extend")
    if k > n_probes * cap:
        raise ValueError(f"k={k} exceeds n_probes*list_capacity={n_probes * cap}")
    with obs.entry_span("search", "ivf_pq", queries=int(queries.shape[0]),
                        k=int(k), n_probes=n_probes) as _sp:
        filt = as_filter(prefilter)
        # materializes "keep"-mode tombstone filters (new ids past the
        # filter default to kept) for the drop-semantics scan kernels —
        # docs/serving.md §5; index.size stays lazy (device reduction)
        bits = resolve_filter_bits(filt, lambda: index.size)
        arrays = (
            queries, index.centers, index.centers_rot, index.rotation,
            index.pq_centers, index.codes, index.indices, index.list_sizes,
            index.rec_norms, None if bits is None else bits.bits,
            index.recon_cache, jnp.float32(index.recon_scale),
            index.cache_scales, index.cache_qnorms, index.cache_fac,
        )  # recon_cache rides along; the body gates its use on lut_dtype
        from raft_tpu.neighbors.ivf_flat import (
            adaptive_query_group, _resolve_scan_impl,
        )

        group = adaptive_query_group(
            int(queries.shape[0]), n_probes, index.n_lists,
            int(search_params.query_group),
        )
        requested = str(search_params.scan_impl)
        lut = _norm_dtype_knob(search_params.lut_dtype)
        use_cache = index.recon_cache is not None and lut in ("auto", "i8")
        if lut == "i8" and index.cache_kind not in ("i8", "i4"):
            raise ValueError(
                "lut_dtype='i8' needs the decoded-residual cache; build with "
                "cache_decoded=True (and within _CACHE_BUDGET)"
            )
        if not use_cache:
            if requested.startswith("pallas"):
                raise ValueError(
                    "scan_impl=%r needs the decoded-residual cache (build "
                    "with cache_decoded=True and keep lut_dtype='auto'/'i8')"
                    % requested
                )
            if index.codes.shape[-1] == 0:
                raise ValueError(
                    "this index was built with keep_codes=False (cache-only); "
                    "decode-path scoring needs the packed codes — search with "
                    "lut_dtype='auto' and the cache scan instead"
                )
            impl = "xla"
        else:
            # cache-only indexes are fine on BOTH impls here: the XLA body
            # also scores from recon_cache when lut_dtype is auto/i8
            impl = _resolve_scan_impl(
                requested, cap, min(k, cap),
                approx=float(search_params.local_recall_target) < 1.0,
            )
            if impl.startswith("pallas") and k > n_probes * min(cap, 256):
                raise ValueError(
                    f"k={k} exceeds the fused kernel's candidate pool "
                    f"n_probes*min(cap,256)={n_probes * min(cap, 256)}; raise "
                    "n_probes or use scan_impl='xla'"
                )
        _sp.set(scan_impl=impl, lut=lut)
        return _pq_search(
            arrays,
            int(k),
            n_probes,
            int(index.metric),
            group,
            int(search_params.bucket_batch),
            int(index.codebook_kind),
            0 if bits is None else int(bits.n_bits),
            str(search_params.compute_dtype),
            float(search_params.local_recall_target),
            float(search_params.merge_recall_target),
            lut,
            _norm_dtype_knob(search_params.internal_distance_dtype),
            int(index.pq_dim),
            int(index.pq_bits),
            impl,
        )


def coarse_margins(index: Index, queries, p: int = 2) -> jax.Array:
    """Per-query difficulty margin from the coarse quantizer (see
    ``ivf_flat.coarse_margins`` — the ivf_pq coarse phase runs the same
    queries x centers selection, so the signal and the jitted kernel
    are shared)."""
    from raft_tpu.neighbors.ivf_flat import coarse_margins as _cm

    return _cm(index, queries, p=p)


def _decode_slots(slots, recon_cache, cache_scales, centers_rot,
                  recon_scale):
    """Decode flattened list slots (``list * cap + slot``) [m, c] from the
    residual cache to [m, c, rot_dim] f32 vectors in rotated space.

    The per-candidate fidelity source for cache-resident refine: packed
    int4 caches hold raw rotated residuals (per-list scales), int8 caches
    hold decoded-PQ residuals (scalar scale); either way the vector is
    ``centers_rot[list] + residual``."""
    if recon_cache.dtype == jnp.uint32:                  # packed int4
        C, nw4, cap = recon_cache.shape
        lst = slots // cap
        sl = slots % cap
        words = recon_cache[lst, :, sl]                  # [m, c, nw4]
        res = unpack_i4(words) * cache_scales[lst]
    else:                                                # int8
        C, cap, _rot = recon_cache.shape
        lst = slots // cap
        sl = slots % cap
        sc = (cache_scales[lst] if cache_scales is not None  # raw i8
              else recon_scale)
        res = recon_cache[lst, sl].astype(jnp.float32) * sc
    return centers_rot[lst] + res


def _refine_slots(queries, slots, k: int, metric_val: int,
                  recon_cache, cache_scales, centers_rot, rotation,
                  recon_scale):
    """Exact re-rank of slot candidates against cache-decoded vectors —
    the refine source that fits the DEEP-1B per-chip budget (the
    reference refines from the raw dataset, detail/refine_device.cuh /
    detail/refine_host-inl.hpp; at 1B scale the f32 dataset is 384 GB
    and never sharded into HBM, but the int4 cache IS — so refine
    decodes the <= k*ratio candidates per query from it on-chip).

    Distances are computed at f32 in rotated space (the rotation is
    orthonormal, so L2/IP are preserved); slots < 0 are invalid.
    Returns (dist [m, k], slots [m, k])."""
    metric = DistanceType(metric_val)
    q32 = jnp.asarray(queries).astype(jnp.float32)
    qrot = dist_dot(q32, rotation.T)                     # [m, rot]
    valid = slots >= 0
    safe = jnp.maximum(slots, 0)
    vec = _decode_slots(safe, recon_cache, cache_scales, centers_rot,
                        recon_scale)                     # [m, c, rot] f32
    if metric == DistanceType.InnerProduct:
        # elementwise mult-sum: XLA fuses it into the gather consumer
        # (the "md,mcd" einsum form measured 4x slower on v5e, r4)
        d = jnp.sum(vec * qrot[:, None, :], axis=-1, dtype=jnp.float32)
    else:
        diff = qrot[:, None, :] - vec
        d = jnp.sum(diff * diff, axis=-1, dtype=jnp.float32)
        if metric == DistanceType.L2SqrtExpanded:
            d = jnp.sqrt(d)
    sentinel = sentinel_for(metric, jnp.float32)
    d = jnp.where(valid, d, sentinel)
    out_d, out_s = merge_topk(d, slots.astype(jnp.int32), k,
                              is_min_close(metric))
    out_s = jnp.where(out_d == sentinel, -1, out_s)
    return out_d, out_s


def _slot_indices(indices):
    """Replace stored global ids [C, cap] with flattened slot positions,
    keeping -1 at padding slots, so a search over the substituted index
    emits WHERE each candidate lives instead of what it is — the id is
    recovered afterwards by one flat gather (``indices.reshape(-1)[slot]``)
    and no O(n_rows) inverse map ever exists."""
    C, cap = indices.shape
    slot_ids = jnp.arange(C * cap, dtype=jnp.int32).reshape(C, cap)
    return jnp.where(indices >= 0, slot_ids, -1)


@functools.partial(jax.jit, static_argnums=(2, 3, 7, 8, 9))
def _refine_slots_codes(queries, slots, k: int, metric_val: int,
                        codes, pq_centers, centers_rot,
                        codebook_kind: int, pq_dim: int, pq_bits: int,
                        rotation=None):
    """Exact re-rank of slot candidates against the PQ-DECODED vectors —
    the rerank source for the rabitq pipeline when the index still
    carries its codes: stage 1 scans 1-bit estimates, stage 2 re-scores
    the shortlist at full PQ fidelity (one codebook gather per
    candidate, ≤ k·ratio rows per query — FusionANNS's
    move-only-shortlist-bytes shape). Distances are f32 in rotated
    space; slots < 0 are invalid. Returns (dist [m, k], slots [m, k])."""
    metric = DistanceType(metric_val)
    q32 = jnp.asarray(queries).astype(jnp.float32)
    qrot = dist_dot(q32, rotation.T)                     # [m, rot]
    valid = slots >= 0
    safe = jnp.maximum(slots, 0)
    if codes.ndim == 2:                                  # flat streamed
        C = centers_rot.shape[0]
        cap = codes.shape[0] // C
        words = codes[safe]                              # [m, c, nw]
    else:
        C, cap, _nw = codes.shape
        words = codes.reshape(C * cap, -1)[safe]         # [m, c, nw]
    lst = safe // cap
    u = unpack_codes(words, pq_dim, pq_bits)             # [m, c, p]
    if codebook_kind == codebook_gen.PER_SUBSPACE:
        recon = _decode_gather(u, pq_centers, codebook_kind)
    else:
        recon = _decode_gather(u, pq_centers, codebook_kind, lst)
    vec = centers_rot[lst] + recon                       # [m, c, rot]
    if metric == DistanceType.InnerProduct:
        d = jnp.sum(vec * qrot[:, None, :], axis=-1, dtype=jnp.float32)
    else:
        diff = qrot[:, None, :] - vec
        d = jnp.sum(diff * diff, axis=-1, dtype=jnp.float32)
        if metric == DistanceType.L2SqrtExpanded:
            d = jnp.sqrt(d)
    sentinel = sentinel_for(metric, jnp.float32)
    d = jnp.where(valid, d, sentinel)
    out_d, out_s = merge_topk(d, slots.astype(jnp.int32), k,
                              is_min_close(metric))
    out_s = jnp.where(out_d == sentinel, -1, out_s)
    return out_d, out_s


def _slot_prefilter(index: Index, prefilter):
    """Translate a stored-id prefilter into SLOT space for the
    slot-substituted inner search: the user/tombstone bitset is keyed by
    global id, but the first stage emits slots — so the keep decision is
    materialized per (list, slot) once, packed into a slot-indexed
    bitset, and composed BEFORE the shortlist exists (a filtered row can
    never reach the rerank). Returns a BitsetFilter or None.

    Cached on the filter object keyed by (bitset version, indices
    identity) — steady-state serving calls this per batch with one
    composed tombstone filter, and the translation's device ops (keep
    test + bit pack) must not be paid N times (the
    ``resolve_filter_bits`` caching idiom)."""
    import weakref

    filt = as_filter(prefilter)
    bits = resolve_filter_bits(filt, lambda: index.size)
    if bits is None:
        return None
    # The cache lives on the LONG-LIVED underlying Bitset, not the
    # BitsetFilter wrapper: serve constructs a fresh wrapper per batch
    # (engine._run_search), so a wrapper-resident entry would never hit
    # and every batch would re-pay the translation's device ops
    # (review fix, r10). The key carries the SOURCE bitset's version,
    # not (only) the resolved one — a keep-mode filter narrower than
    # the index materializes through copy().resize(), whose result
    # sits at _version == 1 every time, which would serve a stale slot
    # filter after the source mutates — plus the wrapper's
    # out_of_range mode (two wrappers over one bitset may disagree).
    src = getattr(filt, "bitset", None)
    host = src if src is not None else filt
    key = (getattr(src, "_version", 0), getattr(bits, "_version", 0),
           int(bits.n_bits), getattr(filt, "out_of_range", "drop"))
    cached = getattr(host, "_slot_filter", None)
    if (cached is not None and cached[0] == key
            and cached[2]() is index.indices):
        return cached[1]
    from raft_tpu.core.bitset import Bitset

    keep = filter_keep(bits.bits, int(bits.n_bits), index.indices)
    keep = keep & (index.indices >= 0)
    out = as_filter(Bitset.from_dense(keep.reshape(-1)))
    try:
        # a WEAK ref ties the entry to this exact indices array without
        # pinning a retired generation's [C, cap] int32 block alive on
        # a long-lived bitset object (review fix, r10); a dead or
        # different referent simply misses the cache
        host._slot_filter = (key, out, weakref.ref(index.indices))
    except (AttributeError, TypeError):  # slotted host / unweakrefable
        pass
    return out


def refined_shortlist_width(search_params: SearchParams, index: Index,
                            k: int, refine_ratio: int) -> int:
    """The first-stage over-fetch width ``search_refined`` uses for
    ``k`` at ``refine_ratio`` — exposed so serve's warmup can trace the
    tiered rerank at exactly the shortlist shapes dispatch will see."""
    cap = index.indices.shape[1]
    n_probes = int(min(search_params.n_probes, index.n_lists))
    return max(int(k), min(int(k * refine_ratio), n_probes * cap))


def search_refined(
    search_params: SearchParams,
    index: Index,
    queries,
    k: int,
    refine_ratio: int = 2,
    prefilter=None,
    dataset=None,
) -> Tuple[jax.Array, jax.Array]:
    """Multi-stage search: cheap first-stage scan over the compressed
    cache, exact re-rank of the over-fetched shortlist (the reference's
    ``refine_ratio`` pattern, bench/ann raft_ivf_pq_wrapper.h; the
    FusionANNS architecture — only shortlist bytes move at fidelity).

    The first stage runs over slot-substituted indices at
    ``k * refine_ratio``; the shortlist is then re-ranked from the
    finest available source and slots resolve to global ids. Rerank
    source resolution:

    * ``dataset`` given — exact originals. A **device** ``jax.Array``
      keeps the resident full-upload fast path
      (:mod:`~raft_tpu.neighbors.refine`); a **host** numpy array or
      ``np.memmap`` routes through the tiered shortlist-only fetch
      (:class:`raft_tpu.neighbors.tiered.HostArraySource` — only the
      unique shortlist rows ever cross the link, bitwise-identical
      results); a :class:`~raft_tpu.neighbors.tiered.RerankSource`
      instance is used as-is (the persistent hot-row-cache path).
      Stage 1 returns global ids directly; no slot indirection needed;
    * i8/i4 residual cache — decoded at f32 on-chip (the billion-scale
      source: the dataset is never HBM-resident);
    * the packed PQ codes (rabitq indexes that kept them) — full PQ
      fidelity over the 1-bit first stage's shortlist.

    ``prefilter`` (tombstone/user bitsets) composes with the FIRST
    stage — filtered rows never enter the shortlist (translated to slot
    space for the inner search). A pq4/no-cache index without a dataset
    still errors: its own scan is already exact PQ, so a codes rerank
    adds nothing. Rerank-stage observability (docs/observability.md):
    ``rerank.queries_total``/``rerank.shortlist_rows`` (valid slots
    only)/``rerank.bytes_fetched_total{source}`` (unique rows on the
    tiered path) + the first-stage vs rerank latency split
    (``rerank.stage_ms{stage}``, device-complete), and ``tiered.*``
    for the host tiers.
    """
    from raft_tpu.neighbors import tiered as _tiered

    if refine_ratio < 1:
        raise ValueError(f"refine_ratio must be >= 1, got {refine_ratio}")
    kind = index.cache_kind
    has_codes = index.codes.shape[-1] > 0
    if dataset is None and kind not in ("i8", "i4") and not (
            kind == "rabitq" and has_codes):
        raise ValueError(
            "search_refined needs a rerank source finer than the first "
            "stage: a residual cache (i8/i4), the packed codes (rabitq "
            "indexes built with keep_codes=True), or an explicit "
            "dataset= — a pq4/no-cache index's own scan is already "
            "exact PQ; for raw-dataset refine there, pass dataset= or "
            "use neighbors.refine"
        )
    from raft_tpu import plan as _plan

    src_obj = None if dataset is None else _tiered.as_source(dataset)
    queries = jnp.asarray(queries)
    kc = refined_shortlist_width(search_params, index, k, refine_ratio)
    # the pipeline is the canonical plan (raft_tpu/plan/canonical.py),
    # compiled fresh per call — the bind work is a handful of closures
    # (serve caches its compiled variants per handle; library callers
    # pay exactly what the hand-wired dispatch paid, since the legacy
    # path also rebuilt the slot substitution per call). The stage
    # spans + rerank.* counters (docs/observability.md) are emitted by
    # the node executors, byte-identical names/labels to the
    # hand-wired emission.
    with obs.span("ivf_pq.search_refined", refine_ratio=int(refine_ratio),
                  k=int(k), cache_kind=kind) as _sp:
        if src_obj is not None:
            source = "host" if src_obj.kind == "host" else "dataset"
            p = _plan.refined_plan("tiered")
        else:
            source = "cache" if kind in ("i8", "i4") else "codes"
            p = _plan.refined_plan(source)
        compiled = _plan.compile(p, index, k=int(k),
                                 search_params=search_params,
                                 refine_ratio=int(refine_ratio),
                                 source=src_obj)
        d, ids = compiled(queries, prefilter=prefilter)
        if obs.enabled():
            _sp.set(source=source, shortlist=kc)
        return d, ids


def search_refined_stream(
    search_params: SearchParams,
    index: Index,
    queries,
    k: int,
    refine_ratio: int = 2,
    prefilter=None,
    dataset=None,
    batch_rows: int = 1024,
    pipeline_depth: Optional[int] = None,
    token=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Batched :func:`search_refined` with graft-flow overlap: batch
    N+1's first-stage scan + shortlist fetch (host gather + H2D upload
    — :meth:`~raft_tpu.neighbors.tiered.RerankSource.prepare`) runs on
    a bounded background producer while batch N's exact rerank scores
    and lands in the host result arrays. This is the batched tiered
    path the serial per-batch loop becomes once the fetch dominates:
    the memmap gather disappears behind device compute
    (``pipeline.stall_ms{path=tiered.rerank}`` shows what is left).

    Requires ``dataset`` (host array / memmap / ``RerankSource`` — the
    overlap hides *its* fetch; the cache/codes reranks never fetch).
    Results are bitwise :func:`search_refined` over the same batches at
    any ``pipeline_depth`` including 0 (off): an overlapped
    ``prepare(N+1)`` can at most classify a row as a host miss that a
    serialized run would have served from the hot cache — the gathered
    values are identical either way (tiered module docstring), only
    ``FetchInfo`` traffic accounting shifts between tiers. ``token``
    cancellation drains the producer at the next batch boundary.
    """
    from raft_tpu.core import pipeline as _pipeline
    from raft_tpu.core.interruptible import Interruptible
    from raft_tpu.neighbors import tiered as _tiered
    from raft_tpu.resilience import faultinject

    if refine_ratio < 1:
        raise ValueError(f"refine_ratio must be >= 1, got {refine_ratio}")
    if dataset is None:
        raise ValueError(
            "search_refined_stream needs dataset= (a host array, memmap "
            "or tiered.RerankSource): the pipeline overlaps the rerank "
            "FETCH, and the cache/codes rerank paths never fetch — use "
            "search_refined for those")
    src_obj = _tiered.as_source(dataset)
    m = int(queries.shape[0])
    kc = refined_shortlist_width(search_params, index, k, refine_ratio)
    bs = max(int(batch_rows), 1)
    out_d = np.empty((m, k), np.float32)
    out_i = np.empty((m, k), np.int32)
    if token is None:
        token = Interruptible.get_token()

    def produce():
        for off in range(0, m, bs):
            qb = jnp.asarray(queries[off:off + bs])
            _, ids1 = search(search_params, index, qb, kc,
                             prefilter=prefilter)
            # the producer's host sync + gather + upload; score() stays
            # with the consumer so device results complete in order
            yield off, src_obj.prepare(qb, ids1)

    pf = _pipeline.Prefetcher(produce, depth=pipeline_depth,
                              path="tiered.rerank", token=token)
    with obs.span("ivf_pq.search_refined_stream", k=int(k),
                  refine_ratio=int(refine_ratio), n_queries=m,
                  batch_rows=bs, pipeline_depth=pf.depth), pf:
        for ci, (off, prepared) in enumerate(pf):
            token.check()
            # the CONSUMING dispatch's fault point: chunk-scoped specs
            # (oom@chunk:N) attribute here — never to the producer's
            # prefetch — and slow@stage:tiered.score lets the CPU-smoke
            # bench model the device scan time the overlap hides behind
            faultinject.check(stage="tiered.score", chunk=ci)
            d, i, _ = src_obj.score(prepared, int(k), index.metric)
            rows = min(bs, m - off)
            out_d[off:off + rows] = np.asarray(d, np.float32)[:rows]
            out_i[off:off + rows] = np.asarray(i)[:rows]
    return out_d, out_i


def _norm_dtype_knob(v) -> str:
    """Normalize a lut/internal dtype knob (string or jnp dtype) to
    'f32' | 'bf16' | 'f8'."""
    if isinstance(v, str):
        s = v.lower()
        if s in ("auto", "i8", "int8"):
            return "auto" if s == "auto" else "i8"
        if s in ("f32", "float32", "fp32"):
            return "f32"
        if s in ("bf16", "bfloat16", "f16", "fp16", "float16"):
            return "bf16"
        if s in ("f8", "fp8", "float8", "float8_e4m3fn", "e4m3"):
            return "f8"
        raise ValueError(f"unknown dtype knob {v!r}")
    dt = jnp.dtype(v)
    if dt == jnp.dtype(jnp.float32):
        return "f32"
    if dt in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16)):
        return "bf16"
    if "float8" in dt.name:
        return "f8"
    raise ValueError(f"unknown dtype knob {v!r}")


# ---------------------------------------------------------------------------
# serialization (reference detail/ivf_pq_serialize.cuh)
# ---------------------------------------------------------------------------


def save(path: str, index: Index) -> None:
    cap = index.indices.shape[1]
    codes_h = np.asarray(index.codes)
    if codes_h.ndim == 2:
        # flat streamed layout: host reshape is free (row-major bytes)
        codes_h = codes_h.reshape(index.n_lists, cap, -1)
    arrays = {
        "centers": np.asarray(index.centers),
        "centers_rot": np.asarray(index.centers_rot),
        "rotation": np.asarray(index.rotation),
        "pq_centers": np.asarray(index.pq_centers),
        "codes": codes_h,
        "indices": np.asarray(index.indices),
        "list_sizes": np.asarray(index.list_sizes),
        "rec_norms": np.asarray(index.rec_norms),
    }
    cache_only = codes_h.shape[-1] == 0 and cap > 0
    if cache_only and index.recon_cache is None:
        raise ValueError("cache-only index has no recon_cache to serialize")
    cache_kind = "none"
    # per-list-scaled caches hold RAW-residual fidelity (i4 streamed/
    # attach_raw_residual_cache, i8 raw) that a rebuild from decoded
    # codes would lose — serialize them, like cache-only caches (round 3
    # silently wrote empty codes and rebuilt a wrong cache on load). The
    # scalar-scale decoded-i8 cache and the pq4 transposed-code cache
    # rebuild exactly from codes and are not serialized.
    # the rabitq cache is serialized whenever present: streamed builds
    # binarize the RAW residual (a rebuild from decoded codes would lose
    # that fidelity), batch builds rebuild identically but the cache is
    # tiny (1 bit/dim + 8 B/row) so one rule covers both
    raw_scaled = (index.cache_scales is not None
                  or index.cache_fac is not None)
    if cache_only or raw_scaled:
        arrays["recon_cache"] = np.asarray(index.recon_cache)
        cache_kind = index.cache_kind
        if raw_scaled:
            if index.cache_scales is not None:
                arrays["cache_scales"] = np.asarray(index.cache_scales)
            if index.cache_fac is not None:
                arrays["cache_fac"] = np.asarray(index.cache_fac)
            if index.cache_qnorms is not None:
                arrays["cache_qnorms"] = np.asarray(index.cache_qnorms)
    write_index_file(
        path, "ivf_pq", _SERIAL_VERSION,
        {
            "metric": int(index.metric),
            "metric_arg": index.metric_arg,
            "codebook_kind": index.codebook_kind,
            "pq_bits": index.pq_bits,
            "pq_dim": index.pq_dim,
            "cache_decoded": bool(index.cache_decoded),
            "cache_dtype": str(index.cache_dtype),
            "serialized_cache": cache_kind,
            "recon_scale": float(index.recon_scale),
        },
        arrays,
    )


def load(path: str) -> Index:
    _, meta, arrays = read_index_file(path, "ivf_pq")
    ser_cache = meta.get("serialized_cache", "none")
    idx = Index(
        centers=jnp.asarray(arrays["centers"]),
        centers_rot=jnp.asarray(arrays["centers_rot"]),
        rotation=jnp.asarray(arrays["rotation"]),
        pq_centers=jnp.asarray(arrays["pq_centers"]),
        codes=jnp.asarray(arrays["codes"]),
        indices=jnp.asarray(arrays["indices"]),
        list_sizes=jnp.asarray(arrays["list_sizes"]),
        rec_norms=jnp.asarray(arrays["rec_norms"]),
        metric=DistanceType(meta["metric"]),
        pq_dim_=int(meta["pq_dim"]),
        metric_arg=meta["metric_arg"],
        codebook_kind=int(meta["codebook_kind"]),
        pq_bits=int(meta["pq_bits"]),
        cache_decoded=bool(meta.get("cache_decoded", True)),
        cache_dtype=str(meta.get("cache_dtype", "auto")),
    )
    if ser_cache != "none":
        # restore the serialized cache verbatim (for cache-only indexes
        # the rec_norms on disk are already the dequantized-vector norms)
        return dataclasses.replace(
            idx,
            recon_cache=jnp.asarray(arrays["recon_cache"]),
            recon_scale=float(meta.get("recon_scale", 1.0)),
            cache_scales=(jnp.asarray(arrays["cache_scales"])
                          if "cache_scales" in arrays else None),
            cache_qnorms=(jnp.asarray(arrays["cache_qnorms"])
                          if "cache_qnorms" in arrays else None),
            cache_fac=(jnp.asarray(arrays["cache_fac"])
                       if "cache_fac" in arrays else None),
        )
    return _attach_cache(idx)
