"""IVF-Flat: inverted-file index with uncompressed vectors.

TPU-native analog of the reference's ivf_flat
(cpp/include/raft/neighbors/ivf_flat.cuh; types ivf_flat_types.hpp:49-84;
build detail/ivf_flat_build.cuh:343; search detail/ivf_flat_search-inl.cuh:38
+ the fused interleaved-scan kernel
detail/ivf_flat_interleaved_scan-inl.cuh:663).

Design — idiomatic TPU, not a port (SURVEY.md §7):

* **Storage**: the reference interleaves each list in groups of 32 vectors
  for warp-coalesced loads (ivf_flat_types.hpp:154-176). TPU vector lanes
  are fed by contiguous (8,128) tiles, so interleaving is pointless; lists
  live in a dense padded block ``[n_lists, cap, dim]`` (cap = longest list,
  tile-aligned) built by sort-by-label + scatter — no atomics
  (the reference's build_index_kernel, ivf_flat_build.cuh:115).

* **Search**: the reference launches one CTA per (query, probe) to scan a
  list with a warp-level priority queue. The TPU analog inverts the
  parallelism: all (query, probe) pairs are grouped **by list** so each
  step is a dense ``[G, d] x [d, cap]`` MXU matmul between a group of
  queries and one list block, followed by a local top-k; a final
  ``select_k`` merges each query's n_probes x k candidates (same merge the
  reference does at ivf_flat_search-inl.cuh:194). Grouping, bucketing and
  un-bucketing are all static-shape sort/cumsum/scatter — jit-compatible.

The per-list query groups are what make this fast: with balanced lists,
m x n_probes / n_lists queries share every list block, so the MXU runs at
high utilization instead of doing per-query gathers.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.core.serialize import read_index_file, write_index_file
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.neighbors.common import (
    as_filter,
    filter_keep,
    merge_topk,
    resolve_filter_bits,
    sentinel_for,
)
from raft_tpu.matrix.select_k import select_k
from raft_tpu.utils.math import round_up_to_multiple
from raft_tpu.utils.precision import dist_dot

_SERIAL_VERSION = 1


# metrics the list-scan kernel implements; anything else would silently be
# scored as expanded L2
_SUPPORTED_METRICS = frozenset({
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded,
    DistanceType.InnerProduct,
    DistanceType.CosineExpanded,
})


@dataclasses.dataclass
class IndexParams:
    """Build params (reference ivf_flat_types.hpp:49-78)."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False
    add_data_on_build: bool = True
    conservative_memory_allocation: bool = False  # API parity; no-op here
    # coarse-quantizer training GEMM dtype: "f32" (HIGH-precision passes,
    # safe for tightly clustered data) or "bf16" (~3x faster training,
    # r2 v5e)
    kmeans_compute_dtype: str = "f32"
    # stored-vector dtype: "f32" keeps the dataset bit-exact (reference
    # ivf_flat stores raw T); "bf16" halves list-scan HBM bytes — the
    # fused kernel is bandwidth-bound, so this trades ~3 significant
    # digits of stored precision for up to ~2x scan throughput (the
    # reference's int8/fp16 ivf_flat instantiations make the same trade).
    # Norms are computed FROM the rounded storage so distances stay
    # internally consistent.
    storage_dtype: str = "f32"

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in _SUPPORTED_METRICS:
            raise ValueError(
                f"ivf_flat supports {sorted(m.name for m in _SUPPORTED_METRICS)}, "
                f"got {self.metric!r}"
            )


@dataclasses.dataclass
class SearchParams:
    """Search params (reference ivf_flat_types.hpp:81-84)."""

    n_probes: int = 20
    # TPU tuning knobs (no reference analog): queries per list-group matmul
    # and list blocks processed per XLA scan step (measured r2 on v5e:
    # 8 -> 4.7k QPS, 32 -> 11.2k, 64 -> 14.7k on SIFT-1M; 32 balances
    # compile time vs throughput)
    query_group: int = 256
    bucket_batch: int = 32
    # matmul operand dtype: "bf16" = single-pass MXU (distances still
    # accumulate in f32), "f32" = exact 6-pass. The reference's analog is
    # its fp16/fp8 LUT ladder (ivf_pq_types.hpp lut_dtype).
    compute_dtype: str = "bf16"
    # recall target for the per-list approx top-k (lane-binned Pallas
    # extraction / approx merge_topk); >= 1.0 switches to exact per-list
    # selection. NOTE: each list's extraction also caps at 256 candidates
    # per list on the fused Pallas path (the reference's kMaxCapacity=256,
    # select_warpsort.cuh:100) — with k > 256 entries of one list's true
    # top-k, the excess is unrecoverable; raise n_probes or force
    # scan_impl="xla" for exact semantics.
    local_recall_target: float = 0.95
    # recall target for the FINAL cross-probe merge. Default 1.0 = exact
    # final selection, matching the reference (ivf_flat_search-inl.cuh:194
    # runs exact select_k); set < 1.0 to use lax.approx_min_k there too
    # (measured r2 on v5e: ~1.2x QPS at 0.95 for ~0.5% recall on
    # SIFT-1M).
    merge_recall_target: float = 1.0
    # scan backend: "auto" picks the fused Pallas kernel on TPU when the
    # index layout allows it, else the XLA bucketized scan. Explicit:
    # "pallas" | "pallas_interpret" (CPU-debug) | "xla"
    scan_impl: str = "auto"


@dataclasses.dataclass
class Index:
    """IVF-Flat index (reference ivf_flat_types.hpp:127+).

    ``storage`` [n_lists, cap, dim] — padded list blocks (source dtype);
    ``indices`` [n_lists, cap] — source row ids, -1 in padding;
    ``list_sizes`` [n_lists]; ``centers`` [n_lists, dim] f32;
    ``data_norms`` — per-point squared norms for expanded-L2/cosine search.
    """

    centers: jax.Array
    storage: jax.Array
    indices: jax.Array
    list_sizes: jax.Array
    metric: DistanceType
    metric_arg: float = 2.0
    adaptive_centers: bool = False
    data_norms: Optional[jax.Array] = None

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def size(self) -> int:
        return int(self.list_sizes.sum())


jax.tree_util.register_dataclass(
    Index,
    data_fields=["centers", "storage", "indices", "list_sizes", "data_norms"],
    meta_fields=["metric", "metric_arg", "adaptive_centers"],
)


def _aligned_cap(max_count: int) -> int:
    """List capacity: lane-aligned (128) once lists are big enough for the
    fused scan kernel; 8-aligned for tiny test indexes."""
    if max_count >= 64:
        return round_up_to_multiple(max_count, 128)
    return max(8, round_up_to_multiple(max_count, 8))


def _coarse_metric(metric: DistanceType) -> DistanceType:
    """Metric for the coarse quantizer: pass IP/Cosine through (the
    reference trains kmeans_balanced with the index metric,
    detail/kmeans_balanced.cuh:659); L2 variants all train as L2."""
    if metric in (DistanceType.InnerProduct, DistanceType.CosineExpanded):
        return metric
    return DistanceType.L2Expanded


def _needs_norms(metric: DistanceType) -> bool:
    return metric in (
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.L2Unexpanded,
        DistanceType.CosineExpanded,
    )


@functools.partial(jax.jit, static_argnums=(3, 4))
def _pack_lists(data, labels, row_ids, n_lists: int, cap: int):
    """Scatter rows into padded list blocks (sort-by-label, no atomics).

    Rows labelled >= n_lists are dropped (their scatter slots fall out of
    bounds, which XLA drops) — device-side ``extend`` uses this to discard
    the padding rows of the old storage without a host round-trip.

    Lists holding more than ``cap`` rows are truncated to their first
    ``cap`` rows in stable row order (IVF builds size cap >= max list
    count so this never fires there; the CAGRA/nn-descent reverse-graph
    packers rely on it to cap hub in-degree). Returned sizes are the
    *stored* (truncated) counts."""
    n, d = data.shape
    if n_lists * cap >= 2**31:
        raise ValueError(
            f"padded list storage n_lists*cap = {n_lists}*{cap} overflows "
            "int32 row indexing — the coarse lists are badly skewed "
            "(undertrained kmeans?) or cap_rows should bound list size"
        )
    order = jnp.argsort(labels, stable=True)
    sorted_labels = labels[order]
    counts = jnp.bincount(labels, length=n_lists)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(n) - starts[jnp.minimum(sorted_labels, n_lists - 1)]
    slot = jnp.where(
        (sorted_labels < n_lists) & (pos < cap),
        sorted_labels * cap + pos,
        n_lists * cap,
    )
    counts = jnp.minimum(counts, cap)
    storage = (
        jnp.zeros((n_lists * cap, d), data.dtype).at[slot].set(data[order])
    ).reshape(n_lists, cap, d)
    indices = (
        jnp.full((n_lists * cap,), -1, jnp.int32).at[slot].set(
            row_ids[order].astype(jnp.int32))
    ).reshape(n_lists, cap)
    return storage, indices, counts.astype(jnp.int32)


def build(params: IndexParams, dataset, row_ids=None) -> Index:
    """Build the index (reference ivf_flat-inl.cuh:65 → build.cuh:343):
    subsample a trainset, balanced-kmeans the coarse centers, label every
    row, and scatter rows into padded lists."""
    dataset = jnp.asarray(dataset)
    n, d = dataset.shape
    n_lists = int(params.n_lists)

    with obs.entry_span("build", "ivf_flat", rows=n, n_lists=n_lists):
        with obs.span("ivf_flat.build.train"):
            # 1. trainset subsample + balanced kmeans (ivf_flat_build.cuh:384)
            frac = float(params.kmeans_trainset_fraction)
            if 0 < frac < 1.0 and int(n * frac) >= n_lists:
                step = max(int(1.0 / frac), 1)
                trainset = dataset[::step]
            else:
                trainset = dataset
            kb = KMeansBalancedParams(
                n_clusters=n_lists,
                n_iters=int(params.kmeans_n_iters),
                metric=_coarse_metric(params.metric),
                compute_dtype=str(params.kmeans_compute_dtype),
            )
            centers = kmeans_balanced.fit(kb, trainset)

        st_dtype = {"f32": jnp.float32, "bf16": jnp.bfloat16}.get(
            str(params.storage_dtype))
        if st_dtype is None:
            raise ValueError(
                f"storage_dtype must be f32|bf16, got {params.storage_dtype!r}")
        if st_dtype == jnp.bfloat16 and dataset.dtype not in (jnp.float32,
                                                              jnp.bfloat16):
            # The halved-bandwidth path narrows f32 storage; for any other
            # dataset dtype (f16, int8, ...) narrowing semantics are
            # undefined-to-lossy, and silently keeping dataset.dtype (the
            # pre-r5 behavior) gave the caller no signal (ADVICE r4).
            raise ValueError(
                f"storage_dtype='bf16' requires a float32 dataset, got "
                f"{dataset.dtype}; pass the dataset as f32 or leave "
                "storage_dtype='f32' to store in the dataset dtype")
        index = Index(
            centers=centers,
            storage=jnp.zeros((n_lists, 0, d),
                              st_dtype if dataset.dtype == jnp.float32
                              else dataset.dtype),
            indices=jnp.full((n_lists, 0), -1, jnp.int32),
            list_sizes=jnp.zeros((n_lists,), jnp.int32),
            metric=params.metric,
            metric_arg=params.metric_arg,
            adaptive_centers=bool(params.adaptive_centers),
        )
        if not params.add_data_on_build:
            return index
        if row_ids is None:
            row_ids = jnp.arange(n, dtype=jnp.int32)
        with obs.span("ivf_flat.build.pack"):
            return extend(index, dataset, jnp.asarray(row_ids))


def extend(index: Index, new_vectors, new_ids=None) -> Index:
    """Add vectors (reference ivf_flat_build.cuh:162 extend): label new rows,
    repack all lists at the new capacity, optionally adapt centers."""
    new_vectors = jnp.asarray(new_vectors)
    n_new = new_vectors.shape[0]
    if new_ids is None:
        new_ids = jnp.arange(index.size, index.size + n_new, dtype=jnp.int32)
    new_ids = jnp.asarray(new_ids).astype(jnp.int32)

    kb = KMeansBalancedParams(
        n_clusters=index.n_lists,
        metric=_coarse_metric(index.metric),
    )
    new_labels = kmeans_balanced.predict(kb, index.centers, new_vectors)

    # flatten existing lists + append, all on device: padding rows get the
    # out-of-range label n_lists so _pack_lists drops them (no host
    # round-trip — the reference extends lists in place on device too,
    # ivf_flat_build.cuh:162)
    C = index.n_lists
    old_cap = index.storage.shape[1]
    if old_cap > 0 and index.size > 0:
        flat = index.storage.reshape(-1, index.dim)
        flat_ids = index.indices.reshape(-1)
        flat_labels = jnp.where(
            flat_ids >= 0,
            jnp.repeat(jnp.arange(C, dtype=jnp.int32), old_cap),
            jnp.int32(C),
        )
        data = jnp.concatenate(
            [flat, new_vectors.astype(flat.dtype)], axis=0
        )
        labels = jnp.concatenate([flat_labels, new_labels])
        ids = jnp.concatenate([flat_ids, new_ids])
    else:
        data = new_vectors.astype(index.storage.dtype)
        labels, ids = new_labels, new_ids

    # only the per-list counts come to the host (they size the static cap)
    counts = np.asarray(index.list_sizes) + np.bincount(
        np.asarray(new_labels), minlength=C
    )
    cap = _aligned_cap(int(counts.max()))
    storage, indices, list_sizes = _pack_lists(data, labels, ids, C, cap)

    centers = index.centers
    if index.adaptive_centers:
        # recompute centers as the mean of their lists
        # (ivf_flat_build.cuh extend with adaptive_centers=true)
        centers, _ = kmeans_balanced.calc_centers_and_sizes(
            data, labels, index.n_lists
        )

    norms = None
    if _needs_norms(index.metric):
        s32 = storage.astype(jnp.float32)
        norms = jnp.sum(s32 * s32, axis=2)  # [n_lists, cap]

    return dataclasses.replace(
        index,
        centers=centers,
        storage=storage,
        indices=indices,
        list_sizes=list_sizes,
        data_norms=norms,
    )


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(2, 3))
def _coarse_margins(queries, centers, metric_val: int, p: int):
    """Normalized coarse-selection margin per query: the top-1 vs top-p
    centroid-distance gap in min-close space, scaled into [0, 1].

    This is the same queries x centers GEMM + select the coarse phase
    of ``_ivf_search`` runs (and ``ivf_pq._pq_search`` mirrors) — the
    difficulty signal is already paid for there; this standalone entry
    exposes it to the serving policy (serve/adaptive.py), which must
    pick the probe rung BEFORE the shape-static search dispatches."""
    metric = DistanceType(metric_val)
    q32 = queries.astype(jnp.float32)
    cdot = dist_dot(q32, centers.T)
    if metric == DistanceType.InnerProduct:
        coarse = -cdot                           # min-close space
    elif metric == DistanceType.CosineExpanded:
        qn = jnp.linalg.norm(q32, axis=1, keepdims=True)
        cn = jnp.linalg.norm(centers, axis=1)
        coarse = 1.0 - cdot / jnp.maximum(qn * cn[None, :], 1e-30)
    else:
        qn2 = jnp.sum(q32 * q32, axis=1, keepdims=True)
        cn2 = jnp.sum(centers * centers, axis=1)
        coarse = qn2 + cn2[None, :] - 2.0 * cdot
    vals, _ = select_k(coarse, p, select_min=True)      # ascending
    d1 = vals[:, 0]
    dp = vals[:, p - 1]
    return jnp.clip((dp - d1) / (jnp.abs(d1) + jnp.abs(dp) + 1e-12),
                    0.0, 1.0)


def coarse_margins(index, queries, p: int = 2) -> jax.Array:
    """Per-query difficulty margin [m] in [0, 1] from the coarse
    quantizer: ~0 means the best ``p`` centroids are indistinguishable
    (hard/ambiguous query — probe wide), large means the query sits
    firmly in one list's basin (easy — few probes recover its
    neighbors). Shared by ivf_flat and ivf_pq (both coarse phases run
    the identical queries x centers selection)."""
    queries = jnp.asarray(queries)
    C = int(index.centers.shape[0])
    if C < 2:
        return jnp.ones((queries.shape[0],), jnp.float32)
    return _coarse_margins(queries, index.centers, int(index.metric),
                           int(max(2, min(int(p), C))))


def adaptive_query_group(m: int, n_probes: int, n_lists: int,
                         base: int) -> int:
    """Pick the per-list query-group size for a batch.

    The bucket table's static bound is total/group + n_lists buckets and
    every bucket costs one [cap, d] list-block fetch (DMA-dominant for
    group ≲ 240 on v5e: block DMA time ≈ matmul time at group ≈ 240), so
    the group never shrinks below a lane-efficient 128 — small batches
    only drop from ``base`` toward 128 to bound the mostly-empty-bucket
    compute waste."""
    from raft_tpu.utils.math import cdiv

    total = m * n_probes
    need = round_up_to_multiple(cdiv(total, max(n_lists, 1)), 8)
    return min(int(base), max(128, need))


def bucketize_pairs(
    probes, m: int, n_probes: int, C: int, group: int, bucket_batch: int
):
    """Group (query, probed-list) pairs into fixed-size per-list buckets.

    The core of the TPU IVF search layout (shared by IVF-Flat and IVF-PQ):
    sort pairs by list id, split each list's pair run into buckets of
    ``group`` queries, and GATHER the dense [n_buckets, group] tables from
    the sorted pair array (element scatters measured 2x the gathers).
    ``n_buckets`` has the static bound total/group + C (each list wastes at
    most one partial bucket), so everything jits with static shapes.

    Returns (bucket_list [nb], bucket_q [nb, group] (-1 = empty slot),
    pair_bucket [total], pair_pos [total], order [total] (the sort
    permutation), total, nb).
    """
    total = m * n_probes
    pair_q = jnp.repeat(jnp.arange(m, dtype=jnp.int32), n_probes)
    pair_l = probes.reshape(-1).astype(jnp.int32)
    order = jnp.argsort(pair_l, stable=True)
    sl = pair_l[order]
    sq = pair_q[order]
    # per-list counts from the sorted keys (binary search beats a
    # 640k-element bincount scatter-add by ~7 ms at SIFT-1M shapes)
    bounds = jnp.searchsorted(sl, jnp.arange(C + 1, dtype=jnp.int32))
    counts = jnp.diff(bounds)
    starts = bounds[:-1]
    rank_in_list = jnp.arange(total) - starts[sl]
    nb_per_list = -(-counts // group)  # ceil
    bucket_start = jnp.cumsum(nb_per_list) - nb_per_list
    pair_bucket = bucket_start[sl] + rank_in_list // group
    pair_pos = rank_in_list % group

    n_buckets = total // group + C + 1  # static upper bound on used buckets
    nb_pad = round_up_to_multiple(n_buckets, bucket_batch)
    # bucket tables by GATHER, not scatter (element scatters measured 2x
    # the equivalent gathers here): each list owns the contiguous bucket
    # range [bucket_start[l], bucket_start[l] + nb_per_list[l]), so a
    # bucket's list id is a binary search and its query slots read the
    # sorted pair array at starts[l] + rel_bucket*group + pos
    b_idx = jnp.arange(nb_pad, dtype=jnp.int32)
    bl = (
        jnp.searchsorted(bucket_start, b_idx, side="right").astype(jnp.int32)
        - 1
    )
    bl = jnp.clip(bl, 0, C - 1)
    rel_b = b_idx - bucket_start[bl]
    src = (starts[bl] + rel_b * group)[:, None] + jnp.arange(
        group, dtype=jnp.int32
    )[None, :]
    valid = src < (starts[bl] + counts[bl])[:, None]
    bucket_q = jnp.where(
        valid, sq[jnp.clip(src, 0, total - 1)], -1
    )
    return bl, bucket_q, pair_bucket, pair_pos, order, total, nb_pad


def unbucketize_merge(
    cand_d, cand_i, pair_bucket, pair_pos, order, total, m, n_probes, kl, k,
    select_min, sentinel, approx: bool = False, recall_target: float = 0.95,
):
    """Map per-bucket top-kl candidates back to query-major order and merge
    each query's n_probes x kl candidates into the final top-k.

    The back-mapping is ONE composed row gather: pair-major slot p reads
    bucket slot ``flat_slot[inv_order[p]]`` (a gather-then-scatter pair
    costs 2x the row traffic; row scatters measured slower still).
    ``approx`` uses the TPU partial-reduce top-k for the final merge —
    k=10 of 1280 candidates is its sweet spot (exact lax.top_k there
    costs ~40 ms at m=10k)."""
    group = cand_d.shape[1]
    flat_slot = pair_bucket * group + pair_pos
    inv = jnp.zeros((total,), jnp.int32).at[order].set(
        jnp.arange(total, dtype=jnp.int32)
    )
    comp = flat_slot[inv]
    pd = cand_d.reshape(-1, kl)[comp]
    pi = cand_i.reshape(-1, kl)[comp]
    return merge_topk(
        pd.reshape(m, n_probes * kl), pi.reshape(m, n_probes * kl), k,
        select_min, approx=approx, recall_target=recall_target,
    )


@functools.partial(
    jax.jit,
    static_argnums=(5, 6, 7, 8, 9, 10, 11, 12, 13),
    static_argnames=("scan_impl",),
)
def _ivf_search(
    queries,
    centers,
    storage,
    indices,
    list_sizes,
    k: int,
    n_probes: int,
    metric_val: int,
    group: int,
    bucket_batch: int,
    filter_nbits: int,
    compute_dtype: str = "bf16",
    local_recall_target: float = 0.95,
    merge_recall_target: float = 1.0,
    data_norms=None,
    filter_bits=None,
    *,
    scan_impl: str = "xla",
):
    metric = DistanceType(metric_val)
    select_min = is_min_close(metric)
    C, cap, d = storage.shape
    q32 = queries.astype(jnp.float32)
    m = q32.shape[0]
    sentinel = sentinel_for(metric, jnp.float32)

    # ---- coarse phase: queries x centers GEMM + select n_probes ----------
    # (reference ivf_flat_search-inl.cuh:90-130)
    cdot = dist_dot(q32, centers.T)
    if metric == DistanceType.InnerProduct:
        coarse = cdot
    elif metric == DistanceType.CosineExpanded:
        qn = jnp.linalg.norm(q32, axis=1, keepdims=True)
        cn = jnp.linalg.norm(centers, axis=1)
        coarse = 1.0 - cdot / jnp.maximum(qn * cn[None, :], 1e-30)
    else:
        qn2 = jnp.sum(q32 * q32, axis=1, keepdims=True)
        cn2 = jnp.sum(centers * centers, axis=1)
        coarse = qn2 + cn2[None, :] - 2.0 * cdot
    _, probes = select_k(coarse, n_probes, select_min=select_min)  # [m, np]

    # ---- bucketize (query, probe) pairs by list --------------------------
    (bucket_list, bucket_q, pair_bucket, pair_pos, order, total, nb_pad) = (
        bucketize_pairs(probes, m, n_probes, C, group, bucket_batch)
    )

    # ---- scan list blocks: one MXU matmul per (group x list) -------------
    # per-list top-k cannot exceed the list capacity; the final merge over
    # n_probes lists restores k (requires n_probes * cap >= k)
    kl = min(k, cap)
    qnorm = jnp.sum(q32 * q32, axis=1)
    qlen = jnp.sqrt(qnorm)

    mm = jnp.bfloat16 if compute_dtype == "bf16" else jnp.float32

    if scan_impl.startswith("pallas"):
        # fused Pallas kernel: list blocks DMA'd by scalar-prefetch index,
        # distances + top-k stay in VMEM (raft_tpu.ops.ivf_scan); k <= 256
        # per list (the R-deep binned extraction's capacity — the
        # reference's fused path similarly caps at kMaxCapacity=256,
        # ivf_pq_search.cuh:439 manage_local_topk)
        from raft_tpu.ops import ivf_scan

        kl = min(kl, 256)
        qsafe_b = jnp.maximum(bucket_q, 0)
        qv = q32[qsafe_b].astype(mm)                         # [nb, G, d]
        if metric == DistanceType.InnerProduct:
            mk, qaux, pn2 = ivf_scan.IP, None, None
        elif metric == DistanceType.CosineExpanded:
            mk, qaux = ivf_scan.COSINE, qlen[qsafe_b]
            pn2 = (data_norms if data_norms is not None
                   else jnp.sum(storage.astype(jnp.float32) ** 2, axis=2))
        else:
            mk, qaux = ivf_scan.L2, qnorm[qsafe_b]
            pn2 = (data_norms if data_norms is not None
                   else jnp.sum(storage.astype(jnp.float32) ** 2, axis=2))
        keep = None
        if filter_bits is not None:
            keep = filter_keep(filter_bits, filter_nbits, indices).astype(
                jnp.int32
            )
        out_d, cand_i = ivf_scan.fused_list_scan_topk(
            storage, indices, list_sizes, bucket_list, qv, qaux, pn2, keep,
            k=kl, metric_kind=mk, approx=local_recall_target < 1.0,
            recall_target=float(local_recall_target),
            interpret=scan_impl == "pallas_interpret",
        )                                                    # ids in-kernel
        if metric == DistanceType.InnerProduct:
            cand_d = -out_d                                  # min-space -> score
        else:
            cand_d = out_d
        cand_d = jnp.where(jnp.isinf(out_d), sentinel, cand_d)
        # candidate width comes off the kernel's output: the fold
        # extraction arm emits its R*128 lane-stack buffer instead of kl
        out_d, out_i = unbucketize_merge(
            cand_d, cand_i, pair_bucket, pair_pos, order, total, m,
            n_probes, int(cand_d.shape[2]), k, select_min, sentinel,
            approx=merge_recall_target < 1.0,
            recall_target=merge_recall_target,
        )
        out_i = jnp.where(out_d == sentinel, -1, out_i)
        if metric == DistanceType.L2SqrtExpanded:
            out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
        return out_d, out_i

    def body(_, inp):
        bl, bq = inp  # [bb], [bb, group]
        block = storage[bl].astype(mm)  # [bb, cap, d] contiguous
        ids = indices[bl]  # [bb, cap]
        sizes = list_sizes[bl]  # [bb]
        qsafe = jnp.maximum(bq, 0)
        qv = q32[qsafe].astype(mm)  # [bb, group, d]
        dots = jnp.einsum(
            "bgd,bcd->bgc", qv, block,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if metric == DistanceType.InnerProduct:
            dist = dots
        elif metric == DistanceType.CosineExpanded:
            pn = jnp.sqrt(jnp.maximum(
                data_norms[bl] if data_norms is not None
                else jnp.sum(block * block, axis=2), 1e-30))
            dist = 1.0 - dots / jnp.maximum(
                qlen[qsafe][:, :, None] * pn[:, None, :], 1e-30)
        else:
            pn2 = (data_norms[bl] if data_norms is not None
                   else jnp.sum(block * block, axis=2))
            dist = jnp.maximum(
                qnorm[qsafe][:, :, None] + pn2[:, None, :] - 2.0 * dots, 0.0)

        col_ok = (jnp.arange(cap)[None, :] < sizes[:, None])[:, None, :]
        valid = col_ok & (bq >= 0)[:, :, None]
        if filter_bits is not None:
            valid = valid & filter_keep(filter_bits, filter_nbits, ids)[:, None, :]
        dist = jnp.where(valid, dist, sentinel)
        ld, lsel = merge_topk(
            dist, jnp.broadcast_to(ids[:, None, :], dist.shape), kl, select_min,
            approx=local_recall_target < 1.0,
            recall_target=local_recall_target,
        )  # [bb, group, kl]
        # flattened minor dims: the scan's stacked output otherwise pads
        # kl to 128 lanes (12.8x HBM at k=10)
        return None, (ld.reshape(ld.shape[0], -1),
                      lsel.reshape(lsel.shape[0], -1))

    xs = (
        bucket_list.reshape(-1, bucket_batch),
        bucket_q.reshape(-1, bucket_batch, group),
    )
    _, (cand_d, cand_i) = jax.lax.scan(body, None, xs)
    cand_d = cand_d.reshape(nb_pad, group, kl)
    cand_i = cand_i.reshape(nb_pad, group, kl)

    # ---- un-bucketize + final merge (search-inl.cuh:194) -----------------
    out_d, out_i = unbucketize_merge(
        cand_d, cand_i, pair_bucket, pair_pos, order, total, m, n_probes,
        kl, k, select_min, sentinel,
        approx=merge_recall_target < 1.0,
        recall_target=merge_recall_target,
    )
    # fewer than k valid candidates in the probed lists: report id -1, not
    # whatever id rode along at sentinel distance (the documented contract;
    # refine would otherwise resurrect filtered-out points)
    out_i = jnp.where(out_d == sentinel, -1, out_i)
    if metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    return out_d, out_i


def search(
    search_params: SearchParams,
    index: Index,
    queries,
    k: int,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Approximate k-NN search (reference ivf_flat-inl.cuh:516).

    Returns (distances [m, k], source ids [m, k]); ids are -1 where fewer
    than k valid candidates were found in the probed lists.
    """
    queries = jnp.asarray(queries)
    n_probes = int(min(search_params.n_probes, index.n_lists))
    cap = index.storage.shape[1]
    if cap == 0:
        raise ValueError("index is empty — build with add_data_on_build or extend")
    if k > n_probes * cap:
        raise ValueError(
            f"k={k} exceeds n_probes*list_capacity={n_probes * cap}"
        )
    with obs.entry_span("search", "ivf_flat",
                        queries=int(queries.shape[0]), k=int(k),
                        n_probes=n_probes) as _sp:
        filt = as_filter(prefilter)
        # materializes "keep"-mode tombstone filters (new ids past the
        # filter default to kept) for the drop-semantics scan kernels —
        # docs/serving.md §5; index.size stays lazy (device reduction)
        bits = resolve_filter_bits(filt, lambda: index.size)
        scan_impl = _resolve_scan_impl(
            str(search_params.scan_impl), cap, min(int(k), cap),
            approx=float(search_params.local_recall_target) < 1.0,
        )
        _sp.set(scan_impl=scan_impl)
        if scan_impl.startswith("pallas") and k > n_probes * min(cap, 256):
            raise ValueError(
                f"k={k} exceeds the fused kernel's candidate pool "
                f"n_probes*min(cap,256)={n_probes * min(cap, 256)}; raise "
                "n_probes or use scan_impl='xla'"
            )
        group = adaptive_query_group(
            int(queries.shape[0]), n_probes, index.n_lists,
            int(search_params.query_group),
        )
        return _ivf_search(
            queries,
            index.centers,
            index.storage,
            index.indices,
            index.list_sizes,
            int(k),
            n_probes,
            int(index.metric),
            group,
            int(search_params.bucket_batch),
            0 if bits is None else int(bits.n_bits),
            str(search_params.compute_dtype),
            float(search_params.local_recall_target),
            float(search_params.merge_recall_target),
            index.data_norms,
            None if bits is None else bits.bits,
            scan_impl=scan_impl,
        )


def _resolve_scan_impl(requested: str, cap: int, kl: int,
                       approx: bool = True) -> str:
    """Pick the scan backend through the per-backend dispatch table
    (``tuning.choose("ivf_scan", ...)`` — docs/dispatch_tuning.md). The
    fused Pallas kernel is only a candidate on TPU with a lane-aligned
    list capacity; the analytic fallback (table miss /
    RAFT_TPU_TUNING=off) additionally requires k <= 64: the kernel's
    R-deep binned extraction supports k <= 256 (force with
    scan_impl="pallas"), but the k-pass unrolled extraction measured
    ~7x slower end-to-end than the XLA path at k=130 (r4 v5e; CAGRA
    self-search, SIFT-100k). Everything else runs the XLA bucketized
    scan."""
    if requested != "auto":
        return requested
    from raft_tpu import tuning

    on_tpu = tuning.backend_name() == "tpu"
    # kl <= 256 is structural (the kernel's per-list extraction budget,
    # the reference's kMaxCapacity analog) — beyond it pallas is not a
    # candidate no matter what the table interpolates
    pallas_ok = on_tpu and cap % 128 == 0 and kl <= 256
    candidates = ["xla"] + (["pallas"] if pallas_ok else [])
    analytic = "pallas" if pallas_ok and kl <= 64 else "xla"
    return tuning.choose(
        "ivf_scan", {"cap": cap, "k": kl, "approx": bool(approx)},
        candidates, analytic,
    )


# ---------------------------------------------------------------------------
# helpers (reference ivf_flat_helpers.cuh / codepacker)
# ---------------------------------------------------------------------------


def get_list_data(index: Index, label: int) -> Tuple[np.ndarray, np.ndarray]:
    """Unpack one list's (vectors, source ids) — codepacker analog."""
    size = int(index.list_sizes[label])
    vecs = np.asarray(index.storage[label, :size])
    ids = np.asarray(index.indices[label, :size])
    return vecs, ids


def reconstruct_dataset(index: Index) -> Tuple[np.ndarray, np.ndarray]:
    """All (vectors, source ids) in storage order."""
    flat = np.asarray(index.storage).reshape(-1, index.dim)
    ids = np.asarray(index.indices).reshape(-1)
    valid = ids >= 0
    return flat[valid], ids[valid]


# ---------------------------------------------------------------------------
# serialization (reference ivf_flat_serialize.cuh)
# ---------------------------------------------------------------------------


def save(path: str, index: Index) -> None:
    storage = index.storage
    bf16 = storage.dtype == jnp.bfloat16
    if bf16:
        # the .npy container stays pure-numpy for interop (the reference
        # serializer writes standard npy, mdspan_numpy_serializer.hpp);
        # ml_dtypes bfloat16 round-trips as an opaque V2 dtype that
        # numpy/jax reject on load, so store bf16 widened to f32 (exact)
        # and narrow back on load via the recorded storage_dtype
        storage = storage.astype(jnp.float32)
    arrays = {
        "centers": np.asarray(index.centers),
        "storage": np.asarray(storage),
        "indices": np.asarray(index.indices),
        "list_sizes": np.asarray(index.list_sizes),
    }
    if index.data_norms is not None:
        arrays["data_norms"] = np.asarray(index.data_norms)
    write_index_file(
        path,
        "ivf_flat",
        _SERIAL_VERSION,
        {
            "metric": int(index.metric),
            "metric_arg": index.metric_arg,
            "adaptive_centers": index.adaptive_centers,
            "storage_dtype": "bf16" if bf16 else str(index.storage.dtype),
        },
        arrays,
    )


def load(path: str) -> Index:
    _, meta, arrays = read_index_file(path, "ivf_flat")
    storage = jnp.asarray(arrays["storage"])
    if meta.get("storage_dtype") == "bf16":
        storage = storage.astype(jnp.bfloat16)
    return Index(
        centers=jnp.asarray(arrays["centers"]),
        storage=storage,
        indices=jnp.asarray(arrays["indices"]),
        list_sizes=jnp.asarray(arrays["list_sizes"]),
        metric=DistanceType(meta["metric"]),
        metric_arg=meta["metric_arg"],
        adaptive_centers=bool(meta["adaptive_centers"]),
        data_norms=(
            jnp.asarray(arrays["data_norms"]) if "data_norms" in arrays else None
        ),
    )
