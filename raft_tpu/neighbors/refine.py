"""Exact re-ranking of ANN candidate lists.

Analog of the reference's ``refine`` (cpp/include/raft/neighbors/refine.cuh;
device impl detail/refine_device.cuh, host OpenMP impl
detail/refine_host-inl.hpp). Given candidate neighbor ids per query, compute
exact distances to those candidates and keep the best k. Used by CAGRA's
graph build and by benchmarks to boost IVF-PQ recall.

The TPU formulation is a batched gather + einsum: candidates [m, c] gather
to [m, c, d]; distances per (query, candidate) via the expanded form on the
MXU; then top-k. Works on device arrays or numpy (the "host" variant is the
same code on CPU backend).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.neighbors.common import merge_topk, sentinel_for


def refine(
    dataset,
    queries,
    candidates,
    k: int,
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates`` [n_queries, n_cand] exactly; return top-k.

    Negative candidate ids are treated as invalid (the reference uses them
    the same way for ragged candidate lists).
    """
    metric = resolve_metric(metric)
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    candidates = jnp.asarray(candidates)
    if k > candidates.shape[1]:
        raise ValueError(f"k={k} > n_candidates={candidates.shape[1]}")
    return _refine(dataset, queries, candidates, int(k), int(metric))


@functools.partial(jax.jit, static_argnums=(3, 4))
def _refine(dataset, queries, candidates, k: int, metric_val: int):
    metric = DistanceType(metric_val)
    compute = jnp.promote_types(queries.dtype, jnp.float32)
    q = queries.astype(compute)  # [m, d]
    valid = candidates >= 0
    safe = jnp.where(valid, candidates, 0)
    cand_vecs = dataset[safe].astype(compute)  # [m, c, d]
    return score_gathered(q, cand_vecs, candidates, k, metric)


def score_gathered(q, cand_vecs, candidates, k: int,
                   metric: DistanceType):
    """Exact-scoring tail shared by every gathered-candidate rerank:
    ``q`` [m, d] and ``cand_vecs`` [m, c, d] already at the compute
    dtype, ``candidates`` [m, c] with < 0 marking invalid slots. ONE
    home on purpose — :mod:`raft_tpu.neighbors.tiered` gathers the same
    rows from its fetched/hot blocks instead of a resident dataset, and
    the bitwise-identity acceptance (tiered vs full-upload rerank on
    the same shortlist) holds exactly because both paths run THIS
    arithmetic on value-identical operands. Called inside jit."""
    compute = q.dtype
    valid = candidates >= 0

    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        # ||q - v||^2 via einsum (MXU): q·v per (query, cand)
        dots = jnp.einsum("md,mcd->mc", q, cand_vecs, preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        qn = jnp.sum(q * q, axis=1, keepdims=True)
        vn = jnp.sum(cand_vecs * cand_vecs, axis=2)
        d = jnp.maximum(qn + vn - 2.0 * dots, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            d = jnp.sqrt(d)
    elif metric == DistanceType.InnerProduct:
        d = jnp.einsum("md,mcd->mc", q, cand_vecs, preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)
    elif metric == DistanceType.CosineExpanded:
        dots = jnp.einsum("md,mcd->mc", q, cand_vecs, preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        qn = jnp.sqrt(jnp.sum(q * q, axis=1, keepdims=True))
        vn = jnp.sqrt(jnp.sum(cand_vecs * cand_vecs, axis=2))
        d = 1.0 - dots / jnp.maximum(qn * vn, jnp.finfo(compute).tiny)
    else:
        # generic elementwise fallback
        diff = q[:, None, :] - cand_vecs
        d = jnp.sum(jnp.abs(diff) if metric == DistanceType.L1 else diff * diff, axis=2)

    sentinel = sentinel_for(metric, compute)
    d = jnp.where(valid, d, sentinel)
    return merge_topk(d, candidates.astype(jnp.int32), k,
                      is_min_close(metric))


def refine_host(
    dataset,
    queries,
    candidates,
    k: int,
    metric="sqeuclidean",
    n_threads: int = 0,
) -> Tuple["np.ndarray", "np.ndarray"]:
    """Host-side exact re-ranking over numpy (or ``np.memmap``) data —
    the analog of the reference's OpenMP ``refine_host``
    (cpp/include/raft/neighbors/detail/refine_host-inl.hpp), used when
    the dataset lives on the host (e.g. file-backed / larger than HBM).

    ``dataset`` is indexed row-wise only (memmap-friendly); work is
    split over ``n_threads`` Python threads (numpy releases the GIL in
    the BLAS/reduction kernels, mirroring the reference's OpenMP loop).
    """
    import concurrent.futures as _cf
    import os as _os

    import numpy as np

    metric = resolve_metric(metric)
    if metric not in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                      DistanceType.InnerProduct):
        raise ValueError(f"refine_host supports L2/IP metrics, got {metric!r}")
    q = np.asarray(queries, dtype=np.float32)
    cand = np.asarray(candidates)
    m, c = cand.shape
    if k > c:
        raise ValueError(f"k={k} > n_candidates={c}")
    if n_threads <= 0:
        n_threads = min(32, _os.cpu_count() or 1)
    out_d = np.empty((m, k), np.float32)
    out_i = np.empty((m, k), np.int32)
    minimize = metric != DistanceType.InnerProduct

    def work(lo, hi):
        for i in range(lo, hi):
            ids = cand[i]
            valid = ids >= 0
            rows = np.asarray(dataset[ids[valid].astype(np.int64)],
                              dtype=np.float32)
            dots = rows @ q[i]
            if metric == DistanceType.InnerProduct:
                d = dots
            else:
                d = (rows * rows).sum(1) - 2.0 * dots + q[i] @ q[i]
                np.maximum(d, 0.0, out=d)
                if metric == DistanceType.L2SqrtExpanded:
                    np.sqrt(d, out=d)
            full = np.full(c, np.inf if minimize else -np.inf, np.float32)
            full[valid] = d
            order = np.argsort(full if minimize else -full, kind="stable")[:k]
            out_d[i] = full[order]
            out_i[i] = np.where(np.isfinite(full[order]), ids[order], -1)

    chunk = max(1, -(-m // n_threads))
    with _cf.ThreadPoolExecutor(max_workers=n_threads) as ex:
        futs = [ex.submit(work, lo, min(lo + chunk, m))
                for lo in range(0, m, chunk)]
        for f in futs:
            f.result()
    return out_d, out_i
