"""NN-descent: iterative all-KNN-graph construction.

TPU-native analog of the reference's nn_descent
(cpp/include/raft/neighbors/nn_descent.cuh; impl detail/nn_descent.cuh:
GnndGraph bloom-filter sampling :303-331, GNND::local_join :342-358,700,
reverse-edge kernel :499-513).

Design — pull-based local join, not a port: the reference's push-style
join (every node scatters candidate edges to *other* nodes' lists with
atomics) is hostile to XLA. The equivalent pull formulation: each node
gathers candidates from its 2-hop neighborhood over the forward+reverse
graph (the same candidate set the reference's local join generates, seen
from the receiving side), scores them, and merges them into its list
with a unique top-K — all static shapes, no atomics. Reverse edges come
from the same sort-scatter pack used by the IVF builds; the bloom-filter
"already tried" tracking is replaced by per-iteration random sampling of
the 2-hop columns, which converges the same way (candidates are
re-drawn, duplicates cost only a re-score).

Rebuilt for the memory hierarchy (the TPU-KNN treatment, ROADMAP item
7): the join is **sample-then-gather** — the sampled columns select
``(pool row, neighbor slot)`` pairs first and only those ``[n, S]``
entries are gathered, never the full two-hop tensor
``graph[pool]`` (``[n, 2K, K]`` int32, ~73 GB at n=1M / K=96, which the
original formulation materialized per iteration) — and the iteration is
**blocked over node tiles**: each dispatch covers ``graph_join_rows``
rows (a tuned budget), so peak transient memory is bounded by the block
size, not n, and the OOM degradation ladder
(``resilience.degrade.run_shrinking_blocks``) applies — a
RESOURCE_EXHAUSTED halves the block and records the survivor size
instead of killing the build.
The two formulations are algebraically identical (same columns of the
same tensor), so the rebuild is bitwise-neutral on results; measured
2026-08-04 on the CPU host (GRAPH_r15.json): 3.5x faster per iteration
at 1M rows/K=48 (361 s -> 102 s), old-path two-hop transient 18.4 GB
per iteration at that scale vs the ~3.2 GB blocked bound here.

Scoring + unique-merge dispatch under the ``graph_join`` op key
(docs/dispatch_tuning.md): the XLA path (einsum scoring +
``_merge_topk_unique``) is the fallback and the bitwise oracle; the
fused Pallas local-join kernel (``ops/graph_join.py``) keeps the
``[B, S+K]`` distance matrix and the merge transients out of HBM.

Convergence is checked against a device-side window: per-iteration
update counts stay on device and the host reads the stacked window once
every ``check_every`` iterations (one transfer per window instead of a
blocking scalar sync per iteration), trading at most ``check_every - 1``
surplus iterations — which only refine the graph — for an unblocked
dispatch pipeline.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.types import DistanceType, resolve_metric

_NO_ID = jnp.int32(2147483647)  # sort-to-end sentinel for invalid ids

# analytic node-block default for the blocked join (rows per dispatch);
# the ``graph_join_rows`` budget (tuned table entry or an OOM-ladder
# survivor) overrides it
_DEF_BLOCK_ROWS = 1 << 16


@dataclasses.dataclass
class IndexParams:
    """Build params (reference nn_descent_types.hpp: graph_degree,
    intermediate_graph_degree, max_iterations, termination_threshold)."""

    graph_degree: int = 64
    intermediate_graph_degree: int = 0     # 0 -> 1.5x graph_degree
    max_iterations: int = 20
    termination_threshold: float = 0.0001
    metric: DistanceType = DistanceType.L2Expanded
    # candidates pulled per node per iteration (the reference's
    # max_candidates analog; sampled from the 2-hop pool)
    n_candidates: int = 128
    seed: int = 0
    # join backend: "auto" = dispatch table (op key "graph_join"; the
    # fused Pallas local-join kernel on TPU, XLA elsewhere);
    # "xla" | "pallas" | "pallas_interpret" force. A forced pallas
    # string may carry its node tile ("pallas:16").
    join_impl: str = "auto"
    # rows per join dispatch; 0 = the graph_join_rows budget (tuned
    # table entry / OOM-ladder survivor, analytic default 65536). Peak
    # per-iteration transient memory is proportional to this, not n.
    block_rows: int = 0
    # convergence host-sync cadence: the device-side update-count
    # window is read once every this many iterations
    check_every: int = 4

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.L2Unexpanded,
            DistanceType.InnerProduct,
        ):
            raise ValueError(
                f"nn_descent supports L2/IP metrics, got {self.metric!r}"
            )


@dataclasses.dataclass
class Index:
    """All-neighbors graph (reference nn_descent index: graph [n, deg])."""

    graph: jax.Array       # [n, graph_degree] int32
    distances: jax.Array   # [n, graph_degree] f32


def _score(q_ids, cand_ids, data, norms, ip: bool):
    """dist(x[q_ids[v]], x[cand_ids[v, :]]) for every node v — batched
    matvec epilogue; min-close in both metrics (IP negated)."""
    qv = data[q_ids]                                     # [n, d]
    cv = data[cand_ids]                                  # [n, C, d]
    dots = jnp.einsum(
        "nd,ncd->nc", qv, cv,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGH,
    )
    if ip:
        return -dots
    return jnp.maximum(
        norms[q_ids][:, None] + norms[cand_ids] - 2.0 * dots, 0.0
    )


def _merge_topk_unique(cur_d, cur_i, new_d, new_i, K: int):
    """Merge candidate (dist, id) lists into each row's unique top-K.

    Dedup: stable id-sort, first copy of each id kept, repeats &
    invalids scored +inf. Duplicate copies of an id carry bitwise-equal
    distances in this pipeline (the same deterministic scoring produces
    them), so keep-first coincides with the fused kernel's keep-min
    (ops/graph_join.py) and the two paths agree bitwise; distance ties
    between DIFFERENT ids resolve to the smallest id on both (the
    id-sorted layout makes top_k's lowest-index tie-break the lowest
    id). The final selection routes through ``merge_topk`` (the
    dispatch-tabled ``merge_topk``/``select_k`` rungs,
    matrix/select_k.py) instead of a hard-coded ``lax.top_k``, so the
    hierarchical rung and any future table winner apply to graph build
    too."""
    from raft_tpu.neighbors.common import merge_topk

    all_d = jnp.concatenate([cur_d, new_d], axis=1)
    all_i = jnp.concatenate([cur_i, new_i], axis=1)
    # dedup by id: stable id-sort; repeats & invalids scored +inf
    order = jnp.argsort(jnp.where(all_i < 0, _NO_ID, all_i), axis=1,
                        stable=True)
    si = jnp.take_along_axis(all_i, order, axis=1)
    sd = jnp.take_along_axis(all_d, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((si.shape[0], 1), jnp.bool_), si[:, 1:] == si[:, :-1]],
        axis=1,
    ) | (si < 0)
    sd = jnp.where(dup, jnp.inf, sd)
    si = jnp.where(dup, -1, si)  # dup slots must not leak ids into the top-K
    return merge_topk(sd, si, K, select_min=True)


@jax.jit
def _make_rev(graph_i):
    """Reverse graph, capped at K per node (kern_make_rev_graph analog):
    pack sources by destination with the IVF sort-scatter."""
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    n, K = graph_i.shape
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), K)
    dst = graph_i.reshape(-1)
    dst = jnp.where(dst >= 0, dst, n)
    _, rev_i, _ = _pack_lists(
        jnp.zeros((n * K, 1), jnp.int8), dst, src, n, K
    )
    return rev_i


@functools.partial(jax.jit, static_argnames=("rows", "ip"))
def _init_block(data, norms, init_i, start, *, rows: int, ip: bool):
    """Exactly score + dedup one node block of the random init."""
    d = data.shape[1]
    K = init_i.shape[1]
    ib = jax.lax.dynamic_slice(init_i, (start, 0), (rows, K))
    q_ids = start + jnp.arange(rows, dtype=jnp.int32)
    idist = _score(q_ids, ib, data, norms, ip)
    return _merge_topk_unique(
        idist, ib, jnp.full((rows, 1), jnp.inf), jnp.full((rows, 1), -1), K
    )


@functools.partial(
    jax.jit, static_argnames=("rows", "ip", "impl", "tile_b"),
)
def _join_block(data, norms, graph_d, graph_i, pool, rev_i, cols, start,
                *, rows: int, ip: bool, impl: str, tile_b: int):
    """One local-join dispatch over node rows [start, start+rows).

    Sample-then-gather: ``cols`` selects (pool slot, neighbor slot)
    pairs, so only the [rows, S] sampled two-hop entries are gathered —
    the full [rows, 2K, K] two-hop tensor is never formed. Row
    independent (the blocked cover is bitwise what one unblocked
    dispatch would produce), which is what lets the OOM ladder split it.
    """
    n, d = data.shape
    K = graph_i.shape[1]
    S = cols.shape[0]
    gd = jax.lax.dynamic_slice(graph_d, (start, 0), (rows, K))
    gi = jax.lax.dynamic_slice(graph_i, (start, 0), (rows, K))
    pool_b = jax.lax.dynamic_slice(pool, (start, 0), (rows, 2 * K))
    rev_b = jax.lax.dynamic_slice(rev_i, (start, 0), (rows, K))

    sel = cols // K                                      # [S] pool slot
    off = cols % K                                       # [S] neighbor slot
    hop_rows = jnp.take(jnp.maximum(pool_b, 0), sel, axis=1)   # [rows, S]
    cand = graph_i[hop_rows, jnp.broadcast_to(off[None, :],
                                              (rows, S))]      # [rows, S]
    src_ok = jnp.take(pool_b, sel, axis=1) >= 0
    cand = jnp.where(src_ok, cand, -1)
    cand = jnp.concatenate([cand, rev_b], axis=1)        # pool reverse too
    node_ids = start + jnp.arange(rows, dtype=jnp.int32)
    cand = jnp.where(cand == node_ids[:, None], -1, cand)  # no self loops

    cand_safe = jnp.maximum(cand, 0)
    if impl.startswith("pallas"):
        from raft_tpu.ops.graph_join import graph_local_join

        qv = jax.lax.dynamic_slice(data, (start, 0), (rows, d))
        new_d, new_i = graph_local_join(
            qv, cand, data[cand_safe], gd, gi,
            None if ip else jax.lax.dynamic_slice(norms, (start,), (rows,)),
            None if ip else norms[cand_safe],
            ip=ip, tile_b=tile_b,
            interpret=impl.startswith("pallas_interpret"),
        )
    else:
        cand_d = _score(node_ids, cand_safe, data, norms, ip)
        cand_d = jnp.where(cand < 0, jnp.inf, cand_d)
        new_d, new_i = _merge_topk_unique(gd, gi, cand_d, cand, K)
    n_updates = jnp.sum(new_i != gi, dtype=jnp.int32)
    return new_d, new_i, n_updates


def _blocked(fn, n: int, block: int):
    """Cover [0, n) with ``fn(start, rows)`` under the OOM ladder —
    every dispatch, single-block covers included, so a
    RESOURCE_EXHAUSTED always halves and records instead of killing the
    build (the ladder's per-block completion sync is the price; the
    per-iteration host read this module used to pay — the scalar
    convergence transfer — stays killed, see the build loop's window)."""
    from raft_tpu.resilience import degrade

    return list(degrade.run_shrinking_blocks(
        fn, n, block, budget_name="graph_join_rows",
        stage="nn_descent.join",
    ))


def _resolve_join_impl(requested: str, C: int, K: int, d: int,
                       ip: bool) -> str:
    """Pick the join backend through the per-backend dispatch table
    (``tuning.choose("graph_join", ...)`` — docs/dispatch_tuning.md).
    The fused kernel is TPU-only and caps at K <= 128 (its K-pass
    extraction budget); winner strings carry the node tile
    (``pallas:<tile_b>``), so a live-chip capture adopts tile geometry
    with no code change. The analytic fallback on TPU is the fused
    kernel at the expression-derived tile; everywhere else the XLA
    join."""
    from raft_tpu import tuning
    from raft_tpu.ops.graph_join import tile_geometry

    if requested != "auto":
        if requested in ("pallas", "pallas_interpret"):
            return f"{requested}:{tile_geometry(C, K, d, ip)['tile_b']}"
        return requested
    if K > 128 or tuning.backend_name() != "tpu":
        return "xla"
    cands = ["xla"] + [f"pallas:{t}" for t in tuning.GRAPH_JOIN_TILES]
    fallback = f"pallas:{tile_geometry(C, K, d, ip)['tile_b']}"
    return tuning.choose(
        "graph_join", {"C": int(C), "K": int(K), "d": int(d)},
        cands, fallback,
    )


def build(params: IndexParams, dataset) -> Index:
    """Build the all-KNN graph (reference nn_descent.cuh build)."""
    from raft_tpu import obs

    data = jnp.asarray(dataset).astype(jnp.float32)
    n, d = data.shape
    with obs.entry_span("build", "nn_descent", rows=n):
        return _build(params, data, n)


def _build(params: IndexParams, data, n: int) -> Index:
    from raft_tpu import obs, tuning

    K = int(params.intermediate_graph_degree) or max(
        int(params.graph_degree * 3 // 2), int(params.graph_degree)
    )
    K = min(K, n - 1)
    out_K = min(int(params.graph_degree), K)
    d = int(data.shape[1])
    ip = params.metric == DistanceType.InnerProduct
    norms = jnp.sum(data * data, axis=1)
    key = jax.random.PRNGKey(params.seed)

    S = int(params.n_candidates)
    impl = _resolve_join_impl(str(params.join_impl), S + K, K, d, ip)
    kind, _, tile = impl.partition(":")
    tile_b = int(tile) if tile else 0

    def block_rows() -> int:
        # re-read per iteration: an OOM downshift records a runtime
        # ceiling mid-build, and later iterations must START at the
        # survivor size instead of re-attempting the known-too-big
        # block once per iteration. An explicit block_rows wins over
        # the tuned default; the learned ceiling outranks both.
        if int(params.block_rows) > 0:
            ceil = tuning.runtime_budget("graph_join_rows")
            b = int(params.block_rows) if ceil is None else min(
                int(params.block_rows), ceil)
        else:
            b = int(tuning.budget("graph_join_rows", _DEF_BLOCK_ROWS))
        return max(1, b)

    # init: random neighbors, exactly scored + deduped, blocked like the
    # join (the [rows, K, d] init gather is the same transient class)
    key, k0 = jax.random.split(key)
    init_i = jax.random.randint(k0, (n, K), 0, n).astype(jnp.int32)
    init_i = jnp.where(init_i == jnp.arange(n)[:, None], (init_i + 1) % n,
                       init_i)
    parts = _blocked(
        lambda s, r: _init_block(data, norms, init_i, s, rows=r, ip=ip),
        n, block_rows(),
    )
    graph_d = jnp.concatenate([p[0] for p in parts], axis=0)
    graph_i = jnp.concatenate([p[1] for p in parts], axis=0)

    threshold = float(params.termination_threshold) * n * K
    check_every = max(1, int(params.check_every))
    updates = []                      # device-side window, read per-window
    with obs.span("nn_descent.iterate", impl=impl, block=block_rows(),
                  iters=int(params.max_iterations)):
        for it in range(int(params.max_iterations)):
            key, kit = jax.random.split(key)
            rev_i = _make_rev(graph_i)
            pool = jnp.concatenate([graph_i, rev_i], axis=1)   # [n, 2K]
            # fresh column draw per iteration — the bloom-filter
            # "new vs old" bookkeeping collapses into re-sampling
            cols = jax.random.randint(kit, (S,), 0, 2 * K * K)
            parts = _blocked(
                lambda s, r: _join_block(
                    data, norms, graph_d, graph_i, pool, rev_i, cols, s,
                    rows=r, ip=ip, impl=kind, tile_b=tile_b),
                n, block_rows(),
            )
            graph_d = jnp.concatenate([p[0] for p in parts], axis=0)
            graph_i = jnp.concatenate([p[1] for p in parts], axis=0)
            updates.append(sum(p[2] for p in parts))
            if len(updates) >= check_every:
                window = jax.device_get(jnp.stack(updates))
                updates = []
                if int(window.min()) <= threshold:
                    break
    dists = graph_d[:, :out_K]
    if params.metric == DistanceType.L2SqrtExpanded:
        dists = jnp.sqrt(jnp.maximum(dists, 0.0))
    elif ip:
        dists = -dists
    return Index(graph=graph_i[:, :out_K], distances=dists)
