"""NN-descent: iterative all-KNN-graph construction.

TPU-native analog of the reference's nn_descent
(cpp/include/raft/neighbors/nn_descent.cuh; impl detail/nn_descent.cuh:
GnndGraph bloom-filter sampling :303-331, GNND::local_join :342-358,700,
reverse-edge kernel :499-513).

Design — pull-based local join, not a port: the reference's push-style
join (every node scatters candidate edges to *other* nodes' lists with
atomics) is hostile to XLA. The equivalent pull formulation: each node
gathers its 2-hop neighborhood over the forward+reverse graph (the same
candidate set the reference's local join generates, seen from the
receiving side), scores the candidates in one batched MXU contraction,
and merges them into its list with a sort-based dedup — all static
shapes, no atomics. Reverse edges come from the same sort-scatter pack
used by the IVF builds; the bloom-filter "already tried" tracking is
replaced by per-iteration random sampling of the 2-hop columns, which
converges the same way (candidates are re-drawn, duplicates cost only a
re-score).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.types import DistanceType, resolve_metric

_NO_ID = jnp.int32(2147483647)  # sort-to-end sentinel for invalid ids


@dataclasses.dataclass
class IndexParams:
    """Build params (reference nn_descent_types.hpp: graph_degree,
    intermediate_graph_degree, max_iterations, termination_threshold)."""

    graph_degree: int = 64
    intermediate_graph_degree: int = 0     # 0 -> 1.5x graph_degree
    max_iterations: int = 20
    termination_threshold: float = 0.0001
    metric: DistanceType = DistanceType.L2Expanded
    # candidates pulled per node per iteration (the reference's
    # max_candidates analog; sampled from the 2-hop pool)
    n_candidates: int = 128
    seed: int = 0

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.L2Unexpanded,
            DistanceType.InnerProduct,
        ):
            raise ValueError(
                f"nn_descent supports L2/IP metrics, got {self.metric!r}"
            )


@dataclasses.dataclass
class Index:
    """All-neighbors graph (reference nn_descent index: graph [n, deg])."""

    graph: jax.Array       # [n, graph_degree] int32
    distances: jax.Array   # [n, graph_degree] f32


def _score(q_ids, cand_ids, data, norms, ip: bool):
    """dist(x[q_ids[v]], x[cand_ids[v, :]]) for every node v — batched
    matvec epilogue; min-close in both metrics (IP negated)."""
    qv = data[q_ids]                                     # [n, d]
    cv = data[cand_ids]                                  # [n, C, d]
    dots = jnp.einsum(
        "nd,ncd->nc", qv, cv,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGH,
    )
    if ip:
        return -dots
    return jnp.maximum(
        norms[q_ids][:, None] + norms[cand_ids] - 2.0 * dots, 0.0
    )


def _merge_topk_unique(cur_d, cur_i, new_d, new_i, K: int):
    """Merge candidate (dist, id) lists into each row's unique top-K."""
    all_d = jnp.concatenate([cur_d, new_d], axis=1)
    all_i = jnp.concatenate([cur_i, new_i], axis=1)
    # dedup by id: stable id-sort; repeats & invalids scored +inf
    order = jnp.argsort(jnp.where(all_i < 0, _NO_ID, all_i), axis=1,
                        stable=True)
    si = jnp.take_along_axis(all_i, order, axis=1)
    sd = jnp.take_along_axis(all_d, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((si.shape[0], 1), jnp.bool_), si[:, 1:] == si[:, :-1]],
        axis=1,
    ) | (si < 0)
    sd = jnp.where(dup, jnp.inf, sd)
    si = jnp.where(dup, -1, si)  # dup slots must not leak ids into the top-K
    nd, sel = jax.lax.top_k(-sd, K)
    return -nd, jnp.take_along_axis(si, sel, axis=1)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _nnd_iter(state, data, norms, K: int, S: int, ip: bool, key=None):
    graph_d, graph_i = state
    n = data.shape[0]
    node_ids = jnp.arange(n, dtype=jnp.int32)

    # reverse graph (kern_make_rev_graph analog): pack sources by dest
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    src = jnp.repeat(node_ids, K)
    dst = graph_i.reshape(-1)
    dst = jnp.where(dst >= 0, dst, n)
    _, rev_i, _ = _pack_lists(
        jnp.zeros((n * K, 1), jnp.int8), dst, src, n, K
    )

    pool = jnp.concatenate([graph_i, rev_i], axis=1)     # [n, 2K]
    pool_safe = jnp.maximum(pool, 0)

    # 2-hop candidates: sample S of the 2K*K columns (fresh draw per call
    # — the bloom-filter "new vs old" bookkeeping collapses into
    # re-sampling)
    cols = jax.random.randint(key, (S,), 0, 2 * K * K)
    two_hop = graph_i[pool_safe]                         # [n, 2K, K]
    cand = two_hop.reshape(n, 2 * K * K)[:, cols]        # [n, S]
    cand = jnp.where(
        jnp.take_along_axis(
            pool, jnp.broadcast_to(cols[None, :] // K, (n, S)), axis=1
        ) >= 0,
        cand, -1,
    )
    cand = jnp.concatenate([cand, rev_i], axis=1)        # pool reverse too
    cand = jnp.where(cand == node_ids[:, None], -1, cand)  # no self loops

    cand_d = _score(node_ids, jnp.maximum(cand, 0), data, norms, ip)
    cand_d = jnp.where(cand < 0, jnp.inf, cand_d)
    new_d, new_i = _merge_topk_unique(graph_d, graph_i, cand_d, cand, K)
    n_updates = jnp.sum(new_i != graph_i)
    return (new_d, new_i), n_updates


def build(params: IndexParams, dataset) -> Index:
    """Build the all-KNN graph (reference nn_descent.cuh build)."""
    from raft_tpu import obs

    data = jnp.asarray(dataset).astype(jnp.float32)
    n, d = data.shape
    with obs.entry_span("build", "nn_descent", rows=n):
        return _build(params, data, n)


def _build(params: IndexParams, data, n: int) -> Index:
    K = int(params.intermediate_graph_degree) or max(
        int(params.graph_degree * 3 // 2), int(params.graph_degree)
    )
    K = min(K, n - 1)
    out_K = min(int(params.graph_degree), K)
    ip = params.metric == DistanceType.InnerProduct
    norms = jnp.sum(data * data, axis=1)
    key = jax.random.PRNGKey(params.seed)

    # init: random neighbors, exactly scored
    key, k0 = jax.random.split(key)
    init_i = jax.random.randint(k0, (n, K), 0, n).astype(jnp.int32)
    init_i = jnp.where(init_i == jnp.arange(n)[:, None], (init_i + 1) % n,
                       init_i)
    init_d = _score(jnp.arange(n, dtype=jnp.int32), init_i, data, norms, ip)
    # dedup the random init
    graph_d, graph_i = _merge_topk_unique(
        init_d, init_i, jnp.full((n, 1), jnp.inf), jnp.full((n, 1), -1), K
    )

    S = int(params.n_candidates)
    state = (graph_d, graph_i)
    threshold = float(params.termination_threshold) * n * K
    for _ in range(int(params.max_iterations)):
        key, kit = jax.random.split(key)
        state, n_updates = _nnd_iter(state, data, norms, K, S, ip, key=kit)
        if int(n_updates) <= threshold:
            break
    graph_d, graph_i = state
    dists = graph_d[:, :out_K]
    if params.metric == DistanceType.L2SqrtExpanded:
        dists = jnp.sqrt(jnp.maximum(dists, 0.0))
    elif ip:
        dists = -dists
    return Index(graph=graph_i[:, :out_K], distances=dists)
