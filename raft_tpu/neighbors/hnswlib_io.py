"""Independent reader for hnswlib v0 index files.

Counterpart check for ``cagra.serialize_to_hnswlib`` (reference export:
detail/cagra/cagra_serialize.cuh serialize_to_hnswlib, consumed
base-layer-only by bench/ann/src/raft/raft_cagra_hnswlib_wrapper.h:96).
The real hnswlib is not installable in this environment, so this module
re-implements ``HierarchicalNSW::loadIndex``'s on-disk contract from the
hnswlib source (hnswalg.h loadIndex: header scalars in declaration
order, then ``cur_element_count`` fixed-stride level-0 records of
[linklist | data | label], then per-node level ints) — deliberately
DRIVEN BY THE HEADER FIELDS (size_data_per_element_, offsetData_,
label_offset_) rather than recomputing the writer's arithmetic, so a
writer/layout disagreement shows up as a parse failure instead of a
symmetric pass.

Also provides a greedy base-layer search so tests can prove the loaded
structure is actually navigable, not just byte-identical.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np


@dataclass
class HnswIndex:
    data: np.ndarray        # [n, dim] f32
    links: np.ndarray       # [n, maxM0] int32 (-1 padded)
    labels: np.ndarray      # [n] int64
    entrypoint: int
    maxM0: int
    M: int
    ef_construction: int


def load_hnswlib_index(path: str, dim: int) -> HnswIndex:
    """Parse an hnswlib v0 file (base layer). ``dim`` is external input,
    as in hnswlib (the file does not store it — the space does)."""
    with open(path, "rb") as f:
        def u64():
            return struct.unpack("<Q", f.read(8))[0]

        offset_level0 = u64()
        max_elements = u64()
        cur_count = u64()
        size_data_per_element = u64()
        label_offset = u64()
        offset_data = u64()
        (maxlevel,) = struct.unpack("<i", f.read(4))
        (entrypoint,) = struct.unpack("<I", f.read(4))
        maxM = u64()
        maxM0 = u64()
        M = u64()
        (mult,) = struct.unpack("<d", f.read(8))
        ef_construction = u64()

        # structural consistency (loadIndex asserts the same relations)
        if offset_level0 != 0:
            raise ValueError(f"offsetLevel0 must be 0, got {offset_level0}")
        data_size = dim * 4
        if label_offset + 8 != size_data_per_element:
            raise ValueError("label region does not end the element record")
        if offset_data + data_size != label_offset:
            raise ValueError("data region does not abut the label region")
        if offset_data < 4 + 4 * maxM0:
            raise ValueError("link region too small for maxM0 links")
        if cur_count > max_elements:
            raise ValueError("cur_element_count exceeds max_elements")

        raw = f.read(cur_count * size_data_per_element)
        if len(raw) != cur_count * size_data_per_element:
            raise ValueError("truncated level-0 records")
        levels = np.frombuffer(f.read(cur_count * 4), dtype="<i4")
        if levels.size != cur_count:
            raise ValueError("truncated element_levels")
        if maxlevel == 0 and levels.any():
            raise ValueError("maxlevel=0 but nonzero element levels present")

    rec = np.frombuffer(raw, dtype=np.uint8).reshape(
        cur_count, size_data_per_element
    )
    # linklist: uint16 count (hnswlib setListCount) in the first 2 bytes
    counts = rec[:, :2].copy().view("<u2")[:, 0].astype(np.int64)
    if (counts > maxM0).any():
        raise ValueError("link count exceeds maxM0")
    links_raw = rec[:, 4:4 + 4 * maxM0].copy().view("<i4").reshape(
        cur_count, maxM0
    ).astype(np.int32)
    lane = np.arange(maxM0)[None, :]
    links = np.where(lane < counts[:, None], links_raw, -1)
    if ((links >= int(cur_count)) | ((links < 0) & (links != -1))).any():
        raise ValueError("link target out of range")
    data = rec[:, offset_data:offset_data + data_size].copy().view(
        "<f4"
    ).reshape(cur_count, dim)
    labels = rec[:, label_offset:label_offset + 8].copy().view(
        "<i8"
    )[:, 0].copy()
    return HnswIndex(
        data=data, links=links, labels=labels, entrypoint=int(entrypoint),
        maxM0=int(maxM0), M=int(M), ef_construction=int(ef_construction),
    )


def greedy_search(index: HnswIndex, query: np.ndarray, k: int,
                  ef: int = 64, max_hops: int = 500):
    """Base-layer best-first search (hnswlib searchBaseLayerST's
    algorithm in plain numpy/heapq) — proves the exported graph is
    navigable the way hnswlib would navigate it."""
    import heapq

    q = np.asarray(query, np.float32)

    def dist(i):
        d = index.data[i] - q
        return float(d @ d)

    ep = index.entrypoint
    visited = {ep}
    cand = [(dist(ep), ep)]              # min-heap of candidates
    top = [(-cand[0][0], ep)]            # max-heap (neg) of best ef
    hops = 0
    while cand and hops < max_hops:
        d_c, c = heapq.heappop(cand)
        if top and d_c > -top[0][0] and len(top) >= ef:
            break
        for nb in index.links[c]:
            if nb < 0 or nb in visited:
                continue
            visited.add(nb)
            d_n = dist(nb)
            if len(top) < ef or d_n < -top[0][0]:
                heapq.heappush(cand, (d_n, nb))
                heapq.heappush(top, (-d_n, nb))
                if len(top) > ef:
                    heapq.heappop(top)
        hops += 1
    best = sorted(((-nd, i) for nd, i in top))[:k]
    ids = np.array([index.labels[i] for _, i in best], np.int64)
    ds = np.array([d for d, _ in best], np.float32)
    return ds, ids
