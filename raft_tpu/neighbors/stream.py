"""Out-of-core (host/file-resident) search orchestration.

Analog of the reference's ``batch_load_iterator``-driven paths
(cpp/include/raft/spatial/knn/detail/ann_utils.cuh:397; the ANN bench
harness mmaps datasets, cpp/bench/ann/src/common/dataset.hpp:45-128):
queries stream host→device in double-buffered batches (the native
prefetcher keeps disk IO ahead of the transfers for file sources), each
batch runs the regular device search, and results land in preallocated
host arrays. The device only ever holds one query batch + the index.

Resilience (docs/resilience.md): every batch dispatch is a fault
boundary. Transient / dead-backend failures are retried with backoff
(:func:`raft_tpu.resilience.run`); a RESOURCE_EXHAUSTED walks the OOM
degradation ladder (:func:`raft_tpu.resilience.degrade.run_halving` —
halve, re-dispatch, record the surviving size so the remaining batches
and later calls start safe); ``checkpoint_dir=`` persists completed
rows per chunk so ``resume=True`` continues a killed job with
bitwise-identical output; and the caller's
:class:`~raft_tpu.core.interruptible.Interruptible` token is checked
between batches so ``cancel()`` from another thread actually stops an
out-of-core job.
"""

from __future__ import annotations

import time
from typing import Callable, Iterable, Optional, Tuple

import jax
import numpy as np

from raft_tpu import obs, resilience, tuning
from raft_tpu.core import pipeline
from raft_tpu.core.interruptible import Interruptible
from raft_tpu.resilience import degrade, faultinject
from raft_tpu.utils.batch import BatchLoadIterator, FileBatchLoadIterator

# the runtime-budget key the OOM ladder records surviving batch rows
# under; search_file/search_host_array clamp their requested batch_rows
# to it so a process that OOMed once starts safe thereafter
STREAM_BATCH_BUDGET = "stream_batch_rows"


def search_stream(
    search_fn: Callable,
    batches: Iterable[Tuple[int, "object"]],
    n_queries: int,
    k: int,
    *,
    stage: str = "search",
    retries: int = 2,
    backoff_s: float = 0.5,
    deadline_s: Optional[float] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 8,
    resume: bool = False,
    token: Optional[Interruptible] = None,
    pipeline_depth: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Run ``search_fn(query_batch) -> (dists, ids)`` over an iterator of
    ``(offset, device_batch)`` pairs (``BatchLoadIterator`` /
    ``FileBatchLoadIterator``), assembling host result arrays.

    Batches may be zero-padded to a fixed shape (``pad_to_full=True`` —
    one compiled program for every batch); rows beyond ``n_queries`` are
    dropped.

    Fault tolerance per batch: transient/dead-backend failures retry
    (``retries``/``backoff_s``/``deadline_s`` feed
    :func:`raft_tpu.resilience.run`), OOM walks the halving ladder and
    shrinks the iterator's remaining batches to the surviving size, and
    ``checkpoint_dir``/``resume`` give killed jobs bitwise-identical
    restarts. Each save rewrites the FULL completed-result prefix (the
    blob is self-contained, one file always resumes), so
    ``checkpoint_every`` trades replayed batches against checkpoint I/O
    — at big-ann result sizes keep it well above 1 (default 8).
    ``token`` (default: the calling thread's token) is checked between
    batches — ``cancel()`` from another thread raises
    ``InterruptedException`` at the next boundary.

    ``pipeline_depth`` sets the graft-flow prefetch depth (default: the
    ``pipeline_depth`` tuning budget, 2 = double-buffered): chunk N+1's
    host read + device upload run on a background producer while chunk
    N scans. Depth only moves when the read happens, never what is
    computed, so any depth (including 0 = off) yields bitwise-identical
    results; checkpoints stay consumption-ordered (a prefetched chunk
    is never marked done), and an OOM downshift rewinds + flushes the
    prefetcher so in-flight chunks re-read at the surviving size
    (docs/resilience.md).
    """
    out_d = np.empty((n_queries, k), np.float32)
    out_i = np.empty((n_queries, k), np.int32)
    ck = (resilience.StreamCheckpoint(checkpoint_dir)
          if checkpoint_dir else None)
    fingerprint = {"n_queries": int(n_queries), "k": int(k), "stage": stage}
    rows_done = 0
    if ck is not None and resume:
        state = ck.load(fingerprint=fingerprint)
        if state is not None:
            _, _, meta, arrays = state
            rows_done = int(meta["rows_done"])
            out_d[:rows_done] = arrays["dists"]
            out_i[:rows_done] = arrays["ids"]
    if token is None:
        token = Interruptible.get_token()

    # graft-flow: a bounded producer keeps the next chunk's host read +
    # H2D upload ahead of the scan; depth 0 degenerates to the original
    # inline loop (bitwise-identical scheduling)
    pf = pipeline.Prefetcher(batches, depth=pipeline_depth,
                             path=f"stream.{stage}", token=token)
    with obs.span("stream.search_stream", stage=stage,
                  n_queries=int(n_queries), k=int(k), resumed=rows_done,
                  pipeline_depth=pf.depth), pf:
        for ci, (offset, batch) in enumerate(pf):
            rows = min(batch.shape[0], n_queries - offset)
            if offset + rows <= rows_done:
                continue                  # resumed past this chunk
            if offset < rows_done:
                raise ValueError(
                    f"resume misalignment: checkpoint covers {rows_done} "
                    f"rows but the iterator produced a batch at offset "
                    f"{offset}; resume with the batch size the checkpoint "
                    "was written at"
                )
            token.check()

            def dispatch(b, _ci=ci):
                faultinject.check(stage=stage, chunk=_ci)
                out = search_fn(b)
                # sync INSIDE the retry-wrapped callable: XLA dispatch is
                # async, so a real transient/dead-backend error surfaces at
                # the wait — it must strike where resilience.run can retry
                # it, not at the ladder's (OOM-only) outer sync
                jax.block_until_ready(out)
                return out

            t0 = time.perf_counter()
            with obs.span("stream.chunk", chunk=ci, offset=int(offset)):
                (d, i), survived = degrade.run_halving(
                    lambda b: resilience.run(
                        dispatch, b, retries=retries, backoff_s=backoff_s,
                        deadline_s=deadline_s, token=token,
                    ),
                    batch,
                    budget_name=STREAM_BATCH_BUDGET,
                )
            # chunk latency is DEVICE-COMPLETE (the dispatch syncs), so
            # this histogram is the per-batch serving latency — unlike the
            # entry-point search_latency_ms, which times async dispatch
            obs.observe("search_latency_ms", (time.perf_counter() - t0) * 1e3,
                        algo="stream", stage=stage)
            obs.counter("stream_rows_total", rows, stage=stage)
            obs.counter("stream_chunks_total", stage=stage)
            out_d[offset:offset + rows] = np.asarray(d[:rows], np.float32)
            out_i[offset:offset + rows] = np.asarray(i[:rows])
            rows_done = offset + rows
            if survived < batch.shape[0] and hasattr(batches, "set_batch_rows"):
                batches.set_batch_rows(survived)
                if pf.depth > 0 and hasattr(batches, "start_row"):
                    # chunks already prefetched carry the pre-downshift
                    # geometry and would re-OOM under real memory
                    # pressure: rewind the source to the consumed row
                    # mark and flush so in-flight work re-reads at the
                    # surviving size (row-exact restart == resume, so
                    # outputs stay bitwise)
                    batches.start_row = rows_done
                    pf.flush()
            if ck is not None and (ci + 1) % max(int(checkpoint_every), 1) == 0:
                ck.save(
                    "search", ci, {"rows_done": rows_done},
                    {"dists": out_d[:rows_done], "ids": out_i[:rows_done]},
                    fingerprint=fingerprint,
                )
    return out_d, out_i


def _clamped_batch_rows(batch_rows: int) -> int:
    """Requested rows clamped to the ladder's recorded OOM-survivor size
    (no-op until an OOM has actually struck in this process)."""
    return max(int(tuning.budget(STREAM_BATCH_BUDGET, int(batch_rows))), 1)


def search_file(
    module,
    search_params,
    index,
    queries_path: str,
    k: int,
    batch_rows: int = 8192,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 8,
    resume: bool = False,
    token: Optional[Interruptible] = None,
    retries: int = 2,
    backoff_s: float = 0.5,
    deadline_s: Optional[float] = None,
    pipeline_depth: Optional[int] = None,
    **search_kwargs,
) -> Tuple[np.ndarray, np.ndarray]:
    """Stream a ``.fbin``-family query file through ``module.search``
    (ivf_flat / ivf_pq / cagra / brute_force-style modules) in fixed-size
    device batches. The file never materializes on the host in full.

    ``checkpoint_dir``/``resume`` checkpoint completed rows per chunk
    (resume at the SAME ``batch_rows``); see :func:`search_stream` for
    the retry/ladder/cancellation semantics.
    """
    it = FileBatchLoadIterator(
        queries_path, _clamped_batch_rows(batch_rows), pad_to_full=True
    )

    def fn(batch):
        return module.search(search_params, index, batch, k,
                             **search_kwargs)

    with obs.span("stream.search_file", path=queries_path, k=int(k)):
        return search_stream(
            fn, it, it.shape[0], k,
            retries=retries, backoff_s=backoff_s, deadline_s=deadline_s,
            checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
            resume=resume, token=token, pipeline_depth=pipeline_depth,
        )


def search_host_array(
    module,
    search_params,
    index,
    queries: np.ndarray,
    k: int,
    batch_rows: int = 8192,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 8,
    resume: bool = False,
    token: Optional[Interruptible] = None,
    retries: int = 2,
    backoff_s: float = 0.5,
    deadline_s: Optional[float] = None,
    pipeline_depth: Optional[int] = None,
    **search_kwargs,
) -> Tuple[np.ndarray, np.ndarray]:
    """Same streaming pattern over a host-resident array (numpy or
    ``np.memmap``) — the double-buffered ``BatchLoadIterator`` overlaps
    host→device copies with the previous batch's search.

    On ``resume`` the iterator starts AT the checkpoint's completed-row
    mark (``start_row``), so already-searched rows are never re-uploaded
    — and because the restart is row-exact, resuming may use a different
    ``batch_rows`` than the killed run (per-query searches are
    row-independent, so the output stays bitwise identical)."""
    start_row = 0
    if resume and checkpoint_dir:
        # manifest-only peek (the blob is re-read once, fingerprinted,
        # inside search_stream); validating the fingerprint HERE keeps a
        # stale checkpoint from steering start_row before the mismatch
        # would surface downstream
        state = resilience.StreamCheckpoint(checkpoint_dir).peek(
            fingerprint={"n_queries": int(queries.shape[0]), "k": int(k),
                         "stage": "search"},
        )
        if state is not None:
            start_row = int(state[2]["rows_done"])
    it = BatchLoadIterator(
        queries, _clamped_batch_rows(batch_rows), pad_to_full=True,
        start_row=start_row,
    )

    def fn(batch):
        return module.search(search_params, index, batch, k,
                             **search_kwargs)

    with obs.span("stream.search_host_array",
                  n_queries=int(queries.shape[0]), k=int(k)):
        return search_stream(
            fn, it, queries.shape[0], k,
            retries=retries, backoff_s=backoff_s, deadline_s=deadline_s,
            checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
            resume=resume, token=token, pipeline_depth=pipeline_depth,
        )
