"""Out-of-core (host/file-resident) search orchestration.

Analog of the reference's ``batch_load_iterator``-driven paths
(cpp/include/raft/spatial/knn/detail/ann_utils.cuh:397; the ANN bench
harness mmaps datasets, cpp/bench/ann/src/common/dataset.hpp:45-128):
queries stream host→device in double-buffered batches (the native
prefetcher keeps disk IO ahead of the transfers for file sources), each
batch runs the regular device search, and results land in preallocated
host arrays. The device only ever holds one query batch + the index.
"""

from __future__ import annotations

from typing import Callable, Iterable, Tuple

import numpy as np

from raft_tpu.utils.batch import BatchLoadIterator, FileBatchLoadIterator


def search_stream(
    search_fn: Callable,
    batches: Iterable[Tuple[int, "object"]],
    n_queries: int,
    k: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Run ``search_fn(query_batch) -> (dists, ids)`` over an iterator of
    ``(offset, device_batch)`` pairs (``BatchLoadIterator`` /
    ``FileBatchLoadIterator``), assembling host result arrays.

    Batches may be zero-padded to a fixed shape (``pad_to_full=True`` —
    one compiled program for every batch); rows beyond ``n_queries`` are
    dropped.
    """
    out_d = np.empty((n_queries, k), np.float32)
    out_i = np.empty((n_queries, k), np.int32)
    for offset, batch in batches:
        d, i = search_fn(batch)
        rows = min(batch.shape[0], n_queries - offset)
        out_d[offset:offset + rows] = np.asarray(d[:rows], np.float32)
        out_i[offset:offset + rows] = np.asarray(i[:rows])
    return out_d, out_i


def search_file(
    module,
    search_params,
    index,
    queries_path: str,
    k: int,
    batch_rows: int = 8192,
    **search_kwargs,
) -> Tuple[np.ndarray, np.ndarray]:
    """Stream a ``.fbin``-family query file through ``module.search``
    (ivf_flat / ivf_pq / cagra / brute_force-style modules) in fixed-size
    device batches. The file never materializes on the host in full."""
    it = FileBatchLoadIterator(queries_path, batch_rows, pad_to_full=True)

    def fn(batch):
        return module.search(search_params, index, batch, k,
                             **search_kwargs)

    return search_stream(fn, it, it.shape[0], k)


def search_host_array(
    module,
    search_params,
    index,
    queries: np.ndarray,
    k: int,
    batch_rows: int = 8192,
    **search_kwargs,
) -> Tuple[np.ndarray, np.ndarray]:
    """Same streaming pattern over a host-resident array (numpy or
    ``np.memmap``) — the double-buffered ``BatchLoadIterator`` overlaps
    host→device copies with the previous batch's search."""
    it = BatchLoadIterator(queries, batch_rows, pad_to_full=True)

    def fn(batch):
        return module.search(search_params, index, batch, k,
                             **search_kwargs)

    return search_stream(fn, it, queries.shape[0], k)
