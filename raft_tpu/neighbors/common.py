"""Shared ANN scaffolding: param structs, sample filters, search utilities.

Analog of the reference's neighbors common layer (SURVEY.md §2.9):
ann_types.hpp (index_params/search_params bases),
sample_filter_types.hpp (none/bitset filters), and the top-k merge used by
multi-part searches (detail/knn_merge_parts.cuh).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.bitset import Bitset
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric


@dataclasses.dataclass
class IndexParams:
    """Base build params (reference neighbors/ann_types.hpp:32-46)."""

    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    add_data_on_build: bool = True

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)


@dataclasses.dataclass
class SearchParams:
    """Base search params (reference neighbors/ann_types.hpp:48)."""


# --------------------------------------------------------------------------
# Sample filters (reference sample_filter_types.hpp)
# --------------------------------------------------------------------------


class NoneSampleFilter:
    """Accept everything (reference none_ivf_sample_filter:27)."""

    def mask(self, sample_ids: jax.Array) -> jax.Array:
        return jnp.ones(sample_ids.shape, jnp.bool_)


class BitsetFilter:
    """Keep samples whose bit is set (reference bitset_filter)."""

    def __init__(self, bitset: Bitset):
        self.bitset = bitset

    def mask(self, sample_ids: jax.Array) -> jax.Array:
        safe = jnp.clip(sample_ids, 0, self.bitset.n_bits - 1)
        ok = Bitset.test_bits(self.bitset.bits, safe)
        return ok & (sample_ids >= 0) & (sample_ids < self.bitset.n_bits)


def as_filter(f) -> NoneSampleFilter | BitsetFilter:
    if f is None:
        return NoneSampleFilter()
    if isinstance(f, Bitset):
        return BitsetFilter(f)
    return f


def filter_keep(filter_bits, filter_nbits: int, sample_ids):
    """Jit-safe keep-mask for a raw bitset: True where the sample id is in
    range and its bit is set. The single implementation behind BitsetFilter
    and the IVF scan kernels."""
    import jax.numpy as _jnp

    safe = _jnp.clip(sample_ids, 0, filter_nbits - 1)
    return (
        Bitset.test_bits(filter_bits, safe)
        & (sample_ids >= 0)
        & (sample_ids < filter_nbits)
    )


# --------------------------------------------------------------------------
# Sentinels and top-k merge
# --------------------------------------------------------------------------


def sentinel_for(metric: DistanceType, dtype=jnp.float32):
    """Worst-possible distance for masking invalid candidates."""
    return jnp.asarray(jnp.inf if is_min_close(metric) else -jnp.inf, dtype)


def merge_topk(
    dists: jax.Array,
    idxs: jax.Array,
    k: int,
    select_min: bool = True,
    approx: bool = False,
    recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Merge candidate lists along the last axis into a top-k.

    ``dists``/``idxs``: [..., c] with c >= k. Returns ([..., k], [..., k])
    sorted best-first. This is the XLA analog of the reference's warp-queue
    ``knn_merge_parts`` merge kernel (detail/knn_merge_parts.cuh:33,140).

    ``approx=True`` uses the TPU-optimized ``lax.approx_min_k`` /
    ``approx_max_k`` (the TPU-KNN partial-reduce op) — dramatically faster
    than a full sort for k << c, at a configurable ``recall_target``. Use it
    for inner candidate-generation stages whose output feeds an exact merge.

    The exact arm routes through ``matrix.select_k``, so large-k merges
    (k > 256, c >> k — CAGRA-build candidate selection, cross-probe
    merges at high refine ratios) can dispatch to the compacting
    tournament instead of ``lax.top_k``'s full-row sort (the reference
    serves this regime with radix select,
    matrix/detail/select_radix.cuh:231). The arm is picked from the
    per-backend dispatch table under the dedicated ``merge_topk`` op key
    (merge pools are wider-batch / shorter-row than raw selects, so they
    get their own measured crossover); a table miss defers to
    ``select_k``'s own dispatch. Tournament rows with fewer than k
    finite entries return id -1 — the library-wide no-neighbor
    convention callers already mask on.
    """
    if approx and k < dists.shape[-1]:
        fn = jax.lax.approx_min_k if select_min else jax.lax.approx_max_k
        vals, sel = fn(dists, k, recall_target=recall_target)
        return vals, jnp.take_along_axis(idxs, sel, axis=-1)
    from raft_tpu import obs
    from raft_tpu.matrix.select_k import dispatch_select_impl, select_k

    shape = dists.shape
    reshaped = dists.ndim != 2
    if reshaped:
        dists = dists.reshape(-1, shape[-1])
        idxs = idxs.reshape(-1, shape[-1])
    impl = dispatch_select_impl(
        int(dists.shape[0]), int(dists.shape[-1]), int(k), dists.dtype,
        op="merge_topk",
        fallback="auto",  # miss -> select_k's own (table-driven) dispatch
    )
    # trace-time span (merge_topk runs under the callers' jits): compile
    # attribution per chosen arm, silent on cached steady-state dispatch
    with obs.span("merge_topk", impl=impl, c=int(dists.shape[-1]),
                  k=int(k)):
        vals, out_i = select_k(dists, k, in_idx=idxs,
                               select_min=select_min, impl=impl)
    if reshaped:
        vals = vals.reshape(*shape[:-1], k)
        out_i = out_i.reshape(*shape[:-1], k)
    return vals, out_i


def knn_merge_parts(
    part_dists: jax.Array,
    part_idxs: jax.Array,
    k: Optional[int] = None,
    select_min: bool = True,
    translations=None,
) -> Tuple[jax.Array, jax.Array]:
    """Merge per-part KNN results into a global top-k.

    ``part_dists``/``part_idxs``: [n_parts, n_queries, k_part]. Optional
    ``translations`` [n_parts] are added to each part's indices (the
    reference uses them to offset shard-local ids —
    detail/knn_merge_parts.cuh:140).
    """
    n_parts, n_q, k_part = part_dists.shape
    k = k if k is not None else k_part
    if translations is not None:
        t = jnp.asarray(translations).reshape(n_parts, 1, 1)
        part_idxs = part_idxs + t.astype(part_idxs.dtype)
    flat_d = jnp.transpose(part_dists, (1, 0, 2)).reshape(n_q, n_parts * k_part)
    flat_i = jnp.transpose(part_idxs, (1, 0, 2)).reshape(n_q, n_parts * k_part)
    return merge_topk(flat_d, flat_i, k, select_min)
