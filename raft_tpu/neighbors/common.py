"""Shared ANN scaffolding: param structs, sample filters, search utilities.

Analog of the reference's neighbors common layer (SURVEY.md §2.9):
ann_types.hpp (index_params/search_params bases),
sample_filter_types.hpp (none/bitset filters), and the top-k merge used by
multi-part searches (detail/knn_merge_parts.cuh).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.bitset import Bitset
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric


@dataclasses.dataclass
class IndexParams:
    """Base build params (reference neighbors/ann_types.hpp:32-46)."""

    metric: DistanceType = DistanceType.L2Expanded
    metric_arg: float = 2.0
    add_data_on_build: bool = True

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)


@dataclasses.dataclass
class SearchParams:
    """Base search params (reference neighbors/ann_types.hpp:48)."""


# --------------------------------------------------------------------------
# Sample filters (reference sample_filter_types.hpp)
# --------------------------------------------------------------------------


class NoneSampleFilter:
    """Accept everything (reference none_ivf_sample_filter:27)."""

    def mask(self, sample_ids: jax.Array) -> jax.Array:
        return jnp.ones(sample_ids.shape, jnp.bool_)


#: valid ``out_of_range`` modes for bitset filters (docs/serving.md §5):
#: ``"drop"`` — a sample id beyond the filter's n_bits is rejected (the
#: historical behavior; right for allow-lists, where absence means
#: not-allowed); ``"keep"`` — an out-of-range id is accepted (right for
#: tombstone/deny-derived keep-masks over an index that was *extended*
#: after the filter was built: new rows were never deleted, so they
#: must default to kept).
OUT_OF_RANGE_MODES = ("drop", "keep")


class BitsetFilter:
    """Keep samples whose bit is set (reference bitset_filter).

    ``out_of_range`` picks the fate of sample ids ``>= bitset.n_bits``
    (see :data:`OUT_OF_RANGE_MODES`). Negative ids (the library-wide
    no-neighbor padding) are always rejected in either mode.
    """

    def __init__(self, bitset: Bitset, out_of_range: str = "drop"):
        if out_of_range not in OUT_OF_RANGE_MODES:
            raise ValueError(
                f"out_of_range must be one of {OUT_OF_RANGE_MODES}, "
                f"got {out_of_range!r}"
            )
        self.bitset = bitset
        self.out_of_range = out_of_range

    def mask(self, sample_ids: jax.Array) -> jax.Array:
        return filter_keep(self.bitset.bits, self.bitset.n_bits,
                           sample_ids, out_of_range=self.out_of_range)


def as_filter(f) -> NoneSampleFilter | BitsetFilter:
    if f is None:
        return NoneSampleFilter()
    if isinstance(f, Bitset):
        return BitsetFilter(f)
    return f


def filter_keep(filter_bits, filter_nbits: int, sample_ids,
                out_of_range: str = "drop"):
    """Jit-safe keep-mask for a raw bitset: True where the sample id's bit
    is set. The single implementation behind BitsetFilter and the IVF scan
    kernels. ``out_of_range`` (static) decides ids ``>= filter_nbits``:
    ``"drop"`` rejects them (allow-list semantics), ``"keep"`` accepts
    them (tombstone semantics over an extended index — new rows were
    never deleted). Negative ids are always rejected."""
    import jax.numpy as _jnp

    safe = _jnp.clip(sample_ids, 0, filter_nbits - 1)
    tested = Bitset.test_bits(filter_bits, safe)
    in_range = sample_ids < filter_nbits
    if out_of_range == "keep":
        tested = tested | ~in_range
        return tested & (sample_ids >= 0)
    return tested & in_range & (sample_ids >= 0)


def resolve_filter_bits(filt, id_bound):
    """Resolve a filter's bitset against an index whose valid ids live in
    ``[0, id_bound)``, honoring its ``out_of_range`` mode for kernels
    that only implement "drop".

    Returns the :class:`~raft_tpu.core.bitset.Bitset` to hand to a scan
    kernel, or ``None`` for an unfiltered search. A ``"keep"``-mode
    filter narrower than ``id_bound`` is *materialized*: resized (on a
    copy) with new bits set, so drop-semantics kernels behave as keep
    without threading another static through every scan. Only meaningful
    when ids are the default contiguous row ids (true for every build in
    this repo unless the caller passed custom ``new_ids`` to extend).

    The materialized bitset is cached on the filter object keyed by
    ``(id_bound, Bitset._version)``, so N filtered searches with one
    filter pay the resize's device ops (copy + pad + set) once, not N
    times; an in-place mutation of the underlying bitset bumps
    ``_version`` and invalidates the entry (the same keying the serve
    engine uses for its composed tombstone filters).

    ``id_bound`` may be a callable evaluated only for "keep"-mode
    filters: ``Index.size`` is a device reduction, and forcing it to a
    Python int on the no-filter/drop path would concretize a tracer when
    the search entry runs under an outer ``jit`` (the GL002 hazard the
    jaxpr audit traces for).
    """
    bits = getattr(filt, "bitset", None)
    if bits is None:
        return None
    if getattr(filt, "out_of_range", "drop") != "keep":
        return bits
    bound = int(id_bound() if callable(id_bound) else id_bound)
    if bits.n_bits >= bound:
        return bits
    key = (bound, getattr(bits, "_version", 0))
    cached = getattr(filt, "_materialized_keep", None)
    if cached is not None and cached[0] == key:
        return cached[1]
    resized = bits.copy().resize(bound, default=True)
    try:
        filt._materialized_keep = (key, resized)
    except AttributeError:      # slotted/frozen filter: serve correct,
        pass                    # just uncached
    return resized


# --------------------------------------------------------------------------
# Sentinels and top-k merge
# --------------------------------------------------------------------------


def sentinel_for(metric: DistanceType, dtype=jnp.float32):
    """Worst-possible distance for masking invalid candidates."""
    return jnp.asarray(jnp.inf if is_min_close(metric) else -jnp.inf, dtype)


def merge_topk(
    dists: jax.Array,
    idxs: jax.Array,
    k: int,
    select_min: bool = True,
    approx: bool = False,
    recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Merge candidate lists along the last axis into a top-k.

    ``dists``/``idxs``: [..., c] with c >= k. Returns ([..., k], [..., k])
    sorted best-first. This is the XLA analog of the reference's warp-queue
    ``knn_merge_parts`` merge kernel (detail/knn_merge_parts.cuh:33,140).

    ``approx=True`` uses the TPU-optimized ``lax.approx_min_k`` /
    ``approx_max_k`` (the TPU-KNN partial-reduce op) — dramatically faster
    than a full sort for k << c, at a configurable ``recall_target``. Use it
    for inner candidate-generation stages whose output feeds an exact merge.

    The exact arm routes through ``matrix.select_k``, so large-k merges
    (k > 256, c >> k — CAGRA-build candidate selection, cross-probe
    merges at high refine ratios) can dispatch to the compacting
    tournament instead of ``lax.top_k``'s full-row sort (the reference
    serves this regime with radix select,
    matrix/detail/select_radix.cuh:231). The arm is picked from the
    per-backend dispatch table under the dedicated ``merge_topk`` op key
    (merge pools are wider-batch / shorter-row than raw selects, so they
    get their own measured crossover); a table miss defers to
    ``select_k``'s own dispatch. Tournament rows with fewer than k
    finite entries return id -1 — the library-wide no-neighbor
    convention callers already mask on.
    """
    if approx and k < dists.shape[-1]:
        fn = jax.lax.approx_min_k if select_min else jax.lax.approx_max_k
        vals, sel = fn(dists, k, recall_target=recall_target)
        return vals, jnp.take_along_axis(idxs, sel, axis=-1)
    from raft_tpu import obs
    from raft_tpu.matrix.select_k import dispatch_select_impl, select_k

    shape = dists.shape
    reshaped = dists.ndim != 2
    if reshaped:
        dists = dists.reshape(-1, shape[-1])
        idxs = idxs.reshape(-1, shape[-1])
    impl = dispatch_select_impl(
        int(dists.shape[0]), int(dists.shape[-1]), int(k), dists.dtype,
        op="merge_topk",
        fallback="auto",  # miss -> select_k's own (table-driven) dispatch
    )
    # trace-time span (merge_topk runs under the callers' jits): compile
    # attribution per chosen arm, silent on cached steady-state dispatch
    with obs.span("merge_topk", impl=impl, c=int(dists.shape[-1]),
                  k=int(k)):
        vals, out_i = select_k(dists, k, in_idx=idxs,
                               select_min=select_min, impl=impl)
    if reshaped:
        vals = vals.reshape(*shape[:-1], k)
        out_i = out_i.reshape(*shape[:-1], k)
    return vals, out_i


def knn_merge_parts(
    part_dists: jax.Array,
    part_idxs: jax.Array,
    k: Optional[int] = None,
    select_min: bool = True,
    translations=None,
) -> Tuple[jax.Array, jax.Array]:
    """Merge per-part KNN results into a global top-k.

    ``part_dists``/``part_idxs``: [n_parts, n_queries, k_part]. Optional
    ``translations`` [n_parts] are added to each part's indices (the
    reference uses them to offset shard-local ids —
    detail/knn_merge_parts.cuh:140).
    """
    n_parts, n_q, k_part = part_dists.shape
    k = k if k is not None else k_part
    if translations is not None:
        t = jnp.asarray(translations).reshape(n_parts, 1, 1)
        part_idxs = part_idxs + t.astype(part_idxs.dtype)
    flat_d = jnp.transpose(part_dists, (1, 0, 2)).reshape(n_q, n_parts * k_part)
    flat_i = jnp.transpose(part_idxs, (1, 0, 2)).reshape(n_q, n_parts * k_part)
    return merge_topk(flat_d, flat_i, k, select_min)
