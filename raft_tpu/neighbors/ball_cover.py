"""Random ball cover — *exact* KNN via landmarks + triangle-inequality
pruning (reference neighbors/ball_cover.cuh: BallCoverIndex,
build_index, knn_query, all_knn_query, eps_nn; impl
spatial/knn/detail/ball_cover.cuh + ball_cover/registers.cuh).

Algorithm (same maths as the reference's rbc):
  build: C ≈ √n landmarks (balanced kmeans), every point stored in its
  nearest landmark's list; per-list radius = max point↔landmark distance.
  search: with true-metric distances, list i can contain a better-than-kth
  neighbor only if d(q, cᵢ) − radiusᵢ < kth. Phase 1 scans the p₀
  closest lists to bound kth; phase 2 scans exactly the per-query prefix
  of the lb-sorted list order where lb < kth — everything outside is
  *provably* prunable, so the result is exact.

TPU design: the reference's per-thread register-tiled pruning loop
becomes two batched phases — an [m, C] landmark GEMM, then a
``lax.scan`` over probe positions that gathers one [m, cap, d] list
block per step and folds it into a running top-k (no per-point
branching: pruning happens at list granularity, which is where the
batched-bound math is MXU-shaped). Probe counts are data-dependent, so
the certification loop doubles the probe prefix on the host (≤ log C
rounds) until every query's remaining lower bounds clear its kth — the
same adaptive widening the IVF search uses for recall targets, but with
an exactness certificate instead of a heuristic.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.distance.types import DistanceType, resolve_metric
from raft_tpu.distance.pairwise import pairwise_distance
from raft_tpu.neighbors.ivf_flat import _aligned_cap, _pack_lists
from raft_tpu.utils.precision import dist_dot

_SUPPORTED = {
    DistanceType.L2SqrtExpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.Haversine,
}


@dataclasses.dataclass
class BallCoverIndex:
    """reference ball_cover_types.hpp BallCoverIndex."""

    landmarks: jax.Array     # [C, d] f32
    storage: jax.Array       # [C, cap, d]
    indices: jax.Array       # [C, cap] i32, -1 pad
    list_sizes: jax.Array    # [C] i32
    radii: jax.Array         # [C] f32 — max member distance per landmark
    metric: DistanceType

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]

    @property
    def dim(self) -> int:
        return self.landmarks.shape[1]

    @property
    def size(self) -> int:
        return int(self.list_sizes.sum())


jax.tree_util.register_dataclass(
    BallCoverIndex,
    data_fields=["landmarks", "storage", "indices", "list_sizes", "radii"],
    meta_fields=["metric"],
)


def _true_metric(metric) -> DistanceType:
    metric = resolve_metric(metric)
    if metric == DistanceType.L2Expanded:
        metric = DistanceType.L2SqrtExpanded  # triangle inequality needs √
    if metric not in _SUPPORTED:
        raise ValueError(
            f"ball_cover needs a true metric (euclidean/haversine), got {metric}"
        )
    return metric


def build(
    dataset, metric="euclidean", n_landmarks: Optional[int] = None, seed: int = 0
) -> BallCoverIndex:
    """Build the ball cover (reference ball_cover.cuh:56 build_index;
    landmark count defaults to √n as in ball_cover_types.hpp)."""
    from raft_tpu import obs
    from raft_tpu.cluster import kmeans_balanced

    metric = _true_metric(metric)
    dataset = jnp.asarray(dataset, jnp.float32)
    n, d = dataset.shape
    C = int(n_landmarks or max(1, int(math.sqrt(n))))

    with obs.entry_span("build", "ball_cover", rows=n, landmarks=C):
        # L2 kmeans for every metric — for Haversine, kmeans in lat/lon
        # radians approximates well for local extents, and landmark
        # geometry only affects pruning efficiency, not exactness
        landmarks = kmeans_balanced.build_hierarchical(
            dataset, C, metric=DistanceType.L2Expanded, seed=seed
        )
        d_pl = pairwise_distance(dataset, landmarks, metric)  # [n, C] true
        labels = jnp.argmin(d_pl, axis=1).astype(jnp.int32)
        dist_to_lm = jnp.min(d_pl, axis=1)

        # graft-lint: allow-host-sync build list capacity must be concrete to allocate
        counts = np.asarray(jnp.bincount(labels, length=C))
        cap = _aligned_cap(int(counts.max()) if n else 1)
        storage, indices, list_sizes = _pack_lists(
            dataset, labels, jnp.arange(n, dtype=jnp.int32), C, cap
        )
        radii = jnp.zeros((C,), jnp.float32).at[labels].max(dist_to_lm)
        return BallCoverIndex(landmarks, storage, indices, list_sizes,
                              radii, metric)


@functools.partial(jax.jit, static_argnums=(5, 6))
def _scan_lists(
    queries, storage, indices, probe_lists, init, k: int, metric_val: int
):
    """Fold the per-query probe lists into a running top-k.

    queries [m, d]; probe_lists [m, P]; init (dists [m, k], ids [m, k])
    carried from a previous phase (±inf/-1 for a fresh start).
    """
    metric = DistanceType(metric_val)
    m, d = queries.shape
    cap = storage.shape[1]

    def step(carry, p):
        top_d, top_i = carry
        lists = probe_lists[:, p]                      # [m]
        block = storage[lists]                         # [m, cap, d]
        ids = indices[lists]                           # [m, cap]
        if metric == DistanceType.Haversine:
            lat1, lon1 = queries[:, 0:1], queries[:, 1:2]
            lat2, lon2 = block[..., 0], block[..., 1]
            sdlat = jnp.sin(0.5 * (lat1 - lat2))
            sdlon = jnp.sin(0.5 * (lon1 - lon2))
            a = sdlat**2 + jnp.cos(lat1) * jnp.cos(lat2) * sdlon**2
            dist = 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
        else:
            # batched L2: ||q||² − 2 q·x + ||x||², einsum rides the MXU
            qn = jnp.sum(queries * queries, axis=1, keepdims=True)
            xn = jnp.sum(block * block, axis=2)
            qx = jnp.einsum(
                "md,mcd->mc", queries, block,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            dist = jnp.sqrt(jnp.maximum(qn - 2.0 * qx + xn, 0.0))
        dist = jnp.where(ids >= 0, dist, jnp.inf)      # mask list padding
        # de-dup vs already-kept ids (lists can repeat across phases)
        seen = jnp.any(ids[:, :, None] == top_i[:, None, :], axis=2)
        dist = jnp.where(seen, jnp.inf, dist)
        cat_d = jnp.concatenate([top_d, dist], axis=1)
        cat_i = jnp.concatenate([top_i, ids], axis=1)
        nd, sel = jax.lax.top_k(-cat_d, k)
        return (-nd, jnp.take_along_axis(cat_i, sel, axis=1)), None

    (top_d, top_i), _ = jax.lax.scan(
        step, init, jnp.arange(probe_lists.shape[1])
    )
    return top_d, top_i


def knn_query(
    index: BallCoverIndex,
    queries,
    k: int,
    query_block: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """Exact KNN (reference ball_cover.cuh:150 knn_query). Certified by the
    triangle inequality — results match brute force bit-for-bit up to ties."""
    queries = jnp.asarray(queries, jnp.float32)
    m = queries.shape[0]
    out = [
        _knn_block(index, queries[r0 : min(r0 + query_block, m)], k)
        for r0 in range(0, m, query_block)
    ]
    return (
        jnp.concatenate([o[0] for o in out]),
        jnp.concatenate([o[1] for o in out]),
    )


def _knn_block(index: BallCoverIndex, queries, k: int):
    C = index.n_landmarks
    m = queries.shape[0]
    dql = pairwise_distance(queries, index.landmarks, index.metric)  # [m, C]
    lb = jnp.maximum(dql - index.radii[None, :], 0.0)
    order = jnp.argsort(lb, axis=1).astype(jnp.int32)                # [m, C]
    lb_sorted = jnp.take_along_axis(lb, order, axis=1)

    k_eff = min(k, max(index.size, 1))
    init = (
        jnp.full((m, k), jnp.inf, jnp.float32),
        jnp.full((m, k), -1, jnp.int32),
    )
    p0 = min(C, max(2, int(math.ceil(math.sqrt(C)))))
    scanned = 0
    top_d, top_i = init
    while scanned < C:
        p1 = min(C, max(p0, 2 * scanned))
        top_d, top_i = _scan_lists(
            queries, index.storage, index.indices,
            order[:, scanned:p1], (top_d, top_i), k, int(index.metric),
        )
        scanned = p1
        if scanned >= C:
            break
        kth = top_d[:, k_eff - 1]
        # certified once no remaining list can beat the kth distance
        # graft-lint: allow-host-sync host-driven certification loop is the algorithm (<= log C syncs)
        need_more = bool(jnp.any(lb_sorted[:, scanned] < kth))
        if not need_more:
            break
    return top_d, top_i


def _reconstruct_dataset(index: BallCoverIndex) -> jax.Array:
    """Stored rows back in source-id order, entirely ON DEVICE: one
    scatter instead of the former numpy round trip (GL001 flagged the
    ``np.asarray`` pair on this query path — two full-index host
    transfers per call)."""
    n = index.size
    flat_i = index.indices.reshape(-1)
    rows = index.storage.reshape(-1, index.dim)
    # padding slots target row n, which mode="drop" discards
    tgt = jnp.where(flat_i >= 0, flat_i, n)
    return jnp.zeros((n, index.dim), rows.dtype).at[tgt].set(rows, mode="drop")


def all_knn_query(
    index: BallCoverIndex, k: int, query_block: int = 4096
) -> Tuple[jax.Array, jax.Array]:
    """Self-KNN over the indexed dataset (ball_cover.cuh:100
    all_knn_query): queries are the stored points in id order."""
    return knn_query(index, _reconstruct_dataset(index), k, query_block)


def eps_nn(
    index: BallCoverIndex, queries, eps: float, query_block: int = 4096
) -> Tuple[jax.Array, jax.Array]:
    """Epsilon neighborhood via the ball cover (ball_cover.cuh:219 eps_nn):
    returns (adj [m, n] bool, vertex degrees [m]).

    List-level pruning bounds the work, then exact distances fill a dense
    adjacency (the reference writes a dense boolean adjacency too).
    """
    from raft_tpu.neighbors.epsilon_neighborhood import eps_neighbors

    queries = jnp.asarray(queries, jnp.float32)
    return eps_neighbors(queries, _reconstruct_dataset(index), eps, index.metric)
