"""CAGRA: graph-based ANN (build: pruned KNN graph; search: beam search).

TPU-native analog of the reference's cagra
(cpp/include/raft/neighbors/cagra.cuh; types cagra_types.hpp:47-175; build
detail/cagra/cagra_build.cuh:43; optimize detail/cagra/graph_core.cuh:128,
320; search detail/cagra/search_single_cta_kernel-inl.cuh:585).

Design — idiomatic TPU, not a port:

* **Graph build** follows the reference pipeline: IVF-PQ index on the
  dataset, batched self-search for ``intermediate_graph_degree`` raw
  neighbors (cagra_build.cuh:103-155), exact ``refine`` re-rank, then
  ``optimize``. An ``nn_descent`` builder is available as the alternative
  (build_algo, cagra_types.hpp:47).

* **optimize** keeps the reference's exact semantics (graph_core.cuh
  comment at :360): the detour count of edge A->B at rank k is the number
  of shorter edges A->D with B in D's adjacency list; edges are kept by
  ascending detour count (rank-stable), then reverse edges are spliced in
  after ``degree/2`` protected slots. The per-node CUDA block + warp
  bitonic becomes a vectorized sort + searchsorted membership test,
  scanned over node chunks — no atomics, one compiled program.

* **search** is the single-CTA beam search re-shaped for SPMD batching:
  every query carries a fixed-size itopk buffer of (distance, id,
  explored) and all queries advance in lockstep inside one
  ``lax.fori_loop`` — parent pickup (best unexplored), neighbor
  expansion (graph gather), distance scoring (batched matvec epilogue on
  MXU), merge + dedup. The reference's visited hash table
  (hashmap.hpp:41) is replaced by sort-based dedup against the itopk
  buffer: revisited ids collapse to one entry whose explored flag is
  kept, so no node is expanded twice — same invariant, no hashing.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.serialize import read_index_file, write_index_file
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.utils.precision import dist_dot

_SERIAL_VERSION = 1


class build_algo:
    """Graph build algorithm (reference cagra_types.hpp:47)."""

    IVF_PQ = 0
    NN_DESCENT = 1


@dataclasses.dataclass
class IndexParams:
    """Build params (reference cagra_types.hpp:47-63)."""

    intermediate_graph_degree: int = 64
    graph_degree: int = 32
    metric: DistanceType = DistanceType.L2Expanded
    graph_build_algo: int = build_algo.IVF_PQ
    add_data_on_build: bool = True  # API parity; dataset always attached

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.L2Unexpanded,
            DistanceType.InnerProduct,
        ):
            raise ValueError(f"cagra supports L2/IP metrics, got {self.metric!r}")
        if self.graph_degree > self.intermediate_graph_degree:
            raise ValueError("graph_degree must be <= intermediate_graph_degree")


@dataclasses.dataclass
class SearchParams:
    """Search params (reference cagra_types.hpp:65-117)."""

    itopk_size: int = 64
    search_width: int = 4          # parents expanded per iteration
    max_iterations: int = 0        # 0 -> auto
    # scoring gather dtype; measured on v5e: bf16 saves nothing (the
    # gather is row-latency-bound, not byte-bound) and costs ~2.5pt
    # recall, so exact f32 is the default
    compute_dtype: str = "f32"
    # reference knobs kept for API parity; the batched-SPMD kernel has no
    # CTA/team/hashmap notion (documented no-ops)
    algo: str = "auto"
    team_size: int = 0
    hashmap_min_bitlen: int = 0
    num_random_samplings: int = 1
    rand_xor_mask: int = 0x128394


@dataclasses.dataclass
class Index:
    """CAGRA index = dataset + fixed-degree graph (cagra_types.hpp:133)."""

    dataset: jax.Array      # [n, d]
    graph: jax.Array        # [n, degree] int32
    metric: DistanceType
    data_norms: Optional[jax.Array] = None  # [n] f32 (L2 metrics)

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]


jax.tree_util.register_dataclass(
    Index,
    data_fields=["dataset", "graph", "data_norms"],
    meta_fields=["metric"],
)


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_knn_graph(
    dataset,
    intermediate_degree: int,
    metric: DistanceType,
    refine_rate: float = 2.0,
    query_batch: int = 16384,
) -> jax.Array:
    """Raw KNN graph via IVF-PQ self-search + exact refine (reference
    detail/cagra/cagra_build.cuh:43; params heuristic :60-68; batch loop
    :103-155). Returns [n, intermediate_degree] int32 (self excluded)."""
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.refine import refine

    dataset = jnp.asarray(dataset)
    n, d = dataset.shape
    k = int(intermediate_degree) + 1          # +1: drop self afterwards
    gpu_top_k = min(n, max(k, int(k * refine_rate)))

    # reference heuristic: n_lists ~ n/2500, pq_dim ~ d/2 rounded up
    n_lists = int(np.clip(n // 2500, 16, 1024))
    pq_dim = max(8, ((d // 2) + 7) // 8 * 8)
    params = ivf_pq.IndexParams(
        n_lists=n_lists,
        pq_dim=min(pq_dim, d),
        metric=(
            DistanceType.InnerProduct
            if metric == DistanceType.InnerProduct
            else DistanceType.L2Expanded
        ),
        kmeans_n_iters=10,
        # full-dataset coarse training measured FASTER end-to-end than a
        # 256-rows/list subsample at n=1M (359 s vs 499 s): better
        # centers -> tighter list balance -> smaller cap -> faster
        # self-search batches, outweighing the kmeans savings
        kmeans_trainset_fraction=min(1.0, max(0.1, 10000.0 * n_lists / n)),
    )
    index = ivf_pq.build(params, dataset)
    sp = ivf_pq.SearchParams(
        n_probes=min(n_lists, max(10, n_lists // 10)),
    )

    rows = []
    for start in range(0, n, query_batch):
        q = dataset[start:start + query_batch]
        _, cand = ivf_pq.search(sp, index, q, gpu_top_k)
        if gpu_top_k > k:
            _, cand = refine(dataset, q, cand, k, metric)
        rows.append(cand)
    graph = jnp.concatenate(rows, axis=0)     # [n, k]

    # drop self-edges: usually in slot 0; fall back to dropping the last
    self_col = graph == jnp.arange(n, dtype=graph.dtype)[:, None]
    # stable push of self (or worst candidate) to the end, then cut
    order = jnp.argsort(self_col.astype(jnp.int32), axis=1, stable=True)
    graph = jnp.take_along_axis(graph, order, axis=1)[:, : int(intermediate_degree)]
    return graph.astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _detour_counts_block(graph, start, rows: int, chunk: int):
    """Detour counts for node range [start, start+rows) (reference
    kern_prune, graph_core.cuh:128).

    For node A with rank-sorted neighbors N: count[kAB] = #{kAD < kAB :
    N[kAB] in graph[N[kAD]]}. Membership is a vectorized D³ equality
    compare per chunk (the VPU chews through it; a binary search lowers
    to a serial gather loop on TPU and is ~100x slower)."""
    n, D = graph.shape
    gb = jax.lax.dynamic_slice(graph, (start, 0), (rows, D))
    tri = jnp.arange(D)[:, None] < jnp.arange(D)[None, :]  # kAD < kAB

    def one_chunk(_, g_chunk):                # [chunk, D]
        nbrs = graph[g_chunk]                 # [chunk, D, D] two-hop lists
        # found[c, kAD, kAB] = N[kAB] ∈ graph[N[kAD]]
        found = jnp.any(
            nbrs[:, :, :, None] == g_chunk[:, None, None, :], axis=2
        )
        counts = jnp.sum(found & tri[None, :, :], axis=1)  # [chunk, D]
        return None, counts.astype(jnp.int32)

    npad = -(-rows // chunk) * chunk
    gp = jnp.pad(gb, ((0, npad - rows), (0, 0)))
    _, counts = jax.lax.scan(
        one_chunk, None, gp.reshape(npad // chunk, chunk, D)
    )
    return counts.reshape(npad, D)[:rows]


def _detour_counts(graph, chunk: int, nodes_per_call: int = 1 << 16):
    """Host-blocked detour counts: one device dispatch per
    ``nodes_per_call`` node range. A single program covering a large graph
    runs minutes on-device, which trips the remote platform's execution
    watchdog (observed: programs > ~2 min kill the TPU worker) — and
    bounded dispatches also keep the scan transients small."""
    graph = jnp.asarray(graph)
    n, _ = graph.shape
    if n <= nodes_per_call:
        return _detour_counts_block(graph, jnp.int32(0), n, chunk)
    parts = [
        _detour_counts_block(
            graph, jnp.int32(s), min(nodes_per_call, n - s), chunk
        )
        for s in range(0, n, nodes_per_call)
    ]
    return jnp.concatenate(parts, axis=0)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _optimize_impl(graph, counts, degree: int, protected: int):
    n, D = graph.shape
    # 1. keep edges by ascending detour count, rank-stable
    #    (graph_core.cuh:424-441)
    key = counts * D + jnp.arange(D, dtype=jnp.int32)[None, :]
    order = jnp.argsort(key, axis=1)
    pruned = jnp.take_along_axis(graph, order[:, :degree], axis=1)

    # 2. reverse graph, capped at degree per node (kern_make_rev_graph)
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), degree)
    dst = pruned.reshape(-1)
    dst = jnp.where(dst >= 0, dst, n)          # drop invalid (OOB label)
    _, rev, rev_sizes = _pack_lists(
        jnp.zeros((n * degree, 1), jnp.int8), dst, src, n, degree
    )                                          # rev [n, degree] (-1 pad)

    # 3. splice reverse edges after the protected prefix
    #    (graph_core.cuh:520-546): final = protected originals, then
    #    reverse edges, then surviving unprotected originals — duplicates
    #    (vs the protected prefix or earlier candidates) dropped.
    #    Chunked over nodes: the [chunk, L, L] dedup masks are the peak
    #    transient (unchunked at n=300k they are ~3 GB each and OOM a v5e
    #    alongside the rest of the build's live buffers).
    prot = pruned[:, :protected]
    cand_full = jnp.concatenate([rev, pruned[:, protected:]], axis=1)  # [n, L]
    L = cand_full.shape[1]
    tri = (jnp.arange(L)[None, :] < jnp.arange(L)[:, None])[None, :, :]

    def splice_chunk(inp):
        cand, pr, tail = inp                               # [c, L], [c, P]
        dup_prot = jnp.any(cand[:, :, None] == pr[:, None, :], axis=2)
        dup_earlier = jnp.any(
            (cand[:, :, None] == cand[:, None, :]) & tri, axis=2
        )
        bad = dup_prot | dup_earlier | (cand < 0)
        # stable-compact the good candidates to the front
        rank = jnp.argsort(bad.astype(jnp.int32), axis=1, stable=True)
        kept = jnp.take_along_axis(cand, rank[:, : degree - protected], axis=1)
        # any remaining -1 (degenerate tiny graphs) falls back to originals
        return jnp.where(kept >= 0, kept, tail)

    chunk = 1 << 14
    tail_full = pruned[:, protected:]
    if n <= chunk:
        cand = splice_chunk((cand_full, prot, tail_full))
    else:
        npad = -(-n // chunk) * chunk
        pad = lambda a: jnp.pad(a, ((0, npad - n), (0, 0)))
        out = jax.lax.map(
            splice_chunk,
            (pad(cand_full).reshape(npad // chunk, chunk, L),
             pad(prot).reshape(npad // chunk, chunk, protected),
             pad(tail_full).reshape(npad // chunk, chunk, degree - protected)),
        )
        cand = out.reshape(npad, degree - protected)[:n]
    return jnp.concatenate([prot, cand], axis=1)


def optimize(graph, degree: int, chunk: int = 1024) -> jax.Array:
    """Prune a KNN graph to ``degree`` by 2-hop detour count + reverse-edge
    augmentation (reference graph_core.cuh:320 optimize)."""
    graph = jnp.asarray(graph).astype(jnp.int32)
    counts = _detour_counts(graph, int(chunk))
    protected = max(int(degree) // 2, 1)
    return _optimize_impl(graph, counts, int(degree), protected)


def build(params: IndexParams, dataset) -> Index:
    """Build the index (reference cagra.cuh:274 build)."""
    dataset = jnp.asarray(dataset)
    metric = params.metric
    if params.graph_build_algo == build_algo.NN_DESCENT:
        from raft_tpu.neighbors import nn_descent

        nd_params = nn_descent.IndexParams(
            graph_degree=int(params.intermediate_graph_degree), metric=metric
        )
        knn = nn_descent.build(nd_params, dataset).graph
    else:
        knn = build_knn_graph(
            dataset, int(params.intermediate_graph_degree), metric
        )
    graph = optimize(knn, int(params.graph_degree))
    norms = None
    if metric != DistanceType.InnerProduct:
        d32 = dataset.astype(jnp.float32)
        norms = jnp.sum(d32 * d32, axis=1)
    return Index(dataset=dataset, graph=graph, metric=metric,
                 data_norms=norms)


def from_graph(dataset, graph, metric=DistanceType.L2Expanded) -> Index:
    """Wrap a prebuilt graph (pylibraft cagra.Index from_graph analog)."""
    dataset = jnp.asarray(dataset)
    metric = resolve_metric(metric)
    norms = None
    if metric != DistanceType.InnerProduct:
        d32 = dataset.astype(jnp.float32)
        norms = jnp.sum(d32 * d32, axis=1)
    return Index(dataset=dataset, graph=jnp.asarray(graph, jnp.int32),
                 metric=metric, data_norms=norms)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7, 8, 9))
def _beam_search(
    queries,       # [m, d] f32
    dataset,       # [n, d]
    graph,         # [n, deg] int32
    data_norms,    # [n] f32 or None
    k: int,
    itopk: int,
    width: int,
    iters: int,
    metric_val: int,
    compute_dtype: str = "f32",
):
    if compute_dtype not in ("f32", "bf16"):
        raise ValueError(f"compute_dtype must be f32|bf16, got {compute_dtype!r}")
    metric = DistanceType(metric_val)
    ip = metric == DistanceType.InnerProduct
    n, d = dataset.shape
    deg = graph.shape[1]
    m = queries.shape[0]
    q32 = queries.astype(jnp.float32)
    # scoring dtype knob (the reference's fp16 dataset mode analog);
    # bf16 rounds the stored vectors, products still accumulate in f32
    mm = jnp.bfloat16 if compute_dtype == "bf16" else jnp.float32
    data = dataset.astype(mm)
    qmm = q32.astype(mm)

    def score(ids):                            # [m, c] -> [m, c] (min-close)
        vecs = data[ids]                       # [m, c, d] (mm dtype)
        dots = jnp.einsum(
            "md,mcd->mc", qmm, vecs,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if ip:
            return -dots
        return data_norms[ids] - 2.0 * dots    # ||q||^2 constant: dropped

    # --- seed: random_pickup (search_single_cta_kernel-inl.cuh:585) ------
    # score more random candidates than the buffer holds (the reference's
    # num_pickup oversampling): wider basin coverage costs one extra
    # gather+GEMM and rescues clustered datasets where few random nodes
    # land near the query's region
    n_seeds = max(2 * itopk, 128)
    seeds = (
        (jnp.arange(m, dtype=jnp.uint32)[:, None] * jnp.uint32(2654435761)
         + jnp.arange(n_seeds, dtype=jnp.uint32)[None, :]
         * jnp.uint32(40503)
         + jnp.uint32(0x128394))
        % jnp.uint32(n)
    ).astype(jnp.int32)                        # [m, n_seeds]
    seed_d = score(seeds)
    # dedup seeds (same trick as the loop): sort by id, kill repeats
    sd_i, sd_d = _dedup_by_id(seeds, seed_d)
    buf_d, ord0 = jax.lax.top_k(-sd_d, itopk)
    buf_d = -buf_d
    buf_i = jnp.take_along_axis(sd_i, ord0, axis=1)
    buf_e = jnp.zeros((m, itopk), jnp.bool_)

    def body(_, state):
        buf_d, buf_i, buf_e = state
        # parent pickup: best `width` unexplored entries
        pick_key = jnp.where(buf_e | (buf_i < 0), jnp.inf, buf_d)
        _, parent_slots = jax.lax.top_k(-pick_key, width)   # [m, w]
        parents = jnp.take_along_axis(buf_i, parent_slots, axis=1)
        # mark explored
        onehot = jnp.zeros((m, itopk), jnp.bool_)
        onehot = onehot.at[
            jnp.arange(m)[:, None], parent_slots
        ].set(True)
        buf_e = buf_e | onehot
        # expand + score (invalid parents contribute nothing)
        nbrs = graph[jnp.maximum(parents, 0)].reshape(m, width * deg)
        nbr_d = score(nbrs)
        parent_ok = jnp.broadcast_to(
            (parents >= 0)[:, :, None], (m, width, deg)
        ).reshape(m, width * deg)
        nbr_d = jnp.where(parent_ok, nbr_d, jnp.inf)
        # merge + dedup + retop
        all_i = jnp.concatenate([buf_i, nbrs], axis=1)
        all_d = jnp.concatenate([buf_d, nbr_d], axis=1)
        all_e = jnp.concatenate(
            [buf_e, jnp.zeros((m, width * deg), jnp.bool_)], axis=1
        )
        all_i, all_d, all_e = _dedup_by_id(all_i, all_d, all_e)
        nd, order = jax.lax.top_k(-all_d, itopk)
        buf_d = -nd
        buf_i = jnp.take_along_axis(all_i, order, axis=1)
        buf_e = jnp.take_along_axis(all_e, order, axis=1)
        return buf_d, buf_i, buf_e

    buf_d, buf_i, buf_e = jax.lax.fori_loop(
        0, iters, body, (buf_d, buf_i, buf_e)
    )
    out_d = buf_d[:, :k]
    out_i = jnp.where(jnp.isinf(out_d), -1, buf_i[:, :k])
    if ip:
        out_d = -out_d
    elif metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                    DistanceType.L2Unexpanded):
        qn = jnp.sum(q32 * q32, axis=1, keepdims=True)
        out_d = jnp.maximum(out_d + qn, 0.0)   # restore dropped ||q||^2
        if metric == DistanceType.L2SqrtExpanded:
            out_d = jnp.sqrt(out_d)
    out_d = jnp.where(out_i < 0, jnp.inf if not ip else -jnp.inf, out_d)
    return out_d, out_i


def _dedup_by_id(ids, dists, explored=None):
    """Collapse duplicate ids along axis 1: keep one entry (preserving an
    explored flag if any duplicate carries it), set the rest to +inf/-1.
    The sort-based replacement for the reference's visited hashmap."""
    order = jnp.argsort(ids, axis=1, stable=True)
    si = jnp.take_along_axis(ids, order, axis=1)
    sd = jnp.take_along_axis(dists, order, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((ids.shape[0], 1), jnp.bool_), si[:, 1:] == si[:, :-1]],
        axis=1,
    )
    sd = jnp.where(dup, jnp.inf, sd)
    si = jnp.where(dup, -1, si)
    if explored is None:
        return si, sd
    # the stable sort puts the buffer entry (the only flag carrier, and
    # unique per id) first in its duplicate run, so the kept entry already
    # owns the right flag
    se = jnp.take_along_axis(explored, order, axis=1)
    return si, sd, se


def search(
    search_params: SearchParams,
    index: Index,
    queries,
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Batched beam search (reference cagra.cuh:299 search)."""
    queries = jnp.asarray(queries)
    itopk = max(int(search_params.itopk_size), k)
    width = max(1, int(search_params.search_width))
    iters = int(search_params.max_iterations)
    if iters <= 0:
        # auto (reference search_plan.cuh: plan-derived): enough pickups to
        # explore the whole buffer plus slack
        iters = max(1 + itopk // width, 10)
    return _beam_search(
        queries,
        index.dataset,
        index.graph,
        index.data_norms,
        int(k),
        itopk,
        width,
        iters,
        int(index.metric),
        str(search_params.compute_dtype),
    )


# ---------------------------------------------------------------------------
# serialize (reference detail/cagra/cagra_serialize.cuh)
# ---------------------------------------------------------------------------


def save(path: str, index: Index) -> None:
    arrays = {
        "dataset": np.asarray(index.dataset),
        "graph": np.asarray(index.graph),
    }
    write_index_file(
        path, "cagra", _SERIAL_VERSION, {"metric": int(index.metric)}, arrays
    )


def load(path: str) -> Index:
    _, meta, arrays = read_index_file(path, "cagra")
    return from_graph(
        arrays["dataset"], arrays["graph"], DistanceType(meta["metric"])
    )


def serialize_to_hnswlib(path: str, index: Index) -> None:
    """Export as an hnswlib-readable base-layer-only index (reference
    detail/cagra/cagra_serialize.cuh serialize_to_hnswlib; consumed
    base-layer-only, bench/ann/src/raft/raft_cagra_hnswlib_wrapper.h:96).

    Writes the hnswlib v0 binary layout with every point on level 0 and
    the CAGRA graph as the level-0 link lists.
    """
    import struct

    data = np.asarray(index.dataset, dtype=np.float32)
    graph = np.asarray(index.graph)
    n, dim = data.shape
    deg = graph.shape[1]
    M = deg // 2
    size_links_level0 = deg * 4 + 4
    data_size = dim * 4
    size_data_per_element = size_links_level0 + data_size + 8  # +label
    offset_data = size_links_level0
    label_offset = size_links_level0 + data_size
    with open(path, "wb") as f:
        # header fields in hnswlib HierarchicalNSW::loadIndex read order:
        # offsetLevel0, max_elements, cur_element_count,
        # size_data_per_element, label_offset, offsetData (size_t each),
        # maxlevel (int), enterpoint (unsigned), maxM, maxM0, M (size_t),
        # mult (double), ef_construction (size_t)
        f.write(struct.pack("<Q", 0))                          # offsetLevel0
        f.write(struct.pack("<Q", n))                          # max_elements
        f.write(struct.pack("<Q", n))                          # cur_count
        f.write(struct.pack("<Q", size_data_per_element))
        f.write(struct.pack("<Q", label_offset))
        f.write(struct.pack("<Q", offset_data))
        f.write(struct.pack("<i", 0))                          # maxlevel
        f.write(struct.pack("<I", 0))                          # entrypoint
        f.write(struct.pack("<Q", M))                          # maxM
        f.write(struct.pack("<Q", deg))                        # maxM0
        f.write(struct.pack("<Q", M))                          # M
        f.write(struct.pack("<d", 1.0 / np.log(max(M, 2))))    # mult
        f.write(struct.pack("<Q", 200))                        # ef_construction
        for i in range(n):
            # link count lives in the first 2 bytes (hnswlib setListCount
            # writes unsigned short); <I with deg < 2^16 matches that
            f.write(struct.pack("<I", deg))
            f.write(graph[i].astype("<u4").tobytes())
            f.write(data[i].astype("<f4").tobytes())
            f.write(struct.pack("<Q", i))                      # label
        f.write(np.zeros(n, dtype="<i4").tobytes())            # levels
