"""CAGRA: graph-based ANN (build: pruned KNN graph; search: beam search).

TPU-native analog of the reference's cagra
(cpp/include/raft/neighbors/cagra.cuh; types cagra_types.hpp:47-175; build
detail/cagra/cagra_build.cuh:43; optimize detail/cagra/graph_core.cuh:128,
320; search detail/cagra/search_single_cta_kernel-inl.cuh:585).

Design — idiomatic TPU, not a port:

* **Graph build** follows the reference pipeline: IVF-PQ index on the
  dataset, batched self-search for ``intermediate_graph_degree`` raw
  neighbors (cagra_build.cuh:103-155), exact ``refine`` re-rank, then
  ``optimize``. An ``nn_descent`` builder is available as the alternative
  (build_algo, cagra_types.hpp:47) — rebuilt for the memory hierarchy
  in r15 (sample-then-gather candidates, node-blocked iteration under
  the OOM ladder, fused local-join kernel): 3.5x faster per iteration
  than the r2–r3-era formulation at 1M rows on the CPU host with
  bitwise-identical graphs, per-iteration transients bounded by the
  ``graph_join_rows`` block (~3.2 GB) instead of the old ``n*2K*K``
  two-hop tensor (18.4 GB/iteration at that scale) (2026-08-04,
  GRAPH_r15.json; TPU re-measure queued behind ROADMAP item 1).

* **optimize** keeps the reference's exact semantics (graph_core.cuh
  comment at :360): the detour count of edge A->B at rank k is the number
  of shorter edges A->D with B in D's adjacency list; edges are kept by
  ascending detour count (rank-stable), then reverse edges are spliced in
  after ``degree/2`` protected slots. The per-node CUDA block + warp
  bitonic becomes a vectorized sort + searchsorted membership test,
  scanned over node chunks — no atomics, one compiled program.

* **search** is the single-CTA beam search re-shaped for SPMD batching:
  every query carries a fixed-size itopk buffer of (distance, id,
  explored) and all queries advance in lockstep inside one
  ``lax.fori_loop`` — parent pickup (best unexplored), neighbor
  expansion, distance scoring, merge + dedup. Profiling on v5e showed
  the naive XLA formulation is bound by per-row HBM gathers (row-count
  bound: gathering 1 f32 norm costs the same as a 512-byte vector) and
  by sort/top_k/take_along_axis (which lower to serial per-row gathers).
  The TPU redesigns, each measured:

  - **Packed inline neighbor rows**: the index stores, per node, ONE
    int32 row ``[deg*d/4 int8-code words | deg norm bitcasts | deg
    neighbor ids]`` (the DiskANN-style layout, fused). One iteration
    gathers ``width`` contiguous ~4.5 KB rows per query instead of
    ``3*width`` (codes + norms + graph) scattered row sets — measured
    0.59 ms vs 4.1 ms per iteration at m=10k (and an int32-element
    gather moves ~4x the bytes/s of an int8 one). Traversal scores are
    int8-approximate; the final buffer prefix is exactly rescored from
    the f32 dataset before results are returned.
  - **Fused Pallas beam step** (ops/beam_step.py): scoring, bitonic
    merge, windowed dedup, and next-parent pickup run in one kernel
    with the buffer state resident in VMEM — the XLA formulation paid
    ~36 HBM round trips per iteration for the compare-exchange network
    alone. The reference's visited hash table (hashmap.hpp:41) becomes
    windowed dedup on the sorted buffer: duplicate ids score
    (near-)identically, so they land adjacent after the merge and
    collapse into one entry that keeps the explored flag — same
    invariant, no hashing.
  - **Shared seed slab**: per-query random seeds cost m*n_seeds HBM
    rows to score; a query-shared pseudo-random slab is one MXU matmul
    (seeds are uniform either way — measured no recall change).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.core.serialize import read_index_file, write_index_file
from raft_tpu.matrix.bitonic import sort_by_key
from raft_tpu.neighbors.common import merge_topk
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.utils.precision import dist_dot

_SERIAL_VERSION = 1


class build_algo:
    """Graph build algorithm (reference cagra_types.hpp:47)."""

    IVF_PQ = 0
    NN_DESCENT = 1


@dataclasses.dataclass
class IndexParams:
    """Build params (reference cagra_types.hpp:47-63)."""

    intermediate_graph_degree: int = 64
    graph_degree: int = 32
    metric: DistanceType = DistanceType.L2Expanded
    graph_build_algo: int = build_algo.IVF_PQ
    add_data_on_build: bool = True  # API parity; dataset always attached
    # build the inline int8 neighbor layout for fast search (auto-skipped
    # above _INLINE_BUDGET bytes; search falls back to scattered gathers)
    inline_codes: bool = True

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.L2Unexpanded,
            DistanceType.InnerProduct,
        ):
            raise ValueError(f"cagra supports L2/IP metrics, got {self.metric!r}")
        if self.graph_degree > self.intermediate_graph_degree:
            raise ValueError("graph_degree must be <= intermediate_graph_degree")


@dataclasses.dataclass
class SearchParams:
    """Search params (reference cagra_types.hpp:65-117)."""

    itopk_size: int = 64
    search_width: int = 4          # parents expanded per iteration
    max_iterations: int = 0        # 0 -> auto
    # traversal scoring: "auto" = packed int8 inline layout when the
    # index has one (the fast path; final top-k is exactly rescored in
    # f32), else scattered exact f32 gathers. "f32" | "bf16" force the
    # scattered exact-gather path with that scoring dtype.
    compute_dtype: str = "auto"
    # random seed candidates scored per query at startup (0 = auto:
    # max(2*itopk, 128) — generous because sparse seeding under-covers
    # clustered data; on smooth manifolds n_seeds=64 measured +20% QPS
    # r3 on v5e for -0.002 recall at SIFT-1M). Coarse entry-point
    # seeding was
    # prototyped and measured: it buys ~nothing (recall at reduced
    # iteration counts is exploration-limited, not start-limited) while
    # adding build cost, so seeds stay random like the reference's.
    n_seeds: int = 0
    # search backend: "auto" = the fused Pallas beam-step kernel on TPU
    # when the index carries the inline int8 layout (score + bitonic
    # merge + dedup + parent pick fused in VMEM, raft_tpu.ops.beam_step),
    # else the XLA paths. "pallas" | "pallas_interpret" | "xla" force.
    scan_impl: str = "auto"
    # reference knobs kept for API parity; the batched-SPMD kernel has no
    # CTA/team/hashmap notion (documented no-ops)
    algo: str = "auto"
    team_size: int = 0
    hashmap_min_bitlen: int = 0
    num_random_samplings: int = 1
    rand_xor_mask: int = 0x128394


@dataclasses.dataclass
class Index:
    """CAGRA index = dataset + fixed-degree graph (cagra_types.hpp:133).

    ``nbr_pack`` is the optional inline search layout: per node, ONE
    packed int32 row ``[deg*d/4 code words | deg norm bitcasts (L2) |
    deg neighbor ids]`` holding its graph neighbors' vectors
    int8-quantized plus their exact norms and ids, so beam-search
    expansion gathers ``width`` contiguous ~4.5 KB rows per query
    instead of ``3*width`` scattered ones (measured ~7x faster r3 on
    v5e; see ops/beam_step.py for the decode). Rebuilt on load; never
    serialized."""

    dataset: jax.Array      # [n, d]
    graph: jax.Array        # [n, degree] int32
    metric: DistanceType
    data_norms: Optional[jax.Array] = None  # [n] f32 (L2 metrics)
    nbr_pack: Optional[jax.Array] = None    # [n, W] int32 packed rows
    flat_codes: Optional[jax.Array] = None  # [n, d] int8 (seed scoring)
    code_scale: float = 1.0                 # int8 dequant scale

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]


jax.tree_util.register_dataclass(
    Index,
    data_fields=["dataset", "graph", "data_norms", "nbr_pack", "flat_codes"],
    meta_fields=["metric", "code_scale"],
)

# inline layout is skipped when the packed table's PER-SHARD residency
# exceeds this budget (bytes); the scattered-gather search path is used
# instead. Analytic default — the per-backend dispatch table can
# override it ("cagra_inline_bytes", see raft_tpu.tuning)
_INLINE_BUDGET = 6 << 30

# queries per Pallas beam-step grid tile (the kernel's lane dimension);
# the analytic default — ``_resolve_beam_tile`` consults the dispatch
# table (op key ``beam_step_tile``) so a live-chip capture adopts tile
# geometry with no code change, like ``fused_topk_tile``
_QUERY_TILE = 128


def _resolve_beam_tile(m: int, itopk: int, width: int, deg: int, d: int,
                       ip: bool) -> int:
    """Query-tile (lane) geometry for the fused beam kernel, dispatched
    under the ``beam_step_tile`` op key (docs/dispatch_tuning.md).
    Candidates are ``tuning.BEAM_STEP_TILES`` values whose VMEM
    footprint (ops/beam_step.py:beam_step_vmem_bytes) fits ~half of
    per-core VMEM; winner strings carry the tile (``pallas:<g>``). The
    analytic fallback keeps the measured r3 default of 128."""
    from raft_tpu import tuning
    from raft_tpu.ops.beam_step import beam_step_vmem_bytes

    budget = 8 * 1024 * 1024
    cands = [
        f"pallas:{g}" for g in tuning.BEAM_STEP_TILES
        if beam_step_vmem_bytes(g, itopk, width, deg, d, ip) <= budget
    ]
    if not cands:
        return _QUERY_TILE
    fallback = f"pallas:{_QUERY_TILE}"
    if fallback not in cands:
        fallback = cands[0]
    w = tuning.choose(
        "beam_step_tile",
        {"m": int(m), "itopk": int(itopk), "deg": int(deg), "d": int(d)},
        cands, fallback,
    )
    try:
        return int(str(w).split(":", 1)[1])
    except (IndexError, ValueError):
        return _QUERY_TILE


@functools.partial(jax.jit, static_argnums=(2, 3))
def _pack_tables(dataset, graph, need_norms: bool, chunk: int = 1 << 14,
                 scale=None):
    """Build the packed inline layout: per node one int32 row
    ``[deg*d/4 code words | deg norm bitcasts | deg ids]`` (norms
    omitted for IP), plus flat int8 codes [n, d] for seed scoring.
    Chunked over nodes to bound the [chunk, deg, d] gather transient.
    Code words pack 4 bytes by shift-or (a narrowing
    lax.bitcast_convert_type lowers to a catastrophic widened
    intermediate on TPU) — the kernel decode (beam_step.py) mirrors the
    byte order by construction. ``scale`` overrides the derived int8
    dequant scale (the sharded build passes a GLOBAL scale so every
    shard's codes share one dequant constant)."""
    n, d = dataset.shape
    deg = graph.shape[1]
    d32 = dataset.astype(jnp.float32)
    if scale is None:
        scale = _code_scale(d32)
    codes = jnp.clip(jnp.round(d32 / scale), -127, 127).astype(jnp.int8)
    norms = jnp.sum(d32 * d32, axis=1) if need_norms else None

    from raft_tpu.ops.beam_step import _a128 as a128

    def pack_chunk(gc):                        # [c, deg] raw graph rows
        c = gc.shape[0]
        g = jnp.maximum(gc, 0)
        nbr = codes[g].reshape(c, deg * d)     # [c, deg*d] i8
        b = nbr.astype(jnp.uint8).astype(jnp.uint32)
        words = (
            b[:, 0::4] | (b[:, 1::4] << 8) | (b[:, 2::4] << 16)
            | (b[:, 3::4] << 24)
        ).astype(jnp.int32)                    # [c, deg*d/4]
        # region order + 128-lane padding follow beam_step.packed_row_layout
        # (the one definition shared with the kernel decode)
        pad_r = lambda x: jnp.pad(x, ((0, 0), (0, a128(x.shape[1]) - x.shape[1])))
        parts = [pad_r(words)]
        if need_norms:
            parts.append(pad_r(
                jax.lax.bitcast_convert_type(norms[g], jnp.int32)))
        parts.append(pad_r(gc))                # raw ids: keep -1 padding
        return jnp.concatenate(parts, axis=1)

    if n <= chunk:
        pack = pack_chunk(graph)
    else:
        npad = -(-n // chunk) * chunk
        gp = jnp.pad(graph, ((0, npad - n), (0, 0)))
        pack = jax.lax.map(
            pack_chunk, gp.reshape(npad // chunk, chunk, deg)
        ).reshape(npad, -1)[:n]
    return pack, codes, scale


def _inline_eligible(n: int, d: int, deg: int, need_norms: bool,
                     max_rows: Optional[int] = None) -> bool:
    """The one inline-layout gate shared by single-device _attach_inline
    and the sharded stacked build: dim word-alignment, packed-table
    budget (row bytes incl. per-region 128-lane padding), and the
    (id<<1)|flag id-packing row bound.

    The budget applies to the PER-SHARD residency ``rows * row_bytes``
    (``max_rows`` = rows per shard; search-time HBM holds one shard's
    table), not the total ``n * row_bytes`` — an S-way mesh keeps the
    fused beam kernel up to S times the single-chip scale, which is the
    scale sharding exists for (ADVICE r5 finding 3). Single-device
    callers pass no ``max_rows``, so rows == n and nothing changes. The
    byte budget itself is tunable per backend
    (``tuning.budget("cagra_inline_bytes")`` — captured from the
    device's real HBM limit by scripts/capture_dispatch_tables.py;
    analytic default ``_INLINE_BUDGET``)."""
    from raft_tpu import tuning
    from raft_tpu.ops.beam_step import packed_row_layout

    if d % 4:
        return False
    row_bytes = 4 * packed_row_layout(deg, d, not need_norms)[3]
    rows = n if max_rows is None else max_rows
    budget = tuning.budget("cagra_inline_bytes", _INLINE_BUDGET)
    return rows * row_bytes <= budget and rows < (1 << 30)


def _code_scale(dataset) -> jax.Array:
    """The int8 dequant scale formula shared by _pack_tables and the
    sharded build's global-scale packing."""
    return jnp.maximum(
        jnp.max(jnp.abs(dataset.astype(jnp.float32))), 1e-30) / 127.0


def _attach_inline(index: Index, inline: bool) -> Index:
    n, d = index.dataset.shape
    deg = index.graph.shape[1]
    need_norms = index.metric != DistanceType.InnerProduct
    if not inline or not _inline_eligible(n, d, deg, need_norms):
        return index
    nbr_pack, flat_codes, scale = _pack_tables(
        index.dataset, index.graph, need_norms
    )
    return dataclasses.replace(
        index, nbr_pack=nbr_pack, flat_codes=flat_codes,
        code_scale=float(scale),
    )


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_knn_graph(
    dataset,
    intermediate_degree: int,
    metric: DistanceType,
    refine_rate: float = 2.0,
    query_batch: int = 16384,
    min_degree: Optional[int] = None,
) -> jax.Array:
    """Raw KNN graph via IVF-PQ self-search + exact refine (reference
    detail/cagra/cagra_build.cuh:43; params heuristic :60-68; batch loop
    :103-155). Returns [n, min(intermediate_degree, 63)] int32 when the
    fast path applies (below), else [n, intermediate_degree]; self
    excluded. ``min_degree`` (the final graph degree) bounds how far the
    fast path may trim the column count."""
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.refine import refine

    dataset = jnp.asarray(dataset)
    n, d = dataset.shape
    k = int(intermediate_degree) + 1          # +1: drop self afterwards
    # The fused Pallas IVF scan auto-dispatches only at k <= 64 (its
    # exact in-kernel extraction budget); k=65 searches fall back to the
    # XLA decode-scan, measured 5x slower r4 on v5e (2.53 s vs 0.50 s
    # per 16k-query batch at SIFT-1M). When 63 candidate columns still satisfy the
    # final graph degree, search k=64 and drop self (-> 63 exact-reranked
    # neighbors) to keep the whole self-search on the fast path; optimize
    # prunes to graph_degree anyway, so 64-vs-63 intermediate candidates
    # is noise. Configs needing >= 64 final columns keep the exact k
    # (slower XLA scan) — correctness over speed.
    if k > 64 and min_degree is not None and min_degree <= 63:
        if k > 65:
            # trimming by more than the free self-column is a quality
            # trade the caller should hear about (ADVICE r3): a requested
            # intermediate degree of e.g. 128 becomes 63 columns fed to
            # optimize(). Opt out by raising graph_degree above 63 or
            # calling build_knn_graph directly (min_degree=None).
            import warnings

            warnings.warn(
                f"CAGRA build: intermediate_graph_degree={k - 1} trimmed "
                f"to 63 to stay on the fused k<=64 self-search (final "
                f"graph_degree={min_degree} is unaffected; pass "
                f"min_degree=None to keep the full candidate pool on the "
                f"slower exact path)", stacklevel=2,
            )
        k = 64       # None (direct callers) keeps the exact column count
    k = min(k, n)    # tiny datasets: refine k cannot exceed n candidates
    gpu_top_k = min(n, max(k, int(k * refine_rate)))
    if k <= 64 and gpu_top_k > 64:
        gpu_top_k = 64                        # stay on the fused path

    # reference heuristic: n_lists ~ n/2500, pq_dim ~ d/2 rounded up
    n_lists = int(np.clip(n // 2500, 16, 1024))
    pq_dim = max(8, ((d // 2) + 7) // 8 * 8)
    params = ivf_pq.IndexParams(
        n_lists=n_lists,
        pq_dim=min(pq_dim, d),
        metric=(
            DistanceType.InnerProduct
            if metric == DistanceType.InnerProduct
            else DistanceType.L2Expanded
        ),
        kmeans_n_iters=10,
        # r2 measured full-dataset coarse training faster END-TO-END when
        # the self-search was the slow XLA path (balance dominated). With
        # the fused k<=64 self-search, a half-dataset trainset gives the
        # SAME list cap (2944 at 1M) for 20 s less kmeans (49 s vs 70 s,
        # steady batch 0.55 s vs 0.50 s)
        kmeans_trainset_fraction=min(0.5, max(0.1, 10000.0 * n_lists / n)),
    )
    index = ivf_pq.build(params, dataset)
    sp = ivf_pq.SearchParams(
        n_probes=min(n_lists, max(10, n_lists // 10)),
    )

    rows = []
    with obs.span("cagra.build.self_search", batches=-(-n // query_batch)):
        for start in range(0, n, query_batch):
            q = dataset[start:start + query_batch]
            _, cand = ivf_pq.search(sp, index, q, gpu_top_k)
            # always exact-rerank: optimize consumes RANK order, and PQ
            # ranks are approximate even when gpu_top_k == k (0.13 s per
            # 16k batch)
            _, cand = refine(dataset, q, cand, k, metric)
            rows.append(cand)
    graph = jnp.concatenate(rows, axis=0)     # [n, k]

    # drop self-edges: usually in slot 0; fall back to dropping the last
    self_col = graph == jnp.arange(n, dtype=graph.dtype)[:, None]
    # stable push of self (or worst candidate) to the end, then cut
    order = jnp.argsort(self_col.astype(jnp.int32), axis=1, stable=True)
    keep = min(int(intermediate_degree), k - 1)
    graph = jnp.take_along_axis(graph, order, axis=1)[:, :keep]
    return graph.astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _detour_counts_block(graph, start, rows: int, chunk: int):
    """Detour counts for node range [start, start+rows) (reference
    kern_prune, graph_core.cuh:128).

    For node A with rank-sorted neighbors N: count[kAB] = #{kAD < kAB :
    N[kAB] in graph[N[kAD]]}. Membership is a vectorized D³ equality
    compare per chunk (the VPU chews through it; a binary search lowers
    to a serial gather loop on TPU and is ~100x slower, r3 v5e)."""
    n, D = graph.shape
    gb = jax.lax.dynamic_slice(graph, (start, 0), (rows, D))
    tri = jnp.arange(D)[:, None] < jnp.arange(D)[None, :]  # kAD < kAB

    def one_chunk(_, g_chunk):                # [chunk, D]
        nbrs = graph[g_chunk]                 # [chunk, D, D] two-hop lists
        # found[c, kAD, kAB] = N[kAB] ∈ graph[N[kAD]]
        found = jnp.any(
            nbrs[:, :, :, None] == g_chunk[:, None, None, :], axis=2
        )
        counts = jnp.sum(found & tri[None, :, :], axis=1)  # [chunk, D]
        return None, counts.astype(jnp.int32)

    npad = -(-rows // chunk) * chunk
    gp = jnp.pad(gb, ((0, npad - rows), (0, 0)))
    _, counts = jax.lax.scan(
        one_chunk, None, gp.reshape(npad // chunk, chunk, D)
    )
    return counts.reshape(npad, D)[:rows]


def _detour_counts(graph, chunk: int, nodes_per_call: int = 1 << 16):
    """Host-blocked detour counts: one device dispatch per
    ``nodes_per_call`` node range. A single program covering a large graph
    runs minutes on-device, which trips the remote platform's execution
    watchdog (observed: programs > ~2 min kill the TPU worker) — and
    bounded dispatches also keep the scan transients small.

    The per-block dispatch is an OOM degradation-ladder boundary
    (docs/resilience.md): the block [chunk, D, D] membership transients
    are the build's peak, and a RESOURCE_EXHAUSTED here used to kill an
    n=300k build outright. Each block is synced before the next dispatch
    (recovery needs the failure AT its block, and the blocks were
    device-serialized anyway); on OOM the node range halves, sticks for
    the remaining blocks, and is recorded as the ``cagra_detour_rows``
    runtime budget so later builds in the process start safe."""
    from raft_tpu import tuning
    from raft_tpu.resilience import degrade

    graph = jnp.asarray(graph)
    n, _ = graph.shape
    block = max(1, int(tuning.budget("cagra_detour_rows",
                                     int(nodes_per_call))))
    parts = list(degrade.run_shrinking_blocks(
        lambda s, rows: _detour_counts_block(graph, jnp.int32(s), rows,
                                             chunk),
        n, block, budget_name="cagra_detour_rows", stage="cagra.detour",
    ))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _optimize_impl(graph, counts, degree: int, protected: int):
    n, D = graph.shape
    # 1. keep edges by ascending detour count, rank-stable
    #    (graph_core.cuh:424-441)
    key = counts * D + jnp.arange(D, dtype=jnp.int32)[None, :]
    order = jnp.argsort(key, axis=1)
    pruned = jnp.take_along_axis(graph, order[:, :degree], axis=1)

    # 2. reverse graph, capped at degree per node (kern_make_rev_graph)
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), degree)
    dst = pruned.reshape(-1)
    dst = jnp.where(dst >= 0, dst, n)          # drop invalid (OOB label)
    _, rev, rev_sizes = _pack_lists(
        jnp.zeros((n * degree, 1), jnp.int8), dst, src, n, degree
    )                                          # rev [n, degree] (-1 pad)

    # 3. splice reverse edges after the protected prefix
    #    (graph_core.cuh:520-546): final = protected originals, then
    #    reverse edges, then surviving unprotected originals — duplicates
    #    (vs the protected prefix or earlier candidates) dropped.
    #    Chunked over nodes: the [chunk, L, L] dedup masks are the peak
    #    transient (unchunked at n=300k they are ~3 GB each and OOM a v5e
    #    alongside the rest of the build's live buffers).
    prot = pruned[:, :protected]
    cand_full = jnp.concatenate([rev, pruned[:, protected:]], axis=1)  # [n, L]
    L = cand_full.shape[1]
    tri = (jnp.arange(L)[None, :] < jnp.arange(L)[:, None])[None, :, :]

    def splice_chunk(inp):
        cand, pr, tail = inp                               # [c, L], [c, P]
        dup_prot = jnp.any(cand[:, :, None] == pr[:, None, :], axis=2)
        dup_earlier = jnp.any(
            (cand[:, :, None] == cand[:, None, :]) & tri, axis=2
        )
        bad = dup_prot | dup_earlier | (cand < 0)
        # stable-compact the good candidates to the front
        rank = jnp.argsort(bad.astype(jnp.int32), axis=1, stable=True)
        kept = jnp.take_along_axis(cand, rank[:, : degree - protected], axis=1)
        # any remaining -1 (degenerate tiny graphs) falls back to originals
        return jnp.where(kept >= 0, kept, tail)

    chunk = 1 << 14
    tail_full = pruned[:, protected:]
    if n <= chunk:
        cand = splice_chunk((cand_full, prot, tail_full))
    else:
        npad = -(-n // chunk) * chunk
        pad = lambda a: jnp.pad(a, ((0, npad - n), (0, 0)))
        out = jax.lax.map(
            splice_chunk,
            (pad(cand_full).reshape(npad // chunk, chunk, L),
             pad(prot).reshape(npad // chunk, chunk, protected),
             pad(tail_full).reshape(npad // chunk, chunk, degree - protected)),
        )
        cand = out.reshape(npad, degree - protected)[:n]
    return jnp.concatenate([prot, cand], axis=1)


def optimize(graph, degree: int, chunk: int = 1024) -> jax.Array:
    """Prune a KNN graph to ``degree`` by 2-hop detour count + reverse-edge
    augmentation (reference graph_core.cuh:320 optimize)."""
    graph = jnp.asarray(graph).astype(jnp.int32)
    counts = _detour_counts(graph, int(chunk))
    protected = max(int(degree) // 2, 1)
    return _optimize_impl(graph, counts, int(degree), protected)


def build(params: IndexParams, dataset) -> Index:
    """Build the index (reference cagra.cuh:274 build)."""
    dataset = jnp.asarray(dataset)
    metric = params.metric
    with obs.entry_span("build", "cagra", rows=int(dataset.shape[0]),
                        graph_degree=int(params.graph_degree)):
        if params.graph_build_algo == build_algo.NN_DESCENT:
            from raft_tpu.neighbors import nn_descent

            nd_params = nn_descent.IndexParams(
                graph_degree=int(params.intermediate_graph_degree),
                metric=metric,
            )
            knn = nn_descent.build(nd_params, dataset).graph
        else:
            knn = build_knn_graph(
                dataset, int(params.intermediate_graph_degree), metric,
                min_degree=int(params.graph_degree),
            )
        with obs.span("cagra.build.optimize"):
            graph = optimize(knn, int(params.graph_degree))
        norms = None
        if metric != DistanceType.InnerProduct:
            d32 = dataset.astype(jnp.float32)
            norms = jnp.sum(d32 * d32, axis=1)
        index = Index(dataset=dataset, graph=graph, metric=metric,
                      data_norms=norms)
        return _attach_inline(index, params.inline_codes)


def from_graph(dataset, graph, metric=DistanceType.L2Expanded,
               inline_codes: bool = True) -> Index:
    """Wrap a prebuilt graph (pylibraft cagra.Index from_graph analog)."""
    dataset = jnp.asarray(dataset)
    metric = resolve_metric(metric)
    norms = None
    if metric != DistanceType.InnerProduct:
        d32 = dataset.astype(jnp.float32)
        norms = jnp.sum(d32 * d32, axis=1)
    index = Index(dataset=dataset, graph=jnp.asarray(graph, jnp.int32),
                  metric=metric, data_norms=norms)
    return _attach_inline(index, inline_codes)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _next_pow2(x: int) -> int:
    return 1 << (int(x) - 1).bit_length()


def _pad_cols(a, L: int, fill):
    pad = L - a.shape[1]
    if pad <= 0:
        return a
    return jnp.pad(a, ((0, 0), (0, pad)), constant_values=fill)


def _window_dedup(sd, si, se, window: int = 2):
    """Windowed dedup on distance-sorted rows: duplicate ids carry
    bitwise-equal distances (same deterministic scoring), so a
    duplicate group forms a contiguous run after the sort. Adjacent-pair
    comparison *chains* through a run of any length (every later copy
    matches its predecessor), so a small window fully blanks arbitrary
    runs — window > 1 only adds robustness against distinct nodes with
    bitwise-identical distances interleaving a run, and flag recovery
    for runs of 3+ whose explored copy sorted late. Each lane-shifted
    compare costs real VPU time (~0.9 ms at [10k, 256]), so the default
    stays small. Later copies are blanked to (+inf, -1, explored) — the
    next iteration's sort sinks them off the buffer; the kept (earliest)
    copy inherits any explored flag — the invariant the reference's
    visited hashmap maintains (hashmap.hpp:41-78)."""
    m, L = si.shape
    dup = jnp.zeros((m, L), jnp.bool_)
    e = se
    for s in range(1, window + 1):
        eq = (si[:, s:] == si[:, :-s]) & (si[:, s:] >= 0)
        dup = dup | jnp.pad(eq, ((0, 0), (s, 0)))
        # earlier copy inherits the later copy's explored flag
        e = e | jnp.pad(eq & se[:, s:], ((0, 0), (0, s)))
    sd = jnp.where(dup, jnp.inf, sd)
    si = jnp.where(dup, -1, si)
    e = jnp.where(dup, True, e)
    return sd, si, e


def _sorted_buffer(dists, ids, itopk: int):
    """Sort candidate rows, dedup, return the first ``itopk`` slots."""
    m, L0 = ids.shape
    L = _next_pow2(max(L0, itopk))
    sd = _pad_cols(dists, L, jnp.inf)
    si = _pad_cols(ids, L, -1)
    se = jnp.zeros((m, L), jnp.bool_)
    sd, (si, se) = sort_by_key(sd, si, se)
    sd, si, se = _window_dedup(sd, si, se)
    return sd[:, :itopk], si[:, :itopk], se[:, :itopk]


def _seed_ids(m: int, n: int, n_seeds: int):
    """Deterministic pseudo-random seed nodes per query
    (random_pickup, search_single_cta_kernel-inl.cuh:585). Oversampled
    past itopk: wider basin coverage rescues clustered datasets."""
    return (
        (jnp.arange(m, dtype=jnp.uint32)[:, None] * jnp.uint32(2654435761)
         + jnp.arange(n_seeds, dtype=jnp.uint32)[None, :]
         * jnp.uint32(40503)
         + jnp.uint32(0x128394))
        % jnp.uint32(n)
    ).astype(jnp.int32)


def _pick_parents(buf_d, buf_i, buf_e, width: int):
    """First ``width`` unexplored entries of the distance-sorted buffer
    (pickup_next_parents, search_single_cta_kernel-inl.cuh:682) — cumsum
    ranking + masked max extraction, no top_k/gather."""
    une = (~buf_e) & (buf_i >= 0) & jnp.isfinite(buf_d)
    rank = jnp.cumsum(une.astype(jnp.int32), axis=1) - 1
    sel = une & (rank < width)
    parents = jnp.stack(
        [
            jnp.max(jnp.where(sel & (rank == j), buf_i, -1), axis=1)
            for j in range(width)
        ],
        axis=1,
    )                                          # [m, width]; -1 = none left
    return parents, buf_e | sel


def _merge_step(buf_d, buf_i, buf_e, cand_d, cand_i, itopk: int,
                window: int = 2):
    """Merge the sorted buffer with fresh candidates: full bitonic sort
    of the concatenation + windowed dedup. A sort-candidates-then-
    bitonic-merge variant (via merge_sorted) measured no faster — the
    network is not the cost, the dedup's lane shifts are — and it forces
    ghost entries to keep real distances (sorted-halves invariant),
    which accumulate and clog the buffer (recall 0.989 -> 0.943 at
    SIFT-100k). Full sort lets dedup blank duplicates to +inf so they
    sink and fall off at the next iteration."""
    m, c = cand_i.shape
    L = _next_pow2(itopk + c)
    all_d = _pad_cols(jnp.concatenate([buf_d, cand_d], axis=1), L, jnp.inf)
    all_i = _pad_cols(jnp.concatenate([buf_i, cand_i], axis=1), L, -1)
    all_e = _pad_cols(
        jnp.concatenate([buf_e, jnp.zeros((m, c), jnp.bool_)], axis=1),
        L, True,
    )
    sd, (si, se) = sort_by_key(all_d, all_i, all_e)
    sd, si, se = _window_dedup(sd, si, se, window)
    return sd[:, :itopk], si[:, :itopk], se[:, :itopk]


def _exact_dedup_prefix(fd, fi, k: int):
    """All-pairs id dedup on the sorted prefix, then resort — closes the
    windowed dedup's escape hatch (interleaved bitwise-equal distances can
    separate a duplicate pair arbitrarily far; an all-pairs compare on a
    small prefix is exact and costs ~[m, 4k, 4k] VPU ops once)."""
    m, L = fi.shape
    P = min(L, _next_pow2(max(2 * k, 16)))
    pi = fi[:, :P]
    pd = fd[:, :P]
    tri = (jnp.arange(P)[None, :] < jnp.arange(P)[:, None])[None, :, :]
    dup = jnp.any((pi[:, :, None] == pi[:, None, :]) & tri
                  & (pi >= 0)[:, :, None], axis=2)
    pd = jnp.where(dup, jnp.inf, pd)
    pi = jnp.where(dup, -1, pi)
    pd, (pi,) = sort_by_key(pd, pi)
    return pd[:, :k], pi[:, :k]


def _side_accumulate(res_d, res_i, dvals, ids, kr: int, window: int = 8):
    """Merge scored candidates into the filtered-search side result
    buffer and collapse duplicate ids (a node is scored once per parent
    that lists it; copies carry bit-identical distances, so they sort
    adjacent — without this collapse the top-kr fills with copies of a
    handful of near nodes and recall craters). ``window`` must cover the
    worst adjacent run: up to ``search_width`` copies of one hub node per
    merge (one per parent listing it), so callers merging expanded
    candidates pass ``window=max(8, width)``; survivors past the window
    only waste side-buffer slots (the final exact dedup keeps results
    correct)."""
    rd, ri = merge_topk(
        jnp.concatenate([res_d, dvals], axis=1),
        jnp.concatenate([res_i, ids], axis=1),
        kr, True,
    )
    dup = jnp.zeros(ri.shape, bool)
    for s in range(1, window + 1):
        eq = (ri[:, s:] == ri[:, :-s]) & (ri[:, s:] >= 0)
        dup = dup | jnp.pad(eq, ((0, 0), (s, 0)))
    rd = jnp.where(dup, jnp.inf, rd)
    ri = jnp.where(dup, -1, ri)
    return rd, ri


def _filter_penalty_vector(filter_bits, filter_nbits: int, n: int, scale):
    """Dense per-node penalty [n] f32: 0 where the bit is set, ``scale``
    where filtered. Built by expanding the bitset words elementwise (no
    gather — a per-id bit gather here would cost a row-count-bound HBM
    pass per call).

    Both callers pass ``scale=jnp.inf``: the penalty only marks
    filtered candidates for exclusion from the SIDE result buffer
    (``_side_accumulate``), never the traversal buffer, so +inf is
    exactly right. A finite scale would only matter for an in-buffer
    penalty design — prototyped and rejected: valid results evict the
    penalized frontier from the shared ranked buffer and recall
    plateaus at 0.64-0.76 under dense filters."""
    w = filter_bits.shape[0]
    bits = (filter_bits[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    flat = bits.reshape(w * 32)
    if w * 32 < n:
        flat = jnp.pad(flat, (0, n - w * 32))
    keep = flat[:n] != 0
    if filter_nbits < n:
        keep = keep & (jnp.arange(n) < filter_nbits)
    return jnp.where(keep, 0.0, jnp.asarray(scale, jnp.float32))


def _finalize(out_d, out_i, q32, metric):
    """Restore the dropped ||q||^2 term / signs and mask invalid slots."""
    ip = metric == DistanceType.InnerProduct
    out_i = jnp.where(jnp.isinf(out_d), -1, out_i)
    if ip:
        out_d = -out_d
    elif metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
                    DistanceType.L2Unexpanded):
        qn = jnp.sum(q32 * q32, axis=1, keepdims=True)
        out_d = jnp.maximum(out_d + qn, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            out_d = jnp.sqrt(out_d)
    out_d = jnp.where(out_i < 0, -jnp.inf if ip else jnp.inf, out_d)
    return out_d, out_i


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7, 8, 9, 10, 12))
def _beam_search(
    queries,       # [m, d] f32
    dataset,       # [n, d]
    graph,         # [n, deg] int32
    data_norms,    # [n] f32 or None
    k: int,
    itopk: int,
    width: int,
    iters: int,
    metric_val: int,
    compute_dtype: str = "f32",
    n_seeds: int = 0,
    filter_bits=None,
    filter_nbits: int = 0,
):
    """Scattered-gather beam search (exact scoring; used when the index
    has no inline layout). Selection/merge are bitonic networks — see
    module docstring."""
    if compute_dtype not in ("f32", "bf16"):
        raise ValueError(f"compute_dtype must be f32|bf16, got {compute_dtype!r}")
    metric = DistanceType(metric_val)
    ip = metric == DistanceType.InnerProduct
    n, d = dataset.shape
    deg = graph.shape[1]
    m = queries.shape[0]
    q32 = queries.astype(jnp.float32)
    mm = jnp.bfloat16 if compute_dtype == "bf16" else jnp.float32
    data = dataset.astype(mm)
    qmm = q32.astype(mm)

    side = filter_nbits > 0
    if side:
        # filtered search, side-accumulation design: traversal runs
        # fully UNFILTERED (the best exploration policy — a single
        # ranked buffer cannot hold both the filtered result set and
        # the traversal frontier without one evicting the other;
        # measured recall plateaus of 0.64-0.76 at 90% filter density
        # for in-buffer penalty/expulsion schemes), and every scored
        # candidate that passes the filter is merged into a separate
        # top-kr result buffer. This realizes the reference's intent
        # (filtered nodes expand, never occupy result slots;
        # search_single_cta_kernel-inl.cuh:725-772) without its
        # slot-contention: measured 0.997/0.996 vs the reference
        # semantics' 0.94/0.76 at 50%/90% density.
        pen = _filter_penalty_vector(filter_bits, filter_nbits, n, jnp.inf)
        kr = max(4 * k, 64)
        res_d = jnp.full((m, kr), jnp.inf, jnp.float32)
        res_i = jnp.full((m, kr), -1, jnp.int32)

    def score(ids):                            # [m, c] -> [m, c] (min-close)
        vecs = data[ids]                       # [m, c, d] (mm dtype)
        dots = (vecs * qmm[:, None, :]).sum(-1, dtype=jnp.float32)
        if ip:
            return -dots
        return data_norms[ids] - 2.0 * dots    # ||q||^2 constant: dropped

    def side_merge(res_d, res_i, ids, dvals):
        vd = dvals + pen[ids]                  # filtered -> +inf
        return _side_accumulate(res_d, res_i, vd, ids, kr,
                                window=max(8, width))

    if n_seeds <= 0:
        n_seeds = max(2 * itopk, 128)
    seeds = _seed_ids(m, n, n_seeds)
    seed_d = score(seeds)
    buf_d, buf_i, buf_e = _sorted_buffer(seed_d, seeds, itopk)
    if side:
        res_d, res_i = side_merge(res_d, res_i, seeds, seed_d)

    def body(_, state):
        if side:
            buf_d, buf_i, buf_e, res_d, res_i = state
        else:
            buf_d, buf_i, buf_e = state
        parents, buf_e = _pick_parents(buf_d, buf_i, buf_e, width)
        nbrs = graph[jnp.maximum(parents, 0)].reshape(m, width * deg)
        nbr_d = score(nbrs)
        parent_ok = jnp.broadcast_to(
            (parents >= 0)[:, :, None], (m, width, deg)
        ).reshape(m, width * deg)
        nbr_d = jnp.where(parent_ok, nbr_d, jnp.inf)
        out = _merge_step(buf_d, buf_i, buf_e, nbr_d, nbrs, itopk)
        if side:
            res_d, res_i = side_merge(res_d, res_i, nbrs, nbr_d)
            return (*out, res_d, res_i)
        return out

    if side:
        buf_d, buf_i, buf_e, res_d, res_i = jax.lax.fori_loop(
            0, iters, body, (buf_d, buf_i, buf_e, res_d, res_i)
        )
        # the filtered result set lives in the side buffer — already
        # sorted by merge_topk; dedup (a node is scored once per parent
        # that lists it) and extract
        LR = _next_pow2(kr)
        fd = _pad_cols(jnp.where(res_i < 0, jnp.inf, res_d), LR, jnp.inf)
        fi = _pad_cols(res_i, LR, -1)
        fd, (fi,) = sort_by_key(fd, fi)
        fd, fi = _exact_dedup_prefix(fd, fi, k)
        return _finalize(fd, fi, q32, metric)

    buf_d, buf_i, buf_e = jax.lax.fori_loop(
        0, iters, body, (buf_d, buf_i, buf_e)
    )
    # sink dedup ghosts (id -1, real distance) below live entries and run
    # a wide-window dedup (one-off, so the cost doesn't matter): integer-
    # valued datasets tie bitwise between DISTINCT points, which can split
    # a duplicate run past the loop's window-2 reach
    L = _next_pow2(itopk)
    fd = jnp.where(buf_i < 0, jnp.inf, buf_d)
    fd = _pad_cols(fd, L, jnp.inf)
    fi = _pad_cols(buf_i, L, -1)
    fd, (fi,) = sort_by_key(fd, fi)
    fd, fi = _exact_dedup_prefix(fd, fi, k)
    return _finalize(fd, fi, q32, metric)


@functools.partial(jax.jit,
                   static_argnums=(7, 8, 9, 10, 11, 12, 13, 15, 16))
def _beam_search_pallas(
    queries,       # [m0, d] f32
    dataset,       # [n, d] (exact rescore)
    graph,         # [n, deg] int32
    data_norms,    # [n] f32 or None (IP)
    nbr_pack,      # [n, W] int32 packed inline rows
    flat_codes,    # [n, d] int8
    code_scale,    # [] f32
    k: int,
    itopk: int,
    width: int,
    iters: int,
    metric_val: int,
    n_seeds: int = 0,
    interpret: bool = False,
    filter_bits=None,
    filter_nbits: int = 0,
    g: int = 0,    # query tile; 0 = the analytic _QUERY_TILE default
):
    """Fused beam search: XLA gathers the packed int32 neighbor rows
    (row gathers are XLA's strength; the int32 fused row measured ~7x
    faster r3 on v5e than separate int8-codes + norms + graph
    gathers); everything
    else in the iteration — int8 decode + scoring, bitonic merge,
    windowed dedup, parent pickup — runs in one Pallas kernel with the
    itopk buffer resident in VMEM (ops/beam_step.py; the reference keeps
    the same state in CTA shared memory,
    search_single_cta_kernel-inl.cuh:585).

    Seeds are a SHARED pseudo-random slab scored by one MXU matmul
    instead of per-query row gathers (HBM gathers are row-count bound:
    per-query seeds cost m*n_seeds rows ~ 4 ms at m=10k; the slab is
    free). Seeds are uniform-random either way, so recall is unchanged.
    """
    from raft_tpu.ops.beam_step import beam_merge_step

    metric = DistanceType(metric_val)
    ip = metric == DistanceType.InnerProduct
    n, d = dataset.shape
    deg = graph.shape[1]
    m0 = queries.shape[0]
    side = filter_nbits > 0
    if side:
        # filtered search, side-accumulation design (see _beam_search):
        # traversal stays fully unfiltered; each iteration's scored
        # candidates come back from the kernel (emit_cands) and the
        # filter-passing ones merge into a separate top-kr result
        # buffer. Costs one [width*deg, m] penalty gather + merge per
        # iteration — filtered mode only.
        pen = _filter_penalty_vector(filter_bits, filter_nbits, n, jnp.inf)
    G = int(g) or _QUERY_TILE
    m = -(-m0 // G) * G
    q32 = jnp.pad(queries.astype(jnp.float32), ((0, m - m0), (0, 0)))
    two_scale = (1.0 if ip else 2.0) * code_scale
    qs = (q32 * two_scale).astype(jnp.bfloat16)
    # per-byte-lane query layout for the in-kernel word decode:
    # qrep[:, j, e*(d/4)+t] = qs[:, 4t+j]
    dq = d // 4
    qperm = jnp.transpose(qs.reshape(m, dq, 4), (0, 2, 1))   # [m, 4, d/4]
    qrep = jnp.tile(qperm, (1, 1, deg))                      # [m, 4, dw]

    # ---- shared seed slab, scored on the MXU -------------------------
    if n_seeds <= 0:
        n_seeds = max(2 * itopk, 128)
    seed_ids = (
        (jnp.arange(n_seeds, dtype=jnp.uint32) * jnp.uint32(2654435761)
         + jnp.uint32(0x128394)) % jnp.uint32(n)
    ).astype(jnp.int32)                                  # [S]
    scodes = flat_codes[seed_ids].astype(jnp.bfloat16)   # [S, d]
    sdots = jax.lax.dot_general(
        qs, scodes,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # [m, S]
    if ip:
        seed_d = -sdots
    else:
        seed_d = data_norms[seed_ids][None, :] - sdots
    seed_i = jnp.broadcast_to(seed_ids[:, None], (n_seeds, m))
    if side:
        kr = max(4 * k, 64)
        res_d = jnp.full((m, kr), jnp.inf, jnp.float32)
        res_i = jnp.full((m, kr), -1, jnp.int32)
        sids = jnp.broadcast_to(seed_ids[None, :], (m, n_seeds))
        res_d, res_i = _side_accumulate(
            res_d, res_i, seed_d + pen[seed_ids][None, :], sids, kr
        )

    buf_d = jnp.full((itopk, m), jnp.inf, jnp.float32)
    buf_i = jnp.full((itopk, m), -1, jnp.int32)
    buf_e = jnp.zeros((itopk, m), jnp.int32)
    buf_d, buf_i, buf_e, parents = beam_merge_step(
        buf_d, buf_i, buf_e, cand_d=seed_d.T, cand_i=seed_i,
        width=width, ip=ip, g=G, interpret=interpret,
    )

    def body(_, state):
        if side:
            bd, bi, be, par, rd_, ri_ = state
        else:
            bd, bi, be, par = state
        gp = jnp.maximum(par.T, 0)                       # [m, width]
        blk = nbr_pack[gp]                               # [m, width, W]
        out = beam_merge_step(
            bd, bi, be, qrep=qrep, pack=blk, parents=par,
            deg=deg, d=d, width=width, ip=ip, g=G, interpret=interpret,
            emit_cands=side,
        )
        if side:
            bd, bi, be, par, cd, ci = out
            cid = ci.T                                   # [m, C]
            vd = cd.T + pen[jnp.maximum(cid, 0)]         # filtered -> inf
            vd = jnp.where(cid < 0, jnp.inf, vd)
            rd_, ri_ = _side_accumulate(rd_, ri_, vd, cid, kr,
                                        window=max(8, width))
            return bd, bi, be, par, rd_, ri_
        return out

    if side:
        buf_d, buf_i, buf_e, parents, res_d, res_i = jax.lax.fori_loop(
            0, iters, body, (buf_d, buf_i, buf_e, parents, res_d, res_i)
        )
    else:
        buf_d, buf_i, buf_e, parents = jax.lax.fori_loop(
            0, iters, body, (buf_d, buf_i, buf_e, parents)
        )

    # ---- exact f32 rescore of the buffer prefix ----------------------
    # R rows/query of HBM gather (row-count bound): 2k-rounded is enough
    # because the int8 traversal ranking is already ~exact at the top
    # (measured: R=32 vs 64 at k=10 changes recall < 0.002, saves ~2 ms
    # of the fixed cost at m=10k)
    if side:
        # the filtered result set lives in the side buffer: rescore all
        # of it exactly (penalized/unfilled tail entries are inf-masked
        # to -1 first, so only filter-passing ids are rescored)
        R = kr
        ri = jnp.where(jnp.isinf(res_d), -1, res_i)[:m0]
    else:
        R = min(itopk, max(32, _next_pow2(2 * k)))
        ri = buf_i.T[:m0, :R]
    q0 = q32[:m0]
    rvec = dataset[jnp.maximum(ri, 0)].astype(jnp.float32)  # [m0, R, d]
    rdots = (rvec * q0[:, None, :]).sum(-1, dtype=jnp.float32)
    if ip:
        rd = -rdots
    else:
        rd = (rvec * rvec).sum(-1) - 2.0 * rdots
    rd = jnp.where(ri < 0, jnp.inf, rd)
    LR = _next_pow2(R)
    rd = _pad_cols(rd, LR, jnp.inf)
    ri = _pad_cols(ri, LR, -1)
    rd, (ri,) = sort_by_key(rd, ri)
    rd, ri = _exact_dedup_prefix(rd, ri, k)
    return _finalize(rd, ri, q0, metric)


def _resolve_beam_impl(requested: str, index: Index,
                       compute_dtype: str) -> str:
    if requested != "auto":
        return requested
    # explicit f32/bf16 compute_dtype selects the scattered exact-gather
    # path (the documented SearchParams contract)
    if index.nbr_pack is None or compute_dtype != "auto":
        return "xla"
    from raft_tpu import tuning

    return "pallas" if tuning.backend_name() == "tpu" else "xla"


# graft-lint: allow-unspanned-entry pure parameter arithmetic — no device dispatch to observe
def search_plan(search_params: SearchParams, k: int):
    """Derive (itopk, width, iters, n_seeds) from params + k (the
    reference's search_plan, detail/cagra/search_plan.cuh:70). Shared
    with the sharded search so the two stay in lockstep."""
    itopk = max(int(search_params.itopk_size), k)
    width = max(1, int(search_params.search_width))
    n_seeds = int(search_params.n_seeds)
    if n_seeds > 0:
        n_seeds = max(n_seeds, k)   # at least k live candidates to return
    iters = int(search_params.max_iterations)
    if iters <= 0:
        # auto: enough pickups to explore the whole buffer plus slack
        iters = max(1 + itopk // width, 10)
    return itopk, width, iters, n_seeds


def search(
    search_params: SearchParams,
    index: Index,
    queries,
    k: int,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Batched beam search (reference cagra.cuh:299 search). Uses the
    fused Pallas beam kernel over the packed inline layout when the
    index carries one (built by default), else the exact
    scattered-gather path.

    ``prefilter`` (a core.Bitset or BitsetFilter) restricts RESULTS to
    set bits via SIDE-ACCUMULATION: graph traversal runs fully
    unfiltered (filtered nodes are expanded like any other, so the beam
    reaches allowed regions through filtered ones), while every scored
    candidate passing the filter is merged into a separate deduplicated
    top-4k result buffer that filtered nodes can never enter. This is a
    deliberate departure from the reference's expel-and-retry in-kernel
    filtering (search_single_cta_kernel-inl.cuh:725-772), whose shared
    itopk buffer lets filtered nodes crowd out results — measured here:
    side-accumulation 0.997/0.996 recall vs reference semantics
    0.94/0.76 at 50%/90% filter density (SIFT-like 10k set). For
    extremely dense filters (>99%) raise ``itopk_size`` /
    ``max_iterations`` so unfiltered traversal explores far enough to
    touch the sparse allowed set."""
    from raft_tpu.neighbors.common import as_filter, resolve_filter_bits

    queries = jnp.asarray(queries)
    with obs.entry_span("search", "cagra", queries=int(queries.shape[0]),
                        k=int(k)) as _sp:
        filt = as_filter(prefilter)
        # materializes "keep"-mode tombstone filters (new node ids past
        # the filter default to kept) for the drop-semantics penalty/
        # side-accumulation masks — docs/serving.md §5
        bits = resolve_filter_bits(filt, int(index.dataset.shape[0]))
        fbits = None if bits is None else bits.bits
        fnbits = 0 if bits is None else int(bits.n_bits)
        itopk, width, iters, n_seeds = search_plan(search_params, k)
        dtype = str(search_params.compute_dtype)
        impl = _resolve_beam_impl(str(search_params.scan_impl), index, dtype)
        _sp.set(scan_impl=impl, itopk=itopk, iters=iters)
        if impl.startswith("pallas"):
            if index.nbr_pack is None:
                raise ValueError(
                    "scan_impl=%r needs the packed inline layout (build with "
                    "inline_codes=True; requires dim %% 4 == 0)" % impl
                )
            if dtype != "auto":
                raise ValueError(
                    "scan_impl=%r scores int8 traversal distances; "
                    "compute_dtype must stay 'auto' (got %r)" % (impl, dtype)
                )
            g = _resolve_beam_tile(
                int(queries.shape[0]), itopk, width,
                int(index.graph.shape[1]), int(index.dim),
                index.metric == DistanceType.InnerProduct,
            )
            _sp.set(beam_tile=g)
            return _beam_search_pallas(
                queries,
                index.dataset,
                index.graph,
                index.data_norms,
                index.nbr_pack,
                index.flat_codes,
                jnp.float32(index.code_scale),
                int(k),
                itopk,
                width,
                iters,
                int(index.metric),
                n_seeds,
                impl == "pallas_interpret",
                fbits,
                fnbits,
                g,
            )
        return _beam_search(
            queries,
            index.dataset,
            index.graph,
            index.data_norms,
            int(k),
            itopk,
            width,
            iters,
            int(index.metric),
            "f32" if dtype == "auto" else dtype,
            n_seeds,
            fbits,
            fnbits,
        )


# ---------------------------------------------------------------------------
# serialize (reference detail/cagra/cagra_serialize.cuh)
# ---------------------------------------------------------------------------


def save(path: str, index: Index) -> None:
    arrays = {
        "dataset": np.asarray(index.dataset),
        "graph": np.asarray(index.graph),
    }
    write_index_file(
        path, "cagra", _SERIAL_VERSION,
        {"metric": int(index.metric),
         "inline_codes": index.nbr_pack is not None},
        arrays,
    )


def load(path: str) -> Index:
    _, meta, arrays = read_index_file(path, "cagra")
    return from_graph(
        arrays["dataset"], arrays["graph"], DistanceType(meta["metric"]),
        inline_codes=bool(meta.get("inline_codes", True)),
    )


def serialize_to_hnswlib(path: str, index: Index) -> None:
    """Export as an hnswlib-readable base-layer-only index (reference
    detail/cagra/cagra_serialize.cuh serialize_to_hnswlib; consumed
    base-layer-only, bench/ann/src/raft/raft_cagra_hnswlib_wrapper.h:96).

    Writes the hnswlib v0 binary layout with every point on level 0 and
    the CAGRA graph as the level-0 link lists.
    """
    import struct

    data = np.asarray(index.dataset, dtype=np.float32)
    graph = np.asarray(index.graph)
    n, dim = data.shape
    deg = graph.shape[1]
    M = deg // 2
    size_links_level0 = deg * 4 + 4
    data_size = dim * 4
    size_data_per_element = size_links_level0 + data_size + 8  # +label
    offset_data = size_links_level0
    label_offset = size_links_level0 + data_size
    with open(path, "wb") as f:
        # header fields in hnswlib HierarchicalNSW::loadIndex read order:
        # offsetLevel0, max_elements, cur_element_count,
        # size_data_per_element, label_offset, offsetData (size_t each),
        # maxlevel (int), enterpoint (unsigned), maxM, maxM0, M (size_t),
        # mult (double), ef_construction (size_t)
        f.write(struct.pack("<Q", 0))                          # offsetLevel0
        f.write(struct.pack("<Q", n))                          # max_elements
        f.write(struct.pack("<Q", n))                          # cur_count
        f.write(struct.pack("<Q", size_data_per_element))
        f.write(struct.pack("<Q", label_offset))
        f.write(struct.pack("<Q", offset_data))
        f.write(struct.pack("<i", 0))                          # maxlevel
        f.write(struct.pack("<I", 0))                          # entrypoint
        f.write(struct.pack("<Q", M))                          # maxM
        f.write(struct.pack("<Q", deg))                        # maxM0
        f.write(struct.pack("<Q", M))                          # M
        f.write(struct.pack("<d", 1.0 / np.log(max(M, 2))))    # mult
        f.write(struct.pack("<Q", 200))                        # ef_construction
        for i in range(n):
            # link count lives in the first 2 bytes (hnswlib setListCount
            # writes unsigned short); <I with deg < 2^16 matches that
            f.write(struct.pack("<I", deg))
            f.write(graph[i].astype("<u4").tobytes())
            f.write(data[i].astype("<f4").tobytes())
            f.write(struct.pack("<Q", i))                      # label
        f.write(np.zeros(n, dtype="<i4").tobytes())            # levels
