"""Hybrid dense + sparse search (ROADMAP 6(a), ISSUE 20): one index
holding a dense embedding block next to a sparse lexical block (CSR at
rest), searched as a ``score_fuse`` PLAN — each leg over-fetches at the
fuse width, the fuse node re-scores every candidate on the OTHER leg
and weight-merges ``w_dense * dense + w_sparse * sparse`` over the
UNION of candidates, and one ``merge_topk`` keeps the fused top-k.

The pipeline is not a code path here: :func:`search` compiles
:func:`raft_tpu.plan.hybrid_plan` and executes it — the same program
the serve engine warms per (bucket, k) and the batcher/registry/
tombstone machinery serves end-to-end (``ServeEngine(algo="hybrid")``).

Rows are stored ``[dense_dim dense columns | vocab sparse columns]``;
queries arrive in the same layout (``split_queries`` cuts them). Both
legs score by inner product — the one metric whose weighted sum is
itself a meaningful ranking score (an RRF-style rank fusion would be a
different ``score_fuse`` op, not a different pipeline).

The exact-fusion trick is the padded ELL sidecar: re-scoring the dense
leg's candidates lexically needs random-access rows of the CSR block,
which CSR cannot give a fixed-shape gather for. ``build`` therefore
keeps ``[n, r_max]`` column/value sidecars (ELL layout, zero-padded);
one fused gather re-scores any candidate set at fixed shape, and the
zero padding contributes nothing to the dot.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import brute_force
from raft_tpu.sparse.types import CSR, dense_to_csr

__all__ = ["IndexParams", "SearchParams", "Index", "build",
           "split_queries", "search", "side_scale"]


@dataclasses.dataclass(frozen=True)
class IndexParams:
    """``dense_dim`` cuts the row layout; the weights set the fused
    ranking score ``w_dense * <q_d, x_d> + w_sparse * <q_s, x_s>``."""
    dense_dim: int
    w_dense: float = 1.0
    w_sparse: float = 1.0


@dataclasses.dataclass(frozen=True)
class SearchParams:
    """``fuse_expand``: each leg over-fetches ``max(k * fuse_expand,
    16)`` candidates before fusion — the hybrid analogue of
    refine_ratio (a candidate missing from BOTH legs' shortlists
    cannot be recovered by the re-score)."""
    fuse_expand: int = 4


@dataclasses.dataclass
class Index:
    dense: jax.Array            # [n, dense_dim] f32
    dense_bf: brute_force.Index  # IP sub-index over the dense block
    docs: CSR                   # [n, vocab] sparse block, CSR at rest
    ell_cols: jax.Array         # [n, r_max] i32, zero-padded
    ell_vals: jax.Array         # [n, r_max] f32, zero-padded
    dense_dim: int
    w_dense: float
    w_sparse: float

    @property
    def metric(self) -> DistanceType:
        return DistanceType.InnerProduct

    @property
    def size(self) -> int:
        return int(self.dense.shape[0])

    @property
    def dim(self) -> int:
        return int(self.dense_dim + self.docs.shape[1])


def build(params: IndexParams, dataset) -> Index:
    """Build from rows laid out ``[dense | sparse]`` (host-side: the
    CSR nnz and the ELL ``r_max`` are data-dependent)."""
    X = np.asarray(dataset, np.float32)
    dd = int(params.dense_dim)
    if X.ndim != 2 or not 0 < dd < X.shape[1]:
        raise ValueError(
            f"hybrid rows are [dense | sparse]: need 2-D data with "
            f"0 < dense_dim < row width, got {X.shape} dense_dim={dd}")
    with obs.entry_span("build", "hybrid", rows=int(X.shape[0]),
                        dense_dim=dd):
        return _build(params, X, dd)


def _build(params: IndexParams, X, dd: int) -> Index:
    dense = X[:, :dd]
    sparse_part = X[:, dd:]
    docs = dense_to_csr(sparse_part)
    indptr = np.diff(np.asarray(docs.indptr))
    r_max = max(int(indptr.max(initial=0)), 1)
    n = X.shape[0]
    ell_cols = np.zeros((n, r_max), np.int32)
    ell_vals = np.zeros((n, r_max), np.float32)
    ptr = np.asarray(docs.indptr)
    cols = np.asarray(docs.indices)
    vals = np.asarray(docs.vals)
    for r in range(n):
        lo, hi = int(ptr[r]), int(ptr[r + 1])
        ell_cols[r, : hi - lo] = cols[lo:hi]
        ell_vals[r, : hi - lo] = vals[lo:hi]
    return Index(
        dense=jnp.asarray(dense),
        dense_bf=brute_force.build(dense, metric="inner_product"),
        docs=docs,
        ell_cols=jnp.asarray(ell_cols),
        ell_vals=jnp.asarray(ell_vals),
        dense_dim=dd,
        w_dense=float(params.w_dense),
        w_sparse=float(params.w_sparse),
    )


def split_queries(index: Index, queries) -> Tuple[jax.Array, jax.Array]:
    """Cut ``[m, dense_dim + vocab]`` query rows into the two legs'
    operands (the layout contract ``build`` stored rows under)."""
    q = jnp.asarray(queries)
    if q.shape[1] != index.dim:
        raise ValueError(f"query width {q.shape[1]} != index dim "
                         f"{index.dim} (= {index.dense_dim} dense + "
                         f"{index.docs.shape[1]} vocab)")
    return q[:, : index.dense_dim], q[:, index.dense_dim:]


def side_scale(index: Index) -> np.ndarray:
    """Per-column weights that make a plain inner product over raw
    ``[dense | sparse]`` rows equal the fused score — the serve side
    buffer scales its rows by this so side hits rank on the same
    scale as main-index hits."""
    return np.concatenate([
        np.full(index.dense_dim, index.w_dense, np.float32),
        np.full(index.docs.shape[1], index.w_sparse, np.float32),
    ])


@jax.jit
def _fuse_rescore(qd, qs, dense, ell_cols, ell_vals,
                  dense_d, dense_i, sparse_d, sparse_i, wd, ws):
    """Union fusion at fixed shape: score each leg's candidates on the
    other leg (ELL gather for lexical, row gather + dot for dense),
    weight-sum, and mask the second leg's duplicates so the union
    carries each candidate once. Invalid slots (id -1) score the
    worst-possible sentinel (IP: -inf) and sink at the merge."""
    m = qd.shape[0]
    rows = jnp.arange(m)[:, None, None]

    # dense-leg candidates: lexical re-score from the ELL sidecar
    dj = jnp.maximum(dense_i, 0)
    lex = jnp.sum(qs[rows, ell_cols[dj]] * ell_vals[dj], axis=-1)
    fused1 = wd * dense_d + ws * lex

    # sparse-leg candidates: dense re-score by row gather + dot
    sj = jnp.maximum(sparse_i, 0)
    den = jnp.einsum("mcd,md->mc", dense[sj], qd)
    fused2 = wd * den + ws * sparse_d

    # union semantics: a candidate on both legs keeps its dense-leg
    # slot; -1 pads never alias a real id (compare against -2)
    dup = jnp.any(
        sparse_i[:, :, None] == jnp.where(dense_i < 0, -2, dense_i)[:, None, :],
        axis=-1)
    neg = jnp.float32(-jnp.inf)
    fused1 = jnp.where(dense_i >= 0, fused1, neg)
    fused2 = jnp.where((sparse_i >= 0) & ~dup, fused2, neg)
    return (jnp.concatenate([fused1, fused2], axis=1),
            jnp.concatenate([dense_i, sparse_i], axis=1))


def search(
    search_params: Optional[SearchParams],
    index: Index,
    queries,
    k: int,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused top-k by compiling and executing the hybrid plan (the
    standalone entry point; serving compiles the same plan per handle).

    Returns (fused scores [m, k], indices [m, k]), best-first
    (inner product: larger is closer).
    """
    from raft_tpu import plan as plan_mod

    sp = search_params if search_params is not None else SearchParams()
    if not 0 < k <= index.size:
        raise ValueError(f"k={k} out of range for index size {index.size}")
    with obs.entry_span("search", "hybrid",
                        queries=int(np.shape(queries)[0]), k=int(k)):
        p = plan_mod.hybrid_plan(fuse_expand=int(sp.fuse_expand))
        compiled = plan_mod.compile(p, index, k=int(k), search_params=sp,
                                    select_min=False)
        return compiled(jnp.asarray(queries), prefilter=prefilter)
