"""Brute-force (exact) KNN.

TPU-native analog of the reference's brute_force index
(cpp/include/raft/neighbors/brute_force.cuh,
detail/knn_brute_force.cuh:325 ``brute_force_knn_impl``,
detail/knn_brute_force.cuh:59 ``tiled_brute_force_knn``). The reference
tiles the dataset, runs pairwise distance + select_k per tile, and merges
per-tile top-ks; chunks go across a CUDA stream pool. Here the same tiling
is a ``lax.scan`` carrying a running top-k: each step is one MXU GEMM (+
epilogue) fused with the merge, so memory stays at n_queries × tile and XLA
pipelines the steps. The reference's separate "fused L2 kNN" small-k path
(spatial/knn/detail/fused_l2_knn-inl.cuh) is subsumed — the scan *is* the
fusion of distance and selection.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.core.serialize import read_index_file, write_index_file
from raft_tpu.distance.pairwise import _block_distance, _EXPANDED, _expanded_path
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.neighbors.common import (
    as_filter,
    filter_keep,
    merge_topk,
    sentinel_for,
)
from raft_tpu.utils.math import round_up_to_multiple
from raft_tpu.utils.precision import dist_dot

_SERIAL_VERSION = 1


@dataclasses.dataclass
class Index:
    """Brute-force index (reference brute_force_types.hpp): the dataset plus
    precomputed norms for expanded metrics."""

    dataset: jax.Array
    metric: DistanceType
    metric_arg: float = 2.0
    norms: Optional[jax.Array] = None

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]


jax.tree_util.register_dataclass(
    Index,
    data_fields=["dataset", "norms"],
    meta_fields=["metric", "metric_arg"],
)


def build(dataset, metric="sqeuclidean", metric_arg: float = 2.0) -> Index:
    """Build a brute-force index (reference brute_force-inl.cuh:345)."""
    metric = resolve_metric(metric)
    dataset = jnp.asarray(dataset)
    with obs.entry_span("build", "brute_force", rows=int(dataset.shape[0])):
        norms = None
        if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded, DistanceType.CosineExpanded):
            ds32 = dataset.astype(jnp.float32)
            norms = jnp.sum(ds32 * ds32, axis=1)
        return Index(dataset=dataset, metric=metric, metric_arg=metric_arg, norms=norms)


def search(
    index: Index,
    queries,
    k: int,
    prefilter=None,
    tile_n: Optional[int] = None,
    fast: bool = False,
    impl: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN search (reference brute_force-inl.cuh:156 ``knn``).

    Returns (distances [n_queries, k], indices [n_queries, k]), best-first.
    ``prefilter``: optional Bitset / filter over dataset row ids
    (reference filtered brute-force via sample_filter).

    ``fast=True`` enables the TPU-first two-phase path (TPU-KNN recipe,
    PAPERS.md): candidate generation with bf16 MXU matmuls at ~4× the
    candidates, then exact fp32 re-ranking — recovers exact-search recall
    at bf16 throughput. Only affects L2/IP/cosine expanded metrics.

    ``impl``: "auto" (measured dispatch through the ``fused_topk_tile``
    table, docs/dispatch_tuning.md) | "scan" (the XLA lax.scan tiling)
    | "fused_exact[:tile_n]" / "fused_fold[:tile_n]" (the fused Pallas
    distance+partial-top-k kernel, ops/fused_topk.py; append
    ":interpret" to run the kernel in interpret mode — the CPU parity
    path). The fold variant is approximate per-tile (bounded loss,
    docs/kernels.md) so "auto" only offers it to the ``fast`` two-phase
    path, which already opted into approximate candidate generation.
    """
    queries = jnp.asarray(queries)
    n = index.size
    if not 0 < k <= n:
        raise ValueError(f"k={k} out of range for dataset size {n}")
    with obs.entry_span("search", "brute_force",
                        queries=int(queries.shape[0]), k=int(k), fast=fast):
        filt = as_filter(prefilter)
        filter_bits = getattr(filt, "bitset", None)
        # out-of-range semantics (docs/serving.md §5): ids >= filter_nbits
        # are padding columns OR rows appended after the filter was built;
        # "drop" (default) rejects them, "keep" (tombstone keep-masks)
        # accepts them
        oor = getattr(filt, "out_of_range", "drop")
        if tile_n is None:
            budget = (128 * 1024 * 1024) // 4
            tile_n = min(n, max(1024, budget // max(queries.shape[0], 1)))
            tile_n = min(tile_n, 65536)

        fast_ok = fast and index.metric in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.CosineExpanded,
            DistanceType.InnerProduct,
        )
        if fast_ok:
            from raft_tpu.neighbors.refine import refine as _refine

            k_cand = min(n, max(4 * k, k + 32))
            cand_d, cand = _search(
                queries.astype(jnp.bfloat16),
                index.dataset.astype(jnp.bfloat16),
                index.norms,
                None if filter_bits is None else filter_bits.bits,
                None if filter_bits is None else filter_bits.n_bits,
                int(k_cand),
                int(index.metric),
                float(index.metric_arg),
                int(min(tile_n, n)),
                oor,
                _resolve_bf_impl(
                    impl, int(queries.shape[0]), n, int(index.dim),
                    int(k_cand), index.metric,
                    filtered=filter_bits is not None, approx_ok=True),
            )
            # candidates at the sentinel distance are padding or
            # prefiltered-out rows; mark them invalid so refine (which runs
            # unfiltered) cannot resurrect them into the final top-k
            sentinel = sentinel_for(index.metric, cand_d.dtype)
            cand = jnp.where(cand_d == sentinel, -1, cand)
            return _refine(index.dataset, queries, cand, k, index.metric)

        return _search(
            queries,
            index.dataset,
            index.norms,
            None if filter_bits is None else filter_bits.bits,
            None if filter_bits is None else filter_bits.n_bits,
            int(k),
            int(index.metric),
            float(index.metric_arg),
            int(min(tile_n, n)),
            oor,
            _resolve_bf_impl(
                impl, int(queries.shape[0]), n, int(index.dim), int(k),
                index.metric, filtered=filter_bits is not None,
                approx_ok=False),
        )


def _resolve_bf_impl(requested: str, m: int, n: int, d: int, k: int,
                     metric: DistanceType, filtered: bool,
                     approx_ok: bool) -> str:
    """Pick the brute-force scan backend through the per-backend
    dispatch table (``tuning.choose("fused_topk_tile", ...)``,
    docs/dispatch_tuning.md). The fused Pallas kernel is only a
    candidate on TPU, unfiltered, for the expanded metrics, and within
    its extraction budgets (exact k <= 128, fold k <= 256); the fold
    arm additionally requires the caller to have opted into approximate
    candidate generation (``approx_ok`` — the ``fast`` path). Candidate
    names carry the row-tile so a live-chip capture run picks the tile
    geometry too; the analytic fallback tiles from
    :func:`raft_tpu.ops.fused_topk.tile_geometry`'s VMEM budget math."""
    if requested != "auto":
        return requested
    from raft_tpu import tuning
    from raft_tpu.ops.fused_topk import tile_geometry

    fused_metric = metric in (
        DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
        DistanceType.CosineExpanded, DistanceType.InnerProduct,
    )
    on_tpu = tuning.backend_name() == "tpu"
    fused_ok = on_tpu and fused_metric and not filtered
    candidates = ["scan"]
    if fused_ok:
        # the canonical (variant, tile) enumeration lives in tuning —
        # the same set microbench races and the graft-kern verifier
        # audits (tuning.kernel_shape_candidates)
        candidates += tuning.fused_topk_candidate_impls(k, approx_ok)
    if len(candidates) == 1:
        return "scan"
    variant = "fold" if approx_ok and k <= 256 else "exact"
    # operand itemsize matches the caller: the fast path (approx_ok)
    # searches bf16 operands, the exact path f32 — sizing the analytic
    # tile for bf16 on an f32 search would undercount VMEM by 2x
    geo_tn = tile_geometry(m, n, d, k, variant,
                           itemsize=2 if approx_ok else 4)["tile_n"]
    analytic = f"fused_{variant}:{geo_tn}"
    if analytic not in candidates:
        analytic = "scan"
    return tuning.choose(
        "fused_topk_tile",
        {"m": int(m), "n": int(n), "d": int(d), "k": int(k)},
        candidates, analytic,
    )


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7, 8, 9, 10))
def _search(queries, dataset, norms, filter_bits, filter_nbits, k, metric_val, p, tile_n,
            out_of_range="drop", impl="scan"):
    metric = DistanceType(metric_val)
    select_min = is_min_close(metric)
    if queries.dtype == jnp.bfloat16:
        # TPU fast path: keep bf16 *operands* for single-pass MXU matmuls;
        # dist_dot accumulates in fp32 (preferred_element_type), so distances
        # are carried in fp32
        mm, acc = jnp.bfloat16, jnp.float32
    else:
        mm = acc = jnp.promote_types(queries.dtype, jnp.float32)
    q = queries.astype(mm)
    n, d = dataset.shape
    m = q.shape[0]
    sentinel = sentinel_for(metric, acc)

    if impl.startswith("fused"):
        # fused Pallas distance+partial-top-k (ops/fused_topk.py): the
        # distance matrix never reaches HBM — per-tile candidates are
        # reduced in-register off the MXU, then one hierarchical merge.
        # The auto resolver only offers fused where these hold, but an
        # EXPLICIT impl= request reaches here unvetted — re-check, or a
        # forced fused search would silently drop its prefilter
        from raft_tpu.ops.fused_topk import (
            COSINE as _FT_COS,
            IP as _FT_IP,
            L2 as _FT_L2,
            fused_topk as _fused_topk,
        )

        if filter_bits is not None:
            raise ValueError(
                "the fused brute-force kernel has no prefilter support; "
                "use impl='scan' (or 'auto') for filtered searches")
        _fused_mks = {DistanceType.L2Expanded: _FT_L2,
                      DistanceType.L2SqrtExpanded: _FT_L2,
                      DistanceType.CosineExpanded: _FT_COS,
                      DistanceType.InnerProduct: _FT_IP}
        if metric not in _fused_mks:
            raise ValueError(
                f"impl={impl!r} supports only the expanded "
                f"L2/IP/cosine metrics, got {metric.name}")
        parts = impl.split(":")
        variant = parts[0][len("fused_"):]
        ftile = next((int(t) for t in parts[1:] if t.isdigit()), None)
        interpret = "interpret" in parts
        mk = _fused_mks[metric]
        xn = norms
        if mk != _FT_IP and xn is None:
            ds32 = dataset.astype(jnp.float32)
            xn = jnp.sum(ds32 * ds32, axis=1)
        out_d, out_i = _fused_topk(
            q, dataset.astype(mm), k, metric_kind=mk, norms=xn,
            variant=variant, tile_n=ftile, interpret=interpret,
        )
        if metric == DistanceType.InnerProduct:
            out_d = -out_d                        # min-space -> score
        elif metric == DistanceType.L2SqrtExpanded:
            out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
        # rows short of k candidates: (+inf, -1) -> library sentinel
        return jnp.where(out_i < 0, sentinel, out_d.astype(acc)), out_i

    if tile_n >= n:
        dists = _dist_block(q, dataset.astype(mm), metric, p, norms).astype(acc)
        if filter_bits is not None:
            dists = _apply_filter(dists, jnp.arange(n)[None, :], filter_bits,
                                  filter_nbits, sentinel, out_of_range)
        return merge_topk(dists, jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (m, n)), k, select_min)

    npad = round_up_to_multiple(n, tile_n)
    ds = jnp.pad(dataset, ((0, npad - n), (0, 0))).astype(mm)
    tiles = ds.reshape(npad // tile_n, tile_n, d)
    norm_tiles = None
    if norms is not None:
        norm_tiles = jnp.pad(norms, (0, npad - n)).reshape(npad // tile_n, tile_n)

    def body(carry, inp):
        best_d, best_i = carry
        if norm_tiles is not None:
            t, db_tile, nt = inp
        else:
            t, db_tile = inp
            nt = None
        dists = _dist_block(q, db_tile, metric, p, nt).astype(acc)
        col = (t * tile_n + jnp.arange(tile_n, dtype=jnp.int32))[None, :]
        dists = jnp.where(col < n, dists, sentinel)
        if filter_bits is not None:
            dists = _apply_filter(dists, col, filter_bits, filter_nbits,
                                  sentinel, out_of_range)
        cand_d = jnp.concatenate([best_d, dists], axis=1)
        cand_i = jnp.concatenate([best_i, jnp.broadcast_to(col, (m, tile_n))], axis=1)
        return merge_topk(cand_d, cand_i, k, select_min), None

    init = (
        jnp.full((m, k), sentinel, acc),
        jnp.full((m, k), -1, jnp.int32),
    )
    xs = (jnp.arange(npad // tile_n), tiles) if norm_tiles is None else (
        jnp.arange(npad // tile_n), tiles, norm_tiles)
    (best_d, best_i), _ = jax.lax.scan(body, init, xs)
    return best_d, best_i


def _dist_block(q, db_tile, metric: DistanceType, p: float, db_norms) -> jax.Array:
    """Distance block with optional precomputed db norms (expanded L2)."""
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        dot = dist_dot(q, db_tile.T)
        qn = jnp.sum(q * q, axis=1)
        yn = db_norms if db_norms is not None else jnp.sum(db_tile * db_tile, axis=1)
        d2 = jnp.maximum(qn[:, None] + yn[None, :] - 2.0 * dot, 0.0)
        return jnp.sqrt(d2) if metric == DistanceType.L2SqrtExpanded else d2
    if metric in _EXPANDED:
        return _expanded_path(q, db_tile, metric)
    return _block_distance(q, db_tile, metric, p)


def _apply_filter(dists, col, filter_bits, filter_nbits, sentinel,
                  out_of_range="drop"):
    """Mask filtered-out columns to the sentinel distance.

    ``out_of_range`` (static) decides ids ``>= filter_nbits``: the old
    behavior silently dropped them, which is wrong for tombstone
    keep-masks over an index extended after the filter was built (new
    rows were never deleted ⇒ must stay eligible) — those pass
    ``"keep"``. Note the scan body masks padding columns (``col >= n``)
    to the sentinel BEFORE this runs, so "keep" cannot resurrect pad
    rows."""
    ids = jnp.broadcast_to(col, dists.shape)
    keep = filter_keep(filter_bits, filter_nbits, ids,
                       out_of_range=out_of_range)
    return jnp.where(keep, dists, sentinel)


def knn(
    queries,
    dataset,
    k: int,
    metric="sqeuclidean",
    metric_arg: float = 2.0,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot exact KNN (reference brute_force-inl.cuh:156 free function)."""
    return search(build(dataset, metric, metric_arg), queries, k, prefilter=prefilter)


def fused_l2_knn(queries, dataset, k: int, sqrt: bool = False):
    """Reference-named alias for the fused L2 path (brute_force-inl.cuh:240)."""
    metric = DistanceType.L2SqrtExpanded if sqrt else DistanceType.L2Expanded
    return knn(queries, dataset, k, metric)


# --------------------------------------------------------------------------
# Serialization (reference brute_force_serialize)
# --------------------------------------------------------------------------


def save(path: str, index: Index) -> None:
    arrays = {"dataset": np.asarray(index.dataset)}
    if index.norms is not None:
        arrays["norms"] = np.asarray(index.norms)
    write_index_file(
        path,
        "brute_force",
        _SERIAL_VERSION,
        {"metric": int(index.metric), "metric_arg": index.metric_arg},
        arrays,
    )


def load(path: str) -> Index:
    _, meta, arrays = read_index_file(path, "brute_force")
    return Index(
        dataset=jnp.asarray(arrays["dataset"]),
        metric=DistanceType(meta["metric"]),
        metric_arg=meta["metric_arg"],
        norms=jnp.asarray(arrays["norms"]) if "norms" in arrays else None,
    )
