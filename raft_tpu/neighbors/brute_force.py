"""Brute-force (exact) KNN.

TPU-native analog of the reference's brute_force index
(cpp/include/raft/neighbors/brute_force.cuh,
detail/knn_brute_force.cuh:325 ``brute_force_knn_impl``,
detail/knn_brute_force.cuh:59 ``tiled_brute_force_knn``). The reference
tiles the dataset, runs pairwise distance + select_k per tile, and merges
per-tile top-ks; chunks go across a CUDA stream pool. Here the same tiling
is a ``lax.scan`` carrying a running top-k: each step is one MXU GEMM (+
epilogue) fused with the merge, so memory stays at n_queries × tile and XLA
pipelines the steps. The reference's separate "fused L2 kNN" small-k path
(spatial/knn/detail/fused_l2_knn-inl.cuh) is subsumed — the scan *is* the
fusion of distance and selection.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.core.serialize import read_index_file, write_index_file
from raft_tpu.distance.pairwise import _block_distance, _EXPANDED, _expanded_path
from raft_tpu.distance.types import DistanceType, is_min_close, resolve_metric
from raft_tpu.neighbors.common import (
    as_filter,
    filter_keep,
    merge_topk,
    sentinel_for,
)
from raft_tpu.utils.math import round_up_to_multiple
from raft_tpu.utils.precision import dist_dot

_SERIAL_VERSION = 1


@dataclasses.dataclass
class Index:
    """Brute-force index (reference brute_force_types.hpp): the dataset plus
    precomputed norms for expanded metrics."""

    dataset: jax.Array
    metric: DistanceType
    metric_arg: float = 2.0
    norms: Optional[jax.Array] = None

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]


jax.tree_util.register_dataclass(
    Index,
    data_fields=["dataset", "norms"],
    meta_fields=["metric", "metric_arg"],
)


def build(dataset, metric="sqeuclidean", metric_arg: float = 2.0) -> Index:
    """Build a brute-force index (reference brute_force-inl.cuh:345)."""
    metric = resolve_metric(metric)
    dataset = jnp.asarray(dataset)
    with obs.entry_span("build", "brute_force", rows=int(dataset.shape[0])):
        norms = None
        if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded, DistanceType.CosineExpanded):
            ds32 = dataset.astype(jnp.float32)
            norms = jnp.sum(ds32 * ds32, axis=1)
        return Index(dataset=dataset, metric=metric, metric_arg=metric_arg, norms=norms)


def search(
    index: Index,
    queries,
    k: int,
    prefilter=None,
    tile_n: Optional[int] = None,
    fast: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Exact k-NN search (reference brute_force-inl.cuh:156 ``knn``).

    Returns (distances [n_queries, k], indices [n_queries, k]), best-first.
    ``prefilter``: optional Bitset / filter over dataset row ids
    (reference filtered brute-force via sample_filter).

    ``fast=True`` enables the TPU-first two-phase path (TPU-KNN recipe,
    PAPERS.md): candidate generation with bf16 MXU matmuls at ~4× the
    candidates, then exact fp32 re-ranking — recovers exact-search recall
    at bf16 throughput. Only affects L2/IP/cosine expanded metrics.
    """
    queries = jnp.asarray(queries)
    n = index.size
    if not 0 < k <= n:
        raise ValueError(f"k={k} out of range for dataset size {n}")
    with obs.entry_span("search", "brute_force",
                        queries=int(queries.shape[0]), k=int(k), fast=fast):
        filt = as_filter(prefilter)
        filter_bits = getattr(filt, "bitset", None)
        # out-of-range semantics (docs/serving.md §5): ids >= filter_nbits
        # are padding columns OR rows appended after the filter was built;
        # "drop" (default) rejects them, "keep" (tombstone keep-masks)
        # accepts them
        oor = getattr(filt, "out_of_range", "drop")
        if tile_n is None:
            budget = (128 * 1024 * 1024) // 4
            tile_n = min(n, max(1024, budget // max(queries.shape[0], 1)))
            tile_n = min(tile_n, 65536)

        fast_ok = fast and index.metric in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.CosineExpanded,
            DistanceType.InnerProduct,
        )
        if fast_ok:
            from raft_tpu.neighbors.refine import refine as _refine

            k_cand = min(n, max(4 * k, k + 32))
            cand_d, cand = _search(
                queries.astype(jnp.bfloat16),
                index.dataset.astype(jnp.bfloat16),
                index.norms,
                None if filter_bits is None else filter_bits.bits,
                None if filter_bits is None else filter_bits.n_bits,
                int(k_cand),
                int(index.metric),
                float(index.metric_arg),
                int(min(tile_n, n)),
                oor,
            )
            # candidates at the sentinel distance are padding or
            # prefiltered-out rows; mark them invalid so refine (which runs
            # unfiltered) cannot resurrect them into the final top-k
            sentinel = sentinel_for(index.metric, cand_d.dtype)
            cand = jnp.where(cand_d == sentinel, -1, cand)
            return _refine(index.dataset, queries, cand, k, index.metric)

        return _search(
            queries,
            index.dataset,
            index.norms,
            None if filter_bits is None else filter_bits.bits,
            None if filter_bits is None else filter_bits.n_bits,
            int(k),
            int(index.metric),
            float(index.metric_arg),
            int(min(tile_n, n)),
            oor,
        )


@functools.partial(jax.jit, static_argnums=(4, 5, 6, 7, 8, 9))
def _search(queries, dataset, norms, filter_bits, filter_nbits, k, metric_val, p, tile_n,
            out_of_range="drop"):
    metric = DistanceType(metric_val)
    select_min = is_min_close(metric)
    if queries.dtype == jnp.bfloat16:
        # TPU fast path: keep bf16 *operands* for single-pass MXU matmuls;
        # dist_dot accumulates in fp32 (preferred_element_type), so distances
        # are carried in fp32
        mm, acc = jnp.bfloat16, jnp.float32
    else:
        mm = acc = jnp.promote_types(queries.dtype, jnp.float32)
    q = queries.astype(mm)
    n, d = dataset.shape
    m = q.shape[0]
    sentinel = sentinel_for(metric, acc)

    if tile_n >= n:
        dists = _dist_block(q, dataset.astype(mm), metric, p, norms).astype(acc)
        if filter_bits is not None:
            dists = _apply_filter(dists, jnp.arange(n)[None, :], filter_bits,
                                  filter_nbits, sentinel, out_of_range)
        return merge_topk(dists, jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (m, n)), k, select_min)

    npad = round_up_to_multiple(n, tile_n)
    ds = jnp.pad(dataset, ((0, npad - n), (0, 0))).astype(mm)
    tiles = ds.reshape(npad // tile_n, tile_n, d)
    norm_tiles = None
    if norms is not None:
        norm_tiles = jnp.pad(norms, (0, npad - n)).reshape(npad // tile_n, tile_n)

    def body(carry, inp):
        best_d, best_i = carry
        if norm_tiles is not None:
            t, db_tile, nt = inp
        else:
            t, db_tile = inp
            nt = None
        dists = _dist_block(q, db_tile, metric, p, nt).astype(acc)
        col = (t * tile_n + jnp.arange(tile_n, dtype=jnp.int32))[None, :]
        dists = jnp.where(col < n, dists, sentinel)
        if filter_bits is not None:
            dists = _apply_filter(dists, col, filter_bits, filter_nbits,
                                  sentinel, out_of_range)
        cand_d = jnp.concatenate([best_d, dists], axis=1)
        cand_i = jnp.concatenate([best_i, jnp.broadcast_to(col, (m, tile_n))], axis=1)
        return merge_topk(cand_d, cand_i, k, select_min), None

    init = (
        jnp.full((m, k), sentinel, acc),
        jnp.full((m, k), -1, jnp.int32),
    )
    xs = (jnp.arange(npad // tile_n), tiles) if norm_tiles is None else (
        jnp.arange(npad // tile_n), tiles, norm_tiles)
    (best_d, best_i), _ = jax.lax.scan(body, init, xs)
    return best_d, best_i


def _dist_block(q, db_tile, metric: DistanceType, p: float, db_norms) -> jax.Array:
    """Distance block with optional precomputed db norms (expanded L2)."""
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        dot = dist_dot(q, db_tile.T)
        qn = jnp.sum(q * q, axis=1)
        yn = db_norms if db_norms is not None else jnp.sum(db_tile * db_tile, axis=1)
        d2 = jnp.maximum(qn[:, None] + yn[None, :] - 2.0 * dot, 0.0)
        return jnp.sqrt(d2) if metric == DistanceType.L2SqrtExpanded else d2
    if metric in _EXPANDED:
        return _expanded_path(q, db_tile, metric)
    return _block_distance(q, db_tile, metric, p)


def _apply_filter(dists, col, filter_bits, filter_nbits, sentinel,
                  out_of_range="drop"):
    """Mask filtered-out columns to the sentinel distance.

    ``out_of_range`` (static) decides ids ``>= filter_nbits``: the old
    behavior silently dropped them, which is wrong for tombstone
    keep-masks over an index extended after the filter was built (new
    rows were never deleted ⇒ must stay eligible) — those pass
    ``"keep"``. Note the scan body masks padding columns (``col >= n``)
    to the sentinel BEFORE this runs, so "keep" cannot resurrect pad
    rows."""
    ids = jnp.broadcast_to(col, dists.shape)
    keep = filter_keep(filter_bits, filter_nbits, ids,
                       out_of_range=out_of_range)
    return jnp.where(keep, dists, sentinel)


def knn(
    queries,
    dataset,
    k: int,
    metric="sqeuclidean",
    metric_arg: float = 2.0,
    prefilter=None,
) -> Tuple[jax.Array, jax.Array]:
    """One-shot exact KNN (reference brute_force-inl.cuh:156 free function)."""
    return search(build(dataset, metric, metric_arg), queries, k, prefilter=prefilter)


def fused_l2_knn(queries, dataset, k: int, sqrt: bool = False):
    """Reference-named alias for the fused L2 path (brute_force-inl.cuh:240)."""
    metric = DistanceType.L2SqrtExpanded if sqrt else DistanceType.L2Expanded
    return knn(queries, dataset, k, metric)


# --------------------------------------------------------------------------
# Serialization (reference brute_force_serialize)
# --------------------------------------------------------------------------


def save(path: str, index: Index) -> None:
    arrays = {"dataset": np.asarray(index.dataset)}
    if index.norms is not None:
        arrays["norms"] = np.asarray(index.norms)
    write_index_file(
        path,
        "brute_force",
        _SERIAL_VERSION,
        {"metric": int(index.metric), "metric_arg": index.metric_arg},
        arrays,
    )


def load(path: str) -> Index:
    _, meta, arrays = read_index_file(path, "brute_force")
    return Index(
        dataset=jnp.asarray(arrays["dataset"]),
        metric=DistanceType(meta["metric"]),
        metric_arg=meta["metric_arg"],
        norms=jnp.asarray(arrays["norms"]) if "norms" in arrays else None,
    )
