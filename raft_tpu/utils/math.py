"""Alignment and power-of-two math (reference util/pow2_utils.cuh,
util/integer_utils.hpp). Used throughout tiled algorithms to align block
shapes to TPU (8,128)/(16,128) tile constraints."""

from __future__ import annotations


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up_to_multiple(x: int, m: int) -> int:
    return cdiv(x, m) * m


def round_down_to_multiple(x: int, m: int) -> int:
    return (x // m) * m


def is_pow2(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


def next_pow2(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def bound_by_power_of_two(x: int) -> int:
    """Largest power of two <= x (x>=1)."""
    return 1 << (x.bit_length() - 1)


class Pow2:
    """Power-of-two alignment helper (reference util/pow2_utils.cuh Pow2<V>)."""

    def __init__(self, value: int):
        assert is_pow2(value), f"Pow2 requires a power of two, got {value}"
        self.value = value
        self.mask = value - 1
        self.log2 = value.bit_length() - 1

    def round_down(self, x: int) -> int:
        return x & ~self.mask

    def round_up(self, x: int) -> int:
        return (x + self.mask) & ~self.mask

    def div(self, x: int) -> int:
        return x >> self.log2

    def mod(self, x: int) -> int:
        return x & self.mask

    def is_aligned(self, x: int) -> bool:
        return (x & self.mask) == 0
