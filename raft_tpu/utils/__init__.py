"""Utility toolbox: tiling/alignment math and small helpers.

TPU analog of the reference's ``cpp/include/raft/util`` (SURVEY.md §2.2).
Most of the reference's device toolbox (warp shuffles, vectorized loads,
atomics) is absorbed by XLA/Pallas; what carries over is the Pow2 tiling
math (util/pow2_utils.cuh), integer utilities (util/integer_utils.hpp), and
batching helpers used by tiled host-side drivers.
"""

from raft_tpu.utils.math import (
    Pow2,
    round_up_to_multiple,
    round_down_to_multiple,
    cdiv,
    is_pow2,
    next_pow2,
    bound_by_power_of_two,
)
from raft_tpu.utils.batch import batch_ranges, BatchLoadIterator

__all__ = [
    "Pow2",
    "round_up_to_multiple",
    "round_down_to_multiple",
    "cdiv",
    "is_pow2",
    "next_pow2",
    "bound_by_power_of_two",
    "batch_ranges",
    "BatchLoadIterator",
]
