"""Matmul precision policy for distance math.

On TPU, the MXU's default fp32 matmul uses bf16 passes (~1e-2 relative
error) — unacceptable for distance computations that feed k-selection.
Distance GEMMs therefore default to ``Precision.HIGHEST`` (full fp32 via
multi-pass). The intended fast path is to feed bf16 *inputs* (the TPU-KNN
recipe): HIGHEST on bf16 operands is a single MXU pass with fp32
accumulation, which is both fast and accurate enough for recall targets.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_precision = jax.lax.Precision.HIGHEST


def set_dist_precision(p) -> None:
    global _precision
    _precision = p


def get_dist_precision():
    return _precision


def dist_dot(a, b):
    """a @ b with fp32 accumulation at the distance-math precision policy."""
    return jnp.dot(a, b, preferred_element_type=jnp.float32, precision=_precision)
