"""Host↔device batched streaming helpers.

Analog of the reference's ``batch_load_iterator``
(cpp/include/raft/spatial/knn/detail/ann_utils.cuh:397), which streams
out-of-core host datasets to the device in fixed-size batches during index
builds. Here batches are numpy slices moved with ``jax.device_put``; a
one-slot prefetch overlaps host slicing with device work (XLA's async
dispatch provides the device-side overlap).
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import jax
import numpy as np


def batch_ranges(n: int, batch_size: int):
    """Yield (start, stop) covering [0, n) in chunks of batch_size."""
    for start in range(0, n, batch_size):
        yield start, min(start + batch_size, n)


class BatchLoadIterator:
    """Iterate device-resident batches of a host array.

    Yields ``(offset, device_batch)``. The final batch may be shorter; pass
    ``pad_to_full=True`` to zero-pad it to ``batch_size`` (static shapes →
    one XLA compilation for all batches).
    """

    def __init__(
        self,
        host_array: np.ndarray,
        batch_size: int,
        device: Optional[jax.Device] = None,
        pad_to_full: bool = False,
        start_row: int = 0,
    ):
        self.host = host_array
        self.batch_size = int(batch_size)
        self.device = device
        self.pad_to_full = pad_to_full
        self.start_row = int(start_row)

    def __len__(self) -> int:
        return -(-max(self.host.shape[0] - self.start_row, 0)
                 // self.batch_size)

    def set_batch_rows(self, rows: int) -> None:
        """Shrink (or grow) the batch size for the REMAINING batches —
        the resilience OOM ladder's iterator hook: after a batch had to
        be split to survive, later batches start at the surviving size
        instead of re-OOMing. Takes effect at the next ``__iter__``
        step (the size is re-read per batch)."""
        self.batch_size = max(int(rows), 1)

    def __iter__(self) -> Iterator[Tuple[int, jax.Array]]:
        from raft_tpu.resilience import faultinject

        n = self.host.shape[0]
        pending: Optional[Tuple[int, jax.Array]] = None
        start = self.start_row
        bi = 0
        while start < n:
            bs = self.batch_size          # re-read: see set_batch_rows
            # the read-side fault point (``stream.read``): a slow@stage
            # spec here models host-tier fetch latency, an error spec
            # strikes on whichever thread runs the read — inline, or a
            # graft-flow producer that carries it to the consuming next()
            faultinject.check(stage="stream.read", chunk=bi,
                              stage_only=True)
            stop = min(start + bs, n)
            chunk = self.host[start:stop]
            if self.pad_to_full and chunk.shape[0] < bs:
                pad = np.zeros((bs - chunk.shape[0],) + chunk.shape[1:], chunk.dtype)
                chunk = np.concatenate([chunk, pad], axis=0)
            dev = jax.device_put(chunk, self.device)
            if pending is not None:
                yield pending
            pending = (start, dev)
            start = stop
            bi += 1
        if pending is not None:
            yield pending


class FileBatchLoadIterator:
    """Stream device-resident row batches straight from a big-ann ``*.bin``
    file (8-byte [n, d] uint32 header) without ever materializing the host
    array — the full analog of the reference's file-backed
    batch_load_iterator (ann_utils.cuh:397): a native double-buffered
    reader thread (raft_tpu.native.FilePrefetcher) keeps disk IO ahead of
    the device transfers.

    Yields ``(offset_rows, device_batch)``; the final batch is zero-padded
    to ``batch_rows`` when ``pad_to_full`` (one XLA shape for all batches).
    """

    def __init__(self, path: str, batch_rows: int, dtype=None,
                 device=None, pad_to_full: bool = False, depth: int = 2,
                 start_row: int = 0):
        from raft_tpu.bench.datasets import _dtype_for

        self.path = path
        self.dtype = _dtype_for(path, dtype)
        header = np.fromfile(path, dtype=np.uint32, count=2)
        self.n, self.d = int(header[0]), int(header[1])
        self.batch_rows = int(batch_rows)
        self.device = device
        self.pad_to_full = pad_to_full
        self.depth = depth
        self.start_row = int(start_row)

    @property
    def shape(self):
        return (self.n, self.d)

    def __len__(self) -> int:
        return -(-max(self.n - self.start_row, 0) // self.batch_rows)

    def set_batch_rows(self, rows: int) -> None:
        """Shrink (or grow) the batch size — the OOM ladder's iterator
        hook (see :meth:`BatchLoadIterator.set_batch_rows`). The native
        prefetcher's block size is fixed per ``__iter__``, so this takes
        effect at the next (re)start, which is exactly when graft-flow's
        downshift flush re-iterates."""
        self.batch_rows = max(int(rows), 1)

    def __iter__(self):
        from raft_tpu.native import FilePrefetcher
        from raft_tpu.resilience import faultinject

        row_bytes = self.d * self.dtype.itemsize
        start = self.start_row                # row-exact restart point
        pf = FilePrefetcher(
            self.path, offset=8 + start * row_bytes,
            block_bytes=self.batch_rows * row_bytes,
            total_bytes=(self.n - start) * row_bytes, depth=self.depth,
        )
        offset = start
        pending = None
        for bi, raw in enumerate(pf):
            faultinject.check(stage="stream.read", chunk=bi,
                              stage_only=True)
            rows = raw.size // row_bytes
            chunk = raw[: rows * row_bytes].view(self.dtype).reshape(
                rows, self.d
            )
            if self.pad_to_full and rows < self.batch_rows:
                pad = np.zeros(
                    (self.batch_rows - rows, self.d), self.dtype
                )
                chunk = np.concatenate([chunk, pad], axis=0)
            dev = jax.device_put(chunk, self.device)
            if pending is not None:
                yield pending
            pending = (offset, dev)
            offset += rows
        if pending is not None:
            yield pending
