"""Fused brute-force distance + partial-top-k Pallas kernel family.

The peak-FLOP/s recipe from TPU-KNN (PAPERS.md, arxiv 2206.14286): at
full MXU utilization the [queries x rows] distance matrix is never
materialized to HBM — each (query-tile x row-tile) grid step computes
its distance block in VMEM straight off the MXU and PARTIALLY REDUCES
it in-register down to a small per-tile candidate buffer. Only the
candidate buffers (k or R*128 entries per tile instead of tile_n) ever
leave the chip, so HBM traffic drops from O(m*n) to
O(m * n/tile_n * C), and the MXU stays busy streaming row tiles while
the VPU folds candidates. The final selection over the concatenated
per-tile buffers is one hierarchical ``select_k`` / ``merge_topk`` —
RAFT's two-level select (per-block select then cross-block merge,
matrix/detail/select_k-inl.cuh layer 4) with the block level fused into
the distance kernel.

Two in-kernel reduction variants (the candidate-buffer sizing math is
docs/kernels.md §candidate-buffers):

``exact``
    k-pass min extraction (the warp-queue analog) — emits the tile's
    EXACT top-k, so the downstream merge is exact end to end (ids
    bitwise vs the XLA oracle). Extraction cost grows with k: eligible
    for k <= 128.
``fold``
    R-deep per-lane partial reduction (TPU-KNN's approximate-then-exact
    PartialReduce): each of the 128 lanes keeps its R smallest
    candidates as a sorted stack, emitting R*128 survivors per tile with
    no extraction loop at all. A true top-k entry is lost only when > R
    of the tile's top-k share a lane (expected C(k, R+1)/128^R per
    tile); the exact cross-tile merge recovers everything that
    survives. The throughput arm for the k <= R*128 regime.

Both variants run under ``interpret=True`` on CPU — tier-1 parity-tests
every arm against the XLA oracle (tests/test_pallas_parity.py) before a
chip ever answers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# metric_kind values (static kernel variants) — shared convention with
# ops.ivf_scan
L2 = 0        # dist = ||q||^2 + ||x||^2 - 2 q.x
IP = 1        # dist = -q.x (min-space; caller negates back)
COSINE = 2    # dist = 1 - q.x / (||q|| ||x||)

_INVALID = -1

# mirror of analysis/lint.py's _VMEM_BUDGET_BYTES: the per-core VMEM the
# tile geometry must fit (pallas guide: ~16 MB/core), spent at ~50% so
# double-buffered pipelining has somewhere to live
_VMEM_BYTES = 16 * 1024 * 1024


def _extract_exact(dist, col, k: int, outd_ref, outi_ref):
    """k-pass min extraction over [G, T]; emits [G, k] dists + global
    column ids (same sweep as ivf_scan._extract_topk, with the id row
    replaced by the tile's global column iota)."""
    G, T = dist.shape
    for j in range(k):
        m = jnp.min(dist, axis=1)                              # [G]
        eq = dist == m[:, None]
        pos = jnp.min(jnp.where(eq, col, jnp.int32(2**31 - 1)), axis=1)
        outd_ref[:, j] = m
        outi_ref[:, j] = jnp.where(jnp.isinf(m), _INVALID, pos)
        if j + 1 < k:
            dist = jnp.where(col == pos[:, None], jnp.inf, dist)


def fold_lane_stacks(dist, ids, R: int):
    """The shared R-deep per-lane fold (TPU-KNN's PartialReduce core):
    lane b keeps its R smallest (value, id) pairs as a sorted
    compare-swap cascade over the T//128 lane chunks of ``dist``/
    ``ids`` [G, T]. Returns (stack_d, stack_i) — R arrays of [G, 128]
    each, sorted per lane, +inf/-1 in unfilled slots. Used by both
    fused kernels (this module's brute-force tiles and
    ops.ivf_scan's fold extraction) so the fold semantics and any
    future retuning stay in ONE place."""
    G, T = dist.shape
    nch = T // 128
    stack_d = [jnp.full((G, 128), jnp.inf, jnp.float32) for _ in range(R)]
    stack_i = [jnp.full((G, 128), _INVALID, jnp.int32) for _ in range(R)]
    for c in range(nch):
        nd = dist[:, c * 128:(c + 1) * 128]
        ni = ids[:, c * 128:(c + 1) * 128]
        for r in range(R):
            swap = nd < stack_d[r]
            sd, si = stack_d[r], stack_i[r]
            stack_d[r] = jnp.where(swap, nd, sd)
            stack_i[r] = jnp.where(swap, ni, si)
            nd = jnp.where(swap, sd, nd)
            ni = jnp.where(swap, si, ni)
    return stack_d, stack_i


def _extract_fold(dist, col, R: int, outd_ref, outi_ref):
    """R-deep per-lane fold over [G, T]: the R*128 survivors are
    written out UNEXTRACTED — selection happens in the cross-tile
    merge (TPU-KNN's approximate-then-exact partial reduction)."""
    stack_d, stack_i = fold_lane_stacks(dist, col, R)
    for r in range(R):
        outd_ref[:, r * 128:(r + 1) * 128] = stack_d[r]
        outi_ref[:, r * 128:(r + 1) * 128] = jnp.where(
            jnp.isinf(stack_d[r]), _INVALID, stack_i[r])


def _fused_kernel(q_ref, x_ref, *refs, k: int, metric_kind: int,
                  variant: str, fold_r: int, n: int, tile_n: int,
                  has_norms: bool):
    refs = list(refs)
    xn_ref = refs.pop(0) if has_norms else None
    qa_ref = refs.pop(0) if metric_kind != IP else None
    outd_ref, outi_ref = refs
    j = pl.program_id(1)
    q = q_ref[...]                                      # [TQ, d] mm dtype
    x = x_ref[...]                                      # [TN, d] mm dtype
    dots = jax.lax.dot_general(
        q, x,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                   # [TQ, TN] f32
    G, T = dots.shape
    if metric_kind == L2:
        dist = jnp.maximum(
            qa_ref[0][:, None] + xn_ref[0][None, :] - 2.0 * dots, 0.0)
    elif metric_kind == IP:
        dist = -dots
    else:  # COSINE
        xlen = jnp.sqrt(jnp.maximum(xn_ref[0], 1e-30))
        dist = 1.0 - dots / jnp.maximum(
            qa_ref[0][:, None] * xlen[None, :], 1e-30)
    col = jax.lax.broadcasted_iota(jnp.int32, (G, T), 1) + j * tile_n
    dist = jnp.where(col < n, dist, jnp.inf)            # mask pad rows
    if variant == "fold":
        _extract_fold(dist, col, fold_r, outd_ref, outi_ref)
    else:
        _extract_exact(dist, col, k, outd_ref, outi_ref)


def tile_geometry(m: int, n: int, d: int, k: int, variant: str,
                  itemsize: int = 2) -> dict:
    """Expression-derived tile geometry for the fused kernel (the VMEM
    budget math is docs/kernels.md §tile-geometry): block bytes =
    q[TQ, d] + x[TN, d] + f32 dist[TQ, TN] + candidate buffers must fit
    ~half of per-core VMEM. The analytic default; the dispatch table
    overrides it per backend (op key ``fused_topk_tile``).

    The query-tile floor is the operand dtype's SUBLANE multiple (8 for
    4-byte, 16 for 2-byte, 32 for 1-byte operands — the (s, 128) tile
    rule analysis/contracts.py codifies): the old flat floor of 8 put
    the bf16 fast path's q-block off the (16, 128) tile at m <= 8 —
    found by graft-kern's computed alignment audit (GL016, r6)."""
    floor = {1: 32, 2: 16}.get(int(itemsize), 8)
    tile_q = 128 if m >= 128 else max(
        floor, 1 << (max(m - 1, 1)).bit_length())
    cand = candidate_width(k, variant)
    budget = _VMEM_BYTES // 2
    tile_n = 2048
    while tile_n > 256:
        used = (tile_q * d * itemsize + tile_n * d * itemsize
                + 4 * tile_q * tile_n + 8 * tile_q * cand)
        if used <= budget:
            break
        tile_n //= 2
    return {"tile_q": int(tile_q), "tile_n": int(tile_n)}


def candidate_width(k: int, variant: str) -> int:
    """Per-tile candidate-buffer width C: ``exact`` emits exactly k,
    ``fold`` emits R*128 with R from :func:`fold_depth` (ceil(k/64),
    floor 2 — sized to the per-lane occupancy tail; rationale there and
    docs/kernels.md §candidate-buffers)."""
    if variant == "fold":
        return 128 * fold_depth(k)
    return int(k)


def fold_depth(k: int) -> int:
    """Lane-stack depth R: at k candidates over 128 lanes the per-lane
    occupancy is Binomial(k, 1/128) — R must clear its tail, not just
    its mean, or lanes overflow and drop true top-k entries (measured:
    R = ceil(k/128) lost ~8% at k=200). R = ceil(k/64) keeps the
    expected overflow under ~1% of k through k=256; floor 2."""
    return max(2, -(-int(k) // 64))


def fused_topk(
    queries,          # [m, d] mm dtype (bf16 for the TPU fast path)
    dataset,          # [n, d] mm dtype
    k: int,
    *,
    metric_kind: int,
    norms=None,       # [n] f32 ||x||^2 (L2/cosine); None for IP
    qaux=None,        # [m] f32 ||q||^2 (L2) or ||q|| (cosine); None for IP
    variant: str = "exact",
    tile_q: int = None,
    tile_n: int = None,
    interpret: bool = False,
):
    """Fused-tile exact KNN in min-space: returns
    (dist [m, k] f32, idx [m, k] int32) best-first. For IP the distances
    are negated scores — negate back after. Rows short of k valid
    candidates come back (+inf, -1).

    ``variant``: "exact" (bitwise-exact ids, k <= 128) | "fold"
    (R-deep lane fold, k <= 256; bounded per-tile loss recovered by the
    exact cross-tile merge). Tile geometry defaults to the
    expression-derived :func:`tile_geometry`; callers resolving through
    the dispatch table pass explicit tiles.
    """
    from raft_tpu import obs

    m, d = queries.shape
    n = dataset.shape[0]
    if variant not in ("exact", "fold"):
        raise ValueError(f"variant must be 'exact'|'fold', got {variant!r}")
    if variant == "exact" and k > 128:
        raise ValueError(
            f"variant='exact' caps at k=128 (k-pass extraction), got {k}")
    if variant == "fold" and k > 256:
        raise ValueError(
            f"variant='fold' caps at k=256 (the R=ceil(k/64) lane-stack "
            f"sizing's validated loss band, docs/kernels.md), got {k}")
    geo = tile_geometry(m, n, d, k, variant,
                        jnp.dtype(queries.dtype).itemsize)
    tq = int(tile_q or geo["tile_q"])
    tn = int(tile_n or geo["tile_n"])
    if variant == "fold" and tn % 128:
        # fold_lane_stacks folds T//128 lane chunks: a non-lane-multiple
        # row tile would silently DROP the tail columns from the
        # reduction (the tail-masking class the kernel contracts exist
        # for) — tile_geometry and the dispatch candidates only produce
        # lane multiples, so only an explicit tile_n can get here
        raise ValueError(
            f"variant='fold' needs tile_n % 128 == 0 (the per-lane "
            f"fold covers tile_n//128 chunks; a remainder is silently "
            f"dropped), got tile_n={tn}")
    # trace-time span: attributes compile cost per (variant, tiles);
    # steady-state cached dispatch is silent
    with obs.span("fused_topk", variant=variant, m=m, n=n, k=int(k),
                  tile_q=tq, tile_n=tn):
        cand_d, cand_i = _fused_topk_tiles(
            queries, dataset, norms, qaux, k=int(k),
            metric_kind=int(metric_kind), variant=variant, tile_q=tq,
            tile_n=tn, interpret=bool(interpret),
        )
        # exact hierarchical merge over the concatenated per-tile
        # buffers (layer-4 select; the per-tile select was in-kernel)
        from raft_tpu.neighbors.common import merge_topk

        out_d, out_i = merge_topk(cand_d[:m], cand_i[:m], int(k),
                                  select_min=True)
    return out_d, out_i


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric_kind", "variant", "tile_q", "tile_n",
                     "interpret"),
)
def _fused_topk_tiles(queries, dataset, norms=None, qaux=None, *, k: int,
                      metric_kind: int, variant: str, tile_q: int,
                      tile_n: int, interpret: bool):
    m, d = queries.shape
    n = dataset.shape[0]
    mq = -(-m // tile_q)
    nt = -(-n // tile_n)
    C = candidate_width(k, variant)
    has_norms = metric_kind != IP

    qpad = mq * tile_q - m
    npad = nt * tile_n - n
    q = jnp.pad(queries, ((0, qpad), (0, 0))) if qpad else queries
    x = jnp.pad(dataset, ((0, npad), (0, 0))) if npad else dataset
    inputs = [q, x]
    in_specs = [
        pl.BlockSpec((tile_q, d), lambda i, j: (i, 0)),
        pl.BlockSpec((tile_n, d), lambda i, j: (j, 0)),
    ]
    if has_norms:
        xn = jnp.sum(x.astype(jnp.float32) ** 2, axis=1) if norms is None \
            else (jnp.pad(norms, (0, npad)) if npad else norms)
        inputs.append(xn.reshape(1, nt * tile_n))
        in_specs.append(pl.BlockSpec((1, tile_n), lambda i, j: (0, j)))
        if qaux is None:
            q32 = q.astype(jnp.float32)
            qa = (jnp.sum(q32 * q32, axis=1) if metric_kind == L2
                  else jnp.linalg.norm(q32, axis=1))
        else:
            qa = jnp.pad(qaux, (0, qpad)) if qpad else qaux
        inputs.append(qa.reshape(1, mq * tile_q))
        in_specs.append(pl.BlockSpec((1, tile_q), lambda i, j: (0, i)))

    kernel = functools.partial(
        _fused_kernel, k=k, metric_kind=metric_kind, variant=variant,
        fold_r=fold_depth(k), n=n, tile_n=tile_n, has_norms=has_norms,
    )
    out_d, out_i = pl.pallas_call(
        kernel,
        grid=(mq, nt),
        in_specs=in_specs,
        out_specs=[
            # graft-lint: allow-tile-align exact-arm candidate width C=k is deliberately narrow — lane-padding it to 128 would multiply the kernel's whole HBM output by 128/k, the very traffic the fusion removes (docs/kernels.md §candidate-buffers); accepted relayout, revalidate when a chip answers (r6)
            pl.BlockSpec((tile_q, C), lambda i, j: (i, j)),
            # graft-lint: allow-tile-align same narrow candidate buffer as the distance output above
            pl.BlockSpec((tile_q, C), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mq * tile_q, nt * C), jnp.float32),
            jax.ShapeDtypeStruct((mq * tile_q, nt * C), jnp.int32),
        ],
        interpret=interpret,
    )(*inputs)
    return out_d, out_i


# ---------------------------------------------------------------------------
# kernel contract (graft-kern: static geometry bindings + the dynamic
# adversarial interpret-mode sweep share these declarations —
# docs/static_analysis.md §engine-4)
# ---------------------------------------------------------------------------

from raft_tpu.analysis.contracts import kernel_contract  # noqa: E402


def _contract_case_ok(case: dict) -> bool:
    k, n = case.get("k", 1), case.get("n", 1)
    if not 0 < k <= n:
        return False
    if case.get("variant") == "exact" and k > 128:
        return False
    if case.get("variant") == "fold" and k > 256:
        return False
    return True


def _contract_case_derive(case: dict) -> dict:
    # tile_q is ALWAYS the analytic choice (dispatch winners carry only
    # the row tile) — bind the real coupling so the static engine does
    # not audit (m, tile_q) pairs the resolver can never produce
    itemsize = 2 if case.get("dtype") == "bfloat16" else 4
    case.setdefault(
        "tile_q",
        tile_geometry(case["m"], case["n"], case["d"], case.get("k", 1),
                      case.get("variant", "exact"), itemsize)["tile_q"])
    return case


kernel_contract(
    "fused_topk",
    module=__name__,
    entry="fused_topk",
    driver="raft_tpu.analysis.contract_drivers:drive_fused_topk",
    tail_rows="masked",           # pad rows masked to +inf in-kernel
    k_range=(1, 256),
    dtypes=("float32", "bfloat16"),
    exactness="bitwise",          # exact arm; fold judged in its band
    recall_floor=0.95,
    base={"m": 16, "n": 403, "d": 32, "metric_kind": L2},
    rows_key="n", batch_key="m",
    arms=({"variant": "exact", "k_max": 128},
          {"variant": "fold", "k_max": 256}),
    arrays={"queries": ("m", "d"), "dataset": ("n", "d"),
            "norms": ("n",), "qaux": ("m",)},
    case_filter=_contract_case_ok,
    derive=_contract_case_derive,
    extra_cases=(
        {"variant": "exact", "k": 10, "m": 16, "n": 403, "d": 32,
         "metric_kind": IP, "dtype": "float32"},
        {"variant": "exact", "k": 10, "m": 16, "n": 403, "d": 32,
         "metric_kind": COSINE, "dtype": "float32"},
        # multi-tile query grid (m >= 128: tile_q=128, mq > 1)
        {"variant": "exact", "k": 10, "m": 256, "n": 403, "d": 32,
         "metric_kind": L2, "dtype": "float32"},
        # the bf16 fast path's smallest batch: the dtype-aware tile_q
        # floor (16 for 2-byte operands) pinned by the GL016 audit
        {"variant": "fold", "k": 10, "m": 4, "n": 403, "d": 32,
         "metric_kind": L2, "dtype": "bfloat16"},
    ),
    notes="fold loses a true top-k entry only when > R share a lane "
          "(R = ceil(k/64), docs/kernels.md §candidate-buffers); the "
          "exact cross-tile merge recovers everything that survives.",
)
