"""Fused CAGRA beam-search step: score + merge + dedup + parent pick.

TPU-native analog of the reference's single-CTA CAGRA search iteration
(cpp/include/raft/neighbors/detail/cagra/search_single_cta_kernel-inl.cuh:585:
topk_by_bitonic_sort :405, pickup_next_parents :682, hashmap dedup
hashmap.hpp:41) — the entire per-iteration pipeline the reference keeps
in CTA shared memory lives here in VMEM:

* the itopk result buffer (distances, ids, explored flags),
* int8 candidate scoring from the PACKED neighbor rows (one int32 row
  per parent carries codes + norms + neighbor ids; measured r3 on v5e
  (PALLAS_PARITY_r03.json): one fused int32 row gather is ~7x faster
  than separate int8-codes + norms + graph gathers of the same bytes),
* the bitonic merge network,
* windowed duplicate collapse (the visited-hashmap analog), and
* next-iteration parent selection,

so one iteration costs one HBM pass over the gathered rows plus a
read+write of the small buffer state, instead of the ~36 full-array HBM
round trips the XLA compare-exchange network pays.

Layout: all per-query state is TRANSPOSED to [slots, n_queries] so the
sort axis is the *sublane* axis — every compare-exchange is a
full-width [j, G]-tile vector op and reshape regrouping touches only
leading dims (the lane dim G stays 128). The un-transposed form would
put the sort axis on lanes, where sub-128 slicing forces relayouts.

Packed row format (built by cagra._attach_inline), per node, int32:
``[deg*d/4 code words | deg norm bitcasts (L2 only) | deg neighbor ids]``
— code word ``e*(d/4)+t`` holds int8 dims ``4t..4t+3`` of neighbor ``e``
(little-endian), so in-kernel decode is shift/mask/sign-extend and the
query rides pre-permuted+tiled (``qrep``) to line up per byte lane.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INVALID = -1

# static-unroll the per-parent-slot scoring loop (saves the fori_loop's
# dynamic-offset loads; costs more scoped VMEM — tune on-chip)
_UNROLL_SCORE = False


def _next_pow2(x: int) -> int:
    return 1 << (int(x) - 1).bit_length()


def _a128(v: int) -> int:
    return -(-v // 128) * 128


def beam_step_vmem_bytes(g: int, L: int, width: int, deg: int, d: int,
                         ip: bool = False) -> int:
    """Per-grid-step VMEM bytes of the packed-scoring beam kernel at
    query tile ``g``: in/out blocks + the [C, g] decode scratch + the
    live [LL, g] sort pair. The eligibility rule behind the
    ``beam_step_tile`` dispatch candidates (cagra._resolve_beam_tile) —
    a tile only races when this fits ~half of per-core VMEM."""
    dw = deg * (d // 4)
    W = packed_row_layout(deg, d, ip)[3]
    C = width * deg
    LL = _next_pow2(max(L + C, 2))
    blocks = (
        6 * L * g * 4            # buffer state in + out (d, i, e)
        + g * 4 * dw * 2         # qrep (bf16)
        + g * width * W * 4      # packed rows (flattened)
        + 2 * width * g * 4      # parents in + out
        + 2 * C * g * 4          # cd/ci decode scratch
    )
    live = 2 * LL * g * 4        # the sort network's key + payload
    return blocks + live


def packed_row_layout(deg: int, d: int, ip: bool = False):
    """THE single definition of the packed inline row layout, shared by
    the builder (cagra._pack_tables), the HBM-budget check
    (cagra._attach_inline) and the kernel decode below: returns
    ``(dw, o_norm, o_id, W)`` — code-word count, norm-region offset,
    id-region offset, total int32 row width. Every region is padded to a
    128-lane multiple (dynamic lane loads need aligned offsets); IP rows
    carry no norm region."""
    dw = deg * (d // 4)
    o_norm = _a128(dw)
    o_id = o_norm + (0 if ip else _a128(deg))
    return dw, o_norm, o_id, o_id + _a128(deg)


def _sort_rows(kd, payloads, LL: int):
    """Bitonic sort along axis 0 (sublanes) of [LL, G] arrays; payloads
    ride the same compare-exchange.

    Stage directions are applied structurally (no mask constants, which
    pallas kernels may not capture): at stage ``k`` the direction is
    constant over each k-block and alternates asc/desc per block, so the
    view [B/2, 2, k/(2j), 2, j, G] lets axis 1 select the direction and
    axis 3 the partner."""
    G = kd.shape[-1]

    k = 2
    while k <= LL:
        j = k // 2
        while j >= 1:
            B = LL // k          # k-blocks; all-ascending when B == 1
            if B == 1:
                shape = (1, 1, k // (2 * j), 2, j, G)
            else:
                shape = (B // 2, 2, k // (2 * j), 2, j, G)

            def pair(x):
                v = x.reshape(shape)
                return v[:, :, :, 0], v[:, :, :, 1]  # [B2, D, k/2j, j, G]

            k0, k1 = pair(kd)
            if B == 1:
                swap = k0 > k1
            else:
                # int32 concat, then compare: Mosaic rejects i1 vector
                # concatenation ("invalid vector register cast")
                swap = jnp.concatenate(
                    [(k0[:, :1] > k1[:, :1]).astype(jnp.int32),
                     (k0[:, 1:] < k1[:, 1:]).astype(jnp.int32)], axis=1
                ) != 0

            def exch(x):
                x0, x1 = pair(x)
                lo = jnp.where(swap, x1, x0)
                hi = jnp.where(swap, x0, x1)
                return jnp.stack([lo, hi], axis=3).reshape(LL, G)

            kd = exch(kd)
            payloads = [exch(p) for p in payloads]
            j //= 2
        k *= 2
    return kd, payloads


def _dedup_rows(kd, kie, window: int):
    """Windowed dup collapse on the sorted [LL, G] buffer (duplicate ids
    score near-identically, so they sort adjacent): later copies blank
    to (+inf, -1); the kept copy inherits the explored flag.

    ``kie`` packs ``(id << 1) | explored`` so the sort network carries
    ONE payload instead of two (ids must stay < 2^30; the -1 sentinel
    encodes (id=-1, explored) since (-1<<1)|1 == -1)."""
    LL, G = kie.shape
    ids = kie >> 1
    dup = jnp.zeros((LL, G), jnp.int32)
    for s in range(1, window + 1):
        eq = ((ids[s:] == ids[:-s]) & (ids[s:] >= 0)).astype(jnp.int32)
        dup = dup | jnp.concatenate(
            [jnp.zeros((s, G), jnp.int32), eq], axis=0
        )
        inherit = eq * (kie[s:] & 1)
        kie = kie | jnp.concatenate(
            [inherit, jnp.zeros((s, G), jnp.int32)], axis=0
        )
    isdup = dup != 0
    kd = jnp.where(isdup, jnp.inf, kd)
    kie = jnp.where(isdup, _INVALID, kie)
    return kd, kie


def _pick_rows(kd, kie, width: int):
    """First ``width`` unexplored live rows per column (lane) —
    prefix-sum rank + masked-max extraction (pickup_next_parents)."""
    L, G = kie.shape
    ids = kie >> 1
    une = ((kie & 1) == 0) & (ids >= 0) & (kd < jnp.inf)
    r = une.astype(jnp.int32)
    off = 1
    while off < L:
        r = r + jnp.concatenate(
            [jnp.zeros((off, G), jnp.int32), r[:-off]], axis=0
        )
        off *= 2
    rank = r - 1                                   # 0-based among unexplored
    sel = une & (rank < width)
    parents = [
        jnp.max(jnp.where(sel & (rank == j), ids, _INVALID), axis=0)
        for j in range(width)
    ]                                              # width x [G]
    return parents, kie | sel.astype(jnp.int32)


def _beam_step_kernel(
    *refs,
    L: int, deg: int, d: int, width: int, window: int, ip: bool,
    scored: bool, emit_cands: bool = False,
):
    refs = list(refs)
    bd_ref = refs.pop(0)        # [L, G] f32
    bi_ref = refs.pop(0)        # [L, G] i32
    be_ref = refs.pop(0)        # [L, G] i32
    G = bd_ref.shape[1]

    if scored:
        cd = refs.pop(0)[...]                      # [C, G] f32 pre-scored
        ci = refs.pop(0)[...]                      # [C, G] i32
        C = ci.shape[0]
        cd = jnp.where(ci < 0, jnp.inf, cd)
        obd_ref, obi_ref, obe_ref, par_ref = refs
    else:
        qrep_ref = refs.pop(0)   # [G, 4, dw] bf16 (pre-scaled + tiled)
        pack_ref = refs.pop(0)   # [G, width*W] i32 packed rows (flat)
        par_ref_in = refs.pop(0)  # [width, G] i32 previous parents
        if emit_cands:
            (obd_ref, obi_ref, obe_ref, par_ref,
             ocd_ref, oci_ref) = refs[:6]
            cd_ref, ci_ref = refs[6:]              # [C, G] VMEM scratch
        else:
            obd_ref, obi_ref, obe_ref, par_ref = refs[:4]
            cd_ref, ci_ref = refs[4:]              # [C, G] VMEM scratch
        C = width * deg
        W = pack_ref.shape[1] // width
        dw, o_norm, o_id, _W = packed_row_layout(deg, d, ip)
        a128 = _a128
        qr = qrep_ref[...]                         # [G, 4, dw]
        # per-32-lane-segment reduction as a one-hot MXU matmul (a
        # minor-dim split reshape + sum is an unsupported Mosaic
        # relayout); seg[l, e] = 1 iff lane l belongs to neighbor e
        seg = (
            jax.lax.broadcasted_iota(jnp.int32, (dw, deg), 0) // (d // 4)
            == jax.lax.broadcasted_iota(jnp.int32, (dw, deg), 1)
        ).astype(jnp.float32)

        def score_one(w, _):
            # fori_loop (not unroll) so the decode temporaries of the
            # ``width`` slots share one VMEM allocation — unrolled, the
            # kernel's scoped-VMEM stack overflows at G=128. The packed
            # rows ride FLATTENED to [G, width*W] so the dynamic slot
            # offset w*W is a 128-aligned LANE offset (dynamic sublane
            # indexing is unsupported in Mosaic).
            base = w * W
            words = pack_ref[:, pl.ds(base, a128(dw))][:, :dw]  # [G, dw]
            acc = jnp.zeros((G, dw), jnp.float32)
            for j in range(4):
                # 2-op sign-extending byte extract: left-align the byte,
                # arithmetic-shift back down
                b = (words << (24 - 8 * j)) >> 24
                acc = acc + (
                    b.astype(jnp.bfloat16) * qr[:, j, :]
                ).astype(jnp.float32)
            dots = jax.lax.dot_general(
                acc, seg,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                      # [G, deg]
            # load full 128-aligned regions, slice statically after
            idw = pack_ref[:, pl.ds(base + o_id, a128(deg))][:, :deg]
            if ip:
                cdw = -dots
            else:
                cdw = jax.lax.bitcast_convert_type(
                    pack_ref[:, pl.ds(base + o_norm, a128(deg))][:, :deg],
                    jnp.float32,
                ) - dots
            # expand the i32 first: a minor-dim insert on i1 vectors is
            # unsupported in Mosaic
            pokw = par_ref_in[pl.ds(w, 1), :]
            pok = pokw.T >= 0                      # [G, 1]
            cdw = jnp.where((idw < 0) | (~pok), jnp.inf, cdw)
            idw = jnp.where(pok, idw, _INVALID)
            cd_ref[pl.ds(w * deg, deg), :] = cdw.T
            ci_ref[pl.ds(w * deg, deg), :] = idw.T
            return _

        if _UNROLL_SCORE:
            for w in range(width):
                score_one(w, 0)
        else:
            jax.lax.fori_loop(0, width, score_one, 0)
        cd = cd_ref[...]
        ci = ci_ref[...]
        if emit_cands:
            # expose this iteration's scored candidates (filtered-search
            # side accumulation collects them outside the kernel)
            ocd_ref[...] = cd
            oci_ref[...] = ci

    LL = _next_pow2(L + C)
    pad = LL - L - C
    # pack (id << 1) | explored so the sort carries ONE payload; note
    # the -1 sentinel is itself (id=-1, explored) under this encoding
    kd_parts = [bd_ref[...], cd]
    kie_parts = [
        (bi_ref[...] << 1) | (be_ref[...] & 1),
        ci << 1,
    ]
    if pad:
        kd_parts.append(jnp.full((pad, G), jnp.inf, jnp.float32))
        kie_parts.append(jnp.full((pad, G), _INVALID, jnp.int32))
    kd = jnp.concatenate(kd_parts, axis=0)
    kie = jnp.concatenate(kie_parts, axis=0)

    kd, (kie,) = _sort_rows(kd, [kie], LL)
    kd, kie = _dedup_rows(kd, kie, window)
    kd, kie = kd[:L], kie[:L]
    parents, kie = _pick_rows(kd, kie, width)

    obd_ref[...] = kd
    obi_ref[...] = kie >> 1
    obe_ref[...] = kie & 1
    for j in range(width):
        par_ref[j, :] = parents[j]


@functools.partial(
    jax.jit,
    static_argnames=("deg", "d", "width", "window", "ip", "g", "interpret",
                     "emit_cands"),
)
def beam_merge_step(
    buf_d,          # [L, m] f32  (sorted, transposed)
    buf_i,          # [L, m] i32
    buf_e,          # [L, m] i32 explored flags
    qrep=None,      # [m, 4, deg*(d//4)] bf16 pre-scaled/permuted/tiled query
    pack=None,      # [m, width, W] i32 gathered packed neighbor rows
    parents=None,   # [width, m] i32 parents the rows were gathered for
    cand_d=None,    # [C, m] f32 pre-computed candidate distances
    cand_i=None,    # [C, m] i32 candidate ids (with cand_d)
    *,
    deg: int = 0,
    d: int = 0,
    width: int,
    window: int = 2,
    ip: bool = False,
    g: int = 128,
    interpret: bool = False,
    emit_cands: bool = False,
):
    """One fused beam-search step over transposed state.

    Either pass ``cand_d`` + ``cand_i`` (pre-scored candidates — used
    for seeding), or ``qrep`` + ``pack`` + ``parents``, in which case
    the packed rows are decoded and scored in-kernel (fold any dequant
    scale into ``qrep`` beforehand; invalid parents (< 0) mask their
    whole candidate block).

    Returns (buf_d, buf_i, buf_e, parents [width, m]); the output
    buffer is distance-sorted, deduplicated, truncated to L slots, with
    the picked parents marked explored. A query count off the ``g``
    lane tile is padded up with inert columns (empty buffer, invalid
    candidates/parents) and sliced back off the outputs — callers no
    longer need to pre-round m.

    ``emit_cands`` (packed-scoring mode only) additionally returns the
    iteration's raw scored candidates (cand_d [C, m] f32, cand_i
    [C, m] i32) so filtered search can side-accumulate valid results
    outside the kernel while traversal itself stays unfiltered.
    """
    L, m0 = buf_d.shape
    scored = cand_d is not None
    m = -(-m0 // g) * g
    if m != m0:
        # tail columns: empty explored buffer + invalid candidates (and
        # parents -1, which mask their whole candidate block in packed
        # mode), so pad lanes compute nothing and pick no parents
        pc = m - m0
        buf_d = jnp.pad(buf_d, ((0, 0), (0, pc)),
                        constant_values=jnp.inf)
        buf_i = jnp.pad(buf_i, ((0, 0), (0, pc)),
                        constant_values=_INVALID)
        buf_e = jnp.pad(buf_e, ((0, 0), (0, pc)), constant_values=1)
        if scored:
            cand_d = jnp.pad(cand_d, ((0, 0), (0, pc)),
                             constant_values=jnp.inf)
            cand_i = jnp.pad(cand_i, ((0, 0), (0, pc)),
                             constant_values=_INVALID)
        else:
            qrep = jnp.pad(qrep, ((0, pc), (0, 0), (0, 0)))
            pack = jnp.pad(pack, ((0, pc), (0, 0), (0, 0)))
            parents = jnp.pad(parents, ((0, 0), (0, pc)),
                              constant_values=_INVALID)
    nsteps = m // g

    col = lambda i: (0, i)
    inputs = [buf_d, buf_i, buf_e]
    in_specs = [pl.BlockSpec((L, g), col) for _ in range(3)]
    if scored:
        C = cand_i.shape[0]
        inputs += [cand_d, cand_i]
        in_specs += [pl.BlockSpec((C, g), col), pl.BlockSpec((C, g), col)]
        dd = 0
    else:
        if d % 4:
            raise ValueError(f"packed scoring needs d % 4 == 0, got {d}")
        W = pack.shape[2]
        if W % 128:
            raise ValueError(f"packed row width must be 128-aligned, got {W}")
        dwq = qrep.shape[2]
        inputs += [qrep, pack.reshape(m, width * W), parents]
        in_specs += [
            # (g, 4, dwq): the 4-row byte-lane query replication. The
            # old literal-GL006 screen needed a suppression here; the
            # graft-kern computed audit proves the spec legal — sublane
            # dim 4 EQUALS the array dim (the real Mosaic rule), so no
            # relayout and no exception needed (r6)
            pl.BlockSpec((g, 4, dwq), lambda i: (i, 0, 0)),
            pl.BlockSpec((g, width * W), lambda i: (i, 0)),
            pl.BlockSpec((width, g), col),
        ]
        dd = d

    emit = emit_cands and not scored
    kernel = functools.partial(
        _beam_step_kernel,
        L=L, deg=deg, d=dd, width=width, window=window, ip=ip,
        scored=scored, emit_cands=emit,
    )
    scratch = []
    if not scored:
        C = width * deg
        scratch = [
            pltpu.VMEM((C, g), jnp.float32),
            pltpu.VMEM((C, g), jnp.int32),
        ]
    out_specs = [
        pl.BlockSpec((L, g), col),
        pl.BlockSpec((L, g), col),
        pl.BlockSpec((L, g), col),
        pl.BlockSpec((width, g), col),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((L, m), jnp.float32),
        jax.ShapeDtypeStruct((L, m), jnp.int32),
        jax.ShapeDtypeStruct((L, m), jnp.int32),
        jax.ShapeDtypeStruct((width, m), jnp.int32),
    ]
    if emit:
        C = width * deg
        out_specs += [pl.BlockSpec((C, g), col), pl.BlockSpec((C, g), col)]
        out_shape += [
            jax.ShapeDtypeStruct((C, m), jnp.float32),
            jax.ShapeDtypeStruct((C, m), jnp.int32),
        ]
    outs = pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=in_specs,
        scratch_shapes=scratch,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*inputs)
    if m != m0:
        outs = tuple(o[:, :m0] for o in outs)
    return outs


# ---------------------------------------------------------------------------
# kernel contract (graft-kern; docs/static_analysis.md §engine-4)
# ---------------------------------------------------------------------------

from raft_tpu.analysis.contracts import kernel_contract  # noqa: E402


def _beam_case_derive(case: dict) -> dict:
    case.setdefault("g", 128)
    case.setdefault("m", case["g"])
    case.setdefault("width", 4)
    case.setdefault("window", 2)
    case.setdefault("ip", False)
    case.setdefault("emit_cands", False)
    if case.get("scored", True):
        case.setdefault("C", 32)
        case["cand_d"] = case["cand_i"] = True
        case["qrep"] = case["pack"] = case["parents"] = False
        case.setdefault("deg", 0)
        case.setdefault("d", 0)
    else:
        case.setdefault("deg", 16)
        case.setdefault("d", 32)
        case["C"] = case["width"] * case["deg"]
        case["W"] = packed_row_layout(case["deg"], case["d"],
                                      case["ip"])[3]
        case["dwq"] = case["deg"] * (case["d"] // 4)
        case["qrep"] = case["pack"] = case["parents"] = True
        case["cand_d"] = case["cand_i"] = False
        case["qrep_dtype"] = "bfloat16"
        case["pack_dtype"] = "int32"
    return case


from raft_tpu.tuning import BEAM_STEP_TILES  # noqa: E402

kernel_contract(
    "beam_step",
    module=__name__,
    entry="beam_merge_step",
    driver="raft_tpu.analysis.contract_drivers:drive_beam_step",
    tail_rows="padded",          # m % g pads inert lanes, sliced off
    k_range=(1, 1),
    k_key=None,                  # no k: the buffer length L is static
    dtypes=("float32",),
    exactness="bitwise",
    base={"L": 16, "m": 128, "g": 128},
    arms=(),
    arrays={"buf_d": ("L", "m"), "buf_i": ("L", "m"), "buf_e": ("L", "m"),
            "cand_d": ("C", "m"), "cand_i": ("C", "m"),
            "qrep": ("m", 4, "dwq"), "pack": ("m", "width", "W"),
            "parents": ("width", "m")},
    derive=_beam_case_derive,
    extra_cases=tuple(
        [
            # scored arm: merge/dedup/pick pipeline vs the numpy oracle
            {"scored": True, "L": 16, "C": 32, "m": 128, "width": 4},
            {"scored": True, "L": 8, "C": 8, "m": 128, "width": 2},
            {"scored": True, "L": 16, "C": 32, "m": 256, "width": 4,
             "window": 3},
            # non-pow2 buffer + candidate counts: LL pads internally
            {"scored": True, "L": 12, "C": 20, "m": 128, "width": 3},
            # tail rows: m off the lane tile pads inert columns
            {"scored": True, "L": 16, "C": 32, "m": 100, "width": 4},
            # k/degree boundary cases: one candidate, one parent; a
            # tiny buffer against a wide candidate block
            {"scored": True, "L": 16, "C": 1, "m": 128, "width": 1},
            {"scored": True, "L": 2, "C": 24, "m": 128, "width": 2,
             "window": 1},
            # packed-scoring arm, DRIVEN: in-kernel int8 word decode +
            # scoring vs the same arithmetic through XLA, then the
            # merge oracle (judged per-id within bf16 rounding)
            {"scored": False, "deg": 8, "d": 32, "L": 16, "m": 128,
             "width": 2},
            {"scored": False, "deg": 8, "d": 32, "L": 8, "m": 128,
             "width": 3, "ip": True},
            {"scored": False, "deg": 16, "d": 64, "L": 16, "m": 128,
             "width": 4, "emit_cands": True},
            # packed arm, tail rows: padded parents mask their blocks
            {"scored": False, "deg": 8, "d": 32, "L": 16, "m": 90,
             "width": 2},
            # deg/d geometry boundaries (static bindings): minimal
            # packed row (every region one 128-pad), and a wide row
            # where the id region crosses its own 128 boundary
            {"scored": False, "deg": 4, "d": 4, "L": 16, "m": 128,
             "width": 4, "static_only": True},
            {"scored": False, "deg": 32, "d": 64, "L": 32, "m": 256,
             "width": 4, "static_only": True},
        ]
        + [
            # every dispatchable query tile (op key beam_step_tile;
            # winner strings carry g) gets a geometry case, so the
            # static audit covers each injectable lane tile
            {"scored": False, "deg": 16, "d": 32, "L": 64, "m": 2 * g,
             "g": g, "width": 4, "static_only": True}
            for g in BEAM_STEP_TILES
        ]
        + [
            {"scored": True, "L": 16, "C": 32, "m": 2 * g, "g": g,
             "width": 4}
            for g in BEAM_STEP_TILES
        ]
    ),
    notes="all per-query state rides TRANSPOSED [slots, m] so the sort "
          "axis is the sublane axis; m off the g lane tile is padded "
          "with inert columns and sliced back (tail_rows='padded'); "
          "the packed arm's int8 word decode is driven against the "
          "same arithmetic through XLA (bf16-rounded products, f32 "
          "accumulation).",
)
