"""Fused IVF list-scan + top-k Pallas kernel.

TPU-native analog of the reference's fused interleaved-scan kernel
(cpp/include/raft/neighbors/detail/ivf_flat_interleaved_scan-inl.cuh:663):
one grid step scans one bucketized (query-group x list) pair — the list
block is DMA'd from HBM by a scalar-prefetch index map (no gather
materialization), distances come off the MXU into VMEM, and the per-list
top-k is extracted on-chip, so the [group x cap] distance tile never
touches HBM. The reference's warp-queue (select_warpsort.cuh:100) becomes
a k-pass vectorized min-extraction; its approx mode mirrors
lax.approx_min_k's lane-binning (one candidate per 128-lane bin, then
extract from bins — collision loss ~C(k,2)/128 per list).

The kernel resolves stored ids in-kernel: the list's id row is DMA'd
alongside the block and the extraction emits global ids directly (the
argmin's position-select runs on the id row instead of a column iota).
Returning positions instead and mapping them outside costs a
[nb, G, k]-element take_along_axis — per-element gathers that measured
~10x the whole kernel's runtime at SIFT-1M scale.

Inputs are produced by ``ivf_flat.bucketize_pairs``: ``bucket_list`` maps
grid step -> list id, ``qv`` holds the pre-gathered query group per step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# metric_kind values (static kernel variants)
L2 = 0        # dist = ||q||^2 + ||x||^2 - 2 q.x   (needs norms + qaux=||q||^2)
IP = 1        # dist = -q.x  (caller negates back; select-min internally)
COSINE = 2    # dist = 1 - q.x / (||q|| ||x||)     (needs norms=||x||^2, qaux=||q||)

# id emitted for invalid (inf-distance) slots; matches the library-wide
# "-1 = no neighbor" contract
_INVALID = -1

# the per-list recall budget binned eligibility is judged against when
# the caller does not say (ivf_flat/ivf_pq SearchParams default)
DEFAULT_RECALL_TARGET = 0.95


def binned_loss_fits(k: int,
                     recall_target: float = DEFAULT_RECALL_TARGET) -> bool:
    """THE single home for the single-slot binning loss model: one
    candidate per lane-bin loses a true top-k entry whenever a better
    one shares its bin — expected lost FRACTION ~ (k-1)/256
    (C(k,2)/128 colliding pairs over k entries). Consumed by the entry
    point's eligibility, the kernel contract's sweep filter, and the
    microbench candidate set, so the three can never drift apart
    (review fix, r6). ``recall_target <= 0`` always fits (forcing)."""
    rt = float(recall_target)
    return rt <= 0.0 or (k - 1) / 256.0 <= max(0.0, 1.0 - rt)


def binned_k_cap(recall_target: float = DEFAULT_RECALL_TARGET) -> int:
    """Largest k the loss model admits at ``recall_target`` (<= the
    structural 64-candidate extraction cap)."""
    k = 64
    while k > 1 and not binned_loss_fits(k, recall_target):
        k -= 1
    return k


def _extract_topk(dist, ids_row, k: int, outd_ref, outi_ref):
    """k-pass min extraction over [G, cap]; emits [G, k] dists + ids."""
    G, cap = dist.shape
    big = jnp.int32(jnp.iinfo(jnp.int32).max)
    col = jax.lax.broadcasted_iota(jnp.int32, (G, cap), 1)
    # one output column per pass — accumulating all k vectors and stacking
    # at the end measured 145 MB of register spill slots at k=130
    for j in range(k):
        m = jnp.min(dist, axis=1)                              # [G]
        eq = dist == m[:, None]
        pos = jnp.min(jnp.where(eq, col, cap), axis=1)         # [G]
        sel = jnp.where(col == pos[:, None], ids_row[None, :], big)
        idv = jnp.min(sel, axis=1)
        outd_ref[0, :, j] = m
        outi_ref[0, :, j] = jnp.where(jnp.isinf(m), _INVALID, idv)
        if j + 1 < k:
            dist = jnp.where(col == pos[:, None], jnp.inf, dist)


def _extract_topk_binned(dist, ids_row, k: int, cap: int, outd_ref, outi_ref):
    """Lane-binned approximate extraction: fold [G, cap] into 128 bins
    (bin b holds min over columns == b mod 128), then extract k from the
    bins. One top-k candidate is lost per same-bin collision among the
    true top-k (expected C(k,2)/128 per list)."""
    G = dist.shape[0]
    nch = cap // 128
    lane = jax.lax.broadcasted_iota(jnp.int32, (G, 128), 1)
    big = jnp.int32(jnp.iinfo(jnp.int32).max)
    binmin = jnp.full((G, 128), jnp.inf, jnp.float32)
    binid = jnp.full((G, 128), _INVALID, jnp.int32)
    binpos = jnp.zeros((G, 128), jnp.int32)
    for c in range(nch):
        chunk = dist[:, c * 128:(c + 1) * 128]
        ids_c = ids_row[c * 128:(c + 1) * 128]
        better = chunk < binmin
        binmin = jnp.where(better, chunk, binmin)
        binid = jnp.where(better, ids_c[None, :], binid)
        binpos = jnp.where(better, lane + c * 128, binpos)
    for j in range(k):
        m = jnp.min(binmin, axis=1)
        eq = binmin == m[:, None]
        pos = jnp.min(jnp.where(eq, binpos, cap), axis=1)
        # eq guard: untouched bins share binpos=0, so a bare binpos==pos
        # match would sweep them in (emitting their -1 id) whenever the
        # winner sits at column 0
        hit = eq & (binpos == pos[:, None])
        idv = jnp.min(jnp.where(hit, binid, big), axis=1)
        outd_ref[0, :, j] = m
        outi_ref[0, :, j] = jnp.where(jnp.isinf(m), _INVALID, idv)
        if j + 1 < k:
            binmin = jnp.where(hit, jnp.inf, binmin)


def _extract_topk_binned_deep(dist, ids_row, k: int, cap: int,
                              outd_ref, outi_ref, R: int = 4):
    """R-deep lane binning for 64 < k <= 256 (the warpsort-analog large-k
    path, select_warpsort.cuh:100): each of the 128 lanes keeps its R
    smallest candidates as a sorted per-lane stack (a compare-swap
    cascade per chunk), giving R*128 survivors; k are then extracted.
    A true top-k entry is lost only when > R of the top-k share a lane:
    expected C(k, R+1)/128^R items (k=130, R=4: ~1% of the list's
    contribution, recovered by the cross-probe merge)."""
    G = dist.shape[0]
    nch = cap // 128
    big = jnp.int32(jnp.iinfo(jnp.int32).max)
    lane = jax.lax.broadcasted_iota(jnp.int32, (G, 128), 1)
    stack_d = [jnp.full((G, 128), jnp.inf, jnp.float32) for _ in range(R)]
    stack_i = [jnp.full((G, 128), _INVALID, jnp.int32) for _ in range(R)]
    for c in range(nch):
        nd = dist[:, c * 128:(c + 1) * 128]
        ids_c = ids_row[c * 128:(c + 1) * 128]      # basic slice, then
        ni = jnp.broadcast_to(ids_c[None, :], (G, 128))  # expand (no gather)
        for r in range(R):
            swap = nd < stack_d[r]
            sd, si = stack_d[r], stack_i[r]
            stack_d[r] = jnp.where(swap, nd, sd)
            stack_i[r] = jnp.where(swap, ni, si)
            nd = jnp.where(swap, sd, nd)
            ni = jnp.where(swap, si, ni)
    for j in range(k):
        m4 = stack_d[0]
        for r in range(1, R):
            m4 = jnp.minimum(m4, stack_d[r])
        m = jnp.min(m4, axis=1)                            # [G]
        pos = jnp.min(jnp.where(m4 == m[:, None], lane, 128), axis=1)
        taken = jnp.zeros((G, 128), jnp.bool_)
        idv = jnp.full((G,), big, jnp.int32)
        for r in range(R):
            hit = ((stack_d[r] == m[:, None]) & (lane == pos[:, None])
                   & (~taken))
            idv = jnp.minimum(
                idv, jnp.min(jnp.where(hit, stack_i[r], big), axis=1)
            )
            stack_d[r] = jnp.where(hit, jnp.inf, stack_d[r])
            taken = taken | hit
        outd_ref[0, :, j] = m
        outi_ref[0, :, j] = jnp.where(jnp.isinf(m), _INVALID, idv)


def _extract_fold(dist, ids_row, cap: int, outd_ref, outi_ref, R: int):
    """Fused-reduction variant (TPU-KNN's PartialReduce): R-deep
    per-lane stacks like ``_extract_topk_binned_deep``'s fold phase, but
    the R*128 survivors are emitted UNEXTRACTED — no k-pass loop at all;
    the final selection happens in the caller's exact cross-probe merge
    (the hierarchical select_k rung's home turf). The fold core and the
    R sizing live in ops.fused_topk (one home for both kernels). Loss
    profile matches binned_deep's fold: a true top-k entry is lost only
    when > R of the list's top-k share a lane."""
    from raft_tpu.ops.fused_topk import fold_lane_stacks

    G = dist.shape[0]
    ids = jnp.broadcast_to(ids_row[None, :], (G, cap))
    stack_d, stack_i = fold_lane_stacks(dist, ids, R)
    for r in range(R):
        outd_ref[0, :, r * 128:(r + 1) * 128] = stack_d[r]
        outi_ref[0, :, r * 128:(r + 1) * 128] = jnp.where(
            jnp.isinf(stack_d[r]), _INVALID, stack_i[r])


def _fold_depth(k: int) -> int:
    """Lane-stack depth R for the fold arm — delegates to the single
    sizing rule in ops.fused_topk.fold_depth (R = ceil(k/64), floor 2;
    rationale there)."""
    from raft_tpu.ops.fused_topk import fold_depth

    return fold_depth(k)


def _scan_kernel(
    bl_ref, ls_ref, *refs,
    k: int, metric_kind: int, extract: str, has_norms: bool,
    has_filter: bool, packed_i4: bool = False, packed_pq4: bool = False,
    packed_bits: bool = False, has_row_scale: bool = False,
):
    refs = list(refs)
    storage_ref = refs.pop(0)
    ids_ref = refs.pop(0)
    norms_ref = refs.pop(0) if has_norms else None
    keep_ref = refs.pop(0) if has_filter else None
    rs_ref = refs.pop(0) if has_row_scale else None
    qv_ref = refs.pop(0)
    w_ref = refs.pop(0) if packed_pq4 else None
    qaux_ref = refs.pop(0) if metric_kind != IP else None
    if packed_i4 or packed_pq4 or packed_bits:
        outd_ref, outi_ref, recon_ref = refs
    else:
        outd_ref, outi_ref = refs

    i = pl.program_id(0)
    size = ls_ref[bl_ref[i]]
    qv = qv_ref[0]                                      # [G, d] mm dtype
    if packed_pq4:
        # packed 4-bit PQ CODES [nw, cap] u32 (8 codes/word, transposed
        # like the i4 cache) scored as a 16-pass one-hot MXU contraction —
        # the TPU answer to the reference's in-kernel shm-LUT code scoring
        # (ivf_pq_compute_similarity-inl.cuh:164-185): TPUs have no
        # per-lane LUT gather, but "which codes equal v" is a VPU compare
        # and "sum LUT[s, v] over matching (s, x)" is a matmul. Pass v:
        #   lut_v[G, s] = qv[G, rot] @ W[v][rot, s]   (block-diag codebook)
        #   dots      += lut_v @ (codes == v)         ([G,p] x [p,cap])
        # Exact PQ distances (no quantization beyond the codes), at 2x
        # fewer HBM bytes than the i8 cache and 16x its MXU work — the
        # high-compression regime trade (see tuning.md ladder).
        blk_w = storage_ref[0].astype(jnp.int32)        # [nw, cap]
        nw = blk_w.shape[0]
        p = w_ref.shape[2]
        for wi in range(nw):
            word = blk_w[wi, :]                          # [cap] i32
            for j in range(8):
                recon_ref[wi * 8 + j, :] = (word >> (4 * j)) & 0xF
        codes_blk = recon_ref[0:p, :]                    # [p, cap] i32
        G = qv.shape[0]
        cap = codes_blk.shape[1]
        dots = jnp.zeros((G, cap), jnp.float32)
        for v in range(16):
            lut_v = jax.lax.dot_general(
                qv, w_ref[v],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                            # [G, p]
            mask_v = (codes_blk == v).astype(qv.dtype)   # [p, cap]
            dots = dots + jax.lax.dot_general(
                lut_v.astype(qv.dtype), mask_v,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
    elif packed_bits:
        # RaBitQ sign-bit block [nw, cap] uint32 (32 sign bits per lane
        # word, transposed like the i4 cache: components on sublanes,
        # rows on lanes). The asymmetric estimator's hot loop is
        # XOR+popcount-shaped — <x̄, q> over ±1 codes — phrased for the
        # MXU: a 2-op VPU decode ((w >> j) & 1 -> 2b-1 ∈ {-1, +1}) into
        # the [d, cap] scratch, then ONE matmul S = qv @ signs. The
        # per-row correction scalar fac = ||r||²/||r||₁ (row_scale) is
        # applied AFTER the matmul (per stored row — it cannot fold into
        # the query side), giving the unbiased dot estimate fac·S; the
        # norm term reads the TRUE ||r||² from ``norms``
        # (docs/kernels.md §rabitq). Pad dims (d -> nw*32) decode to -1
        # but the caller zero-pads qv there, so they contribute nothing.
        blk_w = storage_ref[0].astype(jnp.int32)        # [nw, cap]
        nw = blk_w.shape[0]
        for wi in range(nw):
            word = blk_w[wi, :]                          # [cap] i32
            for j in range(32):
                bit = (word >> j) & 1
                recon_ref[wi * 32 + j, :] = (2 * bit - 1).astype(qv.dtype)
        dots = jax.lax.dot_general(
            qv, recon_ref[...],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # [G, cap]
    elif packed_i4:
        # packed int4 block [nw, cap] uint32 (transposed: components on
        # sublanes, rows on lanes — the Mosaic-dense layout for narrow
        # per-row payloads). Unpack 8 signed nibbles per word with the
        # 2-op sign-extending decode ((w << s) >> 28) and write component
        # rows into the [d, cap] VMEM scratch; one MXU matmul then scores
        # the whole block. Per-component dequant scales are folded into
        # ``qv`` by the caller, so decoded values stay the raw [-8, 7]
        # integers (exact in bf16).
        blk_w = storage_ref[0].astype(jnp.int32)        # [nw, cap]
        nw = blk_w.shape[0]
        for wi in range(nw):
            word = blk_w[wi, :]                          # [cap] i32
            for j in range(8):
                vals = (word << (28 - 4 * j)) >> 28      # [-8, 7]
                recon_ref[wi * 8 + j, :] = vals.astype(qv.dtype)
        dots = jax.lax.dot_general(
            qv, recon_ref[...],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # [G, cap]
    else:
        blk = storage_ref[0].astype(qv.dtype)           # [cap, d]
        dots = jax.lax.dot_general(
            qv, blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # [G, cap]
    G, cap = dots.shape
    if has_row_scale:
        # per-row estimator correction (rabitq): dots -> fac * dots
        dots = dots * rs_ref[0, 0][None, :]
    if metric_kind == L2:
        dist = jnp.maximum(
            qaux_ref[0, 0][:, None] + norms_ref[0, 0][None, :] - 2.0 * dots,
            0.0,
        )
    elif metric_kind == IP:
        dist = -dots
    else:  # COSINE
        plen = jnp.sqrt(jnp.maximum(norms_ref[0, 0], 1e-30))
        dist = 1.0 - dots / jnp.maximum(
            qaux_ref[0, 0][:, None] * plen[None, :], 1e-30
        )
    col = jax.lax.broadcasted_iota(jnp.int32, (G, cap), 1)
    valid = col < size
    if has_filter:
        valid = valid & (keep_ref[0, 0][None, :] > 0)
    dist = jnp.where(valid, dist, jnp.inf)
    ids_row = ids_ref[0, 0]                             # [cap] int32
    if extract == "binned":
        _extract_topk_binned(dist, ids_row, k, cap, outd_ref, outi_ref)
    elif extract == "binned_deep":
        _extract_topk_binned_deep(dist, ids_row, k, cap, outd_ref, outi_ref)
    elif extract == "fold":
        _extract_fold(dist, ids_row, cap, outd_ref, outi_ref,
                      _fold_depth(k))
    else:
        _extract_topk(dist, ids_row, k, outd_ref, outi_ref)


def fused_list_scan_topk(
    storage,        # [C, cap, d] source dtype | [C, d//8, cap] u32 (packed_i4)
    indices,        # [C, cap] int32 stored global ids
    list_sizes,     # [C] int32
    bucket_list,    # [nb] int32
    qv,             # [nb, G, d] bf16 (pre-gathered query groups)
    qaux=None,      # [nb, G] f32: ||q||^2 (L2) or ||q|| (cosine); None for IP
    norms=None,     # [C, cap] f32: ||x||^2; None for IP
    keep=None,      # [C, cap] int32 filter keep-mask; None = no filter
    lut_weights=None,  # [16, rot, p] block-diag codebook (pq4 code scan)
    row_scale=None,    # [C, cap] f32 per-row dot scale (rabitq fac)
    *,
    k: int,
    metric_kind: int,
    approx: bool = True,
    recall_target: float = 0.95,
    interpret: bool = False,
    packed_i4: bool = False,
    packed_bits: bool = False,
    extract: str = None,
):
    """Scan each bucket's list block against its query group and return the
    per-pair top-k in min-space.

    Returns (out_d [nb, G, kc] f32, out_i [nb, G, kc] int32) where out_i
    holds the stored *global ids* (resolved in-kernel). ``kc == k`` for
    the extracting arms; the ``fold`` arm (fused partial reduction —
    per-lane R-deep stacks emitted unextracted, TPU-KNN's PartialReduce)
    returns the WIDER ``kc = R*128`` candidate buffer and defers
    selection to the caller's exact cross-probe merge — callers must
    read the candidate width off the returned shape. For IP the
    distances are negated scores — negate back after the merge. Invalid
    tail entries (list shorter than k after filtering) come back as
    (+inf, -1) — mask on either.

    ``packed_i4``: storage holds signed int4 components packed 8-per-u32,
    TRANSPOSED to [C, d//8, cap] so blocks are Mosaic-dense (components on
    sublanes, rows on lanes) — the in-kernel-decode PQ scan (reference
    ivf_pq_compute_similarity-inl.cuh scores compressed codes in-registers;
    here the compressed form is the int4 reconstruction and the decode is
    a shift/mask VPU prologue feeding one MXU matmul). Per-component
    dequant scales must be pre-folded into ``qv`` (and ``norms`` hold the
    dequantized-vector norms), so the kernel itself is scale-free.

    ``packed_bits`` (the rabitq arm): storage holds 1-bit sign codes of
    the rotated residuals packed 32-per-u32, TRANSPOSED to
    [C, ceil(d/32), cap]; ``row_scale`` must carry the per-row RaBitQ
    correction fac = ||r||²/||r||₁ (applied to the dots after the MXU
    pass — the unbiased estimator <q, r> ≈ fac·Σ±q_j) and ``norms`` the
    TRUE residual norms ||r||². Queries must be zero-padded to the
    word-padded width ceil(d/32)*32 so pad bits score nothing. ~32×
    compressed vs f32 — the cheap first stage of the multi-stage rerank
    pipeline (ivf_pq.search_refined).

    ``lut_weights`` (mutually exclusive with ``packed_i4``): storage holds
    packed 4-bit PQ CODES [C, p//8, cap] u32 and scoring runs the 16-pass
    one-hot contraction against the block-diagonal codebook weights
    W[v][s*pq_len + l, s] = pq_centers[s, v, l]; ``qv`` is the raw rotated
    query (residual) group [nb, G, rot] and ``norms`` the exact
    reconstruction norms. Distances equal the decode-then-matmul path's
    exactly (same codes, same codebook).
    """
    # Extraction variant: the exact k-pass min sweep vs the lane-binned
    # approximations (k <= 64 single-slot, k <= 256 R-deep) vs the fold
    # arm (k <= 256, no in-kernel extraction at all — the R*128-wide
    # candidate buffer goes to the caller's merge). Eligibility
    # is structural (approx opt-in, lane-aligned cap); within the
    # eligible set the winner comes from the per-backend dispatch table
    # ("ivf_scan_extract", captured by microbench.bench_scan_extract),
    # analytic fallback = binned whenever legal (the k-pass sweep's
    # unrolled extraction is the known slow arm). Resolved HERE, outside
    # the jit boundary, so the choice participates in the jit cache key
    # and mode/table changes take effect per call. An explicit
    # ``extract`` bypasses the table (the microbench forcing each arm).
    from raft_tpu import obs, tuning

    cap = (storage.shape[2]
           if (packed_i4 or packed_bits or lut_weights is not None)
           else storage.shape[1])
    binned_ok = approx and cap % 128 == 0 and cap > 128
    # single-slot binning is only eligible when its collision-loss
    # model fits the caller's per-list recall budget (binned_loss_fits
    # above) — the old flat k <= 64 cap admitted ~25% loss at k=64,
    # caught by the kernel-contract sweep's lane-boundary cases (r6,
    # tests/test_kernel_contracts.py)
    eligible = ["exact"]
    if binned_ok and k <= 64 and binned_loss_fits(k, recall_target):
        eligible.append("binned")
    if binned_ok and k <= 256:
        eligible.append("binned_deep")
        eligible.append("fold")
    if extract is None:
        analytic = ("binned" if "binned" in eligible
                    else "binned_deep" if binned_ok and k <= 256
                    else "exact")
        extract = tuning.choose(
            "ivf_scan_extract",
            {"cap": int(cap), "k": int(k), "g": int(qv.shape[1])},
            eligible, analytic,
        )
    elif extract not in eligible:
        raise ValueError(
            f"extract={extract!r} not eligible here (allowed: {eligible})")
    # trace-time span (the kernel runs under the callers' jits):
    # attributes compile cost per extraction arm, silent when cached
    with obs.span("fused_list_scan_topk", extract=extract, cap=int(cap),
                  k=int(k), nb=int(bucket_list.shape[0])):
        return _fused_list_scan_topk(
            storage, indices, list_sizes, bucket_list, qv, qaux, norms,
            keep, lut_weights, row_scale, k=k, metric_kind=metric_kind,
            interpret=interpret, packed_i4=packed_i4,
            packed_bits=packed_bits, extract=extract,
        )


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric_kind", "interpret", "packed_i4",
                     "packed_bits", "extract"),
)
def _fused_list_scan_topk(
    storage, indices, list_sizes, bucket_list, qv, qaux=None, norms=None,
    keep=None, lut_weights=None, row_scale=None, *,
    k: int, metric_kind: int, interpret: bool = False,
    packed_i4: bool = False, packed_bits: bool = False,
    extract: str = "exact",
):
    packed_pq4 = lut_weights is not None
    if packed_pq4 and packed_i4:
        raise ValueError("packed_i4 and lut_weights are mutually exclusive")
    if packed_bits and (packed_i4 or packed_pq4):
        raise ValueError(
            "packed_bits is mutually exclusive with packed_i4/lut_weights")
    if packed_i4:
        C, nw_c, cap = storage.shape
        d = nw_c * 8
    elif packed_bits:
        C, nw_c, cap = storage.shape
        d = nw_c * 32
    elif packed_pq4:
        C, nw_c, cap = storage.shape
        d = lut_weights.shape[1]                       # rot_dim
        p_sub = lut_weights.shape[2]
        if p_sub > nw_c * 8:
            raise ValueError(
                f"lut_weights pq_dim {p_sub} exceeds packed capacity "
                f"{nw_c * 8}")
    else:
        C, cap, d = storage.shape
    nb, G, _ = qv.shape
    has_norms = norms is not None
    has_filter = keep is not None
    has_row_scale = row_scale is not None

    # 2-D per-row arrays are lifted to [*, 1, n] so each block equals the
    # full trailing dims (the Mosaic block rule: last two dims divisible by
    # (8, 128) or equal to the array's)
    inputs = [storage, indices.reshape(C, 1, cap)]
    in_specs = [
        pl.BlockSpec(
            (1, nw_c, cap) if (packed_i4 or packed_pq4 or packed_bits)
            else (1, cap, d),
            lambda i, bl, ls: (bl[i], 0, 0),
        ),
        pl.BlockSpec((1, 1, cap), lambda i, bl, ls: (bl[i], 0, 0)),
    ]
    if has_norms:
        inputs.append(norms.reshape(C, 1, cap))
        in_specs.append(
            pl.BlockSpec((1, 1, cap), lambda i, bl, ls: (bl[i], 0, 0))
        )
    if has_filter:
        inputs.append(keep.reshape(C, 1, cap))
        in_specs.append(
            pl.BlockSpec((1, 1, cap), lambda i, bl, ls: (bl[i], 0, 0))
        )
    if has_row_scale:
        inputs.append(row_scale.reshape(C, 1, cap))
        in_specs.append(
            pl.BlockSpec((1, 1, cap), lambda i, bl, ls: (bl[i], 0, 0))
        )
    inputs.append(qv)
    in_specs.append(pl.BlockSpec((1, G, d), lambda i, bl, ls: (i, 0, 0)))
    if packed_pq4:
        # full codebook weights resident per step (small: 16*rot*p)
        inputs.append(lut_weights.astype(qv.dtype))
        in_specs.append(
            pl.BlockSpec(lut_weights.shape, lambda i, bl, ls: (0, 0, 0))
        )
    if metric_kind != IP:
        inputs.append(qaux.reshape(nb, 1, G))
        in_specs.append(
            pl.BlockSpec((1, 1, G), lambda i, bl, ls: (i, 0, 0))
        )

    kernel = functools.partial(
        _scan_kernel,
        k=k, metric_kind=metric_kind, extract=extract,
        has_norms=has_norms, has_filter=has_filter, packed_i4=packed_i4,
        packed_pq4=packed_pq4, packed_bits=packed_bits,
        has_row_scale=has_row_scale,
    )
    # candidate width: the extracting arms emit k columns; the fold arm
    # emits its full R*128 lane-stack buffer (selection deferred)
    kc = 128 * _fold_depth(k) if extract == "fold" else k
    out_d, out_i = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(nb,),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, G, kc), lambda i, bl, ls: (i, 0, 0)),
                pl.BlockSpec((1, G, kc), lambda i, bl, ls: (i, 0, 0)),
            ],
            scratch_shapes=(
                [pltpu.VMEM((d, cap), qv.dtype)]
                if (packed_i4 or packed_bits)
                else [pltpu.VMEM((nw_c * 8, cap), jnp.int32)] if packed_pq4
                else []
            ),
        ),
        out_shape=[
            jax.ShapeDtypeStruct((nb, G, kc), jnp.float32),
            jax.ShapeDtypeStruct((nb, G, kc), jnp.int32),
        ],
        interpret=interpret,
    )(bucket_list, list_sizes, *inputs)
    return out_d, out_i


# ---------------------------------------------------------------------------
# kernel contract (graft-kern; docs/static_analysis.md §engine-4)
# ---------------------------------------------------------------------------

from raft_tpu.analysis.contracts import kernel_contract  # noqa: E402


def _scan_case_derive(case: dict) -> dict:
    case.setdefault("C", 4)
    case.setdefault("G", 8)
    case.setdefault("nb", 4)
    case.setdefault("d", 32)
    case.setdefault("metric_kind", L2)
    has_norms = case["metric_kind"] != IP
    case.setdefault("norms", has_norms)
    case.setdefault("qaux", has_norms)
    case.setdefault("keep", False)
    if case.get("packed_i4"):
        case["nw_c"] = case["d"] // 8
        case["storage_shape"] = ("C", "nw_c", "cap")
        case["storage_dtype"] = "uint32"
        case["lut_weights"] = False
    elif case.get("rabitq"):
        # 1-bit sign codes: 32/word, last word PARTIAL when d % 32 != 0
        # (pad bits decode -1; queries are zero-padded to dp = nw*32)
        case["nw_c"] = -(-case["d"] // 32)
        case["dp"] = case["nw_c"] * 32
        case["storage_shape"] = ("C", "nw_c", "cap")
        case["storage_dtype"] = "uint32"
        case["qv_shape"] = ("nb", "G", "dp")
        case["packed_bits"] = True
        case["row_scale"] = True
        case["row_scale_dtype"] = "float32"
        case["lut_weights"] = False
    elif case.get("pq4"):
        case["nw_c"] = case.setdefault("p", case["d"] // 4) // 8 or 1
        case.setdefault("rot", case["d"])
        case["storage_shape"] = ("C", "nw_c", "cap")
        case["storage_dtype"] = "uint32"
        case["lut_weights"] = True
    else:
        case["storage_shape"] = ("C", "cap", "d")
        case["lut_weights"] = False
    return case


def _scan_case_ok(case: dict) -> bool:
    cap, k, ex = case.get("cap", 0), case.get("k", 1), case["extract"]
    if not 0 < k:
        return False
    if ex == "exact":
        # cap the unrolled k-pass sweep: the dispatch layer hands
        # k > 64 to the binned/fold arms anyway, and a 200-pass unroll
        # makes the interpret sweep minutes-long
        return k <= 32
    if cap % 128 != 0 or cap <= 128:
        return False
    if ex == "binned":
        # the entry point's own loss model at the default target — no
        # hand-mirrored constant to drift (review fix, r6)
        return binned_loss_fits(k)
    return k <= 256


kernel_contract(
    "ivf_scan",
    module=__name__,
    entry="fused_list_scan_topk",
    driver="raft_tpu.analysis.contract_drivers:drive_list_scan",
    tail_rows="masked",          # col >= size masked to +inf in-kernel
    k_range=(1, 256),
    dtypes=("float32", "bfloat16"),
    exactness="bitwise",
    recall_floor=0.93,           # the tpu_parity binned band
    base={"cap": 256, "C": 4, "G": 8, "nb": 4, "d": 32,
          "metric_kind": L2},
    rows_key="cap", batch_key="G",
    arms=({"extract": "exact", "k_max": 32},
          {"extract": "binned", "k_max": binned_k_cap()},
          {"extract": "binned_deep", "k_max": 65},
          {"extract": "fold", "k_max": 256}),
    arrays={"storage": ("C", "cap", "d"), "indices": ("C", "cap"),
            "list_sizes": ("C",), "bucket_list": ("nb",),
            "qv": ("nb", "G", "d"), "qaux": ("nb", "G"),
            "norms": ("C", "cap"), "keep": ("C", "cap"),
            "row_scale": ("C", "cap"), "lut_weights": (16, "rot", "p")},
    derive=_scan_case_derive,
    case_filter=_scan_case_ok,
    extra_cases=(
        # metric spot checks on the exact arm
        {"extract": "exact", "k": 10, "cap": 256, "metric_kind": IP,
         "dtype": "float32"},
        {"extract": "exact", "k": 10, "cap": 256, "metric_kind": COSINE,
         "dtype": "float32"},
        # filtered-scan geometry (keep-mask block rides the site)
        {"extract": "exact", "k": 10, "cap": 256, "keep": True,
         "dtype": "float32", "static_only": True},
        # packed-storage geometry for the static engine; the packed
        # dynamics are pinned by test_ivf_pq + pallas_parity
        {"extract": "exact", "k": 10, "cap": 256, "packed_i4": True,
         "dtype": "bfloat16", "static_only": True},
        {"extract": "exact", "k": 10, "cap": 256, "pq4": True,
         "dtype": "bfloat16", "static_only": True},
        # rabitq sign-bit arm (ISSUE 11): driven DYNAMICALLY here — the
        # estimator's XLA mirror is the oracle. Adversarial classes:
        # dim divisible by 32, dim NOT divisible by 32 (partial last
        # word: pad bits decode -1, zero-padded queries must null them),
        # non-lane-multiple dims, k == n (whole-list edge), and the
        # single/short-row lists every case exercises via the driver's
        # short-size second pass. The estimator-unbiasedness statistical
        # check vs the exact-distance oracle lives in
        # tests/test_kernel_contracts.py::test_rabitq_estimator_unbiased.
        {"extract": "exact", "k": 10, "cap": 256, "rabitq": True,
         "d": 64, "dtype": "float32"},
        {"extract": "exact", "k": 10, "cap": 256, "rabitq": True,
         "d": 48, "dtype": "float32"},          # partial last word
        {"extract": "exact", "k": 10, "cap": 256, "rabitq": True,
         "d": 40, "dtype": "bfloat16"},         # non-lane-multiple dim
        # k == n at lane-legal geometry (cap < 128 cannot reach the
        # kernel through dispatch — _resolve_scan_impl requires
        # cap % 128 == 0 — so the whole-list edge rides the fold arm)
        {"extract": "fold", "k": 256, "cap": 256, "rabitq": True,
         "d": 64, "dtype": "float32"},
        # k == 1: the driver's short-size pass makes this the
        # single-row-list case (size = 1)
        {"extract": "exact", "k": 1, "cap": 256, "rabitq": True,
         "d": 64, "dtype": "float32"},
        {"extract": "binned", "k": 10, "cap": 256, "rabitq": True,
         "d": 64, "dtype": "float32"},
        {"extract": "fold", "k": 65, "cap": 256, "rabitq": True,
         "d": 64, "dtype": "float32"},
    ),
    notes="binned loses ~C(k,2)/128 per list, binned_deep/fold lose "
          "only when > R of the list's top-k share a lane; the "
          "cross-probe merge recovers survivors (docs/kernels.md).",
)
