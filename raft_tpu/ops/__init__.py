"""Pallas TPU kernels — the fused hot ops.

The reference's performance lives in hand-fused CUDA kernels
(ivf_flat_interleaved_scan-inl.cuh, select_warpsort.cuh); this package is
their TPU-native counterpart: Mosaic/Pallas kernels that fuse MXU
contractions with on-chip epilogues and k-selection so distances never
round-trip through HBM.
"""

from raft_tpu.ops.fused_topk import fused_topk
from raft_tpu.ops.graph_join import graph_local_join
from raft_tpu.ops.ivf_scan import fused_list_scan_topk

__all__ = ["fused_list_scan_topk", "fused_topk", "graph_local_join"]
