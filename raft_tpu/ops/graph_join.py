"""Fused nn-descent local-join Pallas kernel: score + unique-merge top-K.

TPU-native analog of the reference's GNND local join
(cpp/include/raft/neighbors/detail/nn_descent.cuh:342-358,700): the
reference scores each node's sampled 2-hop candidates in CTA shared
memory and pushes winners into neighbor lists with atomics. The pull
formulation here (see neighbors/nn_descent.py) makes the join row-wise —
each node scores its own candidate set and merges it into its current
list — which XLA serves with three HBM round trips per iteration: the
``[B, C]`` distance matrix, the ``[B, K+C]`` concat/sort buffers of the
unique-merge, and the top-K extraction transients. This kernel is the
TPU-KNN treatment (PAPERS.md, arxiv 2206.14286) applied to that join:

* **scoring** — per node-tile, the gathered candidate slab
  ``[tile_b*C, d]`` and the query rows sit in VMEM; each node's
  candidate dots are one MXU ``[1, d] x [d, C]`` contraction (the
  per-slab partials), with the L2 epilogue (norms, clamp) fused on the
  VPU. The ``[B, C]`` distance matrix lives only in registers/VMEM.
* **unique-merge top-K in-register** — the current list rides in as a
  ``[tile_b, K]`` block and the merged output is produced by a K-pass
  min extraction that masks BY ID after each pass, so the output is
  deduplicated by construction (the sort-based dedup + top-K of the XLA
  path collapses into the extraction itself). Duplicate ids keep their
  smallest distance with distance ties resolved to the smallest id —
  which coincides with the XLA fallback
  (``nn_descent._merge_topk_unique``: keep-first in id-stable order,
  lowest-id tie-break) because duplicate copies carry bitwise-equal
  distances in this pipeline (the same deterministic scoring produces
  them), so the two paths agree bitwise on ids over tie-free keys.

Only the ``[B, K]`` merged lists ever leave the chip; HBM traffic per
node drops from ``O(C·d + (K+C)·sort)`` transient round trips to the
candidate-vector gather XLA performs anyway (row gathers are XLA's
strength — the same split ops/beam_step.py uses for its packed rows).

The candidate gather itself stays OUTSIDE the kernel on purpose: it is
the op's byte floor (``C·d·4`` bytes per node against ``~2·C·d`` FLOPs,
arithmetic intensity ~0.5 FLOP/byte — deeply bandwidth-bound), so the
kernel's job is to add zero traffic on top of it, not to feed the MXU at
peak. ``tile_b`` therefore stays small (the f32 sublane floor up to 32)
and is table-dispatched under the ``graph_join`` op key
(docs/dispatch_tuning.md) like ``fused_topk_tile``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_INVALID = -1
_NO_ID = 2147483647          # min-id tie-break sentinel (int32 max)

# mirror of analysis/lint.py's _VMEM_BUDGET_BYTES (pallas guide:
# ~16 MB/core), spent at ~50% so double-buffering has somewhere to live
_VMEM_BYTES = 16 * 1024 * 1024


def _a128(v: int) -> int:
    return -(-int(v) // 128) * 128


def join_vmem_bytes(tile_b: int, C: int, K: int, d: int,
                    ip: bool = False) -> int:
    """Per-grid-step VMEM bytes of the join kernel's blocks plus its
    live intermediates (the pooled [tile_b, Kp+Cp] extraction buffers) —
    the budget rule ``tile_geometry`` and the dispatch candidates apply
    (docs/kernels.md §graph)."""
    Cp = _a128(C)
    Kp = _a128(K)
    blocks = (
        tile_b * d * 4                    # q rows
        + tile_b * Cp * d * 4             # candidate vector slab
        + tile_b * Cp * 4                 # candidate ids
        + 2 * tile_b * Kp * 4             # current list (d + i)
        + 2 * tile_b * Kp * 4             # output list (d + i)
    )
    if not ip:
        blocks += tile_b * 4 + tile_b * Cp * 4    # q norms + cand norms
    live = 2 * tile_b * (Kp + Cp) * 4             # pooled extraction pair
    return blocks + live


def tile_geometry(C: int, K: int, d: int, ip: bool = False) -> dict:
    """Expression-derived node-tile size: the largest of the canonical
    tiles (``tuning.GRAPH_JOIN_TILES`` — the ONE home; a tile added
    there is raced, dispatched, audited, and reachable here) whose
    blocks + extraction pool fit ~half of per-core VMEM; floor = the
    smallest canonical tile (8, the f32 sublane multiple). The analytic
    default — the dispatch table overrides it per backend (op key
    ``graph_join``, winner strings ``pallas:<tile_b>``)."""
    from raft_tpu.tuning import GRAPH_JOIN_TILES

    budget = _VMEM_BYTES // 2
    tiles = sorted(GRAPH_JOIN_TILES)
    tile_b = tiles[0]
    for t in reversed(tiles):
        if join_vmem_bytes(t, C, K, d, ip) <= budget:
            tile_b = t
            break
    return {"tile_b": int(tile_b)}


def _join_kernel(*refs, K: int, Kp: int, Cp: int, tile_b: int, ip: bool,
                 n_rows: int):
    refs = list(refs)
    q_ref = refs.pop(0)          # [TB, d] f32
    cid_ref = refs.pop(0)        # [TB, Cp] i32
    cv_ref = refs.pop(0)         # [TB*Cp, d] f32 candidate slab
    curd_ref = refs.pop(0)       # [TB, Kp] f32
    curi_ref = refs.pop(0)       # [TB, Kp] i32
    if not ip:
        qn_ref = refs.pop(0)     # [TB, 1] f32
        cn_ref = refs.pop(0)     # [TB, Cp] f32
    outd_ref, outi_ref = refs

    # ---- per-node scoring: one [1, d] x [d, Cp] MXU contraction per
    # node row, statically unrolled over the tile (dynamic sublane
    # offsets into the slab are unsupported in Mosaic; tile_b is small
    # by design — the op is gather-bound, see module docstring)
    rows = []
    for b in range(tile_b):
        cb = cv_ref[b * Cp:(b + 1) * Cp, :]            # [Cp, d]
        qb = q_ref[b:b + 1, :]                         # [1, d]
        dots = jax.lax.dot_general(
            qb, cb,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [1, Cp]
        if ip:
            rows.append(-dots)
        else:
            rows.append(jnp.maximum(
                qn_ref[b:b + 1, :] + cn_ref[b:b + 1, :] - 2.0 * dots, 0.0))
    dist = jnp.concatenate(rows, axis=0)               # [TB, Cp]

    ids = cid_ref[...]
    # tail mask: rows past the live count (the padded node tile) are
    # inert regardless of what the pad gather produced — belt to the
    # wrapper's (-1, +inf) sentinel suspenders
    row = (pl.program_id(0) * tile_b
           + jax.lax.broadcasted_iota(jnp.int32, (tile_b, Cp), 0))
    dist = jnp.where((ids < 0) | (row >= n_rows), jnp.inf, dist)

    # ---- unique-merge top-K: pool the current list with the fresh
    # candidates and run a K-pass min extraction that masks BY ID after
    # each pass — uniqueness by construction, duplicate ids keep their
    # smallest distance (ties resolved to the smallest id, matching the
    # XLA fallback's (id, distance)-sorted dedup + top_k)
    pool_d = jnp.concatenate([curd_ref[...], dist], axis=1)
    pool_i = jnp.concatenate([curi_ref[...], ids], axis=1)
    pool_d = jnp.where(pool_i < 0, jnp.inf, pool_d)

    outd_ref[...] = jnp.full((tile_b, Kp), jnp.inf, jnp.float32)
    outi_ref[...] = jnp.full((tile_b, Kp), _INVALID, jnp.int32)
    for j in range(K):
        m = jnp.min(pool_d, axis=1)                    # [TB]
        eq = pool_d == m[:, None]
        win = jnp.min(jnp.where(eq, pool_i, _NO_ID), axis=1)
        win = jnp.where(jnp.isinf(m), _INVALID, win)
        outd_ref[:, j] = m
        outi_ref[:, j] = win
        if j + 1 < K:
            pool_d = jnp.where(pool_i == win[:, None], jnp.inf, pool_d)


def graph_local_join(
    q,                # [B, d] f32 node vectors
    cand_ids,         # [B, C] i32 candidate ids (-1 = invalid slot)
    cand_vecs,        # [B, C, d] f32 gathered candidate vectors
    cur_d,            # [B, K] f32 current list distances (min-space)
    cur_i,            # [B, K] i32 current list ids (unique per row)
    qn=None,          # [B] f32 ||q||^2 (L2); None for IP
    cand_norms=None,  # [B, C] f32 ||cand||^2 (L2); None for IP
    *,
    ip: bool = False,
    tile_b: int = None,
    interpret: bool = False,
):
    """One fused local-join step: merge the scored candidates into each
    row's unique top-K (K = the current list width). Returns
    (new_d [B, K], new_i [B, K]), best-first, unique ids per row, the
    library-wide (+inf, -1) convention in unfilled slots. Distances are
    min-space (L2: ``||q||^2 + ||c||^2 - 2 q.c`` clamped at 0; IP:
    negated scores).

    Bitwise contract vs the XLA fallback
    (``nn_descent._merge_topk_unique`` over the same scores): duplicate
    ids collapse to one copy (bitwise-equal distances in this pipeline,
    so keep-min here == keep-first there), distance ties resolve to the
    smallest id. K caps at 128 (the K-pass extraction budget — the
    dispatch fallback serves larger K).
    """
    B, C = cand_ids.shape
    K = cur_d.shape[1]
    if K > 128:
        raise ValueError(
            f"graph_local_join caps at K=128 (K-pass extraction), got {K}")
    geo = tile_geometry(C, K, q.shape[1], ip)
    tb = int(tile_b or geo["tile_b"])
    return _graph_join_tiles(
        q, cand_ids, cand_vecs, cur_d, cur_i, qn, cand_norms,
        ip=bool(ip), tile_b=tb, interpret=bool(interpret),
    )


@functools.partial(
    jax.jit, static_argnames=("ip", "tile_b", "interpret"),
)
def _graph_join_tiles(q, cand_ids, cand_vecs, cur_d, cur_i, qn=None,
                      cand_norms=None, *, ip: bool, tile_b: int,
                      interpret: bool):
    B, C = cand_ids.shape
    d = q.shape[1]
    K = cur_d.shape[1]
    nt = -(-B // tile_b)
    Bp = nt * tile_b
    Cp = _a128(C)
    Kp = _a128(K)

    rpad = Bp - B
    cpad = Cp - C
    kpad = Kp - K
    qp = jnp.pad(q, ((0, rpad), (0, 0))) if rpad else q
    cid = jnp.pad(cand_ids, ((0, rpad), (0, cpad)), constant_values=-1) \
        if rpad or cpad else cand_ids
    cv = jnp.pad(cand_vecs, ((0, rpad), (0, cpad), (0, 0))) \
        if rpad or cpad else cand_vecs
    curd = jnp.pad(cur_d, ((0, rpad), (0, kpad)),
                   constant_values=jnp.inf) if rpad or kpad else cur_d
    curi = jnp.pad(cur_i, ((0, rpad), (0, kpad)), constant_values=-1) \
        if rpad or kpad else cur_i

    row = lambda i: (i, 0)
    inputs = [qp, cid, cv.reshape(Bp * Cp, d), curd, curi]
    in_specs = [
        pl.BlockSpec((tile_b, d), row),
        pl.BlockSpec((tile_b, Cp), row),
        pl.BlockSpec((tile_b * Cp, d), row),
        pl.BlockSpec((tile_b, Kp), row),
        pl.BlockSpec((tile_b, Kp), row),
    ]
    if not ip:
        qnp = jnp.pad(qn, (0, rpad)) if rpad else qn
        cn = jnp.pad(cand_norms, ((0, rpad), (0, cpad))) \
            if rpad or cpad else cand_norms
        inputs += [qnp.reshape(Bp, 1), cn]
        in_specs += [
            pl.BlockSpec((tile_b, 1), row),
            pl.BlockSpec((tile_b, Cp), row),
        ]
    kernel = functools.partial(
        _join_kernel, K=K, Kp=Kp, Cp=Cp, tile_b=tile_b, ip=ip, n_rows=B,
    )
    out_d, out_i = pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((tile_b, Kp), row),
            pl.BlockSpec((tile_b, Kp), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bp, Kp), jnp.float32),
            jax.ShapeDtypeStruct((Bp, Kp), jnp.int32),
        ],
        interpret=interpret,
    )(*inputs)
    return out_d[:B, :K], out_i[:B, :K]


# ---------------------------------------------------------------------------
# kernel contract (graft-kern; docs/static_analysis.md §engine-4)
# ---------------------------------------------------------------------------

from raft_tpu.analysis.contracts import kernel_contract  # noqa: E402
from raft_tpu.tuning import GRAPH_JOIN_TILES  # noqa: E402


def _join_case_ok(case: dict) -> bool:
    return 0 < case.get("K", 1) <= 128 and case.get("C", 1) >= 1


def _join_case_derive(case: dict) -> dict:
    case.setdefault("ip", False)
    case.setdefault(
        "tile_b",
        tile_geometry(case["C"], case["K"], case["d"],
                      case["ip"])["tile_b"])
    if case["ip"]:
        case["qn"] = case["cand_norms"] = False
    else:
        case["qn"] = case["cand_norms"] = True
    return case


kernel_contract(
    "graph_join",
    module=__name__,
    entry="graph_local_join",
    driver="raft_tpu.analysis.contract_drivers:drive_graph_join",
    tail_rows="masked",          # B/C/K pads carry (-1, +inf) sentinels
    k_range=(1, 128),
    k_key="K",
    dtypes=("float32",),
    exactness="bitwise",
    base={"B": 24, "C": 37, "d": 32, "K": 8},
    rows_key="C", batch_key="B",
    arrays={"q": ("B", "d"), "cand_ids": ("B", "C"),
            "cand_vecs": ("B", "C", "d"),
            "cur_d": ("B", "K"), "cur_i": ("B", "K"),
            "qn": ("B",), "cand_norms": ("B", "C")},
    case_filter=_join_case_ok,
    derive=_join_case_derive,
    extra_cases=tuple(
        [
            # IP metric: no norm operands, negated-dot scores
            {"K": 8, "B": 24, "C": 37, "d": 32, "ip": True,
             "dtype": "float32"},
            # fewer candidates than K: rows must tail out as (+inf, -1)
            {"K": 32, "B": 9, "C": 5, "d": 16, "dtype": "float32"},
            # non-word-multiple dim (d binds block dim == array dim)
            {"K": 8, "B": 24, "C": 37, "d": 30, "dtype": "float32"},
        ]
        + [
            # every dispatchable node tile (the graph_join winner
            # strings carry tile_b — audit each injectable value)
            {"K": 64, "B": 70, "C": 150, "d": 64, "tile_b": t,
             "dtype": "float32"}
            for t in GRAPH_JOIN_TILES
        ]
    ),
    notes="duplicate ids keep their smallest distance (== the XLA "
          "fallback's keep-first: copies tie bitwise under the shared "
          "deterministic scoring), distance ties resolve to the "
          "smallest id on both paths, so ids agree bitwise over "
          "tie-free keys; the candidate-vector gather stays in XLA "
          "(the op's byte floor), the kernel adds zero HBM transients.",
)
