"""Unit tests for the fused nn-descent local-join kernel
(ops/graph_join.py), run in pallas interpret mode on CPU (the on-chip
rerun is scripts/tpu_parity.py::check_graph + the compiled contract
sweep).

Oracle strategy: the XLA dispatch fallback IS the oracle — einsum
scoring + the keep-min ``_merge_topk_unique`` — so these tests pin the
bitwise contract the dispatch table relies on (either arm may serve any
block of a build).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from raft_tpu.neighbors.nn_descent import _merge_topk_unique
from raft_tpu.ops.graph_join import graph_local_join, tile_geometry


def _mk(rng, B, C, d, K, N=500, ip=False):
    vecs = rng.standard_normal((N, d)).astype(np.float32)
    q = rng.standard_normal((B, d)).astype(np.float32)
    cand = rng.integers(0, N, (B, C)).astype(np.int32)
    cand[rng.random((B, C)) < 0.1] = -1
    cur_i = np.stack([
        rng.choice(N, size=K, replace=False).astype(np.int32)
        for _ in range(B)])
    norms = (vecs ** 2).sum(1).astype(np.float32)
    qn = (q ** 2).sum(1).astype(np.float32)
    dots = np.einsum("bd,bkd->bk", q, vecs[cur_i])
    if ip:
        cur_d = (-dots).astype(np.float32)
    else:
        cur_d = np.maximum(
            qn[:, None] + norms[cur_i] - 2.0 * dots, 0.0).astype(np.float32)
    return vecs, q, cand, cur_d, cur_i, qn, norms


def _oracle(q, cand, vecs, cur_d, cur_i, qn, norms, K, ip=False):
    cs = np.maximum(cand, 0)
    dots = jnp.einsum(
        "bd,bcd->bc", jnp.asarray(q), jnp.asarray(vecs[cs]),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGH)
    if ip:
        cd = -dots
    else:
        cd = jnp.maximum(jnp.asarray(qn)[:, None]
                         + jnp.asarray(norms[cs]) - 2.0 * dots, 0.0)
    cd = jnp.where(jnp.asarray(cand) < 0, jnp.inf, cd)
    return _merge_topk_unique(
        jnp.asarray(cur_d), jnp.asarray(cur_i), cd, jnp.asarray(cand), K)


def _run_kernel(q, cand, vecs, cur_d, cur_i, qn, norms, ip=False,
                tile_b=None):
    cs = np.maximum(cand, 0)
    return graph_local_join(
        jnp.asarray(q), jnp.asarray(cand), jnp.asarray(vecs[cs]),
        jnp.asarray(cur_d), jnp.asarray(cur_i),
        None if ip else jnp.asarray(qn),
        None if ip else jnp.asarray(norms[cs]),
        ip=ip, tile_b=tile_b, interpret=True)


@pytest.mark.parametrize("ip", [False, True])
def test_kernel_matches_xla_fallback_bitwise(ip):
    rng = np.random.default_rng(7)
    B, C, d, K = 50, 70, 32, 16
    vecs, q, cand, cur_d, cur_i, qn, norms = _mk(rng, B, C, d, K, ip=ip)
    # plant every duplicate class: in-row dups + already-listed ids
    cand[:, 1] = cand[:, 0]
    cand[:, 2] = cur_i[:, 0]
    kd, ki = _run_kernel(q, cand, vecs, cur_d, cur_i, qn, norms, ip=ip)
    wd, wi = _oracle(q, cand, vecs, cur_d, cur_i, qn, norms, K, ip=ip)
    np.testing.assert_array_equal(np.asarray(ki), np.asarray(wi))
    fin = np.isfinite(np.asarray(wd))
    np.testing.assert_allclose(np.asarray(kd)[fin], np.asarray(wd)[fin],
                               rtol=1e-5, atol=1e-5)
    # uniqueness invariant per row
    for b in range(B):
        live = np.asarray(ki)[b][np.asarray(ki)[b] >= 0]
        assert len(set(live.tolist())) == len(live)


def test_fewer_candidates_than_k_tails_invalid():
    rng = np.random.default_rng(8)
    B, C, d, K = 9, 3, 16, 32
    vecs, q, cand, _, _, qn, norms = _mk(rng, B, C, d, 4)
    cur_d = np.full((B, K), np.inf, np.float32)
    cur_i = np.full((B, K), -1, np.int32)
    kd, ki = _run_kernel(q, cand, vecs, cur_d, cur_i, qn, norms)
    kd, ki = np.asarray(kd), np.asarray(ki)
    assert ((ki == -1) == np.isinf(kd)).all()
    # at most C unique finite entries per row
    assert (np.isfinite(kd).sum(1) <= C).all()


def test_all_invalid_row_is_empty():
    rng = np.random.default_rng(9)
    B, C, d, K = 8, 12, 16, 8
    vecs, q, cand, cur_d, cur_i, qn, norms = _mk(rng, B, C, d, K)
    cand[3, :] = -1
    cur_d[3, :] = np.inf
    cur_i[3, :] = -1
    kd, ki = _run_kernel(q, cand, vecs, cur_d, cur_i, qn, norms)
    assert (np.asarray(ki)[3] == -1).all()
    assert np.isinf(np.asarray(kd)[3]).all()


def test_every_dispatchable_tile_agrees():
    """The graph_join winner strings carry tile_b — every dispatchable
    tile must produce the same answer (geometry is a speed knob, never
    a semantics knob)."""
    from raft_tpu.tuning import GRAPH_JOIN_TILES

    rng = np.random.default_rng(10)
    B, C, d, K = 37, 40, 24, 12
    vecs, q, cand, cur_d, cur_i, qn, norms = _mk(rng, B, C, d, K)
    outs = [
        _run_kernel(q, cand, vecs, cur_d, cur_i, qn, norms, tile_b=t)
        for t in GRAPH_JOIN_TILES
    ]
    for kd, ki in outs[1:]:
        np.testing.assert_array_equal(np.asarray(ki),
                                      np.asarray(outs[0][1]))
        np.testing.assert_array_equal(np.asarray(kd),
                                      np.asarray(outs[0][0]))


def test_tile_geometry_fits_budget():
    from raft_tpu.ops.graph_join import join_vmem_bytes

    for C, K, d in ((128, 64, 64), (256, 96, 128), (512, 128, 256)):
        tb = tile_geometry(C, K, d)["tile_b"]
        assert tb in (8, 16, 32)
        assert join_vmem_bytes(tb, C, K, d) <= 8 * 1024 * 1024
